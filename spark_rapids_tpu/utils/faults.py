"""Conf-gated fault-injection framework.

The reference engine delegates failure handling to Spark's task/stage
retry machinery (RapidsShuffleFetchFailedException -> stage retry,
heartbeat-driven executor exclusion); there is no in-tree chaos layer
because Spark's own test harness injects faults at the RPC/BlockManager
boundary. This engine owns its whole runtime, so it owns its chaos
layer too: named fault points threaded through every failure surface
(shuffle fetch/publish, TCP/DCN socket I/O, spill-store write/read,
worker task execution, H2D upload) that deterministic, seeded fault
specs can trigger in tests and in the ``BENCH_CHAOS=1`` bench phase.

Cost model mirrors the tracer (utils/tracing.py) and the memory
profiler (utils/memprof.py): a module-level ``_INJECTOR`` that is
``None`` when disabled, so every ``fire()`` call on the hot path pays
exactly one global load + is-None check (the zero-overhead pin that
tests/test_faults.py asserts on).

Spec grammar (``spark.rapids.tpu.faults.spec``)::

    spec    := clause (";" clause)*
    clause  := point (":" key "=" value)*
    keys    := p|prob        fire probability in [0,1]   (default 1.0)
               times         stop after N fires          (default unlimited)
               after         skip the first N evaluations (default 0)
               latency_ms    inject latency before returning
               action        raise|kill|corrupt|delay|oom|fatal (default raise)

e.g. ``tcp.connect:p=0.2:times=3;worker.task:after=1:action=kill``.
Each point gets its own ``random.Random(f"{seed}:{point}")`` stream, so
firing decisions are independent of evaluation order at other points
and reproducible across runs — the property the determinism test pins.

The module doubles as the engine-wide **recovery ledger**: every
recovery mechanism (worker respawn, task resubmission, transport retry,
shuffle recompute, spill-corruption recovery) notes what it did via
``note_recovery()``; the event-log writer snapshots/deltas the counters
into schema-v8 ``recovery`` records and the stats registry exposes them
as ``faults_*`` gauges on ``/metrics``.
"""
from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional

from ..conf import register_conf

__all__ = [
    "FAULT_POINTS",
    "FaultInjectedError",
    "FaultInjector",
    "configure_faults",
    "reset_faults",
    "active",
    "fire",
    "note_recovery",
    "recovery_counters",
    "reset_recovery",
    "drain_fault_records",
    "faults_stats",
]

FAULTS_ENABLED = register_conf(
    "spark.rapids.tpu.faults.enabled",
    "Enable the fault-injection framework. When false (the default) "
    "every fault point compiles down to a single module-constant check "
    "and nothing is ever injected.",
    False)

FAULTS_SPEC = register_conf(
    "spark.rapids.tpu.faults.spec",
    "Fault-injection spec: semicolon-separated clauses of the form "
    "point[:key=value]* with keys p|prob (fire probability), times "
    "(max fires), after (skip first N evaluations), latency_ms and "
    "action (raise|kill|corrupt|delay|oom|fatal). See "
    "docs/fault_tolerance.md.",
    "")

FAULTS_SEED = register_conf(
    "spark.rapids.tpu.faults.seed",
    "Seed for the per-point deterministic RNG streams used by "
    "probabilistic fault clauses.",
    0)

#: Catalogue of named fault points threaded through the engine. Specs
#: may only reference these — a typo'd point is a config error, not a
#: silently-never-firing clause.
FAULT_POINTS = (
    "shuffle.fetch",     # shuffle/manager.py read path, before transport fetch
    "shuffle.publish",   # shuffle/manager.py write path, before publishing blocks
    "tcp.connect",       # shuffle/tcp.py client connect to a peer
    "tcp.read",          # shuffle/tcp.py client response read from a peer
    "dcn.publish",       # shuffle/dcn.py cross-slice block publish
    "dcn.fetch",         # shuffle/dcn.py cross-slice block fetch
    "spill.write",       # memory/stores.py disk-spill write (supports corrupt)
    "spill.read",        # memory/stores.py disk-spill restore
    "worker.task",       # parallel/runtime.py worker task execution (supports kill)
    "h2d.upload",        # exec/transitions.py host->device upload
    "alloc.jit",         # memory/retry.py jit-dispatch retry scope (supports oom/fatal)
    "alloc.upload",      # memory/retry.py H2D-upload retry scope (supports oom/fatal)
    "mesh.dispatch",     # exec/mesh.py mesh-stage shard_map dispatch (degrades to the per-partition path)
)

# "fatal" is the non-retryable twin of "oom": memory/retry.py raises an
# INTERNAL-status RuntimeError with no OOM marker, so the retry ladder
# passes it through and the host-fallback boundary (exec/fallback.py)
# classifies it — the injection that exercises the degradation path
# BELOW the ladder.
_ACTIONS = ("raise", "kill", "corrupt", "delay", "oom", "fatal")


class FaultInjectedError(RuntimeError):
    """An injected fault fired with ``action=raise``. Carries the point
    name so recovery errors and forensics can name the fault."""

    def __init__(self, point: str, action: str = "raise"):
        super().__init__(f"injected fault '{point}' (action={action})")
        self.point = point
        self.action = action


class _Clause:
    """One parsed spec clause: firing rule + mutable fire budget."""

    __slots__ = ("point", "prob", "times", "after", "latency_ms",
                 "action", "rng", "evaluations", "fires")

    def __init__(self, point: str, prob: float, times: Optional[int],
                 after: int, latency_ms: float, action: str, seed: int):
        self.point = point
        self.prob = prob
        self.times = times
        self.after = after
        self.latency_ms = latency_ms
        self.action = action
        self.rng = random.Random(f"{seed}:{point}")
        self.evaluations = 0
        self.fires = 0

    def evaluate(self) -> bool:
        """Advance this clause's deterministic stream by one evaluation
        and decide whether it fires."""
        self.evaluations += 1
        # consume one sample per evaluation regardless of the outcome so
        # the stream position depends only on how often the point is
        # reached, never on `after`/`times` state
        sample = self.rng.random()
        if self.evaluations <= self.after:
            return False
        if self.times is not None and self.fires >= self.times:
            return False
        if sample >= self.prob:
            return False
        self.fires += 1
        return True


def _parse_spec(spec: str, seed: int) -> Dict[str, _Clause]:
    clauses: Dict[str, _Clause] = {}
    for raw in spec.replace(";", ",").split(","):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        point = parts[0].strip()
        if point not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; known points: "
                f"{', '.join(FAULT_POINTS)}")
        prob, times, after, latency_ms, action = 1.0, None, 0, 0.0, "raise"
        for kv in parts[1:]:
            if "=" not in kv:
                raise ValueError(f"fault clause option {kv!r} is not key=value")
            k, v = (s.strip() for s in kv.split("=", 1))
            if k in ("p", "prob"):
                prob = float(v)
                if not 0.0 <= prob <= 1.0:
                    raise ValueError(f"fault probability {prob} not in [0,1]")
            elif k == "times":
                times = int(v)
            elif k == "after":
                after = int(v)
            elif k == "latency_ms":
                latency_ms = float(v)
            elif k == "action":
                if v not in _ACTIONS:
                    raise ValueError(
                        f"unknown fault action {v!r}; one of {_ACTIONS}")
                action = v
            else:
                raise ValueError(f"unknown fault clause key {k!r}")
        clauses[point] = _Clause(point, prob, times, after, latency_ms,
                                 action, seed)
    return clauses


# never set: gives injected latency an interruptible, checker-clean wait
_SLEEP_EVT = threading.Event()


class FaultInjector:
    """Deterministic seeded fault injector over the named point set."""

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = seed
        self._clauses = _parse_spec(spec, seed)
        self._lock = threading.Lock()
        self._records: List[Dict[str, Any]] = []

    def fire(self, point: str) -> Optional[str]:
        """Evaluate the clause registered for ``point`` (if any).
        Returns the clause's action string when it fires (after applying
        any configured latency), else None."""
        clause = self._clauses.get(point)
        if clause is None:
            return None
        with self._lock:
            fired = clause.evaluate()
            if not fired:
                return None
            self._records.append({
                "point": point,
                "action": clause.action,
                "fire": clause.fires,
                "evaluation": clause.evaluations,
            })
        if clause.latency_ms > 0:
            _SLEEP_EVT.wait(clause.latency_ms / 1000.0)
        return clause.action

    def counters(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {p: {"evaluations": c.evaluations, "fires": c.fires}
                    for p, c in self._clauses.items()}

    def drain_records(self) -> List[Dict[str, Any]]:
        with self._lock:
            out, self._records = self._records, []
            return out


# ---------------------------------------------------------------------------
# module-level injector: None when disabled (the zero-overhead pin)
# ---------------------------------------------------------------------------
_INJECTOR: Optional[FaultInjector] = None


def fire(point: str) -> Optional[str]:
    """Hot-path fault point. With injection disabled this is one global
    load + is-None check (the zero-overhead pin)."""
    if _INJECTOR is None:
        return None
    return _INJECTOR.fire(point)


def configure_faults(conf, seed_offset: int = 0) -> Optional[FaultInjector]:
    """Install (or clear) the process-wide injector from a RapidsConf.
    Workers call this on startup so a cluster-wide spec reaches every
    process; returns the installed injector (None when disabled).
    ``seed_offset`` (ProcessCluster passes the worker id) decorrelates
    the per-process streams while keeping each one deterministic."""
    global _INJECTOR
    if not conf.get(FAULTS_ENABLED):
        _INJECTOR = None
        return None
    _INJECTOR = FaultInjector(str(conf.get(FAULTS_SPEC)),
                              int(conf.get(FAULTS_SEED)) + seed_offset)
    return _INJECTOR


def install(injector: Optional[FaultInjector]) -> None:
    """Install a pre-built injector (ProcessCluster workers re-install
    their seed-offset injector after a worker-side TpuSession re-runs
    configure_faults with the plain conf seed)."""
    global _INJECTOR
    _INJECTOR = injector


def reset_faults() -> None:
    global _INJECTOR
    _INJECTOR = None


def active() -> Optional[FaultInjector]:
    return _INJECTOR


def drain_fault_records() -> List[Dict[str, Any]]:
    inj = _INJECTOR
    return inj.drain_records() if inj is not None else []


# ---------------------------------------------------------------------------
# recovery ledger: process-wide counters of what recovery machinery did
# ---------------------------------------------------------------------------
_LEDGER_KEYS = (
    "worker_deaths",        # worker processes observed dead (exit/EOF/wedge)
    "worker_respawns",      # dead workers replaced with a fresh process
    "worker_exclusions",    # workers taken out of rotation permanently
    "task_resubmissions",   # in-flight tasks re-run on a surviving worker
    "task_failures",        # tasks that exhausted task.maxFailures
    "task_timeouts",        # _wait deadlines that expired
    "transport_retries",    # transient socket errors retried with backoff
    "transport_giveups",    # peers abandoned after exhausting retries
    "shuffle_recomputes",   # map outputs recomputed after fetch-failed
    "spill_corruptions",    # disk-spill blocks that failed CRC verification
    "oom_retries",          # device-OOM spill-and-retry attempts (memory/retry.py)
    "oom_splits",           # device-OOM row-axis input halvings (memory/retry.py)
    "host_fallbacks",       # batches re-executed on the host engine (exec/fallback.py)
)

_LEDGER: Dict[str, int] = {k: 0 for k in _LEDGER_KEYS}
_LEDGER_LOCK = threading.Lock()


def note_recovery(key: str, n: int = 1) -> None:
    """Record recovery activity. Unknown keys are registered on the fly
    so call sites never crash telemetry."""
    with _LEDGER_LOCK:
        _LEDGER[key] = _LEDGER.get(key, 0) + n


def recovery_counters() -> Dict[str, int]:
    with _LEDGER_LOCK:
        return dict(_LEDGER)


def reset_recovery() -> None:
    with _LEDGER_LOCK:
        _LEDGER.clear()
        _LEDGER.update({k: 0 for k in _LEDGER_KEYS})


def faults_stats() -> Dict[str, Any]:
    """Stats-registry source: recovery counters plus per-point
    injection counts when an injector is active."""
    out: Dict[str, Any] = dict(recovery_counters())
    inj = _INJECTOR
    if inj is not None:
        for point, c in inj.counters().items():
            key = point.replace(".", "_")
            out[f"injected_{key}"] = c["fires"]
    return out
