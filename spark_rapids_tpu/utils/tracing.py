"""Process-wide span tracer + Chrome trace-event exporter.

Reference: the plugin scopes device work in NVTX ranges
(NvtxWithMetrics.scala) and relies on Nsight for timeline analysis; the
TPU runtime owns its execution loop, so it records its own spans instead:
query -> AQE stage -> partition task -> operator batch, plus subsystem
spans (shuffle write/fetch, XLA compile, host->device upload, spill,
semaphore wait) and instant events (device OOM).

Design constraints:
- thread-safe: operators run on executor worker threads; one global ring
  buffer collects events from all of them.
- bounded: a ring buffer (``spark.rapids.tpu.trace.bufferSize`` events)
  caps memory no matter how long the session runs; overflow drops the
  OLDEST events and counts the drops.
- near-zero cost when disabled: ``span()`` yields immediately without
  taking the lock or reading the clock.

The export format is the Chrome trace-event JSON (``ph: "X"`` complete
events with microsecond timestamps), loadable in Perfetto / chrome://tracing
and in TensorBoard's trace viewer.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

from ..conf import register_conf

__all__ = ["TraceEvent", "Tracer", "get_tracer", "set_tracer",
           "configure_tracer", "tracer_stats", "TRACE_ENABLED",
           "TRACE_BUFFER_SIZE", "TRACE_DIR"]

TRACE_ENABLED = register_conf(
    "spark.rapids.tpu.trace.enabled",
    "Record runtime spans (query/stage/task/operator plus shuffle, compile, "
    "upload, spill and semaphore-wait events) into the process-wide tracer "
    "(the NVTX-range analogue; reference: NvtxWithMetrics.scala). Export "
    "with Tracer.to_chrome_trace() or spark.rapids.tpu.trace.dir.", False)

TRACE_BUFFER_SIZE = register_conf(
    "spark.rapids.tpu.trace.bufferSize",
    "Ring-buffer capacity of the tracer in events; overflow drops the "
    "oldest events (drop count is reported in the exported trace metadata).",
    65536, checker=lambda v: None if v > 0 else f"must be positive, got {v}")

TRACE_DIR = register_conf(
    "spark.rapids.tpu.trace.dir",
    "Directory to dump the Chrome trace-event JSON into on session close "
    "(one file per session, loadable in Perfetto / chrome://tracing). "
    "Empty disables the dump.", "")


class TraceEvent:
    """One recorded event. ``ts``/``dur`` are microseconds relative to the
    tracer's epoch; ``ph`` is the Chrome trace phase ("X" complete span,
    "i" instant)."""

    __slots__ = ("name", "cat", "ph", "ts", "dur", "tid", "depth", "args")

    def __init__(self, name: str, cat: str, ph: str, ts: float, dur: float,
                 tid: int, depth: int, args: Optional[Dict] = None):
        self.name = name
        self.cat = cat
        self.ph = ph
        self.ts = ts
        self.dur = dur
        self.tid = tid
        self.depth = depth
        self.args = args or {}

    def to_chrome(self, pid: int = 1) -> Dict:
        ev: Dict = {"name": self.name, "cat": self.cat, "ph": self.ph,
                    "ts": round(self.ts, 3), "pid": pid, "tid": self.tid}
        if self.ph == "X":
            ev["dur"] = round(self.dur, 3)
        if self.ph == "i":
            ev["s"] = "t"  # instant scope: thread
        args = dict(self.args)
        args["depth"] = self.depth
        ev["args"] = args
        return ev

    def __repr__(self):
        return (f"TraceEvent({self.name!r}, cat={self.cat!r}, ph={self.ph!r}, "
                f"ts={self.ts:.1f}us, dur={self.dur:.1f}us, "
                f"depth={self.depth})")


class Tracer:
    """Thread-safe bounded span recorder."""

    def __init__(self, capacity: int = 65536, enabled: bool = False):
        self.enabled = enabled
        self.capacity = capacity
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.epoch = time.perf_counter()
        self.dropped = 0
        self._drop_warned = False

    # -- recording ------------------------------------------------------------
    def _stack(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _record(self, ev: TraceEvent) -> None:
        warn = False
        with self._lock:
            if len(self._events) == self.capacity:
                self.dropped += 1
                if not self._drop_warned:
                    self._drop_warned = True
                    warn = True
            self._events.append(ev)
        if warn:
            # once per session of drops: a wrapped ring buffer means the
            # exported Chrome trace is silently truncated at the front
            import warnings
            warnings.warn(
                "tracer ring buffer wrapped — oldest spans are being "
                "dropped and the exported trace will be truncated; raise "
                "spark.rapids.tpu.trace.bufferSize "
                f"(currently {self.capacity})", RuntimeWarning)

    @contextmanager
    def span(self, name: str, cat: str = "misc", **args):
        """Record a complete event around the with-block. Nesting depth is
        tracked per thread so exported traces preserve the span hierarchy."""
        if not self.enabled:
            yield
            return
        stack = self._stack()
        depth = len(stack)
        stack.append(name)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            stack.pop()
            self._record(TraceEvent(
                name, cat, "X", (t0 - self.epoch) * 1e6, (t1 - t0) * 1e6,
                threading.get_ident(), depth, args))

    def complete(self, name: str, cat: str, start_s: float, dur_s: float,
                 **args) -> None:
        """Record a complete event with caller-measured times
        (``time.perf_counter()`` domain) — for code that already owns its
        own timers, e.g. the per-batch operator instrumentation."""
        if not self.enabled:
            return
        self._record(TraceEvent(
            name, cat, "X", (start_s - self.epoch) * 1e6, dur_s * 1e6,
            threading.get_ident(), len(self._stack()), args))

    def instant(self, name: str, cat: str = "misc", **args) -> None:
        if not self.enabled:
            return
        self._record(TraceEvent(
            name, cat, "i", (time.perf_counter() - self.epoch) * 1e6, 0.0,
            threading.get_ident(), len(self._stack()), args))

    # -- inspection / export --------------------------------------------------
    def events(self) -> List[TraceEvent]:
        with self._lock:
            return list(self._events)

    def categories(self) -> set:
        return {e.cat for e in self.events()}

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0
            self._drop_warned = False

    def to_chrome_trace(self) -> Dict:
        """Chrome trace-event JSON object ({"traceEvents": [...]}), loadable
        in Perfetto/chrome://tracing."""
        evs = self.events()
        return {
            "traceEvents": [e.to_chrome() for e in evs],
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "spark-rapids-tpu",
                "dropped_events": self.dropped,
            },
        }

    def dump(self, path: str) -> str:
        """Write the Chrome trace JSON to ``path``; returns the path."""
        import os
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_chrome_trace(), f)
        return path


def tracer_stats() -> Dict:
    """Flat tracer counters for the process StatsRegistry (utils/metrics.py)
    — ``spans_dropped`` > 0 flags a truncated Perfetto trace that would
    otherwise silently mislead."""
    t = get_tracer()
    with t._lock:
        return {"enabled": t.enabled, "capacity": t.capacity,
                "events_buffered": len(t._events),
                "spans_dropped": t.dropped}


_GLOBAL = Tracer()
_GLOBAL_LOCK = threading.Lock()


def get_tracer() -> Tracer:
    return _GLOBAL


def set_tracer(tracer: Tracer) -> None:
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = tracer


def configure_tracer(conf) -> Tracer:
    """Apply conf to the global tracer (session init chokepoint).

    Sticky semantics: the tracer is process-wide and sessions come and go,
    so a session whose conf leaves tracing at the default must NOT disable
    a tracer another session enabled (nor shrink its buffer, dropping
    already-recorded events). Enabling turns it on; turning it off again is
    an explicit act: ``get_tracer().enabled = False``. The buffer resizes
    only when this conf sets a non-default size; resizing preserves the
    newest events."""
    tracer = _GLOBAL
    with _GLOBAL_LOCK:
        if bool(conf.get(TRACE_ENABLED)):
            tracer.enabled = True
        capacity = int(conf.get(TRACE_BUFFER_SIZE))
        if capacity != tracer.capacity \
                and capacity != TRACE_BUFFER_SIZE.default:
            with tracer._lock:
                tracer.dropped += max(0, len(tracer._events) - capacity)
                tracer.capacity = capacity
                tracer._events = deque(tracer._events, maxlen=capacity)
    return tracer
