"""Process-wide span tracer + Chrome trace-event exporter.

Reference: the plugin scopes device work in NVTX ranges
(NvtxWithMetrics.scala) and relies on Nsight for timeline analysis; the
TPU runtime owns its execution loop, so it records its own spans instead:
query -> AQE stage -> partition task -> operator batch, plus subsystem
spans (shuffle write/fetch, XLA compile, host->device upload, spill,
semaphore wait) and instant events (device OOM).

Design constraints:
- thread-safe: operators run on executor worker threads; one global ring
  buffer collects events from all of them.
- bounded: a ring buffer (``spark.rapids.tpu.trace.bufferSize`` events)
  caps memory no matter how long the session runs; overflow drops the
  OLDEST events and counts the drops.
- near-zero cost when disabled: ``span()`` yields immediately without
  taking the lock or reading the clock.

The export format is the Chrome trace-event JSON (``ph: "X"`` complete
events with microsecond timestamps), loadable in Perfetto / chrome://tracing
and in TensorBoard's trace viewer.
"""
from __future__ import annotations

import itertools
import json
import os
import struct
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

from ..conf import register_conf

__all__ = ["TraceEvent", "Tracer", "TraceContext", "get_tracer",
           "set_tracer", "configure_tracer", "tracer_stats",
           "mint_trace_context", "current_trace_context",
           "activate_trace_context", "new_span_id",
           "TRACE_ENABLED", "TRACE_BUFFER_SIZE", "TRACE_DIR",
           "TRACE_DISTRIBUTED", "TRACE_DISTRIBUTED_DIR",
           "TRACE_CLOCK_PROBES"]

TRACE_ENABLED = register_conf(
    "spark.rapids.tpu.trace.enabled",
    "Record runtime spans (query/stage/task/operator plus shuffle, compile, "
    "upload, spill and semaphore-wait events) into the process-wide tracer "
    "(the NVTX-range analogue; reference: NvtxWithMetrics.scala). Export "
    "with Tracer.to_chrome_trace() or spark.rapids.tpu.trace.dir.", False)

TRACE_BUFFER_SIZE = register_conf(
    "spark.rapids.tpu.trace.bufferSize",
    "Ring-buffer capacity of the tracer in events; overflow drops the "
    "oldest events (drop count is reported in the exported trace metadata).",
    65536, checker=lambda v: None if v > 0 else f"must be positive, got {v}")

TRACE_DIR = register_conf(
    "spark.rapids.tpu.trace.dir",
    "Directory to dump the Chrome trace-event JSON into on session close "
    "(one file per session, loadable in Perfetto / chrome://tracing). "
    "Empty disables the dump.", "")

TRACE_DISTRIBUTED = register_conf(
    "spark.rapids.tpu.trace.distributed.enabled",
    "Propagate the per-query TraceContext (trace_id, parent span id, "
    "query_id) across process boundaries: ProcessCluster task envelopes "
    "and the TCP/DCN shuffle wire headers. Worker-side spans then parent "
    "under the driver's query span in the merged timeline "
    "(tools/trace.py merge). Near-zero cost; only disable to bisect "
    "wire-protocol issues.", True)

TRACE_DISTRIBUTED_DIR = register_conf(
    "spark.rapids.tpu.trace.distributed.dir",
    "Directory where each PROCESS (driver and every ProcessCluster "
    "worker) dumps its own Chrome trace on shutdown/flush, named "
    "trace-<process_name>.json — the input set for "
    "`python -m spark_rapids_tpu.tools.trace merge`. Empty disables.", "")

TRACE_CLOCK_PROBES = register_conf(
    "spark.rapids.tpu.trace.distributed.clockProbes",
    "Number of clock-handshake probes per ProcessCluster worker used to "
    "estimate the worker->driver wall-clock offset (the probe with the "
    "smallest round trip wins, NTP-style); the estimate aligns worker "
    "span timestamps in the merged timeline.", 5,
    checker=lambda v: None if v > 0 else f"must be positive, got {v}")


# ---------------------------------------------------------------------------
# trace context: the cross-process identity of one query's timeline
# ---------------------------------------------------------------------------
_SPAN_SEQ = itertools.count(1)


def new_span_id() -> int:
    """Process-unique span id: pid in the high bits, a monotonic counter
    in the low bits — two processes can never mint the same id, so the
    merged span DAG needs no renumbering."""
    return ((os.getpid() & 0xFFFF) << 40) | (next(_SPAN_SEQ) & 0xFFFFFFFFFF)


class TraceContext:
    """Identity carried across every process boundary a query touches:
    which trace (query execution) an event belongs to and which span it
    parents under. Immutable; ``child()`` derives the context a nested
    span propagates."""

    __slots__ = ("trace_id", "span_id", "query_id")

    #: wire encoding for the TCP shuffle header: 16 ascii-hex chars of
    #: trace_id, u64 parent span id, i64 query id (-1 = none)
    WIRE = struct.Struct("<16sQq")

    def __init__(self, trace_id: str, span_id: int,
                 query_id: Optional[int] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.query_id = query_id

    def child(self, span_id: int) -> "TraceContext":
        return TraceContext(self.trace_id, span_id, self.query_id)

    # -- serialization (task envelopes use the dict form; the TCP wire
    #    uses the fixed-size pack) --------------------------------------
    def to_wire(self) -> Dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "query_id": self.query_id}

    @classmethod
    def from_wire(cls, d: Optional[Dict]) -> Optional["TraceContext"]:
        if not d:
            return None
        return cls(d["trace_id"], d["span_id"], d.get("query_id"))

    def pack(self) -> bytes:
        return self.WIRE.pack(
            self.trace_id[:16].ljust(16, "0").encode("ascii"),
            self.span_id,
            -1 if self.query_id is None else int(self.query_id))

    @classmethod
    def unpack(cls, raw: bytes) -> "TraceContext":
        tid, span_id, qid = cls.WIRE.unpack(raw)
        return cls(tid.decode("ascii"), span_id,
                   None if qid < 0 else qid)

    def __repr__(self):
        return (f"TraceContext({self.trace_id!r}, span={self.span_id}, "
                f"query={self.query_id})")


_CTX_TLS = threading.local()


def current_trace_context() -> Optional[TraceContext]:
    """The TraceContext active on THIS thread (None outside a query)."""
    stack = getattr(_CTX_TLS, "stack", None)
    return stack[-1] if stack else None


@contextmanager
def activate_trace_context(ctx: Optional[TraceContext]):
    """Make ``ctx`` the current context for the with-block (no-op on
    None, so call sites need no conditionals)."""
    if ctx is None:
        yield None
        return
    stack = getattr(_CTX_TLS, "stack", None)
    if stack is None:
        stack = _CTX_TLS.stack = []
    stack.append(ctx)
    try:
        yield ctx
    finally:
        stack.pop()


def mint_trace_context(query_id: Optional[int] = None) -> TraceContext:
    """A fresh trace root (driver side, one per query)."""
    return TraceContext(uuid.uuid4().hex[:16], new_span_id(), query_id)


class TraceEvent:
    """One recorded event. ``ts``/``dur`` are microseconds relative to the
    tracer's epoch; ``ph`` is the Chrome trace phase ("X" complete span,
    "i" instant)."""

    __slots__ = ("name", "cat", "ph", "ts", "dur", "tid", "depth", "args")

    def __init__(self, name: str, cat: str, ph: str, ts: float, dur: float,
                 tid: int, depth: int, args: Optional[Dict] = None):
        self.name = name
        self.cat = cat
        self.ph = ph
        self.ts = ts
        self.dur = dur
        self.tid = tid
        self.depth = depth
        self.args = args or {}

    def to_chrome(self, pid: int = 1) -> Dict:
        ev: Dict = {"name": self.name, "cat": self.cat, "ph": self.ph,
                    "ts": round(self.ts, 3), "pid": pid, "tid": self.tid}
        if self.ph == "X":
            ev["dur"] = round(self.dur, 3)
        if self.ph == "i":
            ev["s"] = "t"  # instant scope: thread
        args = dict(self.args)
        args["depth"] = self.depth
        ev["args"] = args
        return ev

    def __repr__(self):
        return (f"TraceEvent({self.name!r}, cat={self.cat!r}, ph={self.ph!r}, "
                f"ts={self.ts:.1f}us, dur={self.dur:.1f}us, "
                f"depth={self.depth})")


class Tracer:
    """Thread-safe bounded span recorder."""

    def __init__(self, capacity: int = 65536, enabled: bool = False,
                 process_name: Optional[str] = None):
        self.enabled = enabled
        self.capacity = capacity
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._tls = threading.local()
        # epoch (perf_counter domain) and its wall-clock anchor are taken
        # at the SAME instant: merged timelines place this process's
        # events at epoch_unix + ts, then correct by the handshake offset
        self.epoch = time.perf_counter()
        self.epoch_unix = time.time()
        self.process_name = process_name or f"pid-{os.getpid()}"
        self.dropped = 0
        self._drop_warned = False

    # -- recording ------------------------------------------------------------
    def _stack(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _record(self, ev: TraceEvent) -> None:
        warn = False
        with self._lock:
            if len(self._events) == self.capacity:
                self.dropped += 1
                if not self._drop_warned:
                    self._drop_warned = True
                    warn = True
            self._events.append(ev)
        if warn:
            # once per session of drops: a wrapped ring buffer means the
            # exported Chrome trace is silently truncated at the front
            import warnings
            warnings.warn(
                "tracer ring buffer wrapped — oldest spans are being "
                "dropped and the exported trace will be truncated; raise "
                "spark.rapids.tpu.trace.bufferSize "
                f"(currently {self.capacity})", RuntimeWarning)

    @staticmethod
    def _ctx_args(args: Dict,
                  ctx: Optional[TraceContext] = None,
                  span_id: Optional[int] = None) -> Dict:
        """Fold the active TraceContext into event args: trace_id +
        query_id tie the event to one query's timeline, span_id /
        parent_span_id link the cross-process span DAG. No context
        active -> args unchanged (process-local tracing stays lean)."""
        ctx = ctx if ctx is not None else current_trace_context()
        if ctx is None:
            return args
        out = dict(args)
        out["trace_id"] = ctx.trace_id
        out["span_id"] = span_id if span_id is not None else new_span_id()
        out["parent_span_id"] = ctx.span_id
        if ctx.query_id is not None:
            out["query_id"] = out.get("query_id", ctx.query_id)
        return out

    @contextmanager
    def span(self, name: str, cat: str = "misc", **args):
        """Record a complete event around the with-block. Nesting depth is
        tracked per thread so exported traces preserve the span hierarchy.
        Under an active TraceContext the span gets its own span id and
        re-parents the context for the block, so nested spans (this thread
        or a remote process the block talks to) chain under it."""
        if not self.enabled:
            yield
            return
        stack = self._stack()
        depth = len(stack)
        stack.append(name)
        ctx = current_trace_context()
        span_id = new_span_id() if ctx is not None else None
        t0 = time.perf_counter()
        try:
            if ctx is not None:
                with activate_trace_context(ctx.child(span_id)):
                    yield
            else:
                yield
        finally:
            t1 = time.perf_counter()
            stack.pop()
            self._record(TraceEvent(
                name, cat, "X", (t0 - self.epoch) * 1e6, (t1 - t0) * 1e6,
                threading.get_ident(), depth,
                self._ctx_args(args, ctx, span_id)))

    def complete(self, name: str, cat: str, start_s: float, dur_s: float,
                 **args) -> None:
        """Record a complete event with caller-measured times
        (``time.perf_counter()`` domain) — for code that already owns its
        own timers, e.g. the per-batch operator instrumentation."""
        if not self.enabled:
            return
        self._record(TraceEvent(
            name, cat, "X", (start_s - self.epoch) * 1e6, dur_s * 1e6,
            threading.get_ident(), len(self._stack()),
            self._ctx_args(args)))

    def instant(self, name: str, cat: str = "misc", **args) -> None:
        if not self.enabled:
            return
        self._record(TraceEvent(
            name, cat, "i", (time.perf_counter() - self.epoch) * 1e6, 0.0,
            threading.get_ident(), len(self._stack()),
            self._ctx_args(args)))

    # -- inspection / export --------------------------------------------------
    def events(self) -> List[TraceEvent]:
        with self._lock:
            return list(self._events)

    def categories(self) -> set:
        return {e.cat for e in self.events()}

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0
            self._drop_warned = False

    def drain(self) -> Dict:
        """Atomically snapshot-and-reset: returns a Chrome trace of
        everything recorded since the last drain, with the drop count
        scoped to THAT window (per-process, per-flush accounting — a
        worker's per-query flush attributes its drops to the query that
        overflowed the ring, and the counter starts clean for the next
        one). The epoch is NOT reset: timestamps across drains stay in
        one timebase."""
        with self._lock:
            evs = list(self._events)
            dropped = self.dropped
            self._events.clear()
            self.dropped = 0
            self._drop_warned = False
        return self._chrome(evs, dropped)

    def _chrome(self, evs: List[TraceEvent], dropped: int) -> Dict:
        return {
            "traceEvents": [e.to_chrome(pid=os.getpid()) for e in evs],
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "spark-rapids-tpu",
                "dropped_events": dropped,
                "pid": os.getpid(),
                "process_name": self.process_name,
                "epoch_unix": self.epoch_unix,
            },
        }

    def to_chrome_trace(self) -> Dict:
        """Chrome trace-event JSON object ({"traceEvents": [...]}), loadable
        in Perfetto/chrome://tracing. ``otherData`` carries the process
        identity + wall-clock anchor tools/trace.py needs to merge traces
        from several processes onto one timeline."""
        return self._chrome(self.events(), self.dropped)

    def dump(self, path: str) -> str:
        """Write the Chrome trace JSON to ``path``; returns the path."""
        import os
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_chrome_trace(), f)
        return path


def tracer_stats() -> Dict:
    """Flat tracer counters for the process StatsRegistry (utils/metrics.py)
    — ``spans_dropped`` > 0 flags a truncated Perfetto trace that would
    otherwise silently mislead."""
    t = get_tracer()
    with t._lock:
        return {"enabled": t.enabled, "capacity": t.capacity,
                "events_buffered": len(t._events),
                "spans_dropped": t.dropped}


_GLOBAL = Tracer()
_GLOBAL_LOCK = threading.Lock()


def get_tracer() -> Tracer:
    return _GLOBAL


def set_tracer(tracer: Tracer) -> None:
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = tracer


def configure_tracer(conf) -> Tracer:
    """Apply conf to the global tracer (session init chokepoint).

    Sticky semantics: the tracer is process-wide and sessions come and go,
    so a session whose conf leaves tracing at the default must NOT disable
    a tracer another session enabled (nor shrink its buffer, dropping
    already-recorded events). Enabling turns it on; turning it off again is
    an explicit act: ``get_tracer().enabled = False``. The buffer resizes
    only when this conf sets a non-default size; resizing preserves the
    newest events."""
    tracer = _GLOBAL
    with _GLOBAL_LOCK:
        if bool(conf.get(TRACE_ENABLED)):
            tracer.enabled = True
        capacity = int(conf.get(TRACE_BUFFER_SIZE))
        if capacity != tracer.capacity \
                and capacity != TRACE_BUFFER_SIZE.default:
            with tracer._lock:
                tracer.dropped += max(0, len(tracer._events) - capacity)
                tracer.capacity = capacity
                tracer._events = deque(tracer._events, maxlen=capacity)
    return tracer
