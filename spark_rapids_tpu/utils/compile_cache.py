"""Global XLA compile cache (+ the runtime-OOM recovery chokepoint).

Plans are rebuilt per query execution, but the traced computations repeat
(same operator chains over the same shape buckets). jax.jit caches on the
wrapped callable's identity, so per-plan ``jax.jit(fn)`` wrappers would
recompile every run (~1s each). This cache keys jitted callables by a
canonical plan signature so repeated queries hit steady-state dispatch
(~0.1ms). The reference relies on cuDF's precompiled kernels; on TPU the
compile-once-run-many discipline is ours to enforce.

The cache is THREE tiers (ROADMAP item 2 — compile dominates bench wall):

1. the in-process table above (``_CACHE``),
2. XLA's own persistent compilation cache (``jax_compilation_cache_dir``,
   wired under ``spark.rapids.tpu.compile.cacheDir`` and keyed by a
   machine fingerprint + jax version so foreign executables never load),
3. the engine's OWN manifest persisted alongside it: per plan signature,
   cumulative hit counts plus a serialized ``jax.export`` of the traced
   program at its first-call shapes. A fresh process replays the hottest
   exports on background threads at session start (the warm pool,
   ``spark.rapids.tpu.compile.warmPool.*``) and installs ready-to-dispatch
   executables into ``_CACHE`` — the second run of a query in a NEW
   process then executes with zero XLA compiles (``cache_stats()``).

Every load path is corruption-tolerant: a bad manifest, entry, or export
file is dropped (and counted), never fatal.

Every jitted device computation flows through here, which makes it the
TPU-native stand-in for RMM's allocation-failure callback (reference:
DeviceMemoryEventHandler.scala:33): a RESOURCE_EXHAUSTED from the runtime
triggers a synchronous catalog spill and ONE retry; a second failure
re-raises with the catalog's OOM dump attached.
"""
from __future__ import annotations

import functools
import hashlib
import json
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax

from ..conf import register_conf

__all__ = ["cached_jit", "cache_stats", "clear_cache", "oom_retry",
           "configure_introspection", "kernel_table", "kernel_seq",
           "kernels_since", "XLA_INTROSPECTION", "KERNEL_TABLE_SIZE",
           "configure_compile_cache", "persist_compile_cache",
           "machine_fingerprint", "warm_pool_wait", "stop_warm_pool",
           "persistent_cache_dir", "COMPILE_CACHE_DIR",
           "COMPILE_CACHE_ENABLED", "WARM_POOL_ENABLED",
           "WARM_POOL_MAX_SIGNATURES", "WARM_POOL_MAX_SECONDS"]

_CACHE: Dict[str, Callable] = {}
_LOCK = threading.Lock()
_HITS = 0
_MISSES = 0
_COMPILES = 0
_COMPILE_SECONDS = 0.0

# ---------------------------------------------------------------------------
# Kernel table: one row per cache entry (= per XLA program), keyed by the
# plan signature and attributed back to the exec node that requested it
# (utils/node_context.py — pushed by the profiler/event-log
# instrumentation). Flushed into event-log schema v3 ``kernel`` records and
# mined by tools/diagnose.py ("q6 dominated by recompiles: N unique
# signatures for 1 operator"). Flare's lesson applies: inspect what the
# compiler actually generated instead of guessing.
# ---------------------------------------------------------------------------
XLA_INTROSPECTION = register_conf(
    "spark.rapids.tpu.metrics.xlaIntrospection",
    "What the compile cache captures about each XLA program into the "
    "kernel table: 'off' records only compile wall/hit counts; 'lowered' "
    "(default) additionally runs HLO cost analysis on the lowered module "
    "(flops / bytes accessed — one cheap retrace per unique program, no "
    "extra XLA compile); 'compiled' also AOT-compiles the captured input "
    "shapes for memory_analysis() (argument/output/temp bytes) — one "
    "EXTRA compile per unique program, meant for offline analysis runs.",
    "lowered",
    checker=lambda v: None if str(v).lower() in ("off", "lowered",
                                                 "compiled")
    else f"must be one of off/lowered/compiled, got {v!r}")

KERNEL_TABLE_SIZE = register_conf(
    "spark.rapids.tpu.metrics.kernelTableSize",
    "Max kernel-table entries kept in memory; least-recently-touched "
    "entries are dropped past the bound (the jitted callables themselves "
    "stay cached).", 4096,
    checker=lambda v: None if int(v) > 0 else "must be positive")

_INTROSPECT_MODE = "lowered"
_KERNEL_TABLE_MAX = 4096
_KERNELS: "Dict[str, Dict]" = {}   # signature -> kernel entry (mutable dict)
_KERNEL_SEQ = 0                    # bumps on every entry touch


def configure_introspection(conf) -> None:
    """Apply spark.rapids.tpu.metrics.* to the process kernel table
    (called from TpuSession.__init__, like configure_tracer)."""
    global _INTROSPECT_MODE, _KERNEL_TABLE_MAX
    _INTROSPECT_MODE = str(conf.get(XLA_INTROSPECTION)).lower()
    _KERNEL_TABLE_MAX = int(conf.get(KERNEL_TABLE_SIZE))


# ---------------------------------------------------------------------------
# persistent compilation tier (spark.rapids.tpu.compile.*)
# ---------------------------------------------------------------------------
COMPILE_CACHE_ENABLED = register_conf(
    "spark.rapids.tpu.compile.enabled",
    "Master switch for the persistent compilation tier: when true AND "
    "spark.rapids.tpu.compile.cacheDir is set, XLA executables persist "
    "across process restarts (jax_compilation_cache_dir) and the engine's "
    "plan-signature manifest + program exports are saved on session close.",
    True)

COMPILE_CACHE_DIR = register_conf(
    "spark.rapids.tpu.compile.cacheDir",
    "Base directory of the persistent compilation tier; '' (default) "
    "disables it. The engine scopes everything under a "
    "<machine-fingerprint>-jax<version> subdirectory, so a shared "
    "filesystem can hold caches for a fleet and no host ever loads "
    "executables compiled for different CPU features or a different jax.",
    "")

WARM_POOL_ENABLED = register_conf(
    "spark.rapids.tpu.compile.warmPool.enabled",
    "Precompile the hottest persisted plan signatures on background "
    "threads at session start (under the pipeline task pool), so even the "
    "FIRST run of a repeated workload in a fresh process hits steady-state "
    "dispatch. Requires compile.cacheDir.", True)

WARM_POOL_MAX_SIGNATURES = register_conf(
    "spark.rapids.tpu.compile.warmPool.maxSignatures",
    "How many persisted plan signatures the warm pool precompiles, "
    "hottest (by cumulative cross-process hits) first. Also caps how many "
    "program exports are written per session close.", 32,
    checker=lambda v: None if int(v) > 0 else "must be positive")

WARM_POOL_MAX_SECONDS = register_conf(
    "spark.rapids.tpu.compile.warmPool.maxSeconds",
    "Wall-clock budget for warm-pool precompilation; signatures not "
    "reached by the deadline stay cold (they compile on first dispatch as "
    "usual).", 30.0, conf_type=float,
    checker=lambda v: None if float(v) > 0 else "must be positive")

#: refuse to persist a single program export larger than this — a giant
#: export means a builder closed over baked-in data, which the in-process
#: cache contract already forbids; never let one entry bloat the tier
_EXPORT_MAX_BYTES = 32 * 1024 * 1024

# persistent-tier process state. _PERSIST is reconfigured per session
# (most recent wins, like the tracer/pipeline chokepoints); _EXPORTABLE
# retains (builder, aval-skeleton) per signature compiled THIS process so
# session close can export the traced programs. All under _LOCK.
_PERSIST: Dict = {"dir": None, "base": {}, "warm_enabled": True,
                  "warm_max": int(WARM_POOL_MAX_SIGNATURES.default),
                  "warm_seconds": float(WARM_POOL_MAX_SECONDS.default)}
_EXPORTABLE: Dict[str, Tuple[Callable, tuple]] = {}
_PSTATS = {"manifest_entries": 0, "warmed_entries": 0, "hits": 0,
           "misses": 0, "warm_compiles": 0, "warm_errors": 0,
           "exports_written": 0, "dropped_entries": 0}
_WARM_STOP = threading.Event()
_WARM_THREAD: Optional[threading.Thread] = None


def machine_fingerprint() -> str:
    """Stable id for 'programs compiled here run here' (XLA:CPU bakes host
    CPU features into generated code; a foreign cache recompiles or
    SIGILLs — bench.py learned this across rounds)."""
    import platform
    parts = [platform.system(), platform.machine()]
    try:
        want = ("flags", "features", "model name", "cpu model")
        seen = set()
        with open("/proc/cpuinfo") as f:
            for line in f:
                key = line.split(":", 1)[0].strip().lower()
                if key in want and key not in seen:
                    seen.add(key)
                    parts.append(
                        " ".join(sorted(line.split(":", 1)[1].split())))
                if len(seen) == len(want):
                    break
    except OSError:
        pass
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:12]


def _touch_locked(entry: Dict) -> None:
    global _KERNEL_SEQ
    _KERNEL_SEQ += 1
    entry["last_touch"] = _KERNEL_SEQ


def _kernel_entry_locked(key: str) -> Dict:
    entry = _KERNELS.get(key)
    if entry is None:
        from .node_context import current
        ctx = current()
        entry = _KERNELS[key] = {
            "signature": key,
            "node_name": ctx.name if ctx is not None else None,
            "node_id": ctx.node_id if ctx is not None else None,
            "query_id": ctx.query_id if ctx is not None else None,
            "hits": 0, "misses": 0, "compiles": 0, "compile_s": 0.0,
            "cost": {}, "memory": {}, "last_touch": 0,
        }
        # touch BEFORE choosing an eviction victim: a fresh entry holds
        # last_touch=0 (the global minimum) and would otherwise evict
        # itself, freezing the table with stale entries at capacity
        _touch_locked(entry)
        if len(_KERNELS) > _KERNEL_TABLE_MAX:
            victim = min(_KERNELS, key=lambda k: _KERNELS[k]["last_touch"])
            del _KERNELS[victim]
    else:
        _touch_locked(entry)
    return entry


def kernel_seq() -> int:
    """Monotonic touch counter — snapshot before a query, pass to
    ``kernels_since`` after it to get the programs that query exercised."""
    with _LOCK:
        return _KERNEL_SEQ


def kernels_since(seq: int) -> List[Dict]:
    """Kernel entries touched (hit, compiled, or created) after ``seq``."""
    with _LOCK:
        return [dict(e) for e in _KERNELS.values() if e["last_touch"] > seq]


def kernel_table() -> List[Dict]:
    """The full kernel table, hottest compile first."""
    with _LOCK:
        rows = [dict(e) for e in _KERNELS.values()]
    return sorted(rows, key=lambda e: -e["compile_s"])


def _aval_of(x):
    """Shape/dtype skeleton of one pytree leaf (weak types collapse — fine
    for cost analysis)."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return jax.ShapeDtypeStruct(x.shape, x.dtype)
    return x


def _introspect(key: str, builder: Callable[[], Callable],
                args, kwargs) -> None:
    """Capture cost/memory analysis for the program behind ``key``.

    Re-lowers the builder against shape skeletons of the first call's
    arguments (jit.lower accepts ShapeDtypeStruct pytrees, so nothing is
    kept resident). Failures are recorded, never raised — introspection
    must not break execution."""
    mode = _INTROSPECT_MODE
    if mode == "off":
        return
    entry_update: Dict = {}
    try:
        avals = jax.tree_util.tree_map(_aval_of, (args, kwargs))
        lowered = jax.jit(builder()).lower(*avals[0], **avals[1])
        cost = lowered.cost_analysis()
        if mode == "compiled":
            compiled = lowered.compile()
            cca = compiled.cost_analysis()
            if cca:
                cost = cca[0] if isinstance(cca, list) else cca
            mem = compiled.memory_analysis()
            if mem is not None:
                entry_update["memory"] = {
                    "argument_bytes": int(mem.argument_size_in_bytes),
                    "output_bytes": int(mem.output_size_in_bytes),
                    "temp_bytes": int(mem.temp_size_in_bytes),
                    "code_bytes": int(mem.generated_code_size_in_bytes),
                }
        if isinstance(cost, list):
            cost = cost[0] if cost else {}
        if cost:
            # keep the totals; the per-operand breakdown keys ("bytes
            # accessed0{}") would bloat every event log
            entry_update["cost"] = {
                k: float(v) for k, v in cost.items() if "{" not in k}
    except Exception as e:  # pragma: no cover - backend-dependent
        entry_update["introspection_error"] = repr(e)[:200]
    with _LOCK:
        entry = _KERNELS.get(key)
        if entry is not None:
            entry.update(entry_update)

def oom_retry(fn: Callable) -> Callable:
    """Spill-and-retry OOM recovery at the jit chokepoint. The
    classification and the escalation ladder live in memory/retry.py
    (wrap_jit) — this name survives as the cache's chokepoint so every
    existing call site (and test) keeps working."""
    from ..memory.retry import wrap_jit
    return wrap_jit(fn)


def oom_spill_noretry(fn: Callable) -> Callable:
    """OOM handling for DONATING entries (donate_argnums): a failed
    dispatch may already have invalidated the donated input buffers, so
    re-calling with the same arguments is unsound. memory/retry.py's
    wrap_jit_donating re-materializes the input from the host origin
    retained by the upload site and retries; with no origin it spills
    for SUBSEQUENT batches and raises a structured DeviceOomError."""
    from ..memory.retry import wrap_jit_donating
    return wrap_jit_donating(fn)


_EXEC_MISMATCH_MARKERS = ("but got buffer with incompatible size",
                          "buffers but compiled program expected")


def _rebuild_on_mismatch(key: str, builder: Callable[[], Callable],
                         fn: Callable) -> Callable:
    """jax 0.9 workaround: a jit wrapper's dispatch cache can resolve to a
    stale executable for inputs whose treedef+avals are IDENTICAL to a
    previously successful call (observed with (n, 2) two-limb decimal128
    columns — no-lengths 2-D data planes). A fresh jax.jit of the same
    builder always works, so on that specific INVALID_ARGUMENT signature
    the entry is rebuilt once and the call retried."""
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except ValueError as e:
            msg = str(e)
            if not any(m in msg for m in _EXEC_MISMATCH_MARKERS):
                raise
            fresh = oom_retry(jax.jit(builder()))
            with _LOCK:
                _CACHE[key] = _rebuild_on_mismatch(key, builder, fresh)
            return fresh(*args, **kwargs)
    return wrapped


def _time_first_call(key: str, fn: Callable,
                     builder: Optional[Callable[[], Callable]] = None
                     ) -> Callable:
    """Attribute a cache entry's first invocation to XLA compile time.

    jax.jit compiles lazily on first dispatch, so the first call through a
    fresh entry is (compile + run); later calls are steady-state dispatch.
    Timing the first call is the standard approximation for per-plan
    compile seconds (the run part is dwarfed by the ~1s trace+compile),
    and it scopes the call in a "compile" trace span so Perfetto shows
    compile stalls on the query timeline. The first call also feeds the
    kernel table: compile wall + (when introspection is on) the program's
    HLO cost/memory analysis, attributed to the executing node."""
    state = {"done": False}

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        global _COMPILES, _COMPILE_SECONDS
        if state["done"]:
            return fn(*args, **kwargs)
        # shape/dtype skeleton BEFORE dispatch: donated input buffers may
        # be dead afterwards; the skeleton is what session close exports
        # for the persistent tier (cheap — aval metadata only)
        skeleton = None
        if builder is not None and _PERSIST["dir"] is not None:
            try:
                skeleton = jax.tree_util.tree_map(_aval_of, (args, kwargs))
            except Exception:
                skeleton = None
        from .tracing import get_tracer
        t0 = time.perf_counter()
        with get_tracer().span("xla_compile", "compile", key=key[:160]):
            out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        first = False
        with _LOCK:
            # check-and-set under the lock: concurrent first dispatches of
            # one entry must attribute the compile exactly once
            if not state["done"]:
                state["done"] = True
                first = True
                _COMPILES += 1
                _COMPILE_SECONDS += dt
                if skeleton is not None:
                    if len(_EXPORTABLE) >= 512 and key not in _EXPORTABLE:
                        # bound builder-closure retention: beyond any
                        # plausible warm set, drop the oldest capture
                        _EXPORTABLE.pop(next(iter(_EXPORTABLE)))
                    _EXPORTABLE[key] = (builder, skeleton)
                entry = _KERNELS.get(key)
                if entry is not None:
                    entry["compiles"] += 1
                    entry["compile_s"] += dt
                    _touch_locked(entry)
        if first:
            # a finished compile IS engine progress: without this, a
            # compile-heavy warm-up phase (many first dispatches, no
            # batches accounted yet) looks frozen to the health watchdog
            from ..parallel.pipeline import note_progress
            note_progress()
            from .node_context import current_registry
            reg = current_registry()
            if reg is not None:
                from . import metrics as M
                reg.add(M.COMPILE_TIME, dt)
            if builder is not None:
                _introspect(key, builder, args, kwargs)
        return out
    return wrapped


def _attribute(metric_name: str) -> None:
    """Count a cache hit/miss on the executing node's registry (no-op when
    uninstrumented — process-global counters still track)."""
    from .node_context import current_registry
    reg = current_registry()
    if reg is not None:
        reg.add(metric_name, 1)


def cached_jit(key: str, builder: Callable[[], Callable],
               donate_argnums=None) -> Callable:
    """Return a jitted callable for ``key``, building it on first use.

    ``donate_argnums`` requests XLA input-buffer donation for the jitted
    entry (exec/wholestage.py input donation — callers MUST key donating
    and non-donating variants differently: the option is baked into the
    compiled executable)."""
    global _HITS, _MISSES
    from . import metrics as M
    with _LOCK:
        fn = _CACHE.get(key)
        if fn is not None:
            _HITS += 1
            entry = _KERNELS.get(key)
            if entry is not None:
                entry["hits"] += 1
                _touch_locked(entry)
        else:
            _MISSES += 1
            _kernel_entry_locked(key)["misses"] += 1
    if fn is not None:
        if isinstance(fn, _WarmedEntry):
            # warm-pool entries need the builder for output-pytree
            # reconstruction and as the unexpected-shape fallback
            fn.attach_builder(builder, donate_argnums)
        _attribute(M.COMPILE_CACHE_HITS)
        return fn
    _attribute(M.COMPILE_CACHE_MISSES)
    if donate_argnums is None:
        built = _time_first_call(key, _rebuild_on_mismatch(
            key, builder, oom_retry(jax.jit(builder()))), builder)
    else:
        # donating entries get NO call-again recovery with the SAME args
        # (the failed dispatch may have consumed the donated input); the
        # donating ladder re-materializes from the retained host origin
        # instead, or spills-and-raises structured when there is none
        built = _time_first_call(key, oom_spill_noretry(
            jax.jit(builder(), donate_argnums=donate_argnums)), builder)
    with _LOCK:
        fn = _CACHE.setdefault(key, built)
    if fn is not built and isinstance(fn, _WarmedEntry):
        # the warm pool installed this key between our miss check and the
        # setdefault — the warmed entry has never seen a cached_jit() hit,
        # so it still needs the builder for out-tree/fallback dispatch
        fn.attach_builder(builder, donate_argnums)
    return fn


def cache_stats() -> Dict[str, float]:
    # snapshot under _LOCK: the pipeline task pool compiles concurrently,
    # and a lock-free multi-field read can tear (hits from one moment,
    # compiles from another) — stats consumers diff these across queries
    with _LOCK:
        out = {"entries": len(_CACHE), "hits": _HITS, "misses": _MISSES,
               "compiles": _COMPILES,
               "compile_seconds": round(_COMPILE_SECONDS, 6)}
        out.update({f"persist_{k}": v for k, v in _PSTATS.items()})
    return out


def clear_cache():
    global _HITS, _MISSES, _COMPILES, _COMPILE_SECONDS
    with _LOCK:
        _CACHE.clear()
        _KERNELS.clear()
        _EXPORTABLE.clear()
        # flushed deltas track _KERNELS totals; clearing one without the
        # other would produce negative deltas at the next persist
        _PERSIST.pop("flushed", None)
        for k in _PSTATS:
            _PSTATS[k] = 0
        _HITS = _MISSES = 0
        _COMPILES = 0
        _COMPILE_SECONDS = 0.0


# ---------------------------------------------------------------------------
# persistent tier: manifest + program exports + warm pool
# ---------------------------------------------------------------------------
def persistent_cache_dir() -> Optional[str]:
    """The active tier directory (fingerprint+jax scoped), or None."""
    with _LOCK:
        return _PERSIST["dir"]


def _aval_signature(treedef, leaves) -> str:
    """Stable id of a call's input pytree: structure + leaf shape/dtype.
    Identical across processes for identical plans over identical bucket
    ladders — the key that matches a live dispatch to a persisted export."""
    parts = [str(treedef)]
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append(f"{dtype}{tuple(shape)}")
        else:
            parts.append(f"py:{type(leaf).__name__}")
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


class _WarmedEntry:
    """A ``_CACHE`` entry installed by the warm pool BEFORE any builder
    exists in this process: per input-shape signature, an AOT-compiled
    executable replayed from a persisted ``jax.export``.

    Dispatch flattens the call's args, matches the aval signature, runs the
    flat executable and unflattens through the output pytree learned from
    ONE abstract trace of the builder (``jax.eval_shape`` — no XLA compile).
    Any mismatch (unexpected shapes, incompatible arguments) falls back to
    the normal build path, which counts a real compile."""

    def __init__(self, key: str):
        self.key = key
        self._records: Dict[str, Callable] = {}   # aval_sig -> flat dispatch
        self._out_trees: Dict[str, object] = {}   # aval_sig -> out treedef
        self._builder: Optional[Callable] = None
        self._donate = None
        self._fallback: Optional[Callable] = None
        self._elock = threading.Lock()

    def add_record(self, aval_sig: str, dispatch: Callable) -> None:
        self._records[aval_sig] = dispatch

    def attach_builder(self, builder: Callable, donate_argnums) -> None:
        if self._builder is None:
            self._builder = builder
            self._donate = donate_argnums

    def _fallback_fn(self) -> Callable:
        fb = self._fallback
        if fb is not None:
            return fb
        with self._elock:
            if self._fallback is None:
                builder = self._builder
                if builder is None:
                    raise RuntimeError(
                        f"warmed compile-cache entry {self.key!r} dispatched "
                        f"before any cached_jit() call attached its builder")
                if self._donate is None:
                    self._fallback = _time_first_call(
                        self.key, _rebuild_on_mismatch(
                            self.key, builder,
                            oom_retry(jax.jit(builder()))), builder)
                else:
                    self._fallback = _time_first_call(
                        self.key, oom_spill_noretry(jax.jit(
                            builder(), donate_argnums=self._donate)),
                        builder)
            return self._fallback

    def _out_tree_for(self, aval_sig: str, args, kwargs, n_out: int):
        tree = self._out_trees.get(aval_sig)
        if tree is not None:
            return tree
        builder = self._builder
        if builder is None:
            return None
        # one abstract trace to learn the output pytree (cheap: no XLA)
        out_shape = jax.eval_shape(builder(), *args, **kwargs)
        leaves, tree = jax.tree_util.tree_flatten(out_shape)
        if len(leaves) != n_out:
            return None
        with self._elock:
            self._out_trees.setdefault(aval_sig, tree)
        return tree

    def __call__(self, *args, **kwargs):
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        aval_sig = _aval_signature(treedef, leaves)
        dispatch = self._records.get(aval_sig)
        if dispatch is None:
            with _LOCK:
                _PSTATS["misses"] += 1
            return self._fallback_fn()(*args, **kwargs)
        try:
            flat_out = dispatch(*leaves)
            tree = self._out_tree_for(aval_sig, args, kwargs, len(flat_out))
            if tree is None:
                raise TypeError("output arity mismatch")
            out = jax.tree_util.tree_unflatten(tree, flat_out)
        except (TypeError, ValueError) as e:
            # incompatible-argument class of errors only: device OOM
            # (RuntimeError) propagates through the oom_retry wrapper
            with _LOCK:
                self._records.pop(aval_sig, None)
                _PSTATS["warm_errors"] += 1
                _PSTATS["misses"] += 1
            print(f"# warmed entry {self.key[:80]!r} fell back to a live "
                  f"compile: {type(e).__name__}", file=sys.stderr)
            return self._fallback_fn()(*args, **kwargs)
        with _LOCK:
            _PSTATS["hits"] += 1
        return out


def _manifest_path(tier_dir: str) -> str:
    return os.path.join(tier_dir, "manifest.json")


def _load_manifest(path: str) -> Tuple[Dict[str, Dict], int]:
    """Read the persisted plan-signature manifest. Corruption-tolerant by
    contract: a bad file or a bad entry is dropped (counted), never
    raised — a wedged cache must not take the engine down."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return {}, 0
    except (OSError, ValueError):
        return {}, 1
    raw = data.get("entries") if isinstance(data, dict) else None
    if not isinstance(raw, dict):
        return {}, 1
    entries: Dict[str, Dict] = {}
    dropped = 0
    for sig, e in raw.items():
        if not isinstance(e, dict) \
                or not isinstance(e.get("hits", 0), (int, float)) \
                or not isinstance(e.get("compiles", 0), (int, float)):
            dropped += 1
            continue
        exports = e.get("exports", [])
        if not isinstance(exports, list):
            dropped += 1
            continue
        good_exports = [x for x in exports
                        if isinstance(x, dict)
                        and isinstance(x.get("file"), str)
                        and isinstance(x.get("aval_sig"), str)]
        entry = {"hits": int(e.get("hits", 0)),
                 "compiles": int(e.get("compiles", 0)),
                 "compile_s": float(e.get("compile_s", 0.0) or 0.0),
                 "node_name": e.get("node_name"),
                 "exports": good_exports}
        entries[sig] = entry
    return entries, dropped


def configure_compile_cache(conf) -> Optional[str]:
    """Apply spark.rapids.tpu.compile.* (called from TpuSession.__init__,
    most recent session wins). Wires jax's persistent compilation cache,
    loads the engine manifest, and starts the warm pool. Returns the tier
    directory, or None when the tier is off."""
    stop_warm_pool()
    enabled = bool(conf.get(COMPILE_CACHE_ENABLED))
    base = str(conf.get(COMPILE_CACHE_DIR) or "").strip()
    if not enabled or not base:
        with _LOCK:
            was_active = _PERSIST["dir"] is not None
            _PERSIST["dir"] = None
            _PERSIST["base"] = {}
        if was_active:
            # un-wire the XLA disk cache we set earlier: the most recent
            # session owns the chokepoint, and its tier is off
            try:
                jax.config.update("jax_compilation_cache_dir", None)
            except Exception:  # pragma: no cover
                pass
        return None
    tier = os.path.join(os.path.abspath(base),
                        f"{machine_fingerprint()}-jax{jax.__version__}")
    try:
        os.makedirs(os.path.join(tier, "exports"), exist_ok=True)
        os.makedirs(os.path.join(tier, "xla"), exist_ok=True)
    except OSError as e:
        import warnings
        warnings.warn(f"persistent compile cache disabled: cannot create "
                      f"{tier!r} ({e})", RuntimeWarning)
        with _LOCK:
            _PERSIST["dir"] = None
            _PERSIST["base"] = {}
        return None
    try:
        # tier 2: XLA executables survive restarts. min_compile_time 0 —
        # the user opted into a cache dir, so persist everything
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(tier, "xla"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as e:  # pragma: no cover - depends on jax build
        print(f"# jax compilation cache not wired: {e}", file=sys.stderr)
    entries, dropped = _load_manifest(_manifest_path(tier))
    with _LOCK:
        _PERSIST["dir"] = tier
        _PERSIST["base"] = entries
        _PERSIST["warm_enabled"] = bool(conf.get(WARM_POOL_ENABLED))
        _PERSIST["warm_max"] = int(conf.get(WARM_POOL_MAX_SIGNATURES))
        _PERSIST["warm_seconds"] = float(conf.get(WARM_POOL_MAX_SECONDS))
        _PSTATS["manifest_entries"] = len(entries)
        _PSTATS["dropped_entries"] += dropped
        warm = _PERSIST["warm_enabled"]
    if warm and entries:
        _start_warm_pool()
    return tier


def _warm_items_locked() -> List[Tuple[str, str, str]]:
    """(signature, export file, aval_sig) triples for the hottest
    manifest signatures, bounded by warmPool.maxSignatures."""
    ranked = sorted(_PERSIST["base"].items(),
                    key=lambda kv: -(kv[1]["hits"] + kv[1]["compiles"]))
    items: List[Tuple[str, str, str]] = []
    for sig, entry in ranked[:_PERSIST["warm_max"]]:
        for ex in entry["exports"]:
            items.append((sig, ex["file"], ex["aval_sig"]))
    return items


def _start_warm_pool() -> None:
    global _WARM_THREAD
    if _WARM_THREAD is not None and _WARM_THREAD.is_alive():
        # a previous pool outlived its stop request (mid-AOT-compile);
        # clearing _WARM_STOP under it would un-cancel it — skip warming
        print("# warm pool not started: previous pool still draining",
              file=sys.stderr)
        return
    with _LOCK:
        tier = _PERSIST["dir"]
        items = _warm_items_locked()
        deadline = time.monotonic() + _PERSIST["warm_seconds"]
    if not items or tier is None:
        return
    _WARM_STOP.clear()

    def main():
        from ..parallel.pipeline import parallel_map
        try:
            parallel_map(lambda it: _warm_one(tier, deadline, *it), items,
                         stage="warm-pool")
        except Exception as e:  # never let warming break a session
            print(f"# warm pool aborted: {type(e).__name__}: {e}",
                  file=sys.stderr)

    _WARM_THREAD = threading.Thread(target=main, daemon=True,
                                    name="tpu-warm-pool")
    _WARM_THREAD.start()


def _warm_one(tier_dir: str, deadline: float, sig: str, fname: str,
              aval_sig: str) -> None:
    """Replay one persisted export: deserialize, AOT-compile (an XLA
    disk-cache hit when tier 2 already holds the executable), and install
    a dispatchable entry under the plan signature."""
    if _WARM_STOP.is_set() or time.monotonic() > deadline:
        return
    try:
        from jax import export as jax_export
        path = os.path.join(tier_dir, "exports", os.path.basename(fname))
        with open(path, "rb") as f:
            data = f.read()
        exported = jax_export.deserialize(bytearray(data))
        sds = [jax.ShapeDtypeStruct(a.shape, a.dtype)
               for a in exported.in_avals]
        compiled = jax.jit(exported.call).lower(*sds).compile()
        dispatch = oom_retry(compiled)
    except Exception as e:
        with _LOCK:
            _PSTATS["warm_errors"] += 1
        print(f"# warm pool skipped {sig[:80]!r}: "
              f"{type(e).__name__}: {str(e)[:120]}", file=sys.stderr)
        return
    with _LOCK:
        cur = _CACHE.get(sig)
        if cur is None:
            cur = _CACHE[sig] = _WarmedEntry(sig)
            _PSTATS["warmed_entries"] += 1
            entry = _kernel_entry_locked(sig)
            entry["warmed"] = True
        if isinstance(cur, _WarmedEntry):
            cur.add_record(aval_sig, dispatch)
            _PSTATS["warm_compiles"] += 1
        # else: a live compile beat us to the key — keep the live entry


def warm_pool_wait(timeout: Optional[float] = None) -> bool:
    """Block until warm-pool precompilation settles (bench/tests call this
    before measuring). True when the pool is idle."""
    t = _WARM_THREAD
    if t is None or not t.is_alive():
        return True
    with _LOCK:
        budget = _PERSIST["warm_seconds"] + 10.0
    t.join(timeout if timeout is not None else budget)
    return not t.is_alive()


def stop_warm_pool(timeout: float = 10.0) -> None:
    """Cancel + join the warm pool (session close / reconfigure); part of
    the no-leaked-threads contract."""
    global _WARM_THREAD
    t = _WARM_THREAD
    if t is None:
        return
    _WARM_STOP.set()
    t.join(timeout)
    if t.is_alive():
        # join timed out mid-AOT-compile: keep the handle so the leak is
        # VISIBLE (warm_pool_wait / thread checks still see it) and so
        # _start_warm_pool refuses to race a second pool against it
        print("# warm pool still busy after stop request; it will exit "
              "after the in-flight compile", file=sys.stderr)
        return
    _WARM_THREAD = None


def _export_one(key: str, builder: Callable, skeleton, exports_dir: str
                ) -> Optional[Dict[str, str]]:
    """Serialize the traced program behind ``key`` at its captured input
    shapes. The export wraps the computation in a FLAT (leaves-in,
    leaves-out) function so no custom pytree type needs a serializer;
    dispatch re-learns the output tree from one eval_shape."""
    from jax import export as jax_export
    leaves, treedef = jax.tree_util.tree_flatten(skeleton)

    def flat_fn(*flat):
        a, kw = jax.tree_util.tree_unflatten(treedef, flat)
        out = builder()(*a, **kw)
        return tuple(jax.tree_util.tree_flatten(out)[0])

    exported = jax_export.export(jax.jit(flat_fn))(*leaves)
    data = exported.serialize()
    if len(data) > _EXPORT_MAX_BYTES:
        raise ValueError(f"export too large ({len(data)} bytes) — builder "
                         f"likely closed over concrete data")
    aval_sig = _aval_signature(treedef, leaves)
    fname = hashlib.sha256(
        (key + "|" + aval_sig).encode()).hexdigest()[:24] + ".jaxexport"
    path = os.path.join(exports_dir, fname)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(bytes(data))
    os.replace(tmp, path)
    return {"file": fname, "aval_sig": aval_sig}


def persist_compile_cache() -> int:
    """Flush the engine manifest (+ new program exports) to the tier
    directory — called from TpuSession.close(). Merges this process's
    hit/compile counts into the cumulative cross-process totals, exports
    the hottest newly-compiled programs (bounded by
    warmPool.maxSignatures), and atomically replaces manifest.json.
    Returns the number of exports written; never raises."""
    with _LOCK:
        tier = _PERSIST["dir"]
        if tier is None:
            return 0
        entries: Dict[str, Dict] = {
            sig: dict(e, exports=list(e["exports"]))
            for sig, e in _PERSIST["base"].items()}
        # merge DELTAS vs the last flush, not raw process totals: a
        # process cycling several sessions (or a double close()) must not
        # re-merge counts it already persisted
        flushed = _PERSIST.setdefault("flushed", {})
        kernels, totals = {}, {}
        for sig, e in _KERNELS.items():
            cur = (int(e.get("hits", 0)), int(e.get("compiles", 0)),
                   float(e.get("compile_s", 0.0)))
            prev = flushed.get(sig, (0, 0, 0.0))
            totals[sig] = cur
            kernels[sig] = {"hits": cur[0] - prev[0],
                            "compiles": cur[1] - prev[1],
                            "compile_s": cur[2] - prev[2],
                            "node_name": e.get("node_name")}
        exportable = dict(_EXPORTABLE)
        cap = _PERSIST["warm_max"]
    for sig, k in kernels.items():
        e = entries.setdefault(
            sig, {"hits": 0, "compiles": 0, "compile_s": 0.0,
                  "node_name": None, "exports": []})
        e["hits"] += int(k["hits"])
        e["compiles"] += int(k["compiles"])
        e["compile_s"] = round(e["compile_s"] + float(k["compile_s"]), 6)
        e["node_name"] = e["node_name"] or k["node_name"]
    # export the hottest signatures compiled this process whose captured
    # shapes are not persisted yet
    exports_dir = os.path.join(tier, "exports")
    candidates = sorted(
        exportable, key=lambda s: -(entries.get(s, {}).get("hits", 0)
                                    + entries.get(s, {}).get("compiles", 0)))
    written = 0
    exported_keys = []       # captures persisted (or already on disk) —
    stale_files = []         # release the builder closures afterwards
    for sig in candidates:
        if written >= cap:
            break
        builder, skeleton = exportable[sig]
        entry = entries.setdefault(
            sig, {"hits": 0, "compiles": 0, "compile_s": 0.0,
                  "node_name": None, "exports": []})
        try:
            leaves, treedef = jax.tree_util.tree_flatten(skeleton)
            aval_sig = _aval_signature(treedef, leaves)
            if any(x["aval_sig"] == aval_sig for x in entry["exports"]):
                exported_keys.append(sig)
                continue
            rec = _export_one(sig, builder, skeleton, exports_dir)
        except Exception as e:
            print(f"# compile-cache export skipped {sig[:80]!r}: "
                  f"{type(e).__name__}: {str(e)[:120]}", file=sys.stderr)
            continue
        if rec is not None:
            # newest first; bound the per-signature shape fanout, and
            # reclaim the files of records falling off the end
            kept = [rec] + entry["exports"][:3]
            stale_files.extend(x["file"] for x in entry["exports"][3:])
            entry["exports"] = kept
            written += 1
            exported_keys.append(sig)
    try:
        path = _manifest_path(tier)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "tool": "spark-rapids-tpu",
                       "jax": jax.__version__,
                       "fingerprint": machine_fingerprint(),
                       "entries": entries}, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError as e:
        print(f"# compile-cache manifest not written: {e}", file=sys.stderr)
        return written
    for fname in stale_files:   # only after the manifest dropped them
        try:
            os.unlink(os.path.join(exports_dir, os.path.basename(fname)))
        except OSError:
            pass
    with _LOCK:
        _PERSIST["base"] = entries
        _PERSIST["flushed"] = dict(flushed, **totals)
        for sig in exported_keys:
            _EXPORTABLE.pop(sig, None)
        _PSTATS["manifest_entries"] = len(entries)
        _PSTATS["exports_written"] += written
    return written
