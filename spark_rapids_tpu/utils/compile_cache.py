"""Global XLA compile cache (+ the runtime-OOM recovery chokepoint).

Plans are rebuilt per query execution, but the traced computations repeat
(same operator chains over the same shape buckets). jax.jit caches on the
wrapped callable's identity, so per-plan ``jax.jit(fn)`` wrappers would
recompile every run (~1s each). This cache keys jitted callables by a
canonical plan signature so repeated queries hit steady-state dispatch
(~0.1ms). The reference relies on cuDF's precompiled kernels; on TPU the
compile-once-run-many discipline is ours to enforce.

Every jitted device computation flows through here, which makes it the
TPU-native stand-in for RMM's allocation-failure callback (reference:
DeviceMemoryEventHandler.scala:33): a RESOURCE_EXHAUSTED from the runtime
triggers a synchronous catalog spill and ONE retry; a second failure
re-raises with the catalog's OOM dump attached.
"""
from __future__ import annotations

import functools
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

import jax

from ..conf import register_conf

__all__ = ["cached_jit", "cache_stats", "clear_cache", "oom_retry",
           "configure_introspection", "kernel_table", "kernel_seq",
           "kernels_since", "XLA_INTROSPECTION", "KERNEL_TABLE_SIZE"]

_CACHE: Dict[str, Callable] = {}
_LOCK = threading.Lock()
_HITS = 0
_MISSES = 0
_COMPILES = 0
_COMPILE_SECONDS = 0.0

# ---------------------------------------------------------------------------
# Kernel table: one row per cache entry (= per XLA program), keyed by the
# plan signature and attributed back to the exec node that requested it
# (utils/node_context.py — pushed by the profiler/event-log
# instrumentation). Flushed into event-log schema v3 ``kernel`` records and
# mined by tools/diagnose.py ("q6 dominated by recompiles: N unique
# signatures for 1 operator"). Flare's lesson applies: inspect what the
# compiler actually generated instead of guessing.
# ---------------------------------------------------------------------------
XLA_INTROSPECTION = register_conf(
    "spark.rapids.tpu.metrics.xlaIntrospection",
    "What the compile cache captures about each XLA program into the "
    "kernel table: 'off' records only compile wall/hit counts; 'lowered' "
    "(default) additionally runs HLO cost analysis on the lowered module "
    "(flops / bytes accessed — one cheap retrace per unique program, no "
    "extra XLA compile); 'compiled' also AOT-compiles the captured input "
    "shapes for memory_analysis() (argument/output/temp bytes) — one "
    "EXTRA compile per unique program, meant for offline analysis runs.",
    "lowered",
    checker=lambda v: None if str(v).lower() in ("off", "lowered",
                                                 "compiled")
    else f"must be one of off/lowered/compiled, got {v!r}")

KERNEL_TABLE_SIZE = register_conf(
    "spark.rapids.tpu.metrics.kernelTableSize",
    "Max kernel-table entries kept in memory; least-recently-touched "
    "entries are dropped past the bound (the jitted callables themselves "
    "stay cached).", 4096,
    checker=lambda v: None if int(v) > 0 else "must be positive")

_INTROSPECT_MODE = "lowered"
_KERNEL_TABLE_MAX = 4096
_KERNELS: "Dict[str, Dict]" = {}   # signature -> kernel entry (mutable dict)
_KERNEL_SEQ = 0                    # bumps on every entry touch


def configure_introspection(conf) -> None:
    """Apply spark.rapids.tpu.metrics.* to the process kernel table
    (called from TpuSession.__init__, like configure_tracer)."""
    global _INTROSPECT_MODE, _KERNEL_TABLE_MAX
    _INTROSPECT_MODE = str(conf.get(XLA_INTROSPECTION)).lower()
    _KERNEL_TABLE_MAX = int(conf.get(KERNEL_TABLE_SIZE))


def _touch_locked(entry: Dict) -> None:
    global _KERNEL_SEQ
    _KERNEL_SEQ += 1
    entry["last_touch"] = _KERNEL_SEQ


def _kernel_entry_locked(key: str) -> Dict:
    entry = _KERNELS.get(key)
    if entry is None:
        from .node_context import current
        ctx = current()
        entry = _KERNELS[key] = {
            "signature": key,
            "node_name": ctx.name if ctx is not None else None,
            "node_id": ctx.node_id if ctx is not None else None,
            "query_id": ctx.query_id if ctx is not None else None,
            "hits": 0, "misses": 0, "compiles": 0, "compile_s": 0.0,
            "cost": {}, "memory": {}, "last_touch": 0,
        }
        # touch BEFORE choosing an eviction victim: a fresh entry holds
        # last_touch=0 (the global minimum) and would otherwise evict
        # itself, freezing the table with stale entries at capacity
        _touch_locked(entry)
        if len(_KERNELS) > _KERNEL_TABLE_MAX:
            victim = min(_KERNELS, key=lambda k: _KERNELS[k]["last_touch"])
            del _KERNELS[victim]
    else:
        _touch_locked(entry)
    return entry


def kernel_seq() -> int:
    """Monotonic touch counter — snapshot before a query, pass to
    ``kernels_since`` after it to get the programs that query exercised."""
    with _LOCK:
        return _KERNEL_SEQ


def kernels_since(seq: int) -> List[Dict]:
    """Kernel entries touched (hit, compiled, or created) after ``seq``."""
    with _LOCK:
        return [dict(e) for e in _KERNELS.values() if e["last_touch"] > seq]


def kernel_table() -> List[Dict]:
    """The full kernel table, hottest compile first."""
    with _LOCK:
        rows = [dict(e) for e in _KERNELS.values()]
    return sorted(rows, key=lambda e: -e["compile_s"])


def _aval_of(x):
    """Shape/dtype skeleton of one pytree leaf (weak types collapse — fine
    for cost analysis)."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return jax.ShapeDtypeStruct(x.shape, x.dtype)
    return x


def _introspect(key: str, builder: Callable[[], Callable],
                args, kwargs) -> None:
    """Capture cost/memory analysis for the program behind ``key``.

    Re-lowers the builder against shape skeletons of the first call's
    arguments (jit.lower accepts ShapeDtypeStruct pytrees, so nothing is
    kept resident). Failures are recorded, never raised — introspection
    must not break execution."""
    mode = _INTROSPECT_MODE
    if mode == "off":
        return
    entry_update: Dict = {}
    try:
        avals = jax.tree_util.tree_map(_aval_of, (args, kwargs))
        lowered = jax.jit(builder()).lower(*avals[0], **avals[1])
        cost = lowered.cost_analysis()
        if mode == "compiled":
            compiled = lowered.compile()
            cca = compiled.cost_analysis()
            if cca:
                cost = cca[0] if isinstance(cca, list) else cca
            mem = compiled.memory_analysis()
            if mem is not None:
                entry_update["memory"] = {
                    "argument_bytes": int(mem.argument_size_in_bytes),
                    "output_bytes": int(mem.output_size_in_bytes),
                    "temp_bytes": int(mem.temp_size_in_bytes),
                    "code_bytes": int(mem.generated_code_size_in_bytes),
                }
        if isinstance(cost, list):
            cost = cost[0] if cost else {}
        if cost:
            # keep the totals; the per-operand breakdown keys ("bytes
            # accessed0{}") would bloat every event log
            entry_update["cost"] = {
                k: float(v) for k, v in cost.items() if "{" not in k}
    except Exception as e:  # pragma: no cover - backend-dependent
        entry_update["introspection_error"] = repr(e)[:200]
    with _LOCK:
        entry = _KERNELS.get(key)
        if entry is not None:
            entry.update(entry_update)

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED", "Out of memory",
                "out of memory", "OOM")


def _is_device_oom(e: BaseException) -> bool:
    msg = str(e)
    return isinstance(e, (RuntimeError, MemoryError)) \
        and any(m in msg for m in _OOM_MARKERS)


def oom_retry(fn: Callable) -> Callable:
    """Wrap a device-invoking callable with spill-and-retry-once OOM
    recovery (reference: DeviceMemoryEventHandler.scala:33)."""
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception as e:
            if not _is_device_oom(e):
                raise
            from ..memory.catalog import get_catalog
            catalog = get_catalog()
            freed = catalog.handle_device_oom(context=repr(e)[:200])
            print(f"# device OOM: spilled {freed} bytes, retrying once "
                  f"({type(e).__name__})", file=sys.stderr)
            if freed <= 0:
                raise RuntimeError(catalog.oom_dump()) from e
            try:
                return fn(*args, **kwargs)
            except Exception as e2:
                if _is_device_oom(e2):
                    raise RuntimeError(catalog.oom_dump()) from e2
                raise
    return wrapped


def oom_spill_noretry(fn: Callable) -> Callable:
    """OOM handling for DONATING entries (donate_argnums): a failed
    dispatch may already have invalidated the donated input buffers, so
    re-calling with the same arguments — oom_retry's recovery — is
    unsound. Spill to relieve pressure for SUBSEQUENT batches, then
    re-raise with the catalog's OOM dump attached."""
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception as e:
            if not _is_device_oom(e):
                raise
            from ..memory.catalog import get_catalog
            catalog = get_catalog()
            freed = catalog.handle_device_oom(context=repr(e)[:200])
            print(f"# device OOM in donating dispatch: spilled {freed} "
                  f"bytes for later batches (input was donated — no "
                  f"retry)", file=sys.stderr)
            raise RuntimeError(catalog.oom_dump()) from e
    return wrapped


_EXEC_MISMATCH_MARKERS = ("but got buffer with incompatible size",
                          "buffers but compiled program expected")


def _rebuild_on_mismatch(key: str, builder: Callable[[], Callable],
                         fn: Callable) -> Callable:
    """jax 0.9 workaround: a jit wrapper's dispatch cache can resolve to a
    stale executable for inputs whose treedef+avals are IDENTICAL to a
    previously successful call (observed with (n, 2) two-limb decimal128
    columns — no-lengths 2-D data planes). A fresh jax.jit of the same
    builder always works, so on that specific INVALID_ARGUMENT signature
    the entry is rebuilt once and the call retried."""
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except ValueError as e:
            msg = str(e)
            if not any(m in msg for m in _EXEC_MISMATCH_MARKERS):
                raise
            fresh = oom_retry(jax.jit(builder()))
            with _LOCK:
                _CACHE[key] = _rebuild_on_mismatch(key, builder, fresh)
            return fresh(*args, **kwargs)
    return wrapped


def _time_first_call(key: str, fn: Callable,
                     builder: Optional[Callable[[], Callable]] = None
                     ) -> Callable:
    """Attribute a cache entry's first invocation to XLA compile time.

    jax.jit compiles lazily on first dispatch, so the first call through a
    fresh entry is (compile + run); later calls are steady-state dispatch.
    Timing the first call is the standard approximation for per-plan
    compile seconds (the run part is dwarfed by the ~1s trace+compile),
    and it scopes the call in a "compile" trace span so Perfetto shows
    compile stalls on the query timeline. The first call also feeds the
    kernel table: compile wall + (when introspection is on) the program's
    HLO cost/memory analysis, attributed to the executing node."""
    state = {"done": False}

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        global _COMPILES, _COMPILE_SECONDS
        if state["done"]:
            return fn(*args, **kwargs)
        from .tracing import get_tracer
        t0 = time.perf_counter()
        with get_tracer().span("xla_compile", "compile", key=key[:160]):
            out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        first = False
        with _LOCK:
            # check-and-set under the lock: concurrent first dispatches of
            # one entry must attribute the compile exactly once
            if not state["done"]:
                state["done"] = True
                first = True
                _COMPILES += 1
                _COMPILE_SECONDS += dt
                entry = _KERNELS.get(key)
                if entry is not None:
                    entry["compiles"] += 1
                    entry["compile_s"] += dt
                    _touch_locked(entry)
        if first:
            # a finished compile IS engine progress: without this, a
            # compile-heavy warm-up phase (many first dispatches, no
            # batches accounted yet) looks frozen to the health watchdog
            from ..parallel.pipeline import note_progress
            note_progress()
            from .node_context import current_registry
            reg = current_registry()
            if reg is not None:
                from . import metrics as M
                reg.add(M.COMPILE_TIME, dt)
            if builder is not None:
                _introspect(key, builder, args, kwargs)
        return out
    return wrapped


def _attribute(metric_name: str) -> None:
    """Count a cache hit/miss on the executing node's registry (no-op when
    uninstrumented — process-global counters still track)."""
    from .node_context import current_registry
    reg = current_registry()
    if reg is not None:
        reg.add(metric_name, 1)


def cached_jit(key: str, builder: Callable[[], Callable],
               donate_argnums=None) -> Callable:
    """Return a jitted callable for ``key``, building it on first use.

    ``donate_argnums`` requests XLA input-buffer donation for the jitted
    entry (exec/wholestage.py input donation — callers MUST key donating
    and non-donating variants differently: the option is baked into the
    compiled executable)."""
    global _HITS, _MISSES
    from . import metrics as M
    with _LOCK:
        fn = _CACHE.get(key)
        if fn is not None:
            _HITS += 1
            entry = _KERNELS.get(key)
            if entry is not None:
                entry["hits"] += 1
                _touch_locked(entry)
        else:
            _MISSES += 1
            _kernel_entry_locked(key)["misses"] += 1
    if fn is not None:
        _attribute(M.COMPILE_CACHE_HITS)
        return fn
    _attribute(M.COMPILE_CACHE_MISSES)
    if donate_argnums is None:
        built = _time_first_call(key, _rebuild_on_mismatch(
            key, builder, oom_retry(jax.jit(builder()))), builder)
    else:
        # donating entries get NO call-again recovery (oom_retry or the
        # mismatch rebuild): the failed dispatch may have consumed the
        # donated input, so the only sound OOM response is spill-and-raise
        built = _time_first_call(key, oom_spill_noretry(
            jax.jit(builder(), donate_argnums=donate_argnums)), builder)
    with _LOCK:
        return _CACHE.setdefault(key, built)


def cache_stats() -> Dict[str, float]:
    return {"entries": len(_CACHE), "hits": _HITS, "misses": _MISSES,
            "compiles": _COMPILES,
            "compile_seconds": round(_COMPILE_SECONDS, 6)}


def clear_cache():
    global _HITS, _MISSES, _COMPILES, _COMPILE_SECONDS
    with _LOCK:
        _CACHE.clear()
        _KERNELS.clear()
        _HITS = _MISSES = 0
        _COMPILES = 0
        _COMPILE_SECONDS = 0.0
