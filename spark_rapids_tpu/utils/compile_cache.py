"""Global XLA compile cache.

Plans are rebuilt per query execution, but the traced computations repeat
(same operator chains over the same shape buckets). jax.jit caches on the
wrapped callable's identity, so per-plan ``jax.jit(fn)`` wrappers would
recompile every run (~1s each). This cache keys jitted callables by a
canonical plan signature so repeated queries hit steady-state dispatch
(~0.1ms). The reference relies on cuDF's precompiled kernels; on TPU the
compile-once-run-many discipline is ours to enforce.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict

import jax

__all__ = ["cached_jit", "cache_stats", "clear_cache"]

_CACHE: Dict[str, Callable] = {}
_LOCK = threading.Lock()
_HITS = 0
_MISSES = 0


def cached_jit(key: str, builder: Callable[[], Callable]) -> Callable:
    """Return a jitted callable for ``key``, building it on first use."""
    global _HITS, _MISSES
    with _LOCK:
        fn = _CACHE.get(key)
        if fn is not None:
            _HITS += 1
            return fn
        _MISSES += 1
    built = jax.jit(builder())
    with _LOCK:
        return _CACHE.setdefault(key, built)


def cache_stats() -> Dict[str, int]:
    return {"entries": len(_CACHE), "hits": _HITS, "misses": _MISSES}


def clear_cache():
    global _HITS, _MISSES
    with _LOCK:
        _CACHE.clear()
        _HITS = _MISSES = 0
