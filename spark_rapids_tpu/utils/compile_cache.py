"""Global XLA compile cache (+ the runtime-OOM recovery chokepoint).

Plans are rebuilt per query execution, but the traced computations repeat
(same operator chains over the same shape buckets). jax.jit caches on the
wrapped callable's identity, so per-plan ``jax.jit(fn)`` wrappers would
recompile every run (~1s each). This cache keys jitted callables by a
canonical plan signature so repeated queries hit steady-state dispatch
(~0.1ms). The reference relies on cuDF's precompiled kernels; on TPU the
compile-once-run-many discipline is ours to enforce.

Every jitted device computation flows through here, which makes it the
TPU-native stand-in for RMM's allocation-failure callback (reference:
DeviceMemoryEventHandler.scala:33): a RESOURCE_EXHAUSTED from the runtime
triggers a synchronous catalog spill and ONE retry; a second failure
re-raises with the catalog's OOM dump attached.
"""
from __future__ import annotations

import functools
import sys
import threading
import time
from typing import Callable, Dict

import jax

__all__ = ["cached_jit", "cache_stats", "clear_cache", "oom_retry"]

_CACHE: Dict[str, Callable] = {}
_LOCK = threading.Lock()
_HITS = 0
_MISSES = 0
_COMPILES = 0
_COMPILE_SECONDS = 0.0

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED", "Out of memory",
                "out of memory", "OOM")


def _is_device_oom(e: BaseException) -> bool:
    msg = str(e)
    return isinstance(e, (RuntimeError, MemoryError)) \
        and any(m in msg for m in _OOM_MARKERS)


def oom_retry(fn: Callable) -> Callable:
    """Wrap a device-invoking callable with spill-and-retry-once OOM
    recovery (reference: DeviceMemoryEventHandler.scala:33)."""
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception as e:
            if not _is_device_oom(e):
                raise
            from ..memory.catalog import get_catalog
            catalog = get_catalog()
            freed = catalog.handle_device_oom(context=repr(e)[:200])
            print(f"# device OOM: spilled {freed} bytes, retrying once "
                  f"({type(e).__name__})", file=sys.stderr)
            if freed <= 0:
                raise RuntimeError(catalog.oom_dump()) from e
            try:
                return fn(*args, **kwargs)
            except Exception as e2:
                if _is_device_oom(e2):
                    raise RuntimeError(catalog.oom_dump()) from e2
                raise
    return wrapped


_EXEC_MISMATCH_MARKERS = ("but got buffer with incompatible size",
                          "buffers but compiled program expected")


def _rebuild_on_mismatch(key: str, builder: Callable[[], Callable],
                         fn: Callable) -> Callable:
    """jax 0.9 workaround: a jit wrapper's dispatch cache can resolve to a
    stale executable for inputs whose treedef+avals are IDENTICAL to a
    previously successful call (observed with (n, 2) two-limb decimal128
    columns — no-lengths 2-D data planes). A fresh jax.jit of the same
    builder always works, so on that specific INVALID_ARGUMENT signature
    the entry is rebuilt once and the call retried."""
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except ValueError as e:
            msg = str(e)
            if not any(m in msg for m in _EXEC_MISMATCH_MARKERS):
                raise
            fresh = oom_retry(jax.jit(builder()))
            with _LOCK:
                _CACHE[key] = _rebuild_on_mismatch(key, builder, fresh)
            return fresh(*args, **kwargs)
    return wrapped


def _time_first_call(key: str, fn: Callable) -> Callable:
    """Attribute a cache entry's first invocation to XLA compile time.

    jax.jit compiles lazily on first dispatch, so the first call through a
    fresh entry is (compile + run); later calls are steady-state dispatch.
    Timing the first call is the standard approximation for per-plan
    compile seconds (the run part is dwarfed by the ~1s trace+compile),
    and it scopes the call in a "compile" trace span so Perfetto shows
    compile stalls on the query timeline."""
    state = {"done": False}

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        global _COMPILES, _COMPILE_SECONDS
        if state["done"]:
            return fn(*args, **kwargs)
        from .tracing import get_tracer
        t0 = time.perf_counter()
        with get_tracer().span("xla_compile", "compile", key=key[:160]):
            out = fn(*args, **kwargs)
        with _LOCK:
            # check-and-set under the lock: concurrent first dispatches of
            # one entry must attribute the compile exactly once
            if not state["done"]:
                state["done"] = True
                _COMPILES += 1
                _COMPILE_SECONDS += time.perf_counter() - t0
        return out
    return wrapped


def cached_jit(key: str, builder: Callable[[], Callable]) -> Callable:
    """Return a jitted callable for ``key``, building it on first use."""
    global _HITS, _MISSES
    with _LOCK:
        fn = _CACHE.get(key)
        if fn is not None:
            _HITS += 1
            return fn
        _MISSES += 1
    built = _time_first_call(key, _rebuild_on_mismatch(
        key, builder, oom_retry(jax.jit(builder()))))
    with _LOCK:
        return _CACHE.setdefault(key, built)


def cache_stats() -> Dict[str, float]:
    return {"entries": len(_CACHE), "hits": _HITS, "misses": _MISSES,
            "compiles": _COMPILES,
            "compile_seconds": round(_COMPILE_SECONDS, 6)}


def clear_cache():
    global _HITS, _MISSES, _COMPILES, _COMPILE_SECONDS
    with _LOCK:
        _CACHE.clear()
        _HITS = _MISSES = 0
        _COMPILES = 0
        _COMPILE_SECONDS = 0.0
