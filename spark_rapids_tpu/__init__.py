"""spark-rapids-tpu: a TPU-native columnar SQL acceleration framework.

A ground-up re-design of the RAPIDS Accelerator for Apache Spark
(reference: hyperbolic2346/spark-rapids) targeting TPU via JAX/XLA:

- columnar device batches are JAX pytrees with bucketed static shapes
  (``spark_rapids_tpu.columnar``)
- a Catalyst-style plan framework tags and lowers logical plans onto device
  operators with per-op fallback reasons (``spark_rapids_tpu.plan``)
- device operators execute as fused, jitted XLA computations
  (``spark_rapids_tpu.exec``)
- exchanges ride device-mesh collectives (``spark_rapids_tpu.shuffle``,
  ``spark_rapids_tpu.parallel``)
"""

__version__ = "0.1.0"

import jax as _jax

# SQL semantics require 64-bit longs/doubles/timestamps; JAX defaults to 32.
_jax.config.update("jax_enable_x64", True)

from .conf import RapidsConf  # noqa: F401
from .columnar import (  # noqa: F401
    HostTable, DeviceTable, TypeSig,
)
