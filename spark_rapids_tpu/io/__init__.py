from .memory import InMemorySource  # noqa: F401
