"""Device-side parquet WRITE (PLAIN v1 pages).

Reference: GpuParquetFileFormat.scala:351 + ColumnarOutputWriter.scala —
the GPU encodes column chunks and the host only assembles file framing.
The TPU-native split: the DEVICE compacts each batch and packs every
column's non-null values dense (gather/argsort kernels — the actual data
movement); the HOST turns the downloaded dense buffers into PLAIN pages
and writes the thrift framing (page headers + footer) with a minimal
compact-protocol writer (io/parquet_thrift.py is the matching reader).

Scope: flat columns — BOOLEAN/INT32/INT64/FLOAT/DOUBLE physical types
(+ DATE/TIMESTAMP_MICROS logical annotations) and BYTE_ARRAY strings/
binary; one data page per column chunk, one row group per device batch;
UNCOMPRESSED or SNAPPY page codec. Everything else falls back to the
pyarrow writer in io/writer.py.
"""
from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import numpy as np

from ..columnar import dtypes as dt
from ..columnar.device import DeviceTable
from ..conf import register_conf

__all__ = ["PARQUET_DEVICE_WRITE", "schema_supported",
           "write_device_parquet"]

PARQUET_DEVICE_WRITE = register_conf(
    "spark.rapids.tpu.parquet.deviceWrite.enabled",
    "Encode parquet output from device buffers (device compaction + dense "
    "packing; host assembles PLAIN v1 pages and thrift framing — "
    "reference: GpuParquetFileFormat.scala:351). Unsupported schemas fall "
    "back to the pyarrow writer.", True)

# parquet.thrift enums
_T_BOOLEAN, _T_INT32, _T_INT64, _T_FLOAT, _T_DOUBLE, _T_BYTE_ARRAY = \
    0, 1, 2, 4, 5, 6
_CT_UTF8, _CT_DATE, _CT_TS_MICROS = 0, 6, 10
_ENC_PLAIN, _ENC_RLE = 0, 3
_CODEC = {"none": 0, "uncompressed": 0, "snappy": 1}


def _phys_of(d: dt.DataType) -> Optional[Tuple[int, Optional[int]]]:
    """-> (physical type, converted type) or None if unsupported."""
    if isinstance(d, dt.BooleanType):
        return _T_BOOLEAN, None
    if isinstance(d, dt.IntegerType):
        return _T_INT32, None
    if isinstance(d, dt.LongType):
        return _T_INT64, None
    if isinstance(d, dt.FloatType):
        return _T_FLOAT, None
    if isinstance(d, dt.DoubleType):
        return _T_DOUBLE, None
    if isinstance(d, dt.DateType):
        return _T_INT32, _CT_DATE
    if isinstance(d, dt.TimestampType):
        # naive (session-local) micros: ConvertedType TIMESTAMP_MICROS
        # would imply isAdjustedToUTC=true, so only the LogicalType is
        # written (as pyarrow does for naive timestamps)
        return _T_INT64, None
    if isinstance(d, dt.StringType):
        return _T_BYTE_ARRAY, _CT_UTF8
    if isinstance(d, dt.BinaryType):
        return _T_BYTE_ARRAY, None
    return None


def schema_supported(schema) -> bool:
    return all(_phys_of(f.dtype) is not None for f in schema)


# ---------------------------------------------------------------------------
# Thrift compact-protocol WRITER (inverse of parquet_thrift.py's reader)
# ---------------------------------------------------------------------------
_CTW_BOOL_TRUE = 1
_CTW_I32 = 5
_CTW_I64 = 6
_CTW_BINARY = 8
_CTW_LIST = 9
_CTW_STRUCT = 12


class _ThriftWriter:
    def __init__(self):
        self.b = bytearray()
        self._fid_stack: List[int] = []
        self._fid = 0

    def _varint(self, v: int):
        while True:
            if v < 0x80:
                self.b.append(v)
                return
            self.b.append((v & 0x7F) | 0x80)
            v >>= 7

    def _zig(self, v: int):
        self._varint((v << 1) ^ (v >> 63) if v < 0 else (v << 1))

    def field(self, fid: int, ctype: int):
        delta = fid - self._fid
        if 0 < delta < 16:
            self.b.append((delta << 4) | ctype)
        else:
            self.b.append(ctype)
            self._zig(fid)
        self._fid = fid

    def i32(self, fid: int, v: int):
        self.field(fid, _CTW_I32)
        self._zig(v)

    def i64(self, fid: int, v: int):
        self.field(fid, _CTW_I64)
        self._zig(v)

    def binary(self, fid: int, data: bytes):
        self.field(fid, _CTW_BINARY)
        self._varint(len(data))
        self.b += data

    def string(self, fid: int, s: str):
        self.binary(fid, s.encode())

    def bool_field(self, fid: int, value: bool):
        self.field(fid, _CTW_BOOL_TRUE if value else 2)  # 2 = compact FALSE

    def struct_begin(self, fid: int):
        self.field(fid, _CTW_STRUCT)
        self._fid_stack.append(self._fid)
        self._fid = 0

    def struct_end(self):
        self.b.append(0)
        self._fid = self._fid_stack.pop()

    def list_begin(self, fid: int, etype: int, n: int):
        self.field(fid, _CTW_LIST)
        if n < 15:
            self.b.append((n << 4) | etype)
        else:
            self.b.append(0xF0 | etype)
            self._varint(n)

    def elem_struct_begin(self):
        self._fid_stack.append(self._fid)
        self._fid = 0

    def elem_struct_end(self):
        self.b.append(0)
        self._fid = self._fid_stack.pop()

    def elem_i32(self, v: int):
        self._zig(v)


# ---------------------------------------------------------------------------
# page assembly
# ---------------------------------------------------------------------------
def _rle_def_levels(validity: np.ndarray) -> bytes:
    """Validity -> RLE-hybrid stream at bit width 1 (run-length encoded;
    vectorized run detection)."""
    n = len(validity)
    if n == 0:
        return b""
    v = validity.astype(np.uint8)
    change = np.nonzero(np.diff(v))[0] + 1
    starts = np.concatenate(([0], change))
    ends = np.concatenate((change, [n]))
    out = bytearray()
    for s, e in zip(starts.tolist(), ends.tolist()):
        run = e - s
        header = run << 1            # LSB 0 = RLE run
        while header >= 0x80:
            out.append((header & 0x7F) | 0x80)
            header >>= 7
        out.append(header)
        out.append(int(v[s]))        # 1-byte value at bit width 1
    return bytes(out)


def _plain_byte_array(mat: np.ndarray, lengths: np.ndarray) -> bytes:
    """(n, w) byte matrix + lengths -> PLAIN BYTE_ARRAY stream (u32 length
    prefix per value), assembled with one vectorized scatter."""
    n = len(lengths)
    lengths = lengths.astype(np.int64)
    rec_starts = np.cumsum(4 + lengths) - (4 + lengths)
    total = int((4 + lengths).sum())
    out = np.zeros(total, dtype=np.uint8)
    lenb = lengths.astype("<u4").view(np.uint8).reshape(n, 4)
    pos4 = (rec_starts[:, None] + np.arange(4)[None, :]).ravel()
    out[pos4] = lenb.ravel()
    tot_data = int(lengths.sum())
    if tot_data:
        rows = np.repeat(np.arange(n, dtype=np.int64), lengths)
        prefix = np.cumsum(lengths) - lengths
        cols = np.arange(tot_data, dtype=np.int64) - np.repeat(prefix, lengths)
        out[np.repeat(rec_starts + 4, lengths) + cols] = mat[rows, cols]
    return out.tobytes()


def _compress(data: bytes, codec: str) -> bytes:
    if _CODEC.get(codec, 0) == 0:
        return data
    import pyarrow as pa
    return pa.compress(data, codec="snappy", asbytes=True)


class _ColumnState:
    def __init__(self, name: str, dtype: dt.DataType, nullable: bool):
        self.name = name
        self.dtype = dtype
        self.nullable = nullable
        self.phys, self.conv = _phys_of(dtype)


def _dense_device(table: DeviceTable):
    """DEVICE work: compact active rows, then pack each column's non-null
    values dense (argsort gather) — one eager jnp pass; only dense
    buffers + validity bits download."""
    import jax.numpy as jnp
    t = table.compact()
    n = int(t.num_rows)
    out = []
    for c in t.columns:
        validity = jnp.logical_and(
            c.validity, jnp.arange(t.capacity) < t.num_rows)
        order = jnp.argsort(jnp.logical_not(validity), stable=True)
        dense = jnp.take(c.data, order, axis=0)
        dlen = jnp.take(c.lengths, order) if c.lengths is not None else None
        n_valid = int(jnp.sum(validity))
        host_vals = np.asarray(dense)[:n_valid]
        host_lens = None if dlen is None else np.asarray(dlen)[:n_valid]
        host_valid = np.asarray(validity)[:n]
        out.append((host_vals, host_lens, host_valid, n_valid))
    return n, out


def write_device_parquet(batches: List[DeviceTable], path: str, schema,
                         codec: str = "snappy") -> int:
    """Write one parquet file (one row group per batch). Returns rows."""
    cols = [_ColumnState(f.name, f.dtype, f.nullable) for f in schema]
    body = bytearray(b"PAR1")
    row_groups = []   # (num_rows, [(col, num_values, dpo, comp, uncomp)])
    total_rows = 0
    for batch in batches:
        n, dense = _dense_device(batch)
        if n == 0:
            continue
        total_rows += n
        chunk_meta = []
        for cs, (vals, lens, valid, n_valid) in zip(cols, dense):
            # definition levels (v1: length-prefixed RLE) when nullable
            parts = []
            if cs.nullable:
                levels = _rle_def_levels(valid)
                parts.append(struct.pack("<I", len(levels)) + levels)
            if cs.phys == _T_BOOLEAN:
                parts.append(np.packbits(
                    vals.astype(np.uint8), bitorder="little").tobytes())
            elif cs.phys == _T_BYTE_ARRAY:
                parts.append(_plain_byte_array(vals, lens))
            else:
                npdt = {_T_INT32: "<i4", _T_INT64: "<i8",
                        _T_FLOAT: "<f4", _T_DOUBLE: "<f8"}[cs.phys]
                parts.append(np.ascontiguousarray(
                    vals).astype(npdt, copy=False).tobytes())
            raw = b"".join(parts)
            page = _compress(raw, codec)
            hdr = _ThriftWriter()
            hdr.i32(1, 0)                       # PageType.DATA_PAGE
            hdr.i32(2, len(raw))                # uncompressed_page_size
            hdr.i32(3, len(page))               # compressed_page_size
            hdr.struct_begin(5)                 # DataPageHeader
            hdr.i32(1, n)                       # num_values (incl. nulls)
            hdr.i32(2, _ENC_PLAIN)
            hdr.i32(3, _ENC_RLE)                # definition levels
            hdr.i32(4, _ENC_RLE)                # repetition levels (unused)
            hdr.struct_end()
            hdr.b.append(0)                     # end PageHeader struct
            dpo = len(body)
            body += bytes(hdr.b) + page
            chunk_meta.append(
                (cs, n, dpo, len(bytes(hdr.b)) + len(page),
                 len(bytes(hdr.b)) + len(raw)))
        row_groups.append((n, chunk_meta))

    # ---- footer (FileMetaData)
    fw = _ThriftWriter()
    fw.i32(1, 1)                                # version
    fw.list_begin(2, _CTW_STRUCT, len(cols) + 1)   # schema
    fw.elem_struct_begin()                      # root SchemaElement
    fw.string(4, "schema")
    fw.i32(5, len(cols))                        # num_children
    fw.elem_struct_end()
    for cs in cols:
        fw.elem_struct_begin()
        fw.i32(1, cs.phys)
        fw.i32(3, 1 if cs.nullable else 0)      # OPTIONAL / REQUIRED
        fw.string(4, cs.name)
        if cs.conv is not None:
            fw.i32(6, cs.conv)
        if isinstance(cs.dtype, dt.TimestampType):
            fw.struct_begin(10)                 # LogicalType union
            fw.struct_begin(8)                  # .TIMESTAMP
            fw.bool_field(1, False)             # isAdjustedToUTC
            fw.struct_begin(2)                  # unit union
            fw.struct_begin(2)                  # .MICROS {}
            fw.struct_end()
            fw.struct_end()
            fw.struct_end()
            fw.struct_end()
        fw.elem_struct_end()
    fw.i64(3, total_rows)
    fw.list_begin(4, _CTW_STRUCT, len(row_groups))
    for n, chunk_meta in row_groups:
        fw.elem_struct_begin()                  # RowGroup
        fw.list_begin(1, _CTW_STRUCT, len(chunk_meta))
        total_bytes = 0
        for cs, nvals, dpo, comp, uncomp in chunk_meta:
            fw.elem_struct_begin()              # ColumnChunk
            fw.i64(2, dpo)                      # file_offset
            fw.struct_begin(3)                  # ColumnMetaData
            fw.i32(1, cs.phys)
            fw.list_begin(2, _CTW_I32, 2)       # encodings
            fw.elem_i32(_ENC_PLAIN)
            fw.elem_i32(_ENC_RLE)
            fw.list_begin(3, _CTW_BINARY, 1)    # path_in_schema
            fw._varint(len(cs.name.encode()))
            fw.b += cs.name.encode()
            fw.i32(4, _CODEC.get(codec, 0))
            fw.i64(5, nvals)
            fw.i64(6, uncomp)
            fw.i64(7, comp)
            fw.i64(9, dpo)                      # data_page_offset
            fw.struct_end()
            fw.elem_struct_end()
            total_bytes += comp
        fw.i64(2, total_bytes)
        fw.i64(3, n)
        fw.elem_struct_end()
    fw.string(6, "spark-rapids-tpu device writer")
    fw.b.append(0)                              # end FileMetaData
    footer = bytes(fw.b)
    body += footer + struct.pack("<I", len(footer)) + b"PAR1"
    with open(path, "wb") as f:
        f.write(bytes(body))
    return total_rows
