"""Device-side parquet decode.

Reference: the GPU plugin's biggest IO win is decoding parquet ON the
accelerator — raw column chunks go to the device and cuDF kernels expand
them (GpuParquetScanBase.scala:995,1194). The TPU-native shape of that
design, mapped onto XLA's static-shape world:

- HOST does the byte plumbing: file reads, page-header parsing
  (io/parquet_thrift.py), page decompression, and a one-pass scan of the
  RLE/bit-packed hybrid streams into *run tables* (a few entries per run,
  NOT per value — the classic GPU decoder split).
- DEVICE does the per-value work, one fused jit per column chunk:
  run-table expansion (searchsorted over run starts), bit-field extraction
  of dictionary indices from the packed blob, dictionary gather, and
  null-scatter of the dense non-null values into row slots via a validity
  cumsum.

Supported (everything else falls back per COLUMN to pyarrow + upload):
flat columns (no repetition), physical BOOLEAN/INT32/INT64/FLOAT/DOUBLE/
BYTE_ARRAY (strings/binary via the bucketed byte-matrix layout), data-page
v1 AND v2, PLAIN or RLE_DICTIONARY values including chunks whose pages
switch dictionary->plain mid-chunk (the pyarrow dictionary-overflow
fallback), any pyarrow-decompressible codec. Output is bit-identical to
the host path (DeviceTable.from_host of the pyarrow read).
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..columnar import dtypes as dt
from ..columnar.host import _arrow_to_dtype
from ..conf import register_conf
from .parquet_thrift import Encoding, PageType, read_page_header

__all__ = ["PARQUET_DEVICE_DECODE", "chunk_supported", "decode_row_group",
           "UnsupportedChunk"]

PARQUET_DEVICE_DECODE = register_conf(
    "spark.rapids.tpu.parquet.deviceDecode.enabled",
    "Decode supported parquet columns on the device (run-table expansion + "
    "dictionary gather kernels; reference: GpuParquetScanBase device "
    "decode). Unsupported columns fall back to host decode per column.",
    True)

# per-type device-decode gates (reference: the per-type read enables of
# RapidsConf.scala:877-917 — risky parses get their own kill switch)
PARQUET_DEVICE_DECODE_STRINGS = register_conf(
    "spark.rapids.tpu.parquet.deviceDecode.strings.enabled",
    "Decode BYTE_ARRAY (string/binary) parquet columns on device; false "
    "keeps strings on the per-column host decode.", True)

PARQUET_DEVICE_DECODE_BOOLEANS = register_conf(
    "spark.rapids.tpu.parquet.deviceDecode.booleans.enabled",
    "Decode BOOLEAN parquet columns on device; false keeps booleans on "
    "the per-column host decode.", True)

_PHYS_OK = {"BOOLEAN", "INT32", "INT64", "FLOAT", "DOUBLE", "BYTE_ARRAY"}
_ENC_OK = {"PLAIN", "RLE", "RLE_DICTIONARY", "PLAIN_DICTIONARY",
           "BIT_PACKED"}


class UnsupportedChunk(Exception):
    """Column chunk outside the device decoder's subset."""


def chunk_supported(col_meta, arrow_field, conf=None) -> bool:
    """Static (metadata-only) eligibility of one column chunk."""
    import pyarrow as pa
    if col_meta.physical_type not in _PHYS_OK:
        return False
    if conf is not None:
        if col_meta.physical_type == "BYTE_ARRAY" \
                and not conf.get(PARQUET_DEVICE_DECODE_STRINGS):
            return False
        if col_meta.physical_type == "BOOLEAN" \
                and not conf.get(PARQUET_DEVICE_DECODE_BOOLEANS):
            return False
    if any(e not in _ENC_OK for e in col_meta.encodings):
        return False
    t = arrow_field.type
    if pa.types.is_nested(t) or pa.types.is_dictionary(t):
        return False
    try:
        d = _arrow_to_dtype(t)
    except Exception:
        return False
    if isinstance(d, dt.DecimalType):
        return False
    if isinstance(d, (dt.StringType, dt.BinaryType)):
        return col_meta.physical_type == "BYTE_ARRAY"
    return col_meta.physical_type != "BYTE_ARRAY"


# ---------------------------------------------------------------------------
# Host side: pages -> merged run tables
# ---------------------------------------------------------------------------
def _decompress(buf: bytes, codec: str, uncompressed_size: int) -> bytes:
    if codec in ("UNCOMPRESSED", None):
        return buf
    import pyarrow as pa
    return pa.decompress(buf, decompressed_size=uncompressed_size,
                         codec=codec.lower()).to_pybytes()


class _RunTable:
    """Accumulated RLE/bit-packed runs across a chunk's pages."""

    def __init__(self):
        self.out_start: List[int] = []
        self.count: List[int] = []
        self.is_rle: List[bool] = []
        self.rle_value: List[int] = []
        self.bit_base: List[int] = []   # absolute first-bit into self.packed
        self.width: List[int] = []      # PER-RUN bit width (pages with a
        # growing dictionary are written at increasing widths!)
        self.packed = bytearray()
        self.total = 0

    def parse_hybrid(self, buf: bytes, pos: int, end: int, width: int,
                     max_count: int) -> None:
        """One RLE-hybrid stream (parquet format spec): header varint LSB
        selects bit-packed groups vs RLE run."""
        if width == 0:
            # zero-width stream: max_count zeros, no bytes
            self._push_rle(max_count, 0)
            return
        produced = 0
        vbytes = (width + 7) // 8
        while pos < end and produced < max_count:
            header = 0
            shift = 0
            while True:
                b = buf[pos]
                pos += 1
                header |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            if header & 1:  # bit-packed groups
                groups = header >> 1
                nvals = min(groups * 8, max_count - produced)
                nbytes = groups * width  # groups*8 values * width/8 bits
                self.out_start.append(self.total)
                self.count.append(nvals)
                self.is_rle.append(False)
                self.rle_value.append(0)
                self.bit_base.append(len(self.packed) * 8)
                self.width.append(width)
                self.packed.extend(buf[pos:pos + nbytes])
                pos += nbytes
                self.total += nvals
                produced += nvals
            else:           # RLE run
                run = min(header >> 1, max_count - produced)
                v = int.from_bytes(buf[pos:pos + vbytes], "little")
                pos += vbytes
                self._push_rle(run, v)
                produced += run

    def _push_rle(self, run: int, v: int) -> None:
        if run <= 0:
            return
        self.out_start.append(self.total)
        self.count.append(run)
        self.is_rle.append(True)
        self.rle_value.append(v)
        self.bit_base.append(0)
        self.width.append(0)
        self.total += run

    def arrays(self) -> Tuple[np.ndarray, ...]:
        # pow2-pad entry count and packed blob so XLA sees a bounded shape
        # set across chunks (padding runs have out_start == total -> the
        # searchsorted expansion never selects them)
        n = _pow2(max(1, len(self.out_start)))
        pad = n - len(self.out_start)
        out_start = np.asarray(self.out_start + [self.total] * pad, np.int64)
        packed = np.frombuffer(bytes(self.packed) or b"\0", np.uint8)
        packed = np.pad(packed, (0, _pow2(len(packed)) - len(packed)))
        return (out_start,
                np.asarray(self.is_rle + [True] * pad, np.bool_),
                np.asarray(self.rle_value + [0] * pad, np.int64),
                np.asarray(self.bit_base + [0] * pad, np.int64),
                np.asarray(self.width + [0] * pad, np.int64),
                packed)


class _Chunk:
    """Parsed column chunk: run tables + dense plain values + dictionary.

    The dense non-null value stream of a chunk is [dictionary-encoded
    pages' values] ++ [plain pages' values] — parquet writers that
    overflow their dictionary (pyarrow's 1MB default) switch to PLAIN for
    the REST of the chunk, never back, so segment order is statically
    dict-then-plain."""

    def __init__(self):
        self.defs = _RunTable()      # definition levels (width 1)
        self.idx = _RunTable()       # dictionary indices (width per page)
        self.idx_width: int = 0
        self.plain_parts: List[bytes] = []
        self.dictionary: Optional[np.ndarray] = None
        # BYTE_ARRAY: dictionary entries + per-page plain streams, kept as
        # (starts, lengths, blob) triples until the matrix assembly
        self.ba_dict: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None
        self.ba_plain: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        self.num_rows = 0
        self.nullable = False
        self.bool_plain: List[Tuple[bytes, int]] = []  # packed bits, count
        self.uses_dict = False
        self.uses_plain = False


def _parse_byte_array_stream(buf: bytes, n: int
                             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Walk a PLAIN BYTE_ARRAY stream (u32 length prefix per value) ->
    (starts, lengths, blob) without copying the value bytes. The walk is
    sequential; the native C helper does it at memory speed, with a
    Python loop as the compiler-less fallback."""
    from .. import native
    walked = native.ba_walk(buf, n)
    if walked is not None:
        starts, lens, pos = walked
        return starts, lens, np.frombuffer(buf, np.uint8, pos)
    import struct as _struct
    starts = np.empty(n, np.int64)
    lens = np.empty(n, np.int64)
    pos = 0
    unpack = _struct.unpack_from
    for i in range(n):
        (ln,) = unpack("<I", buf, pos)
        pos += 4
        starts[i] = pos
        lens[i] = ln
        pos += ln
    return starts, lens, np.frombuffer(buf, np.uint8, pos)


def _ba_matrix(parts: List[Tuple[np.ndarray, np.ndarray, np.ndarray]],
               width: int) -> Tuple[np.ndarray, np.ndarray]:
    """(starts, lens, blob) segments -> dense (n, width) matrix + lengths
    via one vectorized scatter (same trick as _encode_string_matrix)."""
    n = sum(len(p[1]) for p in parts)
    mat = np.zeros((max(n, 1), width), dtype=np.uint8)
    out_lens = np.zeros(max(n, 1), dtype=np.int32)
    row0 = 0
    for starts, lens, blob in parts:
        k = len(lens)
        total = int(lens.sum())
        if total:
            rows = row0 + np.repeat(np.arange(k, dtype=np.int64), lens)
            prefix = np.cumsum(lens) - lens
            cols = np.arange(total, dtype=np.int64) - np.repeat(prefix, lens)
            mat[rows, cols] = blob[np.repeat(starts, lens) + cols]
        out_lens[row0:row0 + k] = lens
        row0 += k
    return mat, out_lens


def _parse_chunk(raw: bytes, col_meta, nullable: bool) -> _Chunk:
    ch = _Chunk()
    ch.nullable = nullable
    phys = col_meta.physical_type
    codec = col_meta.compression
    off = col_meta.dictionary_page_offset
    if off is None:
        off = col_meta.data_page_offset
    end = off + col_meta.total_compressed_size
    pos = off
    while pos < end:
        hdr = read_page_header(raw, pos)
        data_start = pos + hdr.header_bytes
        page = raw[data_start:data_start + hdr.compressed_size]
        pos = data_start + hdr.compressed_size
        if hdr.page_type == PageType.DICTIONARY_PAGE:
            page = _decompress(page, codec, hdr.uncompressed_size)
            if phys == "BYTE_ARRAY":
                ch.ba_dict = _parse_byte_array_stream(page, hdr.num_values)
            else:
                ch.dictionary = _plain_values(page, phys, hdr.num_values)
            continue
        nvals = hdr.num_values
        if hdr.page_type == PageType.DATA_PAGE:
            page = _decompress(page, codec, hdr.uncompressed_size)
            p = 0
            # flat columns: no repetition levels; definition levels only
            # when the column is nullable (length-prefixed RLE, width 1)
            n_nonnull = nvals
            if nullable:
                if hdr.def_level_encoding != Encoding.RLE:
                    # legacy BIT_PACKED levels have no length prefix;
                    # parsing them as RLE would read garbage "plausibly"
                    raise UnsupportedChunk(
                        f"definition-level encoding {hdr.def_level_encoding}")
                (dl_len,) = np.frombuffer(page, np.uint32, 1, p)
                p += 4
                before = ch.defs.total
                ch.defs.parse_hybrid(page, p, p + int(dl_len), 1, nvals)
                if ch.defs.total - before < nvals:  # stream may omit tail
                    ch.defs._push_rle(nvals - (ch.defs.total - before), 1)
                p += int(dl_len)
                n_nonnull = _count_defined(ch.defs, before)
            else:
                ch.defs._push_rle(nvals, 1)
        elif hdr.page_type == PageType.DATA_PAGE_V2:
            # v2 layout: [rep levels][def levels] UNCOMPRESSED, then the
            # values section (compressed iff is_compressed); levels are
            # RLE with NO length prefix (lengths live in the header)
            if hdr.rep_levels_byte_length:
                raise UnsupportedChunk("v2 repetition levels on flat column")
            dl = hdr.def_levels_byte_length
            levels = page[:dl]
            vals = page[dl:]
            if hdr.v2_is_compressed:
                vals = _decompress(vals, codec,
                                   hdr.uncompressed_size - dl)
            n_nonnull = nvals - hdr.num_nulls
            before = ch.defs.total
            if dl:
                ch.defs.parse_hybrid(levels, 0, dl, 1, nvals)
            if ch.defs.total - before < nvals:
                ch.defs._push_rle(nvals - (ch.defs.total - before), 1)
            page = vals
            p = 0
        else:
            raise UnsupportedChunk(f"page type {hdr.page_type}")
        if hdr.encoding in (Encoding.RLE_DICTIONARY,
                            Encoding.PLAIN_DICTIONARY):
            if ch.uses_plain:
                # dense-stream order would break (plain segment sits last)
                raise UnsupportedChunk("dictionary page after plain page")
            width = page[p]
            p += 1
            if width > 24:
                raise UnsupportedChunk(f"dict index width {width}")
            ch.idx_width = max(ch.idx_width, width)
            ch.idx.parse_hybrid(page, p, len(page), width, n_nonnull)
            ch.uses_dict = True
        elif hdr.encoding == Encoding.PLAIN:
            if phys == "BOOLEAN":
                ch.bool_plain.append((page[p:], n_nonnull))
            elif phys == "BYTE_ARRAY":
                ch.ba_plain.append(
                    _parse_byte_array_stream(page[p:], n_nonnull))
            else:
                ch.plain_parts.append(page[p:])
            ch.uses_plain = True
        else:
            raise UnsupportedChunk(f"encoding {hdr.encoding}")
        ch.num_rows += nvals
    if ch.uses_dict and ch.bool_plain:
        raise UnsupportedChunk("mixed dict+plain boolean pages")
    return ch


def _count_defined(rt: _RunTable, from_entry_total: int) -> int:
    """Non-null count contributed by def-level entries after a checkpoint —
    needed because dictionary index streams hold only non-null values."""
    # walk entries added since the checkpoint
    total = 0
    acc = 0
    for i in range(len(rt.out_start)):
        if rt.out_start[i] < from_entry_total:
            continue
        if rt.is_rle[i]:
            total += rt.count[i] * (1 if rt.rle_value[i] else 0)
        else:
            # bit-packed def levels at width 1: count set bits in the run
            base = rt.bit_base[i] // 8
            nbits = rt.count[i]
            blob = bytes(rt.packed[base:base + (nbits + 7) // 8])
            bits = np.unpackbits(np.frombuffer(blob, np.uint8),
                                 bitorder="little")[:nbits]
            total += int(bits.sum())
        acc += rt.count[i]
    return total


_NP_BY_PHYS = {"INT32": np.int32, "INT64": np.int64,
               "FLOAT": np.float32, "DOUBLE": np.float64}


def _plain_values(buf: bytes, phys: str, n: int) -> np.ndarray:
    if phys == "BOOLEAN":
        bits = np.unpackbits(np.frombuffer(buf, np.uint8, (n + 7) // 8),
                             bitorder="little")[:n]
        return bits.astype(np.bool_)
    npdt = _NP_BY_PHYS[phys]
    return np.frombuffer(buf, npdt, n)


# ---------------------------------------------------------------------------
# Device side: one fused kernel per chunk
# ---------------------------------------------------------------------------
def _pow2(n: int) -> int:
    c = 1
    while c < n:
        c *= 2
    return c


def _expand_hybrid_device(out_start, is_rle, rle_value, bit_base, widths,
                          packed, iota):
    """values[i] for each output position in ``iota``: expand the run table
    on device (searchsorted for run id + LSB-first bit-field extraction for
    bit-packed runs). ``widths`` is PER RUN — successive pages of one chunk
    may bit-pack at different widths as the dictionary grows."""
    import jax.numpy as jnp
    i = iota.astype(jnp.int64)
    run = jnp.clip(jnp.searchsorted(out_start, i, side="right") - 1,
                   0, out_start.shape[0] - 1)
    within = i - out_start[run]
    w = widths[run]
    bit = bit_base[run] + within * w
    byte0 = bit >> 3
    shift = (bit & 7).astype(jnp.uint32)
    nb = packed.shape[0]
    g = lambda k: packed[jnp.clip(byte0 + k, 0, nb - 1)].astype(jnp.uint32)
    dword = g(0) | (g(1) << 8) | (g(2) << 16) | (g(3) << 24)
    # width <= 24 enforced at parse time, so 4 gathered bytes always cover
    mask = (jnp.uint32(1) << w.astype(jnp.uint32)) - jnp.uint32(1)
    bp_val = (dword >> shift) & mask
    return jnp.where(is_rle[run], rle_value[run].astype(jnp.int64),
                     bp_val.astype(jnp.int64))


def _mixed_kernel_builder(npdt_str: str):
    """Fixed-width decode: dense stream = dict segment ++ plain segment.

    Row r's dense position ``pos[r]`` reads from the dictionary gather
    while pos < n_dict (the count of dictionary-encoded non-null values)
    and from the host-parsed plain array after — one kernel covers
    dict-only (plain is a 1-slot dummy), plain-only (n_dict = 0), and the
    pyarrow dictionary-overflow mixed chunk."""
    def fn(v_start, v_rle, v_val, v_bit, v_width, v_packed,
           d_start, d_rle, d_val, d_bit, d_width, d_packed, dvals,
           plain, n_dict, n, iota_cap, iota_nv):
        import jax.numpy as jnp
        validity = _expand_hybrid_device(
            v_start, v_rle, v_val, v_bit, v_width, v_packed, iota_cap) > 0
        validity = jnp.logical_and(validity, iota_cap < n)
        pos = (jnp.cumsum(validity.astype(jnp.int32)) - 1).astype(jnp.int64)
        idx = _expand_hybrid_device(d_start, d_rle, d_val, d_bit, d_width,
                                    d_packed, iota_nv)
        dense_dict = dvals[jnp.clip(idx, 0, dvals.shape[0] - 1)]
        from_dict = pos < n_dict
        v_dict = dense_dict[jnp.clip(pos, 0, dense_dict.shape[0] - 1)]
        v_plain = plain[jnp.clip(pos - n_dict, 0, plain.shape[0] - 1)]
        vals = jnp.where(from_dict, v_dict, v_plain)
        vals = jnp.where(validity, vals, jnp.zeros((), vals.dtype))
        return vals.astype(jnp.dtype(npdt_str)), validity
    return lambda: fn


def _ba_kernel_builder():
    """BYTE_ARRAY decode into the bucketed (rows, width) byte-matrix +
    lengths layout — dictionary rows gather as whole matrix rows (an
    MXU-friendly 2D gather), plain rows come from the host-assembled
    matrix, segment choice as in _mixed_kernel_builder."""
    def fn(v_start, v_rle, v_val, v_bit, v_width, v_packed,
           d_start, d_rle, d_val, d_bit, d_width, d_packed,
           dict_mat, dict_lens, plain_mat, plain_lens,
           n_dict, n, iota_cap, iota_nv):
        import jax.numpy as jnp
        validity = _expand_hybrid_device(
            v_start, v_rle, v_val, v_bit, v_width, v_packed, iota_cap) > 0
        validity = jnp.logical_and(validity, iota_cap < n)
        pos = (jnp.cumsum(validity.astype(jnp.int32)) - 1).astype(jnp.int64)
        idx = _expand_hybrid_device(d_start, d_rle, d_val, d_bit, d_width,
                                    d_packed, iota_nv)
        from_dict = pos < n_dict
        didx = idx[jnp.clip(pos, 0, idx.shape[0] - 1)]
        row_dict = dict_mat[jnp.clip(didx, 0, dict_mat.shape[0] - 1)]
        len_dict = dict_lens[jnp.clip(didx, 0, dict_lens.shape[0] - 1)]
        ppos = jnp.clip(pos - n_dict, 0, plain_mat.shape[0] - 1)
        row_plain = plain_mat[ppos]
        len_plain = plain_lens[ppos]
        data = jnp.where(from_dict[:, None], row_dict, row_plain)
        lengths = jnp.where(from_dict, len_dict, len_plain)
        ok = validity[:, None]
        data = jnp.where(ok, data, jnp.zeros((), jnp.uint8))
        lengths = jnp.where(validity, lengths, 0).astype(jnp.int32)
        return data, lengths, validity
    return lambda: fn


def _empty_run_tables() -> Tuple[np.ndarray, ...]:
    return _RunTable().arrays()


def _decode_column_device(ch: _Chunk, out_dtype: dt.DataType, cap: int):
    """-> DeviceColumn with row capacity ``cap`` (device kernels; compiled
    callables shared via the global compile cache, shapes pow2-bucketed)."""
    import numpy as _np

    from ..columnar.device import DeviceColumn, bucket_width
    from ..utils.compile_cache import cached_jit

    n = ch.num_rows
    v_tables = ch.defs.arrays()
    iota_cap = _np.arange(cap, dtype=_np.int64)
    d_tables = ch.idx.arrays() if ch.uses_dict else _empty_run_tables()
    n_dict = ch.idx.total if ch.uses_dict else 0
    nvcap = _pow2(max(1, n_dict))
    iota_nv = _np.arange(nvcap, dtype=_np.int64)

    if isinstance(out_dtype, (dt.StringType, dt.BinaryType)):
        max_len = 1
        if ch.ba_dict is not None and len(ch.ba_dict[1]):
            max_len = max(max_len, int(ch.ba_dict[1].max()))
        for _, lens, _b in ch.ba_plain:
            if len(lens):
                max_len = max(max_len, int(lens.max()))
        width = bucket_width(max_len)
        if ch.uses_dict:
            if ch.ba_dict is None:
                raise UnsupportedChunk("dict-encoded pages, no dict page")
            dm, dlens = _ba_matrix([ch.ba_dict], width)
            pad_to = _pow2(dm.shape[0])
            dm = _np.pad(dm, ((0, pad_to - dm.shape[0]), (0, 0)))
            dlens = _np.pad(dlens, (0, pad_to - len(dlens)))
        else:
            dm = _np.zeros((1, width), _np.uint8)
            dlens = _np.zeros(1, _np.int32)
        if ch.ba_plain:
            pm, plens = _ba_matrix(ch.ba_plain, width)
            pad_to = _pow2(pm.shape[0])
            pm = _np.pad(pm, ((0, pad_to - pm.shape[0]), (0, 0)))
            plens = _np.pad(plens, (0, pad_to - len(plens)))
        else:
            pm = _np.zeros((1, width), _np.uint8)
            plens = _np.zeros(1, _np.int32)
        fn = cached_jit("pq_ba", _ba_kernel_builder())
        data, lengths, validity = fn(
            *v_tables, *d_tables, dm, dlens.astype(_np.int32),
            pm, plens.astype(_np.int32), _np.int64(n_dict), _np.int64(n),
            iota_cap, iota_nv)
        return DeviceColumn(data, validity, out_dtype, lengths)

    npdt = out_dtype.np_dtype()
    npdt_str = _np.dtype(npdt).str
    if ch.bool_plain and not ch.uses_dict:
        parts = [_plain_values(b, "BOOLEAN", c) for b, c in ch.bool_plain]
        plain = _np.concatenate(parts) if parts else _np.zeros(0, _np.bool_)
    elif ch.plain_parts:
        blob = b"".join(ch.plain_parts)
        d_ = _np.dtype(npdt)
        if d_.kind == "f":
            phys = "FLOAT" if d_.itemsize == 4 else "DOUBLE"
        else:  # ints + date32/timestamp storage types
            phys = "INT32" if d_.itemsize == 4 else "INT64"
        count = len(blob) // _np.dtype(_NP_BY_PHYS[phys]).itemsize
        plain = _plain_values(blob, phys, count)
    else:
        plain = _np.zeros(0, npdt)
    plain = _np.asarray(plain, npdt)
    plain = _np.pad(plain, (0, _pow2(max(1, len(plain))) - len(plain)))
    if ch.uses_dict:
        dict_vals = _np.asarray(ch.dictionary, npdt)
    else:
        dict_vals = _np.zeros(1, npdt)
    dv = _np.pad(dict_vals,
                 (0, _pow2(max(1, len(dict_vals))) - len(dict_vals)))
    fn = cached_jit(f"pq_mix|{npdt_str}", _mixed_kernel_builder(npdt_str))
    data, validity = fn(*v_tables, *d_tables, dv, plain,
                        _np.int64(n_dict), _np.int64(n), iota_cap, iota_nv)
    return DeviceColumn(data, validity, out_dtype, None)


def decode_row_group(raw: bytes, pf_metadata, rg: int, arrow_schema,
                     columns: List[str], min_bucket: int, conf=None):
    """Decode one row group into a DeviceTable; per-column fallback to
    pyarrow host decode + upload for unsupported chunks. Returns
    (DeviceTable, n_device_decoded_columns)."""
    from ..columnar.device import DeviceTable, bucket_rows
    rg_meta = pf_metadata.row_group(rg)
    n = rg_meta.num_rows
    cap = bucket_rows(max(n, 1), min_bucket)
    name_to_ci = {pf_metadata.schema.column(i).path: i
                  for i in range(pf_metadata.num_columns)}
    cols = {}
    fallback: List[str] = []
    n_device = 0
    for name in columns:
        ci = name_to_ci.get(name)
        field = arrow_schema.field(name)
        col_meta = rg_meta.column(ci) if ci is not None else None
        if col_meta is None or not chunk_supported(col_meta, field, conf):
            fallback.append(name)
            continue
        try:
            ch = _parse_chunk(raw, col_meta, field.nullable)
            if ch.num_rows != n:
                raise UnsupportedChunk("row count mismatch")
            cols[name] = _decode_column_device(
                ch, _arrow_to_dtype(field.type), cap)
            n_device += 1
        except Exception:
            # ANY decode problem (unsupported feature, codec pa.decompress
            # can't handle — e.g. hadoop-framed LZ4 — or a parse error)
            # falls back to the per-column host decode, never crashes the
            # query: the host reader is the always-correct tier
            fallback.append(name)
    if fallback:
        # per-column host decode for the leftovers (reference: the plugin
        # likewise keeps unsupported columns on the CPU decode path)
        import io as _io

        import pyarrow.parquet as pq

        from ..columnar.host import HostTable
        t = pq.ParquetFile(_io.BytesIO(raw)).read_row_group(
            rg, columns=fallback)
        ht = HostTable.from_arrow(t)
        host_dt = DeviceTable.from_host(ht, min_bucket, capacity=cap)
        for cname, c in zip(host_dt.names, host_dt.columns):
            cols[cname] = c
    import jax.numpy as jnp
    iota = jnp.arange(cap, dtype=jnp.int32)
    mask = iota < n
    ordered = tuple(cols[c] for c in columns)
    return (DeviceTable(ordered, mask, jnp.int32(n), tuple(columns)),
            n_device)
