"""Device CSV decode: newline split on host, field split + typed parse as
byte-matrix kernels on the accelerator (round-4 VERDICT item 4; reference:
GpuTextBasedPartitionReader.scala:44 host line framing + device parse,
GpuCSVScan per-type enables RapidsConf.scala:877-917).

TPU-first shape discipline: one CSV batch becomes a (rows, W) uint8 line
matrix (W = bucketed max line width). Field k of every row is carved out by
a cumulative separator count + one scatter, giving each column its own
(rows, W) byte matrix that feeds the existing string->{long,double,bool,
date} cast kernels (expr/cast_kernels.py) — the whole decode is one jitted
program per (schema, bucket) signature.

Unsupported on device (host pyarrow fallback, tag-time): quoted fields,
timestamp columns, multi-char separators.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..columnar import dtypes as dt
from ..conf import register_conf

CSV_DEVICE_DECODE = register_conf(
    "spark.rapids.tpu.csv.deviceDecode.enabled",
    "Decode CSV scans on the accelerator (field split + numeric/date parse "
    "as byte-matrix kernels). Quoted fields and timestamp columns fall "
    "back to the host reader (reference: GpuTextBasedPartitionReader).",
    True)

__all__ = ["CSV_DEVICE_DECODE", "split_lines", "decode_lines",
           "device_decodable_reason"]


def device_decodable_reason(schema, sep: str, header_sample: bytes,
                            explicit_schema: bool = False) -> Optional[str]:
    """None when the device decoder can handle this scan, else the reason."""
    if len(sep) != 1:
        return f"multi-char separator {sep!r}"
    if b'"' in header_sample:
        return "quoted fields use the host reader"
    if explicit_schema:
        # the host reader RAISES on malformed cells under an explicit
        # schema; a traced kernel cannot, so keep those scans host-side
        return "explicit schema (host reader enforces parse errors)"
    for f in schema:
        d = f.dtype
        if isinstance(d, dt.TimestampType):
            return f"timestamp column {f.name} parses on the host"
        if not isinstance(d, (dt.StringType, dt.BooleanType, dt.ByteType,
                              dt.ShortType, dt.IntegerType, dt.LongType,
                              dt.FloatType, dt.DoubleType, dt.DateType)):
            return f"column {f.name}: {d!r} has no device CSV parser"
    return None


def split_lines(raw: bytes, skip_header: bool) -> Tuple[np.ndarray,
                                                        np.ndarray]:
    """File bytes -> (line starts, line lengths) without copying the blob.

    Vectorized newline scan; \\r\\n normalized; a trailing unterminated
    line is kept; trailing empty line dropped."""
    buf = np.frombuffer(raw, dtype=np.uint8)
    nl = np.flatnonzero(buf == ord("\n"))
    starts = np.concatenate([np.zeros(1, dtype=np.int64), nl + 1])
    ends = np.concatenate([nl, np.asarray([len(buf)], dtype=np.int64)])
    keep = starts < ends
    keep[-1] = keep[-1] and starts[-1] < len(buf)
    starts, ends = starts[keep], ends[keep]
    # strip \r
    has_cr = np.zeros(len(starts), dtype=bool)
    if len(starts):
        has_cr = buf[np.clip(ends - 1, 0, len(buf) - 1)] == ord("\r")
    lengths = ends - starts - has_cr.astype(np.int64)
    # CRLF blank lines survive the starts<ends filter as length-0 lines
    # after the CR strip; pyarrow (ignore_empty_lines) skips them — match
    nonempty = lengths > 0
    starts, lengths = starts[nonempty], lengths[nonempty]
    if skip_header and len(starts):
        starts, lengths = starts[1:], lengths[1:]
    return starts, lengths


def lines_to_matrix(raw: bytes, starts: np.ndarray, lengths: np.ndarray,
                    capacity: int, width: int) -> np.ndarray:
    """Gather line bytes into a (capacity, width) matrix (host side)."""
    buf = np.frombuffer(raw, dtype=np.uint8)
    n = len(starts)
    mat = np.zeros((capacity, width), dtype=np.uint8)
    total = int(lengths.sum())
    if total:
        rows = np.repeat(np.arange(n, dtype=np.int64), lengths)
        prefix = np.cumsum(lengths) - lengths
        cols = np.arange(total, dtype=np.int64) - np.repeat(prefix, lengths)
        mat[rows, cols] = buf[np.repeat(starts, lengths) + cols]
    return mat


def _null_token_mask(fmat, flen):
    """True where the field equals one of pyarrow's default CSV null
    tokens ('', 'NULL', 'NaN', 'n/a', ... — exact byte match), keeping
    host-reader parity: the host engine reads via pyarrow, which nulls
    these for EVERY column type, including 'NaN' for doubles."""
    import jax.numpy as jnp
    import pyarrow.csv as pacsv
    rows, w = fmat.shape
    isnull = flen == 0
    for tok in pacsv.ConvertOptions().null_values:
        t = tok.encode()
        if not t or len(t) > w:
            continue
        tv = np.zeros(w, dtype=np.uint8)
        tv[:len(t)] = np.frombuffer(t, dtype=np.uint8)
        eq = jnp.all(fmat[:, :len(t)] == jnp.asarray(tv[:len(t)])[None, :],
                     axis=1)
        isnull = jnp.logical_or(
            isnull, jnp.logical_and(eq, flen == len(t)))
    return isnull


def decode_lines(mat, lengths, fields: List[Tuple[str, dt.DataType]],
                 sep: int, col_indices: List[int]):
    """Jit-traceable: (rows, W) line matrix -> per-column (values, validity
    [, field matrix + lengths for strings]).

    Returns a list aligned with ``col_indices``: string columns yield
    (field matrix, validity, field lengths); scalar columns yield
    (values, validity) — callers branch on the static dtype."""
    import jax.numpy as jnp

    from ..expr.cast_kernels import (string_to_bool_device,
                                     string_to_date_device,
                                     string_to_double_device,
                                     string_to_long_device)
    rows, w = mat.shape
    j = jnp.arange(w, dtype=jnp.int32)
    in_line = j[None, :] < lengths[:, None]
    sep_mask = jnp.logical_and(mat == np.uint8(sep), in_line)
    # field id of each byte = number of separators strictly before it
    cum = jnp.cumsum(sep_mask.astype(jnp.int32), axis=1)
    field_id = cum - sep_mask.astype(jnp.int32)
    nfields = 1 + cum[:, -1]
    rix = jnp.broadcast_to(jnp.arange(rows, dtype=jnp.int32)[:, None],
                           (rows, w))

    out = []
    for k in col_indices:
        name, d = fields[k]
        content = jnp.logical_and(
            jnp.logical_and(field_id == k, jnp.logical_not(sep_mask)),
            in_line)
        flen = content.sum(axis=1).astype(jnp.int32)
        any_c = jnp.any(content, axis=1)
        fstart = jnp.where(any_c, jnp.argmax(content, axis=1), 0) \
            .astype(jnp.int32)
        dest = jnp.where(content, j - fstart[:, None], w)
        fmat = jnp.zeros((rows, w + 1), jnp.uint8) \
            .at[rix, dest].set(mat, mode="drop")[:, :w]
        # a row with fewer fields than k+1 yields a MISSING field -> null
        present = nfields > k
        not_null_tok = jnp.logical_not(_null_token_mask(fmat, flen))
        if isinstance(d, dt.StringType):
            # null tokens ('', 'NULL', 'NaN', ...) -> null (pyarrow
            # strings_can_be_null=True parity with the host reader)
            valid = jnp.logical_and(present, not_null_tok)
            out.append((fmat, valid, flen))
            continue
        if isinstance(d, dt.BooleanType):
            vals, ok = string_to_bool_device(fmat, flen)
        elif isinstance(d, (dt.ByteType, dt.ShortType, dt.IntegerType,
                            dt.LongType)):
            vals, ok = string_to_long_device(fmat, flen)
            info = np.iinfo(d.np_dtype())
            ok = jnp.logical_and(
                ok, jnp.logical_and(vals >= info.min, vals <= info.max))
            vals = vals.astype(d.np_dtype())
        elif isinstance(d, (dt.FloatType, dt.DoubleType)):
            vals, ok = string_to_double_device(fmat, flen)
            vals = vals.astype(d.np_dtype())
        elif isinstance(d, dt.DateType):
            vals, ok = string_to_date_device(fmat, flen)
        else:  # pragma: no cover - gated by device_decodable_reason
            raise TypeError(f"no device CSV parser for {d!r}")
        # null tokens -> null (not a parse error); malformed -> null too
        valid = jnp.logical_and(jnp.logical_and(present, not_null_tok), ok)
        vals = jnp.where(valid, vals, jnp.zeros((), vals.dtype))
        out.append((vals, valid))
    return out
