"""Debug batch dumps (reference: DumpUtils.scala +
spark.rapids.sql.debug.dumpPrefix — persist operator input batches as
parquet so a failing query's exact data can be replayed offline)."""
from __future__ import annotations

import os
import re

from ..conf import register_conf
from ..columnar.host import HostTable

__all__ = ["DEBUG_DUMP_PATH", "dump_scan_batch"]

DEBUG_DUMP_PATH = register_conf(
    "spark.rapids.tpu.debug.dumpPath",
    "When set, every scan batch is also written to this directory as "
    "parquet (scan-<source>-p<partition>-b<batch>.parquet) for offline "
    "repro (reference: DumpUtils / spark.rapids.sql.debug.dumpPrefix). "
    "Empty disables.", "")


def dump_scan_batch(directory: str, source_name: str, pidx: int,
                    batch_idx: int, table: HostTable) -> str:
    import pyarrow.parquet as pq
    os.makedirs(directory, exist_ok=True)
    safe = re.sub(r"[^A-Za-z0-9_.-]+", "_", source_name)[:64]
    path = os.path.join(directory,
                        f"scan-{safe}-p{pidx}-b{batch_idx}.parquet")
    pq.write_table(table.to_arrow(), path)
    return path
