"""ORC scan (reference: GpuOrcScanBase.scala — multithread/coalescing readers
with predicate pushdown via OrcFilters search-arguments; here the pushdown
rides the pyarrow dataset reader and files decode on a thread pool)."""
from __future__ import annotations

import concurrent.futures as cf
import math
import os
import glob as _glob
from typing import Iterator, List, Optional

import pyarrow as pa
import pyarrow.orc as paorc

from ..conf import MULTITHREAD_READ_NUM_THREADS, RapidsConf, register_conf
from ..columnar.host import HostTable
from ..plan.logical import DataSource
from ..plan.schema import Field, Schema

ORC_ENABLED = register_conf(
    "spark.rapids.sql.format.orc.enabled",
    "Enable ORC scans (reference: RapidsConf orc flags).", True)

ORC_READER_TYPE = register_conf(
    "spark.rapids.sql.format.orc.reader.type",
    "ORC multi-file reader strategy: PERFILE (stripe-at-a-time per file, "
    "preserves input_file_name), MULTITHREADED (bounded read-ahead pool), "
    "COALESCING (stitch small files into full batches), or AUTO "
    "(reference: GpuOrcScanBase.scala multithread/coalescing readers, "
    "GpuMultiFileReader.scala:126).", "AUTO",
    checker=lambda v: None if str(v).upper() in
    ("AUTO", "PERFILE", "MULTITHREADED", "COALESCING")
    else "must be AUTO|PERFILE|MULTITHREADED|COALESCING")

__all__ = ["OrcSource"]


class OrcSource(DataSource):
    def __init__(self, paths, conf: Optional[RapidsConf] = None,
                 num_partitions: Optional[int] = None,
                 batch_rows: Optional[int] = None):
        if isinstance(paths, (str, os.PathLike)):
            paths = [paths]
        files: List[str] = []
        for p in paths:
            p = os.fspath(p)
            if os.path.isdir(p):
                files.extend(sorted(
                    _glob.glob(os.path.join(p, "**", "*.orc"), recursive=True)))
            elif any(ch in p for ch in "*?["):
                files.extend(sorted(_glob.glob(p)))
            else:
                files.append(p)
        if not files:
            raise FileNotFoundError(f"no orc files for {paths}")
        self.files = files
        self.conf = conf or RapidsConf()
        from ..conf import READER_BATCH_SIZE_ROWS
        self.batch_rows = batch_rows if batch_rows is not None \
            else self.conf.get(READER_BATCH_SIZE_ROWS)
        self.reader_type = str(self.conf.get(ORC_READER_TYPE)).upper()
        self.filter_expr = None  # pyarrow dataset pushdown (OrcFilters)
        first = paorc.ORCFile(self.files[0]).schema
        ht = HostTable.from_arrow(first.empty_table())
        self._schema = Schema([Field(n, c.dtype, True)
                               for n, c in zip(ht.names, ht.columns)])
        nparts = num_partitions or min(len(self.files), 8)
        per = math.ceil(len(self.files) / nparts)
        self._file_parts = [self.files[i * per:(i + 1) * per]
                            for i in range(nparts)
                            if self.files[i * per:(i + 1) * per]]

    def schema(self) -> Schema:
        return self._schema

    def push_filter(self, arrow_expr) -> None:
        """Planner pushdown hook (io/pushdown.py) — the OrcFilters
        search-argument analogue, applied through the dataset reader."""
        self.filter_expr = arrow_expr if self.filter_expr is None \
            else (self.filter_expr & arrow_expr)

    def partitions(self) -> int:
        return len(self._file_parts)

    def _read_file(self, f: str, columns) -> pa.Table:
        if self.filter_expr is not None:
            import pyarrow.dataset as pads
            ds = pads.dataset(f, format="orc")
            return ds.to_table(columns=columns, filter=self.filter_expr)
        return paorc.ORCFile(f).read(columns=columns)

    def read_partition(self, pidx: int, columns: Optional[List[str]] = None
                       ) -> Iterator[HostTable]:
        files = self._file_parts[pidx]
        if self.reader_type == "PERFILE":
            yield from self._read_perfile(files, columns)
        elif self.reader_type == "COALESCING":
            yield from self._read_coalescing(files, columns)
        else:  # MULTITHREADED (also AUTO)
            yield from self._read_multithreaded(files, columns)

    # -- strategies (reference: GpuOrcScanBase multithread/coalescing
    # readers; PERFILE decodes stripe-at-a-time = stripe clipping) ----------
    def _read_perfile(self, files, columns) -> Iterator[HostTable]:
        from .file_block import set_input_file
        for fname in files:
            set_input_file(fname, 0, os.path.getsize(fname))
            if self.filter_expr is not None:
                yield from self._slice_out(self._read_file(fname, columns))
                continue
            f = paorc.ORCFile(fname)
            if f.nstripes == 0:
                yield HostTable.from_arrow(
                    f.schema.empty_table() if columns is None
                    else f.schema.empty_table().select(columns))
                continue
            for s in range(f.nstripes):
                # stripe-at-a-time: bounded memory per file regardless of
                # file size (the stripe-clipping analogue)
                yield from self._slice_out(f.read_stripe(s, columns=columns))

    def _read_multithreaded(self, files, columns) -> Iterator[HostTable]:
        from .file_block import set_input_file
        from .prefetch import prefetched
        nthreads = self.conf.get(MULTITHREAD_READ_NUM_THREADS)
        # bounded read-ahead: at most nthreads decoded tables resident
        for fname, t in prefetched(
                files, lambda f: self._read_file(f, columns), nthreads):
            set_input_file(fname, 0, os.path.getsize(fname))
            yield from self._slice_out(t)
            del t

    def _read_coalescing(self, files, columns) -> Iterator[HostTable]:
        # merged batches span files: no single-file attribution (the
        # planner's InputFileBlockRule selects PERFILE when file-info
        # expressions appear, like the reference's reader selection)
        from .file_block import clear_input_file
        from .prefetch import coalesce_tables
        clear_input_file()
        for merged in coalesce_tables(
                files, lambda f: self._read_file(f, columns),
                self.batch_rows):
            yield from self._slice_out(merged)

    def name(self) -> str:
        return f"ORC[{len(self.files)} files, {self.reader_type}]"
