"""JSON-lines scan (reference: GpuJsonScan.scala — cuDF JSON decode; here
pyarrow.json host decode with the same source/partitioning shape)."""
from __future__ import annotations

import math
import os
import glob as _glob
from typing import Iterator, List, Optional

import pyarrow as pa
import pyarrow.json as pajson

from ..conf import RapidsConf, register_conf
from ..columnar.host import HostTable
from ..plan.logical import DataSource
from ..plan.schema import Field, Schema

JSON_ENABLED = register_conf(
    "spark.rapids.sql.format.json.enabled",
    "Enable JSON scans.", True)

__all__ = ["JsonSource"]


class JsonSource(DataSource):
    def __init__(self, paths, conf: Optional[RapidsConf] = None,
                 num_partitions: Optional[int] = None,
                 batch_rows: Optional[int] = None):
        if isinstance(paths, (str, os.PathLike)):
            paths = [paths]
        files: List[str] = []
        for p in paths:
            p = os.fspath(p)
            if os.path.isdir(p):
                files.extend(sorted(
                    _glob.glob(os.path.join(p, "**", "*.json*"), recursive=True)))
            elif any(ch in p for ch in "*?["):
                files.extend(sorted(_glob.glob(p)))
            else:
                files.append(p)
        if not files:
            raise FileNotFoundError(f"no json files for {paths}")
        self.files = files
        self.conf = conf or RapidsConf()
        from ..conf import READER_BATCH_SIZE_ROWS
        self.batch_rows = batch_rows if batch_rows is not None \
            else self.conf.get(READER_BATCH_SIZE_ROWS)
        first = pajson.read_json(self.files[0])
        ht = HostTable.from_arrow(first.slice(0, 0))
        self._schema = Schema([Field(n, c.dtype, True)
                               for n, c in zip(ht.names, ht.columns)])
        nparts = num_partitions or min(len(self.files), 8)
        per = math.ceil(len(self.files) / nparts)
        self._file_parts = [self.files[i * per:(i + 1) * per]
                            for i in range(nparts)
                            if self.files[i * per:(i + 1) * per]]

    def schema(self) -> Schema:
        return self._schema

    def partitions(self) -> int:
        return len(self._file_parts)

    def sample_head(self, nbytes: int = 1 << 16) -> bytes:
        """First bytes of the first file — escape sniffing for the device
        decoder gate (exec/scan.py TpuJsonScanExec)."""
        with open(self.files[0], "rb") as f:
            return f.read(nbytes)

    def _read_file(self, path: str) -> pa.Table:
        return pajson.read_json(path)

    def read_partition(self, pidx: int, columns: Optional[List[str]] = None
                       ) -> Iterator[HostTable]:
        from .file_block import set_input_file
        for f in self._file_parts[pidx]:
            t = self._read_file(f)
            set_input_file(f, 0, os.path.getsize(f))
            yield from self._slice_out(t, columns)

    def name(self) -> str:
        return f"JSON[{len(self.files)} files]"
