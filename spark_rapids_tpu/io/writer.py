"""Write path (reference: GpuParquetFileFormat.scala, GpuOrcFileFormat.scala,
ColumnarOutputWriter.scala, GpuFileFormatDataWriter.scala — dynamic
partitioning + write stats trackers).

Writes execute per input partition producing part files (Spark layout:
``part-NNNNN-*.ext``); ``partition_by`` columns produce Hive-style
``col=value/`` directories via the dynamic partitioning path. Stats
(files/rows/bytes written) mirror BasicColumnarWriteStatsTracker.
"""
from __future__ import annotations

import os
import uuid
from typing import Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.orc as paorc
import pyarrow.parquet as pq

__all__ = ["write_parquet", "write_csv", "write_orc", "WriteStats"]


class WriteStats:
    """reference: BasicColumnarWriteStatsTracker.scala"""

    def __init__(self):
        self.num_files = 0
        self.num_rows = 0
        self.num_bytes = 0
        self.partitions: List[str] = []

    def record(self, path: str, rows: int):
        self.num_files += 1
        self.num_rows += rows
        try:
            self.num_bytes += os.path.getsize(path)
        except OSError:
            pass

    def __repr__(self):
        return (f"WriteStats(files={self.num_files}, rows={self.num_rows}, "
                f"bytes={self.num_bytes}, partitions={len(self.partitions)})")


def _write_one(table: pa.Table, path: str, fmt: str, **kw):
    if fmt == "parquet":
        pq.write_table(table, path, **kw)
    elif fmt == "orc":
        paorc.write_table(table, path)
    elif fmt == "csv":
        pacsv.write_csv(table, path)
    else:
        raise ValueError(fmt)


def _partition_value_str(v) -> str:
    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"
    return str(v)


def _write_table(df, path: str, fmt: str,
                 partition_by: Optional[Sequence[str]] = None,
                 mode: str = "error", **kw) -> WriteStats:
    ext = {"parquet": "parquet", "orc": "orc", "csv": "csv"}[fmt]
    if os.path.exists(path) and os.listdir(path):
        if mode == "error":
            raise FileExistsError(f"path {path} already exists (mode=error)")
        if mode == "overwrite":
            import shutil
            shutil.rmtree(path)
    os.makedirs(path, exist_ok=True)
    stats = WriteStats()
    job_id = uuid.uuid4().hex[:8]
    if fmt == "parquet" and not partition_by and not kw:
        from .parquet_encode import (PARQUET_DEVICE_WRITE, schema_supported,
                                     write_device_parquet)
        conf = df.session.conf
        if conf.get(PARQUET_DEVICE_WRITE) and conf.is_sql_enabled \
                and schema_supported(df.logical.schema):
            # device encode path (reference: GpuParquetFileFormat.scala:351
            # — device packs column chunks, host assembles framing)
            plan = df.session._physical(df.logical, device=True)
            for pidx in range(plan.num_partitions):
                batches = [b for b in df._batches_from_plan(plan, pidx)
                           if int(b.num_rows)]  # srtpu: sync-ok(file write path; the parquet encode downloads anyway)
                if not batches:
                    continue
                fpath = os.path.join(path,
                                     f"part-{pidx:05d}-{job_id}.parquet")
                rows = write_device_parquet(batches, fpath,
                                            df.logical.schema)
                stats.record(fpath, rows)
            open(os.path.join(path, "_SUCCESS"), "w").close()
            return stats
    plan = df.session._physical(df.logical)

    def write_partition(pidx: int) -> List[tuple]:
        """One map task: drain, slice by partition values, write part
        files. Returns (file path, rows, partition dir or None) records so
        the caller can fold WriteStats in deterministic partition order."""
        from ..memory.semaphore import get_semaphore
        from ..parallel.pipeline import task_admission
        with task_admission(), \
                get_semaphore(df.session.conf).task_scope():
            batches = list(plan.execute(pidx))
        if not batches:
            return []
        from ..columnar.host import HostTable
        table = HostTable.concat(batches).to_arrow()
        if table.num_rows == 0:
            return []
        written: List[tuple] = []
        if partition_by:
            # dynamic partitioning (reference: GpuFileFormatDataWriter)
            keys = [table.column(k).to_pylist() for k in partition_by]
            combos: Dict[tuple, List[int]] = {}
            for i, combo in enumerate(zip(*keys)):
                combos.setdefault(combo, []).append(i)
            data_cols = [c for c in table.column_names if c not in partition_by]
            for combo, idxs in combos.items():
                sub = table.take(pa.array(idxs)).select(data_cols)
                dirpath = os.path.join(path, *[
                    f"{k}={_partition_value_str(v)}"
                    for k, v in zip(partition_by, combo)])
                os.makedirs(dirpath, exist_ok=True)
                fpath = os.path.join(
                    dirpath, f"part-{pidx:05d}-{job_id}.{ext}")
                _write_one(sub, fpath, fmt, **kw)
                written.append((fpath, sub.num_rows,
                                os.path.relpath(dirpath, path)))
        else:
            fpath = os.path.join(path, f"part-{pidx:05d}-{job_id}.{ext}")
            _write_one(table, fpath, fmt, **kw)
            written.append((fpath, table.num_rows, None))
        return written

    # pipelined write: part files are independent, so map partitions run
    # on the bounded task pool (parallel/pipeline.py; sequential when
    # pipeline.enabled=false); stats fold in partition order. Shed any
    # semaphore hold earlier main-thread work left on this thread first —
    # blocking in the pool while holding the only permit deadlocks.
    from ..memory.semaphore import peek_semaphore
    from ..parallel.pipeline import parallel_map
    sem = peek_semaphore()
    if sem is not None:
        sem.release_all()
    for part in parallel_map(write_partition, range(plan.num_partitions),
                             stage="write"):
        for fpath, rows, rel in part:
            if rel is not None and rel not in stats.partitions:
                stats.partitions.append(rel)
            stats.record(fpath, rows)
    # _SUCCESS marker like Hadoop committers
    open(os.path.join(path, "_SUCCESS"), "w").close()
    return stats


def write_parquet(df, path: str, partition_by=None, mode: str = "error",
                  **kw) -> WriteStats:
    return _write_table(df, path, "parquet", partition_by, mode, **kw)


def write_orc(df, path: str, partition_by=None, mode: str = "error",
              **kw) -> WriteStats:
    return _write_table(df, path, "orc", partition_by, mode, **kw)


def write_csv(df, path: str, partition_by=None, mode: str = "error",
              **kw) -> WriteStats:
    return _write_table(df, path, "csv", partition_by, mode, **kw)
