"""Parquet scan (reference: GpuParquetScanBase.scala:83 + GpuMultiFileReader).

The reference offers three reader strategies (RapidsConf.scala:721):
- PERFILE: one reader per file
- COALESCING: stitch row groups of many small files, single device decode
  (MultiFileParquetPartitionReader, GpuParquetScanBase.scala:995)
- MULTITHREADED: background read+decode pipelining for cloud storage
  (MultiFileCloudParquetPartitionReader, :1194; pool :934)

Here decode runs host-side via pyarrow (the "host-decode then upload" stopgap
called out in SURVEY §7.5) with the same three scheduling strategies:
COALESCING merges small files into one batch per target size; MULTITHREADED
prefetches files on a thread pool. Predicate pushdown uses parquet row-group
statistics via pyarrow filters.
"""
from __future__ import annotations

import concurrent.futures as cf
import glob as _glob
import math
import os
from typing import Iterator, List, Optional, Sequence

import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

from ..conf import MULTITHREAD_READ_NUM_THREADS, PARQUET_READER_TYPE, RapidsConf
from ..columnar.host import HostTable
from ..plan.logical import DataSource
from ..plan.schema import Field, Schema
from .memory import InMemorySource  # noqa: F401 (re-export convenience)

__all__ = ["ParquetSource"]


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        p = os.fspath(p)
        if os.path.isdir(p):
            out.extend(sorted(_glob.glob(os.path.join(p, "**", "*.parquet"),
                                         recursive=True)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no parquet files for {paths}")
    return out


class ParquetSource(DataSource):
    def __init__(self, paths, conf: Optional[RapidsConf] = None,
                 num_partitions: Optional[int] = None,
                 batch_rows: Optional[int] = None,
                 filter_expr=None):
        self.files = _expand_paths(paths)
        self.conf = conf or RapidsConf()
        self.reader_type = str(self.conf.get(PARQUET_READER_TYPE)).upper()
        from ..conf import READER_BATCH_SIZE_ROWS
        self.batch_rows = batch_rows if batch_rows is not None \
            else self.conf.get(READER_BATCH_SIZE_ROWS)
        self.filter_expr = filter_expr  # pyarrow dataset filter (pushdown)
        first = pq.read_schema(self.files[0])
        ht = HostTable.from_arrow(first.empty_table())
        self._schema = Schema([Field(n, c.dtype, True)
                               for n, c in zip(ht.names, ht.columns)])
        nparts = num_partitions or min(len(self.files), 8)
        per = math.ceil(len(self.files) / nparts)
        self._file_parts = [self.files[i * per:(i + 1) * per]
                            for i in range(nparts)
                            if self.files[i * per:(i + 1) * per]]

    def schema(self) -> Schema:
        return self._schema

    def push_filter(self, arrow_expr) -> None:
        """Planner pushdown hook (io/pushdown.py): AND into any existing
        filter; row groups whose statistics exclude the predicate are
        skipped (reference: GpuParquetScanBase filter pushdown)."""
        self.filter_expr = arrow_expr if self.filter_expr is None \
            else (self.filter_expr & arrow_expr)

    def partitions(self) -> int:
        return len(self._file_parts)

    def read_partition(self, pidx: int, columns: Optional[List[str]] = None
                       ) -> Iterator[HostTable]:
        files = self._file_parts[pidx]
        if self.reader_type == "MULTITHREADED":
            yield from self._read_multithreaded(files, columns)
        elif self.reader_type == "PERFILE":
            for f in files:
                for t in self._read_file_batches(f, columns):
                    yield t
        else:  # COALESCING (also AUTO)
            yield from self._read_coalescing(files, columns)

    # -- strategies ----------------------------------------------------------
    def _read_file(self, path: str, columns) -> pa.Table:
        if self.filter_expr is not None:
            ds = pads.dataset(path, format="parquet")
            return ds.to_table(columns=columns, filter=self.filter_expr)
        return pq.read_table(path, columns=columns, use_threads=True)

    def _read_file_batches(self, path: str, columns) -> Iterator[HostTable]:
        from .file_block import set_input_file
        t = self._read_file(path, columns)
        set_input_file(path, 0, os.path.getsize(path))
        pos = 0
        while pos < t.num_rows:
            yield HostTable.from_arrow(t.slice(pos, self.batch_rows))
            pos += self.batch_rows
        if t.num_rows == 0:
            yield HostTable.from_arrow(t)

    def _read_coalescing(self, files: Sequence[str], columns
                         ) -> Iterator[HostTable]:
        # merged batches span files: no single-file attribution (the
        # InputFileBlockRule analogue selects PERFILE when file-info
        # expressions appear, exactly like the reference's readers)
        from .file_block import clear_input_file
        clear_input_file()
        pending: List[pa.Table] = []
        pending_rows = 0
        for f in files:
            t = self._read_file(f, columns)
            pending.append(t)
            pending_rows += t.num_rows
            if pending_rows >= self.batch_rows:
                merged = pa.concat_tables(pending)
                yield from self._slice_out(merged)
                pending, pending_rows = [], 0
        if pending:
            merged = pa.concat_tables(pending)
            yield from self._slice_out(merged, allow_empty=True)

    def _slice_out(self, t: pa.Table, allow_empty: bool = False
                   ) -> Iterator[HostTable]:
        if t.num_rows == 0 and allow_empty:
            yield HostTable.from_arrow(t)
            return
        pos = 0
        while pos < t.num_rows:
            yield HostTable.from_arrow(t.slice(pos, self.batch_rows))
            pos += self.batch_rows

    def _read_multithreaded(self, files: Sequence[str], columns
                            ) -> Iterator[HostTable]:
        nthreads = self.conf.get(MULTITHREAD_READ_NUM_THREADS)
        with cf.ThreadPoolExecutor(max_workers=nthreads,
                                   thread_name_prefix="srtpu-pq-read") \
                as pool:
            from .file_block import set_input_file
            futures = [pool.submit(self._read_file, f, columns) for f in files]
            for f, fut in zip(files, futures):  # file order kept, reads overlap
                t = fut.result()
                set_input_file(f, 0, os.path.getsize(f))
                yield from self._slice_out(t, allow_empty=True)

    def estimated_size_bytes(self):
        return sum(os.path.getsize(f) for f in self.files)

    def name(self) -> str:
        return f"Parquet[{len(self.files)} files, {self.reader_type}]"
