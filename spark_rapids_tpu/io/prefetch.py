"""Bounded read-ahead over an ordered work list.

Shared by the file scanners (reference: the multithreaded readers'
read-pool pipelining, GpuMultiFileReader.scala:934): submit up to
``window`` items to a thread pool, yield results in ORDER as
``(item, result)`` pairs, and keep the window full as items complete.
Bounding the window caps resident decoded data (a whole-partition submit
would pin every file's result until the consumer drains).
"""
from __future__ import annotations

import concurrent.futures as cf
from collections import deque
from typing import Callable, Iterable, Iterator, Tuple, TypeVar

__all__ = ["prefetched", "coalesce_tables"]


def coalesce_tables(files, read_fn, batch_rows: int):
    """COALESCING reader core shared by the file formats: accumulate small
    files until at least ``batch_rows`` rows are pending, then yield ONE
    concatenated arrow table (reference: the coalescing multi-file readers,
    GpuMultiFileReader.scala:126 — small files stitch into full-size
    batches so each device upload/decode sees real work)."""
    import pyarrow as pa
    pending, pending_rows = [], 0
    for f in files:
        t = read_fn(f)
        pending.append(t)
        pending_rows += t.num_rows
        if pending_rows >= batch_rows:
            yield pa.concat_tables(pending)
            pending, pending_rows = [], 0
    if pending:
        yield pa.concat_tables(pending)

T = TypeVar("T")
R = TypeVar("R")


def prefetched(items: Iterable[T], fn: Callable[[T], R],
               window: int) -> Iterator[Tuple[T, R]]:
    items = list(items)
    if not items:
        return
    window = max(1, window)
    with cf.ThreadPoolExecutor(max_workers=window,
                               thread_name_prefix="srtpu-io-prefetch") \
            as pool:
        pending: deque = deque()  # (item, future): pairing stays exact
        it = iter(items)
        for x in it:
            pending.append((x, pool.submit(fn, x)))
            if len(pending) >= window:
                break
        while pending:
            item, fut = pending.popleft()
            result = fut.result()
            nxt = next(it, None)
            if nxt is not None:
                pending.append((nxt, pool.submit(fn, nxt)))
            yield item, result
