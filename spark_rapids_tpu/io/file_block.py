"""Input-file block context.

Reference: Spark's InputFileBlockHolder thread-local, which readers populate
and input_file_name()/input_file_block_start()/input_file_block_length()
read; the plugin's InputFileBlockRule additionally forces the PERFILE reader
when these expressions appear, because the coalescing reader merges many
files into one batch and loses attribution (GpuParquetScanBase docs).

Same design here: sources set the holder right before yielding each batch;
expression evaluation happens while the generator frame is suspended, so the
holder still describes the batch being processed.
"""
from __future__ import annotations

import threading
from typing import Tuple

__all__ = ["set_input_file", "clear_input_file", "current_input_file"]

_TL = threading.local()


def set_input_file(name: str, start: int = 0, length: int = -1) -> None:
    _TL.info = (name, int(start), int(length))


def clear_input_file() -> None:
    _TL.info = ("", 0, -1)


def current_input_file() -> Tuple[str, int, int]:
    return getattr(_TL, "info", ("", 0, -1))
