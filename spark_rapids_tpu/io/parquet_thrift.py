"""Minimal Thrift compact-protocol reader for parquet page headers.

Reference: the plugin's device parquet reader walks raw column chunks and
parses page headers itself rather than round-tripping through the host
decoder (GpuParquetScanBase.scala:995,1194; the native kernels consume raw
page buffers). pyarrow exposes file/row-group/column-chunk METADATA but not
page boundaries, so this module implements just enough of the Thrift compact
protocol (parquet.thrift PageHeader and friends) to split a column chunk
into its pages. Implemented from the public Thrift compact protocol spec.

Only the fields the device decoder needs are materialized; everything else
is skipped structurally (unknown fields must be skipped, not rejected, for
forward compatibility).
"""
from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Optional, Tuple

__all__ = ["PageHeader", "read_page_header", "Encoding", "PageType"]


class PageType:
    DATA_PAGE = 0
    INDEX_PAGE = 1
    DICTIONARY_PAGE = 2
    DATA_PAGE_V2 = 3


class Encoding:
    PLAIN = 0
    PLAIN_DICTIONARY = 2
    RLE = 3
    BIT_PACKED = 4
    RLE_DICTIONARY = 8


@dataclass
class PageHeader:
    page_type: int
    uncompressed_size: int
    compressed_size: int
    num_values: int = 0
    encoding: int = Encoding.PLAIN
    def_level_encoding: int = Encoding.RLE
    rep_level_encoding: int = Encoding.RLE
    header_bytes: int = 0  # length of the serialized header itself
    # DataPageHeaderV2 extras (parquet.thrift): levels sit uncompressed in
    # front of the (optionally compressed) values section
    num_nulls: int = 0
    def_levels_byte_length: int = 0
    rep_levels_byte_length: int = 0
    v2_is_compressed: bool = True


# -- compact protocol primitives --------------------------------------------
_CT_STOP = 0
_CT_TRUE = 1
_CT_FALSE = 2
_CT_BYTE = 3
_CT_I16 = 4
_CT_I32 = 5
_CT_I64 = 6
_CT_DOUBLE = 7
_CT_BINARY = 8
_CT_LIST = 9
_CT_SET = 10
_CT_MAP = 11
_CT_STRUCT = 12


def _varint(buf: bytes, pos: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _skip(buf: bytes, pos: int, ctype: int) -> int:
    if ctype in (_CT_TRUE, _CT_FALSE):
        return pos
    if ctype == _CT_BYTE:
        return pos + 1
    if ctype in (_CT_I16, _CT_I32, _CT_I64):
        _, pos = _varint(buf, pos)
        return pos
    if ctype == _CT_DOUBLE:
        return pos + 8
    if ctype == _CT_BINARY:
        n, pos = _varint(buf, pos)
        return pos + n
    if ctype in (_CT_LIST, _CT_SET):
        head = buf[pos]
        pos += 1
        size = head >> 4
        etype = head & 0x0F
        if size == 15:
            size, pos = _varint(buf, pos)
        for _ in range(size):
            pos = _skip(buf, pos, etype)
        return pos
    if ctype == _CT_MAP:
        size, pos = _varint(buf, pos)
        if size:
            kv = buf[pos]
            pos += 1
            for _ in range(size):
                pos = _skip(buf, pos, kv >> 4)
                pos = _skip(buf, pos, kv & 0x0F)
        return pos
    if ctype == _CT_STRUCT:
        fid = 0
        while True:
            head = buf[pos]
            pos += 1
            if head == _CT_STOP:
                return pos
            delta = head >> 4
            ftype = head & 0x0F
            if delta:
                fid += delta
            else:
                z, pos = _varint(buf, pos)
                fid = _zigzag(z)
            pos = _skip(buf, pos, ftype)
    raise ValueError(f"unknown thrift compact type {ctype}")


class _StructReader:
    """Iterate (field_id, ctype, pos) over one compact struct."""

    def __init__(self, buf: bytes, pos: int):
        self.buf = buf
        self.pos = pos
        self.fid = 0

    def fields(self):
        while True:
            head = self.buf[self.pos]
            self.pos += 1
            if head == _CT_STOP:
                return
            delta = head >> 4
            ctype = head & 0x0F
            if delta:
                self.fid += delta
            else:
                z, self.pos = _varint(self.buf, self.pos)
                self.fid = _zigzag(z)
            yield self.fid, ctype

    def read_i32(self) -> int:
        z, self.pos = _varint(self.buf, self.pos)
        return _zigzag(z)

    def skip(self, ctype: int):
        self.pos = _skip(self.buf, self.pos, ctype)


def read_page_header(buf: bytes, pos: int = 0) -> PageHeader:
    """Parse one PageHeader starting at ``pos``; header_bytes records how
    many bytes the header consumed (page data follows immediately)."""
    start = pos
    hdr = PageHeader(page_type=-1, uncompressed_size=0, compressed_size=0)
    r = _StructReader(buf, pos)
    for fid, ctype in r.fields():
        if fid == 1:        # PageType
            hdr.page_type = r.read_i32()
        elif fid == 2:      # uncompressed_page_size
            hdr.uncompressed_size = r.read_i32()
        elif fid == 3:      # compressed_page_size
            hdr.compressed_size = r.read_i32()
        elif fid == 5 and ctype == _CT_STRUCT:   # DataPageHeader
            dr = _StructReader(r.buf, r.pos)
            for dfid, dctype in dr.fields():
                if dfid == 1:
                    hdr.num_values = dr.read_i32()
                elif dfid == 2:
                    hdr.encoding = dr.read_i32()
                elif dfid == 3:
                    hdr.def_level_encoding = dr.read_i32()
                elif dfid == 4:
                    hdr.rep_level_encoding = dr.read_i32()
                else:
                    dr.skip(dctype)
            r.pos = dr.pos
        elif fid == 7 and ctype == _CT_STRUCT:   # DictionaryPageHeader
            dr = _StructReader(r.buf, r.pos)
            for dfid, dctype in dr.fields():
                if dfid == 1:
                    hdr.num_values = dr.read_i32()
                elif dfid == 2:
                    hdr.encoding = dr.read_i32()
                else:
                    dr.skip(dctype)
            r.pos = dr.pos
        elif fid == 8 and ctype == _CT_STRUCT:   # DataPageHeaderV2
            dr = _StructReader(r.buf, r.pos)
            for dfid, dctype in dr.fields():
                if dfid == 1:
                    hdr.num_values = dr.read_i32()
                elif dfid == 2:
                    hdr.num_nulls = dr.read_i32()
                elif dfid == 4:
                    hdr.encoding = dr.read_i32()
                elif dfid == 5:
                    hdr.def_levels_byte_length = dr.read_i32()
                elif dfid == 6:
                    hdr.rep_levels_byte_length = dr.read_i32()
                elif dfid == 7:
                    # bool lives in the field-header type nibble
                    hdr.v2_is_compressed = (dctype == _CT_TRUE)
                else:
                    dr.skip(dctype)
            r.pos = dr.pos
        else:
            r.skip(ctype)
    hdr.header_bytes = r.pos - start
    return hdr
