"""Device JSON-lines decode (reference: GpuJsonScan.scala — cuDF's device
JSON parse with per-type gates, RapidsConf.scala:877-917).

Scope (tag-gated; anything else falls back to the host pyarrow reader):
flat schemas of bool/int/float/string/date, standard JSON-lines with NO
backslash escapes in the sampled bytes. Within that scope the decode is
exact and fully vectorized over the (rows, W) line byte matrix:

- string state = parity of a cumulative double-quote count (valid because
  escapes are excluded), so key tokens, value spans, and top-level
  delimiters are all recognizable elementwise;
- per field: match the ``"name"`` token at string-opening positions,
  locate the colon, slice the value span (quote-delimited for strings,
  up-to-top-level ``,``/``}`` otherwise), scatter it into a field byte
  matrix, and feed the existing string->typed cast kernels
  (expr/cast_kernels.py) — one jitted program per (schema, bucket).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..columnar import dtypes as dt
from ..conf import register_conf

JSON_DEVICE_DECODE = register_conf(
    "spark.rapids.tpu.json.deviceDecode.enabled",
    "Decode JSON-lines scans on the accelerator (quote-parity span "
    "extraction + typed parse kernels). Escaped strings, nested values, "
    "and timestamp columns fall back to the host reader (reference: "
    "GpuJsonScan per-type gates).", True)

__all__ = ["JSON_DEVICE_DECODE", "decode_json_lines",
           "json_device_decodable_reason"]


def json_device_decodable_reason(schema, sample: bytes) -> Optional[str]:
    """None when the device decoder can handle this scan, else the reason."""
    if b"\\" in sample:
        return "escaped strings use the host reader"
    for f in schema:
        d = f.dtype
        if isinstance(d, (dt.ArrayType, dt.StructType, dt.MapType)):
            return f"nested column {f.name} decodes host-side"
        if isinstance(d, dt.TimestampType):
            return f"timestamp column {f.name} parses on the host"
        if not isinstance(d, (dt.StringType, dt.BooleanType, dt.ByteType,
                              dt.ShortType, dt.IntegerType, dt.LongType,
                              dt.FloatType, dt.DoubleType, dt.DateType)):
            return f"column {f.name}: {d!r} has no device JSON parser"
    return None


def _match_token(mat, token: bytes):
    """(rows, W) bool: token starts at byte j (overruns never match)."""
    import jax.numpy as jnp
    rows, w = mat.shape
    eq = jnp.ones((rows, w), dtype=bool)
    for l, ch in enumerate(token):
        if l == 0:
            shifted = mat
        else:
            shifted = jnp.pad(mat[:, l:], ((0, 0), (0, l)))
        eq = jnp.logical_and(eq, shifted == np.uint8(ch))
    if len(token) > 1:
        j = jnp.arange(w, dtype=jnp.int32)
        eq = jnp.logical_and(eq, j[None, :] <= w - len(token))
    return eq


def decode_json_lines(mat, lengths,
                      fields: List[Tuple[str, dt.DataType]],
                      col_indices: List[int]):
    """Jit-traceable: (rows, W) JSON-line matrix -> per-column planes,
    same output contract as csv_device.decode_lines."""
    import jax
    import jax.numpy as jnp

    from ..expr.cast_kernels import (string_to_bool_device,
                                     string_to_date_device,
                                     string_to_double_device,
                                     string_to_long_device)
    rows, w = mat.shape
    j = jnp.arange(w, dtype=jnp.int32)
    in_line = j[None, :] < lengths[:, None]
    quote = jnp.logical_and(mat == np.uint8(ord('"')), in_line)
    # parity BEFORE byte j: True = byte j sits inside a string literal
    cum_q = jnp.cumsum(quote.astype(jnp.int32), axis=1)
    in_str = ((cum_q - quote.astype(jnp.int32)) % 2) == 1
    is_space = jnp.logical_or(
        mat == np.uint8(ord(" ")),
        jnp.logical_or(mat == np.uint8(ord("\t")),
                       mat == np.uint8(ord("\r"))))
    top_delim = jnp.logical_and(
        jnp.logical_and(
            jnp.logical_or(mat == np.uint8(ord(",")),
                           mat == np.uint8(ord("}"))),
            jnp.logical_not(in_str)), in_line)
    rix = jnp.broadcast_to(jnp.arange(rows, dtype=jnp.int32)[:, None],
                           (rows, w))
    null_tok = _match_token(mat, b"null")

    out = []
    for k in col_indices:
        name, d = fields[k]
        token = b'"' + name.encode() + b'"'
        L = len(token)
        # key token: opening quote at non-string parity, and the first
        # non-whitespace byte after it must be a colon (any run of
        # spaces/tabs tolerated — standard JSON formatting)
        m = jnp.logical_and(_match_token(mat, token),
                            jnp.logical_not(in_str))
        m = jnp.logical_and(m, in_line)
        nonspace_l = jnp.logical_and(jnp.logical_not(is_space), in_line)
        # next_ns[i, jj] = first column >= jj with a non-space byte (w-1
        # clamp; suffix-min scan) — lets every candidate validate "next
        # non-space is ':'" so a string VALUE equal to the key token can
        # never shadow the real key
        ns_idx = jnp.where(nonspace_l, j[None, :], w)
        next_ns = jax.lax.cummin(ns_idx[:, ::-1], axis=1)[:, ::-1]
        next_ns_safe = jnp.clip(next_ns, 0, w - 1)
        colon_at_next = jnp.take_along_axis(mat, next_ns_safe, axis=1) \
            == np.uint8(ord(":"))
        colon_at_next = jnp.logical_and(colon_at_next, next_ns < w)
        # candidate at j is a real key iff colon_at_next at column j+L
        colon_after = jnp.pad(colon_at_next[:, L:], ((0, 0), (0, L)))
        valid_cand = jnp.logical_and(m, colon_after)
        present = jnp.any(valid_cand, axis=1)
        kpos = jnp.where(present, jnp.argmax(valid_cand, axis=1), 0) \
            .astype(jnp.int32)
        cpos = jnp.take_along_axis(
            next_ns_safe, jnp.clip(kpos + L, 0, w - 1)[:, None],
            axis=1)[:, 0]
        after_colon = j[None, :] > cpos[:, None]
        nonspace = jnp.logical_and(jnp.logical_not(is_space), in_line)
        vstart_mask = jnp.logical_and(after_colon, nonspace)
        has_v = jnp.any(vstart_mask, axis=1)
        vstart = jnp.where(has_v, jnp.argmax(vstart_mask, axis=1), 0) \
            .astype(jnp.int32)
        first_byte = jnp.take_along_axis(mat, vstart[:, None], axis=1)[:, 0]
        is_str_val = first_byte == np.uint8(ord('"'))
        # string value: [vstart+1, next quote); other: [vstart, next
        # top-level , or } )
        after_vs = j[None, :] > vstart[:, None]
        closeq = jnp.logical_and(quote, after_vs)
        q_end = jnp.where(jnp.any(closeq, axis=1),
                          jnp.argmax(closeq, axis=1),
                          lengths).astype(jnp.int32)
        d_end_mask = jnp.logical_and(top_delim, after_vs)
        d_end = jnp.where(jnp.any(d_end_mask, axis=1),
                          jnp.argmax(d_end_mask, axis=1),
                          lengths).astype(jnp.int32)
        start = jnp.where(is_str_val, vstart + 1, vstart)
        end = jnp.where(is_str_val, q_end, d_end)
        # null literal or absent key -> null
        v_null = jnp.take_along_axis(null_tok, vstart[:, None], axis=1)[:, 0]
        valid_span = jnp.logical_and(
            jnp.logical_and(present, has_v),
            jnp.logical_and(jnp.logical_not(v_null), end >= start))
        span = jnp.logical_and(j[None, :] >= start[:, None],
                               j[None, :] < end[:, None])
        span = jnp.logical_and(span, in_line)
        flen = jnp.where(valid_span, (end - start), 0).astype(jnp.int32)
        dest = jnp.where(span, j - start[:, None], w)
        fmat = jnp.zeros((rows, w + 1), jnp.uint8) \
            .at[rix, dest].set(mat, mode="drop")[:, :w]
        if isinstance(d, dt.StringType):
            # empty strings "" stay VALID strings in JSON (unlike CSV)
            out.append((fmat, jnp.logical_and(valid_span, is_str_val),
                        flen))
            continue
        if isinstance(d, dt.BooleanType):
            vals, ok = string_to_bool_device(fmat, flen)
        elif isinstance(d, (dt.ByteType, dt.ShortType, dt.IntegerType,
                            dt.LongType)):
            vals, ok = string_to_long_device(fmat, flen)
            info = np.iinfo(d.np_dtype())
            ok = jnp.logical_and(
                ok, jnp.logical_and(vals >= info.min, vals <= info.max))
            vals = vals.astype(d.np_dtype())
        elif isinstance(d, (dt.FloatType, dt.DoubleType)):
            vals, ok = string_to_double_device(fmat, flen)
            vals = vals.astype(d.np_dtype())
        elif isinstance(d, dt.DateType):
            # dates arrive as quoted strings
            vals, ok = string_to_date_device(fmat, flen)
        else:  # pragma: no cover - gated by json_device_decodable_reason
            raise TypeError(f"no device JSON parser for {d!r}")
        valid = jnp.logical_and(jnp.logical_and(valid_span, flen > 0), ok)
        vals = jnp.where(valid, vals, jnp.zeros((), vals.dtype))
        out.append((vals, valid))
    return out
