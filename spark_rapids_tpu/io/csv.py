"""CSV scan (reference: GpuTextBasedPartitionReader.scala +
GpuReadCSVFileFormat.scala — host line handling + device parse; here pyarrow
does the host decode, the same per-type enable flags gate planning
(RapidsConf.scala:877-917)).
"""
from __future__ import annotations

import concurrent.futures as cf
import glob as _glob
import math
import os
from typing import Iterator, List, Optional

import pyarrow as pa
import pyarrow.csv as pacsv

from ..conf import MULTITHREAD_READ_NUM_THREADS, RapidsConf, register_conf
from ..columnar.host import HostTable, _dtype_to_arrow
from ..plan.logical import DataSource
from ..plan.schema import Field, Schema

CSV_ENABLED = register_conf(
    "spark.rapids.sql.format.csv.enabled",
    "Enable CSV scans (reference: RapidsConf.scala csv flags).", True)

# per-type enable flags (reference: RapidsConf.scala:877-917 csv read type
# flags — a disabled type is read as raw strings instead of parsed values,
# the conservative fallback the reference achieves by keeping the scan on
# the CPU for those columns)
_CSV_TYPE_FLAGS = {}
for _t, _pa_check in (("bool", "is_boolean"), ("int", "is_integer"),
                      ("float", "is_float32"), ("double", "is_float64"),
                      ("date", "is_date"), ("timestamp", "is_timestamp")):
    _CSV_TYPE_FLAGS[_t] = (register_conf(
        f"spark.rapids.sql.csv.read.{_t}.enabled",
        f"Parse {_t} columns in CSV scans; when false, inferred {_t} "
        "columns are read as strings (reference: csv per-type read flags, "
        "RapidsConf.scala:877-917).", True), _pa_check)

CSV_READER_TYPE = register_conf(
    "spark.rapids.sql.format.csv.reader.type",
    "CSV multi-file reader strategy: PERFILE, MULTITHREADED (read pool), "
    "COALESCING (stitch small files into full batches), or AUTO "
    "(reference: GpuMultiFileReader.scala:126 reader selection).", "AUTO",
    checker=lambda v: None if str(v).upper() in
    ("AUTO", "PERFILE", "MULTITHREADED", "COALESCING")
    else "must be AUTO|PERFILE|MULTITHREADED|COALESCING")

__all__ = ["CsvSource"]


def _type_disabled(conf: RapidsConf, t: pa.DataType) -> bool:
    import pyarrow.types as pat
    for flag, check in _CSV_TYPE_FLAGS.values():
        if getattr(pat, check)(t) and not conf.get(flag):
            return True
    return False


def _expand(paths) -> List[str]:
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        p = os.fspath(p)
        if os.path.isdir(p):
            out.extend(sorted(_glob.glob(os.path.join(p, "**", "*.csv"),
                                         recursive=True)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no csv files for {paths}")
    return out


class CsvSource(DataSource):
    def __init__(self, paths, conf: Optional[RapidsConf] = None, schema=None,
                 header: bool = True, sep: str = ",",
                 num_partitions: Optional[int] = None,
                 batch_rows: Optional[int] = None):
        self.files = _expand(paths)
        self.conf = conf or RapidsConf()
        self.header = header
        self.sep = sep
        from ..conf import READER_BATCH_SIZE_ROWS
        self.batch_rows = batch_rows if batch_rows is not None \
            else self.conf.get(READER_BATCH_SIZE_ROWS)
        self.reader_type = str(self.conf.get(CSV_READER_TYPE)).upper()
        self._explicit_schema = schema
        self._forced_strings: List[str] = []
        sample = self._read_file(self.files[0], nrows=1000)
        self._forced_strings = [
            f.name for f in sample.schema
            if _type_disabled(self.conf, f.type)]
        first = self._read_file(self.files[0], nrows=1000) \
            if self._forced_strings else sample
        ht = HostTable.from_arrow(first.slice(0, 0))
        self._schema = Schema([Field(n, c.dtype, True)
                               for n, c in zip(ht.names, ht.columns)])
        nparts = num_partitions or min(len(self.files), 8)
        per = math.ceil(len(self.files) / nparts)
        self._file_parts = [self.files[i * per:(i + 1) * per]
                            for i in range(nparts)
                            if self.files[i * per:(i + 1) * per]]

    def _read_options(self, nrows=None):
        ro = pacsv.ReadOptions(autogenerate_column_names=not self.header)
        po = pacsv.ParseOptions(delimiter=self.sep)
        column_types = {}
        if self._explicit_schema:
            column_types = {k: _dtype_to_arrow(v)
                            for k, v in self._explicit_schema.items()}
        for name in self._forced_strings:
            column_types.setdefault(name, pa.string())
        co = pacsv.ConvertOptions(column_types=column_types or None,
                                  strings_can_be_null=True)
        return ro, po, co

    def _read_file(self, path: str, nrows=None) -> pa.Table:
        ro, po, co = self._read_options(nrows)
        if nrows is not None:
            # bounded streaming sample for schema inference: small block
            # size so a malformed row deep in the file neither fails nor
            # slows source construction (full reads surface it instead)
            ro = pacsv.ReadOptions(
                autogenerate_column_names=not self.header,
                block_size=1 << 12)
            batches = []
            got = 0
            schema = None
            try:
                # malformed rows inside the sample window: schema
                # inference is best-effort — the FULL read raises the
                # parse error on whichever engine runs the scan
                with pacsv.open_csv(path, read_options=ro,
                                    parse_options=po,
                                    convert_options=co) as reader:
                    schema = reader.schema
                    for b in reader:
                        batches.append(b)
                        got += b.num_rows
                        if got >= nrows:
                            break
            except (StopIteration, pa.ArrowInvalid):
                if schema is None and not batches:
                    raise  # not even one clean block: surface the error
            if not batches:
                return schema.empty_table()
            return pa.Table.from_batches(batches).slice(0, nrows)
        return pacsv.read_csv(path, read_options=ro, parse_options=po,
                              convert_options=co)

    def schema(self) -> Schema:
        return self._schema

    def sample_head(self, nbytes: int = 1 << 16) -> bytes:
        """First bytes of the first file — quote sniffing for the device
        decoder gate (exec/scan.py TpuCsvScanExec)."""
        with open(self.files[0], "rb") as f:
            return f.read(nbytes)

    def partitions(self) -> int:
        return len(self._file_parts)

    def read_partition(self, pidx: int, columns: Optional[List[str]] = None
                       ) -> Iterator[HostTable]:
        files = self._file_parts[pidx]
        rtype = str(self.reader_type).upper()   # planner may force PERFILE
        if rtype == "COALESCING":
            yield from self._read_coalescing(files, columns)
            return
        from .file_block import set_input_file
        if rtype == "PERFILE":
            for f in files:
                t = self._read_file(f)
                set_input_file(f, 0, os.path.getsize(f))
                yield from self._slice_out(t, columns)
            return
        nthreads = self.conf.get(MULTITHREAD_READ_NUM_THREADS)
        with cf.ThreadPoolExecutor(max_workers=nthreads,
                                   thread_name_prefix="srtpu-csv-read") \
                as pool:
            futures = [pool.submit(self._read_file, f) for f in files]
            for f, fut in zip(files, futures):
                t = fut.result()
                set_input_file(f, 0, os.path.getsize(f))
                yield from self._slice_out(t, columns)

    def _read_coalescing(self, files, columns) -> Iterator[HostTable]:
        from .file_block import clear_input_file
        from .prefetch import coalesce_tables
        clear_input_file()
        for merged in coalesce_tables(files, self._read_file,
                                      self.batch_rows):
            yield from self._slice_out(merged, columns)

    def name(self) -> str:
        return f"CSV[{len(self.files)} files]"
