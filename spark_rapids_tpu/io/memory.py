"""In-memory data source (arrow/pandas/pydict), the LocalTableScan analogue."""
from __future__ import annotations

import math
from typing import Iterator, List, Optional

import pyarrow as pa

from ..columnar.host import HostTable
from ..plan.logical import DataSource
from ..plan.schema import Field, Schema

__all__ = ["InMemorySource"]


class InMemorySource(DataSource):
    def __init__(self, table: pa.Table, num_partitions: int = 1,
                 batch_rows: int = 1 << 20):
        self.table = table
        self._parts = max(1, num_partitions)
        self.batch_rows = batch_rows
        self._decoded = {}  # (pidx, columns) -> List[HostTable]
        ht = HostTable.from_arrow(table.slice(0, 0))
        # trust declared nullability only when the data agrees: pyarrow
        # does not validate nullable=False against the arrays, and device
        # gates (e.g. map() null-key rejection) rely on this bit
        self._schema = Schema([
            Field(n, c.dtype, table.schema.field(i).nullable
                  or table.column(i).null_count > 0)
            for i, (n, c) in enumerate(zip(ht.names, ht.columns))])

    def schema(self) -> Schema:
        return self._schema

    def partitions(self) -> int:
        return self._parts

    def read_partition(self, pidx: int, columns: Optional[List[str]] = None
                       ) -> Iterator[HostTable]:
        from .file_block import clear_input_file
        clear_input_file()  # in-memory data has no source file
        key = (pidx, None if columns is None else tuple(columns))
        cached = self._decoded.get(key)
        if cached is not None:
            yield from cached
            return
        n = self.table.num_rows
        per = math.ceil(n / self._parts) if n else 0
        lo = min(n, pidx * per)
        hi = min(n, (pidx + 1) * per)
        t = self.table.slice(lo, hi - lo)
        if columns:
            t = t.select(columns)
        out: List[HostTable] = []
        pos = 0
        while pos < t.num_rows or (pos == 0 and t.num_rows == 0):
            chunk = t.slice(pos, self.batch_rows)
            ht = HostTable.from_arrow(chunk)
            out.append(ht)
            yield ht
            pos += self.batch_rows
            if t.num_rows == 0:
                break
        # arrow->HostTable decode is deterministic and the source is
        # immutable: cache it so repeated executions (AQE double passes,
        # warm-then-timed bench runs) skip the object-array decode.
        # Bounded: decoded object arrays can dwarf the arrow buffers, so
        # distinct column subsets must not accumulate without limit
        if len(self._decoded) >= 4 * self._parts:
            self._decoded.clear()
        self._decoded[key] = out

    def estimated_size_bytes(self):
        return self.table.nbytes

    def name(self) -> str:
        return f"InMemory[{self.table.num_rows} rows]"
