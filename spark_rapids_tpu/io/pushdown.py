"""Scan predicate pushdown.

Reference: the plugin forwards Spark's pushed filters into its readers —
parquet row-group pruning via statistics (GpuParquetScanBase) and ORC
search-arguments (OrcFilters → GpuOrcScanBase). Here the planner translates
supported conjuncts of a Filter-over-Scan into a ``pyarrow.dataset``
expression attached to the source; parquet prunes row groups by statistics,
ORC prunes via the dataset reader. The full filter stays in the plan (the
pushdown is a may-skip-data optimization, exactly like the reference).
"""
from __future__ import annotations

from typing import Optional

from ..expr.base import AttributeReference, Expression, Literal

__all__ = ["to_arrow_filter", "push_filter_into_scan"]


def _is_widening(src, dst) -> bool:
    """Value-preserving numeric widening only: every src value maps to the
    SAME logical value in dst (so stripping the cast cannot change a
    comparison). Narrowing casts (double->int truncation etc.) must NOT be
    stripped — the cast changes the compared value."""
    from ..columnar import dtypes as dt
    if src == dst:
        return True
    int_rank = {dt.BYTE: 1, dt.SHORT: 2, dt.INT: 3, dt.LONG: 4}
    fp_rank = {dt.FLOAT: 1, dt.DOUBLE: 2}
    if src in int_rank and dst in int_rank:
        return int_rank[src] <= int_rank[dst]
    if src in fp_rank and dst in fp_rank:
        return fp_rank[src] <= fp_rank[dst]
    # int -> float is exact only within the mantissa
    if src in int_rank and dst in fp_rank:
        bits = {dt.BYTE: 8, dt.SHORT: 16, dt.INT: 32, dt.LONG: 64}[src]
        mant = {dt.FLOAT: 24, dt.DOUBLE: 53}[dst]
        return bits <= mant
    return False


def to_arrow_filter(e: Expression, strict: bool = False):
    """Translate a supported predicate subtree into a pyarrow.dataset
    expression; None when any part is untranslatable. Non-strict mode may
    return a PARTIAL conjunction (sound for positive pushdown: it only
    over-approximates the kept rows); under Not the child must translate in
    ``strict`` mode — negating a partial conjunction would DROP rows."""
    import pyarrow.dataset as pads

    from ..expr.predicates import (And, EqualTo, GreaterThan,
                                   GreaterThanOrEqual, In, IsNotNull, IsNull,
                                   LessThan, LessThanOrEqual, Not, Or)

    def unwrap(x):
        # type coercion wraps operands in value-preserving widening casts
        # (int literal vs long column etc.); only those may be stripped
        from ..expr.cast import Cast
        while isinstance(x, Cast):
            try:
                src = x.child.data_type
            except Exception:
                break
            if _is_widening(src, x.to):
                x = x.child
            else:
                break
        return x

    def field_lit(a, b):
        a, b = unwrap(a), unwrap(b)
        if isinstance(a, AttributeReference) and isinstance(b, Literal):
            return pads.field(a.column_name), b.value
        return None, None

    if isinstance(e, And):
        l = to_arrow_filter(e.left, strict)
        r = to_arrow_filter(e.right, strict)
        if l is not None and r is not None:
            return l & r
        if strict:
            return None  # a negation context needs FULL fidelity
        return l if r is None else r  # partial conjunction is still sound
    if isinstance(e, Or):
        l = to_arrow_filter(e.left, strict)
        r = to_arrow_filter(e.right, strict)
        # a partial disjunction would DROP rows; need both sides
        return (l | r) if l is not None and r is not None else None
    if isinstance(e, Not):
        inner = to_arrow_filter(e.children[0], strict=True)
        return ~inner if inner is not None else None
    if isinstance(e, IsNull):
        c = e.children[0]
        if isinstance(c, AttributeReference):
            import pyarrow.dataset as pads
            return pads.field(c.column_name).is_null()
        return None
    if isinstance(e, IsNotNull):
        c = e.children[0]
        if isinstance(c, AttributeReference):
            return ~pads.field(c.column_name).is_null()
        return None
    if isinstance(e, In):
        c = e.children[0]
        opts = e.children[1:]
        if isinstance(c, AttributeReference) \
                and all(isinstance(o, Literal) for o in opts):
            vals = [o.value for o in opts]
            if any(v is None for v in vals):
                return None
            return pads.field(c.column_name).isin(vals)
        return None
    ops = {EqualTo: "==", LessThan: "<", LessThanOrEqual: "<=",
           GreaterThan: ">", GreaterThanOrEqual: ">="}
    for cls, op in ops.items():
        if type(e) is cls:
            f, v = field_lit(e.left, e.right)
            flipped = {"==": "==", "<": ">", "<=": ">=", ">": "<", ">=": "<="}
            if f is None:
                f, v = field_lit(e.right, e.left)
                op = flipped[op]
            if f is None or v is None:
                return None
            import datetime as _dt
            if isinstance(v, (_dt.date, _dt.datetime, int, float, str, bool,
                              bytes)):
                return {"==": f.__eq__, "<": f.__lt__, "<=": f.__le__,
                        ">": f.__gt__, ">=": f.__ge__}[op](v)
            return None
    return None


def push_filter_into_scan(scan_source, condition: Expression) -> bool:
    """Attach the translatable part of ``condition`` to a source that
    supports it (ParquetSource/OrcSource ``push_filter``); returns True if
    anything was pushed."""
    push = getattr(scan_source, "push_filter", None)
    if push is None:
        return False
    arrow_expr = to_arrow_filter(condition)
    if arrow_expr is None:
        return False
    push(arrow_expr)
    return True
