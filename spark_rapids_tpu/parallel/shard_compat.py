"""Version-portable ``shard_map`` (the per-shard SPMD entry point).

jax moved ``shard_map`` out of ``jax.experimental`` and renamed its
replication-check keyword along the way:

- old jax: ``jax.experimental.shard_map.shard_map(..., check_rep=...)``
- new jax: ``jax.shard_map(..., check_vma=...)``

The engine's collectives (shuffle/ici.py all-to-all exchange, the driver
dry run) must disable the replication checker — the exchange's output specs
are data-dependent in ways the static checker rejects — so the keyword has
to be spelled per version. ``shard_map`` below resolves the import path and
the keyword once at import time; call it with ``check=False`` and forget
which jax is installed.
"""
from __future__ import annotations

import inspect

__all__ = ["shard_map"]

try:
    from jax import shard_map as _shard_map  # jax >= 0.6
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

# the replication/varying-manual-axes check kwarg: check_vma on new jax,
# check_rep before the rename; probe the signature instead of the version
# string (backports exist)
_PARAMS = inspect.signature(_shard_map).parameters
if "check_vma" in _PARAMS:
    _CHECK_KW = "check_vma"
elif "check_rep" in _PARAMS:
    _CHECK_KW = "check_rep"
else:  # pragma: no cover - future jax dropped the knob entirely
    _CHECK_KW = None


def shard_map(f, mesh, in_specs, out_specs, check: bool = True):
    """Map ``f`` over shards of ``mesh`` (jax.shard_map across versions).

    ``check=False`` disables the output-replication checker under whichever
    keyword the installed jax spells it."""
    kwargs = {}
    if not check and _CHECK_KW is not None:
        kwargs[_CHECK_KW] = False
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)
