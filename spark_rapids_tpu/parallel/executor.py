"""Executor-side runtime context + failure detection.

Reference mapping:
- ``ExecutorContext.initialize``  ~ RapidsExecutorPlugin.init
  (Plugin.scala:189-241): bind device, init memory pools/catalog, init
  semaphore, init shuffle env, register with the driver's heartbeat manager.
- ``ExecutorContext.shutdown``    ~ Plugin.scala:269-275.
- ``FailureDetector``             ~ the driver side of
  RapidsShuffleHeartbeatManager.scala: peers that miss beats are declared
  dead and listeners (shuffle manager, scheduler) are told to exclude them;
  recovery itself is delegated to host-engine retry the way the reference
  delegates to Spark stage retry (SURVEY §5 failure detection).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..conf import RapidsConf
from ..memory.catalog import BufferCatalog
from ..memory.semaphore import TpuSemaphore
from ..shuffle.manager import ShuffleManager
from ..shuffle.transport import ShuffleTransport

__all__ = ["ExecutorContext", "FailureDetector"]


class ExecutorContext:
    """Everything one executor process owns: device binding, buffer catalog
    (spill tiers), admission semaphore, shuffle manager."""

    def __init__(self, executor_id: int, conf: Optional[RapidsConf] = None,
                 transport: Optional[ShuffleTransport] = None,
                 device_index: Optional[int] = None):
        self.executor_id = executor_id
        self.conf = conf or RapidsConf()
        self.device_index = device_index if device_index is not None \
            else executor_id
        self._transport = transport
        self.catalog: Optional[BufferCatalog] = None
        self.semaphore: Optional[TpuSemaphore] = None
        self.shuffle: Optional[ShuffleManager] = None
        self.initialized = False

    def initialize(self) -> "ExecutorContext":
        """Fail-fast like the reference: an executor that cannot init its
        device/memory raises immediately (Plugin.scala:233-240 hard-exits)."""
        from ..conf import CONCURRENT_TPU_TASKS
        self.catalog = BufferCatalog(self.conf)
        self.semaphore = TpuSemaphore(self.conf.get(CONCURRENT_TPU_TASKS))
        self.shuffle = ShuffleManager(self.conf, self._transport)
        self.shuffle.heartbeats.register(self.executor_id)
        # broadcast relations materialize once and re-materialize from the
        # transport per executor (reference:
        # GpuBroadcastExchangeExec.scala:336-345)
        from ..shuffle.broadcast import BroadcastManager
        self.broadcast = BroadcastManager(
            self.shuffle.transport, self.catalog,
            self.conf.min_bucket_rows)
        self.initialized = True
        return self

    def dcn_transport(self):
        """Lazily-created DCN-tier transport (device-resident blocks,
        TCP wire between worker processes — shuffle/dcn.py
        TcpDcnShuffleTransport)."""
        if getattr(self, "_dcn", None) is None:
            from ..shuffle.dcn import TcpDcnShuffleTransport
            self._dcn = TcpDcnShuffleTransport(self.conf,
                                               catalog=self.catalog)
        return self._dcn

    def heartbeat(self):
        if self.shuffle is not None:
            self.shuffle.heartbeats.heartbeat(self.executor_id)

    def shutdown(self):
        if getattr(self, "_dcn", None) is not None:
            self._dcn.close()
            self._dcn = None
        if self.shuffle is not None:
            # free device-resident shuffle blocks (the catalog would
            # otherwise hold them for the process lifetime)
            self.shuffle.unregister_all()
        if self.shuffle is not None and self.shuffle.transport is not None \
                and self._transport is None:
            # only close transports we created ourselves
            self.shuffle.transport.close()
        self.initialized = False


class FailureDetector:
    """Declares peers dead after ``timeout_s`` without a heartbeat and
    notifies listeners once per death. Listener errors are swallowed — failure
    handling must not take down the control plane."""

    def __init__(self, timeout_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        self.timeout_s = timeout_s
        self._clock = clock
        self._last: Dict[int, float] = {}
        self._dead: set = set()
        self._listeners: List[Callable[[int], None]] = []
        self._lock = threading.Lock()

    def on_peer_lost(self, fn: Callable[[int], None]):
        self._listeners.append(fn)

    def heartbeat(self, executor_id: int):
        with self._lock:
            self._last[executor_id] = self._clock()
            # a returning executor id is treated as recovered
            self._dead.discard(executor_id)

    def check(self) -> List[int]:
        """Scan for newly-dead peers; fire listeners; return them."""
        now = self._clock()
        newly = []
        with self._lock:
            for e, t in self._last.items():
                if e not in self._dead and now - t >= self.timeout_s:
                    self._dead.add(e)
                    newly.append(e)
        for e in newly:
            for fn in self._listeners:
                try:
                    fn(e)
                except Exception:
                    pass  # srtpu: net-ok(a buggy listener must not stop the failure detector from notifying the remaining listeners)
        return newly

    def live(self) -> List[int]:
        with self._lock:
            return sorted(e for e in self._last if e not in self._dead)

    def dead(self) -> List[int]:
        with self._lock:
            return sorted(self._dead)
