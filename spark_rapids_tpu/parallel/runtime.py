"""Distributed runtime: driver control plane + local-cluster simulation.

Reference mapping:
- ``DriverRuntime``  ~ RapidsDriverPlugin (Plugin.scala:146-178): owns the
  heartbeat manager/failure detector, hands out executor ids, wires the
  shared transport.
- ``LocalCluster``   ~ Spark ``local-cluster[N, cores, mem]`` mode, the
  reference's no-real-cluster distribution test vehicle
  (integration_tests/README.md:66-86): N executor contexts in one process,
  each running its partitions on a worker thread, exchanging shuffle blocks
  through the shared transport. Device work is serialized per chip by each
  executor's TpuSemaphore (SURVEY §7 hard part (d)).

The GSPMD path (one jitted program over a Mesh, collectives over ICI) lives
in shuffle/ici.py + __graft_entry__.dryrun_multichip; this module is the
*task-parallel* path that mirrors the reference's executor model, used when
partitions outnumber chips or when running multi-host without a shared
program.
"""
from __future__ import annotations

import itertools
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

import pyarrow as pa

from ..columnar.host import HostTable
from ..conf import RapidsConf, _positive, register_conf
from ..shuffle.transport import LocalShuffleTransport, ShuffleTransport
from ..utils import faults
from .executor import ExecutorContext, FailureDetector

__all__ = ["DriverRuntime", "LocalCluster", "ProcessCluster",
           "TaskFailedError", "TaskTimeoutError"]

TASK_TIMEOUT = register_conf(
    "spark.rapids.tpu.task.timeout",
    "Default seconds a ProcessCluster task may run before the driver gives "
    "up and raises TaskTimeoutError with worker forensics (last heartbeat "
    "age, pending-queue depth). Per-call override via run_on(timeout_s=...).",
    300.0, checker=_positive("task timeout"))

TASK_MAX_FAILURES = register_conf(
    "spark.rapids.tpu.task.maxFailures",
    "Times a task may be attempted across worker deaths before the driver "
    "fails it with TaskFailedError (the spark.task.maxFailures analogue; "
    "tasks are only re-attempted on worker loss, never on application "
    "errors, which fail fast).",
    4, checker=_positive("max failures"))

TASK_RESPAWN_WORKERS = register_conf(
    "spark.rapids.tpu.task.respawnWorkers",
    "Replace a worker process that died on its own (crash, injected kill, "
    "heartbeat wedge) with a fresh one on the same slot. Deliberate "
    "ProcessCluster.kill() always excludes the slot instead.",
    True)

TASK_MAX_WORKER_RESPAWNS = register_conf(
    "spark.rapids.tpu.task.maxWorkerRespawns",
    "Respawns allowed per worker slot before the slot is excluded from "
    "the cluster (the executor-exclusion analogue).",
    2)

TASK_HEARTBEAT_INTERVAL = register_conf(
    "spark.rapids.tpu.task.heartbeatInterval",
    "Seconds between worker heartbeat records on the result queue.",
    2.0, checker=_positive("heartbeat interval"))

TASK_HEARTBEAT_TIMEOUT = register_conf(
    "spark.rapids.tpu.task.heartbeatTimeout",
    "Seconds of heartbeat silence (measured only while the driver is "
    "actively waiting on a task) before a live-looking worker process is "
    "declared wedged, recycled, and its tasks resubmitted.",
    60.0, checker=_positive("heartbeat timeout"))


class TaskFailedError(RuntimeError):
    """A ProcessCluster task failed terminally: its worker(s) died and the
    task exhausted resubmission, or no live workers remain. Carries the
    forensics the old silent 300s hang threw away."""

    def __init__(self, message: str, *, task_id: Optional[int] = None,
                 worker: Optional[int] = None, attempts: int = 0,
                 history: Tuple[str, ...] = (),
                 fault: Optional[str] = None,
                 last_heartbeat_age_s: Optional[float] = None,
                 pending_tasks: Optional[int] = None,
                 exitcode: Optional[int] = None):
        super().__init__(message)
        self.task_id = task_id
        self.worker = worker
        self.attempts = attempts
        self.history = tuple(history)
        self.fault = fault
        self.last_heartbeat_age_s = last_heartbeat_age_s
        self.pending_tasks = pending_tasks
        self.exitcode = exitcode


class TaskTimeoutError(TaskFailedError):
    """The task.timeout deadline expired while a task was in flight."""


class DriverRuntime:
    """Driver-side control plane."""

    def __init__(self, conf: Optional[RapidsConf] = None,
                 heartbeat_timeout_s: float = 60.0):
        self.conf = conf or RapidsConf()
        self.detector = FailureDetector(heartbeat_timeout_s)
        self._ids = itertools.count()
        self._lock = threading.Lock()
        self.executors: Dict[int, ExecutorContext] = {}

    def register_executor(self, ctx: ExecutorContext) -> int:
        with self._lock:
            self.executors[ctx.executor_id] = ctx
        self.detector.heartbeat(ctx.executor_id)
        return ctx.executor_id

    def next_executor_id(self) -> int:
        return next(self._ids)

    def heartbeat(self, executor_id: int):
        self.detector.heartbeat(executor_id)

    def live_executors(self) -> List[int]:
        self.detector.check()
        return self.detector.live()


class LocalCluster:
    """N executors in-process sharing one transport; partitions of a
    DataFrame run round-robin across executors on worker threads."""

    def __init__(self, n_executors: int, conf: Optional[RapidsConf] = None,
                 device: bool = True):
        self.conf = conf or RapidsConf()
        self.device = device
        self.driver = DriverRuntime(self.conf)
        self.transport: ShuffleTransport = LocalShuffleTransport(self.conf)
        self.executors: List[ExecutorContext] = []
        for _ in range(n_executors):
            eid = self.driver.next_executor_id()
            ctx = ExecutorContext(eid, self.conf, transport=self.transport)
            ctx.initialize()
            self.driver.register_executor(ctx)
            self.executors.append(ctx)
        self._pool = ThreadPoolExecutor(max_workers=n_executors,
                                        thread_name_prefix="srtpu-exec")

    def __enter__(self) -> "LocalCluster":
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self):
        self._pool.shutdown(wait=True)
        for ctx in self.executors:
            ctx.shutdown()
        self.transport.close()

    # -- execution ------------------------------------------------------------
    def run(self, df) -> pa.Table:
        """Execute a DataFrame's physical plan with partitions spread across
        the executors (reference: one Spark task per partition, tasks pinned
        to an executor's GPU via GpuSemaphore)."""
        plan = df.session._physical(df.logical, device=self.device)
        n_parts = plan.num_partitions

        def run_partition(pidx: int) -> List[HostTable]:
            from ..utils.tracing import get_tracer
            ctx = self.executors[pidx % len(self.executors)]
            ctx.heartbeat()
            out: List[HostTable] = []
            with get_tracer().span("task", "task", partition=pidx,
                                   executor=ctx.executor_id):
                if self.device:
                    # the device plan root (DeviceToHostExec) downloads
                    # batches; the chip is held for the whole partition like
                    # a Spark task holds GpuSemaphore
                    with ctx.semaphore.held():
                        out.extend(plan.execute(pidx))
                else:
                    out.extend(plan.execute(pidx))
            return out

        futures = [self._pool.submit(run_partition, p) for p in range(n_parts)]
        tables: List[HostTable] = []
        for f in futures:
            tables.extend(f.result())
        if not tables:
            from ..columnar.host import HostColumn
            from ..plan.physical import _empty_values
            empty = HostTable(plan.schema.names,
                              [HostColumn(f.dtype, _empty_values(f.dtype))
                               for f in plan.schema])
            return empty.to_arrow()
        merged = HostTable.concat(tables)
        return merged.to_arrow()

    def map_executors(self, fn: Callable[[ExecutorContext], object]
                      ) -> List[object]:
        futures = [self._pool.submit(fn, ctx) for ctx in self.executors]
        return [f.result() for f in futures]


# ---------------------------------------------------------------------------
# Multi-process cluster: executors as OS processes over the TCP transport
# (reference: real Spark executors + RapidsShuffleServer/Client crossing
# process/host boundaries; LocalCluster above is the threads-only analogue of
# local-cluster mode)
# ---------------------------------------------------------------------------
def _worker_main(worker_id: int, conf_values: dict, addr_q, task_q, result_q):
    # never let a worker grab the TPU tunnel (it admits one process);
    # jax.config is the only channel the axon plugin respects
    import os
    import time

    import jax
    jax.config.update("jax_platforms", "cpu")
    from ..conf import RapidsConf
    from ..shuffle.tcp import TcpShuffleTransport
    from ..utils import faults as wfaults
    from ..utils.tracing import (TRACE_DISTRIBUTED_DIR, TraceContext,
                                 activate_trace_context, configure_tracer,
                                 get_tracer)
    from .executor import ExecutorContext

    conf = RapidsConf(conf_values)
    # per-worker seed offset decorrelates probabilistic chaos streams
    # across workers while keeping every process deterministic
    wfaults.configure_faults(conf, seed_offset=worker_id)
    tracer = configure_tracer(conf)
    tracer.process_name = f"worker-{worker_id}"
    transport = TcpShuffleTransport(conf)
    addr_q.put((worker_id, transport.address))

    # heartbeat publisher: the driver's FailureDetector distinguishes a
    # busy worker from a wedged one only through these records
    hb_stop = threading.Event()
    hb_interval = float(conf.get(TASK_HEARTBEAT_INTERVAL))

    def _heartbeat_loop():
        while not hb_stop.is_set():
            try:
                result_q.put((-1, "hb", (worker_id, time.time())))
            except Exception:  # queue torn down mid-shutdown
                return
            hb_stop.wait(hb_interval)

    threading.Thread(target=_heartbeat_loop, daemon=True,
                     name=f"srtpu-worker-hb-{worker_id}").start()
    ctx = None
    try:
        while True:
            task = task_q.get()
            if task is None:
                return
            tid, kind, payload, ctx_wire = task
            if kind == "peers":
                for host, port in payload:
                    transport.add_peer(host, port)
                ctx = ExecutorContext(worker_id, conf,
                                      transport=transport).initialize()
                result_q.put((tid, "ok", None))
                continue
            if kind == "addpeer":
                # a respawned worker announcing its replacement address;
                # the stale address stays in the peer list and simply
                # fails fast on the next fetch attempt
                host, port = payload
                transport.add_peer(host, port)
                result_q.put((tid, "ok", None))
                continue
            if kind == "clock":
                # clock handshake: the driver brackets this round trip and
                # estimates our wall-clock offset NTP-style from the reply
                result_q.put((tid, "ok",
                              (time.time(), tracer.epoch_unix)))
                continue
            fn, args = payload
            try:
                action = wfaults.fire("worker.task")
                if action == "kill":
                    # simulate abrupt worker loss, but first tell the
                    # driver which fault did it so TaskFailedError can
                    # name it; flush the queue feeder thread before the
                    # no-cleanup exit or the notice can be lost
                    result_q.put((tid, "dying",
                                  "injected fault 'worker.task' "
                                  "(action=kill)"))
                    result_q.close()
                    result_q.join_thread()
                    os._exit(13)
                tctx = TraceContext.from_wire(ctx_wire)
                with activate_trace_context(tctx), \
                        get_tracer().span("task", "task", worker=worker_id,
                                          fn=getattr(fn, "__name__", "?")):
                    if action is not None:
                        raise wfaults.FaultInjectedError("worker.task",
                                                         action)
                    out = fn(ctx, *args)
                result_q.put((tid, "ok", out))
            except Exception as e:  # surface to the driver, keep serving
                result_q.put((tid, "err", f"{type(e).__name__}: {e}"))
    finally:
        hb_stop.set()
        if ctx is not None:
            ctx.shutdown()
        transport.close()
        dump_dir = str(conf.get(TRACE_DISTRIBUTED_DIR))
        if dump_dir and tracer.enabled:
            tracer.dump(os.path.join(
                dump_dir, f"trace-{tracer.process_name}.json"))


class ProcessCluster:
    """N executor processes, each owning a TcpShuffleTransport server, all
    peered with each other. Task functions must be module-level (pickled by
    reference) and take the worker's ExecutorContext as first argument.

    Every task envelope carries the submitting thread's TraceContext
    (``spark.rapids.tpu.trace.distributed.enabled``), so worker-side spans
    parent under the driver's query span; a per-worker clock handshake at
    startup estimates each worker's wall-clock offset for the merged
    timeline (tools/trace.py)."""

    def __init__(self, n_executors: int, conf: Optional[dict] = None,
                 start_timeout_s: float = 120.0):
        import multiprocessing as mp

        from ..utils.tracing import TRACE_CLOCK_PROBES, TRACE_DISTRIBUTED
        self._mp = mp.get_context("spawn")
        self._addr_q = self._mp.Queue()
        self._result_q = self._mp.Queue()
        self._task_qs = [self._mp.Queue() for _ in range(n_executors)]
        self._conf_values = dict(conf or {})
        rconf = RapidsConf(self._conf_values)
        self._propagate = bool(rconf.get(TRACE_DISTRIBUTED))
        self._clock_probes = int(rconf.get(TRACE_CLOCK_PROBES))
        self._task_timeout = float(rconf.get(TASK_TIMEOUT))
        self._max_failures = int(rconf.get(TASK_MAX_FAILURES))
        self._respawn_enabled = bool(rconf.get(TASK_RESPAWN_WORKERS))
        self._max_respawns = int(rconf.get(TASK_MAX_WORKER_RESPAWNS))
        self._hb_timeout = float(rconf.get(TASK_HEARTBEAT_TIMEOUT))
        self._start_timeout = float(start_timeout_s)
        #: wedge detection over worker heartbeat records (reference:
        #: heartbeat-driven executor exclusion, Plugin.scala:149-161)
        self.detector = FailureDetector(self._hb_timeout)
        self._inflight: Dict[int, dict] = {}
        self._excluded: set = set()
        self._respawns: Dict[int, int] = {}
        self._last_hb: Dict[int, float] = {}
        self._closing = False
        self._recovering = False
        self.procs = [self._spawn_process(i) for i in range(n_executors)]
        for p in self.procs:
            p.start()
        addrs: Dict[int, tuple] = {}
        for _ in range(n_executors):
            wid, addr = self._addr_q.get(timeout=start_timeout_s)
            addrs[wid] = addr
        self.addresses = [addrs[i] for i in range(n_executors)]
        self._tids = itertools.count()
        self._done: Dict[int, tuple] = {}
        # peer everyone with everyone else
        for i in range(n_executors):
            peers = [a for j, a in enumerate(self.addresses) if j != i]
            self._wait(self._submit(i, "peers", peers))
        #: worker id -> estimated (worker_wall - driver_wall) seconds
        self.clock_offsets: Dict[int, float] = {
            i: self._estimate_clock_offset(i) for i in range(n_executors)}
        #: worker id -> the worker tracer's epoch_unix (merge anchor)
        self.worker_epochs: Dict[int, float] = dict(self._epochs)

    def _spawn_process(self, worker: int):
        return self._mp.Process(
            target=_worker_main,
            args=(worker, self._conf_values, self._addr_q,
                  self._task_qs[worker], self._result_q), daemon=True)

    def live_workers(self) -> List[int]:
        return [i for i, p in enumerate(self.procs)
                if i not in self._excluded and p.is_alive()]

    def _estimate_clock_offset(self, worker: int) -> float:
        """NTP-style offset estimate: bracket N clock round trips and keep
        the probe with the smallest RTT — queue latency inflates RTT
        symmetrically, so the tightest bracket bounds the offset best."""
        import time
        best_rtt, offset, epoch = float("inf"), 0.0, 0.0
        for _ in range(max(1, self._clock_probes)):
            t0 = time.time()
            t1, worker_epoch = self._wait(self._submit(worker, "clock", None))
            t2 = time.time()
            rtt = t2 - t0
            if rtt < best_rtt:
                best_rtt = rtt
                offset = t1 - (t0 + t2) / 2.0
                epoch = worker_epoch
        if not hasattr(self, "_epochs"):
            self._epochs: Dict[int, float] = {}
        self._epochs[worker] = epoch
        return offset

    def _submit(self, worker: int, kind: str, payload) -> int:
        from ..utils.tracing import current_trace_context
        tid = next(self._tids)
        ctx = current_trace_context() if self._propagate else None
        wire = None if ctx is None else ctx.to_wire()
        self._inflight[tid] = {"worker": worker, "kind": kind,
                               "payload": payload, "wire": wire,
                               "attempts": 1, "history": [], "fault": None}
        self._task_qs[worker].put((tid, kind, payload, wire))
        return tid

    def submit(self, worker: int, fn, *args) -> int:
        """Run ``fn(ctx, *args)`` on a worker; returns a task id."""
        return self._submit(worker, "call", (fn, args))

    def _wait(self, tid: int, timeout_s: Optional[float] = None):
        import queue as _queue
        import time
        budget = self._task_timeout if timeout_s is None else float(timeout_s)
        deadline = time.monotonic() + budget
        # baseline the detector: wedge detection measures heartbeat
        # silence during THIS wait — nobody drains the result queue while
        # the driver is idle, so stale stamps would be false positives
        for w in self.live_workers():
            self.detector.heartbeat(w)
        while tid not in self._done:
            try:
                got_tid, status, value = self._result_q.get(timeout=0.2)
            except _queue.Empty:
                self._check_workers()
                if time.monotonic() >= deadline:
                    self._raise_timeout(tid, budget)
                continue
            if status == "hb":
                wid, _ts = value
                self.detector.heartbeat(wid)
                self._last_hb[wid] = time.monotonic()
                continue
            if status == "dying":
                # a worker's last words before an injected kill: remember
                # the fault name for the task's forensics
                rec = self._inflight.get(got_tid)
                if rec is not None:
                    rec["fault"] = value
                continue
            if got_tid not in self._inflight:
                # stale duplicate: the task was already resubmitted after
                # its first worker died mid-answer, or already failed
                continue
            self._inflight.pop(got_tid, None)
            self._done[got_tid] = (status, value)
        status, value = self._done.pop(tid)
        if status == "failed":
            raise value
        if status == "err":
            raise RuntimeError(f"task {tid} failed on worker: {value}")
        return value

    def _raise_timeout(self, tid: int, budget: float):
        import time
        rec = self._inflight.pop(tid, None)
        faults.note_recovery("task_timeouts")
        worker = rec["worker"] if rec else None
        hb_age = None
        depth = None
        if worker is not None:
            last = self._last_hb.get(worker)
            hb_age = None if last is None else time.monotonic() - last
            try:
                depth = self._task_qs[worker].qsize()
            except (NotImplementedError, OSError):
                depth = None
        age_txt = "never seen" if hb_age is None else f"{hb_age:.1f}s ago"
        depth_txt = "?" if depth is None else str(depth)
        raise TaskTimeoutError(
            f"task {tid} timed out after {budget:.1f}s on worker {worker} "
            f"(last heartbeat {age_txt}, ~{depth_txt} pending tasks); "
            f"raise spark.rapids.tpu.task.timeout if the task is legitimately "
            f"slow",
            task_id=tid, worker=worker,
            attempts=rec["attempts"] if rec else 0,
            history=tuple(rec["history"]) if rec else (),
            fault=rec.get("fault") if rec else None,
            last_heartbeat_age_s=hb_age, pending_tasks=depth)

    # -- worker supervision ---------------------------------------------------
    def _check_workers(self):
        """Detect dead or wedged workers and run recovery. Called from
        inside _wait's poll loop; re-entrancy (recovery itself waits on
        control tasks) is cut off with the _recovering latch."""
        if self._closing or self._recovering:
            return
        self._recovering = True
        try:
            for i, p in enumerate(self.procs):
                if i in self._excluded:
                    continue
                if not p.is_alive():
                    self._on_worker_death(
                        i, f"worker {i} process exited "
                           f"(exitcode={p.exitcode})")
            for wid in self.detector.check():
                if wid in self._excluded or wid >= len(self.procs):
                    continue
                p = self.procs[wid]
                if p.is_alive():
                    # alive but silent past heartbeatTimeout: wedged
                    p.terminate()
                    p.join(timeout=10)
                    self._on_worker_death(
                        wid, f"worker {wid} wedged (no heartbeat for "
                             f"{self._hb_timeout:.0f}s)")
        finally:
            self._recovering = False

    def _on_worker_death(self, worker: int, reason: str,
                         allow_respawn: bool = True):
        faults.note_recovery("worker_deaths")
        orphans = [t for t, r in self._inflight.items()
                   if r["worker"] == worker]
        respawned = False
        if (allow_respawn and self._respawn_enabled and not self._closing
                and self._respawns.get(worker, 0) < self._max_respawns):
            try:
                self._respawn_worker(worker)
                respawned = True
                faults.note_recovery("worker_respawns")
            except Exception:
                respawned = False
        if not respawned:
            self._excluded.add(worker)
            faults.note_recovery("worker_exclusions")
        for t in orphans:
            self._resubmit_or_fail(t, reason)

    def _respawn_worker(self, worker: int):
        """Replace a dead worker with a fresh process on the same slot:
        fresh task queue (the old one may hold stale envelopes), new
        transport address announced to every surviving peer, clock offset
        re-estimated."""
        self._respawns[worker] = self._respawns.get(worker, 0) + 1
        old_q = self._task_qs[worker]
        self._task_qs[worker] = self._mp.Queue()
        p = self._spawn_process(worker)
        self.procs[worker] = p
        p.start()
        while True:
            wid, addr = self._addr_q.get(timeout=self._start_timeout)
            if wid == worker:
                break
        self.addresses[worker] = addr
        peers = [a for j, a in enumerate(self.addresses)
                 if j != worker and j not in self._excluded
                 and self.procs[j].is_alive()]
        self._wait(self._submit(worker, "peers", peers),
                   timeout_s=self._start_timeout)
        for j in self.live_workers():
            if j != worker:
                self._wait(self._submit(j, "addpeer", addr),
                           timeout_s=self._start_timeout)
        self.clock_offsets[worker] = self._estimate_clock_offset(worker)
        self.worker_epochs[worker] = self._epochs[worker]
        old_q.close()

    def _resubmit_or_fail(self, tid: int, reason: str):
        """Bounded task re-attempt after worker loss. Control tasks and
        exhausted tasks become terminal TaskFailedError results that the
        owning _wait raises."""
        rec = self._inflight.get(tid)
        if rec is None:
            return
        rec["history"].append(reason)
        live = self.live_workers()
        terminal = None
        if rec["kind"] != "call":
            terminal = "control task cannot be resubmitted"
        elif rec["attempts"] >= self._max_failures:
            terminal = (f"exhausted spark.rapids.tpu.task.maxFailures="
                        f"{self._max_failures}")
        elif not live:
            terminal = "no live workers remain"
        if terminal is not None:
            self._inflight.pop(tid, None)
            faults.note_recovery("task_failures")
            fault = rec.get("fault")
            msg = (f"task {tid} failed after {rec['attempts']} attempt(s): "
                   f"{terminal}; failures: {'; '.join(rec['history'])}")
            if fault:
                msg += f"; fault: {fault}"
            self._done[tid] = ("failed", TaskFailedError(
                msg, task_id=tid, worker=rec["worker"],
                attempts=rec["attempts"], history=tuple(rec["history"]),
                fault=fault))
            return
        rec["attempts"] += 1
        target = live[rec["attempts"] % len(live)]
        rec["worker"] = target
        faults.note_recovery("task_resubmissions")
        self._task_qs[target].put((tid, rec["kind"], rec["payload"], rec["wire"]))  # srtpu: trace-ok(resubmission replays the original envelope whose context was captured at _submit)

    def run_on(self, worker: int, fn, *args,
               timeout_s: Optional[float] = None):
        return self._wait(self.submit(worker, fn, *args), timeout_s)

    def run_tpch_query(self, query: str, sf: float = 0.01,
                       tiny: bool = True, num_partitions: int = 4,
                       timeout_s: Optional[float] = None) -> pa.Table:
        """Fan the partitions of one TPC-H query across the live workers
        and merge the results — the chaos-parity vehicle: a mid-query
        worker kill must yield exactly the sequential answer via
        supervision + resubmission."""
        from ..shuffle.serializer import deserialize_table
        live = self.live_workers()
        if not live:
            raise TaskFailedError("no live workers to plan the query on")
        n_parts = self.run_on(live[0], query_num_partitions_task, query,
                              sf, tiny, num_partitions, self._conf_values,
                              timeout_s=timeout_s)
        tids = []
        for pidx in range(n_parts):
            live = self.live_workers()
            if not live:
                raise TaskFailedError(
                    f"no live workers remain for partition {pidx}")
            w = live[pidx % len(live)]
            tids.append(self.submit(w, run_query_task, query, sf, tiny,
                                    num_partitions, pidx,
                                    self._conf_values))
        parts: List[HostTable] = []
        for tid in tids:
            payload = self._wait(tid, timeout_s)
            if payload is not None:
                parts.append(deserialize_table(payload))
        if not parts:
            return pa.table({})
        return HostTable.concat(parts).to_arrow()

    # -- distributed trace collection -----------------------------------------
    def collect_traces(self, drain: bool = False) -> List[dict]:
        """One Chrome-trace dict per process (driver first, then every
        live worker), each annotated with its clock-offset estimate —
        the input set for tools/trace.py merge_process_traces. With
        ``drain`` the worker rings are flushed (snapshot-and-reset), so
        per-query collection attributes ring drops to the right query."""
        from ..utils.tracing import get_tracer
        tracer = get_tracer()
        driver = tracer.drain() if drain else tracer.to_chrome_trace()
        driver["otherData"]["process_name"] = tracer.process_name
        driver["otherData"]["clock_offset_s"] = 0.0
        driver["otherData"]["role"] = "driver"
        traces = [driver]
        for w, p in enumerate(self.procs):
            if not p.is_alive():
                continue
            t = self.run_on(w, trace_flush_task, drain)
            t["otherData"]["clock_offset_s"] = self.clock_offsets.get(w, 0.0)
            t["otherData"]["role"] = f"worker-{w}"
            traces.append(t)
        return traces

    def dump_traces(self, directory: str, drain: bool = False) -> List[str]:
        """Write one trace-<process_name>.json per process into
        ``directory`` (the file set ``python -m spark_rapids_tpu.tools.trace
        merge <directory>`` consumes); returns the paths."""
        import json
        import os
        os.makedirs(directory, exist_ok=True)
        paths = []
        for t in self.collect_traces(drain=drain):
            name = t["otherData"].get("process_name", "unknown")
            path = os.path.join(directory, f"trace-{name}.json")
            with open(path, "w", encoding="utf-8") as f:
                json.dump(t, f)
            paths.append(path)
        return paths

    def kill(self, worker: int):
        """Hard-kill one executor process (deliberate failure injection).
        The slot is excluded — never respawned — and any of its in-flight
        tasks are resubmitted to surviving workers."""
        self.procs[worker].terminate()
        self.procs[worker].join(timeout=30)
        self._on_worker_death(worker, f"worker {worker} killed by driver",
                              allow_respawn=False)

    def close(self):
        self._closing = True
        for i, p in enumerate(self.procs):
            if p.is_alive():
                try:
                    self._task_qs[i].put(None)  # srtpu: trace-ok(shutdown sentinel, not a task envelope — no context to inject)
                except Exception:
                    pass  # srtpu: net-ok(a full queue or dead worker during shutdown is fine; terminate below is the backstop)
        for p in self.procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- reusable cross-process task functions (module-level => picklable) -------
def trace_flush_task(ctx: ExecutorContext, drain: bool = False) -> dict:
    """Export this worker's tracer ring as a Chrome-trace dict (with the
    process identity + wall-clock anchor in otherData). ``drain`` resets
    the ring so the NEXT flush starts clean — per-process drop counts then
    attribute to the window that overflowed."""
    from ..utils.tracing import get_tracer
    tracer = get_tracer()
    return tracer.drain() if drain else tracer.to_chrome_trace()


def metrics_text_task(ctx: ExecutorContext) -> str:
    """This worker's StatsRegistry as Prometheus text — the scrape body
    the driver's MetricsFederation (tools/statusd.py) pulls through the
    task queue (workers run no HTTP server; the queue IS the scrape
    transport)."""
    from ..utils.metrics import get_stats
    return get_stats().prometheus_text()


def trace_probe_task(ctx: ExecutorContext, depth: int = 0) -> Optional[dict]:
    """Record one probe span and report the TraceContext active inside it
    — the round-trip test for envelope propagation (None when no context
    arrived)."""
    from ..utils.tracing import current_trace_context, get_tracer
    with get_tracer().span("trace_probe", "task", depth=depth):
        ctx_now = current_trace_context()
        return None if ctx_now is None else ctx_now.to_wire()


def shuffle_write_task(ctx: ExecutorContext, shuffle_id: int, map_id: int,
                       payload: bytes, key_names: List[str],
                       num_parts: int) -> List[int]:
    from ..columnar.device import DeviceTable
    from ..shuffle.serializer import deserialize_table
    # srtpu: bucket-ok(cross-process wire protocol: payloads re-bucket at the tiny fixed floor so worker shard shapes never depend on the driver's session ladder)
    table = DeviceTable.from_host(deserialize_table(payload), min_bucket=8)
    return ctx.shuffle.write_partition(shuffle_id, map_id, iter([table]),
                                       key_names, num_parts)


def dcn_address_task(ctx: ExecutorContext) -> tuple:
    """Start (if needed) the worker's DCN-tier transport; -> (host, port)."""
    return ctx.dcn_transport().address


def dcn_add_peer_task(ctx: ExecutorContext, host: str, port: int) -> None:
    ctx.dcn_transport().add_peer(host, port)


def dcn_publish_task(ctx: ExecutorContext, shuffle_id: int, map_id: int,
                     reduce_id: int, payload: bytes) -> int:
    """Upload the payload table and publish it DEVICE-RESIDENT on this
    worker's DCN transport (serialization to the wire is lazy)."""
    from ..columnar.device import DeviceTable
    from ..shuffle.serializer import deserialize_table
    from ..shuffle.transport import BlockId
    # srtpu: bucket-ok(cross-process wire protocol: fixed floor keeps published block shapes driver-independent)
    table = DeviceTable.from_host(deserialize_table(payload), min_bucket=8)
    ctx.dcn_transport().publish_table(
        BlockId(shuffle_id, map_id, reduce_id), table)
    return int(table.num_rows)  # srtpu: sync-ok(cross-process DCN publish requires host bytes)


def dcn_fetch_task(ctx: ExecutorContext, shuffle_id: int, map_id: int,
                   reduce_id: int) -> bytes:
    """Fetch one block over the DCN tier; returns its serialized rows (for
    test verification — the table itself lands device-resident)."""
    from ..shuffle.serializer import serialize_table
    from ..shuffle.transport import BlockId
    blocks = dict(ctx.dcn_transport().fetch_tables(
        [BlockId(shuffle_id, map_id, reduce_id)]))
    table = blocks[BlockId(shuffle_id, map_id, reduce_id)]
    return serialize_table(table.to_host())


def shuffle_read_task(ctx: ExecutorContext, shuffle_id: int, num_maps: int,
                      reduce_id: int) -> Optional[bytes]:
    from ..shuffle.serializer import serialize_table
    # srtpu: bucket-ok(cross-process wire protocol: result is serialized back to exact rows, bucket only pads transient upload)
    out = list(ctx.shuffle.read_partition(shuffle_id, num_maps, reduce_id,
                                          min_bucket=8))
    if not out:
        return None
    return serialize_table(out[0].to_host())


def shuffle_read_recompute_task(ctx: ExecutorContext, shuffle_id: int,
                                num_maps: int, reduce_id: int,
                                map_payloads: Dict[int, bytes],
                                key_names: List[str],
                                num_parts: int) -> Optional[bytes]:
    """Read with a recompute hook: a fetch-failed map task is re-run locally
    from its input (the lineage-recompute analogue of Spark stage retry)."""
    def recompute(map_id: int):
        shuffle_write_task(ctx, shuffle_id, map_id, map_payloads[map_id],
                           key_names, num_parts)

    from ..shuffle.serializer import serialize_table
    # srtpu: bucket-ok(cross-process wire protocol: result is serialized back to exact rows, bucket only pads transient upload)
    out = list(ctx.shuffle.read_partition(shuffle_id, num_maps, reduce_id,
                                          min_bucket=8, recompute=recompute))
    if not out:
        return None
    return serialize_table(out[0].to_host())


def broadcast_build_task(ctx: ExecutorContext, bcast_id: int,
                         payload: bytes) -> Tuple[int, int]:
    """Designated-builder side of a cross-process broadcast (reference:
    the driver-side relationFuture, GpuBroadcastExchangeExec.scala:336)."""
    from ..columnar.device import DeviceTable
    from ..shuffle.serializer import deserialize_table

    def build():
        # srtpu: bucket-ok(cross-process wire protocol: broadcast build shape must match across workers regardless of session ladder)
        return DeviceTable.from_host(deserialize_table(payload),
                                     min_bucket=8)
    ctx.broadcast.build_and_publish(bcast_id, build)
    return ctx.broadcast.builds, ctx.broadcast.fetches


#: (query, sf, tiny, partitions, conf) -> (TpuSession, physical plan);
#: per-worker plan cache so every partition task reuses one build
_QUERY_PLANS: Dict[tuple, tuple] = {}


def _query_plan(query: str, sf: float, tiny: bool, num_partitions: int,
                conf_overrides: Optional[dict]):
    from ..session import TpuSession
    from ..tools import tpch
    key = (query, sf, tiny, num_partitions,
           tuple(sorted((conf_overrides or {}).items())))
    cached = _QUERY_PLANS.get(key)
    if cached is None:
        # a worker-side TpuSession re-runs configure_faults with the
        # plain conf seed — preserve this worker's seed-offset injector
        prev_injector = faults.active()
        sess = TpuSession(dict(conf_overrides or {}))
        faults.install(prev_injector)
        tables = tpch.gen_all(sf, tiny=tiny)
        dfs = tpch.build_dataframes(sess, tables,
                                    num_partitions=num_partitions)
        df = tpch.QUERIES[query](dfs)
        cached = (sess, sess._physical(df.logical, device=False))
        _QUERY_PLANS[key] = cached
    return cached


def query_num_partitions_task(ctx: ExecutorContext, query: str, sf: float,
                              tiny: bool, num_partitions: int,
                              conf_overrides: Optional[dict] = None) -> int:
    """Build (and cache) the query plan worker-side; -> its output
    partition count, which the driver fans run_query_task over."""
    _sess, plan = _query_plan(query, sf, tiny, num_partitions,
                              conf_overrides)
    return int(plan.num_partitions)


def run_query_task(ctx: ExecutorContext, query: str, sf: float, tiny: bool,
                   num_partitions: int, pidx: int,
                   conf_overrides: Optional[dict] = None
                   ) -> Optional[bytes]:
    """Execute one output partition of a TPC-H query inside the worker.
    Every worker regenerates the seeded TPC-H tables and materializes its
    own exchanges — duplicated work, but each partition's rows are exactly
    the sequential run's, which is what the chaos-parity tests pin."""
    from ..shuffle.serializer import serialize_table
    _sess, plan = _query_plan(query, sf, tiny, num_partitions,
                              conf_overrides)
    out = list(plan.execute(pidx))
    if not out:
        return None
    return serialize_table(HostTable.concat(out))


def broadcast_probe_task(ctx: ExecutorContext, bcast_id: int,
                         probe_payload: bytes, key: str
                         ) -> Tuple[bytes, int, int]:
    """Probe side: re-materialize the broadcast build table from the
    transport (never re-executing the build) and hash-join the local probe
    partition against it on ``key``."""
    import numpy as np

    from ..shuffle.serializer import deserialize_table, serialize_table
    build = ctx.broadcast.get(bcast_id).to_host()
    probe = deserialize_table(probe_payload)
    bk = np.sort(build.column(key).values)
    pk = probe.column(key).values
    if len(bk):
        pos = np.clip(np.searchsorted(bk, pk), 0, len(bk) - 1)
        hit = bk[pos] == pk
    else:
        hit = np.zeros(len(pk), dtype=bool)
    joined = probe.take(np.nonzero(hit)[0])
    return (serialize_table(joined), ctx.broadcast.builds,
            ctx.broadcast.fetches)
