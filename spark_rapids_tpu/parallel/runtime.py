"""Distributed runtime: driver control plane + local-cluster simulation.

Reference mapping:
- ``DriverRuntime``  ~ RapidsDriverPlugin (Plugin.scala:146-178): owns the
  heartbeat manager/failure detector, hands out executor ids, wires the
  shared transport.
- ``LocalCluster``   ~ Spark ``local-cluster[N, cores, mem]`` mode, the
  reference's no-real-cluster distribution test vehicle
  (integration_tests/README.md:66-86): N executor contexts in one process,
  each running its partitions on a worker thread, exchanging shuffle blocks
  through the shared transport. Device work is serialized per chip by each
  executor's TpuSemaphore (SURVEY §7 hard part (d)).

The GSPMD path (one jitted program over a Mesh, collectives over ICI) lives
in shuffle/ici.py + __graft_entry__.dryrun_multichip; this module is the
*task-parallel* path that mirrors the reference's executor model, used when
partitions outnumber chips or when running multi-host without a shared
program.
"""
from __future__ import annotations

import itertools
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

import pyarrow as pa

from ..columnar.host import HostTable
from ..conf import RapidsConf
from ..shuffle.transport import LocalShuffleTransport, ShuffleTransport
from .executor import ExecutorContext, FailureDetector

__all__ = ["DriverRuntime", "LocalCluster"]


class DriverRuntime:
    """Driver-side control plane."""

    def __init__(self, conf: Optional[RapidsConf] = None,
                 heartbeat_timeout_s: float = 60.0):
        self.conf = conf or RapidsConf()
        self.detector = FailureDetector(heartbeat_timeout_s)
        self._ids = itertools.count()
        self._lock = threading.Lock()
        self.executors: Dict[int, ExecutorContext] = {}

    def register_executor(self, ctx: ExecutorContext) -> int:
        with self._lock:
            self.executors[ctx.executor_id] = ctx
        self.detector.heartbeat(ctx.executor_id)
        return ctx.executor_id

    def next_executor_id(self) -> int:
        return next(self._ids)

    def heartbeat(self, executor_id: int):
        self.detector.heartbeat(executor_id)

    def live_executors(self) -> List[int]:
        self.detector.check()
        return self.detector.live()


class LocalCluster:
    """N executors in-process sharing one transport; partitions of a
    DataFrame run round-robin across executors on worker threads."""

    def __init__(self, n_executors: int, conf: Optional[RapidsConf] = None,
                 device: bool = True):
        self.conf = conf or RapidsConf()
        self.device = device
        self.driver = DriverRuntime(self.conf)
        self.transport: ShuffleTransport = LocalShuffleTransport(self.conf)
        self.executors: List[ExecutorContext] = []
        for _ in range(n_executors):
            eid = self.driver.next_executor_id()
            ctx = ExecutorContext(eid, self.conf, transport=self.transport)
            ctx.initialize()
            self.driver.register_executor(ctx)
            self.executors.append(ctx)
        self._pool = ThreadPoolExecutor(max_workers=n_executors,
                                        thread_name_prefix="srtpu-exec")

    def __enter__(self) -> "LocalCluster":
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self):
        self._pool.shutdown(wait=True)
        for ctx in self.executors:
            ctx.shutdown()
        self.transport.close()

    # -- execution ------------------------------------------------------------
    def run(self, df) -> pa.Table:
        """Execute a DataFrame's physical plan with partitions spread across
        the executors (reference: one Spark task per partition, tasks pinned
        to an executor's GPU via GpuSemaphore)."""
        plan = df.session._physical(df.logical, device=self.device)
        n_parts = plan.num_partitions

        def run_partition(pidx: int) -> List[HostTable]:
            from ..utils.tracing import get_tracer
            ctx = self.executors[pidx % len(self.executors)]
            ctx.heartbeat()
            out: List[HostTable] = []
            with get_tracer().span("task", "task", partition=pidx,
                                   executor=ctx.executor_id):
                if self.device:
                    # the device plan root (DeviceToHostExec) downloads
                    # batches; the chip is held for the whole partition like
                    # a Spark task holds GpuSemaphore
                    with ctx.semaphore.held():
                        out.extend(plan.execute(pidx))
                else:
                    out.extend(plan.execute(pidx))
            return out

        futures = [self._pool.submit(run_partition, p) for p in range(n_parts)]
        tables: List[HostTable] = []
        for f in futures:
            tables.extend(f.result())
        if not tables:
            from ..columnar.host import HostColumn
            from ..plan.physical import _empty_values
            empty = HostTable(plan.schema.names,
                              [HostColumn(f.dtype, _empty_values(f.dtype))
                               for f in plan.schema])
            return empty.to_arrow()
        merged = HostTable.concat(tables)
        return merged.to_arrow()

    def map_executors(self, fn: Callable[[ExecutorContext], object]
                      ) -> List[object]:
        futures = [self._pool.submit(fn, ctx) for ctx in self.executors]
        return [f.result() for f in futures]


# ---------------------------------------------------------------------------
# Multi-process cluster: executors as OS processes over the TCP transport
# (reference: real Spark executors + RapidsShuffleServer/Client crossing
# process/host boundaries; LocalCluster above is the threads-only analogue of
# local-cluster mode)
# ---------------------------------------------------------------------------
def _worker_main(worker_id: int, conf_values: dict, addr_q, task_q, result_q):
    # never let a worker grab the TPU tunnel (it admits one process);
    # jax.config is the only channel the axon plugin respects
    import time

    import jax
    jax.config.update("jax_platforms", "cpu")
    from ..conf import RapidsConf
    from ..shuffle.tcp import TcpShuffleTransport
    from ..utils.tracing import (TRACE_DISTRIBUTED_DIR, TraceContext,
                                 activate_trace_context, configure_tracer,
                                 get_tracer)
    from .executor import ExecutorContext

    conf = RapidsConf(conf_values)
    tracer = configure_tracer(conf)
    tracer.process_name = f"worker-{worker_id}"
    transport = TcpShuffleTransport(conf)
    addr_q.put((worker_id, transport.address))
    ctx = None
    try:
        while True:
            task = task_q.get()
            if task is None:
                return
            tid, kind, payload, ctx_wire = task
            if kind == "peers":
                for host, port in payload:
                    transport.add_peer(host, port)
                ctx = ExecutorContext(worker_id, conf,
                                      transport=transport).initialize()
                result_q.put((tid, "ok", None))
                continue
            if kind == "clock":
                # clock handshake: the driver brackets this round trip and
                # estimates our wall-clock offset NTP-style from the reply
                result_q.put((tid, "ok",
                              (time.time(), tracer.epoch_unix)))
                continue
            fn, args = payload
            try:
                tctx = TraceContext.from_wire(ctx_wire)
                with activate_trace_context(tctx), \
                        get_tracer().span("task", "task", worker=worker_id,
                                          fn=getattr(fn, "__name__", "?")):
                    out = fn(ctx, *args)
                result_q.put((tid, "ok", out))
            except Exception as e:  # surface to the driver, keep serving
                result_q.put((tid, "err", f"{type(e).__name__}: {e}"))
    finally:
        if ctx is not None:
            ctx.shutdown()
        transport.close()
        dump_dir = str(conf.get(TRACE_DISTRIBUTED_DIR))
        if dump_dir and tracer.enabled:
            import os
            tracer.dump(os.path.join(
                dump_dir, f"trace-{tracer.process_name}.json"))


class ProcessCluster:
    """N executor processes, each owning a TcpShuffleTransport server, all
    peered with each other. Task functions must be module-level (pickled by
    reference) and take the worker's ExecutorContext as first argument.

    Every task envelope carries the submitting thread's TraceContext
    (``spark.rapids.tpu.trace.distributed.enabled``), so worker-side spans
    parent under the driver's query span; a per-worker clock handshake at
    startup estimates each worker's wall-clock offset for the merged
    timeline (tools/trace.py)."""

    def __init__(self, n_executors: int, conf: Optional[dict] = None,
                 start_timeout_s: float = 120.0):
        import multiprocessing as mp

        from ..utils.tracing import TRACE_CLOCK_PROBES, TRACE_DISTRIBUTED
        self._mp = mp.get_context("spawn")
        self._addr_q = self._mp.Queue()
        self._result_q = self._mp.Queue()
        self._task_qs = [self._mp.Queue() for _ in range(n_executors)]
        rconf = RapidsConf(conf or {})
        self._propagate = bool(rconf.get(TRACE_DISTRIBUTED))
        self._clock_probes = int(rconf.get(TRACE_CLOCK_PROBES))
        self.procs = [
            self._mp.Process(
                target=_worker_main,
                args=(i, conf or {}, self._addr_q, self._task_qs[i],
                      self._result_q), daemon=True)
            for i in range(n_executors)]
        for p in self.procs:
            p.start()
        addrs: Dict[int, tuple] = {}
        for _ in range(n_executors):
            wid, addr = self._addr_q.get(timeout=start_timeout_s)
            addrs[wid] = addr
        self.addresses = [addrs[i] for i in range(n_executors)]
        self._tids = itertools.count()
        self._done: Dict[int, tuple] = {}
        # peer everyone with everyone else (reference: heartbeat-driven
        # executor discovery, Plugin.scala:149-161)
        for i in range(n_executors):
            peers = [a for j, a in enumerate(self.addresses) if j != i]
            self._wait(self._submit(i, "peers", peers))
        #: worker id -> estimated (worker_wall - driver_wall) seconds
        self.clock_offsets: Dict[int, float] = {
            i: self._estimate_clock_offset(i) for i in range(n_executors)}
        #: worker id -> the worker tracer's epoch_unix (merge anchor)
        self.worker_epochs: Dict[int, float] = dict(self._epochs)

    def _estimate_clock_offset(self, worker: int) -> float:
        """NTP-style offset estimate: bracket N clock round trips and keep
        the probe with the smallest RTT — queue latency inflates RTT
        symmetrically, so the tightest bracket bounds the offset best."""
        import time
        best_rtt, offset, epoch = float("inf"), 0.0, 0.0
        for _ in range(max(1, self._clock_probes)):
            t0 = time.time()
            t1, worker_epoch = self._wait(self._submit(worker, "clock", None))
            t2 = time.time()
            rtt = t2 - t0
            if rtt < best_rtt:
                best_rtt = rtt
                offset = t1 - (t0 + t2) / 2.0
                epoch = worker_epoch
        if not hasattr(self, "_epochs"):
            self._epochs: Dict[int, float] = {}
        self._epochs[worker] = epoch
        return offset

    def _submit(self, worker: int, kind: str, payload) -> int:
        from ..utils.tracing import current_trace_context
        tid = next(self._tids)
        ctx = current_trace_context() if self._propagate else None
        self._task_qs[worker].put(
            (tid, kind, payload, None if ctx is None else ctx.to_wire()))
        return tid

    def submit(self, worker: int, fn, *args) -> int:
        """Run ``fn(ctx, *args)`` on a worker; returns a task id."""
        return self._submit(worker, "call", (fn, args))

    def _wait(self, tid: int, timeout_s: float = 300.0):
        while tid not in self._done:
            got_tid, status, value = self._result_q.get(timeout=timeout_s)
            self._done[got_tid] = (status, value)
        status, value = self._done.pop(tid)
        if status == "err":
            raise RuntimeError(f"task {tid} failed on worker: {value}")
        return value

    def run_on(self, worker: int, fn, *args, timeout_s: float = 300.0):
        return self._wait(self.submit(worker, fn, *args), timeout_s)

    # -- distributed trace collection -----------------------------------------
    def collect_traces(self, drain: bool = False) -> List[dict]:
        """One Chrome-trace dict per process (driver first, then every
        live worker), each annotated with its clock-offset estimate —
        the input set for tools/trace.py merge_process_traces. With
        ``drain`` the worker rings are flushed (snapshot-and-reset), so
        per-query collection attributes ring drops to the right query."""
        from ..utils.tracing import get_tracer
        tracer = get_tracer()
        driver = tracer.drain() if drain else tracer.to_chrome_trace()
        driver["otherData"]["process_name"] = tracer.process_name
        driver["otherData"]["clock_offset_s"] = 0.0
        driver["otherData"]["role"] = "driver"
        traces = [driver]
        for w, p in enumerate(self.procs):
            if not p.is_alive():
                continue
            t = self.run_on(w, trace_flush_task, drain)
            t["otherData"]["clock_offset_s"] = self.clock_offsets.get(w, 0.0)
            t["otherData"]["role"] = f"worker-{w}"
            traces.append(t)
        return traces

    def dump_traces(self, directory: str, drain: bool = False) -> List[str]:
        """Write one trace-<process_name>.json per process into
        ``directory`` (the file set ``python -m spark_rapids_tpu.tools.trace
        merge <directory>`` consumes); returns the paths."""
        import json
        import os
        os.makedirs(directory, exist_ok=True)
        paths = []
        for t in self.collect_traces(drain=drain):
            name = t["otherData"].get("process_name", "unknown")
            path = os.path.join(directory, f"trace-{name}.json")
            with open(path, "w", encoding="utf-8") as f:
                json.dump(t, f)
            paths.append(path)
        return paths

    def kill(self, worker: int):
        """Hard-kill one executor process (failure injection)."""
        self.procs[worker].terminate()
        self.procs[worker].join(timeout=30)

    def close(self):
        for i, p in enumerate(self.procs):
            if p.is_alive():
                try:
                    self._task_qs[i].put(None)  # srtpu: trace-ok(shutdown sentinel, not a task envelope — no context to inject)
                except Exception:
                    pass
        for p in self.procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- reusable cross-process task functions (module-level => picklable) -------
def trace_flush_task(ctx: ExecutorContext, drain: bool = False) -> dict:
    """Export this worker's tracer ring as a Chrome-trace dict (with the
    process identity + wall-clock anchor in otherData). ``drain`` resets
    the ring so the NEXT flush starts clean — per-process drop counts then
    attribute to the window that overflowed."""
    from ..utils.tracing import get_tracer
    tracer = get_tracer()
    return tracer.drain() if drain else tracer.to_chrome_trace()


def metrics_text_task(ctx: ExecutorContext) -> str:
    """This worker's StatsRegistry as Prometheus text — the scrape body
    the driver's MetricsFederation (tools/statusd.py) pulls through the
    task queue (workers run no HTTP server; the queue IS the scrape
    transport)."""
    from ..utils.metrics import get_stats
    return get_stats().prometheus_text()


def trace_probe_task(ctx: ExecutorContext, depth: int = 0) -> Optional[dict]:
    """Record one probe span and report the TraceContext active inside it
    — the round-trip test for envelope propagation (None when no context
    arrived)."""
    from ..utils.tracing import current_trace_context, get_tracer
    with get_tracer().span("trace_probe", "task", depth=depth):
        ctx_now = current_trace_context()
        return None if ctx_now is None else ctx_now.to_wire()


def shuffle_write_task(ctx: ExecutorContext, shuffle_id: int, map_id: int,
                       payload: bytes, key_names: List[str],
                       num_parts: int) -> List[int]:
    from ..columnar.device import DeviceTable
    from ..shuffle.serializer import deserialize_table
    # srtpu: bucket-ok(cross-process wire protocol: payloads re-bucket at the tiny fixed floor so worker shard shapes never depend on the driver's session ladder)
    table = DeviceTable.from_host(deserialize_table(payload), min_bucket=8)
    return ctx.shuffle.write_partition(shuffle_id, map_id, iter([table]),
                                       key_names, num_parts)


def dcn_address_task(ctx: ExecutorContext) -> tuple:
    """Start (if needed) the worker's DCN-tier transport; -> (host, port)."""
    return ctx.dcn_transport().address


def dcn_add_peer_task(ctx: ExecutorContext, host: str, port: int) -> None:
    ctx.dcn_transport().add_peer(host, port)


def dcn_publish_task(ctx: ExecutorContext, shuffle_id: int, map_id: int,
                     reduce_id: int, payload: bytes) -> int:
    """Upload the payload table and publish it DEVICE-RESIDENT on this
    worker's DCN transport (serialization to the wire is lazy)."""
    from ..columnar.device import DeviceTable
    from ..shuffle.serializer import deserialize_table
    from ..shuffle.transport import BlockId
    # srtpu: bucket-ok(cross-process wire protocol: fixed floor keeps published block shapes driver-independent)
    table = DeviceTable.from_host(deserialize_table(payload), min_bucket=8)
    ctx.dcn_transport().publish_table(
        BlockId(shuffle_id, map_id, reduce_id), table)
    return int(table.num_rows)  # srtpu: sync-ok(cross-process DCN publish requires host bytes)


def dcn_fetch_task(ctx: ExecutorContext, shuffle_id: int, map_id: int,
                   reduce_id: int) -> bytes:
    """Fetch one block over the DCN tier; returns its serialized rows (for
    test verification — the table itself lands device-resident)."""
    from ..shuffle.serializer import serialize_table
    from ..shuffle.transport import BlockId
    blocks = dict(ctx.dcn_transport().fetch_tables(
        [BlockId(shuffle_id, map_id, reduce_id)]))
    table = blocks[BlockId(shuffle_id, map_id, reduce_id)]
    return serialize_table(table.to_host())


def shuffle_read_task(ctx: ExecutorContext, shuffle_id: int, num_maps: int,
                      reduce_id: int) -> Optional[bytes]:
    from ..shuffle.serializer import serialize_table
    # srtpu: bucket-ok(cross-process wire protocol: result is serialized back to exact rows, bucket only pads transient upload)
    out = list(ctx.shuffle.read_partition(shuffle_id, num_maps, reduce_id,
                                          min_bucket=8))
    if not out:
        return None
    return serialize_table(out[0].to_host())


def shuffle_read_recompute_task(ctx: ExecutorContext, shuffle_id: int,
                                num_maps: int, reduce_id: int,
                                map_payloads: Dict[int, bytes],
                                key_names: List[str],
                                num_parts: int) -> Optional[bytes]:
    """Read with a recompute hook: a fetch-failed map task is re-run locally
    from its input (the lineage-recompute analogue of Spark stage retry)."""
    def recompute(map_id: int):
        shuffle_write_task(ctx, shuffle_id, map_id, map_payloads[map_id],
                           key_names, num_parts)

    from ..shuffle.serializer import serialize_table
    # srtpu: bucket-ok(cross-process wire protocol: result is serialized back to exact rows, bucket only pads transient upload)
    out = list(ctx.shuffle.read_partition(shuffle_id, num_maps, reduce_id,
                                          min_bucket=8, recompute=recompute))
    if not out:
        return None
    return serialize_table(out[0].to_host())


def broadcast_build_task(ctx: ExecutorContext, bcast_id: int,
                         payload: bytes) -> Tuple[int, int]:
    """Designated-builder side of a cross-process broadcast (reference:
    the driver-side relationFuture, GpuBroadcastExchangeExec.scala:336)."""
    from ..columnar.device import DeviceTable
    from ..shuffle.serializer import deserialize_table

    def build():
        # srtpu: bucket-ok(cross-process wire protocol: broadcast build shape must match across workers regardless of session ladder)
        return DeviceTable.from_host(deserialize_table(payload),
                                     min_bucket=8)
    ctx.broadcast.build_and_publish(bcast_id, build)
    return ctx.broadcast.builds, ctx.broadcast.fetches


def broadcast_probe_task(ctx: ExecutorContext, bcast_id: int,
                         probe_payload: bytes, key: str
                         ) -> Tuple[bytes, int, int]:
    """Probe side: re-materialize the broadcast build table from the
    transport (never re-executing the build) and hash-join the local probe
    partition against it on ``key``."""
    import numpy as np

    from ..shuffle.serializer import deserialize_table, serialize_table
    build = ctx.broadcast.get(bcast_id).to_host()
    probe = deserialize_table(probe_payload)
    bk = np.sort(build.column(key).values)
    pk = probe.column(key).values
    if len(bk):
        pos = np.clip(np.searchsorted(bk, pk), 0, len(bk) - 1)
        hit = bk[pos] == pk
    else:
        hit = np.zeros(len(pk), dtype=bool)
    joined = probe.take(np.nonzero(hit)[0])
    return (serialize_table(joined), ctx.broadcast.builds,
            ctx.broadcast.fetches)
