"""Distributed runtime: driver control plane + local-cluster simulation.

Reference mapping:
- ``DriverRuntime``  ~ RapidsDriverPlugin (Plugin.scala:146-178): owns the
  heartbeat manager/failure detector, hands out executor ids, wires the
  shared transport.
- ``LocalCluster``   ~ Spark ``local-cluster[N, cores, mem]`` mode, the
  reference's no-real-cluster distribution test vehicle
  (integration_tests/README.md:66-86): N executor contexts in one process,
  each running its partitions on a worker thread, exchanging shuffle blocks
  through the shared transport. Device work is serialized per chip by each
  executor's TpuSemaphore (SURVEY §7 hard part (d)).

The GSPMD path (one jitted program over a Mesh, collectives over ICI) lives
in shuffle/ici.py + __graft_entry__.dryrun_multichip; this module is the
*task-parallel* path that mirrors the reference's executor model, used when
partitions outnumber chips or when running multi-host without a shared
program.
"""
from __future__ import annotations

import itertools
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional

import pyarrow as pa

from ..columnar.host import HostTable
from ..conf import RapidsConf
from ..shuffle.transport import LocalShuffleTransport, ShuffleTransport
from .executor import ExecutorContext, FailureDetector

__all__ = ["DriverRuntime", "LocalCluster"]


class DriverRuntime:
    """Driver-side control plane."""

    def __init__(self, conf: Optional[RapidsConf] = None,
                 heartbeat_timeout_s: float = 60.0):
        self.conf = conf or RapidsConf()
        self.detector = FailureDetector(heartbeat_timeout_s)
        self._ids = itertools.count()
        self._lock = threading.Lock()
        self.executors: Dict[int, ExecutorContext] = {}

    def register_executor(self, ctx: ExecutorContext) -> int:
        with self._lock:
            self.executors[ctx.executor_id] = ctx
        self.detector.heartbeat(ctx.executor_id)
        return ctx.executor_id

    def next_executor_id(self) -> int:
        return next(self._ids)

    def heartbeat(self, executor_id: int):
        self.detector.heartbeat(executor_id)

    def live_executors(self) -> List[int]:
        self.detector.check()
        return self.detector.live()


class LocalCluster:
    """N executors in-process sharing one transport; partitions of a
    DataFrame run round-robin across executors on worker threads."""

    def __init__(self, n_executors: int, conf: Optional[RapidsConf] = None,
                 device: bool = True):
        self.conf = conf or RapidsConf()
        self.device = device
        self.driver = DriverRuntime(self.conf)
        self.transport: ShuffleTransport = LocalShuffleTransport(self.conf)
        self.executors: List[ExecutorContext] = []
        for _ in range(n_executors):
            eid = self.driver.next_executor_id()
            ctx = ExecutorContext(eid, self.conf, transport=self.transport)
            ctx.initialize()
            self.driver.register_executor(ctx)
            self.executors.append(ctx)
        self._pool = ThreadPoolExecutor(max_workers=n_executors,
                                        thread_name_prefix="srtpu-exec")

    def __enter__(self) -> "LocalCluster":
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self):
        self._pool.shutdown(wait=True)
        for ctx in self.executors:
            ctx.shutdown()
        self.transport.close()

    # -- execution ------------------------------------------------------------
    def run(self, df) -> pa.Table:
        """Execute a DataFrame's physical plan with partitions spread across
        the executors (reference: one Spark task per partition, tasks pinned
        to an executor's GPU via GpuSemaphore)."""
        plan = df.session._physical(df.logical, device=self.device)
        n_parts = plan.num_partitions

        def run_partition(pidx: int) -> List[HostTable]:
            ctx = self.executors[pidx % len(self.executors)]
            ctx.heartbeat()
            out: List[HostTable] = []
            if self.device:
                # the device plan root (DeviceToHostExec) downloads batches;
                # the chip is held for the whole partition like a Spark task
                # holds GpuSemaphore
                with ctx.semaphore.held():
                    out.extend(plan.execute(pidx))
            else:
                out.extend(plan.execute(pidx))
            return out

        futures = [self._pool.submit(run_partition, p) for p in range(n_parts)]
        tables: List[HostTable] = []
        for f in futures:
            tables.extend(f.result())
        if not tables:
            from ..columnar.host import HostColumn
            from ..plan.physical import _empty_values
            empty = HostTable(plan.schema.names,
                              [HostColumn(f.dtype, _empty_values(f.dtype))
                               for f in plan.schema])
            return empty.to_arrow()
        merged = HostTable.concat(tables)
        return merged.to_arrow()

    def map_executors(self, fn: Callable[[ExecutorContext], object]
                      ) -> List[object]:
        futures = [self._pool.submit(fn, ctx) for ctx in self.executors]
        return [f.result() for f in futures]
