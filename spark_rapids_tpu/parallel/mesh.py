"""Device mesh / topology management.

Reference mapping: the reference binds one GPU per executor from Spark's
resource scheduler (GpuDeviceManager.scala:124-139) and discovers peers via
the shuffle heartbeat control plane (Plugin.scala:149-161). On TPU the
topology is richer: chips within a slice are connected by ICI (fast, used for
all-to-all/all-gather), slices/hosts by DCN. This module owns constructing
``jax.sharding.Mesh`` objects for the execution patterns the engine uses:

- ``data_parallel_mesh``: 1-D ``(dp,)`` — partitions-as-shards, the analogue
  of Spark tasks across executors (SURVEY §2.7 parallelism census).
- ``grid_mesh``: 2-D ``(dp, ici)`` — batch rows over hosts/DCN, intra-batch
  exchange over ICI (hash shuffles ride the fast axis).
- ``virtual_cpu_mesh``: N-device CPU mesh for tests / the driver's
  ``dryrun_multichip`` (xla_force_host_platform_device_count).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["MeshTopology", "data_parallel_mesh", "grid_mesh",
           "virtual_cpu_mesh", "describe_devices"]


@dataclasses.dataclass(frozen=True)
class MeshTopology:
    """Physical layout summary used to pick mesh shapes.

    ``process_index``/``process_count`` describe the multi-host dimension
    (DCN); ``local_devices`` the per-host chips (ICI-connected within a
    slice)."""
    process_index: int
    process_count: int
    n_devices: int
    n_local: int
    platform: str

    @staticmethod
    def detect() -> "MeshTopology":
        devs = jax.devices()
        return MeshTopology(
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            n_devices=len(devs),
            n_local=len(jax.local_devices()),
            platform=devs[0].platform if devs else "none",
        )

    @property
    def multi_host(self) -> bool:
        return self.process_count > 1


def describe_devices() -> List[dict]:
    out = []
    for d in jax.devices():
        out.append({
            "id": d.id,
            "platform": d.platform,
            "process_index": d.process_index,
            "kind": getattr(d, "device_kind", "unknown"),
        })
    return out


def data_parallel_mesh(n: Optional[int] = None, axis: str = "dp") -> Mesh:
    """1-D mesh over the first ``n`` addressable devices."""
    devs = jax.devices()
    if n is not None:
        if n > len(devs):
            raise ValueError(f"need {n} devices, have {len(devs)}")
        devs = devs[:n]
    return Mesh(np.array(devs), (axis,))


def grid_mesh(dp: int, ici: int, axes: Sequence[str] = ("dp", "ici")) -> Mesh:
    """2-D mesh: ``dp`` (slow/DCN-ish) × ``ici`` (fast axis). Devices are
    laid out so the ``ici`` axis maps to consecutive device ids — on real
    TPU topologies consecutive ids are ICI neighbors within a slice, so
    collectives over that axis stay off DCN (SURVEY §2.7 TPU mapping)."""
    devs = jax.devices()
    if dp * ici > len(devs):
        raise ValueError(f"need {dp * ici} devices, have {len(devs)}")
    arr = np.array(devs[:dp * ici]).reshape(dp, ici)
    return Mesh(arr, tuple(axes))


def virtual_cpu_mesh(n: int, axis: str = "dp") -> Mesh:
    """CPU test mesh; requires xla_force_host_platform_device_count >= n
    (tests/conftest.py sets 8)."""
    devs = [d for d in jax.devices() if d.platform == "cpu"]
    if len(devs) < n:
        raise ValueError(
            f"need {n} cpu devices, have {len(devs)} — set "
            "XLA_FLAGS=--xla_force_host_platform_device_count")
    return Mesh(np.array(devs[:n]), (axis,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def row_sharded(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(axis))
