"""Pipelined multi-partition execution engine.

The reference plugin gets its throughput from running many Spark tasks
concurrently against one device, gated by ``GpuSemaphore``, so host-side
decode/serialization overlaps device kernels (Plugin.scala +
GpuSemaphore.scala). The sequential port executed partitions one at a time
through synchronous iterators, leaving the TPU idle during every host
decode, H2D upload and shuffle write. This module supplies the two
overlap mechanisms:

- ``pipelined_collect(plan, conf)``: drains multiple partitions
  concurrently from a bounded task pool, each task holding the
  ``TpuSemaphore`` while it drives device work (the ExecutorContext /
  concurrent-GPU-tasks analogue). Host-side stages of one partition
  overlap device stages of another.
- ``prefetched(make_iter, ...)``: stage-decouples an iterator chain with a
  SMALL BOUNDED queue fed by a background worker, so host decode/IO,
  ``HostToDeviceExec`` upload, jitted compute (riding JAX async dispatch)
  and downloads/shuffle writes run double-buffered within one partition.
  Exec nodes opt in at their stage boundaries (exec/transitions.py,
  exec/wholestage.py, exec/exchange.py).

Design rules:

- Every queue is BOUNDED (``prefetchDepth``); an unbounded queue would
  re-materialize whole partitions in memory and is rejected by the tier-1
  lint test (tests/test_pipeline.py).
- Failure propagation: a worker exception crosses the queue as a poison
  pill carrying the originating stage context, the queues drain, and the
  ORIGINAL exception re-raises in the consumer — an error must surface,
  never hang.
- The input-file holder (io/file_block.py) is thread-local; each queue
  item carries the producer's holder state and the consumer restores it
  before yielding, so ``input_file_name()`` attribution survives the
  thread hop.
- ``pipelineWait`` (seconds the consumer blocked on an empty queue) and
  ``prefetchQueueDepth`` (occupancy histogram) are accounted on the
  consuming node's ``MetricRegistry`` and mirrored as ``pipeline`` trace
  spans, so ``tools/diagnose.py`` can rank pipeline stalls.

Sequential mode (``spark.rapids.tpu.pipeline.enabled=false``) keeps the
old synchronous behavior and is the correctness oracle.
"""
from __future__ import annotations

import queue
import threading
import time
from contextlib import contextmanager
from typing import Callable, Iterator, List, Optional, Sequence, TypeVar

from ..conf import register_conf

__all__ = ["PIPELINE_ENABLED", "PIPELINE_PREFETCH_DEPTH",
           "PIPELINE_TASK_POOL", "configure_pipeline", "pipeline_enabled",
           "prefetch_depth", "task_pool_size", "prefetched",
           "maybe_prefetched", "pipelined_collect", "parallel_map",
           "active_workers", "shutdown_workers", "pipeline_stats",
           "pipeline_snapshot", "note_progress", "stage_name"]


def stage_name(node) -> str:
    """Display name of a plan node for span/metric labels (tolerates test
    stubs without the PhysicalPlan surface)."""
    fn = getattr(node, "node_name", None)
    try:
        return fn() if callable(fn) else type(node).__name__
    except Exception:
        return type(node).__name__


# ---------------------------------------------------------------------------
# semaphore exemption for pipeline worker threads.
#
# Admission is TASK-scoped: the partition's task thread holds the
# TpuSemaphore; the prefetch/map workers it spawns run UNDER that
# admission. A worker must therefore never acquire a permit of its own —
# with concurrentGpuTasks=1 a task blocked on its own worker while the
# worker blocks acquiring the permit the task holds is a deadlock
# (observed with the python-UDF exec's release-reacquire pattern,
# udf/python_exec.py). TpuSemaphore.acquire_if_necessary consults
# ``semaphore_exempt()``; ``pipelined_collect`` clears the flag in its
# drain (the pool thread IS the task there and must take admission).
# ---------------------------------------------------------------------------
_WORKER_TLS = threading.local()


def semaphore_exempt() -> bool:
    """True on pipeline worker threads — device admission was already
    granted to the owning task (memory/semaphore.py consults this)."""
    return getattr(_WORKER_TLS, "exempt", False)


@contextmanager
def _worker_scope():
    prev = getattr(_WORKER_TLS, "exempt", False)
    _WORKER_TLS.exempt = True
    try:
        yield
    finally:
        _WORKER_TLS.exempt = prev


#: public name for the same scope, used by nodes whose SHARED materialize
#: lock may be held while operators (python-UDF exec) release/reacquire
#: the semaphore. Invariant: a thread must never BLOCK on the TpuSemaphore
#: while holding a materialize lock another admitted task may want —
#: permit-holder A (in the lock, reacquiring) and lock-waiter B (holding
#: the permit) would deadlock at concurrentGpuTasks=1. Inside this scope
#: acquires no-op; admission is advisory there.
exempt_admission = _worker_scope


@contextmanager
def task_admission():
    """The inverse scope: this thread is a TASK and takes real admission
    (used by pipelined_collect's drains and the write path's map tasks —
    anything that is a top-level unit of device work, not a stage worker
    under an already-admitted task)."""
    prev = getattr(_WORKER_TLS, "exempt", False)
    _WORKER_TLS.exempt = False
    try:
        yield
    finally:
        _WORKER_TLS.exempt = prev


_task_admission = task_admission  # internal alias

PIPELINE_ENABLED = register_conf(
    "spark.rapids.tpu.pipeline.enabled",
    "Overlap host decode, host->device upload, XLA compute and "
    "shuffle/download work: partitions drain concurrently from a bounded "
    "task pool under TpuSemaphore admission, and stage boundaries inside a "
    "partition hand batches through small bounded prefetch queues "
    "(reference: concurrent Spark tasks gated by GpuSemaphore, "
    "Plugin.scala + GpuSemaphore.scala). 'false' restores strictly "
    "sequential execution (the correctness oracle).", True)

PIPELINE_PREFETCH_DEPTH = register_conf(
    "spark.rapids.tpu.pipeline.prefetchDepth",
    "Bound of each inter-stage prefetch queue, in batches. 2 double-"
    "buffers every stage boundary; larger values absorb burstier stages "
    "at the cost of more resident batches.", 2,
    checker=lambda v: None if int(v) > 0 else "must be positive")

PIPELINE_TASK_POOL = register_conf(
    "spark.rapids.tpu.pipeline.taskPool",
    "Maximum partitions drained concurrently by the pipelined executor "
    "(the Spark-task-parallelism analogue). Each task holds the "
    "TpuSemaphore for its drain, so CROSS-partition concurrency is "
    "bounded by spark.rapids.sql.concurrentGpuTasks (raise it to overlap "
    "partitions); the decode/upload/compute/download overlap WITHIN a "
    "partition runs on admission-free prefetch workers regardless.", 4,
    checker=lambda v: None if int(v) > 0 else "must be positive")

# process-wide settings snapshot (session-init chokepoint, like
# utils/tracing.configure_tracer: exec nodes have no conf at execute time)
_SETTINGS_LOCK = threading.Lock()
_SETTINGS = {
    "enabled": bool(PIPELINE_ENABLED.default),
    "depth": int(PIPELINE_PREFETCH_DEPTH.default),
    "task_pool": int(PIPELINE_TASK_POOL.default),
}

# live prefetch workers (for the shutdown/no-leak contract); counters feed
# pipeline_stats() and the StatsRegistry
_WORKERS_LOCK = threading.Lock()
_WORKERS: dict = {}            # thread -> cancel Event
_STATS = {"workers_started": 0, "items_queued": 0, "stage_errors": 0,
          "tasks_run": 0}

# live introspection for the health watchdog (utils/health.py): every
# bounded prefetch queue and every in-flight pooled task registers here so
# a stalled engine can report WHICH stage is wedged and for how long, and
# a monotonically increasing progress marker distinguishes "slow" from
# "stuck" (the stall detector compares tokens across ticks).
import itertools as _it

_QUEUE_IDS = _it.count()
_QUEUES: dict = {}             # qid -> {"stage", "queue", "created"}
_INFLIGHT_IDS = _it.count()
_INFLIGHT: dict = {}           # token -> {"stage", "thread", "started"}
_PROGRESS = {"counter": 0, "ts": time.monotonic()}


def note_progress() -> None:
    """Bump the engine-wide progress marker (an operator accounted a
    batch, a batch crossed a stage boundary, or a task finished). The
    stall detector treats an unchanged marker with work in flight as a
    hang candidate.

    Deliberately LOCK-FREE: this runs on the hottest per-batch paths
    (exec/base.py account_batch, every queue hop), and the detector only
    needs "did it move" — a racing increment that loses an update still
    moves the counter, so taking _WORKERS_LOCK here would buy nothing
    but cross-operator contention."""
    _PROGRESS["counter"] += 1
    _PROGRESS["ts"] = time.monotonic()


def pipeline_snapshot() -> dict:
    """Live pipeline state for /status and the watchdog report: per-queue
    stage/depth/bound/age, in-flight pooled tasks with ages, worker count,
    and the progress marker + its age."""
    now = time.monotonic()
    with _WORKERS_LOCK:
        queues = [{"stage": info["stage"],
                   "depth": info["queue"].qsize(),
                   "bound": info["queue"].maxsize,
                   "age_s": round(now - info["created"], 3)}
                  for info in _QUEUES.values()]
        in_flight = [{"stage": e["stage"], "thread": e["thread"],
                      "age_s": round(now - e["started"], 3)}
                     for e in _INFLIGHT.values()]
        return {"queues": queues, "in_flight": in_flight,
                "active_workers": sum(1 for t in _WORKERS if t.is_alive()),
                "stats": dict(_STATS),
                "progress_counter": _PROGRESS["counter"],
                "last_progress_age_s": round(now - _PROGRESS["ts"], 3)}


def configure_pipeline(conf) -> None:
    """Apply spark.rapids.tpu.pipeline.* to the process settings (called
    from TpuSession.__init__; the most recent session wins)."""
    with _SETTINGS_LOCK:
        _SETTINGS["enabled"] = bool(conf.get(PIPELINE_ENABLED))
        _SETTINGS["depth"] = int(conf.get(PIPELINE_PREFETCH_DEPTH))
        _SETTINGS["task_pool"] = int(conf.get(PIPELINE_TASK_POOL))


def pipeline_enabled() -> bool:
    with _SETTINGS_LOCK:
        return _SETTINGS["enabled"]


def prefetch_depth() -> int:
    with _SETTINGS_LOCK:
        return _SETTINGS["depth"]


def task_pool_size() -> int:
    with _SETTINGS_LOCK:
        return _SETTINGS["task_pool"]


def pipeline_stats() -> dict:
    """Process-wide pipeline counters (a StatsRegistry source)."""
    with _WORKERS_LOCK:
        out = dict(_STATS)
        out["active_workers"] = sum(1 for t in _WORKERS if t.is_alive())
    return out


def active_workers() -> int:
    """Live prefetch worker threads (0 after queries drain / shutdown)."""
    with _WORKERS_LOCK:
        return sum(1 for t in _WORKERS if t.is_alive())


def shutdown_workers(timeout_s: float = 5.0) -> int:
    """Cancel and join any straggling prefetch workers (session.close()).

    Workers exit on their own when their iterator drains; this is the
    backstop for consumers abandoned mid-stream. PROCESS-GLOBAL, like the
    tracer and the pipeline settings: closing a session while another
    session's query is mid-collect cancels that query's workers too (its
    consumer receives a 'pipeline stage cancelled' error, never a hang) —
    the runtime assumes one active session per process, matching the
    sticky conf semantics in configure_pipeline. Returns the number of
    workers that were still alive when called."""
    with _WORKERS_LOCK:
        items = [(t, ev) for t, ev in _WORKERS.items() if t.is_alive()]
    for _t, ev in items:
        ev.set()
    deadline = time.monotonic() + timeout_s
    for t, _ev in items:
        t.join(timeout=max(0.0, deadline - time.monotonic()))
    with _WORKERS_LOCK:
        for t in [t for t in _WORKERS if not t.is_alive()]:
            _WORKERS.pop(t, None)
    return len(items)


# ---------------------------------------------------------------------------
# stage-decoupling prefetch queue
# ---------------------------------------------------------------------------
class _Done:
    """Poison pill: producer finished cleanly."""


class _Failure:
    """Poison pill: producer raised. Carries the original exception with
    the originating stage context already attached."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


def _attach_context(exc: BaseException, stage: str) -> BaseException:
    """Tag an exception with the pipeline stage that raised it without
    changing its type (callers must see the SAME exception)."""
    note = f"raised in pipeline stage {stage!r}"
    add_note = getattr(exc, "add_note", None)
    if add_note is not None:
        try:
            add_note(f"[spark-rapids-tpu] {note}")
        except Exception:
            pass  # srtpu: net-ok(annotating a propagating error is cosmetic; the original exception still raises either way)
    try:
        ctx = getattr(exc, "pipeline_context", ())
        exc.pipeline_context = tuple(ctx) + (stage,)
    except Exception:
        pass  # srtpu: net-ok(exceptions with slots reject new attributes; the note or type is all we get and the error still raises)
    return exc


def prefetched(make_iter: Callable[[], Iterator], *, stage: str,
               depth: Optional[int] = None, registry=None) -> Iterator:
    """Run ``make_iter()`` on a worker thread, handing items through a
    BOUNDED queue; yields them in order on the calling thread.

    Consumer-side blocked time accounts to ``pipelineWait`` and queue
    occupancy to the ``prefetchQueueDepth`` histogram on ``registry``; the
    same wait is a ``pipeline`` trace span so overlapped stages show up in
    the Chrome trace. Early consumer exit (close/throw) cancels the worker
    and drains the queue; a producer exception re-raises here with the
    stage context attached."""
    from ..io.file_block import current_input_file, set_input_file
    from ..utils import metrics as M
    from ..utils.tracing import get_tracer

    depth = prefetch_depth() if depth is None else max(1, int(depth))
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    cancel = threading.Event()

    def _put(item) -> bool:
        """put that never blocks forever: gives up when cancelled."""
        while not cancel.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _put_final(item) -> None:
        """Best-effort sentinel delivery AFTER cancellation: a consumer
        still blocked in get() must never hang just because its producer
        was shut down (an abandoned consumer's finally-drain keeps the
        queue emptying, so this terminates)."""
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            try:
                q.put(item, timeout=0.05)
                return
            except queue.Full:
                continue

    def produce():
        _WORKER_TLS.exempt = True  # runs under the owning task's admission
        try:
            it = make_iter()
            try:
                for item in it:
                    with _WORKERS_LOCK:
                        _STATS["items_queued"] += 1
                    note_progress()
                    # carry the thread-local input-file holder across the
                    # thread hop (io/file_block.py contract)
                    if not _put((item, current_input_file())):
                        _put_final(_Failure(_attach_context(
                            RuntimeError("pipeline stage cancelled "
                                         "(shutdown)"), stage)))
                        return
            finally:
                close = getattr(it, "close", None)
                if close is not None:
                    close()
            if not _put(_Done):
                _put_final(_Done)
        except BaseException as e:  # noqa: BLE001 — crosses the queue  # srtpu: degrade-ok(the failure is forwarded through the queue and re-raised in the consumer)
            with _WORKERS_LOCK:
                _STATS["stage_errors"] += 1
            if not _put(_Failure(_attach_context(e, stage))):
                _put_final(_Failure(_attach_context(e, stage)))

    t = threading.Thread(target=produce, daemon=True,
                         name=f"tpu-prefetch:{stage}")
    qid = next(_QUEUE_IDS)
    with _WORKERS_LOCK:
        _WORKERS[t] = cancel
        _STATS["workers_started"] += 1
        _QUEUES[qid] = {"stage": stage, "queue": q,
                        "created": time.monotonic()}
        # opportunistic GC of finished workers so the registry stays small
        for dead in [w for w in _WORKERS if not w.is_alive() and w is not t]:
            _WORKERS.pop(dead, None)
    t.start()

    tracer = get_tracer()

    def _get():
        # cooperative deadline: the consumer must not block forever on a
        # producer that wedged after the query's deadline passed — poll
        # with a short timeout only while a deadline is armed (the plain
        # blocking get stays on the hot path otherwise)
        from ..utils.deadline import check_deadline, deadline_active
        if not deadline_active():
            return q.get()
        while True:
            check_deadline()
            try:
                return q.get(timeout=0.25)
            except queue.Empty:
                continue

    try:
        while True:
            t0 = time.perf_counter()
            item = _get()
            wait = time.perf_counter() - t0
            if registry is not None:
                registry.add(M.PIPELINE_WAIT, wait)
                registry.observe(M.PREFETCH_QUEUE_DEPTH, q.qsize())
            tracer.complete("pipeline_wait", "pipeline", t0, wait,
                            stage=stage, depth=q.qsize())
            if item is _Done:
                return
            if isinstance(item, _Failure):
                raise item.exc
            batch, file_info = item
            note_progress()
            set_input_file(*file_info)
            yield batch
    finally:
        with _WORKERS_LOCK:
            _QUEUES.pop(qid, None)
        cancel.set()
        # unblock a producer stuck in put()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass


def maybe_prefetched(make_iter: Callable[[], Iterator], *, stage: str,
                     registry=None, depth: Optional[int] = None) -> Iterator:
    """``prefetched`` when pipelining is on, else the plain iterator —
    the one switch every stage boundary goes through so
    ``pipeline.enabled=false`` restores strictly sequential execution."""
    if not pipeline_enabled():
        return make_iter()
    return prefetched(make_iter, stage=stage, registry=registry, depth=depth)


# ---------------------------------------------------------------------------
# bounded task pool helpers
# ---------------------------------------------------------------------------
T = TypeVar("T")
R = TypeVar("R")


def parallel_map(fn: Callable[[T], R], items: Sequence[T],
                 max_workers: Optional[int] = None,
                 stage: str = "map") -> List[R]:
    """Apply ``fn`` to every item on a bounded pool; results in input
    order. The FIRST exception re-raises (with stage context) after the
    in-flight work settles — no orphaned workers. Falls back to a plain
    loop when pipelining is off, one item, or one worker."""
    items = list(items)
    workers = task_pool_size() if max_workers is None else int(max_workers)
    workers = min(max(1, workers), len(items)) if items else 1
    if not pipeline_enabled() or workers <= 1 or len(items) <= 1:
        return [fn(x) for x in items]
    import concurrent.futures as cf
    with _WORKERS_LOCK:
        _STATS["tasks_run"] += len(items)

    def run_exempt(x):
        # pool threads run under the submitting task's admission (see
        # semaphore_exempt); pipelined_collect re-opts into admission.
        # Register the task in the in-flight table (watchdog forensics:
        # a wedged task shows its stage + age) and mark progress when it
        # completes — either way — so the stall detector sees liveness.
        token = next(_INFLIGHT_IDS)
        with _WORKERS_LOCK:
            _INFLIGHT[token] = {
                "stage": stage,
                "thread": threading.current_thread().name,
                "started": time.monotonic()}
        try:
            from ..utils.deadline import check_deadline
            check_deadline()  # expired deadline: fail fast, don't start
            with _worker_scope():
                return fn(x)
        finally:
            with _WORKERS_LOCK:
                _INFLIGHT.pop(token, None)
            note_progress()

    with cf.ThreadPoolExecutor(
            max_workers=workers,
            thread_name_prefix=f"tpu-pipeline:{stage}") as pool:
        futs = [pool.submit(run_exempt, x) for x in items]
        try:
            return [f.result() for f in futs]
        except BaseException as e:
            for f in futs:
                f.cancel()
            raise _attach_context(e, stage)


def pipelined_collect(plan, conf=None):
    """Drain every partition of ``plan`` concurrently (bounded by
    ``taskPool``) and concatenate in partition order — the pipelined
    replacement for ``PhysicalPlan.collect``.

    Each task holds the TpuSemaphore while it drives its partition
    (admission control: only ``concurrentGpuTasks`` tasks dispatch device
    work at once; the rest overlap host-side stages). Materializing nodes
    (exchanges, AQE, broadcast builds) serialize internally behind their
    own locks, so whichever task arrives first runs the shared work while
    the others wait — exactly one materialization, same as sequential
    mode."""
    from ..columnar.host import HostTable
    from ..memory.semaphore import get_semaphore
    from ..utils.tracing import get_tracer

    n = plan.num_partitions
    if not pipeline_enabled() or n <= 1:
        return plan.collect()
    sem = get_semaphore(conf)
    tracer = get_tracer()
    # async-first drain (ROADMAP item 1): when the plan root is a
    # DeviceToHostExec and async execution is on, tasks accumulate DEVICE
    # batches — no task ever blocks in to_host, so partition P+1's
    # dispatch overlaps partition P's device execution — and the whole
    # query materializes in ONE bulk device_get after every partition
    # drains (exec/transitions.py download -> device.py to_host_batched).
    from ..columnar.device import async_enabled
    deferred = (async_enabled()
                and hasattr(plan, "device_batches")
                and hasattr(plan, "download"))
    # num_partitions above may have run AQE stage materialization on THIS
    # thread; operators (python-UDF exec) end that work re-holding the
    # semaphore for the "task" to release. This thread's task is done —
    # shed every hold, or the drains below starve while we block in
    # result() (single-permit deadlock)
    sem.release_all()

    def drain(p: int):
        with tracer.span("task", "task", partition=p, pipelined=True), \
                _task_admission():
            if deferred:
                # no iterator to close: device_batches drains eagerly
                with sem.task_scope():
                    return plan.device_batches(p)
            it = plan.execute(p)
            try:
                # task_scope, not held(): operators (python-UDF exec) may
                # end a batch re-holding the semaphore, relying on task
                # completion to release — a pooled thread must shed every
                # hold before its next task
                with sem.task_scope():
                    return list(it)
            finally:
                close = getattr(it, "close", None)
                if close is not None:
                    close()

    try:
        per_part = parallel_map(drain, range(n),
                                max_workers=min(task_pool_size(), n),
                                stage="collect")
    finally:
        sem.release_all()  # holds a failed/partial run left on this thread
    batches = [b for part in per_part for b in part]
    if deferred:
        # one bulk transfer for the whole output drain (the ≤1-device_get
        # pin in tests/test_async_exec.py holds across partitions too)
        batches = plan.download(batches)
    if not batches:
        from ..plan.physical import empty_result_table
        return empty_result_table(plan.schema)
    return HostTable.concat(batches)
