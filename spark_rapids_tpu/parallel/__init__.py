"""Distributed execution: mesh/topology management (mesh.py), executor
runtime + failure detection (executor.py), driver control plane and
local-cluster simulation (runtime.py). The on-device GSPMD exchange lives in
shuffle/ici.py; this package is the runtime around it."""
from .executor import ExecutorContext, FailureDetector
from .mesh import (MeshTopology, data_parallel_mesh, grid_mesh,
                   virtual_cpu_mesh)
from .runtime import DriverRuntime, LocalCluster, ProcessCluster

__all__ = ["ExecutorContext", "FailureDetector", "MeshTopology",
           "data_parallel_mesh", "grid_mesh", "virtual_cpu_mesh",
           "DriverRuntime", "LocalCluster", "ProcessCluster"]
