"""ctypes loader for the native runtime library (srtpu_native.cpp).

The reference consumes its native layer through JNI jars (SURVEY.md §2.9);
here the C++ is built on demand with g++ into a cached .so and reached via
ctypes (no pybind11 in the image). Every entry point has a pure-Python
fallback so the framework works without a compiler; ``available()`` reports
which path is active (used by tests and the shuffle codec chooser).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

__all__ = ["available", "get_lib", "lz4_compress", "lz4_decompress",
           "xxhash64", "murmur3_columns", "hash_partition",
           "HashedPriorityQueue", "HostArena", "ba_walk"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "srtpu_native.cpp")
_LOCK = threading.Lock()
_LIB: "Optional[ctypes.CDLL]" = None
_TRIED = False


def _build() -> Optional[str]:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    so = os.path.join(_HERE, f"_srtpu_native_{digest}.so")
    if os.path.exists(so):
        return so
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", so + ".tmp",
           _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return None
    os.replace(so + ".tmp", so)
    return so


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("SRTPU_DISABLE_NATIVE"):
            return None
        so = _build()
        if so is None:
            return None
        lib = ctypes.CDLL(so)
        c = ctypes
        u8p, i32p = c.POINTER(c.c_uint8), c.POINTER(c.c_int32)
        i64p, u32p = c.POINTER(c.c_int64), c.POINTER(c.c_uint32)
        u64p = c.POINTER(c.c_uint64)
        sigs = {
            "srtpu_lz4_compress_bound": (c.c_int64, [c.c_int64]),
            "srtpu_lz4_compress": (c.c_int64, [u8p, c.c_int64, u8p, c.c_int64]),
            "srtpu_lz4_decompress": (c.c_int64, [u8p, c.c_int64, u8p, c.c_int64]),
            "srtpu_xxhash64_buffer": (c.c_uint64, [u8p, c.c_int64, c.c_uint64]),
            "srtpu_xxhash64_records": (None, [u8p, i32p, c.c_int64, c.c_uint64,
                                              u64p]),
            "srtpu_murmur3_int": (None, [i32p, c.c_int64, u32p]),
            "srtpu_murmur3_long": (None, [i64p, c.c_int64, u32p]),
            "srtpu_murmur3_double": (None, [c.POINTER(c.c_double), c.c_int64,
                                            u32p]),
            "srtpu_murmur3_bytes": (None, [u8p, i32p, c.c_int64, u32p]),
            "srtpu_hash_partition": (None, [u32p, c.c_int64, c.c_int32, i32p,
                                            i64p, i64p]),
            "srtpu_pq_create": (c.c_void_p, []),
            "srtpu_pq_destroy": (None, [c.c_void_p]),
            "srtpu_pq_push": (c.c_int64, [c.c_void_p, c.c_int64, c.c_int64]),
            "srtpu_pq_update": (c.c_int, [c.c_void_p, c.c_int64, c.c_int64]),
            "srtpu_pq_remove": (c.c_int, [c.c_void_p, c.c_int64]),
            "srtpu_pq_pop": (c.c_int, [c.c_void_p, i64p, i64p]),
            "srtpu_pq_size": (c.c_int64, [c.c_void_p]),
            "srtpu_arena_create": (c.c_void_p, [c.c_int64]),
            "srtpu_arena_destroy": (None, [c.c_void_p]),
            "srtpu_arena_alloc": (c.c_int64, [c.c_void_p, c.c_int64]),
            "srtpu_arena_free": (c.c_int, [c.c_void_p, c.c_int64]),
            "srtpu_arena_used": (c.c_int64, [c.c_void_p]),
            "srtpu_arena_capacity": (c.c_int64, [c.c_void_p]),
            "srtpu_arena_base": (c.c_void_p, [c.c_void_p]),
            "srtpu_ba_walk": (c.c_int64, [u8p, c.c_int64, c.c_int64,
                                          i64p, i64p]),
        }
        for name, (res, args) in sigs.items():
            fn = getattr(lib, name)
            fn.restype = res
            fn.argtypes = args
        _LIB = lib
        return _LIB


def available() -> bool:
    return get_lib() is not None


def _u8(buf) -> "ctypes.Array":
    return (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf)


def _np_ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


# ---------------------------------------------------------------------------
# LZ4
# ---------------------------------------------------------------------------

def lz4_compress(data: bytes) -> bytes:
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(data)
    bound = lib.srtpu_lz4_compress_bound(n)
    out = (ctypes.c_uint8 * bound)()
    src = _u8(data)
    written = lib.srtpu_lz4_compress(src, n, out, bound)
    if written < 0:
        raise RuntimeError("lz4 compression failed")
    return bytes(out[:written])


def lz4_decompress(data: bytes, uncompressed_size: int) -> bytes:
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    out = (ctypes.c_uint8 * uncompressed_size)()
    src = _u8(data)
    got = lib.srtpu_lz4_decompress(src, len(data), out, uncompressed_size)
    if got != uncompressed_size:
        raise RuntimeError(f"lz4 decompression: {got} != {uncompressed_size}")
    return bytes(out)


# ---------------------------------------------------------------------------
# Hashing
# ---------------------------------------------------------------------------

def xxhash64(data: bytes, seed: int = 0) -> int:
    """True xxhash64 of ``data``; raises when the native library is absent.

    Failing loudly beats a silent non-portable fallback: a checksum minted by
    a native-enabled process must verify identically everywhere, so a
    mixed-fleet exchange would see spurious corruption if some processes hash
    with a different flavor.
    """
    lib = get_lib()
    if lib is None:
        raise RuntimeError(
            "native library unavailable: xxhash64 checksums would not be "
            "portable across processes; build srtpu_native or avoid "
            "checksummed exchange")
    return int(lib.srtpu_xxhash64_buffer(_u8(data), len(data), seed))


def murmur3_columns(columns, seed: int = 42) -> np.ndarray:
    """Spark-style chained murmur3_x86_32 over host numpy columns.

    ``columns`` is a list of (values, validity_or_None) with values either a
    fixed-width numpy array or an object array of strings. Null values leave
    the running hash unchanged (Spark semantics).
    """
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable; callers must check "
                           "available() (the host engine has its own "
                           "murmur3 in expr/hashing.py)")
    n = len(columns[0][0]) if columns else 0
    h = np.full(n, seed, dtype=np.uint32)
    for values, validity in columns:
        if validity is not None and not validity.all():
            keep = h.copy()
        else:
            keep = None
        if values.dtype.kind in "biu" and values.dtype not in (np.int32,
                                                               np.int64):
            values = values.astype(np.int32)  # Spark widens narrow ints
        elif values.dtype == np.float32:
            # Spark hashes FloatType as its 4-byte bit pattern after
            # normalizing -0.0 -> 0.0 and NaN -> canonical NaN; must match
            # expr/hashing.py bit-for-bit (same shuffle bucket choice).
            # float64 normalization lives in srtpu_murmur3_double (C++).
            f = np.where(values == 0.0, np.float32(0.0), values)
            f = np.where(np.isnan(f), np.float32("nan"), f).astype(np.float32)
            values = f.view(np.int32)
        if values.dtype == object:
            encoded = [v.encode("utf-8") if isinstance(v, str) else b""
                       for v in values]
            offsets = np.zeros(n + 1, dtype=np.int32)
            lens = np.fromiter((len(b) for b in encoded), dtype=np.int32,
                               count=n)
            np.cumsum(lens, out=offsets[1:])
            blob = b"".join(encoded)
            lib.srtpu_murmur3_bytes(_u8(blob), _np_ptr(offsets, ctypes.c_int32),
                                    n, _np_ptr(h, ctypes.c_uint32))
        elif values.dtype == np.int32:
            v = np.ascontiguousarray(values)
            lib.srtpu_murmur3_int(_np_ptr(v, ctypes.c_int32), n,
                                  _np_ptr(h, ctypes.c_uint32))
        elif values.dtype == np.int64:
            v = np.ascontiguousarray(values)
            lib.srtpu_murmur3_long(_np_ptr(v, ctypes.c_int64), n,
                                   _np_ptr(h, ctypes.c_uint32))
        elif values.dtype == np.float64:
            v = np.ascontiguousarray(values)
            lib.srtpu_murmur3_double(_np_ptr(v, ctypes.c_double), n,
                                     _np_ptr(h, ctypes.c_uint32))
        else:
            raise TypeError(f"unhashable column dtype {values.dtype}")
        if keep is not None:
            h = np.where(validity, h, keep)
    return h


def hash_partition(hashes: np.ndarray, num_partitions: int):
    """-> (pids, counts, order): stable grouped row order (one gather =
    contiguous partitions; reference GpuPartitioning/contiguous_split)."""
    h = np.ascontiguousarray(hashes, dtype=np.uint32)
    n = len(h)
    lib = get_lib()
    if lib is None:
        pids = (h.view(np.int32) % num_partitions).astype(np.int32)
        pids[pids < 0] += num_partitions
        order = np.argsort(pids, kind="stable").astype(np.int64)
        counts = np.bincount(pids, minlength=num_partitions).astype(np.int64)
        return pids, counts, order
    pids = np.empty(n, dtype=np.int32)
    counts = np.empty(num_partitions, dtype=np.int64)
    order = np.empty(n, dtype=np.int64)
    lib.srtpu_hash_partition(_np_ptr(h, ctypes.c_uint32), n, num_partitions,
                             _np_ptr(pids, ctypes.c_int32),
                             _np_ptr(counts, ctypes.c_int64),
                             _np_ptr(order, ctypes.c_int64))
    return pids, counts, order


# ---------------------------------------------------------------------------
# Hashed priority queue (native when possible; heapq fallback)
# ---------------------------------------------------------------------------

class HashedPriorityQueue:
    """Pop-lowest-priority queue with O(log n) update-by-handle
    (reference: sql-plugin HashedPriorityQueue.java used by the spill
    stores' priority tracking)."""

    def __init__(self):
        self._lib = get_lib()
        if self._lib is not None:
            self._q = self._lib.srtpu_pq_create()
        else:
            import heapq  # noqa: F401
            self._heap = []  # (priority, handle)
            self._entries = {}  # handle -> priority (None = removed)
            self._next = 1

    def push(self, priority: int, payload: int = 0) -> int:
        if self._lib is not None:
            return int(self._lib.srtpu_pq_push(self._q, priority, payload))
        import heapq
        h = self._next
        self._next += 1
        self._entries[h] = (priority, payload)
        heapq.heappush(self._heap, (priority, h))
        return h

    def update(self, handle: int, priority: int) -> bool:
        if self._lib is not None:
            return bool(self._lib.srtpu_pq_update(self._q, handle, priority))
        import heapq
        if handle not in self._entries:
            return False
        payload = self._entries[handle][1]
        self._entries[handle] = (priority, payload)
        heapq.heappush(self._heap, (priority, handle))
        return True

    def remove(self, handle: int) -> bool:
        if self._lib is not None:
            return bool(self._lib.srtpu_pq_remove(self._q, handle))
        return self._entries.pop(handle, None) is not None

    def pop(self):
        """-> (priority, payload) of the lowest-priority entry, or None."""
        if self._lib is not None:
            payload = ctypes.c_int64()
            priority = ctypes.c_int64()
            if self._lib.srtpu_pq_pop(self._q, ctypes.byref(payload),
                                      ctypes.byref(priority)):
                return int(priority.value), int(payload.value)
            return None
        import heapq
        while self._heap:
            priority, h = heapq.heappop(self._heap)
            entry = self._entries.get(h)
            if entry is not None and entry[0] == priority:
                del self._entries[h]
                return priority, entry[1]
        return None

    def __len__(self) -> int:
        if self._lib is not None:
            return int(self._lib.srtpu_pq_size(self._q))
        return len(self._entries)

    def __del__(self):
        if getattr(self, "_lib", None) is not None and self._q:
            self._lib.srtpu_pq_destroy(self._q)
            self._q = None


# ---------------------------------------------------------------------------
# Host arena (spill staging pool)
# ---------------------------------------------------------------------------

class HostArena:
    """Offset-based first-fit host arena with coalescing free (reference:
    RMM ARENA / AddressSpaceAllocator.scala). ``alloc`` returns an offset or
    None when full — the caller runs the spill path and retries (the
    DeviceMemoryEventHandler pattern)."""

    def __init__(self, capacity: int):
        self._lib = get_lib()
        self.capacity = capacity
        if self._lib is not None:
            self._a = self._lib.srtpu_arena_create(capacity)
            if not self._a:
                raise MemoryError(f"arena of {capacity} bytes")
        else:
            self._free = [(0, (capacity + 63) // 64 * 64)]
            self._allocs = {}
            self._used = 0
            self._buf = bytearray((capacity + 63) // 64 * 64)

    def alloc(self, size: int):
        if self._lib is not None:
            off = self._lib.srtpu_arena_alloc(self._a, size)
            return None if off < 0 else int(off)
        size = max((size + 63) // 64 * 64, 64)
        for i, (off, blk) in enumerate(self._free):
            if blk >= size:
                rest = blk - size
                if rest:
                    self._free[i] = (off + size, rest)
                else:
                    del self._free[i]
                self._allocs[off] = size
                self._used += size
                return off
        return None

    def free(self, offset: int) -> bool:
        if self._lib is not None:
            return bool(self._lib.srtpu_arena_free(self._a, offset))
        size = self._allocs.pop(offset, None)
        if size is None:
            return False
        self._used -= size
        self._free.append((offset, size))
        self._free.sort()
        merged = []
        for off, blk in self._free:
            if merged and merged[-1][0] + merged[-1][1] == off:
                merged[-1] = (merged[-1][0], merged[-1][1] + blk)
            else:
                merged.append((off, blk))
        self._free = merged
        return True

    @property
    def used(self) -> int:
        if self._lib is not None:
            return int(self._lib.srtpu_arena_used(self._a))
        return self._used

    def write(self, offset: int, data: bytes):
        if self._lib is not None:
            base = self._lib.srtpu_arena_base(self._a)
            ctypes.memmove(base + offset, data, len(data))
        else:
            self._buf[offset:offset + len(data)] = data

    def read(self, offset: int, size: int) -> bytes:
        if self._lib is not None:
            base = self._lib.srtpu_arena_base(self._a)
            return ctypes.string_at(base + offset, size)
        return bytes(self._buf[offset:offset + size])

    def __del__(self):
        if getattr(self, "_lib", None) is not None and getattr(self, "_a", None):
            self._lib.srtpu_arena_destroy(self._a)
            self._a = None


# ---------------------------------------------------------------------------
# Parquet helpers
# ---------------------------------------------------------------------------

def ba_walk(buf, n: int):
    """Walk a parquet PLAIN BYTE_ARRAY stream -> (starts, lens) int64
    arrays, or None when the native library is absent (callers fall back
    to the Python loop). Raises ValueError on a malformed stream."""
    lib = get_lib()
    if lib is None:
        return None
    starts = np.empty(max(n, 1), np.int64)
    lens = np.empty(max(n, 1), np.int64)
    src = _np_ptr(np.frombuffer(buf, np.uint8), ctypes.c_uint8)  # zero-copy
    consumed = lib.srtpu_ba_walk(src, len(buf), n,
                                 _np_ptr(starts, ctypes.c_int64),
                                 _np_ptr(lens, ctypes.c_int64))
    if consumed < 0:
        raise ValueError("malformed BYTE_ARRAY stream")
    return starts[:n], lens[:n], consumed
