// Native runtime kernels for spark-rapids-tpu.
//
// The reference reaches its native layer (libcudf/RMM/nvcomp/UCX) through JNI
// (SURVEY.md §2.9). Here the device compute path is XLA; this library provides
// the *host-runtime* native surface instead:
//   - LZ4 block-format codec        (role of nvcomp LZ4 batched codec,
//                                    reference NvcompLZ4CompressionCodec.scala)
//   - xxhash64 / murmur3 kernels    (reference HashFunctions.scala, hot on the
//                                    host shuffle-partitioning path)
//   - hash_partition counting sort  (reference GpuPartitioning contiguous
//                                    split: one pass pid assignment + stable
//                                    row order so each partition is one slice)
//   - hashed priority queue         (reference HashedPriorityQueue.java, spill
//                                    priority maintenance with O(log n) update)
//   - host arena allocator          (reference RMM ARENA mode / bounce-buffer
//                                    AddressSpaceAllocator.scala: offset-based
//                                    first-fit with coalescing free)
//
// Exposed as a C ABI consumed via ctypes (no pybind11 in the image).
// Implemented from the public LZ4 block & xxHash format specifications.

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <limits>
#include <map>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// LZ4 block format
// ---------------------------------------------------------------------------

int64_t srtpu_lz4_compress_bound(int64_t n) {
  return n + n / 255 + 16;
}

// Greedy LZ4 block compressor: 16-bit hash chain over 4-byte windows.
int64_t srtpu_lz4_compress(const uint8_t* src, int64_t n, uint8_t* dst,
                           int64_t cap) {
  if (n < 0 || cap < srtpu_lz4_compress_bound(n)) return -1;
  uint8_t* op = dst;
  const uint8_t* ip = src;
  const uint8_t* const iend = src + n;
  const uint8_t* anchor = src;
  // matches may not extend into the final 12 bytes; final 5 must be literals
  const uint8_t* const mflimit = (n >= 13) ? iend - 12 : src;

  auto read32 = [](const uint8_t* p) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    return v;
  };
  auto hash4 = [&](const uint8_t* p) {
    return (read32(p) * 2654435761u) >> 16;
  };

  std::vector<int64_t> table(1 << 16, -1);

  auto emit = [&](const uint8_t* lit_start, int64_t lit_len, int64_t mlen,
                  int64_t offset) {
    int64_t ml_token = (mlen > 0) ? mlen - 4 : 0;
    uint8_t token = (uint8_t)(((lit_len >= 15 ? 15 : lit_len) << 4)
                              | (mlen > 0 ? (ml_token >= 15 ? 15 : ml_token) : 0));
    *op++ = token;
    if (lit_len >= 15) {
      int64_t rest = lit_len - 15;
      while (rest >= 255) { *op++ = 255; rest -= 255; }
      *op++ = (uint8_t)rest;
    }
    std::memcpy(op, lit_start, lit_len);
    op += lit_len;
    if (mlen > 0) {
      *op++ = (uint8_t)(offset & 0xff);
      *op++ = (uint8_t)((offset >> 8) & 0xff);
      if (ml_token >= 15) {
        int64_t rest = ml_token - 15;
        while (rest >= 255) { *op++ = 255; rest -= 255; }
        *op++ = (uint8_t)rest;
      }
    }
  };

  ip = src;
  while (ip < mflimit) {
    uint32_t h = hash4(ip);
    int64_t cand = table[h];
    table[h] = ip - src;
    if (cand >= 0 && (ip - src) - cand <= 65535 &&
        read32(src + cand) == read32(ip)) {
      // extend match forward
      const uint8_t* m = src + cand;
      const uint8_t* p = ip + 4;
      const uint8_t* q = m + 4;
      const uint8_t* match_limit = iend - 5;
      while (p < match_limit && *p == *q) { ++p; ++q; }
      int64_t mlen = p - ip;
      emit(anchor, ip - anchor, mlen, ip - m);
      ip += mlen;
      anchor = ip;
    } else {
      ++ip;
    }
  }
  // trailing literals
  emit(anchor, iend - anchor, 0, 0);
  return op - dst;
}

int64_t srtpu_lz4_decompress(const uint8_t* src, int64_t n, uint8_t* dst,
                             int64_t cap) {
  const uint8_t* ip = src;
  const uint8_t* const iend = src + n;
  uint8_t* op = dst;
  uint8_t* const oend = dst + cap;
  while (ip < iend) {
    uint8_t token = *ip++;
    int64_t lit = token >> 4;
    if (lit == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        lit += b;
      } while (b == 255);
    }
    if (ip + lit > iend || op + lit > oend) return -1;
    std::memcpy(op, ip, lit);
    ip += lit;
    op += lit;
    if (ip >= iend) break;  // last sequence has no match
    if (ip + 2 > iend) return -1;
    int64_t offset = ip[0] | (ip[1] << 8);
    ip += 2;
    if (offset == 0 || op - dst < offset) return -1;
    int64_t mlen = (token & 0xf) + 4;
    if ((token & 0xf) == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        mlen += b;
      } while (b == 255);
    }
    if (op + mlen > oend) return -1;
    const uint8_t* m = op - offset;
    for (int64_t i = 0; i < mlen; ++i) op[i] = m[i];  // overlap-safe
    op += mlen;
  }
  return op - dst;
}

// ---------------------------------------------------------------------------
// xxHash64 (one hash per variable-length record via offsets, or whole buffer)
// ---------------------------------------------------------------------------

static const uint64_t P1 = 11400714785074694791ULL;
static const uint64_t P2 = 14029467366897019727ULL;
static const uint64_t P3 = 1609587929392839161ULL;
static const uint64_t P4 = 9650029242287828579ULL;
static const uint64_t P5 = 2870177450012600261ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static uint64_t xxh64(const uint8_t* p, size_t len, uint64_t seed) {
  const uint8_t* end = p + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed, v4 = seed - P1;
    const uint8_t* limit = end - 32;
    do {
      uint64_t k;
      std::memcpy(&k, p, 8); v1 = rotl64(v1 + k * P2, 31) * P1; p += 8;
      std::memcpy(&k, p, 8); v2 = rotl64(v2 + k * P2, 31) * P1; p += 8;
      std::memcpy(&k, p, 8); v3 = rotl64(v3 + k * P2, 31) * P1; p += 8;
      std::memcpy(&k, p, 8); v4 = rotl64(v4 + k * P2, 31) * P1; p += 8;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    uint64_t vs[4] = {v1, v2, v3, v4};
    for (uint64_t v : vs) {
      h ^= rotl64(v * P2, 31) * P1;
      h = h * P1 + P4;
    }
  } else {
    h = seed + P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    uint64_t k;
    std::memcpy(&k, p, 8);
    h ^= rotl64(k * P2, 31) * P1;
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    uint32_t k;
    std::memcpy(&k, p, 4);
    h ^= (uint64_t)k * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p++) * P5;
    h = rotl64(h, 11) * P1;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

uint64_t srtpu_xxhash64_buffer(const uint8_t* data, int64_t n, uint64_t seed) {
  return xxh64(data, (size_t)n, seed);
}

void srtpu_xxhash64_records(const uint8_t* blob, const int32_t* offsets,
                            int64_t n, uint64_t seed, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    out[i] = xxh64(blob + offsets[i], (size_t)(offsets[i + 1] - offsets[i]),
                   seed);
  }
}

// ---------------------------------------------------------------------------
// Murmur3 x86_32 (Spark flavor: per-value chained hash, seed in/out)
// ---------------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}
static inline uint32_t mix_k1(uint32_t k1) {
  k1 *= 0xcc9e2d51u;
  k1 = rotl32(k1, 15);
  k1 *= 0x1b873593u;
  return k1;
}
static inline uint32_t mix_h1(uint32_t h1, uint32_t k1) {
  h1 ^= k1;
  h1 = rotl32(h1, 13);
  return h1 * 5 + 0xe6546b64u;
}
static inline uint32_t fmix(uint32_t h1, uint32_t len) {
  h1 ^= len;
  h1 ^= h1 >> 16;
  h1 *= 0x85ebca6bu;
  h1 ^= h1 >> 13;
  h1 *= 0xc2b2ae35u;
  h1 ^= h1 >> 16;
  return h1;
}

void srtpu_murmur3_int(const int32_t* v, int64_t n, uint32_t* inout) {
  for (int64_t i = 0; i < n; ++i) {
    uint32_t h1 = mix_h1(inout[i], mix_k1((uint32_t)v[i]));
    inout[i] = fmix(h1, 4);
  }
}

void srtpu_murmur3_long(const int64_t* v, int64_t n, uint32_t* inout) {
  for (int64_t i = 0; i < n; ++i) {
    uint32_t lo = (uint32_t)(uint64_t)v[i];
    uint32_t hi = (uint32_t)((uint64_t)v[i] >> 32);
    uint32_t h1 = mix_h1(inout[i], mix_k1(lo));
    h1 = mix_h1(h1, mix_k1(hi));
    inout[i] = fmix(h1, 8);
  }
}

void srtpu_murmur3_double(const double* v, int64_t n, uint32_t* inout) {
  for (int64_t i = 0; i < n; ++i) {
    // normalize -0.0 and NaN bit patterns (Spark rule; must match the
    // device path's _normalize_float in expr/hashing.py bit-for-bit)
    double d = (v[i] == 0.0) ? 0.0 : v[i];
    if (d != d) d = std::numeric_limits<double>::quiet_NaN();
    int64_t bits;
    std::memcpy(&bits, &d, 8);
    uint32_t lo = (uint32_t)(uint64_t)bits;
    uint32_t hi = (uint32_t)((uint64_t)bits >> 32);
    uint32_t h1 = mix_h1(inout[i], mix_k1(lo));
    h1 = mix_h1(h1, mix_k1(hi));
    inout[i] = fmix(h1, 8);
  }
}

// Spark hashUnsafeBytes: 4-byte little-endian blocks then per-byte tail.
void srtpu_murmur3_bytes(const uint8_t* blob, const int32_t* offsets,
                         int64_t n, uint32_t* inout) {
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* p = blob + offsets[i];
    uint32_t len = (uint32_t)(offsets[i + 1] - offsets[i]);
    uint32_t h1 = inout[i];
    uint32_t nblocks = len / 4;
    for (uint32_t b = 0; b < nblocks; ++b) {
      uint32_t k;
      std::memcpy(&k, p + b * 4, 4);
      h1 = mix_h1(h1, mix_k1(k));
    }
    for (uint32_t j = nblocks * 4; j < len; ++j) {
      h1 = mix_h1(h1, mix_k1((uint32_t)(int32_t)(int8_t)p[j]));
    }
    inout[i] = fmix(h1, len);
  }
}

// ---------------------------------------------------------------------------
// Hash partition assignment + stable counting-sort row order
// ---------------------------------------------------------------------------

// pids[i] = hashes[i] mod p (non-negative); counts[k] = rows in partition k;
// order = row indices stably grouped by partition so each output partition is
// one contiguous slice of a single gather (reference: contiguous_split).
void srtpu_hash_partition(const uint32_t* hashes, int64_t n, int32_t p,
                          int32_t* pids, int64_t* counts, int64_t* order) {
  for (int32_t k = 0; k < p; ++k) counts[k] = 0;
  for (int64_t i = 0; i < n; ++i) {
    int32_t pid = (int32_t)((int32_t)hashes[i] % p);
    if (pid < 0) pid += p;
    pids[i] = pid;
    counts[pid]++;
  }
  std::vector<int64_t> cursor(p, 0);
  int64_t acc = 0;
  for (int32_t k = 0; k < p; ++k) {
    cursor[k] = acc;
    acc += counts[k];
  }
  for (int64_t i = 0; i < n; ++i) {
    order[cursor[pids[i]]++] = i;
  }
}

// ---------------------------------------------------------------------------
// Hashed priority queue (reference HashedPriorityQueue.java):
// pop-lowest-priority with O(log n) priority update by handle.
// ---------------------------------------------------------------------------

struct SrtpuPQ {
  // multimap priority -> (handle, payload); handle -> iterator for O(log n)
  // removal. Ties pop in insertion order (handle order).
  std::multimap<std::pair<int64_t, int64_t>, int64_t> heap;
  std::unordered_map<int64_t,
      std::multimap<std::pair<int64_t, int64_t>, int64_t>::iterator> index;
  int64_t next_handle = 1;
};

void* srtpu_pq_create() { return new SrtpuPQ(); }
void srtpu_pq_destroy(void* q) { delete (SrtpuPQ*)q; }

int64_t srtpu_pq_push(void* qp, int64_t priority, int64_t payload) {
  SrtpuPQ* q = (SrtpuPQ*)qp;
  int64_t h = q->next_handle++;
  auto it = q->heap.emplace(std::make_pair(priority, h), payload);
  q->index[h] = it;
  return h;
}

int srtpu_pq_update(void* qp, int64_t handle, int64_t priority) {
  SrtpuPQ* q = (SrtpuPQ*)qp;
  auto f = q->index.find(handle);
  if (f == q->index.end()) return 0;
  int64_t payload = f->second->second;
  q->heap.erase(f->second);
  auto it = q->heap.emplace(std::make_pair(priority, handle), payload);
  f->second = it;
  return 1;
}

int srtpu_pq_remove(void* qp, int64_t handle) {
  SrtpuPQ* q = (SrtpuPQ*)qp;
  auto f = q->index.find(handle);
  if (f == q->index.end()) return 0;
  q->heap.erase(f->second);
  q->index.erase(f);
  return 1;
}

int srtpu_pq_pop(void* qp, int64_t* payload_out, int64_t* priority_out) {
  SrtpuPQ* q = (SrtpuPQ*)qp;
  if (q->heap.empty()) return 0;
  auto it = q->heap.begin();
  *priority_out = it->first.first;
  *payload_out = it->second;
  q->index.erase(it->first.second);
  q->heap.erase(it);
  return 1;
}

int64_t srtpu_pq_size(void* qp) {
  return (int64_t)((SrtpuPQ*)qp)->heap.size();
}

// ---------------------------------------------------------------------------
// Host arena allocator (offset-based first-fit, coalescing free — the spill
// staging pool; reference: RMM ARENA mode + AddressSpaceAllocator.scala)
// ---------------------------------------------------------------------------

struct SrtpuArena {
  uint8_t* base;
  int64_t capacity;
  int64_t used = 0;
  std::map<int64_t, int64_t> free_blocks;   // offset -> size
  std::unordered_map<int64_t, int64_t> allocs;  // offset -> size
};

static const int64_t kAlign = 64;

void* srtpu_arena_create(int64_t capacity) {
  SrtpuArena* a = new SrtpuArena();
  capacity = (capacity + kAlign - 1) / kAlign * kAlign;
  a->base = (uint8_t*)std::malloc((size_t)capacity);
  if (!a->base) {
    delete a;
    return nullptr;
  }
  a->capacity = capacity;
  a->free_blocks[0] = capacity;
  return a;
}

void srtpu_arena_destroy(void* ap) {
  SrtpuArena* a = (SrtpuArena*)ap;
  std::free(a->base);
  delete a;
}

int64_t srtpu_arena_alloc(void* ap, int64_t size) {
  SrtpuArena* a = (SrtpuArena*)ap;
  if (size <= 0) size = kAlign;
  size = (size + kAlign - 1) / kAlign * kAlign;
  for (auto it = a->free_blocks.begin(); it != a->free_blocks.end(); ++it) {
    if (it->second >= size) {
      int64_t off = it->first;
      int64_t remaining = it->second - size;
      a->free_blocks.erase(it);
      if (remaining > 0) a->free_blocks[off + size] = remaining;
      a->allocs[off] = size;
      a->used += size;
      return off;
    }
  }
  return -1;  // caller spills and retries (DeviceMemoryEventHandler pattern)
}

int srtpu_arena_free(void* ap, int64_t offset) {
  SrtpuArena* a = (SrtpuArena*)ap;
  auto f = a->allocs.find(offset);
  if (f == a->allocs.end()) return 0;
  int64_t size = f->second;
  a->allocs.erase(f);
  a->used -= size;
  // insert and coalesce with neighbors
  auto it = a->free_blocks.emplace(offset, size).first;
  if (it != a->free_blocks.begin()) {
    auto prev = std::prev(it);
    if (prev->first + prev->second == it->first) {
      prev->second += it->second;
      a->free_blocks.erase(it);
      it = prev;
    }
  }
  auto next = std::next(it);
  if (next != a->free_blocks.end() && it->first + it->second == next->first) {
    it->second += next->second;
    a->free_blocks.erase(next);
  }
  return 1;
}

int64_t srtpu_arena_used(void* ap) { return ((SrtpuArena*)ap)->used; }
int64_t srtpu_arena_capacity(void* ap) { return ((SrtpuArena*)ap)->capacity; }
uint8_t* srtpu_arena_base(void* ap) { return ((SrtpuArena*)ap)->base; }

// ---------------------------------------------------------------------------
// Parquet PLAIN BYTE_ARRAY stream walk (parquet format spec: each value is a
// u32 little-endian length prefix followed by that many bytes). The walk is
// inherently sequential, so it lives here instead of a per-value Python
// loop. Returns bytes consumed, or -1 when a length overruns the buffer.
// ---------------------------------------------------------------------------
int64_t srtpu_ba_walk(const uint8_t* buf, int64_t nbytes, int64_t n,
                      int64_t* starts, int64_t* lens) {
  int64_t pos = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (pos + 4 > nbytes) return -1;
    uint32_t ln = (uint32_t)buf[pos] | ((uint32_t)buf[pos + 1] << 8) |
                  ((uint32_t)buf[pos + 2] << 16) |
                  ((uint32_t)buf[pos + 3] << 24);
    pos += 4;
    if (pos + (int64_t)ln > nbytes) return -1;
    starts[i] = pos;
    lens[i] = (int64_t)ln;
    pos += (int64_t)ln;
  }
  return pos;
}

}  // extern "C"
