"""Scalar subquery + runtime-filter (DPP analogue) tests (reference:
GpuScalarSubquery / ExecSubqueryExpression and GpuSubqueryBroadcastExec)."""
import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.expr.functions import (avg, col, scalar_subquery,
                                             sum as f_sum)

from harness import assert_tables_equal, assert_tpu_cpu_equal


@pytest.fixture
def sess():
    return TpuSession({"spark.rapids.tpu.shuffle.mode": "host",
                       "spark.rapids.tpu.shuffle.partitions": 4})


def test_scalar_subquery_in_filter(sess):
    """TPC-H q17 shape: quantity < 0.2 * avg(quantity) — no cross join."""
    rng = np.random.default_rng(3)
    df = sess.create_dataframe(pd.DataFrame({
        "q": rng.uniform(0, 100, 2000)}), num_partitions=3)
    threshold = scalar_subquery(df.agg(avg(col("q")).alias("a")))
    out = df.filter(col("q") < 0.2 * threshold)
    expected = df.collect(device=False).to_pandas()
    cut = 0.2 * expected.q.mean()
    exp_rows = int((expected.q < cut).sum())
    got = assert_tpu_cpu_equal(out)
    assert got.num_rows == exp_rows


def test_scalar_subquery_in_projection(sess):
    df = sess.create_dataframe(pd.DataFrame({"v": [1.0, 2.0, 3.0]}))
    total = scalar_subquery(df.agg(f_sum(col("v")).alias("s")))
    q = df.select((col("v") / total).alias("share"))
    out = q.collect(device=False)
    assert out.column("share").to_pylist() == pytest.approx(
        [1 / 6, 2 / 6, 3 / 6])
    assert_tpu_cpu_equal(q)


def test_scalar_subquery_empty_is_null(sess):
    df = sess.create_dataframe(pd.DataFrame({"v": [1.0, 2.0]}))
    empty = sess.create_dataframe(pd.DataFrame({"v": [1.0]})) \
        .filter(col("v") > 100).select("v")
    q = df.select((col("v") + scalar_subquery(empty)).alias("x"))
    out = q.collect(device=False)
    assert out.column("x").to_pylist() == [None, None]


def test_scalar_subquery_multi_row_raises(sess):
    df = sess.create_dataframe(pd.DataFrame({"v": [1.0, 2.0]}))
    with pytest.raises(ValueError, match="returned 2 rows"):
        df.select((col("v") + scalar_subquery(df.select("v"))).alias("x")) \
            .collect(device=False)


def test_scalar_subquery_requires_one_column(sess):
    df = sess.create_dataframe(pd.DataFrame({"a": [1], "b": [2]}))
    with pytest.raises(ValueError, match="exactly one column"):
        scalar_subquery(df)


def test_runtime_filter_pushes_build_keys_into_probe_scan(sess, tmp_path):
    """A demoted broadcast join pushes the build side's distinct keys into
    the probe parquet scan as an IN filter (DPP analogue)."""
    rng = np.random.default_rng(5)
    n = 4000
    t = pa.table({
        "k": pa.array(rng.integers(0, 1000, n), type=pa.int64()),
        "v": pa.array(rng.normal(size=n)),
    })
    p = str(tmp_path / "probe.parquet")
    pq.write_table(t, p, row_group_size=500)
    s = TpuSession({
        "spark.rapids.tpu.shuffle.mode": "host",
        "spark.rapids.tpu.shuffle.partitions": 4,
        "spark.rapids.tpu.autoBroadcastJoinThreshold": -1,  # force SHJ
        "spark.rapids.tpu.aqe.autoBroadcastJoinThreshold": 1 << 20,
    })
    probe = s.read_parquet(p)
    build = s.create_dataframe(pd.DataFrame({
        "k": np.arange(5, dtype=np.int64),
        "w": np.ones(5)}), num_partitions=2)
    q = probe.join(build, on="k").select("k", "v", "w")
    plan = s._physical(q.logical, True)
    got = plan.collect().to_arrow()
    exp = q.collect(device=False)
    assert_tables_equal(got, exp)
    assert any("runtime IN-filter" in e for e in plan.events), plan.events
    pdf = t.to_pandas()
    assert got.num_rows == int(pdf.k.isin(range(5)).sum())


def test_runtime_filter_skipped_for_outer_join(sess, tmp_path):
    t = pa.table({"k": pa.array(np.arange(100, dtype=np.int64)),
                  "v": pa.array(np.ones(100))})
    p = str(tmp_path / "probe2.parquet")
    pq.write_table(t, p)
    s = TpuSession({
        "spark.rapids.tpu.shuffle.mode": "host",
        "spark.rapids.tpu.shuffle.partitions": 4,
        "spark.rapids.tpu.autoBroadcastJoinThreshold": -1,
        "spark.rapids.tpu.aqe.autoBroadcastJoinThreshold": 1 << 20,
    })
    probe = s.read_parquet(p)
    build = s.create_dataframe(pd.DataFrame({
        "k": np.arange(3, dtype=np.int64), "w": np.ones(3)}),
        num_partitions=2)
    q = probe.join(build, on="k", how="left").select("k", "v", "w")
    plan = s._physical(q.logical, True)
    got = plan.collect().to_arrow()
    # every probe row must survive the left join
    assert got.num_rows == 100
    assert not any("runtime IN-filter" in e for e in plan.events), plan.events
