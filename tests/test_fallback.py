"""Graceful degradation: runtime host fallback, operator quarantine and
query deadlines (PR-15).

The contract under test (docs/fault_tolerance.md "Degradation ladder"):
a terminal device failure — the OOM ladder exhausted, or a classified
non-retryable XLA error — re-executes the failing batch through the
host engine and the query still returns exactly the healthy-device
answer, leaving a schema-v10 ``fallback`` event-log record. Repeated
failures quarantine the (operator, plan-signature, failure-class) key:
a later session plans the operator on host outright, with explain()
showing the reason. A query past
``spark.rapids.tpu.query.timeoutSeconds`` cancels cooperatively with a
structured QueryTimeoutError carrying a forensics dump, leaving no
stuck semaphore permits or arbiter state behind.
"""
import json
import os
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.exec import fallback as fb
from spark_rapids_tpu.exec.fallback import (classify_failure,
                                            configure_fallback,
                                            drain_fallback_records,
                                            fallback_stats, note_quarantine,
                                            persist_quarantine,
                                            quarantine_entries,
                                            quarantine_reason,
                                            reset_fallback_state,
                                            with_host_fallback)
from spark_rapids_tpu.memory.retry import DeviceOomError, configure_oom_retry
from spark_rapids_tpu.utils import faults
from spark_rapids_tpu.utils.deadline import (QueryTimeoutError,
                                             deadline_active, deadline_scope,
                                             reset_deadline)
from spark_rapids_tpu.utils.faults import configure_faults


@pytest.fixture(autouse=True)
def _pristine_degradation():
    """The quarantine store, fallback ledger and deadline state are
    process-global by design; every test starts and ends zeroed with
    the production defaults for the sticky config."""
    def reset():
        reset_fallback_state()
        configure_fallback(RapidsConf({}))
        reset_deadline()
        configure_oom_retry(RapidsConf({}))
        faults.reset_faults()
        faults.reset_recovery()
    reset()
    yield
    reset()


def _chaos_conf(spec):
    return RapidsConf({"spark.rapids.tpu.faults.enabled": "true",
                       "spark.rapids.tpu.faults.seed": "7",
                       "spark.rapids.tpu.faults.spec": spec})


def _assert_parity(got, ref):
    assert got.num_rows == ref.num_rows
    for name in ref.column_names:
        g, r = got.column(name).to_pylist(), ref.column(name).to_pylist()
        if ref.column(name).type in (pa.float64(), pa.float32()):
            np.testing.assert_allclose(np.array(g, dtype=float),
                                       np.array(r, dtype=float), rtol=1e-9)
        else:
            assert g == r, name


# ---------------------------------------------------------------------------
# failure classification
# ---------------------------------------------------------------------------
def test_classify_failure():
    # fallback-eligible terminal classes
    assert classify_failure(DeviceOomError("exhausted")) == "oom_exhausted"
    assert classify_failure(RuntimeError(
        "INVALID_ARGUMENT: donated buffer reused")) == "xla_invalid_argument"
    assert classify_failure(RuntimeError(
        "UNIMPLEMENTED: no kernel for dtype")) == "xla_unimplemented"
    assert classify_failure(RuntimeError(
        "Compilation failure: while lowering")) == "xla_compile"
    assert classify_failure(RuntimeError(
        "INTERNAL: unexpected HLO pass failure")) == "xla_internal"
    # an escaped retryable OOM is still a recoverable device failure
    assert classify_failure(RuntimeError(
        "RESOURCE_EXHAUSTED: out of memory")) == "oom"
    # never fallback-eligible: cancellation, plain bugs, non-Runtime types
    assert classify_failure(QueryTimeoutError(1.0, 2.0)) is None
    assert classify_failure(RuntimeError("shape mismatch")) is None
    assert classify_failure(ValueError("INTERNAL: nope")) is None
    assert classify_failure(KeyError("INVALID_ARGUMENT")) is None


def test_query_timeout_error_is_not_retryable_oom():
    """The timeout message must never pattern-match the OOM markers —
    a deadline expiry inside a retry scope has to propagate, not spin
    the ladder."""
    from spark_rapids_tpu.memory.retry import is_retryable_oom
    err = QueryTimeoutError(0.5, 1.25)
    assert not is_retryable_oom(err)
    assert "deadline" in str(err)


# ---------------------------------------------------------------------------
# the fallback boundary (unit)
# ---------------------------------------------------------------------------
class _FakeNode:
    def plan_signature(self):
        return "Fake|sig"

    def node_desc(self):
        return "fake"


def _device_batch(n=16):
    from spark_rapids_tpu.columnar.device import DeviceTable
    from spark_rapids_tpu.columnar.host import HostTable
    t = pa.table({"a": pa.array(np.arange(n, dtype=np.int64)),
                  "b": pa.array(np.arange(n, dtype=np.float64))})
    return DeviceTable.from_host(HostTable.from_arrow(t), min_bucket=8)


def test_with_host_fallback_recovers_and_records():
    batch = _device_batch()

    def device_fn(b):
        raise RuntimeError("INTERNAL: injected")

    def host_fn(ht):
        return ht  # identity on the host engine

    out = with_host_fallback(_FakeNode(), device_fn, host_fn)(batch)
    got = out.to_host().to_arrow()
    assert got.column("a").to_pylist() == list(range(16))
    s = fallback_stats()
    assert s["host_fallbacks"] == 1
    assert s["fallback_bytes_down"] > 0 and s["fallback_bytes_up"] > 0
    assert faults.recovery_counters()["host_fallbacks"] == 1
    (rec,) = drain_fallback_records()
    for key in ("ts", "operator", "context", "failure_class", "reason",
                "rows", "bytes_down", "bytes_up", "wall_s"):
        assert key in rec, key
    assert rec["operator"] == "_FakeNode"
    assert rec["failure_class"] == "xla_internal"
    assert rec["rows"] == 16
    # the failure was noted in the quarantine store either way
    (ent,) = quarantine_entries()
    assert ent["operator"] == "_FakeNode" and ent["count"] == 1


def test_with_host_fallback_without_host_path_reraises_but_quarantines():
    def device_fn(b):
        raise RuntimeError("UNIMPLEMENTED: no kernel")

    run = with_host_fallback(_FakeNode(), device_fn, None)
    with pytest.raises(RuntimeError, match="UNIMPLEMENTED"):
        run(_device_batch())
    s = fallback_stats()
    assert s["host_fallbacks"] == 0 and s["fallback_failures"] == 1
    (ent,) = quarantine_entries()
    assert ent["failure_class"] == "xla_unimplemented"


def test_with_host_fallback_passes_through_unclassified_errors():
    def device_fn(b):
        raise ValueError("a plain bug")

    run = with_host_fallback(_FakeNode(), device_fn, lambda ht: ht)
    with pytest.raises(ValueError):
        run(_device_batch())
    assert fallback_stats()["host_fallbacks"] == 0
    assert not quarantine_entries()


def test_with_host_fallback_disabled_is_identity():
    configure_fallback(RapidsConf(
        {"spark.rapids.tpu.fallback.enabled": "false"}))
    def device_fn(b):
        return b
    assert with_host_fallback(_FakeNode(), device_fn, None) is device_fn


# ---------------------------------------------------------------------------
# quarantine store: threshold, TTL, eviction, persistence
# ---------------------------------------------------------------------------
def test_quarantine_threshold_and_reason():
    configure_fallback(RapidsConf(
        {"spark.rapids.tpu.fallback.quarantine.threshold": "3"}))
    for _ in range(2):
        note_quarantine("TpuFilterExec", "Filter|sig", "xla_internal",
                        "RuntimeError: INTERNAL: boom")
    assert quarantine_reason("TpuFilterExec", "Filter|sig") is None
    note_quarantine("TpuFilterExec", "Filter|sig", "xla_internal",
                    "RuntimeError: INTERNAL: boom")
    reason = quarantine_reason("TpuFilterExec", "Filter|sig")
    assert reason is not None and "3 runtime xla_internal" in reason
    # a different signature of the same operator is NOT quarantined
    assert quarantine_reason("TpuFilterExec", "Filter|other") is None


def test_quarantine_ttl_expiry(monkeypatch):
    configure_fallback(RapidsConf(
        {"spark.rapids.tpu.fallback.quarantine.threshold": "1",
         "spark.rapids.tpu.fallback.quarantine.ttlSeconds": "60"}))
    note_quarantine("TpuSortExec", "Sort|sig", "xla_compile", "boom")
    assert quarantine_reason("TpuSortExec", "Sort|sig") is not None
    # age the entry past the TTL: the operator gets retried on device
    real_time = time.time
    monkeypatch.setattr(time, "time", lambda: real_time() + 120.0)
    assert quarantine_reason("TpuSortExec", "Sort|sig") is None
    assert not quarantine_entries()


def test_quarantine_max_entries_evicts_oldest():
    configure_fallback(RapidsConf(
        {"spark.rapids.tpu.fallback.quarantine.maxEntries": "4"}))
    for i in range(8):
        note_quarantine(f"Op{i}", f"sig{i}", "xla_internal", "boom")
    ents = quarantine_entries()
    assert len(ents) == 4
    assert {e["operator"] for e in ents} == {"Op4", "Op5", "Op6", "Op7"}


def test_quarantine_persist_and_load_roundtrip(tmp_path):
    path = str(tmp_path / "quarantine.json")
    configure_fallback(RapidsConf(
        {"spark.rapids.tpu.fallback.quarantine.threshold": "2"}))
    for _ in range(2):
        note_quarantine("TpuProjectExec", "Project|sig", "xla_internal",
                        "boom")
    fb._QUARANTINE.persist(path)
    reset_fallback_state()
    assert quarantine_reason("TpuProjectExec", "Project|sig") is None
    fb._QUARANTINE.load(path)
    configure_fallback(RapidsConf(
        {"spark.rapids.tpu.fallback.quarantine.threshold": "2"}))
    assert quarantine_reason("TpuProjectExec", "Project|sig") is not None


def test_quarantine_load_tolerates_corruption(tmp_path):
    path = tmp_path / "quarantine.json"
    path.write_text("{ not json", encoding="utf-8")
    fb._QUARANTINE.load(str(path))  # must not raise
    assert not quarantine_entries()
    fb._QUARANTINE.load(str(tmp_path / "missing.json"))  # ditto


# ---------------------------------------------------------------------------
# deadline scope (unit)
# ---------------------------------------------------------------------------
def test_deadline_scope_noop_when_unset():
    with deadline_scope(0.0):
        assert not deadline_active()


def test_deadline_scope_arms_fires_and_disarms(tmp_path):
    from spark_rapids_tpu.utils.deadline import check_deadline
    with pytest.raises(QueryTimeoutError) as ei:
        with deadline_scope(0.01, report_dir=str(tmp_path)):
            assert deadline_active()
            time.sleep(0.05)
            check_deadline()
    err = ei.value
    assert err.timeout_s == 0.01 and err.elapsed_s >= 0.01
    assert err.forensics_path and os.path.exists(err.forensics_path)
    doc = json.loads(open(err.forensics_path, encoding="utf-8").read())
    for key in ("timeout_s", "elapsed_s", "semaphore", "oom_arbiter",
                "pipeline"):
        assert key in doc, key
    assert not deadline_active()  # disarmed on scope exit


# ---------------------------------------------------------------------------
# chaos matrix: q1/q3/q6 x {fatal XLA error, ladder exhaustion, deadline}
# ---------------------------------------------------------------------------
# q3 (the join shape, ~14s of compile) runs in the slow tier; the
# injection mechanism itself is shape-independent and q1/q6 keep the
# agg- and filter-shaped runs in tier-1
@pytest.mark.parametrize(
    "query", ["q1", pytest.param("q3", marks=pytest.mark.slow), "q6"])
def test_tpch_parity_under_fatal_xla_failure(session, query):
    """Acceptance pin: an injected NON-retryable failure (action=fatal
    at alloc.jit) re-executes the failing batches through the host
    engine and the answer is bit-identical to the clean run."""
    from spark_rapids_tpu.tools import tpch
    tables = tpch.gen_all(0, tiny=True)
    dfs = tpch.build_dataframes(session, tables, num_partitions=2)
    q = getattr(tpch, query)(dfs)
    ref = q.collect(device=True)

    configure_faults(_chaos_conf("alloc.jit:times=2:action=fatal"))
    got = q.collect(device=True)
    faults.reset_faults()

    _assert_parity(got, ref)
    s = fallback_stats()
    assert s["host_fallbacks"] >= 1
    assert faults.recovery_counters()["host_fallbacks"] >= 1
    recs = drain_fallback_records()
    assert recs and all(r["failure_class"] == "xla_internal" for r in recs)


@pytest.mark.parametrize("query", ["q1", "q3", "q6"])
def test_tpch_parity_under_ladder_exhaustion(session, query):
    """With the escalation ladder pinned shut (maxRetries=0,
    maxSplits=0) an injected OOM terminates in DeviceOomError — the
    fallback boundary catches the structured error and the host engine
    still produces the exact answer."""
    from spark_rapids_tpu.tools import tpch
    tables = tpch.gen_all(0, tiny=True)
    dfs = tpch.build_dataframes(session, tables, num_partitions=2)
    q = getattr(tpch, query)(dfs)
    ref = q.collect(device=True)

    configure_oom_retry(RapidsConf({"spark.rapids.tpu.oom.maxRetries": "0",
                                    "spark.rapids.tpu.oom.maxSplits": "0"}))
    configure_faults(_chaos_conf("alloc.jit:times=1:action=oom"))
    got = q.collect(device=True)
    faults.reset_faults()
    configure_oom_retry(RapidsConf({}))

    _assert_parity(got, ref)
    recs = drain_fallback_records()
    assert recs and all(r["failure_class"] == "oom_exhausted" for r in recs)


def test_tpch_deadline_expiry_cancels_cleanly():
    """A query wedged past spark.rapids.tpu.query.timeoutSeconds
    cancels with the structured QueryTimeoutError, the forensics dump
    exists, and no semaphore permits or arbiter state leak."""
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.tools import tpch
    sess = TpuSession(RapidsConf({
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.query.timeoutSeconds": "0.2",
        "spark.rapids.tpu.faults.enabled": "true",
        "spark.rapids.tpu.faults.seed": "7",
        "spark.rapids.tpu.faults.spec": "alloc.jit:action=delay:latency_ms=300",
    }))
    try:
        tables = tpch.gen_all(0, tiny=True)
        dfs = tpch.build_dataframes(sess, tables, num_partitions=2)
        with pytest.raises(QueryTimeoutError) as ei:
            tpch.q1(dfs).collect(device=True)
        err = ei.value
        assert err.timeout_s == pytest.approx(0.2)
        assert err.forensics_path and os.path.exists(err.forensics_path)
        # released runtime state: no stuck permits, no engaged arbiter
        from spark_rapids_tpu.memory.retry import arbiter_snapshot
        from spark_rapids_tpu.memory.semaphore import peek_semaphore
        sem = peek_semaphore()
        if sem is not None:
            assert sem.holder_count() == 0 and sem.waiter_count() == 0
        arb = arbiter_snapshot()
        assert arb["active_retriers"] == 0 and not arb["gate_active"]
        assert not deadline_active()
    finally:
        faults.reset_faults()
        sess.close()


# ---------------------------------------------------------------------------
# plan-time quarantine routing
# ---------------------------------------------------------------------------
def test_quarantine_routes_operator_to_host_at_plan_time(session):
    """After the threshold, explain() shows the quarantine reason and a
    re-planned query runs the operator on host — zero device attempts
    (no further fallbacks) while still matching the clean answer."""
    from spark_rapids_tpu.tools import tpch
    configure_fallback(RapidsConf(
        {"spark.rapids.tpu.fallback.quarantine.threshold": "2"}))
    tables = tpch.gen_all(0, tiny=True)
    dfs = tpch.build_dataframes(session, tables, num_partitions=2)
    q = tpch.q6(dfs)
    ref = q.collect(device=True)

    configure_faults(_chaos_conf("alloc.jit:times=2:action=fatal"))
    got = q.collect(device=True)
    faults.reset_faults()
    _assert_parity(got, ref)
    assert any(e["count"] >= 2 for e in quarantine_entries())

    text = q.explain("tpu")
    assert "quarantined:" in text and "xla_internal" in text

    before = fallback_stats()
    got2 = q.collect(device=True)
    _assert_parity(got2, ref)
    after = fallback_stats()
    # the quarantined operators planned on host: the planner routed them
    # and the run needed no runtime fallbacks (zero device attempts)
    assert after["quarantine_plan_routes"] > before["quarantine_plan_routes"]
    assert after["host_fallbacks"] == before["host_fallbacks"]


def test_quarantine_survives_into_fresh_session(tmp_path):
    """The store persists next to the compile-cache manifest on session
    close; a FRESH session over the same cache dir plans the operator
    on host before ever dispatching it."""
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.tools import tpch
    cache_dir = str(tmp_path / "cache")
    conf = {"spark.rapids.tpu.batchRowsMinBucket": 8,
            "spark.rapids.tpu.compile.cacheDir": cache_dir,
            "spark.rapids.tpu.fallback.quarantine.threshold": "2"}
    sess1 = TpuSession(RapidsConf(dict(conf)))
    try:
        tables = tpch.gen_all(0, tiny=True)
        dfs = tpch.build_dataframes(sess1, tables, num_partitions=2)
        q = tpch.q6(dfs)
        ref = q.collect(device=True)
        configure_faults(_chaos_conf("alloc.jit:times=2:action=fatal"))
        q.collect(device=True)
        faults.reset_faults()
        assert any(e["count"] >= 2 for e in quarantine_entries())
    finally:
        faults.reset_faults()
        sess1.close()  # persists quarantine.json into the cache tier
    reset_fallback_state()
    assert not quarantine_entries()

    sess2 = TpuSession(RapidsConf(dict(conf)))
    try:
        assert quarantine_entries(), "fresh session did not load the store"
        tables = tpch.gen_all(0, tiny=True)
        dfs = tpch.build_dataframes(sess2, tables, num_partitions=2)
        q = tpch.q6(dfs)
        text = q.explain("tpu")
        assert "quarantined:" in text
        before = fallback_stats()
        got = q.collect(device=True)
        _assert_parity(got, ref)
        after = fallback_stats()
        assert after["quarantine_plan_routes"] > 0
        assert after["host_fallbacks"] == before["host_fallbacks"] == 0
    finally:
        sess2.close()


# ---------------------------------------------------------------------------
# schema-v10 fallback records in the event log
# ---------------------------------------------------------------------------
def test_eventlog_v10_fallback_records(tmp_path):
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.tools import tpch
    from spark_rapids_tpu.tools.eventlog import load_event_log
    sess = TpuSession(RapidsConf({
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.faults.enabled": "true",
        "spark.rapids.tpu.faults.seed": "7",
        "spark.rapids.tpu.faults.spec": "alloc.jit:times=2:action=fatal",
    }))
    try:
        tables = tpch.gen_all(0, tiny=True)
        dfs = tpch.build_dataframes(sess, tables, num_partitions=2)
        tpch.q6(dfs).collect(device=True)
        path = sess._eventlog.path
    finally:
        faults.reset_faults()
        sess.close()
    app = load_event_log(path)
    assert app.schema_version == 12
    (q,) = [q for q in app.queries.values() if q.fallbacks]
    for rec in q.fallbacks:
        for key in ("event", "query_id", "ts", "operator", "context",
                    "failure_class", "reason", "rows", "bytes_down",
                    "bytes_up", "wall_s"):
            assert key in rec, key
        assert rec["event"] == "fallback"
        assert rec["failure_class"] == "xla_internal"
    # replay health check surfaces the degradation
    warnings = app.health_check()
    assert any("fell back to the host engine" in w for w in warnings)
