"""Distributed tracing: TraceContext wire round-trips, cross-process
propagation through a real ProcessCluster worker, merged-timeline
determinism + clock-skew alignment, per-query critical-path math on a
hand-built span DAG, ring-drop flagging, and the driver-side metrics
federation (reference: Spark's SQLAppStatusListener + the RAPIDS
qualification tool's per-stage attribution, crossed with Chrome
trace-event semantics)."""
import copy
import json

import pytest

from spark_rapids_tpu.tools.trace import (critical_path,
                                          merge_process_traces,
                                          query_trace_ids)
from spark_rapids_tpu.utils.tracing import (TraceContext,
                                            Tracer,
                                            activate_trace_context,
                                            current_trace_context,
                                            mint_trace_context,
                                            new_span_id)


# ---------------------------------------------------------------------------
# synthetic per-process traces (the shape collect_traces() emits)
# ---------------------------------------------------------------------------
def _proc_trace(process_name, role, epoch_unix, clock_offset_s, events,
                dropped=0):
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "spark-rapids-tpu",
            "pid": 1234,
            "process_name": process_name,
            "role": role,
            "epoch_unix": epoch_unix,
            "clock_offset_s": clock_offset_s,
            "dropped_events": dropped,
        },
    }


def _ev(name, cat, ts, dur, span_id=None, parent=None, trace_id=None,
        tid=0):
    args = {}
    if span_id is not None:
        args["span_id"] = span_id
    if parent is not None:
        args["parent_span_id"] = parent
    if trace_id is not None:
        args["trace_id"] = trace_id
    return {"name": name, "cat": cat, "ph": "X", "ts": float(ts),
            "dur": float(dur), "pid": 0, "tid": tid, "args": args}


# ---------------------------------------------------------------------------
# TraceContext identity
# ---------------------------------------------------------------------------
def test_trace_context_wire_roundtrips():
    ctx = mint_trace_context(query_id=42)
    assert len(ctx.trace_id) == 16

    # dict form (pickled task envelopes)
    back = TraceContext.from_wire(ctx.to_wire())
    assert (back.trace_id, back.span_id, back.query_id) == \
        (ctx.trace_id, ctx.span_id, ctx.query_id)
    assert TraceContext.from_wire(None) is None

    # fixed-size form (TCP shuffle header), including query_id=None -> -1
    for qid in (42, None):
        c = TraceContext(ctx.trace_id, ctx.span_id, qid)
        raw = c.pack()
        assert len(raw) == TraceContext.WIRE.size
        u = TraceContext.unpack(raw)
        assert (u.trace_id, u.span_id, u.query_id) == \
            (c.trace_id, c.span_id, qid)

    # child derivation keeps the trace, swaps the parent span
    sid = new_span_id()
    kid = ctx.child(sid)
    assert kid.trace_id == ctx.trace_id
    assert kid.span_id == sid != ctx.span_id
    assert kid.query_id == 42


def test_span_ids_are_process_unique_and_monotonic():
    a, b = new_span_id(), new_span_id()
    assert a != b
    # same pid in the high bits, increasing counter in the low bits
    assert (a >> 40) == (b >> 40)
    assert (b & 0xFFFFFFFFFF) > (a & 0xFFFFFFFFFF)


def test_span_reparents_under_active_context():
    tracer = Tracer(enabled=True)
    ctx = mint_trace_context(query_id=9)
    with tracer.span("outside", "task"):
        pass
    with activate_trace_context(ctx):
        with tracer.span("root_child", "task"):
            inner_ctx = current_trace_context()
            with tracer.span("grandchild", "shuffle"):
                pass
    assert current_trace_context() is None

    by_name = {e.name: e for e in tracer.events()}
    # no active context -> no trace identity keys
    assert "trace_id" not in by_name["outside"].args
    child = by_name["root_child"].args
    assert child["trace_id"] == ctx.trace_id
    assert child["parent_span_id"] == ctx.span_id
    assert child["query_id"] == 9
    # the span re-parented the context for its body
    assert inner_ctx.span_id == child["span_id"]
    grand = by_name["grandchild"].args
    assert grand["parent_span_id"] == child["span_id"]
    assert grand["trace_id"] == ctx.trace_id


def test_tracer_drain_is_window_scoped():
    tracer = Tracer(capacity=4, enabled=True, process_name="w")
    with pytest.warns(RuntimeWarning, match="ring buffer wrapped"):
        for i in range(10):
            tracer.instant(f"e{i}", "task")
    first = tracer.drain()
    assert first["otherData"]["dropped_events"] == 6
    assert len(first["traceEvents"]) == 4
    epoch = first["otherData"]["epoch_unix"]

    # the drain reset the window: ring empty, drop count rebased,
    # but the clock anchor is NOT reset (merge alignment depends on it)
    tracer.instant("fresh", "task")
    second = tracer.drain()
    assert second["otherData"]["dropped_events"] == 0
    assert [e["name"] for e in second["traceEvents"]] == ["fresh"]
    assert second["otherData"]["epoch_unix"] == epoch


# ---------------------------------------------------------------------------
# merged timeline: clock alignment, determinism, drop flagging
# ---------------------------------------------------------------------------
def _two_process_traces():
    tid = "deadbeefcafe0042"
    d_root = 1
    w_task = (77 << 40) | 1
    driver = _proc_trace("driver", "driver", 1000.0, 0.0, [
        _ev("query", "query", 0.0, 1000.0, span_id=d_root, trace_id=tid),
    ])
    # worker's clock runs 0.0002s AHEAD of the driver's; its tracer was
    # born 0.0004s (of its own wall time) after the driver's
    worker = _proc_trace("worker-0", "worker-0", 1000.0004, 0.0002, [
        _ev("task", "task", 300.0, 400.0, span_id=w_task, parent=d_root,
            trace_id=tid),
    ])
    return driver, worker, tid


def test_merge_aligns_worker_clock_skew():
    driver, worker, _ = _two_process_traces()
    merged = merge_process_traces([driver, worker])
    by_name = {e["name"]: e for e in merged["traceEvents"]
               if e.get("ph") == "X"}
    # worker wall anchor 1000.0004 minus the 0.0002 offset estimate puts
    # its epoch 200us after the driver's -> ts 300 lands at 500
    assert by_name["query"]["ts"] == 0.0
    assert by_name["task"]["ts"] == 500.0
    # deterministic pids: driver first
    assert by_name["query"]["pid"] == 1
    assert by_name["task"]["pid"] == 2
    procs = merged["otherData"]["processes"]
    assert [p["role"] for p in procs] == ["driver", "worker-0"]
    assert merged["otherData"]["reference_epoch_unix"] == 1000.0
    assert merged["otherData"]["clock_aligned"] is True


def test_merge_is_deterministic_under_input_order():
    driver, worker, _ = _two_process_traces()
    a = merge_process_traces([copy.deepcopy(driver), copy.deepcopy(worker)])
    b = merge_process_traces([copy.deepcopy(worker), copy.deepcopy(driver)])
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_merge_flags_dropped_events():
    driver, worker, _ = _two_process_traces()
    worker["otherData"]["dropped_events"] = 3
    merged = merge_process_traces([driver, worker])
    trunc = [e for e in merged["traceEvents"]
             if e["name"] == "trace_truncated"]
    assert len(trunc) == 1
    assert trunc[0]["ph"] == "i"
    assert trunc[0]["pid"] == 2
    # flagged at the front of the worker's row
    assert trunc[0]["ts"] == 500.0
    assert trunc[0]["args"]["dropped_events"] == 3
    assert merged["otherData"]["truncated_processes"] == ["worker-0"]
    procs = {p["process_name"]: p for p in merged["otherData"]["processes"]}
    assert procs["worker-0"]["truncated"] is True
    assert procs["driver"]["truncated"] is False


def test_merge_trace_id_filter_drops_silent_processes():
    driver, worker, tid = _two_process_traces()
    other = _proc_trace("worker-1", "worker-1", 1000.0, 0.0, [
        _ev("task", "task", 10.0, 5.0, span_id=99,
            trace_id="0000000000000099"),
    ])
    merged = merge_process_traces([driver, worker, other], trace_id=tid)
    assert merged["otherData"]["trace_id_filter"] == tid
    names = {p["process_name"] for p in merged["otherData"]["processes"]}
    # worker-1 contributed nothing to this query: no row, no metadata
    assert names == {"driver", "worker-0"}
    assert all(e["args"].get("trace_id") == tid
               for e in merged["traceEvents"] if e.get("ph") == "X")


def test_query_trace_ids_lists_roots():
    driver, worker, tid = _two_process_traces()
    merged = merge_process_traces([driver, worker])
    ids = query_trace_ids(merged["traceEvents"])
    assert [t for t, _ in ids] == [tid]


# ---------------------------------------------------------------------------
# critical-path attribution on a hand-built span DAG
# ---------------------------------------------------------------------------
def test_critical_path_math_on_hand_built_dag():
    """query(1000us) -> task(600us) -> {download 300us, shuffle 200us};
    plus compile 100us directly under the query. Self-times: query 300,
    task 100, download 300 (sync_wait), shuffle 200, compile 100."""
    tid = "00000000000000aa"
    events = [
        _ev("query", "query", 0.0, 1000.0, span_id=1, trace_id=tid),
        _ev("task", "task", 100.0, 600.0, span_id=2, parent=1,
            trace_id=tid),
        _ev("device_sync", "download", 200.0, 300.0, span_id=3, parent=2,
            trace_id=tid),
        _ev("shuffle_fetch", "shuffle", 500.0, 200.0, span_id=4, parent=2,
            trace_id=tid),
        _ev("jit_compile", "compile", 800.0, 100.0, span_id=5, parent=1,
            trace_id=tid),
    ]
    cp = critical_path(events, trace_id=tid)
    assert cp.trace_id == tid
    assert cp.total_s == pytest.approx(1000e-6)
    assert cp.span_count == 5

    cats = cp.categories
    assert cats["sync_wait"] == pytest.approx(300e-6)
    assert cats["shuffle_transfer"] == pytest.approx(200e-6)
    assert cats["compile"] == pytest.approx(100e-6)
    # query self 300us + task self 100us
    assert cats["other"] == pytest.approx(400e-6)
    # self-time attribution covers the root wall exactly
    assert sum(cats.values()) == pytest.approx(cp.total_s)
    assert cp.coverage == pytest.approx(1.0)
    assert cp.sync_wait_frac == pytest.approx(0.3)

    # the ranked chain follows the longest child at each level
    assert [s["name"] for s in cp.ranked_path] == \
        ["query", "task", "device_sync"]

    d = cp.to_dict()
    assert d["sync_wait_frac"] == pytest.approx(0.3)
    assert d["coverage"] >= 0.95
    assert set(d["fractions"]) == set(cats)
    assert set(d["categories_s"]) == set(cats)

    # the human rendering names the dominant categories
    text = cp.render()
    assert "sync_wait" in text and "device_sync" in text


def test_critical_path_adopts_cross_process_orphans():
    """A worker span whose parent id references a span that never made it
    into the merged set (ring wrap) still attributes under the query
    root instead of vanishing."""
    tid = "00000000000000bb"
    events = [
        _ev("query", "query", 0.0, 100.0, span_id=1, trace_id=tid),
        # parent 999 was dropped from the ring -> orphan, adopted by root
        _ev("upload", "upload", 10.0, 40.0, span_id=2, parent=999,
            trace_id=tid),
    ]
    cp = critical_path(events, trace_id=tid)
    assert cp.categories["h2d_upload"] == pytest.approx(40e-6)
    assert cp.coverage == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# real cross-process round trip (spawn is the dominant cost; the dcn
# tier test in test_process_cluster.py sets the non-slow precedent)
# ---------------------------------------------------------------------------
@pytest.fixture
def _fresh_global_tracer():
    """configure_tracer() is sticky on the process-wide tracer; swap in a
    throwaway so enabling tracing here can't leak into other tests."""
    from spark_rapids_tpu.utils.tracing import get_tracer, set_tracer
    prev = get_tracer()
    set_tracer(Tracer())
    yield
    set_tracer(prev)


def test_trace_context_roundtrip_through_process_cluster(
        _fresh_global_tracer):
    from spark_rapids_tpu.parallel.runtime import (ProcessCluster,
                                                   trace_probe_task)
    from spark_rapids_tpu.utils.tracing import configure_tracer
    from spark_rapids_tpu.conf import RapidsConf
    conf = {"spark.rapids.tpu.trace.enabled": "true"}
    configure_tracer(RapidsConf(conf))
    with ProcessCluster(2, conf=conf) as cluster:
        # the startup handshake estimated every worker's clock offset
        assert set(cluster.clock_offsets) == {0, 1}
        assert all(abs(off) < 5.0 for off in cluster.clock_offsets.values())

        ctx = mint_trace_context(query_id=7)
        with activate_trace_context(ctx):
            wire = cluster.run_on(0, trace_probe_task)
        # the worker saw OUR trace, under a worker-minted child span
        assert wire is not None
        assert wire["trace_id"] == ctx.trace_id
        assert wire["query_id"] == 7
        assert wire["span_id"] != ctx.span_id

        # no active context -> the probe reports none (no stale leakage)
        assert cluster.run_on(1, trace_probe_task) is None

        traces = cluster.collect_traces(drain=True)
        assert [t["otherData"]["role"] for t in traces] == \
            ["driver", "worker-0", "worker-1"]
        assert traces[0]["otherData"]["clock_offset_s"] == 0.0

        merged = merge_process_traces(traces, trace_id=ctx.trace_id)
        probes = [e for e in merged["traceEvents"]
                  if e.get("name") == "trace_probe"]
        assert len(probes) == 1
        assert probes[0]["args"]["trace_id"] == ctx.trace_id
        # the context the probe reported IS the probe span's identity
        assert probes[0]["args"]["span_id"] == wire["span_id"]
        # ...which parents under the worker's envelope "task" span,
        # which itself parents under the driver's minted query context
        tasks = [e for e in merged["traceEvents"]
                 if e.get("ph") == "X" and e.get("name") == "task"]
        assert probes[0]["args"]["parent_span_id"] in \
            {t["args"]["span_id"] for t in tasks}
        assert any(t["args"]["parent_span_id"] == ctx.span_id
                   for t in tasks)


# ---------------------------------------------------------------------------
# metrics federation (driver aggregates worker registries)
# ---------------------------------------------------------------------------
def test_label_prometheus_text_injects_process_label():
    from spark_rapids_tpu.tools.statusd import label_prometheus_text
    src = ("# HELP srtpu_tasks tasks\n"
           "# TYPE srtpu_tasks counter\n"
           "srtpu_tasks 3\n"
           'srtpu_spans{cat="shuffle"} 7\n')
    out = label_prometheus_text(src, "worker-0")
    assert 'srtpu_tasks{process="worker-0"} 3' in out
    assert 'srtpu_spans{process="worker-0",cat="shuffle"} 7' in out
    # comments pass through untouched
    assert "# HELP srtpu_tasks tasks" in out


def test_metrics_federation_scrape_degrades_per_peer():
    from spark_rapids_tpu.tools.statusd import MetricsFederation
    fed = MetricsFederation(local_name="driver")
    fed.register_puller("worker-0", lambda: "srtpu_up 1\n")

    def boom():
        raise ConnectionError("peer gone")
    fed.register_puller("worker-1", boom)

    res = fed.scrape()
    assert res["worker-0"]["ok"] is True
    assert res["worker-1"]["ok"] is False
    assert "peer gone" in res["worker-1"]["error"]

    page = fed.prometheus_text()
    assert 'srtpu_up{process="worker-0"} 1' in page
    assert "# federated from worker-0" in page
    assert "worker-1 FAILED" in page

    fed.unregister("worker-1")
    assert "worker-1" not in fed.peers()
