"""Ops tooling: qualification scorer, profiler, cost-based optimizer
(reference: tools/ QualificationMain + ProfileMain, CostBasedOptimizer)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr.functions import col, lit, sum as fsum
from spark_rapids_tpu.tools.qualification import qualify
from spark_rapids_tpu.tools.profiler import profile_query


@pytest.fixture()
def numeric_df(session):
    rng = np.random.default_rng(3)
    t = pa.table({"k": rng.integers(0, 10, 4000),
                  "v": rng.normal(size=4000)})
    return session.create_dataframe(t, num_partitions=2)


def test_qualify_all_device(numeric_df):
    q = numeric_df.filter(col("v") > lit(0.0)) \
        .group_by("k").agg(fsum(col("v")).alias("s"))
    rep = qualify(q)
    assert 0.0 < rep.score <= 1.0
    assert rep.supported_ops > 0
    assert rep.estimated_speedup > 1.0
    assert "qualification score" in rep.summary()


def test_qualify_unsupported_ops(session):
    import spark_rapids_tpu.expr.functions as F
    t = pa.table({"arr": pa.array([[1, 2]], type=pa.list_(pa.int64()))})
    df = session.create_dataframe(t).select(F.size(col("arr")).alias("s"))
    rep = qualify(df)
    assert rep.supported_ops < rep.total_ops
    bad = [r for _, ok, r in rep.per_op if not ok]
    assert any(r for r in bad)


def test_profiler(numeric_df):
    q = numeric_df.filter(col("v") > lit(0.0)) \
        .group_by("k").agg(fsum(col("v")).alias("s"))
    prof = profile_query(q, device=True)
    assert prof.total_s > 0
    assert any(n.rows > 0 for n in prof.nodes)
    names = [n.name for n in prof.nodes]
    assert any("Scan" in n or "Tpu" in n or "Cpu" in n for n in names)
    assert "total wall time" in prof.summary()
    prof.to_json()
    assert isinstance(prof.health_check(), list)


def test_profiler_results_still_correct(numeric_df):
    q = numeric_df.group_by("k").agg(fsum(col("v")).alias("s"))
    prof = profile_query(q, device=False)
    total_rows_out = [n for n in prof.nodes if n.depth == 0][0].rows
    assert total_rows_out == 10


def test_cbo_demotes_small_sections(session):
    rng = np.random.default_rng(4)
    t = pa.table({"v": rng.normal(size=100)})
    df = session.create_dataframe(t)
    q = df.filter(col("v") > lit(0.0))  # one tiny device op
    base = session.conf
    try:
        session.conf = session.conf.set(
            "spark.rapids.sql.optimizer.enabled", True).set(
            "spark.rapids.sql.optimizer.transitionWeight", 100.0)
        text = q.explain("tpu")  # explain path doesn't run cbo; check collect
        out = q.collect(device=True)
        exp = q.collect(device=False)
        assert out.num_rows == exp.num_rows
        # with absurd transition weight, the section must be demoted: the
        # device plan prints no Tpu nodes
        plan = session._physical(q.logical, device=True)
        assert "Tpu" not in plan.tree_string()
    finally:
        session.conf = base


def test_cbo_keeps_big_sections(session):
    rng = np.random.default_rng(5)
    t = pa.table({"k": rng.integers(0, 5, 1000), "v": rng.normal(size=1000)})
    df = session.create_dataframe(t)
    q = df.filter(col("v") > lit(0.0)).group_by("k") \
        .agg(fsum(col("v")).alias("s"))
    base = session.conf
    try:
        session.conf = session.conf.set(
            "spark.rapids.sql.optimizer.enabled", True)
        plan = session._physical(q.logical, device=True)
        assert "Tpu" in plan.tree_string()
    finally:
        session.conf = base


def test_to_jax_ml_handoff(session, rng):
    """DataFrame -> jax.Array export (reference: ColumnarRdd.scala:42 +
    InternalColumnarRddConverter, the XGBoost handoff)."""
    import jax.numpy as jnp
    import pyarrow as pa
    t = pa.table({"x": rng.normal(size=100), "y": rng.integers(0, 2, 100),
                  "s": [f"r{i}" for i in range(100)]})
    df = session.create_dataframe(t, num_partitions=2)
    arrs = df.to_jax()
    assert arrs["x"].shape == (100,) and arrs["y"].shape == (100,)
    assert isinstance(arrs["s"], tuple)            # (bytes matrix, lengths)
    assert float(jnp.sum(arrs["x"])) == pytest.approx(
        float(t.column("x").to_pandas().sum()), rel=1e-6)
    # nulls guarded
    df2 = session.create_dataframe(pa.table({"a": [1.0, None]}))
    with pytest.raises(ValueError, match="nulls"):
        df2.to_jax()
    m = df2.to_jax(allow_nulls=True)
    assert "a__validity" in m
    # ColumnarRdd analogue: device batches per partition
    from spark_rapids_tpu.columnar.device import DeviceTable
    assert all(isinstance(b, DeviceTable)
               for p in range(df.num_partitions())
               for b in df.to_device_batches(p))


def test_exec_kill_switch_forces_fallback(session, rng):
    """Per-op conf keys (auto-derived from rule registries, reference
    GpuOverrides.scala:211-303) force device fallback with a reason."""
    t = pa.table({"k": [1, 2, 1], "v": [1.0, 2.0, 3.0]})
    s2 = type(session)({"spark.rapids.sql.exec.HashAggregateExec": False,
                        "spark.rapids.tpu.batchRowsMinBucket": 8})
    df = s2.create_dataframe(t)
    q = df.group_by("k").agg(fsum(col("v")).alias("s"))
    text = q.explain("tpu")
    assert "disabled by spark.rapids.sql.exec.HashAggregateExec" in text, text
    out = q.collect(device=True)        # falls back, still correct
    assert sorted(out.column("s").to_pylist()) == [2.0, 4.0]


def test_expression_kill_switch(session):
    t = pa.table({"s": ["ab", "cd"]})
    s2 = type(session)({"spark.rapids.sql.expression.Upper": False,
                        "spark.rapids.tpu.batchRowsMinBucket": 8})
    from spark_rapids_tpu.expr.functions import upper
    df = s2.create_dataframe(t)
    q = df.select(upper(col("s")).alias("u"))
    text = q.explain("tpu")
    assert "disabled by spark.rapids.sql.expression.Upper" in text, text
    assert q.collect(device=True).column("u").to_pylist() == ["AB", "CD"]


def test_supported_ops_doc_generates(tmp_path):
    """docs/supported_ops.md regenerates from the live registries
    (reference: SupportedOpsDocs, TypeChecks.scala:1638)."""
    from spark_rapids_tpu.tools.supported_ops import (supported_ops_markdown,
                                                      write_supported_ops)
    text = supported_ops_markdown()
    assert "| ShuffledHashJoinExec |" in text
    assert "`spark.rapids.sql.exec.ShuffledHashJoinExec`" in text
    assert "## Expressions" in text
    p = write_supported_ops(str(tmp_path / "ops.md"))
    assert (tmp_path / "ops.md").read_text() == text
    # the committed doc must be current
    import os
    committed = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "supported_ops.md")
    if os.path.exists(committed):
        assert open(committed).read() == text, \
            "docs/supported_ops.md is stale; regenerate with " \
            "python -m spark_rapids_tpu.tools.supported_ops"
