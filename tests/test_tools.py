"""Ops tooling: qualification scorer, profiler, cost-based optimizer
(reference: tools/ QualificationMain + ProfileMain, CostBasedOptimizer)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr.functions import col, lit, sum as fsum
from spark_rapids_tpu.tools.qualification import qualify
from spark_rapids_tpu.tools.profiler import profile_query


@pytest.fixture()
def numeric_df(session):
    rng = np.random.default_rng(3)
    t = pa.table({"k": rng.integers(0, 10, 4000),
                  "v": rng.normal(size=4000)})
    return session.create_dataframe(t, num_partitions=2)


def test_qualify_all_device(numeric_df):
    q = numeric_df.filter(col("v") > lit(0.0)) \
        .group_by("k").agg(fsum(col("v")).alias("s"))
    rep = qualify(q)
    assert 0.0 < rep.score <= 1.0
    assert rep.supported_ops > 0
    assert rep.estimated_speedup > 1.0
    assert "qualification score" in rep.summary()


def test_qualify_unsupported_ops(session):
    import spark_rapids_tpu.expr.functions as F
    t = pa.table({"arr": pa.array([[1, 2]], type=pa.list_(pa.int64()))})
    df = session.create_dataframe(t).select(F.size(col("arr")).alias("s"))
    rep = qualify(df)
    assert rep.supported_ops < rep.total_ops
    bad = [r for _, ok, r in rep.per_op if not ok]
    assert any(r for r in bad)


def test_profiler(numeric_df):
    q = numeric_df.filter(col("v") > lit(0.0)) \
        .group_by("k").agg(fsum(col("v")).alias("s"))
    prof = profile_query(q, device=True)
    assert prof.total_s > 0
    assert any(n.rows > 0 for n in prof.nodes)
    names = [n.name for n in prof.nodes]
    assert any("Scan" in n or "Tpu" in n or "Cpu" in n for n in names)
    assert "total wall time" in prof.summary()
    prof.to_json()
    assert isinstance(prof.health_check(), list)


def test_profiler_results_still_correct(numeric_df):
    q = numeric_df.group_by("k").agg(fsum(col("v")).alias("s"))
    prof = profile_query(q, device=False)
    total_rows_out = [n for n in prof.nodes if n.depth == 0][0].rows
    assert total_rows_out == 10


def test_cbo_demotes_small_sections(session):
    rng = np.random.default_rng(4)
    t = pa.table({"v": rng.normal(size=100)})
    df = session.create_dataframe(t)
    q = df.filter(col("v") > lit(0.0))  # one tiny device op
    base = session.conf
    try:
        session.conf = session.conf.set(
            "spark.rapids.sql.optimizer.enabled", True).set(
            "spark.rapids.sql.optimizer.transitionWeight", 100.0)
        text = q.explain("tpu")  # explain path doesn't run cbo; check collect
        out = q.collect(device=True)
        exp = q.collect(device=False)
        assert out.num_rows == exp.num_rows
        # with absurd transition weight, the section must be demoted: the
        # device plan prints no Tpu nodes
        plan = session._physical(q.logical, device=True)
        assert "Tpu" not in plan.tree_string()
    finally:
        session.conf = base


def test_cbo_keeps_big_sections(session):
    rng = np.random.default_rng(5)
    t = pa.table({"k": rng.integers(0, 5, 1000), "v": rng.normal(size=1000)})
    df = session.create_dataframe(t)
    q = df.filter(col("v") > lit(0.0)).group_by("k") \
        .agg(fsum(col("v")).alias("s"))
    base = session.conf
    try:
        session.conf = session.conf.set(
            "spark.rapids.sql.optimizer.enabled", True)
        plan = session._physical(q.logical, device=True)
        assert "Tpu" in plan.tree_string()
    finally:
        session.conf = base
