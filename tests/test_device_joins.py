"""Device join kernel tests (reference analogues: join_test.py +
HashJoinSuite). Verifies the Tpu join node is actually in the plan, then
differentials device vs CPU engine across join types and edge cases."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr.functions import col, lit
from harness import assert_tpu_cpu_equal, data_gen


def _has_node(plan, cls_name: str) -> bool:
    if type(plan).__name__ == cls_name:
        return True
    return any(_has_node(c, cls_name) for c in plan.children)


@pytest.fixture
def sides(session, rng):
    lt = data_gen(rng, 200, {"k": ("int32", 0, 30), "k2": ("int64", 0, 4),
                             "a": "int64", "fa": "float64"})
    rt = data_gen(rng, 150, {"k": ("int32", 0, 30), "k2": ("int64", 0, 4),
                             "b": "float64"})
    return (session.create_dataframe(lt, num_partitions=2),
            session.create_dataframe(rt, num_partitions=2))


def test_device_join_in_plan(session, sides):
    l, r = sides
    q = l.join(r.select("k", "b"), on="k")
    plan = session._physical(q.logical, True)
    assert _has_node(plan, "TpuBroadcastHashJoinExec") \
        or _has_node(plan, "TpuShuffledHashJoinExec"), plan.tree_string()


@pytest.mark.parametrize("how", ["inner", "left", "left_semi", "left_anti"])
def test_device_join_types(sides, how):
    l, r = sides
    assert_tpu_cpu_equal(l.join(r.select("k", "b"), on="k", how=how))


def test_device_join_multi_key(sides):
    l, r = sides
    assert_tpu_cpu_equal(l.join(r, on=["k", "k2"]))


def test_device_join_null_keys(session):
    lt = pa.table({"k": [1, None, 2, None, 3], "a": [1, 2, 3, 4, 5]})
    rt = pa.table({"k": [1, None, 3, 4], "b": [10.0, 20.0, 30.0, 40.0]})
    l = session.create_dataframe(lt)
    r = session.create_dataframe(rt)
    for how in ["inner", "left", "left_semi", "left_anti"]:
        assert_tpu_cpu_equal(l.join(r, on="k", how=how))
    out = l.join(r, on="k").collect(device=True)
    assert sorted(out.column("k").to_pylist()) == [1, 3]  # nulls never match


def test_device_join_float_keys_nan_zero(session):
    lt = pa.table({"k": [1.0, float("nan"), -0.0, 2.5],
                   "a": [1, 2, 3, 4]})
    rt = pa.table({"k": [float("nan"), 0.0, 2.5],
                   "b": [10, 20, 30]})
    l = session.create_dataframe(lt)
    r = session.create_dataframe(rt)
    out = assert_tpu_cpu_equal(l.join(r, on="k"))
    # NaN matches NaN, -0.0 matches 0.0
    assert out.num_rows == 3


def test_device_join_duplicate_expansion(session, rng):
    # heavy duplicates: expansion >> probe rows exercises the bucketed
    # out_cap path (the reference's oversized-gather handling)
    lt = pa.table({"k": np.repeat([1, 2], 50), "a": np.arange(100)})
    rt = pa.table({"k": np.repeat([1, 2, 3], 40), "b": np.arange(120)})
    l = session.create_dataframe(lt)
    r = session.create_dataframe(rt)
    out = assert_tpu_cpu_equal(l.join(r, on="k"))
    assert out.num_rows == 2 * 50 * 40


def test_device_join_empty_sides(session):
    l = session.create_dataframe(pa.table({"k": pa.array([], type=pa.int64()),
                                           "a": pa.array([], type=pa.int64())}))
    r = session.create_dataframe(pa.table({"k": [1, 2], "b": [1.0, 2.0]}))
    assert_tpu_cpu_equal(l.join(r, on="k"))
    assert_tpu_cpu_equal(r.join(l, on="k", how="left"))
    assert_tpu_cpu_equal(r.join(l, on="k", how="left_anti"))


def test_device_join_residual_condition(session, rng):
    lt = data_gen(rng, 80, {"lk": ("int32", 0, 10), "a": "int64"})
    rt = data_gen(rng, 60, {"rk": ("int32", 0, 10), "b": "float64"})
    l = session.create_dataframe(lt)
    r = session.create_dataframe(rt)
    q = l.join(r, condition=(col("lk") == col("rk"))
               & (col("a").cast(__import__("spark_rapids_tpu.columnar.dtypes",
                                           fromlist=["DOUBLE"]).DOUBLE)
                  > col("b")))
    assert_tpu_cpu_equal(q)


def test_shuffled_path_forced(session, rng):
    # disable broadcast -> shuffled hash join with exchanges
    s2 = type(session)(session.conf.set(
        "spark.rapids.tpu.autoBroadcastJoinThreshold", -1))
    lt = data_gen(rng, 100, {"k": ("int32", 0, 10), "a": "int64"})
    rt = data_gen(rng, 80, {"k": ("int32", 0, 10), "b": "float64"})
    l = s2.create_dataframe(lt, num_partitions=2)
    r = s2.create_dataframe(rt, num_partitions=2)
    q = l.join(r, on="k")
    plan = s2._physical(q.logical, True)
    assert _has_node(plan, "TpuShuffledHashJoinExec"), plan.tree_string()
    assert_tpu_cpu_equal(q)


def test_string_join_keys_fall_back(session):
    lt = pa.table({"k": ["a", "b"], "v": [1, 2]})
    rt = pa.table({"k": ["b", "c"], "w": [3, 4]})
    l = session.create_dataframe(lt)
    r = session.create_dataframe(rt)
    q = l.join(r, on="k")
    plan = session._physical(q.logical, True)
    assert not _has_node(plan, "TpuBroadcastHashJoinExec")
    assert not _has_node(plan, "TpuShuffledHashJoinExec")
    out = q.collect(device=True)
    assert out.column("k").to_pylist() == ["b"]


def test_right_outer_not_broadcast_with_partitions(session, rng):
    # regression: broadcast-right must not be used for right/full outer joins
    lt = data_gen(rng, 40, {"k": ("int32", 0, 5), "a": "int64"})
    rt = pa.table({"k": [1, 99], "b": [1.0, 2.0]})
    l = session.create_dataframe(lt, num_partitions=2)
    r = session.create_dataframe(rt)
    for how in ("right", "full"):
        out = l.join(r.select("k", "b"), on="k", how=how).collect()
        # unmatched right row (k=99) must appear exactly once
        assert out.column("k").to_pylist().count(99) == 1


def test_broadcast_threshold_string_conf(session, rng):
    # regression: late-registered conf keys set as strings must be converted
    s2 = type(session)({"spark.rapids.tpu.autoBroadcastJoinThreshold": "-1",
                        "spark.rapids.tpu.batchRowsMinBucket": 8})
    lt = data_gen(rng, 20, {"k": ("int32", 0, 5), "a": "int64"})
    rt = data_gen(rng, 10, {"k": ("int32", 0, 5), "b": "float64"})
    out = s2.create_dataframe(lt).join(
        s2.create_dataframe(rt), on="k").collect()
    assert out.num_rows > 0
