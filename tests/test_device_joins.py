"""Device join kernel tests (reference analogues: join_test.py +
HashJoinSuite). Verifies the Tpu join node is actually in the plan, then
differentials device vs CPU engine across join types and edge cases."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr.functions import col, lit
from harness import assert_tpu_cpu_equal, data_gen


def _has_node(plan, cls_name: str) -> bool:
    from spark_rapids_tpu.plan.aqe import AdaptiveExec
    if isinstance(plan, AdaptiveExec):
        plan = plan.final_plan()
    if type(plan).__name__ == cls_name:
        return True
    kids = list(plan.children)
    for attr in ("inner", "stage"):  # AQE stage leaves/readers hide subtrees
        sub = getattr(plan, attr, None)
        if sub is not None:
            kids.append(sub)
    return any(_has_node(c, cls_name) for c in kids)


@pytest.fixture
def sides(session, rng):
    lt = data_gen(rng, 200, {"k": ("int32", 0, 30), "k2": ("int64", 0, 4),
                             "a": "int64", "fa": "float64"})
    rt = data_gen(rng, 150, {"k": ("int32", 0, 30), "k2": ("int64", 0, 4),
                             "b": "float64"})
    return (session.create_dataframe(lt, num_partitions=2),
            session.create_dataframe(rt, num_partitions=2))


def test_device_join_in_plan(session, sides):
    l, r = sides
    q = l.join(r.select("k", "b"), on="k")
    plan = session._physical(q.logical, True)
    assert _has_node(plan, "TpuBroadcastHashJoinExec") \
        or _has_node(plan, "TpuShuffledHashJoinExec"), plan.tree_string()


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "left_semi", "left_anti"])
def test_device_join_types(sides, how):
    l, r = sides
    assert_tpu_cpu_equal(l.join(r.select("k", "b"), on="k", how=how))


def test_device_join_multi_key(sides):
    l, r = sides
    assert_tpu_cpu_equal(l.join(r, on=["k", "k2"]))


def test_device_join_null_keys(session):
    lt = pa.table({"k": [1, None, 2, None, 3], "a": [1, 2, 3, 4, 5]})
    rt = pa.table({"k": [1, None, 3, 4], "b": [10.0, 20.0, 30.0, 40.0]})
    l = session.create_dataframe(lt)
    r = session.create_dataframe(rt)
    for how in ["inner", "left", "right", "full", "left_semi", "left_anti"]:
        assert_tpu_cpu_equal(l.join(r, on="k", how=how))
    out = l.join(r, on="k").collect(device=True)
    assert sorted(out.column("k").to_pylist()) == [1, 3]  # nulls never match
    # full outer: null keys from BOTH sides appear as unmatched rows
    out = l.join(r, on="k", how="full").collect(device=True)
    # 2 matches (1,3) + 3 unmatched left (None,2,None) + 2 unmatched right
    assert out.num_rows == 7


def test_device_join_float_keys_nan_zero(session):
    lt = pa.table({"k": [1.0, float("nan"), -0.0, 2.5],
                   "a": [1, 2, 3, 4]})
    rt = pa.table({"k": [float("nan"), 0.0, 2.5],
                   "b": [10, 20, 30]})
    l = session.create_dataframe(lt)
    r = session.create_dataframe(rt)
    out = assert_tpu_cpu_equal(l.join(r, on="k"))
    # NaN matches NaN, -0.0 matches 0.0
    assert out.num_rows == 3


def test_device_join_duplicate_expansion(session, rng):
    # heavy duplicates: expansion >> probe rows exercises the bucketed
    # out_cap path (the reference's oversized-gather handling)
    lt = pa.table({"k": np.repeat([1, 2], 50), "a": np.arange(100)})
    rt = pa.table({"k": np.repeat([1, 2, 3], 40), "b": np.arange(120)})
    l = session.create_dataframe(lt)
    r = session.create_dataframe(rt)
    out = assert_tpu_cpu_equal(l.join(r, on="k"))
    assert out.num_rows == 2 * 50 * 40


def test_device_join_empty_sides(session):
    l = session.create_dataframe(pa.table({"k": pa.array([], type=pa.int64()),
                                           "a": pa.array([], type=pa.int64())}))
    r = session.create_dataframe(pa.table({"k": [1, 2], "b": [1.0, 2.0]}))
    assert_tpu_cpu_equal(l.join(r, on="k"))
    assert_tpu_cpu_equal(r.join(l, on="k", how="left"))
    assert_tpu_cpu_equal(r.join(l, on="k", how="left_anti"))


def test_device_join_residual_condition(session, rng):
    lt = data_gen(rng, 80, {"lk": ("int32", 0, 10), "a": "int64"})
    rt = data_gen(rng, 60, {"rk": ("int32", 0, 10), "b": "float64"})
    l = session.create_dataframe(lt)
    r = session.create_dataframe(rt)
    q = l.join(r, condition=(col("lk") == col("rk"))
               & (col("a").cast(__import__("spark_rapids_tpu.columnar.dtypes",
                                           fromlist=["DOUBLE"]).DOUBLE)
                  > col("b")))
    assert_tpu_cpu_equal(q)


def test_shuffled_path_forced(session, rng):
    # disable broadcast -> shuffled hash join with exchanges
    s2 = type(session)(session.conf.set(
        "spark.rapids.tpu.autoBroadcastJoinThreshold", -1).set(
        "spark.rapids.tpu.aqe.autoBroadcastJoinThreshold", -1))
    lt = data_gen(rng, 100, {"k": ("int32", 0, 10), "a": "int64"})
    rt = data_gen(rng, 80, {"k": ("int32", 0, 10), "b": "float64"})
    l = s2.create_dataframe(lt, num_partitions=2)
    r = s2.create_dataframe(rt, num_partitions=2)
    q = l.join(r, on="k")
    plan = s2._physical(q.logical, True)
    assert _has_node(plan, "TpuShuffledHashJoinExec"), plan.tree_string()
    assert_tpu_cpu_equal(q)


def test_string_join_keys_on_device(session, rng):
    """String join keys run on device via packed-word join codes (the
    reference gets native string keys from cudf hash join)."""
    lt = pa.table({"k": ["a", "b", None, "longer-key-aaaa", "b"],
                   "v": [1, 2, 3, 4, 5]})
    rt = pa.table({"k": ["b", "c", None, "longer-key-aaaa"],
                   "w": [3, 4, 5, 6]})
    l = session.create_dataframe(lt)
    r = session.create_dataframe(rt)
    q = l.join(r, on="k")
    plan = session._physical(q.logical, True)
    assert _has_node(plan, "TpuBroadcastHashJoinExec") \
        or _has_node(plan, "TpuShuffledHashJoinExec"), plan.tree_string()
    for how in ["inner", "left", "right", "full", "left_semi", "left_anti"]:
        assert_tpu_cpu_equal(l.join(r, on="k", how=how))
    out = q.collect(device=True)
    assert sorted(out.column("k").to_pylist()) == ["b", "b",
                                                   "longer-key-aaaa"]


@pytest.mark.parametrize("how", ["inner", "left", "right", "full",
                                 "left_semi", "left_anti"])
def test_outer_residual_condition(session, rng, how):
    """Residual conditions on outer joins: a probe row whose every candidate
    fails the condition must still appear null-padded (matched-flag fixup,
    reference GpuHashJoin.scala:507)."""
    from spark_rapids_tpu.columnar import dtypes as dt
    lt = data_gen(rng, 120, {"lk": ("int32", 0, 12), "a": "int64"})
    rt = data_gen(rng, 90, {"rk": ("int32", 0, 12), "b": "float64"})
    l = session.create_dataframe(lt, num_partitions=2)
    r = session.create_dataframe(rt, num_partitions=2)
    q = l.join(r, how=how,
               condition=(col("lk") == col("rk"))
               & (col("a").cast(dt.DOUBLE) > col("b")))
    assert_tpu_cpu_equal(q)


@pytest.mark.parametrize("how", ["inner", "cross", "left", "right", "full",
                                 "left_semi", "left_anti"])
def test_device_bnlj(session, rng, how):
    """Non-equi conditions lower to the device nested-loop join."""
    lt = data_gen(rng, 60, {"a": ("int64", 0, 40)})
    rt = data_gen(rng, 25, {"b": ("int64", 0, 40)})
    l = session.create_dataframe(lt, num_partitions=2)
    r = session.create_dataframe(rt)
    cond = None if how == "cross" else col("a") > col("b")
    q = l.join(r, how=how, condition=cond)
    plan = session._physical(q.logical, True)
    assert _has_node(plan, "TpuBroadcastNestedLoopJoinExec"), \
        plan.tree_string()
    assert_tpu_cpu_equal(q)


@pytest.mark.slow
def test_bnlj_unmatched_broadcast_rows_once(session, rng):
    """right/full BNLJ: unmatched broadcast rows appear exactly once even
    with multiple stream partitions and batches. Slow tier: compiles the
    BNLJ kernel for two join kinds (~27s); tier-1 keeps the hash-join
    unmatched-once guard (test_right_outer_not_broadcast_with_partitions)."""
    lt = data_gen(rng, 50, {"a": ("int64", 0, 10)}, null_prob=0.0)
    rt = pa.table({"b": [5, 1000]})
    l = session.create_dataframe(lt, num_partitions=3)
    r = session.create_dataframe(rt)
    for how in ("right", "full"):
        q = l.join(r, how=how, condition=col("a") > col("b"))
        out = assert_tpu_cpu_equal(q)
        assert out.column("b").to_pylist().count(1000) == 1


def test_right_outer_not_broadcast_with_partitions(session, rng):
    # regression: broadcast-right must not be used for right/full outer joins
    lt = data_gen(rng, 40, {"k": ("int32", 0, 5), "a": "int64"})
    rt = pa.table({"k": [1, 99], "b": [1.0, 2.0]})
    l = session.create_dataframe(lt, num_partitions=2)
    r = session.create_dataframe(rt)
    for how in ("right", "full"):
        out = l.join(r.select("k", "b"), on="k", how=how).collect()
        # unmatched right row (k=99) must appear exactly once
        assert out.column("k").to_pylist().count(99) == 1


def test_broadcast_threshold_string_conf(session, rng):
    # regression: late-registered conf keys set as strings must be converted
    s2 = type(session)({"spark.rapids.tpu.autoBroadcastJoinThreshold": "-1",
                        "spark.rapids.tpu.batchRowsMinBucket": 8})
    lt = data_gen(rng, 20, {"k": ("int32", 0, 5), "a": "int64"})
    rt = data_gen(rng, 10, {"k": ("int32", 0, 5), "b": "float64"})
    out = s2.create_dataframe(lt).join(
        s2.create_dataframe(rt), on="k").collect()
    assert out.num_rows > 0


def test_bnlj_build_side_windowing(session, rng):
    """A broadcast side bigger than the pair-slot budget splits into build
    windows; results stay identical incl. right/full leftover emission."""
    s2 = type(session)({"spark.rapids.sql.batchSizeBytes": 64 * 1024,
                        "spark.rapids.tpu.batchRowsMinBucket": 8,
                        "spark.rapids.tpu.autoBroadcastJoinThreshold": -1})
    lt = data_gen(rng, 150, {"a": ("int64", 0, 60)}, null_prob=0.05)
    rt = data_gen(rng, 400, {"b": ("int64", 0, 60)}, null_prob=0.05)
    l = s2.create_dataframe(lt, num_partitions=2)
    r = s2.create_dataframe(rt)
    from spark_rapids_tpu.expr.functions import col as _c
    for how in ("inner", "left", "right", "full", "left_semi", "left_anti"):
        q = l.join(r, how=how, condition=_c("a") == _c("b") + 1)
        dev = q.collect(device=True)
        cpu = q.collect(device=False)
        import pyarrow.compute as pc
        assert dev.num_rows == cpu.num_rows, (how, dev.num_rows, cpu.num_rows)
        d = dev.to_pandas().sort_values(list(dev.column_names)).reset_index(drop=True)
        c = cpu.to_pandas().sort_values(list(cpu.column_names)).reset_index(drop=True)
        import pandas.testing as pdt
        pdt.assert_frame_equal(d, c, check_dtype=False)


def test_mixed_type_join_keys_coerce(session):
    """int64 vs float64 join keys must hash to the same partitions (Spark
    inserts implicit casts): USING joins output the COMMON type, semi/anti
    keep the left side's ORIGINAL type (hidden-key coercion)."""
    import pandas as pd
    s2 = type(session)(session.conf.set(
        "spark.rapids.tpu.autoBroadcastJoinThreshold", -1))
    fact = s2.create_dataframe(pa.table({
        "k": pa.array(np.arange(40, dtype=np.int64) % 10),
        "v": pa.array(np.ones(40))}), num_partitions=3)
    dim = s2.create_dataframe(pa.table({
        "k": pa.array(np.arange(0, 10, 2, dtype=np.float64)),
        "w": pa.array(np.arange(5, dtype=np.float64))}), num_partitions=2)
    # USING inner join: every k in {0,2,4,6,8} matches (4 rows each)
    j = fact.join(dim, on="k")
    assert str(j.schema.field("k").dtype) == "double"  # common type
    out = assert_tpu_cpu_equal(j)
    assert out.num_rows == 20
    # full join: 20 matches + 20 unmatched fact rows
    jf = fact.join(dim, on="k", how="full")
    assert assert_tpu_cpu_equal(jf).num_rows == 40
    # semi/anti: left types preserved, matching still works
    js = fact.join(dim, on="k", how="left_semi")
    assert str(js.schema.field("k").dtype) == "bigint"
    out_s = assert_tpu_cpu_equal(js)
    assert out_s.num_rows == 20
    assert str(out_s.schema.field("k").type) == "int64"
    ja = fact.join(dim, on="k", how="left_anti")
    assert assert_tpu_cpu_equal(ja).num_rows == 20


@pytest.mark.parametrize("strategy", ["sort", "hash"])
def test_join_strategy_differential(strategy):
    """The sort-free hash slot-table join (spark.rapids.tpu.join.strategy)
    matches the sorted searchsorted path and the host engine, including
    duplicate-key builds (which fall back to the general count path) and
    null keys."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.expr.functions import col, lit
    from spark_rapids_tpu.session import TpuSession
    rng = np.random.default_rng(13)
    n = 20_000
    kv = rng.integers(0, 3000, n)
    kmask = np.ones(n, bool)
    kmask[::37] = False
    fact = pa.table({"k": pa.array(kv, mask=~kmask),
                     "v": rng.normal(size=n)})
    dim = pa.table({"k": np.arange(3000, dtype=np.int64),
                    "w": rng.normal(size=3000)})
    dup = pa.table({"k": np.repeat(np.arange(50, dtype=np.int64), 2),
                    "w": rng.normal(size=100)})
    sess = TpuSession({"spark.rapids.tpu.batchRowsMinBucket": 2048,
                       "spark.rapids.tpu.join.strategy": strategy,
                       "spark.rapids.tpu.autoBroadcastJoinThreshold": -1})
    f = sess.create_dataframe(fact, num_partitions=2)
    for build in (dim, dup):
        d = sess.create_dataframe(build, num_partitions=2)
        for how in ("inner", "left", "left_semi", "left_anti"):
            q = f.join(d.filter(col("k") < lit(1500)), on="k", how=how)
            dev = sorted(map(str, q.collect(device=True).to_pylist()))
            cpu = sorted(map(str, q.collect(device=False).to_pylist()))
            assert dev == cpu, (strategy, how, build.num_rows)
