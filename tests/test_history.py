"""History store + regression sentinel + history-server UI
(tools/history.py, tools/historyd.py).

Synthetic event logs are hand-written record dicts (the
test_health.py idiom) so verdicts are deterministic; one integration
test drives a real session with ``spark.rapids.tpu.history.dir`` set to
pin the close()-appends contract end to end.
"""
import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.tools.history import (COMPILE_COUNT_KEY,
                                            SYNC_COUNT_KEY, HistoryStore,
                                            run_sentinel)
from spark_rapids_tpu.tools.history import main as history_main


def _write_log(path, app_id, wall=1.0, stats=None, skew_rows=None,
               n_queries=2, error_qid=None, fault_qids=()):
    """One synthetic schema-v7 event log: ``n_queries`` queries of
    ``wall`` seconds each, a two-node plan, optional per-query counter
    stats, and an optional shuffle_skew record built from an explicit
    per-partition row list. Queries in ``fault_qids`` additionally carry
    schema-v8 ``fault`` + ``recovery`` records (an injected-chaos run)."""
    recs = [{"event": "app_start", "app_id": app_id, "schema_version": 7,
             "ts": 1000.0, "conf": {}}]
    for qid in range(1, n_queries + 1):
        t0 = 1000.0 + qid * 10
        recs.append({"event": "query_start", "query_id": qid, "ts": t0,
                     "plan": "TpuHashAggregateExec\n  TpuScanExec"})
        recs.append({"event": "node", "query_id": qid, "node_id": 0,
                     "parent_id": -1, "name": "TpuHashAggregateExec",
                     "desc": "keys=[g]", "depth": 0, "wall_s": wall,
                     "rows": 100, "batches": 1, "t_first": 0.0,
                     "t_last": wall, "peak_device_bytes": 1 << 20,
                     "metrics": {}})
        recs.append({"event": "node", "query_id": qid, "node_id": 1,
                     "parent_id": 0, "name": "TpuScanExec",
                     "desc": "table", "depth": 1, "wall_s": wall * 0.4,
                     "rows": 400, "batches": 2, "t_first": 0.0,
                     "t_last": wall * 0.4, "peak_device_bytes": 1 << 18,
                     "metrics": {}})
        if skew_rows is not None:
            mean = sum(skew_rows) / len(skew_rows)
            recs.append({
                "event": "shuffle_skew", "query_id": qid, "node_id": 2,
                "name": "ShuffleExchangeExec",
                "partitions": len(skew_rows),
                "rows": {"min": min(skew_rows),
                         "p50": sorted(skew_rows)[len(skew_rows) // 2],
                         "max": max(skew_rows), "mean": mean,
                         "imbalance": max(skew_rows) / mean},
                "bytes": {"min": 8 * min(skew_rows),
                          "p50": 8 * sorted(skew_rows)[len(skew_rows) // 2],
                          "max": 8 * max(skew_rows), "mean": 8 * mean,
                          "imbalance": max(skew_rows) / mean},
                "per_partition_rows": list(skew_rows)})
        if qid in fault_qids:
            recs.append({"event": "fault", "query_id": qid, "ts": t0,
                         "point": "worker.task", "action": "kill",
                         "fire": 1, "evaluation": 2})
            recs.append({"event": "recovery", "query_id": qid,
                         "ts": t0 + wall,
                         "recovery": {"worker_deaths": 1,
                                      "task_resubmissions": 1}})
        end = {"event": "query_end", "query_id": qid, "ts": t0 + wall,
               "wall_s": wall, "stats": dict(stats or {})}
        if qid == error_qid:
            end["error"] = "RuntimeError: boom"
        recs.append(end)
    recs.append({"event": "app_end", "ts": 2000.0})
    with open(path, "w", encoding="utf-8") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    return path


_BASE_STATS = {SYNC_COUNT_KEY: 5, COMPILE_COUNT_KEY: 3}


def test_store_round_trip_and_headline(tmp_path):
    log = _write_log(str(tmp_path / "a.jsonl"), "app-a",
                     stats=_BASE_STATS, skew_rows=[10, 10, 300, 10])
    art = tmp_path / "trace.json"
    art.write_text("{}")
    store = HistoryStore(str(tmp_path / "store"))
    app_id = store.append_run(log, artifacts=[str(art)])
    assert app_id == "app-a"

    h = store.index()["app-a"]
    assert h["schema_version"] == 7
    assert h["n_queries"] == 2 and h["n_errors"] == 0
    assert h["total_wall_s"] == pytest.approx(2.0)
    q1 = h["queries"]["1"] if "1" in h["queries"] else h["queries"][1]
    assert q1["wall_s"] == pytest.approx(1.0)
    assert q1["sync_count"] == 5 and q1["compile_count"] == 3
    # the headline surfaces the run's worst rows-imbalance
    assert q1["skew_imbalance"] == pytest.approx(300 / 82.5)

    # a FRESH store object over the same directory (new-process analogue)
    # lists the run and replays the copied event log + artifact
    fresh = HistoryStore(str(tmp_path / "store"))
    assert [a["app_id"] for a in fresh.apps()] == ["app-a"]
    app = fresh.load("app-a")
    assert app.schema_version == 7
    assert len(app.query(1).shuffle_skew) == 1
    assert os.path.exists(os.path.join(
        fresh.app_dir("app-a"), "artifacts", "trace.json"))


def test_index_survives_concurrent_writers(tmp_path):
    """Racing appends must converge on a complete, never-torn index:
    every writer rebuilds by rescanning app dirs and atomically replaces
    index.json, so the last replace wins with the full superset."""
    store_dir = str(tmp_path / "store")
    n = 8
    logs = [_write_log(str(tmp_path / f"l{i}.jsonl"), f"app-{i:02d}",
                       stats=_BASE_STATS) for i in range(n)]
    errors = []

    def _append(i):
        try:
            HistoryStore(store_dir).append_run(logs[i])
        except Exception as e:  # pragma: no cover — the failure signal
            errors.append(e)

    threads = [threading.Thread(target=_append, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    store = HistoryStore(store_dir)
    # index.json parses (atomic replace: no torn writes) and, after a
    # rebuild by any reader, covers every app dir on disk
    store.rebuild_index()
    assert sorted(store.index()) == [f"app-{i:02d}" for i in range(n)]


def test_sentinel_clean_then_regressed(tmp_path):
    store = HistoryStore(str(tmp_path / "store"))
    store.append_run(_write_log(str(tmp_path / "b.jsonl"), "base",
                                wall=1.0, stats=_BASE_STATS))
    store.append_run(_write_log(str(tmp_path / "c.jsonl"), "clean",
                                wall=1.0, stats=_BASE_STATS))
    store.pin_baseline("base")
    assert store.baseline_app_id() == "base"

    v = run_sentinel(store, candidate="clean")
    assert v["ok"] is True and v["status"] == "clean"
    assert v["baseline"] == "base" and v["flags"] == []
    # the verdict persists into the store and folds into the index
    assert store.verdict("clean")["ok"] is True
    assert store.index()["clean"]["verdict"]["ok"] is True

    # regressed run: 10x wall plus sync/compile counter explosions well
    # past the 10%/abs-2 count gates
    store.append_run(_write_log(
        str(tmp_path / "r.jsonl"), "regressed", wall=10.0,
        stats={SYNC_COUNT_KEY: 60, COMPILE_COUNT_KEY: 58}))
    v = run_sentinel(store, candidate="regressed")
    assert v["ok"] is False and v["status"] == "regressed"
    assert "wall_time" in v["flags"]
    assert "sync_count" in v["flags"]
    assert "compile_count" in v["flags"]
    assert v["sync_count_regressions"] and v["compile_count_regressions"]
    assert store.index()["regressed"]["verdict"]["ok"] is False


def test_sentinel_treats_recovered_chaos_run_as_clean(tmp_path):
    """A candidate whose queries carry schema-v8 fault records but no
    errors (an injected-chaos run that recovered to the right answer,
    e.g. BENCH_CHAOS=1) is exempt from every gate — its recovery
    overhead is paid on purpose. A query that regressed WITHOUT
    injection in the same run still flags."""
    store = HistoryStore(str(tmp_path / "store"))
    store.append_run(_write_log(str(tmp_path / "b.jsonl"), "base",
                                wall=1.0, stats=_BASE_STATS))
    store.pin_baseline("base")

    # every query slower + counter explosions, but all injected+recovered
    store.append_run(_write_log(
        str(tmp_path / "ch.jsonl"), "chaos", wall=10.0,
        stats={SYNC_COUNT_KEY: 60, COMPILE_COUNT_KEY: 58},
        fault_qids=(1, 2)))
    v = run_sentinel(store, candidate="chaos")
    assert v["ok"] is True and v["status"] == "clean"
    assert v["flags"] == []
    assert v["chaos_recovered_queries"] == [1, 2]

    # same slowdown but only query 2 was injected: query 1's regression
    # is real and still gates
    store.append_run(_write_log(
        str(tmp_path / "m.jsonl"), "mixed", wall=10.0,
        stats=_BASE_STATS, fault_qids=(2,)))
    v = run_sentinel(store, candidate="mixed", baseline="base")
    assert v["ok"] is False and "wall_time" in v["flags"]
    assert v["wall_regressed_queries"] == [1]
    assert v["chaos_recovered_queries"] == [2]

    # an injected query that ERRORED is not exempt — recovery failed
    store.append_run(_write_log(
        str(tmp_path / "e.jsonl"), "chaos-err", wall=10.0,
        stats=_BASE_STATS, fault_qids=(1, 2), error_qid=1))
    v = run_sentinel(store, candidate="chaos-err", baseline="base")
    assert v["chaos_recovered_queries"] == [2]


def test_sentinel_total_wall_gate(tmp_path):
    """The v13 aggregate gate (the MULTICHIP trajectory number): summed
    wall over the queries present in both runs flags past the relative
    threshold AND the 2s absolute floor — a material fleet-wide slowdown
    trips it, while the same relative growth on a tiny run doesn't
    flap the sentinel."""
    store = HistoryStore(str(tmp_path / "store"))
    store.append_run(_write_log(str(tmp_path / "b.jsonl"), "base",
                                wall=1.0, stats=_BASE_STATS))
    store.pin_baseline("base")

    # 2 queries x (1.0s -> 3.0s): total 2s -> 6s, past 20% and the floor
    store.append_run(_write_log(str(tmp_path / "s.jsonl"), "slow",
                                wall=3.0, stats=_BASE_STATS))
    v = run_sentinel(store, candidate="slow")
    assert "total_wall" in v["flags"]
    assert v["total_wall"]["baseline_s"] == pytest.approx(2.0)
    assert v["total_wall"]["candidate_s"] == pytest.approx(6.0)
    assert v["total_wall"]["n_queries"] == 2

    # 50% relative growth but only +1s aggregate: under the 2s floor,
    # the per-query wall gate still owns this one
    store.append_run(_write_log(str(tmp_path / "j.jsonl"), "jitter",
                                wall=1.5, stats=_BASE_STATS))
    v = run_sentinel(store, candidate="jitter", baseline="base")
    assert "total_wall" not in v["flags"]
    assert v["total_wall"]["candidate_s"] == pytest.approx(3.0)


def test_sentinel_no_baseline_and_cli_exit_codes(tmp_path):
    store_dir = str(tmp_path / "store")
    store = HistoryStore(store_dir)
    store.append_run(_write_log(str(tmp_path / "one.jsonl"), "only",
                                stats=_BASE_STATS))
    v = run_sentinel(store)
    assert v["ok"] is True and v["status"] == "no-baseline"

    # second run regresses against the implicit prior-run baseline —
    # the CLI contract: exit 1 on regression, 0 on clean
    store.append_run(_write_log(
        str(tmp_path / "two.jsonl"), "slow", wall=9.0,
        stats={SYNC_COUNT_KEY: 90, COMPILE_COUNT_KEY: 80}))
    assert history_main(["sentinel", "--dir", store_dir,
                         "--candidate", "slow"]) == 1
    store.append_run(_write_log(str(tmp_path / "three.jsonl"), "ok-run",
                                stats=_BASE_STATS))
    assert history_main(["sentinel", "--dir", store_dir,
                         "--candidate", "ok-run",
                         "--baseline", "only"]) == 0
    assert history_main(["list", "--dir", store_dir]) == 0


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def test_history_server_ui_smoke(tmp_path):
    from spark_rapids_tpu.tools.historyd import HistoryServer
    store = HistoryStore(str(tmp_path / "store"))
    store.append_run(_write_log(str(tmp_path / "a.jsonl"), "run-a",
                                wall=1.0, stats=_BASE_STATS,
                                skew_rows=[5, 5, 200, 5]))
    store.append_run(_write_log(str(tmp_path / "b.jsonl"), "run-b",
                                wall=2.0, stats=_BASE_STATS,
                                skew_rows=[5, 5, 200, 5]))
    run_sentinel(store, candidate="run-b", baseline="run-a")

    srv = HistoryServer(store, port=0).start()
    try:
        assert srv.port > 0  # ephemeral bind
        st, body = _get(srv.url + "/")
        assert st == 200 and "run-a" in body and "run-b" in body
        assert "<svg" in body  # trend sparkline (two runs)

        st, body = _get(srv.url + "/app/run-a")
        assert st == 200 and "/app/run-a/query/1" in body

        st, body = _get(srv.url + "/app/run-a/query/1")
        assert st == 200
        assert "TpuHashAggregateExec" in body and "self-time" in body
        assert "shuffle skew" in body  # the v7 table renders

        st, body = _get(srv.url + "/diff?a=run-a&b=run-b")
        assert st == 200

        st, body = _get(srv.url + "/healthz")
        assert st == 200 and json.loads(body)["runs_indexed"] == 2

        st, body = _get(srv.url + "/metrics")
        assert st == 200
        assert "spark_rapids_tpu_history_runs_indexed 2" in body
        assert "spark_rapids_tpu_history_store_bytes" in body
        assert 'outcome="regressed"' in body

        st, _body = _get(srv.url + "/app/no-such-run")
        assert st == 404
        st, _body = _get(srv.url + "/nope")
        assert st == 404
    finally:
        srv.stop()


def test_shuffle_skew_record_schema_v7_pin():
    """The skew pin: shuffle_skew is registered at exactly schema 7
    (the writer has since moved on — v8 fault/recovery, v9 oom_retry), and
    the summary math the exchanges feed from (utils/metrics.py)
    produces the pinned stat keys."""
    from spark_rapids_tpu.tools.eventlog import (RECORD_TYPES,
                                                 SCHEMA_VERSION)
    from spark_rapids_tpu.utils.metrics import (build_skew_record,
                                                skew_summary)
    assert SCHEMA_VERSION == 12
    assert RECORD_TYPES["shuffle_skew"] == 7
    assert max(RECORD_TYPES.values()) == SCHEMA_VERSION

    s = skew_summary([10, 10, 300, 10])
    assert set(s) == {"min", "p50", "max", "mean", "imbalance"}
    assert s["min"] == 10 and s["max"] == 300
    assert s["imbalance"] == pytest.approx(300 / 82.5)
    rec = build_skew_record([10, 10, 300, 10], [80, 80, 2400, 80])
    assert set(rec) == {"partitions", "rows", "bytes",
                        "per_partition_rows"}
    assert rec["partitions"] == 4
    assert rec["per_partition_rows"] == [10, 10, 300, 10]
    # degenerate inputs stay well-formed (imbalance 1.0 = balanced)
    assert skew_summary([])["imbalance"] == 1.0


def test_session_close_appends_run(tmp_path):
    """Integration: a session with spark.rapids.tpu.history.dir appends
    its run on close; a fresh store over the same directory lists it and
    replays per-query detail including v7 skew records."""
    from spark_rapids_tpu.expr.functions import col, sum as f_sum
    from spark_rapids_tpu.session import TpuSession
    store_dir = str(tmp_path / "store")
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path / "logs"),
        "spark.rapids.tpu.history.dir": store_dir,
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.shuffle.partitions": 2,
        "spark.rapids.tpu.shuffle.mode": "host",
    })
    rng = np.random.default_rng(5)
    df = sess.create_dataframe(pd.DataFrame({
        "g": rng.integers(0, 5, 300).astype(np.int64),
        "x": rng.normal(size=300)}), num_partitions=2)
    df.group_by("g").agg(f_sum(col("x")).alias("sx")).collect(device=True)
    sess.close()

    store = HistoryStore(store_dir)
    apps = store.apps()
    assert len(apps) == 1
    h = apps[0]
    assert h["n_queries"] == 1 and h["schema_version"] == 12
    app = store.load(h["app_id"])
    (q,) = app.queries.values()
    assert q.nodes  # plan replays
    assert q.shuffle_skew  # the host group-by shuffle emitted v7 records


def test_memory_gate_needs_relative_and_absolute_growth():
    """The sentinel's peak-memory gate: >10% AND >=1MiB. Tiny queries
    jitter past 10% run-to-run, so the relative gate alone would flag
    clean back-to-back runs."""
    from spark_rapids_tpu.tools.compare import (
        MEM_PEAK_FLAG_MIN_BYTES, memory_delta)
    # 20% growth but only bytes: noise, must not flag
    _, flagged = memory_delta({"peak_bytes": 20_000, "spill_bytes": 0},
                              {"peak_bytes": 24_000, "spill_bytes": 0})
    assert flagged == []
    # 20% growth and past the absolute floor: flags
    base = 100 * MEM_PEAK_FLAG_MIN_BYTES
    _, flagged = memory_delta({"peak_bytes": base, "spill_bytes": 0},
                              {"peak_bytes": int(base * 1.2),
                               "spill_bytes": 0})
    assert flagged == ["peak_bytes"]
    # big absolute delta but under 10% relative: must not flag either
    _, flagged = memory_delta({"peak_bytes": base, "spill_bytes": 0},
                              {"peak_bytes": int(base * 1.05),
                               "spill_bytes": 0})
    assert flagged == []
