"""Memory/spill framework tests (reference analogues: RapidsBufferCatalogSuite,
RapidsDeviceMemoryStoreSuite, RapidsDiskStoreSuite, GpuSemaphore tests)."""
import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import DeviceTable, HostTable
from spark_rapids_tpu.memory import (BufferCatalog, SpillPriorities,
                                     StorageTier, TpuSemaphore)


def _table(n=64, seed=0):
    rng = np.random.default_rng(seed)
    t = pa.table({"a": rng.integers(0, 100, n), "b": rng.uniform(0, 1, n),
                  "s": [f"str{i}" for i in range(n)]})
    return DeviceTable.from_host(HostTable.from_arrow(t), min_bucket=8)


def test_register_acquire_roundtrip():
    cat = BufferCatalog(device_limit=1 << 30, host_limit=1 << 30)
    t = _table()
    h = cat.register(t)
    assert h.tier == StorageTier.DEVICE
    got = h.get()
    assert got.to_host().to_arrow().equals(t.to_host().to_arrow())
    h.close()
    assert cat.stats()["buffers"] == 0


def test_spill_to_host_and_restore():
    t1 = _table(seed=1)
    nbytes = t1.nbytes()
    cat = BufferCatalog(device_limit=int(nbytes * 1.5), host_limit=1 << 30)
    h1 = cat.register(t1, SpillPriorities.INPUT)
    t2 = _table(seed=2)
    h2 = cat.register(t2, SpillPriorities.ACTIVE_ON_DECK)
    # t1 (lower priority) must have spilled to host
    assert h1.tier == StorageTier.HOST
    assert h2.tier == StorageTier.DEVICE
    assert cat.spill_count[StorageTier.HOST] == 1
    # restoring t1 pushes t2 out
    got1 = h1.get()
    assert got1.to_host().to_arrow().equals(t1.to_host().to_arrow())
    assert h1.tier == StorageTier.DEVICE


def test_spill_to_disk_and_restore(tmp_path):
    t1 = _table(seed=3)
    nbytes = t1.nbytes()
    cat = BufferCatalog(device_limit=int(nbytes * 1.5),
                        host_limit=int(nbytes * 1.5),
                        disk_dir=str(tmp_path))
    h1 = cat.register(t1)
    h2 = cat.register(_table(seed=4))
    h3 = cat.register(_table(seed=5))
    tiers = sorted([h1.tier, h2.tier, h3.tier])
    assert tiers == [StorageTier.DEVICE, StorageTier.HOST, StorageTier.DISK]
    assert cat.spill_count[StorageTier.DISK] >= 1
    got1 = h1.get()
    assert got1.to_host().to_arrow().equals(t1.to_host().to_arrow())


def test_priorities_respected():
    t = _table(seed=6)
    nbytes = t.nbytes()
    cat = BufferCatalog(device_limit=int(nbytes * 2.5), host_limit=1 << 30)
    low = cat.register(_table(seed=7), SpillPriorities.INPUT)
    high = cat.register(_table(seed=8), SpillPriorities.BROADCAST)
    cat.register(_table(seed=9), SpillPriorities.ACTIVE_ON_DECK)
    assert low.tier == StorageTier.HOST  # lowest priority spilled first
    assert high.tier == StorageTier.DEVICE


def test_acquired_buffers_not_spilled():
    t = _table(seed=10)
    nbytes = t.nbytes()
    cat = BufferCatalog(device_limit=int(nbytes * 1.5), host_limit=1 << 30)
    h1 = cat.register(t)
    with h1 as acquired:  # pinned while in use
        cat.register(_table(seed=11))
        assert h1.tier == StorageTier.DEVICE
        assert acquired is not None


def test_semaphore_admission():
    sem = TpuSemaphore(1)
    order = []

    def worker(i):
        with sem.held(task_id=i):
            order.append(("in", i))
            import time
            time.sleep(0.02)
            order.append(("out", i))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    # never two tasks inside at once
    depth = 0
    for kind, _ in order:
        depth += 1 if kind == "in" else -1
        assert depth <= 1
    assert sem.acquire_count == 3


def test_semaphore_reentrant():
    sem = TpuSemaphore(1)
    sem.acquire_if_necessary(task_id=7)
    sem.acquire_if_necessary(task_id=7)  # reentrant, no deadlock
    sem.release_if_held(task_id=7)
    sem.release_if_held(task_id=7)
    sem.acquire_if_necessary(task_id=8)
    sem.release_if_held(task_id=8)


def test_pool_mode_none_and_strict():
    """Pool-mode selection (reference: RMM mode selection,
    GpuDeviceManager.scala:224): 'none' never spills on budget, 'strict'
    raises when a registration cannot fit after spilling."""
    import numpy as np
    from spark_rapids_tpu.columnar.host import HostColumn, HostTable
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.device import DeviceTable
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.memory.catalog import BufferCatalog

    def tbl(rows=2048):
        return DeviceTable.from_host(HostTable(
            ["a"], [HostColumn(dt.DOUBLE,
                               np.random.default_rng(0).normal(size=rows))]),
            64)

    none_cat = BufferCatalog(RapidsConf(
        {"spark.rapids.tpu.memory.pool.mode": "none"}),
        device_limit=1000, host_limit=10**6)
    for _ in range(3):
        none_cat.register(tbl())
    assert sum(none_cat.spill_count.values()) == 0  # over budget, no spill

    strict_cat = BufferCatalog(RapidsConf(
        {"spark.rapids.tpu.memory.pool.mode": "strict"}),
        device_limit=1000, host_limit=10**6)
    import pytest as _pytest
    with _pytest.raises(MemoryError, match="strict pool mode"):
        strict_cat.register(tbl())
    # the strict OOM queued a postmortem on the process-global memory
    # profiler; drain it so it doesn't ride into the next test's event log
    from spark_rapids_tpu.utils import memprof
    mp = memprof.active()
    if mp is not None:
        mp.drain_postmortems()
