"""Test config: run on a virtual 8-device CPU mesh (no TPU needed in CI).

Mirrors the reference's approach of testing distributed behavior without a
cluster (SURVEY §4: local-cluster + transport mocks): JAX is forced onto CPU
with 8 virtual devices so sharding/collective paths compile and run.
"""
import os

# NOTE: the environment may pre-set JAX_PLATFORMS (e.g. to a TPU plugin);
# plain env setdefault is not enough — force CPU through jax.config.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def session():
    from spark_rapids_tpu.session import TpuSession
    return TpuSession({
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.shuffle.partitions": 4,
    })


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Compiled-program caches accumulate across the whole suite (every
    jitted kernel x shape combo); XLA's CPU compiler can exhaust memory and
    segfault near the end. Dropping caches between modules keeps peak
    memory bounded while preserving within-module reuse."""
    yield
    import jax
    jax.clear_caches()
    from spark_rapids_tpu.utils.compile_cache import clear_cache
    clear_cache()
    from spark_rapids_tpu.exec.mesh import clear_mesh_programs
    from spark_rapids_tpu.shuffle.ici import clear_exchange_programs
    clear_mesh_programs()
    clear_exchange_programs()


@pytest.fixture(autouse=True, scope="module")
def _drain_oom_telemetry_per_module():
    """OOM-ladder failures queue postmortem/retry records in process-wide
    stores for the event-log writer to fold into the NEXT query. Tests
    that exercise the ladder outside a query would otherwise leak those
    records into whichever module logs a query next — drain between
    modules so each starts clean."""
    yield
    from spark_rapids_tpu.memory.retry import reset_retry_state
    from spark_rapids_tpu.utils.memprof import active
    reset_retry_state()
    mp = active()
    if mp is not None:
        mp.drain_postmortems()


@pytest.fixture(autouse=True, scope="module")
def _drain_degradation_state_per_module():
    """The degradation layer's quarantine store, fallback ledger and
    deadline state are process-wide by design (exec/fallback.py,
    utils/deadline.py). A module that drove operators into quarantine
    would otherwise poison the NEXT module's planning (its operators
    silently route to host) — reset between modules, and restore the
    production defaults for the sticky fallback.* config."""
    yield
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.exec.fallback import (configure_fallback,
                                                reset_fallback_state)
    from spark_rapids_tpu.utils.deadline import reset_deadline
    reset_fallback_state()
    configure_fallback(RapidsConf({}))
    reset_deadline()


@pytest.fixture(autouse=True, scope="module")
def _drain_shuffle_observatory_per_module():
    """The shuffle observatory is process-wide and installed by whichever
    session configured it last (shuffle/telemetry.py). A module that
    turned it on would otherwise keep every later module's transfers
    recording — and its per-query accumulators would leak into the next
    module's shuffle_summary records. Reset between modules so the
    default (off, zero-overhead) state is restored."""
    yield
    from spark_rapids_tpu.shuffle.telemetry import reset_shuffle_telemetry
    reset_shuffle_telemetry()


@pytest.fixture(autouse=True, scope="module")
def _drain_movement_state_per_module():
    """The movement ledger is process-wide and installed by whichever
    session configured it last (utils/movement.py). A module that turned
    the observatory on would otherwise keep every later module's funnels
    recording — and its per-query accumulators would leak into the next
    module's movement_summary records. Clear the ledger between modules
    so the default (off, zero-overhead) state is restored."""
    yield
    from spark_rapids_tpu.utils.movement import reset_movement
    reset_movement()
