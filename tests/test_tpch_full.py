"""All 22 TPC-H queries at tiny scale: device path vs CPU engine differential
(reference analogue: integration_tests qa_nightly_select_test.py — the whole
query surface run on both engines and compared), plus independent pandas
cross-checks for a sample of queries.
"""
import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_tpu.tools import tpch
from harness import assert_tables_equal

# queries whose final sort fully determines row order (compare ordered)
_ORDERED = {"q1", "q4", "q5", "q7", "q8", "q9", "q12", "q13", "q15", "q16",
            "q20", "q22"}
# queries with limit-after-sort where ties make the cut nondeterministic
# across engines; compare only sorted numeric columns
_LIMITED = {"q2", "q3", "q10", "q18", "q21"}


@pytest.fixture(scope="module")
def tables():
    return tpch.gen_all(0, tiny=True)


@pytest.fixture(scope="module")
def dfs(session, tables):
    return tpch.build_dataframes(session, tables, num_partitions=2)


# the heaviest queries (multi-join, 8-17s each) run in the slow tier;
# tier-1 keeps the rest. q3/q5 land here too — both still run (device,
# both async modes) every tier-1 pass via tests/test_async_exec.py
_HEAVY = {"q2", "q3", "q5", "q7", "q9", "q10", "q16", "q18", "q21"}


@pytest.mark.parametrize(
    "name",
    [q if q not in _HEAVY else pytest.param(q, marks=pytest.mark.slow)
     for q in sorted(tpch.QUERIES, key=lambda q: int(q[1:]))])
def test_query_device_vs_cpu(dfs, name):
    q = tpch.QUERIES[name](dfs)
    device = q.collect(device=True)
    cpu = q.collect(device=False)
    if name in _LIMITED:
        assert device.num_rows == cpu.num_rows
        assert device.column_names == cpu.column_names
        for cname in device.column_names:
            field = device.schema.field(cname)
            if pa.types.is_floating(field.type):
                np.testing.assert_allclose(
                    np.sort(device.column(cname).to_numpy(zero_copy_only=False)),
                    np.sort(cpu.column(cname).to_numpy(zero_copy_only=False)),
                    rtol=1e-9)
    else:
        assert_tables_equal(device, cpu, ignore_order=name not in _ORDERED,
                            rel_tol=1e-9)


def _pdf(tables, name):
    df = tables[name].to_pandas()
    for col in tables[name].column_names:
        if pa.types.is_date32(tables[name].schema.field(col).type):
            df[col] = tables[name].column(col).combine_chunks() \
                .cast(pa.int32()).to_numpy()
    return df


def test_q4_pandas(session, tables, dfs):
    out = tpch.q4(dfs).collect(device=False).to_pandas()
    o = _pdf(tables, "orders")
    li = _pdf(tables, "lineitem")
    o = o[(o.o_orderdate >= 8582) & (o.o_orderdate < 8674)]
    late = li[li.l_commitdate < li.l_receiptdate].l_orderkey.unique()
    o = o[o.o_orderkey.isin(late)]
    exp = o.groupby("o_orderpriority").size().sort_index()
    got = out.set_index("o_orderpriority")["order_count"].sort_index()
    assert (got == exp).all() and len(got) == len(exp)


def test_q5_pandas(session, tables, dfs):
    out = tpch.q5(dfs).collect(device=False).to_pandas()
    c, o, li = (_pdf(tables, n) for n in ("customer", "orders", "lineitem"))
    s, n, r = (_pdf(tables, n) for n in ("supplier", "nation", "region"))
    o = o[(o.o_orderdate >= 8766) & (o.o_orderdate < 9131)]
    j = (c.merge(o, left_on="c_custkey", right_on="o_custkey")
          .merge(li, left_on="o_orderkey", right_on="l_orderkey")
          .merge(s, left_on="l_suppkey", right_on="s_suppkey"))
    j = j[j.c_nationkey == j.s_nationkey]
    j = j.merge(n, left_on="s_nationkey", right_on="n_nationkey") \
         .merge(r, left_on="n_regionkey", right_on="r_regionkey")
    j = j[j.r_name == "ASIA"]
    j["rev"] = j.l_extendedprice * (1 - j.l_discount)
    exp = j.groupby("n_name").rev.sum().sort_values(ascending=False)
    got = out.set_index("n_name")["revenue"]
    assert list(got.index) == list(exp.index)
    np.testing.assert_allclose(got.to_numpy(), exp.to_numpy(), rtol=1e-9)


def test_q13_pandas(session, tables, dfs):
    out = tpch.q13(dfs).collect(device=False).to_pandas()
    c = _pdf(tables, "customer")
    o = _pdf(tables, "orders")
    o = o[~o.o_comment.str.contains("special.*requests")]
    cnt = o.groupby("o_custkey").size()
    c_count = c.c_custkey.map(cnt).fillna(0).astype(int)
    exp = c_count.value_counts().sort_index()
    got = out.set_index("c_count")["custdist"].sort_index()
    assert (got == exp).all() and len(got) == len(exp)


def test_q14_pandas(session, tables, dfs):
    out = tpch.q14(dfs).collect(device=False)
    li = _pdf(tables, "lineitem")
    p = _pdf(tables, "part")
    li = li[(li.l_shipdate >= 9374) & (li.l_shipdate < 9404)]
    j = li.merge(p, left_on="l_partkey", right_on="p_partkey")
    j["rev"] = j.l_extendedprice * (1 - j.l_discount)
    promo = j.loc[j.p_type.str.startswith("PROMO"), "rev"].sum()
    exp = 100.0 * promo / j.rev.sum()
    assert out.column("promo_revenue")[0].as_py() == pytest.approx(exp, rel=1e-9)


def test_q19_pandas(session, tables, dfs):
    out = tpch.q19(dfs).collect(device=False)
    li = _pdf(tables, "lineitem")
    p = _pdf(tables, "part")
    li = li[li.l_shipmode.isin(["AIR", "AIR REG"])
            & (li.l_shipinstruct == "DELIVER IN PERSON")]
    j = li.merge(p, left_on="l_partkey", right_on="p_partkey")
    c1 = ((j.p_brand == "Brand#12")
          & j.p_container.isin(["SM CASE", "SM BOX", "SM PACK", "SM PKG"])
          & j.l_quantity.between(1, 11) & j.p_size.between(1, 5))
    c2 = ((j.p_brand == "Brand#23")
          & j.p_container.isin(["MED BAG", "MED BOX", "MED PKG", "MED PACK"])
          & j.l_quantity.between(10, 20) & j.p_size.between(1, 10))
    c3 = ((j.p_brand == "Brand#34")
          & j.p_container.isin(["LG CASE", "LG BOX", "LG PACK", "LG PKG"])
          & j.l_quantity.between(20, 30) & j.p_size.between(1, 15))
    j = j[c1 | c2 | c3]
    exp = (j.l_extendedprice * (1 - j.l_discount)).sum()
    got = out.column("revenue")[0].as_py()
    if got is None:
        assert exp == 0
    else:
        assert got == pytest.approx(exp, rel=1e-9)


def test_q22_pandas(session, tables, dfs):
    out = tpch.q22(dfs).collect(device=False).to_pandas()
    c = _pdf(tables, "customer")
    o = _pdf(tables, "orders")
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    c = c[c.c_phone.str[:2].isin(codes)]
    avg_bal = c.loc[c.c_acctbal > 0, "c_acctbal"].mean()
    c = c[c.c_acctbal > avg_bal]
    c = c[~c.c_custkey.isin(o.o_custkey)]
    exp = c.groupby(c.c_phone.str[:2]).agg(
        numcust=("c_acctbal", "size"), tot=("c_acctbal", "sum"))
    got = out.set_index("cntrycode").sort_index()
    assert (got["numcust"] == exp["numcust"].sort_index()).all()
    np.testing.assert_allclose(got["totacctbal"].to_numpy(),
                               exp["tot"].sort_index().to_numpy(), rtol=1e-9)


def test_distinct(session, tables):
    df = session.create_dataframe(tables["lineitem"], num_partitions=2)
    d = df.select("l_returnflag", "l_linestatus").distinct()
    device = d.collect(device=True)
    cpu = d.collect(device=False)
    assert_tables_equal(device, cpu)
    pdf = tables["lineitem"].to_pandas()
    exp = pdf[["l_returnflag", "l_linestatus"]].drop_duplicates()
    assert device.num_rows == len(exp)
