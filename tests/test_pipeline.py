"""Pipelined multi-partition execution engine (parallel/pipeline.py).

Covers the PR 3 acceptance contract:
- pipelined and sequential modes return identical results (TPC-H smoke
  queries + shuffle/broadcast paths),
- an injected mid-stream operator exception surfaces as the SAME
  exception (never a hang) with the originating stage context attached,
- no leaked worker threads / bounded-queue shutdown after
  ``session.close()``,
- the tier-1 queue lint: every prefetch queue in the package is bounded,
- pipelineWait / prefetchQueueDepth metrics flow into the event log and
  are ranked by tools/diagnose.py,
- input donation (donate_argnums) and the byte-based coalesce goal.
"""
import json
import threading
import time
import warnings

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.parallel import pipeline as P
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.tools import tpch

ROWS = 8_000


@pytest.fixture(scope="module")
def lineitem():
    return tpch.gen_lineitem(0, seed=11, rows=ROWS)


@pytest.fixture(scope="module")
def orders():
    return tpch.gen_orders(0, seed=12, rows=2_000)


@pytest.fixture(scope="module")
def customer():
    return tpch.gen_customer(0, seed=13, rows=500)


def _session(pipelined: bool, **extra):
    # TpuSession.__init__ applies the pipeline conf process-wide
    # (configure_pipeline), so build the session right before collecting
    return TpuSession({
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.shuffle.partitions": 4,
        "spark.rapids.tpu.pipeline.enabled": pipelined,
        **extra,
    })


def _sorted_pandas(tbl: pa.Table):
    df = tbl.to_pandas()
    return df.sort_values(list(df.columns)).reset_index(drop=True)


# ---------------------------------------------------------------------------
# correctness parity: pipelined == sequential (rows + ordering semantics)
# ---------------------------------------------------------------------------
def _run_mode(build_query, pipelined: bool, device: bool):
    sess = _session(pipelined)
    try:
        return build_query(sess).collect(device=device)
    finally:
        sess.close()


@pytest.mark.parametrize("qname", ["q1", "q6"])
@pytest.mark.parametrize("device", [True, False])
def test_tpch_smoke_parity(qname, device, lineitem):
    def build(sess):
        df = sess.create_dataframe(lineitem, num_partitions=4)
        return getattr(tpch, qname)({"lineitem": df})

    pipe = _run_mode(build, True, device)
    seq = _run_mode(build, False, device)
    # q1 is ordered (sort by returnflag/linestatus): compare positionally
    assert pipe.num_rows == seq.num_rows
    pd_pipe = pipe.to_pandas().reset_index(drop=True)
    pd_seq = seq.to_pandas().reset_index(drop=True)
    for col in pd_seq.columns:
        if pd_seq[col].dtype.kind in "fc":
            np.testing.assert_allclose(pd_pipe[col], pd_seq[col], rtol=1e-9)
        else:
            assert (pd_pipe[col].astype(str) == pd_seq[col].astype(str)).all()


@pytest.mark.parametrize("device", [True, False])
def test_shuffle_and_broadcast_parity(device, lineitem, orders, customer):
    """q3 exercises the broadcast + shuffled join paths and a sorted
    limit; a plain group-by exercises the exchange tiers."""
    def q3(sess):
        return tpch.q3({
            "lineitem": sess.create_dataframe(lineitem, num_partitions=4),
            "orders": sess.create_dataframe(orders, num_partitions=2),
            "customer": sess.create_dataframe(customer)})

    pipe = _run_mode(q3, True, device)
    seq = _run_mode(q3, False, device)
    np.testing.assert_allclose(
        np.sort(pipe.column("revenue").to_numpy(zero_copy_only=False)),
        np.sort(seq.column("revenue").to_numpy(zero_copy_only=False)),
        rtol=1e-9)

    from spark_rapids_tpu.expr.functions import col, sum as s_

    def grouped(sess):
        df = sess.create_dataframe(lineitem, num_partitions=4)
        return df.group_by("l_returnflag").agg(
            s_(col("l_quantity")).alias("q"))

    gp = _sorted_pandas(_run_mode(grouped, True, device))
    gs = _sorted_pandas(_run_mode(grouped, False, device))
    np.testing.assert_allclose(gp["q"], gs["q"], rtol=1e-9)
    assert (gp["l_returnflag"] == gs["l_returnflag"]).all()


# ---------------------------------------------------------------------------
# failure propagation: same exception, no hang, stage context attached
# ---------------------------------------------------------------------------
class _Injected(ValueError):
    pass


def test_midstream_exception_surfaces_not_hangs(lineitem):
    from spark_rapids_tpu.columnar import dtypes as dt

    sess = _session(True)
    try:
        df = sess.create_dataframe(lineitem, num_partitions=4)

        def bad(it):
            for i, pdf in enumerate(it):
                raise _Injected("boom from operator")
                yield pdf  # pragma: no cover

        q = df.map_in_pandas(bad, {"l_orderkey": dt.LONG})
        t0 = time.monotonic()
        with pytest.raises(_Injected, match="boom from operator"):
            q.collect()
        assert time.monotonic() - t0 < 60, "error took hang-like time"
    finally:
        sess.close()
    assert P.active_workers() == 0


def test_prefetched_propagates_original_exception_with_context():
    def make_iter():
        yield 1
        raise _Injected("stage blew up")

    it = P.prefetched(make_iter, stage="unit:test")
    assert next(it) == 1
    with pytest.raises(_Injected, match="stage blew up") as ei:
        next(it)
    assert "unit:test" in getattr(ei.value, "pipeline_context", ())


def test_prefetched_carries_input_file_holder_across_threads():
    from spark_rapids_tpu.io.file_block import (clear_input_file,
                                                current_input_file,
                                                set_input_file)

    def make_iter():
        for i in range(3):
            set_input_file(f"file{i}.parquet", i, 10)
            yield i

    clear_input_file()
    seen = []
    for item in P.prefetched(make_iter, stage="unit:file"):
        seen.append((item, current_input_file()[0]))
    assert seen == [(0, "file0.parquet"), (1, "file1.parquet"),
                    (2, "file2.parquet")]


# ---------------------------------------------------------------------------
# shutdown: no leaked threads, queues drained, abandoned iterators reaped
# ---------------------------------------------------------------------------
def test_no_leaked_threads_after_close(lineitem):
    from spark_rapids_tpu.expr.functions import col, sum as s_

    before = {t.name for t in threading.enumerate()}
    sess = _session(True)
    df = sess.create_dataframe(lineitem, num_partitions=4)
    df.group_by("l_returnflag").agg(
        s_(col("l_quantity")).alias("q")).collect(device=True)

    # abandon a prefetched iterator mid-stream: close() must reap it
    it = P.prefetched(iter, stage="unit:abandoned", depth=1)  # type: ignore[arg-type]

    def slow():
        for i in range(100):
            time.sleep(0.01)
            yield i

    it = P.prefetched(slow, stage="unit:abandoned", depth=1)
    assert next(it) == 0
    del it
    sess.close()
    deadline = time.monotonic() + 10
    while P.active_workers() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert P.active_workers() == 0
    lingering = {t.name for t in threading.enumerate()} - before
    assert not [n for n in lingering if n.startswith("tpu-prefetch")
                or n.startswith("tpu-pipeline")], lingering


# ---------------------------------------------------------------------------
# tier-1 lint: every prefetch queue in the package must be bounded
# ---------------------------------------------------------------------------
def test_lint_no_unbounded_queues():
    """Migrated into the srtpu-analyze framework (PR 6): the AST-based
    thread checker subsumes the old regex lint. The queue-bound contract
    stays ABSOLUTE — no baseline allowance, no suppressions: an unbounded
    queue at a stage boundary silently re-materializes whole partitions
    in memory."""
    import pathlib

    import spark_rapids_tpu
    from spark_rapids_tpu.tools.analyze import analyze_paths

    pkg = pathlib.Path(spark_rapids_tpu.__file__).parent
    report = analyze_paths([str(pkg)], checks=["thread"])
    offenders = [f.render() for f in report.findings + report.suppressed
                 if f.rule == "thread-unbounded-queue"]
    assert not offenders, offenders
    # the lint is live: a seeded unbounded queue must be caught
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        bad = pathlib.Path(d) / "bad.py"
        bad.write_text("import queue\nq = queue.Queue()\n")
        seeded = analyze_paths([str(bad)], checks=["thread"])
        assert any(f.rule == "thread-unbounded-queue"
                   for f in seeded.findings)
    assert "maxsize" in (pkg / "parallel" / "pipeline.py").read_text()


# ---------------------------------------------------------------------------
# observability: metrics land in the event log; diagnose ranks stalls
# ---------------------------------------------------------------------------
def test_pipeline_metrics_in_event_log_and_trace(tmp_path, lineitem):
    from spark_rapids_tpu.expr.functions import col, sum as s_
    from spark_rapids_tpu.utils.tracing import get_tracer

    sess = _session(True, **{
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.trace.enabled": True,
    })
    try:
        get_tracer().clear()
        df = sess.create_dataframe(lineitem, num_partitions=4)
        df.group_by("l_returnflag").agg(
            s_(col("l_quantity")).alias("q")).collect(device=True)
        events = get_tracer().events()
    finally:
        sess.close()
        get_tracer().enabled = False

    # pipelineWait / prefetchQueueDepth on at least one node record
    logs = list(tmp_path.glob("*.jsonl"))
    assert logs
    waits, depths = [], []
    for line in logs[0].read_text().splitlines():
        rec = json.loads(line)
        if rec.get("event") == "node":
            m = rec.get("metrics") or {}
            if "pipelineWait" in m:
                waits.append(rec["name"])
            if "prefetchQueueDepth" in m:
                depths.append(rec["name"])
    assert waits, "no node recorded pipelineWait"
    assert depths, "no node recorded prefetchQueueDepth"

    # trace shows pipeline spans AND genuinely overlapped work: two spans
    # on different threads whose time windows intersect
    assert any(e.cat == "pipeline" for e in events)
    spans = [e for e in events if e.ph == "X" and e.dur > 0]
    overlapped = any(
        a.tid != b.tid and a.ts < b.ts + b.dur and b.ts < a.ts + a.dur
        for i, a in enumerate(spans) for b in spans[i + 1:i + 60])
    assert overlapped, "no overlapping spans across threads in the trace"


def test_diagnose_ranks_pipeline_stalls(tmp_path):
    from spark_rapids_tpu.tools.diagnose import diagnose_path

    records = [
        {"event": "app_start", "app_id": "a", "schema_version": 3,
         "ts": 0.0, "conf": {}},
        {"event": "query_start", "query_id": 1, "ts": 0.0, "plan": "p"},
        {"event": "node", "query_id": 1, "node_id": 0, "parent_id": -1,
         "name": "TpuWholeStage[Project+Filter]", "desc": "", "depth": 0,
         "wall_s": 0.9, "rows": 1000, "batches": 4, "t_first": 0.0,
         "t_last": 0.9, "metrics": {
             "pipelineWait": 0.5,
             "prefetchQueueDepth": {"count": 4, "sum": 0.0, "min": 0.0,
                                    "max": 0.0, "p50": 0.0, "p90": 0.0,
                                    "p99": 0.0}}},
        {"event": "query_end", "query_id": 1, "ts": 1.0, "wall_s": 1.0,
         "final_plan": "p", "aqe_events": [], "spill_count": {},
         "semaphore_wait_s": 0.0, "stats": {}},
        {"event": "app_end", "ts": 1.0},
    ]
    path = tmp_path / "stall.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in records) + "\n")
    rep = diagnose_path(str(path))
    finds = rep.queries[0].findings
    stall = [f for f in finds if f.metric == "pipelineWait"]
    assert stall, [f.metric for f in finds]
    assert "prefetchDepth" in stall[0].suggestion
    assert "queue depth p50=0" in stall[0].detail


# ---------------------------------------------------------------------------
# input donation + byte-based coalesce goal
# ---------------------------------------------------------------------------
def test_donation_entry_point_and_metric(lineitem):
    from spark_rapids_tpu.exec.wholestage import TpuWholeStageExec
    from spark_rapids_tpu.expr.functions import col

    with warnings.catch_warnings():
        # XLA:CPU ignores the donation request with a warning; forcing it
        # here exercises the donating entry point end to end
        warnings.simplefilter("ignore")
        sess = _session(True, **{
            "spark.rapids.tpu.donation.force": True,
            "spark.rapids.tpu.scan.deviceCache.enabled": False,
        })
        try:
            df = sess.create_dataframe(lineitem, num_partitions=2)
            q = df.filter(col("l_quantity") > 10.0).select(
                (col("l_extendedprice") * 0.5).alias("half"))
            plan = sess._physical(q.logical, True)
            ws = [n for n in _walk(plan) if isinstance(n, TpuWholeStageExec)]
            assert ws and all(w.donate_inputs for w in ws)
            out = [b for p in range(plan.num_partitions)
                   for b in plan.execute(p)]
            donated = sum(w.metrics.snapshot().get("donatedBytes", 0)
                          for w in ws)
            assert donated > 0
            # parity against the non-donating run
            seq = _run_mode(
                lambda s: s.create_dataframe(lineitem, num_partitions=2)
                .filter(col("l_quantity") > 10.0)
                .select((col("l_extendedprice") * 0.5).alias("half")),
                False, True)
            import pyarrow as _pa
            got = _pa.concat_tables([t.to_arrow() for t in out])
            np.testing.assert_allclose(
                np.sort(got.column("half").to_numpy(zero_copy_only=False)),
                np.sort(seq.column("half").to_numpy(zero_copy_only=False)),
                rtol=1e-7)
        finally:
            sess.close()


def test_cached_uploads_are_never_donated(lineitem):
    """The scan device cache retains uploads; donating them would corrupt
    the next execution. Exclusive marks must only appear when caching is
    off / declined."""
    from spark_rapids_tpu.columnar.host import HostTable
    from spark_rapids_tpu.exec.transitions import (HostToDeviceExec,
                                                   take_exclusive)
    from spark_rapids_tpu.plan.physical import CpuScanExec
    from spark_rapids_tpu.io.memory import InMemorySource

    src = CpuScanExec(InMemorySource(lineitem.select(["l_quantity"]), 1))
    cached = HostToDeviceExec(src, min_bucket=8, cache_max_bytes=1 << 30)
    for b in cached.execute_columnar(0):
        assert not take_exclusive(b), "cached upload marked exclusive"
    uncached = HostToDeviceExec(src, min_bucket=8, cache_max_bytes=0)
    for b in uncached.execute_columnar(0):
        assert take_exclusive(b), "uncached upload must be exclusive"
        assert not take_exclusive(b), "exclusivity must be consumed once"


def test_cache_retained_batches_are_not_donated(lineitem):
    """df.cache() retains the very DeviceTable objects it yields; the
    cache node must consume the exclusive mark so a donating fused stage
    above it never frees buffers the cache re-serves."""
    from spark_rapids_tpu.exec.wholestage import TpuWholeStageExec
    from spark_rapids_tpu.expr.functions import col

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        sess = _session(True, **{
            "spark.rapids.tpu.donation.force": True,
            "spark.rapids.tpu.scan.deviceCache.enabled": False,
        })
        try:
            df = sess.create_dataframe(lineitem, num_partitions=2).cache()
            q = df.select((col("l_extendedprice") * 0.5).alias("half"))
            first = q.collect(device=True)
            plan = sess._physical(q.logical, True)
            ws = [n for n in _walk(plan) if isinstance(n, TpuWholeStageExec)]
            out = [b for p in range(plan.num_partitions)
                   for b in plan.execute(p)]
            assert sum(int(t.num_rows) for t in out) == ROWS
            donated = sum(w.metrics.snapshot().get("donatedBytes", 0)
                          for w in ws)
            assert donated == 0, "donated a cache-retained batch"
            # the cached second execution must still serve intact data
            second = q.collect(device=True)
            np.testing.assert_allclose(
                np.sort(first.column("half").to_numpy(zero_copy_only=False)),
                np.sort(second.column("half").to_numpy(zero_copy_only=False)),
                rtol=0)
        finally:
            sess.close()


def test_coalesce_bytes_target():
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.device import DeviceTable
    from spark_rapids_tpu.columnar.host import HostColumn, HostTable
    from spark_rapids_tpu.exec.transitions import TpuCoalesceBatchesExec
    from spark_rapids_tpu.plan.schema import Field, Schema

    tables = []
    for i in range(6):
        vals = np.arange(64, dtype=np.float64) + 100 * i
        ht = HostTable(["x"], [HostColumn(dt.DOUBLE, vals)])
        tables.append(DeviceTable.from_host(ht, 8))
    per_batch = tables[0].nbytes()

    class _Src:
        children = ()
        schema = Schema([Field("x", dt.DOUBLE, False)])
        num_partitions = 1

        def execute_columnar(self, pidx):
            yield from tables

    # rows goal alone would coalesce everything into one flush; the byte
    # goal forces flushes of ~2 batches each (wide-schema OOM guard)
    node = TpuCoalesceBatchesExec(_Src(), target_rows=1 << 30,
                                  min_bucket=8,
                                  target_bytes=2 * per_batch)
    out = list(node.execute_columnar(0))
    assert 2 <= len(out) < 6, [int(t.num_rows) for t in out]
    assert sum(int(t.num_rows) for t in out) == 6 * 64
    snap = node.metrics.snapshot()
    assert snap.get("coalescedBytes", 0) > 0
    assert "bytes=" in node.node_desc()

    # without the byte goal: single flush (row goal never reached)
    node2 = TpuCoalesceBatchesExec(_Src(), target_rows=1 << 30, min_bucket=8)
    assert len(list(node2.execute_columnar(0))) == 1


def test_coalesce_after_upload_conf_wiring(lineitem):
    from spark_rapids_tpu.exec.transitions import TpuCoalesceBatchesExec
    from spark_rapids_tpu.expr.functions import col

    sess = _session(True, **{
        "spark.rapids.tpu.coalesce.afterUpload.enabled": True,
        "spark.rapids.tpu.coalesce.targetBytes": 1 << 20,
    })
    try:
        df = sess.create_dataframe(lineitem, num_partitions=2)
        q = df.select((col("l_quantity") + 1.0).alias("qq"))
        plan = sess._physical(q.logical, True)
        nodes = [n for n in _walk(plan)
                 if isinstance(n, TpuCoalesceBatchesExec)]
        assert nodes, "coalesce.afterUpload did not insert the exec"
        assert all(n.target_bytes == 1 << 20 for n in nodes)
        got = q.collect(device=True)
        assert got.num_rows == ROWS
    finally:
        sess.close()


def _walk(plan):
    yield plan
    for c in plan.children:
        yield from _walk(c)


# ---------------------------------------------------------------------------
# conf plumbing / sequential fallback
# ---------------------------------------------------------------------------
def test_pipeline_conf_snapshot():
    sess = _session(False, **{
        "spark.rapids.tpu.pipeline.prefetchDepth": 7,
        "spark.rapids.tpu.pipeline.taskPool": 3,
    })
    try:
        assert not P.pipeline_enabled()
        assert P.prefetch_depth() == 7
        assert P.task_pool_size() == 3
        # maybe_prefetched degrades to the plain iterator when off
        it = P.maybe_prefetched(lambda: iter([1, 2]), stage="unit:off")
        assert list(it) == [1, 2]
        assert P.active_workers() == 0
    finally:
        sess.close()
        TpuSession({"spark.rapids.tpu.pipeline.enabled": True}).close()
