"""input_file_name()/input_file_block_start()/length() tests
(reference: GpuInputFileName + InputFileBlockRule.scala — the rule forces
the PERFILE reader because coalesced batches lose file attribution)."""
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.expr.functions import (col, input_file_block_length,
                                             input_file_block_start,
                                             input_file_name)


@pytest.fixture
def files(tmp_path):
    paths = []
    for i in range(3):
        t = pa.table({"k": np.arange(i * 10, (i + 1) * 10, dtype=np.int64)})
        p = str(tmp_path / f"part-{i}.parquet")
        pq.write_table(t, p)
        paths.append(p)
    return paths


def _sess(**extra):
    conf = {"spark.rapids.tpu.shuffle.mode": "host"}
    conf.update(extra)
    return TpuSession(conf)


def test_input_file_name_per_file(files):
    sess = _sess()
    df = _sess().read_parquet(files)
    q = df.select(col("k"), input_file_name().alias("f"))
    for device in (False, True):
        out = q.collect(device=device).to_pandas()
        assert len(out) == 30
        for _, row in out.iterrows():
            expected_file = files[int(row.k) // 10]
            assert row.f == expected_file, (device, row.k, row.f)


def test_input_file_block_fields(files):
    df = _sess().read_parquet(files[0])
    q = df.select(input_file_name().alias("f"),
                  input_file_block_start().alias("s"),
                  input_file_block_length().alias("l"))
    out = q.collect(device=False)
    assert set(out.column("f").to_pylist()) == {files[0]}
    assert set(out.column("s").to_pylist()) == {0}
    assert set(out.column("l").to_pylist()) == {os.path.getsize(files[0])}


def test_rule_forces_perfile_reader(files):
    """COALESCING would merge the three files into one batch; the
    InputFileBlockRule analogue must switch the scan to PERFILE."""
    sess = _sess(**{"spark.rapids.sql.format.parquet.reader.type":
                    "COALESCING"})
    df = sess.read_parquet(files)
    q = df.select(col("k"), input_file_name().alias("f"))
    plan = sess._physical(q.logical, False)
    text = plan.tree_string()
    assert "PERFILE" in text, text
    out = q.collect(device=False).to_pandas()
    assert all(out.f[i] == files[int(out.k[i]) // 10]
               for i in range(len(out)))
    # without the file expr the reader choice is untouched
    plan2 = sess._physical(df.select("k").logical, False)
    assert "COALESCING" in plan2.tree_string()


def test_in_memory_source_yields_empty_name():
    sess = _sess()
    df = sess.create_dataframe(pa.table({"a": [1, 2, 3]}))
    out = df.select(input_file_name().alias("f")).collect(device=False)
    assert out.column("f").to_pylist() == ["", "", ""]


def test_post_shuffle_attribution_is_cleared(files):
    """Rows of a shuffled partition come from many files: Spark's
    input_file_name() returns "" after an exchange, and so does ours."""
    sess = _sess(**{"spark.rapids.tpu.shuffle.partitions": 4})
    df = sess.read_parquet(files)
    q = df.group_by("k").count().select(input_file_name().alias("f"))
    for device in (False, True):
        out = q.collect(device=device)
        assert set(out.column("f").to_pylist()) == {""}, (device, out)


def test_range_source_has_no_file(files):
    sess = _sess()
    # poison the holder via a prior scan, then read from range
    list(sess.read_parquet(files[0]).collect(device=False).column("k"))
    out = sess.range(5).select(input_file_name().alias("f")) \
        .collect(device=False)
    assert set(out.column("f").to_pylist()) == {""}


def test_filter_on_input_file_name(files):
    sess = _sess()
    df = sess.read_parquet(files)
    q = df.filter(input_file_name() == files[1]).select("k")
    out = sorted(q.collect(device=False).column("k").to_pylist())
    assert out == list(range(10, 20))
