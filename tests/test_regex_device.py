

def test_regexp_replace_group_refs(session):
    """$n group references run on device over the group-plan subset
    (reference: GpuRegExpReplace, stringFunctions.scala:895)."""
    import re as _re

    import pyarrow as pa

    from spark_rapids_tpu.expr.functions import col, regexp_replace
    data = ["abc-123 def-456", "x-1", "nope", "", "zz-99 a-1 b-22",
            "tail abc-7", "-", "ab-12cd-34"]
    df = session.create_dataframe(pa.table({"s": data}))
    cases = [(r"([a-z]+)-(\d+)", "$2:$1"),
             (r"([a-z]+)-(\d+)", "[$0]"),
             (r"([a-z]+)-(\d+)", "$1"),
             (r"([a-z]+)-(\d+)", r"\$$2"),
             (r"([a-z]+)-(\d+)", "<$1-$2>")]
    for pat, repl in cases:
        q = df.select(regexp_replace(col("s"), pat, repl).alias("r"))
        dev = q.collect(device=True).column("r").to_pylist()
        cpu = q.collect(device=False).column("r").to_pylist()
        pyrep = _re.sub(r"\$(\d+)", r"\\g<\1>",
                        repl.replace("\\$", "\0")).replace("\0", "$")
        exp = [_re.sub(pat, pyrep, s) for s in data]
        assert dev == exp, (pat, repl, dev, exp)
        assert cpu == exp, (pat, repl)
    # alternation pattern + refs: falls back, still correct
    q = df.select(regexp_replace(col("s"), r"(ab|zz)-(\d+)", "$2").alias("r"))
    assert q.collect(device=True).column("r").to_pylist() \
        == q.collect(device=False).column("r").to_pylist()
