"""Expand (rollup/cube/grouping sets), TakeOrderedAndProject, CollectLimit,
and Sample exec nodes (reference: GpuExpandExec.scala, limit.scala,
GpuPoissonSampler; exec rules in GpuOverrides.scala:3481ff)."""
import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.expr.functions as F
from spark_rapids_tpu.expr.functions import col, lit
from harness import assert_tpu_cpu_equal, data_gen


def _has_node(plan, cls_name: str) -> bool:
    from spark_rapids_tpu.plan.aqe import AdaptiveExec
    if isinstance(plan, AdaptiveExec):
        plan = plan.final_plan()
    if type(plan).__name__ == cls_name:
        return True
    kids = list(plan.children)
    for attr in ("inner", "stage"):  # AQE stage leaves/readers hide subtrees
        sub = getattr(plan, attr, None)
        if sub is not None:
            kids.append(sub)
    kids.extend(getattr(plan, "chain", ()))  # whole-stage fused nodes
    return any(_has_node(c, cls_name) for c in kids)


@pytest.fixture
def gdata(session, rng):
    t = data_gen(rng, 300, {"a": ("int32", 0, 4), "b": ("int64", 0, 3),
                            "v": "float64", "s": "string"})
    return session.create_dataframe(t, num_partitions=2)


def test_rollup_device(session, gdata):
    q = gdata.rollup("a", "b").agg(F.sum(col("v")).alias("s"),
                                   F.count_star().alias("c"))
    plan = session._physical(q.logical, True)
    assert _has_node(plan, "TpuExpandExec") \
        or "Expand" in plan.tree_string(), plan.tree_string()
    assert_tpu_cpu_equal(q)


def test_cube_device(session, gdata):
    assert_tpu_cpu_equal(
        gdata.cube("a", "b").agg(F.avg(col("v")).alias("m")))


def test_grouping_sets(session, gdata):
    q = gdata.grouping_sets([["a"], ["b"], []], "a", "b") \
        .agg(F.min(col("v")).alias("lo"), F.max(col("v")).alias("hi"))
    out = assert_tpu_cpu_equal(q)
    # one row per distinct a (NULL data included) + same for b + grand total
    import pyarrow.compute as pc
    base = gdata.collect(device=False)
    n_a = len(pc.unique(base.column("a")))
    n_b = len(pc.unique(base.column("b")))
    assert out.num_rows == n_a + n_b + 1


def test_rollup_string_grouping_null_literal(session, gdata):
    # rollup over a string column exercises device null string literals
    assert_tpu_cpu_equal(
        gdata.rollup("s", "a").agg(F.count_star().alias("c")))


def test_rollup_distinguishes_real_nulls(session):
    # a NULL data value groups separately from the aggregated-away marker
    t = pa.table({"a": [1, None, 1, None], "v": [1.0, 2.0, 3.0, 4.0]})
    df = session.create_dataframe(t)
    out = assert_tpu_cpu_equal(df.rollup("a").agg(F.sum(col("v")).alias("s")))
    rows = sorted(out.to_pylist(), key=lambda r: (r["a"] is None, r["a"] or 0,
                                                  r["s"]))
    # groups: a=1 (4.0), a=NULL (6.0), total (10.0)
    assert [r["s"] for r in rows] == [4.0, 6.0, 10.0]


def test_take_ordered_device(session, rng):
    t = data_gen(rng, 400, {"k": "int64", "v": "float64", "s": "string"})
    df = session.create_dataframe(t, num_partitions=3)
    q = df.sort(col("v")).limit(7)
    plan = session._physical(q.logical, True)
    assert _has_node(plan, "TpuTakeOrderedExec"), plan.tree_string()
    assert not _has_node(plan, "TpuSortExec")
    assert_tpu_cpu_equal(q, ignore_order=False)
    # descending, string key, nulls present
    assert_tpu_cpu_equal(df.sort(col("s"), ascending=False).limit(9),
                         ignore_order=False)


def test_take_ordered_n_larger_than_data(session, rng):
    t = data_gen(rng, 30, {"v": "float64"})
    df = session.create_dataframe(t, num_partitions=2)
    out = assert_tpu_cpu_equal(df.sort(col("v")).limit(1000),
                               ignore_order=False)
    assert out.num_rows == 30


def test_collect_limit_device(session, rng):
    t = data_gen(rng, 200, {"v": "float64"})
    df = session.create_dataframe(t, num_partitions=3)
    q = df.limit(17)
    plan = session._physical(q.logical, True)
    assert _has_node(plan, "CpuCollectLimitExec") \
        or _has_node(plan, "TpuLocalLimitExec"), plan.tree_string()
    assert q.collect(device=True).num_rows == 17
    assert q.collect(device=False).num_rows == 17


def test_sample_deterministic_and_differential(session, rng):
    t = pa.table({"k": np.arange(1500, dtype=np.int64)})
    df = session.create_dataframe(t, num_partitions=3)
    q = df.sample(0.25, seed=11)
    plan = session._physical(q.logical, True)
    assert _has_node(plan, "TpuSampleExec"), plan.tree_string()
    out = assert_tpu_cpu_equal(q)  # bit-for-bit: same rows both engines
    frac = out.num_rows / 1500
    assert 0.18 < frac < 0.32
    # same seed -> same rows; different seed -> (almost surely) different
    again = df.sample(0.25, seed=11).collect(device=True)
    assert sorted(again.column("k").to_pylist()) \
        == sorted(out.column("k").to_pylist())
    other = df.sample(0.25, seed=12).collect(device=True)
    assert sorted(other.column("k").to_pylist()) \
        != sorted(out.column("k").to_pylist())


def test_sample_after_filter_positions_agree(session, rng):
    t = data_gen(rng, 800, {"k": "int64", "v": "float64"}, null_prob=0.1)
    df = session.create_dataframe(t, num_partitions=2)
    assert_tpu_cpu_equal(df.filter(col("v") > lit(0.0)).sample(0.5, seed=3))


def test_sample_fraction_bounds(session):
    df = session.create_dataframe(pa.table({"a": [1, 2]}))
    with pytest.raises(ValueError):
        df.sample(1.5, seed=1)
    assert df.sample(0.0, seed=1).collect().num_rows == 0
    assert df.sample(1.0, seed=1).collect().num_rows == 2


def test_rollup_aggregates_grouping_column(session):
    """Spark: rollup('a').agg(sum('a')) sums REAL values even in rows where
    'a' is aggregated away — the Expand keeps an un-nulled input copy."""
    df = session.create_dataframe(pa.table({"a": [1, 2, 3]}))
    q = df.rollup("a").agg(F.sum(col("a")).alias("s"),
                           F.count(col("a")).alias("c"))
    out = assert_tpu_cpu_equal(q)
    rows = sorted(out.to_pylist(),
                  key=lambda r: (r["a"] is None, r["a"] or 0))
    assert rows[-1] == {"a": None, "s": 6, "c": 3}
