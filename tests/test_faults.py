"""Chaos suite: the fault-injection framework + end-to-end recovery.

The contract under test (docs/fault_tolerance.md): injected faults are
deterministic and conf-gated (zero overhead when off); every recovery
surface — worker supervision/resubmission, transport retry, spill CRC →
recompute, shuffle fetch-failed → recompute — yields exactly the
uninjected answer (parity) or a structured error naming the fault
(never a hang)."""
import threading

import numpy as np
import pytest

from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.utils import faults
from spark_rapids_tpu.utils.faults import (FaultInjectedError, FaultInjector,
                                           configure_faults)

_FAULT_CONF = {
    "spark.rapids.tpu.faults.enabled": "true",
    "spark.rapids.tpu.faults.seed": "7",
}


@pytest.fixture(autouse=True)
def _pristine_faults():
    """Every test starts and ends with injection off and the recovery
    ledger zeroed — the injector is process-global by design."""
    faults.reset_faults()
    faults.reset_recovery()
    yield
    faults.reset_faults()
    faults.reset_recovery()


def _conf(spec, **extra):
    vals = dict(_FAULT_CONF)
    vals["spark.rapids.tpu.faults.spec"] = spec
    vals.update({k: str(v) for k, v in extra.items()})
    return vals


# ---------------------------------------------------------------------------
# injector semantics
# ---------------------------------------------------------------------------
def test_spec_validation_rejects_typos():
    with pytest.raises(ValueError, match="unknown fault point"):
        FaultInjector("shuffel.fetch")
    with pytest.raises(ValueError, match="unknown fault clause key"):
        FaultInjector("shuffle.fetch:chance=0.5")
    with pytest.raises(ValueError, match="unknown fault action"):
        FaultInjector("shuffle.fetch:action=explode")
    with pytest.raises(ValueError, match="not in"):
        FaultInjector("shuffle.fetch:p=1.5")
    with pytest.raises(ValueError, match="not key=value"):
        FaultInjector("shuffle.fetch:p")


def test_injector_is_deterministic_and_streams_are_independent():
    spec = "tcp.connect:p=0.3;spill.read:p=0.3"

    def run(seed, order):
        inj = FaultInjector(spec, seed=seed)
        return [(p, inj.fire(p) is not None) for p in order]

    interleaved = ["tcp.connect", "spill.read"] * 50
    grouped = ["tcp.connect"] * 50 + ["spill.read"] * 50
    a = dict_of_streams(run(7, interleaved))
    # same seed, same per-point decision sequence regardless of how the
    # points interleave (each point owns its RNG stream)
    b = dict_of_streams(run(7, grouped))
    assert a == b
    # a different seed (e.g. another worker's seed offset) decorrelates
    c = dict_of_streams(run(8, interleaved))
    assert a != c
    # and re-running the same seed reproduces exactly
    assert dict_of_streams(run(7, interleaved)) == a


def dict_of_streams(pairs):
    out = {}
    for point, fired in pairs:
        out.setdefault(point, []).append(fired)
    return out


def test_times_after_and_budget():
    inj = FaultInjector("worker.task:after=2:times=2")
    got = [inj.fire("worker.task") for _ in range(6)]
    assert got == [None, None, "raise", "raise", None, None]
    c = inj.counters()["worker.task"]
    assert c == {"evaluations": 6, "fires": 2}
    recs = inj.drain_records()
    assert [r["evaluation"] for r in recs] == [3, 4]
    assert [r["fire"] for r in recs] == [1, 2]
    assert all(r["point"] == "worker.task" and r["action"] == "raise"
               for r in recs)
    assert inj.drain_records() == []


def test_zero_overhead_pin():
    """With injection disabled, a fault point is ONE module-global
    is-None check. Pin the shape so a refactor cannot quietly put
    parsing, dict lookups, or locks on the disabled path."""
    assert faults.active() is None  # the default: nothing installed
    assert faults.fire("shuffle.fetch") is None
    assert faults.drain_fault_records() == []
    # the fast path reads the module constant FIRST — the first global
    # the function body touches is _INJECTOR, and the disabled branch
    # calls nothing else
    assert faults.fire.__code__.co_names[0] == "_INJECTOR"
    # disabled conf clears any previously-installed injector
    configure_faults(RapidsConf(_conf("shuffle.fetch")))
    assert faults.active() is not None
    configure_faults(RapidsConf({}))
    assert faults.active() is None


def test_configure_faults_seed_offset_decorrelates_workers():
    spec = _conf("worker.task:p=0.4")
    w0 = configure_faults(RapidsConf(spec), seed_offset=0)
    s0 = [w0.fire("worker.task") is not None for _ in range(40)]
    w1 = configure_faults(RapidsConf(spec), seed_offset=1)
    s1 = [w1.fire("worker.task") is not None for _ in range(40)]
    assert s0 != s1
    w0b = configure_faults(RapidsConf(spec), seed_offset=0)
    assert [w0b.fire("worker.task") is not None for _ in range(40)] == s0


def test_fault_error_names_point_and_action():
    e = FaultInjectedError("spill.read", "corrupt")
    assert e.point == "spill.read" and e.action == "corrupt"
    assert "spill.read" in str(e) and "corrupt" in str(e)


def test_recovery_ledger_and_stats_source():
    faults.note_recovery("transport_retries")
    faults.note_recovery("transport_retries")
    faults.note_recovery("some_new_mechanism")  # unknown keys register
    assert faults.recovery_counters()["transport_retries"] == 2
    assert faults.recovery_counters()["some_new_mechanism"] == 1
    stats = faults.faults_stats()
    assert stats["transport_retries"] == 2
    configure_faults(RapidsConf(_conf("tcp.read:times=1")))
    faults.fire("tcp.read")
    assert faults.faults_stats()["injected_tcp_read"] == 1
    # the gauge reaches /metrics through the default stats sources
    from spark_rapids_tpu.utils.metrics import get_stats
    collected = get_stats().collect()
    assert collected.get("faults_transport_retries") == 2.0
    faults.reset_recovery()
    assert faults.recovery_counters()["transport_retries"] == 0


def test_delay_action_is_latency_only():
    configure_faults(RapidsConf(_conf(
        "shuffle.fetch:action=delay:latency_ms=1")))
    assert faults.fire("shuffle.fetch") == "delay"


# ---------------------------------------------------------------------------
# spill integrity: CRC32 on write, verified on restore
# ---------------------------------------------------------------------------
def _stored_table(buffer_id=1):
    from spark_rapids_tpu.columnar import dtypes as _dt
    from spark_rapids_tpu.columnar.device import DeviceTable
    from spark_rapids_tpu.columnar.host import HostColumn, HostTable
    from spark_rapids_tpu.memory.stores import HostStore, StoredTable
    host = HostTable(["v"], [HostColumn(
        _dt.LONG, np.arange(64, dtype=np.int64))])
    table = DeviceTable.from_host(host, min_bucket=8)
    stored = StoredTable(buffer_id, table, priority=0, size_bytes=1024)
    HostStore(1 << 20).put(stored)
    return stored


@pytest.mark.parametrize("direct", [True, False])
def test_spill_corrupt_action_is_caught_on_restore(tmp_path, direct):
    from spark_rapids_tpu.memory.stores import DiskStore, \
        SpillCorruptionError
    configure_faults(RapidsConf(_conf("spill.write:action=corrupt:times=1")))
    store = DiskStore(str(tmp_path / ("d" if direct else "z")),
                      direct=direct)
    stored = _stored_table()
    store.put(stored)  # the injected action flips a byte AFTER the CRC
    with pytest.raises(SpillCorruptionError, match="integrity check"):
        store.load(stored)
    assert faults.recovery_counters()["spill_corruptions"] >= 1
    # an uncorrupted spill round-trips and verifies clean
    faults.reset_faults()
    clean = _stored_table(buffer_id=2)
    store.put(clean)
    arrays = store.load(clean)
    assert "col0.data" in arrays


@pytest.mark.parametrize("direct", [True, False])
def test_spill_roundtrip_without_checksum_still_works(tmp_path, direct):
    from spark_rapids_tpu.memory.stores import DiskStore
    store = DiskStore(str(tmp_path), direct=direct, checksum=False)
    stored = _stored_table()
    store.put(stored)
    assert "col0.data" in store.load(stored)
    store.drop(stored)
    assert store.used_bytes == 0


def test_spill_read_injection_surfaces_as_corruption(tmp_path):
    from spark_rapids_tpu.memory.stores import DiskStore, \
        SpillCorruptionError
    store = DiskStore(str(tmp_path), direct=True)
    stored = _stored_table()
    store.put(stored)
    configure_faults(RapidsConf(_conf("spill.read:times=1")))
    with pytest.raises(SpillCorruptionError, match="spill.read"):
        store.load(stored)
    # bounded: the next restore succeeds (times=1 exhausted)
    assert "col0.data" in store.load(stored)


# ---------------------------------------------------------------------------
# shuffle manager: injected fetch failures recover through recompute
# ---------------------------------------------------------------------------
def _host_table(vals, keys):
    from spark_rapids_tpu.columnar import dtypes as _dt
    from spark_rapids_tpu.columnar.host import HostColumn, HostTable
    return HostTable(["k", "v"], [
        HostColumn(_dt.LONG, np.asarray(keys, dtype=np.int64)),
        HostColumn(_dt.LONG, np.asarray(vals, dtype=np.int64))])


def _manager_rows(conf_extra, spec=None):
    """Write 2 map outputs, read every reduce partition back (with a
    recompute hook), return the sorted row multiset."""
    from spark_rapids_tpu.columnar.device import DeviceTable
    from spark_rapids_tpu.shuffle.manager import ShuffleManager
    from spark_rapids_tpu.shuffle.transport import LocalShuffleTransport
    if spec is not None:
        configure_faults(RapidsConf(_conf(spec)))
    mgr = ShuffleManager(RapidsConf(conf_extra),
                         transport=LocalShuffleTransport())
    sid = mgr.new_shuffle_id()
    tables = {m: _host_table(np.arange(m * 10, m * 10 + 10),
                             np.arange(10) % 3) for m in range(2)}

    def write(m):
        mgr.write_partition(sid, m, iter([DeviceTable.from_host(
            tables[m], min_bucket=8)]), ["k"], 3)

    for m in tables:
        write(m)
    rows = []
    for r in range(3):
        for t in mgr.read_partition(sid, 2, r, min_bucket=8,
                                    recompute=write):
            h = t.to_host()
            rows.extend(zip(h.column("k").values.tolist(),
                            h.column("v").values.tolist()))
    return sorted(rows)


def test_manager_injected_fetch_failures_recompute_to_parity():
    baseline = _manager_rows({"spark.rapids.tpu.shuffle.cacheWrites": "off"})
    # recompute is once-per-map, so each injected failure must land on a
    # fresh map: a deterministic single shot, then a probabilistic one
    for spec in ("shuffle.fetch:times=1", "shuffle.fetch:p=0.4:times=1"):
        faults.reset_faults()
        faults.reset_recovery()
        chaotic = _manager_rows(
            {"spark.rapids.tpu.shuffle.cacheWrites": "off"}, spec=spec)
        assert chaotic == baseline
        assert faults.recovery_counters()["shuffle_recomputes"] >= 1


def test_manager_cached_tier_injected_miss_recomputes_to_parity():
    baseline = _manager_rows({})
    faults.reset_faults()
    faults.reset_recovery()
    chaotic = _manager_rows({}, spec="shuffle.fetch:times=1")
    assert chaotic == baseline
    assert faults.recovery_counters()["shuffle_recomputes"] >= 1


def test_manager_publish_fault_surfaces_structured():
    from spark_rapids_tpu.columnar.device import DeviceTable
    from spark_rapids_tpu.shuffle.manager import ShuffleManager
    from spark_rapids_tpu.shuffle.transport import LocalShuffleTransport
    configure_faults(RapidsConf(_conf("shuffle.publish:times=1")))
    mgr = ShuffleManager(RapidsConf({}), transport=LocalShuffleTransport())
    sid = mgr.new_shuffle_id()
    with pytest.raises(FaultInjectedError, match="shuffle.publish"):
        mgr.write_partition(sid, 0, iter([DeviceTable.from_host(
            _host_table([1], [0]), min_bucket=8)]), ["k"], 1)


# ---------------------------------------------------------------------------
# TCP transport: transient socket errors retry to parity
# ---------------------------------------------------------------------------
def test_tcp_transient_socket_errors_retry_to_parity():
    from spark_rapids_tpu.shuffle.serializer import deserialize_table, \
        serialize_table
    from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport
    from spark_rapids_tpu.shuffle.transport import BlockId
    conf = RapidsConf({
        "spark.rapids.tpu.shuffle.tcp.retryBackoffMs": "5",
        "spark.rapids.tpu.shuffle.tcp.retryMaxBackoffMs": "20",
    })
    a = TcpShuffleTransport(conf)
    b = TcpShuffleTransport(conf)
    try:
        b.add_peer(*a.address)
        payload = serialize_table(_host_table([1, 2, 3], [0, 1, 2]))
        a.publish(BlockId(5, 0, 0), payload)
        # first connect attempt AND first read attempt fail; the retry
        # loop must deliver the identical payload anyway
        configure_faults(RapidsConf(_conf(
            "tcp.connect:times=1;tcp.read:times=1")))
        got = dict(b.fetch([BlockId(5, 0, 0)]))
        assert deserialize_table(got[BlockId(5, 0, 0)]) \
            .column("v").values.tolist() == [1, 2, 3]
        assert faults.recovery_counters()["transport_retries"] >= 1
        assert faults.recovery_counters()["transport_giveups"] == 0
    finally:
        a.close()
        b.close()


def test_tcp_exhausted_retries_become_fetch_failed_not_hang():
    from spark_rapids_tpu.shuffle.serializer import serialize_table
    from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport
    from spark_rapids_tpu.shuffle.transport import BlockId, \
        ShuffleFetchFailedException
    conf = RapidsConf({
        "spark.rapids.tpu.shuffle.tcp.retryAttempts": "2",
        "spark.rapids.tpu.shuffle.tcp.retryBackoffMs": "5",
        "spark.rapids.tpu.shuffle.tcp.retryMaxBackoffMs": "10",
    })
    a = TcpShuffleTransport(conf)
    b = TcpShuffleTransport(conf)
    try:
        b.add_peer(*a.address)
        a.publish(BlockId(6, 0, 0), serialize_table(
            _host_table([1], [0])))
        configure_faults(RapidsConf(_conf("tcp.connect")))  # always
        with pytest.raises(ShuffleFetchFailedException):
            list(b.fetch([BlockId(6, 0, 0)]))
        assert faults.recovery_counters()["transport_giveups"] >= 1
    finally:
        a.close()
        b.close()


def test_tcp_missing_block_is_definitive_not_retried():
    """A live peer answering found=0 must NOT consume the retry budget —
    the miss goes straight to fetch-failed -> recompute."""
    from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport
    from spark_rapids_tpu.shuffle.transport import BlockId, \
        ShuffleFetchFailedException
    a = TcpShuffleTransport()
    b = TcpShuffleTransport()
    try:
        b.add_peer(*a.address)
        with pytest.raises(ShuffleFetchFailedException):
            list(b.fetch([BlockId(9, 9, 9)]))
        assert faults.recovery_counters()["transport_retries"] == 0
    finally:
        a.close()
        b.close()


def test_host_block_store_bounds_provider_reserves():
    """A crash-looping lazy provider is re-registered at most
    maxProviderRetries times, then the block reports missing (->
    fetch-failed -> recompute) instead of pinning its inputs forever."""
    from spark_rapids_tpu.shuffle.tcp import _HostBlockStore
    from spark_rapids_tpu.shuffle.transport import BlockId
    store = _HostBlockStore(1 << 20, max_provider_retries=3)
    block = BlockId(1, 0, 0)
    calls = []

    def bad_provider():
        calls.append(1)
        raise RuntimeError("serialization keeps failing")

    store.put_lazy(block, bad_provider)
    for _ in range(5):  # ask more times than the budget allows
        try:
            store.length(block)
        except RuntimeError:
            continue
    assert len(calls) == 3      # bounded: budget consumed, then dropped
    assert store.length(block) is None   # missing, no further calls
    assert len(calls) == 3
    # a provider that recovers clears its retry count on success
    good = BlockId(1, 1, 0)
    flaky = {"n": 0}

    def flaky_provider():
        flaky["n"] += 1
        if flaky["n"] == 1:
            raise RuntimeError("transient")
        return b"payload"

    store.put_lazy(good, flaky_provider)
    try:
        store.length(good)
    except RuntimeError:
        pass
    assert store.length(good) == len(b"payload")
    assert good not in store._provider_retries


# ---------------------------------------------------------------------------
# event-log + replay integration (schema v8)
# ---------------------------------------------------------------------------
def test_eventlog_recovery_record_null_when_disabled(tmp_path):
    from spark_rapids_tpu.tools.eventlog import EventLogWriter, \
        load_event_log

    class _Plan:
        children = ()

        def tree_string(self):
            return "plan"

        def release_spill_handles(self):
            pass

    w = EventLogWriter(str(tmp_path), "app-clean", {})
    w.run_query(_Plan(), lambda: 42)
    w.close()
    app = load_event_log(w.path)
    assert app.query(1).recovery is None
    assert app.query(1).faults == []
    assert app.health_check() == []


def test_eventlog_fault_and_recovery_records(tmp_path):
    from spark_rapids_tpu.tools.diagnose import diagnose_path
    from spark_rapids_tpu.tools.eventlog import EventLogWriter, \
        load_event_log

    class _Plan:
        children = ()

        def tree_string(self):
            return "plan"

        def release_spill_handles(self):
            pass

    configure_faults(RapidsConf(_conf("h2d.upload:times=1")))

    def collect():
        faults.fire("h2d.upload")
        faults.note_recovery("transport_retries", 3)
        faults.note_recovery("shuffle_recomputes")
        return 1

    w = EventLogWriter(str(tmp_path), "app-chaos", {})
    w.run_query(_Plan(), collect)

    # error path: recovery-so-far is still persisted before the raise
    def boom():
        faults.note_recovery("spill_corruptions")
        raise RuntimeError("query died")

    with pytest.raises(RuntimeError):
        w.run_query(_Plan(), boom)
    w.close()

    app = load_event_log(w.path)
    q1 = app.query(1)
    assert q1.recovery == {"transport_retries": 3, "shuffle_recomputes": 1}
    assert [f["point"] for f in q1.faults] == ["h2d.upload"]
    assert q1.faults[0]["action"] == "raise"
    q2 = app.query(2)
    assert q2.error and q2.recovery == {"spill_corruptions": 1}
    warnings = app.health_check()
    assert any("recovered from failures" in s for s in warnings)
    # diagnose surfaces the recovery ledger as ranked findings
    rep = diagnose_path(w.path)
    metrics = [f.metric for q in rep.queries for f in q.findings]
    assert "transportRetries" in metrics


# ---------------------------------------------------------------------------
# worker supervision: kills, resubmission, structured exhaustion
# ---------------------------------------------------------------------------
def _thread_names():
    return {t.name for t in threading.enumerate()
            if t is not threading.main_thread()}


def test_worker_kill_resubmits_and_query_reaches_parity():
    """Acceptance pin: a worker killed mid-query (injected worker.task
    kill) yields exactly the uninjected sequential answer — supervision
    detects the death, respawns/excludes, and resubmits the orphaned
    partition tasks."""
    from spark_rapids_tpu.parallel.runtime import (ProcessCluster,
                                                   _query_plan)
    from spark_rapids_tpu.columnar.host import HostTable

    # 2 output partitions keep the fan-out to one task per worker, so
    # the kill below lands on exactly one process across the whole run
    shuffle = {"spark.rapids.tpu.shuffle.partitions": "2"}
    # sequential (uninjected) reference, built in-process with the same
    # plan cache the workers use
    _sess, plan = _query_plan("q1", 0.01, True, 2, dict(shuffle))
    parts = []
    for pidx in range(plan.num_partitions):
        parts.extend(plan.execute(pidx))
    expected = HostTable.concat(parts).to_arrow()

    # after=1 lets worker 0's first task through (the plan-partition
    # probe), then its partition task dies mid-query
    conf = _conf("worker.task:after=1:times=1:action=kill",
                 **{"spark.rapids.tpu.task.timeout": 120,
                    "spark.rapids.tpu.task.heartbeatInterval": 0.5,
                    "spark.rapids.tpu.task.heartbeatTimeout": 60,
                    **shuffle})
    before = _thread_names()
    with ProcessCluster(2, conf=conf) as cluster:
        got = cluster.run_tpch_query("q1", sf=0.01, tiny=True,
                                     num_partitions=2, timeout_s=120)
    # supervision noted the death + resubmission in the driver's ledger
    assert faults.recovery_counters()["worker_deaths"] >= 1
    assert faults.recovery_counters()["task_resubmissions"] >= 1
    assert got.num_rows == expected.num_rows
    key = [(c, "ascending") for c in expected.column_names]
    assert got.sort_by(key).equals(expected.sort_by(key))
    # supervision leaves no non-daemon driver threads behind after close
    leaked = [t for t in threading.enumerate()
              if t is not threading.main_thread()
              and t.name not in before and not t.daemon]
    assert not leaked, leaked


def test_exhausted_max_failures_is_structured_not_a_hang():
    """Every submitted task dies (kill on every evaluation) with
    respawn disabled: the task must fail FAST with a TaskFailedError
    naming the injected fault and the exhausted conf — the old behavior
    was a silent 300s hang."""
    from spark_rapids_tpu.parallel.runtime import (ProcessCluster,
                                                   TaskFailedError,
                                                   trace_probe_task)
    conf = _conf("worker.task:action=kill",
                 **{"spark.rapids.tpu.task.maxFailures": 2,
                    "spark.rapids.tpu.task.respawnWorkers": "false",
                    "spark.rapids.tpu.task.timeout": 60})
    with ProcessCluster(2, conf=conf) as cluster:
        with pytest.raises(TaskFailedError) as ei:
            cluster.run_on(0, trace_probe_task, timeout_s=60)
    e = ei.value
    msg = str(e)
    assert "maxFailures=2" in msg or "no live workers" in msg
    assert e.attempts >= 1 and e.task_id is not None
    assert e.history, "failure history missing from the structured error"
    assert e.fault and "worker.task" in e.fault, \
        f"error does not name the injected fault: {msg}"
    assert faults.recovery_counters()["task_failures"] >= 1
    assert faults.recovery_counters()["worker_exclusions"] >= 1


@pytest.mark.slow
def test_worker_kill_parity_q3_q5():
    """The full acceptance matrix: join-heavy TPC-H queries reach exact
    parity through a mid-query worker kill."""
    from spark_rapids_tpu.columnar.host import HostTable
    from spark_rapids_tpu.parallel.runtime import (ProcessCluster,
                                                   _query_plan)
    shuffle = {"spark.rapids.tpu.shuffle.partitions": "2"}
    for query in ("q3", "q5"):
        faults.reset_faults()
        faults.reset_recovery()
        _sess, plan = _query_plan(query, 0.01, True, 2, dict(shuffle))
        parts = []
        for pidx in range(plan.num_partitions):
            parts.extend(plan.execute(pidx))
        expected = HostTable.concat(parts).to_arrow()
        conf = _conf("worker.task:after=1:times=1:action=kill",
                     **{"spark.rapids.tpu.task.timeout": 240,
                        "spark.rapids.tpu.task.heartbeatInterval": 0.5,
                        **shuffle})
        with ProcessCluster(2, conf=conf) as cluster:
            got = cluster.run_tpch_query(query, sf=0.01, tiny=True,
                                         num_partitions=2, timeout_s=240)
        assert faults.recovery_counters()["worker_deaths"] >= 1
        key = [(c, "ascending") for c in expected.column_names]
        assert got.sort_by(key).equals(expected.sort_by(key)), query
