"""Bitwise expressions, get_json_object, mapInPandas (reference:
bitwise.scala, GpuGetJsonObject.scala, GpuMapInPandasExec)."""
import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.expr.functions import (bitwise_not, col,
                                             get_json_object, lit, shiftleft,
                                             shiftright, shiftrightunsigned)

from harness import assert_tpu_cpu_equal


@pytest.fixture
def sess():
    return TpuSession({"spark.rapids.tpu.shuffle.mode": "host"})


def test_bitwise_and_or_xor_not(sess):
    rng = np.random.default_rng(4)
    df = sess.create_dataframe(pd.DataFrame({
        "a": rng.integers(-1000, 1000, 500).astype(np.int64),
        "b": rng.integers(-1000, 1000, 500).astype(np.int64),
    }), num_partitions=2)
    q = df.select(
        col("a").bitwiseAND(col("b")).alias("band"),
        col("a").bitwiseOR(col("b")).alias("bor"),
        col("a").bitwiseXOR(col("b")).alias("bxor"),
        bitwise_not(col("a")).alias("bnot"),
    )
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    pdf = df.collect().to_pandas()
    assert out.column("band").to_pylist() == (pdf.a & pdf.b).tolist()
    assert out.column("bor").to_pylist() == (pdf.a | pdf.b).tolist()
    assert out.column("bxor").to_pylist() == (pdf.a ^ pdf.b).tolist()
    assert out.column("bnot").to_pylist() == (~pdf.a).tolist()
    # boolean & stays logical AND
    qb = df.select(((col("a") > 0) & (col("b") > 0)).alias("both"))
    got = assert_tpu_cpu_equal(qb, ignore_order=False)
    assert got.column("both").to_pylist() == \
        ((pdf.a > 0) & (pdf.b > 0)).tolist()


def test_shifts_mask_like_java(sess):
    df = sess.create_dataframe(pd.DataFrame({
        "v": np.array([1, -8, 1 << 40, -1], dtype=np.int64),
        "s": np.array([1, 2, 65, 63], dtype=np.int32),
    }))
    q = df.select(shiftleft(col("v"), col("s")).alias("sl"),
                  shiftright(col("v"), col("s")).alias("sr"),
                  shiftrightunsigned(col("v"), col("s")).alias("sru"))
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    # shift 65 on a long masks to 1 (Java semantics)
    assert out.column("sl").to_pylist()[2] == (1 << 40) << 1
    assert out.column("sr").to_pylist()[1] == -8 >> 2
    assert out.column("sru").to_pylist()[3] == 1  # -1 >>> 63


def test_get_json_object(sess):
    docs = ['{"a": {"b": 1}, "arr": [10, 20]}',
            '{"a": "plain"}',
            'not json',
            None,
            '{"a": {"b": {"c": "deep"}}}']
    df = sess.create_dataframe(pa.table({"j": docs}))
    q = df.select(get_json_object(col("j"), "$.a.b").alias("ab"),
                  get_json_object(col("j"), "$.arr[1]").alias("a1"),
                  get_json_object(col("j"), "$.a.b.c").alias("abc"))
    out = q.collect(device=True)
    assert out.column("ab").to_pylist() == ["1", None, None, None,
                                            '{"c":"deep"}']
    assert out.column("a1").to_pylist() == ["20", None, None, None, None]
    assert out.column("abc").to_pylist() == [None, None, None, None, "deep"]
    assert_tpu_cpu_equal(q, ignore_order=False)


def test_map_in_pandas(sess):
    rng = np.random.default_rng(6)
    df = sess.create_dataframe(pd.DataFrame({
        "k": rng.integers(0, 5, 300).astype(np.int64),
        "v": rng.normal(size=300),
    }), num_partitions=3)

    def double_v(frames):
        for pdf in frames:
            out = pdf.copy()
            out["v2"] = out.v * 2
            yield out[["k", "v2"]]

    q = df.map_in_pandas(double_v, {"k": dt.LONG, "v2": dt.DOUBLE})
    out = assert_tpu_cpu_equal(q)
    pdf = df.collect().to_pandas()
    assert out.num_rows == 300
    assert sorted(out.column("v2").to_pylist()) == pytest.approx(
        sorted((pdf.v * 2).tolist()))


def test_map_in_pandas_casts_to_declared_schema(sess):
    """fn may yield int64 where the declared schema says DOUBLE; the exec
    must cast so downstream device kernels see the declared dtype."""
    df = sess.create_dataframe(pd.DataFrame({"a": [1, 2, 3]}))

    def ints(frames):
        for pdf in frames:
            yield pd.DataFrame({"x": pdf.a * 10})  # int64, schema says DOUBLE

    q = df.map_in_pandas(ints, {"x": dt.DOUBLE})
    out = q.collect(device=True)
    assert str(out.schema.field("x").type) == "double"
    assert out.column("x").to_pylist() == [10.0, 20.0, 30.0]
    assert_tpu_cpu_equal(q)


def test_map_in_pandas_composes_with_engine_ops(sess):
    df = sess.create_dataframe(pd.DataFrame({
        "x": np.arange(100, dtype=np.int64)}), num_partitions=2)

    def add_flag(frames):
        for pdf in frames:
            pdf = pdf.copy()
            pdf["flag"] = pdf.x % 3 == 0
            yield pdf

    q = (df.map_in_pandas(add_flag, {"x": dt.LONG, "flag": dt.BOOLEAN})
           .filter(col("flag"))
           .agg(__import__("spark_rapids_tpu.expr.functions",
                           fromlist=["count_star"]).count_star().alias("n")))
    out = q.collect(device=True)
    assert out.column("n").to_pylist() == [34]
