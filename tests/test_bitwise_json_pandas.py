"""Bitwise expressions, get_json_object, mapInPandas (reference:
bitwise.scala, GpuGetJsonObject.scala, GpuMapInPandasExec)."""
import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.expr.functions import (bitwise_not, col,
                                             get_json_object, lit, shiftleft,
                                             shiftright, shiftrightunsigned)

from harness import assert_tpu_cpu_equal


@pytest.fixture
def sess():
    return TpuSession({"spark.rapids.tpu.shuffle.mode": "host"})


def test_bitwise_and_or_xor_not(sess):
    rng = np.random.default_rng(4)
    df = sess.create_dataframe(pd.DataFrame({
        "a": rng.integers(-1000, 1000, 500).astype(np.int64),
        "b": rng.integers(-1000, 1000, 500).astype(np.int64),
    }), num_partitions=2)
    q = df.select(
        col("a").bitwiseAND(col("b")).alias("band"),
        col("a").bitwiseOR(col("b")).alias("bor"),
        col("a").bitwiseXOR(col("b")).alias("bxor"),
        bitwise_not(col("a")).alias("bnot"),
    )
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    pdf = df.collect().to_pandas()
    assert out.column("band").to_pylist() == (pdf.a & pdf.b).tolist()
    assert out.column("bor").to_pylist() == (pdf.a | pdf.b).tolist()
    assert out.column("bxor").to_pylist() == (pdf.a ^ pdf.b).tolist()
    assert out.column("bnot").to_pylist() == (~pdf.a).tolist()
    # boolean & stays logical AND
    qb = df.select(((col("a") > 0) & (col("b") > 0)).alias("both"))
    got = assert_tpu_cpu_equal(qb, ignore_order=False)
    assert got.column("both").to_pylist() == \
        ((pdf.a > 0) & (pdf.b > 0)).tolist()


def test_shifts_mask_like_java(sess):
    df = sess.create_dataframe(pd.DataFrame({
        "v": np.array([1, -8, 1 << 40, -1], dtype=np.int64),
        "s": np.array([1, 2, 65, 63], dtype=np.int32),
    }))
    q = df.select(shiftleft(col("v"), col("s")).alias("sl"),
                  shiftright(col("v"), col("s")).alias("sr"),
                  shiftrightunsigned(col("v"), col("s")).alias("sru"))
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    # shift 65 on a long masks to 1 (Java semantics)
    assert out.column("sl").to_pylist()[2] == (1 << 40) << 1
    assert out.column("sr").to_pylist()[1] == -8 >> 2
    assert out.column("sru").to_pylist()[3] == 1  # -1 >>> 63


def test_get_json_object(sess):
    docs = ['{"a": {"b": 1}, "arr": [10, 20]}',
            '{"a": "plain"}',
            'not json',
            None,
            '{"a": {"b": {"c": "deep"}}}']
    df = sess.create_dataframe(pa.table({"j": docs}))
    q = df.select(get_json_object(col("j"), "$.a.b").alias("ab"),
                  get_json_object(col("j"), "$.arr[1]").alias("a1"),
                  get_json_object(col("j"), "$.a.b.c").alias("abc"))
    out = q.collect(device=True)
    assert out.column("ab").to_pylist() == ["1", None, None, None,
                                            '{"c":"deep"}']
    assert out.column("a1").to_pylist() == ["20", None, None, None, None]
    assert out.column("abc").to_pylist() == [None, None, None, None, "deep"]
    assert_tpu_cpu_equal(q, ignore_order=False)


def test_map_in_pandas(sess):
    rng = np.random.default_rng(6)
    df = sess.create_dataframe(pd.DataFrame({
        "k": rng.integers(0, 5, 300).astype(np.int64),
        "v": rng.normal(size=300),
    }), num_partitions=3)

    def double_v(frames):
        for pdf in frames:
            out = pdf.copy()
            out["v2"] = out.v * 2
            yield out[["k", "v2"]]

    q = df.map_in_pandas(double_v, {"k": dt.LONG, "v2": dt.DOUBLE})
    out = assert_tpu_cpu_equal(q)
    pdf = df.collect().to_pandas()
    assert out.num_rows == 300
    assert sorted(out.column("v2").to_pylist()) == pytest.approx(
        sorted((pdf.v * 2).tolist()))


def test_map_in_pandas_casts_to_declared_schema(sess):
    """fn may yield int64 where the declared schema says DOUBLE; the exec
    must cast so downstream device kernels see the declared dtype."""
    df = sess.create_dataframe(pd.DataFrame({"a": [1, 2, 3]}))

    def ints(frames):
        for pdf in frames:
            yield pd.DataFrame({"x": pdf.a * 10})  # int64, schema says DOUBLE

    q = df.map_in_pandas(ints, {"x": dt.DOUBLE})
    out = q.collect(device=True)
    assert str(out.schema.field("x").type) == "double"
    assert out.column("x").to_pylist() == [10.0, 20.0, 30.0]
    assert_tpu_cpu_equal(q)


def test_apply_in_pandas_per_group(sess):
    """applyInPandas: fn sees each key group whole (the planner hash-
    exchanges on the keys), across multiple input partitions."""
    rng = np.random.default_rng(8)
    df = sess.create_dataframe(pd.DataFrame({
        "g": rng.integers(0, 6, 600).astype(np.int64),
        "v": rng.normal(size=600),
    }), num_partitions=3)

    def summarize(group):
        return pd.DataFrame({"g": [group.g.iloc[0]],
                             "n": [len(group)],
                             "s": [group.v.sum()]})

    q = df.group_by("g").apply_in_pandas(
        summarize, {"g": dt.LONG, "n": dt.LONG, "s": dt.DOUBLE})
    out = assert_tpu_cpu_equal(q)
    pdf = df.collect().to_pandas()
    exp = pdf.groupby("g").v.agg(["count", "sum"])
    got = {r["g"]: (r["n"], r["s"]) for r in out.to_pylist()}
    assert len(got) == len(exp)
    for g, row in exp.iterrows():
        n, s = got[g]
        assert n == row["count"] and s == pytest.approx(row["sum"])


def test_apply_in_pandas_group_integrity(sess):
    """Every group must arrive in ONE fn call even with many partitions."""
    df = sess.create_dataframe(pd.DataFrame({
        "g": np.arange(40, dtype=np.int64) % 4,
        "v": np.ones(40),
    }), num_partitions=4)
    sizes = []

    def record(group):
        sizes.append(len(group))
        return pd.DataFrame({"g": [group.g.iloc[0]]})

    q = df.group_by("g").apply_in_pandas(record, {"g": dt.LONG})
    out = q.collect(device=False)
    assert out.num_rows == 4
    assert sorted(sizes) == [10, 10, 10, 10], sizes


def test_map_in_pandas_iterator_spans_whole_partition(sess):
    """PySpark contract: fn runs ONCE per partition and its iterator covers
    every batch — a stateful fn must see whole-partition counts."""
    df = sess.create_dataframe(pd.DataFrame({
        "a": np.arange(100, dtype=np.int64)}), num_partitions=2)
    calls = []

    def summarize(frames):
        n = 0
        for pdf in frames:
            n += len(pdf)
        calls.append(n)
        yield pd.DataFrame({"n": [n]})

    q = df.map_in_pandas(summarize, {"n": dt.LONG})
    out = q.collect(device=False)
    assert out.num_rows == 2            # one summary row per PARTITION
    assert sum(out.column("n").to_pylist()) == 100
    assert sorted(calls) == sorted(out.column("n").to_pylist())


def test_map_in_pandas_runs_on_empty_partitions(sess):
    """PySpark calls the fn for EMPTY partitions too — it may emit
    per-partition rows (headers/sentinels)."""
    # 3 rows over 4 partitions -> at least one empty partition
    df = sess.create_dataframe(pd.DataFrame({
        "a": np.arange(3, dtype=np.int64)}), num_partitions=4)

    def sentinel(frames):
        n = sum(len(f) for f in frames)
        yield pd.DataFrame({"n": [n]})

    q = df.map_in_pandas(sentinel, {"n": dt.LONG})
    out = q.collect(device=False)
    assert out.num_rows == 4            # one row per partition, empty incl.
    assert sum(out.column("n").to_pylist()) == 3
    assert 0 in out.column("n").to_pylist()


def test_cogroup_matches_null_keys(sess):
    """Null keys become pandas NaN; both sides' null groups must meet in
    ONE fn call (NaN != NaN would split them)."""
    import pyarrow as pa
    a = sess.create_dataframe(pa.table({
        "k": pa.array([1, None, None], type=pa.int64()),
        "x": pa.array([1.0, 2.0, 3.0])}))
    b = sess.create_dataframe(pa.table({
        "k": pa.array([None, 2], type=pa.int64()),
        "y": pa.array([10.0, 20.0])}))
    seen = []

    def pair(l, r):
        seen.append((len(l), len(r)))
        return pd.DataFrame({"nl": [len(l)], "nr": [len(r)]})

    q = a.group_by("k").cogroup(b.group_by("k")).apply_in_pandas(
        pair, {"nl": dt.LONG, "nr": dt.LONG})
    out = q.collect(device=False)
    rows = sorted((r["nl"], r["nr"]) for r in out.to_pylist())
    # groups: k=1 -> (1,0); k=null -> (2,1) TOGETHER; k=2 -> (0,1)
    assert rows == [(0, 1), (1, 0), (2, 1)], rows


def test_cogroup_empty_side_has_full_schema(sess):
    """A side with no rows at all still hands fn a frame with its FULL
    column set (Spark semantics), not just the key columns."""
    a = sess.create_dataframe(pd.DataFrame({
        "k": np.array([1], dtype=np.int64), "x": [5.0]}))
    b = sess.create_dataframe(pd.DataFrame({
        "k": np.array([], dtype=np.int64), "y": np.array([], dtype=np.float64)}))

    def probe(l, r):
        return pd.DataFrame({"k": [l.k.iloc[0] if len(l) else r.k.iloc[0]],
                             "ysum": [float(r.y.sum())]})  # touches r.y

    q = a.group_by("k").cogroup(b.group_by("k")).apply_in_pandas(
        probe, {"k": dt.LONG, "ysum": dt.DOUBLE})
    out = q.collect(device=False)
    assert out.to_pylist() == [{"k": 1, "ysum": 0.0}]


def test_get_json_object_rejects_malformed_paths(sess):
    import pyarrow as pa
    df = sess.create_dataframe(pa.table({"j": ['{"a": 1}']}))
    q = df.select(get_json_object(col("j"), "$x").alias("bad1"),
                  get_json_object(col("j"), "$.a??").alias("bad2"),
                  get_json_object(col("j"), "$").alias("whole"))
    out = q.collect(device=False)
    assert out.column("bad1").to_pylist() == [None]
    assert out.column("bad2").to_pylist() == [None]
    assert out.column("whole").to_pylist() == ['{"a":1}']


def test_cogroup_apply_in_pandas(sess):
    """cogroup: fn sees both sides' frames per key; keys present on only
    one side get an empty frame for the other."""
    rng = np.random.default_rng(9)
    a = sess.create_dataframe(pd.DataFrame({
        "k": np.array([0, 0, 1, 1, 2], dtype=np.int64),
        "x": np.arange(5, dtype=np.float64)}), num_partitions=2)
    b = sess.create_dataframe(pd.DataFrame({
        "k": np.array([1, 2, 2, 3], dtype=np.int64),
        "y": np.arange(4, dtype=np.float64) * 10}), num_partitions=3)

    def merge(l, r):
        k = l.k.iloc[0] if len(l) else r.k.iloc[0]
        return pd.DataFrame({"k": [k], "nx": [len(l)], "ny": [len(r)],
                             "sx": [l.x.sum() if len(l) else 0.0],
                             "sy": [r.y.sum() if len(r) else 0.0]})

    q = a.group_by("k").cogroup(b.group_by("k")).apply_in_pandas(
        merge, {"k": dt.LONG, "nx": dt.LONG, "ny": dt.LONG,
                "sx": dt.DOUBLE, "sy": dt.DOUBLE})
    out = q.collect(device=False)
    got = {r["k"]: (r["nx"], r["ny"], r["sx"], r["sy"])
           for r in out.to_pylist()}
    assert got == {0: (2, 0, 1.0, 0.0), 1: (2, 1, 5.0, 0.0),
                   2: (1, 2, 4.0, 30.0), 3: (0, 1, 0.0, 30.0)}
    assert_tpu_cpu_equal(q)


def test_map_in_pandas_composes_with_engine_ops(sess):
    df = sess.create_dataframe(pd.DataFrame({
        "x": np.arange(100, dtype=np.int64)}), num_partitions=2)

    def add_flag(frames):
        for pdf in frames:
            pdf = pdf.copy()
            pdf["flag"] = pdf.x % 3 == 0
            yield pdf

    q = (df.map_in_pandas(add_flag, {"x": dt.LONG, "flag": dt.BOOLEAN})
           .filter(col("flag"))
           .agg(__import__("spark_rapids_tpu.expr.functions",
                           fromlist=["count_star"]).count_star().alias("n")))
    out = q.collect(device=True)
    assert out.column("n").to_pylist() == [34]
