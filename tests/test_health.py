"""Live engine health subsystem (utils/health.py + tools/statusd.py).

Covers the PR 4 acceptance contract:
- an injected stall (semaphore holder sleeping past health.stallTimeout)
  is detected by a deterministic manual tick and the forensics report
  names the holder thread, per-queue depths and the catalog dump,
- /healthz, /metrics and /status respond while a query runs (probed from
  inside a mapInPandas UDF) and die with session.close(),
- event-log schema v4: heartbeat records round-trip through
  load_event_log and tools/diagnose.py (stall windows ranked, queries
  that heartbeated into OOM territory flagged),
- no monitor/HTTP threads leak after session.close(),
- the tier-1 conf-docs lint: every registered spark.rapids.* conf key
  appears in docs/configs.md,
- satellites: semaphore holder attribution + held-duration histogram,
  tracer spans_dropped counting (warn-once), and the explicit
  DeviceColumn.gather keep_all_valid contract.
"""
import glob
import json
import os
import threading
import time
import urllib.error
import urllib.request
import warnings

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.utils.health import HealthMonitor


# ---------------------------------------------------------------------------
# semaphore attribution (satellite): named holders, wait queue, held hist
# ---------------------------------------------------------------------------
def test_semaphore_dump_names_holders_and_waiters():
    from spark_rapids_tpu.memory.semaphore import TpuSemaphore

    sem = TpuSemaphore(1)
    ready, release = threading.Event(), threading.Event()

    def holder():
        sem.acquire_if_necessary()
        sem.acquire_if_necessary()  # reentrant: depth 2, one permit
        ready.set()
        release.wait(10)
        sem.release_all()

    t = threading.Thread(target=holder, name="permit-hog", daemon=True)
    t.start()
    assert ready.wait(5)
    waiter_going = threading.Event()

    def waiter():
        waiter_going.set()
        with sem.task_scope():
            pass

    w = threading.Thread(target=waiter, name="permit-waiter", daemon=True)
    w.start()
    assert waiter_going.wait(5)
    time.sleep(0.1)  # let the waiter block in acquire
    d = sem.dump()
    hogs = [h for h in d["holders"] if h["thread"] == "permit-hog"]
    assert hogs and hogs[0]["depth"] == 2 and hogs[0]["held_s"] >= 0
    assert d["available"] == 0
    assert [x for x in d["waiters"] if x["thread"] == "permit-waiter"]
    release.set()
    t.join(5)
    w.join(5)
    d = sem.dump()
    assert not d["holders"] and not d["waiters"] and d["available"] == 1
    # both full holds landed in the held-duration histogram
    assert d["held_seconds"]["count"] == 2


# ---------------------------------------------------------------------------
# stall detection: injected stall -> deterministic tick -> forensics
# ---------------------------------------------------------------------------
def test_watchdog_detects_injected_stall(tmp_path):
    from spark_rapids_tpu.memory.catalog import get_catalog
    from spark_rapids_tpu.memory.semaphore import get_semaphore
    from spark_rapids_tpu.parallel import pipeline as P

    conf = RapidsConf({
        "spark.rapids.tpu.health.stallTimeout": 5.0,
        "spark.rapids.tpu.health.reportDir": str(tmp_path),
    })
    mon = HealthMonitor(conf)
    get_catalog()  # the report's catalog section needs one to exist
    sem = get_semaphore()
    ready, release = threading.Event(), threading.Event()

    def stuck_holder():
        sem.acquire_if_necessary()
        ready.set()
        release.wait(30)  # the injected "lock-holder sleep"
        sem.release_all()

    t = threading.Thread(target=stuck_holder, name="stuck-holder",
                         daemon=True)
    # a live (starved) prefetch queue so the report shows per-queue depth
    feed = threading.Event()

    def slow_iter():
        yield 0
        feed.wait(30)
        yield 1

    it = P.prefetched(slow_iter, stage="unit:stalled-scan", depth=1)
    try:
        assert next(it) == 0  # generator body runs: queue registered
        t.start()
        assert ready.wait(5)
        t0 = time.monotonic()
        assert mon.tick(now=t0) is None  # baseline: progress just observed
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            report = mon.tick(now=t0 + 6.0)
        assert report is not None, "stall not detected"
        assert mon.stalled and mon.stalls_detected == 1
        # forensics: named semaphore holder with held-duration + stack
        assert "thread='stuck-holder'" in report
        assert "held for" in report
        assert "stuck_holder" in report  # its frame in the stack section
        assert "-- thread stacks --" in report
        # per-queue depths
        assert "stage='unit:stalled-scan' depth=0/1" in report
        # catalog dump
        assert "-- catalog --" in report and "device_used_bytes" in report
        # stall-<ts>.txt written and identical in content
        (path,) = glob.glob(os.path.join(str(tmp_path), "stall-*.txt"))
        with open(path, encoding="utf-8") as f:
            assert "stuck-holder" in f.read()
        assert mon.last_stall_report_path == path
        # once per stall episode: no re-dump while still stuck
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            assert mon.tick(now=t0 + 7.0) is None
        assert mon.stalls_detected == 1
        # the catalog diagnostics channel carries the stall note
        assert any("watchdog stall" in n for n in get_catalog().diagnostics)
    finally:
        release.set()
        feed.set()
        t.join(5)
        for _ in it:  # drain the queue so the worker exits
            pass
    # progress (queue drain) re-arms the detector
    assert mon.tick(now=t0 + 8.0) is None
    assert not mon.stalled


def test_watchdog_survives_wedged_catalog_lock():
    """The stall may BE a thread stuck holding the catalog lock; the
    monitor tick and the forensics dump must time-bound their acquires
    instead of joining the hang."""
    from spark_rapids_tpu.memory.catalog import get_catalog

    cat = get_catalog()
    mon = HealthMonitor(RapidsConf({
        "spark.rapids.tpu.health.stallTimeout": 1.0}))
    acquired, release = threading.Event(), threading.Event()

    def wedge():
        with cat._lock:
            acquired.set()
            release.wait(30)

    t = threading.Thread(target=wedge, name="catalog-wedger", daemon=True)
    t.start()
    assert acquired.wait(5)
    try:
        t0 = time.monotonic()
        mon.tick()  # watermark sample skipped, not blocked
        assert time.monotonic() - t0 < 5
        report = mon.stall_report(99.0)
        assert "catalog lock UNAVAILABLE" in report
    finally:
        release.set()
        t.join(5)
    assert "dump:" in mon.stall_report(1.0)  # lock free again


def test_monitor_ignores_idle_engine():
    """No work in flight -> never a stall, however old the progress."""
    mon = HealthMonitor(RapidsConf({
        "spark.rapids.tpu.health.stallTimeout": 1.0}))
    t0 = time.monotonic()
    assert mon.tick(now=t0) is None
    assert mon.tick(now=t0 + 1e6) is None
    assert not mon.stalled and mon.stalls_detected == 0


def test_no_false_stall_after_idle_gap():
    """Idle gap longer than stallTimeout, then new work: the first busy
    tick must restart the progress clock, not read the idle age as a
    stall — while a genuine post-transition freeze still detects."""
    from spark_rapids_tpu.parallel import pipeline as P

    P.configure_pipeline(RapidsConf())  # pipeline on (sticky settings)
    mon = HealthMonitor(RapidsConf({
        "spark.rapids.tpu.health.stallTimeout": 5.0}))
    t0 = time.monotonic()
    mon.tick(now=t0)
    mon.tick(now=t0 + 100)  # long idle: no work, no stall
    assert not mon.stalled
    hold = threading.Event()

    def task(x):
        hold.wait(30)
        return x

    runner = threading.Thread(
        target=lambda: P.parallel_map(task, [1, 2], max_workers=2,
                                      stage="unit:idlegap"),
        daemon=True)
    runner.start()
    deadline = time.monotonic() + 5
    while not P.pipeline_snapshot()["in_flight"] \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert P.pipeline_snapshot()["in_flight"]
    try:
        # first busy tick after the gap: transition reset, no stall
        assert mon.tick(now=t0 + 101) is None
        assert not mon.stalled and mon.stalls_detected == 0
        # a genuine freeze measured FROM the transition still fires
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            assert mon.tick(now=t0 + 107) is not None
        assert mon.stalls_detected == 1
    finally:
        hold.set()
        runner.join(5)


# ---------------------------------------------------------------------------
# HTTP endpoints: respond while a query runs, die with the session
# ---------------------------------------------------------------------------
def test_status_endpoints_respond_while_query_runs():
    from spark_rapids_tpu.columnar import dtypes as dt

    sess = TpuSession({
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.health.enabled": True,
        "spark.rapids.tpu.health.intervalMs": 50,
        "spark.rapids.tpu.health.port": 0,  # ephemeral
    })
    base = sess._health.server.url
    try:
        seen = {}

        def probe(batches):
            # executes mid-query, with the semaphore held by this task
            for pdf in batches:
                with urllib.request.urlopen(base + "/healthz",
                                            timeout=10) as r:
                    seen["healthz"] = (r.status, json.loads(r.read()))
                with urllib.request.urlopen(base + "/status",
                                            timeout=10) as r:
                    seen["status"] = json.loads(r.read())
                yield pdf

        df = sess.create_dataframe(
            pa.table({"x": np.arange(64.0)}), num_partitions=2)
        out = df.map_in_pandas(probe, {"x": dt.DOUBLE}).collect()
        assert out.num_rows == 64
        code, hz = seen["healthz"]
        assert code == 200 and hz["status"] == "ok"
        snap = seen["status"]
        for key in ("semaphore", "pipeline", "catalog", "active_operators",
                    "stalled", "last_progress_age_s"):
            assert key in snap, key
        assert snap["semaphore"]["permits"] >= 1
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "# TYPE spark_rapids_tpu_" in text
        assert "spark_rapids_tpu_tracer_spans_dropped" in text
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/nope", timeout=10)
    finally:
        sess.close()
    with pytest.raises(OSError):  # server gone after close
        urllib.request.urlopen(base + "/healthz", timeout=2)


# ---------------------------------------------------------------------------
# event-log schema v4: heartbeats round-trip through replay + diagnose
# ---------------------------------------------------------------------------
HEARTBEAT_REQUIRED_KEYS = {
    "event", "ts", "seq", "uptime_s", "device_used_bytes",
    "device_peak_bytes", "device_limit_bytes", "semaphore_holders",
    "semaphore_waiters", "queues", "queue_depth", "in_flight",
    "active_workers", "last_progress_age_s", "stalled",
}


def test_heartbeat_schema_v4_roundtrip(tmp_path):
    from spark_rapids_tpu.expr.functions import col, sum as f_sum
    from spark_rapids_tpu.tools.diagnose import diagnose_path
    from spark_rapids_tpu.tools.eventlog import (SCHEMA_VERSION,
                                                 load_event_log)

    assert SCHEMA_VERSION == 12  # v12: shuffle_summary records (see
    # test_observability.py + test_shuffle_observatory.py pins);
    # heartbeat records are unchanged from v4
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.health.enabled": True,
        # interval so large the thread never ticks on its own: the ticks
        # below are manual, so the heartbeat count is deterministic
        "spark.rapids.tpu.health.intervalMs": 3_600_000,
    })
    try:
        rng = np.random.default_rng(9)
        df = sess.create_dataframe(pa.table({
            "g": rng.integers(0, 4, 200), "x": rng.normal(size=200)}),
            num_partitions=2)
        df.group_by("g").agg(f_sum(col("x")).alias("sx")).collect()
        sess._health.monitor.tick()
        sess._health.monitor.tick()
    finally:
        sess.close()
    (path,) = glob.glob(os.path.join(str(tmp_path), "*.jsonl"))
    records = [json.loads(line) for line in open(path, encoding="utf-8")]
    hbs = [r for r in records if r["event"] == "heartbeat"]
    assert len(hbs) == 2
    for hb in hbs:
        missing = HEARTBEAT_REQUIRED_KEYS - set(hb)
        assert not missing, missing
    assert [hb["seq"] for hb in hbs] == [1, 2]
    # replay: heartbeats surface on the app, version pinned
    app = load_event_log(path)
    assert app.schema_version == 12
    assert len(app.heartbeats) == 2
    # query window timestamps replay (heartbeats here fired after the
    # query, so the window is empty — attribution, not accidental capture)
    q = app.query(1)
    assert q.ts_start > 0 and q.ts_end >= q.ts_start
    assert q.heartbeats_in_window(app.heartbeats) == []
    # diagnose consumes a v4 log cleanly
    diagnose_path(path).summary()


def test_diagnose_ranks_stall_window_and_oom_territory(tmp_path):
    """Synthetic v4 log: a stalled heartbeat + HBM at 95% inside the
    query window -> ranked stall finding + 'OOM territory' flag."""
    from spark_rapids_tpu.tools.diagnose import diagnose_app
    from spark_rapids_tpu.tools.eventlog import load_event_log

    hb = {"event": "heartbeat", "ts": 15.0, "seq": 1, "uptime_s": 5.0,
          "device_used_bytes": 95, "device_peak_bytes": 95,
          "device_limit_bytes": 100, "semaphore_holders": 1,
          "semaphore_waiters": 2, "queues": {"decode": 0},
          "queue_depth": 0, "in_flight": 1, "active_workers": 2,
          "last_progress_age_s": 8.0, "stalled": True}
    records = [
        {"event": "app_start", "app_id": "h", "schema_version": 4,
         "ts": 0.0, "conf": {}},
        {"event": "query_start", "query_id": 1, "ts": 10.0, "plan": "p"},
        hb,
        {"event": "query_end", "query_id": 1, "ts": 20.0, "wall_s": 10.0,
         "final_plan": "p", "aqe_events": [], "spill_count": {},
         "semaphore_wait_s": 0.0, "stats": {}},
        {"event": "app_end", "ts": 21.0},
    ]
    path = tmp_path / "hb.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in records))
    app = load_event_log(str(path))
    assert app.query(1).heartbeats_in_window(app.heartbeats) == [hb]
    rep = diagnose_app(app, str(path))
    (qd,) = rep.queries
    metrics = {f.metric for f in qd.findings}
    assert "stall" in metrics and "hbmPressure" in metrics
    text = rep.summary(top=5)
    assert "watchdog stall window" in text
    assert "OOM territory" in text
    # the stall window ranks by its no-progress share of wall
    stall = next(f for f in qd.findings if f.metric == "stall")
    assert stall.fraction == pytest.approx(0.8)
    # replay-level health check flags it too
    assert any("stalled engine" in w for w in app.health_check())


# ---------------------------------------------------------------------------
# no leaked threads: monitor + HTTP server die with session.close()
# ---------------------------------------------------------------------------
def test_no_leaked_threads_after_close_with_health_enabled():
    from spark_rapids_tpu.expr.functions import col, sum as f_sum
    from spark_rapids_tpu.parallel import pipeline as P

    before = {t.name for t in threading.enumerate()}
    sess = TpuSession({
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.health.enabled": True,
        "spark.rapids.tpu.health.intervalMs": 20,
        "spark.rapids.tpu.health.port": 0,
    })
    rng = np.random.default_rng(2)
    df = sess.create_dataframe(pa.table({
        "k": rng.integers(0, 3, 300), "v": rng.normal(size=300)}),
        num_partitions=2)
    df.group_by("k").agg(f_sum(col("v")).alias("s")).collect(device=True)
    time.sleep(0.1)  # let the monitor tick at least once
    assert sess._health.monitor.ticks >= 1
    sess.close()
    deadline = time.monotonic() + 10
    while P.active_workers() and time.monotonic() < deadline:
        time.sleep(0.05)
    lingering = {t.name for t in threading.enumerate()} - before
    leaked = [n for n in lingering
              if n.startswith(("tpu-health", "tpu-prefetch",
                               "tpu-pipeline"))]
    assert not leaked, leaked


# ---------------------------------------------------------------------------
# tier-1 lint: every registered conf key appears in docs/configs.md
# ---------------------------------------------------------------------------
def test_every_conf_key_documented():
    """Keeps the doc regen honest: a conf registered anywhere in the
    package must appear in docs/configs.md (regenerate with
    `python -m spark_rapids_tpu.conf`)."""
    import pathlib

    import spark_rapids_tpu
    from spark_rapids_tpu.conf import conf_entries, import_conf_modules

    import_conf_modules()
    docs = (pathlib.Path(spark_rapids_tpu.__file__).parent.parent
            / "docs" / "configs.md").read_text(encoding="utf-8")
    missing = [e.key for e in conf_entries()
               if not e.internal and f"`{e.key}`" not in docs]
    assert not missing, (
        f"conf keys missing from docs/configs.md — regenerate with "
        f"`python -m spark_rapids_tpu.conf`: {missing}")
    # the lint is live: the health keys this PR added are in scope
    keys = {e.key for e in conf_entries()}
    assert "spark.rapids.tpu.health.stallTimeout" in keys


# ---------------------------------------------------------------------------
# tracer satellite: spans_dropped counted + warn-once on ring wrap
# ---------------------------------------------------------------------------
def test_tracer_counts_dropped_spans_and_warns_once():
    from spark_rapids_tpu.utils.metrics import get_stats
    from spark_rapids_tpu.utils.tracing import (Tracer, get_tracer,
                                                set_tracer, tracer_stats)

    tr = Tracer(capacity=4, enabled=True)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        for i in range(10):
            tr.instant(f"e{i}")
    assert tr.dropped == 6
    wraps = [w for w in caught if issubclass(w.category, RuntimeWarning)
             and "ring buffer wrapped" in str(w.message)]
    assert len(wraps) == 1, "wrap warning must fire exactly once"
    old = get_tracer()
    set_tracer(tr)
    try:
        assert tracer_stats()["spans_dropped"] == 6
        # surfaces through the process stats registry (and /metrics)
        assert get_stats().collect()["tracer_spans_dropped"] == 6
    finally:
        set_tracer(old)
    tr.clear()
    assert tr.dropped == 0
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        for i in range(10):
            tr.instant(f"f{i}")
    assert len([w for w in caught
                if "ring buffer wrapped" in str(w.message)]) == 1


# ---------------------------------------------------------------------------
# gather all-valid contract satellite (ADVICE #3)
# ---------------------------------------------------------------------------
def test_gather_keep_all_valid_contract():
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar import device as D
    from spark_rapids_tpu.columnar import dtypes as dt

    col = D.DeviceColumn(jnp.arange(8.0), jnp.ones(8, bool), dt.DOUBLE,
                         all_valid=True)
    idx = jnp.arange(8, dtype=jnp.int32)
    # default (implicit legacy) preserves the promise
    assert col.gather(idx).all_valid
    assert col.gather(idx, keep_all_valid=True).all_valid
    # explicit opt-out always drops it
    assert not col.gather(idx, keep_all_valid=False).all_valid
    # a non-promising column never gains the promise
    plain = D.DeviceColumn(jnp.arange(8.0), jnp.ones(8, bool), dt.DOUBLE)
    assert not plain.gather(idx, keep_all_valid=True).all_valid
    # debug assertions: implicit call sites lose the promise (an
    # un-audited gather cannot expose padding garbage as non-null)
    D.configure_debug(RapidsConf({"spark.rapids.tpu.debug.assertions": True}))
    try:
        assert D.debug_assertions_enabled()
        assert not col.gather(idx).all_valid
        assert col.gather(idx, keep_all_valid=True).all_valid
    finally:
        D.configure_debug(RapidsConf())
    assert not D.debug_assertions_enabled()


def test_debug_assertions_query_parity():
    """End-to-end guard: a sort+filter query returns identical results
    with debug assertions on (the promise drop is semantic-neutral)."""
    from spark_rapids_tpu.expr.functions import col

    def run(extra):
        sess = TpuSession({"spark.rapids.tpu.batchRowsMinBucket": 8,
                           **extra})
        try:
            df = sess.create_dataframe(pa.table({
                "x": [3.0, 1.0, None, 2.0, 5.0, 4.0] * 4}))
            return df.filter(col("x") > 1.0).sort("x") \
                .collect(device=True).to_pandas()
        finally:
            sess.close()

    base = run({})
    debug = run({"spark.rapids.tpu.debug.assertions": True})
    assert base.equals(debug)


# ---------------------------------------------------------------------------
# pipeline introspection API
# ---------------------------------------------------------------------------
def test_pipeline_snapshot_tracks_queues_and_progress():
    from spark_rapids_tpu.parallel import pipeline as P

    before = P.pipeline_snapshot()
    gate = threading.Event()

    def producer():
        yield 1
        gate.wait(30)
        yield 2

    it = P.prefetched(producer, stage="unit:snap", depth=2)
    try:
        assert next(it) == 1
        snap = P.pipeline_snapshot()
        stages = [q["stage"] for q in snap["queues"]]
        assert "unit:snap" in stages
        assert snap["progress_counter"] > before["progress_counter"]
        assert snap["last_progress_age_s"] >= 0
    finally:
        gate.set()
        for _ in it:
            pass
    # queue unregisters once the consumer drains
    stages = [q["stage"] for q in P.pipeline_snapshot()["queues"]]
    assert "unit:snap" not in stages


def test_sequential_mode_bumps_progress_marker():
    """pipeline.enabled=false never touches a prefetch queue or pooled
    task; operator batch accounting (exec/base.py) must still move the
    progress marker or a healthy sequential drain reads as a stall."""
    from spark_rapids_tpu.parallel import pipeline as P

    sess = TpuSession({"spark.rapids.tpu.batchRowsMinBucket": 8,
                       "spark.rapids.tpu.pipeline.enabled": False})
    try:
        before = P.pipeline_snapshot()["progress_counter"]
        df = sess.create_dataframe(pa.table({"x": [1.0] * 64}),
                                   num_partitions=2)
        assert df.count() == 64
        assert P.pipeline_snapshot()["progress_counter"] > before
    finally:
        sess.close()


def test_healthz_probe_ticks_without_monitor_thread():
    """health.port without health.enabled: the 503-while-stalled contract
    must still hold, so /healthz samples on the probe itself (without
    flooding the event log with heartbeats)."""
    sess = TpuSession({"spark.rapids.tpu.batchRowsMinBucket": 8,
                       "spark.rapids.tpu.health.port": 0})
    try:
        mon = sess._health.monitor
        assert not mon.ticking()
        base = sess._health.server.url
        t0, hb0 = mon.ticks, mon.heartbeats_emitted
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            assert r.status == 200
        assert mon.ticks == t0 + 1
        assert mon.heartbeats_emitted == hb0  # probe ticks emit no heartbeat
    finally:
        sess.close()


def test_health_status_without_monitor():
    """session.health_status() works with the subsystem fully off (the
    bench snapshot path must never require the monitor thread)."""
    sess = TpuSession({"spark.rapids.tpu.batchRowsMinBucket": 8})
    try:
        assert sess._health is None
        snap = sess.health_status()
        assert "pipeline" in snap and "stalled" in snap
    finally:
        sess.close()
