"""Device string group-by/sort keys via packed uint64 surrogate words
(columnar/device.py pack_string_key_words). The reference gets native string
keys from cudf; here any-width strings pack 8 bytes/word + length tiebreak."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr.functions import col, sum as fsum, count_star
from harness import assert_tpu_cpu_equal


def _plan_text(df, device=True):
    from spark_rapids_tpu.plan.aqe import AdaptiveExec
    plan = df.session._physical(df.logical, device=device)
    if isinstance(plan, AdaptiveExec):
        plan = plan.final_plan()
    return plan.tree_string()


def test_string_groupby_runs_on_device(session):
    rng = np.random.default_rng(7)
    t = pa.table({
        "k": rng.choice(np.array(["A", "N", "R"]), 3000),
        "k2": rng.choice(np.array(["alpha", "beta", "a-much-longer-key-value",
                                   "gamma-gamma-gamma"]), 3000),
        "v": rng.normal(size=3000),
    })
    df = session.create_dataframe(t, num_partitions=2)
    q = df.group_by("k", "k2").agg(fsum(col("v")).alias("s"),
                                   count_star().alias("n"))
    assert "TpuHashAggregate" in _plan_text(q) or "WholeStage" in _plan_text(q)
    out = assert_tpu_cpu_equal(q)
    pdf = t.to_pandas()
    exp = pdf.groupby(["k", "k2"]).v.sum()
    assert out.num_rows == len(exp)
    got = {(r["k"], r["k2"]): r["s"] for r in out.to_pylist()}
    for (k, k2), s in exp.items():
        assert got[(k, k2)] == pytest.approx(s, rel=1e-9)


def test_string_key_padding_vs_embedded_nul(session):
    # "ab" vs "ab\x00" must be distinct groups (length tiebreak word)
    t = pa.table({"k": ["ab", "ab\x00", "ab", "a", "ab\x00"],
                  "v": [1, 10, 100, 1000, 10000]})
    df = session.create_dataframe(t)
    q = df.group_by("k").agg(fsum(col("v")).alias("s"))
    out = assert_tpu_cpu_equal(q)
    got = dict(zip(out.column("k").to_pylist(), out.column("s").to_pylist()))
    assert got == {"ab": 101, "ab\x00": 10010, "a": 1000}


def test_string_sort_on_device(session):
    rng = np.random.default_rng(8)
    words = np.array(["pear", "apple", "fig", "apple pie", "appl",
                      "zebra", "app", ""])
    t = pa.table({"k": rng.choice(words, 500),
                  "v": np.arange(500, dtype=np.int64)})
    df = session.create_dataframe(t, num_partitions=2)
    q = df.sort("k")
    assert "TpuSort" in _plan_text(q)
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    ks = out.column("k").to_pylist()
    assert ks == sorted(ks)
    q2 = df.sort(col("k").desc())
    out2 = assert_tpu_cpu_equal(q2, ignore_order=False)
    ks2 = out2.column("k").to_pylist()
    assert ks2 == sorted(ks2, reverse=True)


def test_string_groupby_with_nulls(session):
    t = pa.table({"k": ["x", None, "x", None, "y"],
                  "v": [1.0, 2.0, 3.0, 4.0, 5.0]})
    df = session.create_dataframe(t)
    q = df.group_by("k").agg(fsum(col("v")).alias("s"))
    out = assert_tpu_cpu_equal(q)
    got = dict(zip(out.column("k").to_pylist(), out.column("s").to_pylist()))
    assert got == {"x": 4.0, None: 6.0, "y": 5.0}


def test_q1_fully_on_device(session):
    """TPC-H Q1's grouped aggregate (string keys) must now lower to the
    device (the BASELINE ladder workload)."""
    from spark_rapids_tpu.tools import tpch
    li = tpch.gen_lineitem(0, seed=3, rows=4000)
    df = session.create_dataframe(li, num_partitions=2)
    q = tpch.q1({"lineitem": df})
    text = _plan_text(q)
    assert "TpuHashAggregate" in text or "WholeStage" in text
    assert_tpu_cpu_equal(q, ignore_order=False)
