"""Debug-allocator sanitizers + catalog/spill concurrency stress
(reference: RMM debug allocator, spark.rapids.memory.gpu.debug; the
reference also races its stores under the ThreadedShuffle tests)."""
import threading

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar.device import DeviceTable
from spark_rapids_tpu.columnar.host import HostTable
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.memory.catalog import (BufferCatalog, DebugMemoryError,
                                             SpillPriorities)


def _table(seed: int, rows: int = 256) -> DeviceTable:
    rng = np.random.default_rng(seed)
    ht = HostTable.from_arrow(pa.table({
        "a": rng.integers(0, 1 << 30, rows).astype(np.int64),
        "b": rng.normal(size=rows),
    }))
    return DeviceTable.from_host(ht, 256)


def _debug_catalog(**kw) -> BufferCatalog:
    conf = RapidsConf({"spark.rapids.tpu.memory.debug": True})
    return BufferCatalog(conf, **kw)


def test_double_free_detected():
    cat = _debug_catalog(device_limit=1 << 24)
    h = cat.register(_table(1))
    h.close()
    with pytest.raises(DebugMemoryError, match="double free"):
        h.close()


def test_release_underflow_detected():
    cat = _debug_catalog(device_limit=1 << 24)
    h = cat.register(_table(2))
    with pytest.raises(DebugMemoryError, match="underflow"):
        cat.release(h.buffer_id)
    h.close()


def test_use_after_close_detected():
    cat = _debug_catalog(device_limit=1 << 24)
    h = cat.register(_table(3))
    h.close()
    with pytest.raises(DebugMemoryError, match="use-after-close"):
        h.get()


def test_leak_check_reports_creation_site():
    cat = _debug_catalog(device_limit=1 << 24)
    h = cat.register(_table(4))
    with pytest.raises(DebugMemoryError, match="leaked buffer"):
        cat.assert_no_leaks()
    h.close()
    cat.assert_no_leaks()


def test_poison_on_free():
    """Freed host-tier buffers are filled with 0xDD so stale readers see
    deterministic garbage, not silently-valid data."""
    cat = _debug_catalog(device_limit=1)  # everything spills to host
    h = cat.register(_table(5))
    stored = cat._buffers[h.buffer_id]
    cat.synchronous_spill(1 << 20)
    assert stored.host_arrays is not None
    arrays = stored.host_arrays
    h.close()
    poisoned = arrays["col0.data"].view("uint8")
    assert (poisoned == 0xDD).all()


def test_non_debug_mode_keeps_lenient_semantics():
    cat = BufferCatalog(RapidsConf(), device_limit=1 << 24)
    h = cat.register(_table(6))
    h.close()
    h.close()               # silent no-op outside debug mode
    cat.release(12345)      # unknown release tolerated


def test_direct_disk_spill_roundtrip():
    """GDS-analogue direct mode: disk restores are read-only memory maps
    (the device upload streams from the file) and data survives the full
    device->host->disk->device cycle."""
    from spark_rapids_tpu.memory.stores import StorageTier
    cat = BufferCatalog(RapidsConf(), device_limit=4000, host_limit=4000)
    assert cat.disk.direct
    t = _table(11, rows=512)
    expect = np.asarray(t.columns[0].data).copy()
    h = cat.register(t)
    cat.synchronous_spill(1 << 20)   # -> host
    cat._spill_host_to_disk(1 << 30)  # force -> disk
    stored = cat._buffers[h.buffer_id]
    assert stored.tier == StorageTier.DISK
    loaded = cat.disk.load(stored)
    assert any(isinstance(a, np.memmap) for a in loaded.values()), \
        {k: type(v) for k, v in loaded.items()}
    back = h.get()
    assert (np.asarray(back.columns[0].data) == expect).all()
    h.close()


def test_npz_disk_mode_still_works():
    conf = RapidsConf({"spark.rapids.tpu.memory.disk.direct": False})
    cat = BufferCatalog(conf, device_limit=4000, host_limit=4000)
    assert not cat.disk.direct
    t = _table(12, rows=512)
    expect = np.asarray(t.columns[0].data).copy()
    h = cat.register(t)
    cat.synchronous_spill(1 << 20)
    cat._spill_host_to_disk(1 << 30)
    back = h.get()
    assert (np.asarray(back.columns[0].data) == expect).all()
    h.close()


def test_concurrent_register_spill_close_stress():
    """Many threads hammer register/acquire/release/close against a pool
    small enough to force constant spilling; accounting must stay exact and
    every buffer must round-trip its own data."""
    cat = _debug_catalog(device_limit=200_000, host_limit=400_000)
    errors = []

    def worker(tid: int):
        try:
            rng = np.random.default_rng(tid)
            for i in range(12):
                t = _table(tid * 1000 + i)
                expect = np.asarray(t.columns[0].data)
                h = cat.register(t, SpillPriorities.INPUT)
                if rng.random() < 0.5:
                    cat.synchronous_spill(50_000)
                with h as back:
                    got = np.asarray(back.columns[0].data)
                    if not (got == expect).all():
                        errors.append(f"t{tid} i{i}: data corrupted")
                h.close()
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append(f"t{tid}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=120)
    assert not errors, errors[:5]
    cat.assert_no_leaks()
    cat._check_invariants()
    assert sum(cat.spill_count.values()) > 0, "stress never spilled"
