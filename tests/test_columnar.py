"""Columnar representation tests (reference analogues:
GpuColumnVector round-trips, GpuCoalesceBatchesSuite, GpuPartitioningSuite)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import (DeviceTable, HostTable, bucket_rows,
                                       concat_device_tables)


def test_bucket_rows():
    assert bucket_rows(1, 8) == 8
    assert bucket_rows(8, 8) == 8
    assert bucket_rows(9, 8) == 16
    assert bucket_rows(1000, 1024) == 1024
    assert bucket_rows(1025, 1024) == 2048


def _roundtrip(t: pa.Table):
    ht = HostTable.from_arrow(t)
    dt = DeviceTable.from_host(ht, min_bucket=8)
    back = dt.to_host().to_arrow()
    assert back.cast(t.schema).equals(t)


def test_roundtrip_numeric_nulls():
    _roundtrip(pa.table({
        "i8": pa.array([1, None, -3], type=pa.int8()),
        "i64": pa.array([2**40, None, -5], type=pa.int64()),
        "f32": pa.array([1.5, None, float("inf")], type=pa.float32()),
        "f64": pa.array([1e300, -0.0, None], type=pa.float64()),
        "b": pa.array([True, None, False]),
    }))


def test_roundtrip_strings_dates():
    _roundtrip(pa.table({
        "s": ["", "hello", None, "ünïcode", "x" * 100],
        "d": pa.array([0, 100, None, 7, -1], type=pa.int32()).cast(pa.date32()),
        "ts": pa.array([0, None, 2**45, 1, 2],
                       type=pa.int64()).cast(pa.timestamp("us")),
    }))


def test_filter_mask_and_compact():
    t = pa.table({"a": list(range(10))})
    dt = DeviceTable.from_host(HostTable.from_arrow(t), min_bucket=8)
    import jax.numpy as jnp
    keep = jnp.asarray(np.arange(16) % 2 == 0)
    f = dt.filter_mask(keep)
    assert int(f.num_rows) == 5
    c = f.compact()
    out = c.to_host().to_arrow()
    assert out.column("a").to_pylist() == [0, 2, 4, 6, 8]


def test_concat_device_tables():
    t1 = pa.table({"a": [1, 2, 3], "s": ["x", "yy", None]})
    t2 = pa.table({"a": [4, None], "s": ["zzzzzzzzzzzzzzzz", "w"]})
    d1 = DeviceTable.from_host(HostTable.from_arrow(t1), min_bucket=8)
    d2 = DeviceTable.from_host(HostTable.from_arrow(t2), min_bucket=8)
    out = concat_device_tables([d1, d2]).to_host().to_arrow()
    assert out.column("a").to_pylist() == [1, 2, 3, 4, None]
    assert out.column("s").to_pylist() == ["x", "yy", None, "zzzzzzzzzzzzzzzz", "w"]


def test_decimal_roundtrip():
    import decimal
    t = pa.table({"d": pa.array(
        [None, decimal.Decimal("1.25"), decimal.Decimal("-3.50")],
        type=pa.decimal128(10, 2))})
    _roundtrip(t)


def test_empty_table():
    _roundtrip(pa.table({"a": pa.array([], type=pa.int64()),
                         "s": pa.array([], type=pa.string())}))
