"""Complex types: arrays/structs/maps, collection ops, higher-order
functions, explode, collect_list/set, approx_percentile.

Reference test analogues: integration_tests array_test.py / map_test.py /
struct_test.py / collection_ops_test.py / generate_expr_test.py.

These ops are host-engine; the device plan must FALL BACK with a recorded
reason and still produce identical results (the reference's fallback
assertion pattern, asserts.py:361 assert_gpu_fallback_collect).
"""
import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.expr.functions as F
from spark_rapids_tpu.expr.functions import col, lit
from harness import assert_tpu_cpu_equal


@pytest.fixture()
def adf(session):
    t = pa.table({
        "id": [1, 2, 3, 4],
        "arr": pa.array([[1, 2, 3], [], None, [4, None, 6]],
                        type=pa.list_(pa.int64())),
        "darr": pa.array([[1.5, float("nan"), 0.5], [2.0], None, []],
                         type=pa.list_(pa.float64())),
    })
    return session.create_dataframe(t, num_partitions=2)


def test_roundtrip_nested(session):
    t = pa.table({
        "a": pa.array([[1, 2], None, [3]], type=pa.list_(pa.int64())),
        "s": pa.array([{"x": 1, "y": "a"}, {"x": 2, "y": None}, None],
                      type=pa.struct([("x", pa.int64()), ("y", pa.string())])),
        "m": pa.array([[("k1", 1)], [], None],
                      type=pa.map_(pa.string(), pa.int64())),
    })
    df = session.create_dataframe(t)
    out = df.collect(device=False)
    assert out.column("a").to_pylist() == [[1, 2], None, [3]]
    assert out.column("s").to_pylist()[1] == {"x": 2, "y": None}
    assert out.column("m").to_pylist() == [[("k1", 1)], [], None]


def test_size_and_element_at(adf):
    q = adf.select(
        col("id"),
        F.size(col("arr")).alias("sz"),
        F.element_at(col("arr"), 1).alias("e1"),
        F.element_at(col("arr"), -1).alias("em1"),
        F.element_at(col("arr"), 99).alias("oob"))
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    assert out.column("sz").to_pylist() == [3, 0, -1, 3]
    assert out.column("e1").to_pylist() == [1, None, None, 4]
    assert out.column("em1").to_pylist() == [3, None, None, 6]
    assert out.column("oob").to_pylist() == [None] * 4


def test_get_item_and_contains(adf):
    q = adf.select(
        col("arr")[0].alias("a0"),
        F.array_contains(col("arr"), 2).alias("has2"),
        F.array_contains(col("arr"), 99).alias("has99"),
        F.array_position(col("arr"), 6).alias("p6"))
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    assert out.column("a0").to_pylist() == [1, None, None, 4]
    assert out.column("has2").to_pylist() == [True, False, None, None]
    # arr row 3 contains a null and no 99 -> unknown (null)
    assert out.column("has99").to_pylist() == [False, False, None, None]
    assert out.column("p6").to_pylist() == [0, 0, None, 3]


def test_min_max_sort_distinct(adf):
    q = adf.select(
        F.array_min(col("arr")).alias("mn"),
        F.array_max(col("arr")).alias("mx"),
        F.array_min(col("darr")).alias("dmn"),
        F.array_max(col("darr")).alias("dmx"),
        F.sort_array(col("arr")).alias("sorted"),
        F.sort_array(col("arr"), asc=False).alias("rsorted"))
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    assert out.column("mn").to_pylist() == [1, None, None, 4]
    assert out.column("mx").to_pylist() == [3, None, None, 6]
    assert out.column("dmn").to_pylist() == [0.5, 2.0, None, None]
    # NaN is greatest in Spark's total order
    dmx = out.column("dmx").to_pylist()
    assert np.isnan(dmx[0]) and dmx[1] == 2.0
    assert out.column("sorted").to_pylist() == \
        [[1, 2, 3], [], None, [None, 4, 6]]
    assert out.column("rsorted").to_pylist() == \
        [[3, 2, 1], [], None, [6, 4, None]]


def test_create_array_struct_map(session):
    t = pa.table({"a": [1, 2], "b": [10.5, 20.5], "s": ["x", "y"]})
    df = session.create_dataframe(t)
    q = df.select(
        F.array(col("a"), col("a") + lit(1)).alias("arr"),
        F.named_struct("k", col("a"), "v", col("s")).alias("st"),
        F.create_map(col("s"), col("b")).alias("mp"))
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    assert out.column("arr").to_pylist() == [[1, 2], [2, 3]]
    assert out.column("st").to_pylist() == [{"k": 1, "v": "x"},
                                            {"k": 2, "v": "y"}]
    assert out.column("mp").to_pylist() == [[("x", 10.5)], [("y", 20.5)]]
    q2 = df.select(F.named_struct("k", col("a"), "v", col("s")).alias("st")) \
        .select(col("st").getField("v").alias("v"))
    out2 = assert_tpu_cpu_equal(q2, ignore_order=False)
    assert out2.column("v").to_pylist() == ["x", "y"]


def test_flatten_slice_sequence_repeat(session):
    t = pa.table({
        "nested": pa.array([[[1, 2], [3]], [[4]], None, [[5], None]],
                           type=pa.list_(pa.list_(pa.int64()))),
        "n": [1, 2, 3, 4],
    })
    df = session.create_dataframe(t)
    q = df.select(
        F.flatten(col("nested")).alias("flat"),
        F.sequence(lit(1), col("n")).alias("seq"),
        F.array_repeat(col("n"), lit(2)).alias("rep"))
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    assert out.column("flat").to_pylist() == [[1, 2, 3], [4], None, None]
    assert out.column("seq").to_pylist() == [[1], [1, 2], [1, 2, 3],
                                             [1, 2, 3, 4]]
    assert out.column("rep").to_pylist() == [[1, 1], [2, 2], [3, 3], [4, 4]]
    q2 = df.select(F.slice(F.sequence(lit(1), lit(10)), col("n"), lit(2))
                   .alias("sl"))
    out2 = assert_tpu_cpu_equal(q2, ignore_order=False)
    assert out2.column("sl").to_pylist() == [[1, 2], [2, 3], [3, 4], [4, 5]]


def test_higher_order_functions(adf):
    q = adf.select(
        col("id"),
        F.transform(col("arr"), lambda x: x * lit(10)).alias("t"),
        F.transform(col("arr"), lambda x, i: x + i).alias("ti"),
        F.filter(col("arr"), lambda x: x > lit(1)).alias("f"),
        F.exists(col("arr"), lambda x: x == lit(2)).alias("ex"),
        F.aggregate(col("arr"), lit(0), lambda acc, x: acc + x).alias("agg"))
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    assert out.column("t").to_pylist() == [[10, 20, 30], [], None,
                                           [40, None, 60]]
    assert out.column("ti").to_pylist() == [[1, 3, 5], [], None,
                                            [4, None, 8]]
    assert out.column("f").to_pylist() == [[2, 3], [], None, [4, 6]]
    assert out.column("ex").to_pylist() == [True, False, None, None]
    # null element -> null fold result (acc + null = null)
    assert out.column("agg").to_pylist() == [6, 0, None, None]


def test_hofs_run_on_device(adf):
    """HOF lambdas run columnar on device (round-4 VERDICT item 6;
    reference: higherOrderFunctions.scala:209) — no fallback reasons."""
    q = adf.select(
        F.transform(col("arr"), lambda x: x * lit(10)).alias("t"),
        F.filter(col("arr"), lambda x: x > lit(1)).alias("f"),
        F.exists(col("arr"), lambda x: x == lit(2)).alias("ex"),
        F.aggregate(col("arr"), lit(0), lambda acc, x: acc + x).alias("agg"))
    ex = q.explain("tpu")
    assert "CpuProjectExec will run on TPU" in ex, ex
    assert "no device implementation" not in ex, ex
    assert_tpu_cpu_equal(q, ignore_order=False)


def test_hof_captures_outer_column_on_device(adf):
    q = adf.select(
        F.transform(col("arr"), lambda x: x + col("id")).alias("t"))
    ex = q.explain("tpu")
    assert "CpuProjectExec will run on TPU" in ex, ex
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    assert out.column("t").to_pylist() == [[2, 3, 4], [], None,
                                           [8, None, 10]]


def test_aggregate_with_finish(adf):
    q = adf.select(
        F.aggregate(col("darr"), lit(0.0), lambda acc, x: acc + x,
                    lambda acc: acc * lit(2.0)).alias("dbl"))
    out = q.collect(device=False)
    got = out.column("dbl").to_pylist()
    assert got[1] == 4.0 and got[3] == 0.0


def test_explode_method_and_select(session):
    t = pa.table({
        "id": [1, 2, 3],
        "arr": pa.array([[10, 20], [], None], type=pa.list_(pa.int64())),
    })
    df = session.create_dataframe(t, num_partitions=2)
    out = assert_tpu_cpu_equal(df.explode("arr", "e"), ignore_order=False)
    assert out.column("id").to_pylist() == [1, 1]
    assert out.column("e").to_pylist() == [10, 20]
    # outer keeps empty/null rows with null element
    outer = assert_tpu_cpu_equal(df.explode("arr", "e", outer=True),
                                 ignore_order=False)
    assert outer.column("id").to_pylist() == [1, 1, 2, 3]
    assert outer.column("e").to_pylist() == [10, 20, None, None]
    # posexplode
    pos = assert_tpu_cpu_equal(df.explode("arr", pos=True),
                               ignore_order=False)
    assert pos.column("pos").to_pylist() == [0, 1]
    assert pos.column("col").to_pylist() == [10, 20]
    # select-embedded explode
    sel = assert_tpu_cpu_equal(
        session.create_dataframe(t).select(
            col("id"), F.explode(col("arr")).alias("x")),
        ignore_order=False)
    assert sel.column_names == ["id", "x"]
    assert sel.column("x").to_pylist() == [10, 20]


def test_explode_map(session):
    t = pa.table({
        "id": [1, 2],
        "m": pa.array([[("a", 1), ("b", 2)], []],
                      type=pa.map_(pa.string(), pa.int64())),
    })
    df = session.create_dataframe(t)
    out = assert_tpu_cpu_equal(df.explode("m"), ignore_order=False)
    assert out.column("key").to_pylist() == ["a", "b"]
    assert out.column("value").to_pylist() == [1, 2]


def test_collect_list_set(session):
    rng = np.random.default_rng(5)
    t = pa.table({
        "k": rng.integers(0, 4, 200),
        "v": rng.integers(0, 10, 200),
    })
    df = session.create_dataframe(t, num_partitions=3)
    q = df.group_by("k").agg(F.collect_list(col("v")).alias("lst"),
                             F.collect_set(col("v")).alias("st"))
    # element ORDER is engine-specific (Spark guarantees none for
    # collect_*; the device merge dedups sets by value sort) — compare
    # per-group multisets against both engines and pandas
    dev = q.collect(device=True).to_pandas().sort_values("k") \
        .reset_index(drop=True)
    cpu = q.collect(device=False).to_pandas().sort_values("k") \
        .reset_index(drop=True)
    pdf = t.to_pandas()
    assert (dev.k == cpu.k).all()
    for i in range(len(dev)):
        exp = pdf[pdf.k == dev.k[i]].v.tolist()
        assert sorted(dev.lst[i]) == sorted(cpu.lst[i]) == sorted(exp)
        assert sorted(dev.st[i]) == sorted(cpu.st[i]) == sorted(set(exp))


def test_approx_percentile(session):
    rng = np.random.default_rng(6)
    t = pa.table({
        "k": rng.integers(0, 3, 500),
        "v": rng.normal(size=500),
    })
    df = session.create_dataframe(t, num_partitions=2)
    q = df.group_by("k").agg(
        F.approx_percentile(col("v"), 0.5).alias("med"),
        F.approx_percentile(col("v"), [0.25, 0.75]).alias("iqr"))
    out = assert_tpu_cpu_equal(q)
    pdf = t.to_pandas()
    for k, med, iqr in zip(out.column("k").to_pylist(),
                           out.column("med").to_pylist(),
                           out.column("iqr").to_pylist()):
        vals = np.sort(pdf[pdf.k == k].v.to_numpy())
        # t-digest interpolates between centroids (reference
        # GpuApproximatePercentile documents the same divergence from the
        # exact-value pick); at default accuracy the rank error is tiny
        lo = vals[max(0, round(0.5 * (len(vals) - 1)) - 2)]
        hi = vals[min(len(vals) - 1, round(0.5 * (len(vals) - 1)) + 2)]
        assert lo <= med <= hi, (k, med, lo, hi)
        assert len(iqr) == 2 and iqr[0] <= med <= iqr[1]


def test_approx_percentile_accuracy_bounds_state():
    """The accuracy argument bounds the sketch size (ADVICE: partial state
    must not be O(rows)); rank error stays within ~1/accuracy."""
    from spark_rapids_tpu.utils.tdigest import (build_digest, digest_quantiles,
                                                merge_digests)
    rng = np.random.default_rng(42)
    data = rng.lognormal(size=200_000)
    delta = 200
    parts = [build_digest(chunk, delta)
             for chunk in np.array_split(data, 16)]
    assert all(len(p) <= 2 + 2 * (delta // 2 + 2) for p in parts), \
        max(len(p) for p in parts)
    merged = merge_digests(parts, delta)
    assert len(merged) <= 2 + 2 * (delta // 2 + 2)
    svals = np.sort(data)
    for q in (0.01, 0.25, 0.5, 0.9, 0.99):
        (est,) = digest_quantiles(merged, [q])
        # rank of the estimate vs requested rank
        rank = np.searchsorted(svals, est) / len(svals)
        assert abs(rank - q) < 0.02, (q, rank)


def test_approx_percentile_accuracy_param(session):
    rng = np.random.default_rng(8)
    t = pa.table({"v": rng.normal(size=5000)})
    df = session.create_dataframe(t, num_partitions=3)
    q = df.agg(F.approx_percentile(col("v"), 0.5, accuracy=100).alias("med"))
    out = assert_tpu_cpu_equal(q)
    med = out.column("med").to_pylist()[0]
    exact = float(np.quantile(t.column("v").to_numpy(), 0.5))
    assert med == pytest.approx(exact, abs=0.1)


def test_device_plan_falls_back_with_reason(adf):
    q = adf.select(F.size(col("arr")).alias("sz"))
    text = q.explain("tpu")
    assert "cannot run on TPU" in text
    # and the device-path collect still works via fallback
    out = q.collect(device=True)
    assert out.column("sz").to_pylist() == [3, 0, -1, 3]


def test_map_keys_values(session):
    t = pa.table({
        "m": pa.array([[("a", 1)], [("b", 2), ("c", 3)], None],
                      type=pa.map_(pa.string(), pa.int64())),
    })
    df = session.create_dataframe(t)
    q = df.select(F.map_keys(col("m")).alias("ks"),
                  F.map_values(col("m")).alias("vs"),
                  F.element_at(col("m"), lit("b")).alias("b"))
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    assert out.column("ks").to_pylist() == [["a"], ["b", "c"], None]
    assert out.column("vs").to_pylist() == [[1], [2, 3], None]
    assert out.column("b").to_pylist() == [None, 2, None]


def test_create_map_dedup_policy(session):
    t = pa.table({"a": [1, 2]})
    df = session.create_dataframe(t)
    # Spark 3.x default spark.sql.mapKeyDedupPolicy=EXCEPTION: duplicates throw
    q = df.select(F.create_map(lit("k"), col("a"), lit("k"), lit(9)).alias("m"))
    with pytest.raises(ValueError, match="Duplicate map key"):
        q.collect(device=False)
    # explicit LAST_WIN override keeps the last value
    q2 = df.select(F.create_map(lit("k"), col("a"), lit("k"), lit(9),
                                dedup_policy="LAST_WIN").alias("m"))
    out = q2.collect(device=False)
    assert out.column("m").to_pylist() == [[("k", 9)], [("k", 9)]]
    # session conf drives the default policy (RapidsConf is immutable)
    saved = session.conf
    session.conf = session.conf.set("spark.sql.mapKeyDedupPolicy", "last_win")
    try:
        out = q.collect(device=False)
        assert out.column("m").to_pylist() == [[("k", 9)], [("k", 9)]]
    finally:
        session.conf = saved


def test_create_map_nan_keys_dedup(session):
    # distinct NaN objects are ONE key after Spark float-key normalization
    t = pa.table({"f": [float("nan"), 1.0]})
    df = session.create_dataframe(t)
    q = df.select(F.create_map(col("f"), lit(1),
                               lit(float("nan")), lit(2)).alias("m"))
    with pytest.raises(ValueError, match="Duplicate map key"):
        q.collect(device=False)


def test_map_dedup_policy_bound_at_plan_time(session):
    """Conf-sensitive expressions freeze their semantics when the plan is
    built: a lazily-executed plan keeps ITS session's policy even after
    another session plans in the meantime."""
    import spark_rapids_tpu.expr.functions as F
    a = type(session)({"spark.sql.mapKeyDedupPolicy": "last_win",
                       "spark.rapids.tpu.batchRowsMinBucket": 8})
    dfa = a.create_dataframe(pa.table({"v": [1]})).select(
        F.create_map(lit("k"), col("v"), lit("k"), lit(9)).alias("m"))
    plan = a._physical(dfa.logical, False)
    b = type(session)({"spark.rapids.tpu.batchRowsMinBucket": 8})
    b.create_dataframe(pa.table({"z": [1]})).collect()   # b becomes active
    out = list(plan.execute(0))
    assert out[0].column("m").values[0] == [("k", 9)]    # A's LAST_WIN


# ---------------------------------------------------------------------------
# Device list layout (round-2 missing #2-#4): ARRAY<fixed-width> with
# containsNull=false runs ON DEVICE — values matrix + lengths, the string
# trick generalized (reference: per-op nesting support TypeChecks.scala:166,
# GpuGenerateExec.scala:631, GpuCollectList/Set AggregateFunctions.scala).
# ---------------------------------------------------------------------------

def _nn_list(elem=pa.int64()):
    return pa.list_(pa.field("item", elem, nullable=False))


@pytest.fixture()
def devarr(session, rng):
    n = 300
    lists = [rng.integers(0, 50, rng.integers(0, 7)).tolist()
             for _ in range(n)]
    mask = rng.random(n) < 0.15
    t = pa.table({
        "a": pa.array([None if m else l for l, m in zip(lists, mask)],
                      type=_nn_list()),
        "f": pa.array([rng.normal(size=rng.integers(0, 5)).tolist()
                       for _ in range(n)], type=_nn_list(pa.float64())),
        "k": pa.array(rng.integers(0, 8, n), type=pa.int64()),
        "v": pa.array(np.where(rng.random(n) < 0.1, None,
                               rng.integers(0, 25, n)), type=pa.int64()),
    })
    return session.create_dataframe(t, num_partitions=2), t


def test_device_array_passthrough_roundtrip(devarr):
    df, t = devarr
    dev = df.collect(device=True)
    cpu = df.collect(device=False)
    assert dev.column("a").to_pylist() == cpu.column("a").to_pylist() \
        == t.column("a").to_pylist()
    assert dev.column("f").to_pylist() == t.column("f").to_pylist()


def test_device_array_scalar_ops(devarr):
    df, t = devarr
    from spark_rapids_tpu.expr.collections import (
        ArrayContains, ArrayMax, ArrayMin, ElementAt, GetArrayItem, Size)
    from spark_rapids_tpu.expr.functions import Column
    q = df.select(
        Column(Size(col("a").expr)).alias("sz"),
        Column(GetArrayItem(col("a").expr, lit(1).expr)).alias("g1"),
        Column(ElementAt(col("a").expr, lit(-1).expr)).alias("em1"),
        Column(ElementAt(col("a").expr, lit(2).expr)).alias("e2"),
        Column(ArrayContains(col("a").expr, lit(25).expr)).alias("ct"),
        Column(ArrayMin(col("a").expr)).alias("mn"),
        Column(ArrayMax(col("a").expr)).alias("mx"),
        Column(ArrayMin(col("f").expr)).alias("fmn"),
        Column(ArrayMax(col("f").expr)).alias("fmx"),
    )
    ex = q.explain("tpu")
    assert "CpuProjectExec will run on TPU" in ex, ex
    d = q.collect(device=True)
    c = q.collect(device=False)
    for name in d.column_names:
        got, exp = d.column(name).to_pylist(), c.column(name).to_pylist()
        for g, e in zip(got, exp):
            same = (g == e) or (isinstance(g, float) and isinstance(e, float)
                                and np.isnan(g) and np.isnan(e))
            assert same, (name, g, e)


def test_device_explode_posexplode_matrix(devarr):
    df, t = devarr
    for outer in (False, True):
        for pos in (False, True):
            q = df.explode("a", *(["p", "e"] if pos else ["e"]),
                           outer=outer, pos=pos)
            ex = q.explain("tpu")
            assert "CpuGenerateExec will run on TPU" in ex, ex
            d = q.collect(device=True)
            c = q.collect(device=False)
            assert d.num_rows == c.num_rows, (outer, pos)
            for name in d.column_names:
                assert d.column(name).to_pylist() == \
                    c.column(name).to_pylist(), (outer, pos, name)


def test_device_collect_list_set(devarr):
    df, t = devarr
    q = df.group_by("k").agg(F.collect_list(col("v")).alias("cl"),
                             F.collect_set(col("v")).alias("cs"))
    d = q.collect(device=True).to_pandas().sort_values("k") \
        .reset_index(drop=True)
    c = q.collect(device=False).to_pandas().sort_values("k") \
        .reset_index(drop=True)
    assert (d.k == c.k).all()
    pdf = t.to_pandas().dropna(subset=["v"])
    exp = pdf.groupby("k").v.apply(
        lambda s: sorted(s.astype(int))).to_dict()
    for i in range(len(d)):
        # element ORDER is engine-specific (as in Spark); compare multisets
        assert sorted(d.cl[i]) == sorted(c.cl[i]) == exp.get(d.k[i], [])
        assert sorted(d.cs[i]) == sorted(c.cs[i]) == \
            sorted(set(exp.get(d.k[i], [])))
        assert len(d.cs[i]) == len(set(d.cs[i]))


def test_device_collect_feeds_explode(devarr):
    """collect_list output (device list layout) flows on into explode."""
    df, t = devarr
    q = df.group_by("k").agg(F.collect_list(col("v")).alias("cl")) \
        .explode("cl", "e")
    d = q.collect(device=True).to_pandas().sort_values(["k", "e"]) \
        .reset_index(drop=True)
    c = q.collect(device=False).to_pandas().sort_values(["k", "e"]) \
        .reset_index(drop=True)
    assert (d.k == c.k).all() and (d.e == c.e).all()


def test_inner_null_arrays_run_on_device(session):
    """containsNull=true arrays ride the element-validity plane on device
    (round-4 VERDICT item 5): size/element access honor inner nulls and the
    plan does NOT fall back."""
    t = pa.table({"a": pa.array([[1, None, 3], [4]],
                                type=pa.list_(pa.int64()))})
    df = session.create_dataframe(t)
    from spark_rapids_tpu.expr.collections import GetArrayItem, Size
    from spark_rapids_tpu.expr.base import Literal
    from spark_rapids_tpu.expr.functions import Column
    from spark_rapids_tpu.columnar import dtypes as dt
    q = df.select(
        Column(Size(col("a").expr)).alias("sz"),
        Column(GetArrayItem(col("a").expr, Literal(1, dt.INT))).alias("e1"))
    ex = q.explain("tpu")
    assert "containsNull" not in ex, ex
    d = q.collect(device=True)
    assert d.column("sz").to_pylist() == [3, 1]
    assert d.column("e1").to_pylist() == [None, None]
    # round-trip: the null element survives upload + download
    rt = df.collect(device=True).column("a").to_pylist()
    assert rt == [[1, None, 3], [4]]


def test_supported_ops_shows_array_support():
    from spark_rapids_tpu.tools.supported_ops import supported_ops_markdown
    md = supported_ops_markdown()
    for op in ("Size", "GetArrayItem", "ElementAt", "ArrayContains",
               "ArrayMin", "ArrayMax"):
        row = next((l for l in md.splitlines()
                    if l.startswith(f"| {op} ")), None)
        assert row is not None, op
        assert "PS" in row or " S " in row, row


# ---------------------------------------------------------------------------
# device struct/map layout (round-4 VERDICT item 5)
# ---------------------------------------------------------------------------

def test_struct_device_roundtrip_and_field_access(session):
    """Struct-of-planes: nested struct with string/array fields round-trips
    through the device and field access is a plane select."""
    t = pa.table({
        "s": pa.array(
            [{"x": 1, "y": "ab", "a": [1, 2], "in": {"z": 9.5}},
             {"x": 2, "y": None, "a": [None, 3], "in": None},
             None],
            type=pa.struct([
                ("x", pa.int64()), ("y", pa.string()),
                ("a", pa.list_(pa.int64())),
                ("in", pa.struct([("z", pa.float64())]))])),
    })
    df = session.create_dataframe(t)
    rt = df.collect(device=True).column("s").to_pylist()
    assert rt == t.column("s").to_pylist()
    q = df.select(
        col("s").getField("x").alias("x"),
        col("s").getField("y").alias("y"),
        col("s").getField("a").alias("a"),
        col("s").getField("in").getField("z").alias("z"))
    ex = q.explain("tpu")
    assert "CpuProjectExec will run on TPU" in ex, ex
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    assert out.column("x").to_pylist() == [1, 2, None]
    assert out.column("y").to_pylist() == ["ab", None, None]
    assert out.column("a").to_pylist() == [[1, 2], [None, 3], None]
    assert out.column("z").to_pylist() == [9.5, None, None]


def test_map_device_ops(session):
    t = pa.table({
        "m": pa.array([[(1, 10.5), (2, 20.5)], [], None, [(3, None)]],
                      type=pa.map_(pa.int64(), pa.float64())),
        "k": [1, 1, 1, 3],
    })
    df = session.create_dataframe(t)
    q = df.select(
        F.element_at(col("m"), 1).alias("e1"),
        F.map_keys(col("m")).alias("mk"),
        F.map_values(col("m")).alias("mv"),
        F.size(col("m")).alias("sz"))
    ex = q.explain("tpu")
    assert "CpuProjectExec will run on TPU" in ex, ex
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    assert out.column("e1").to_pylist() == [10.5, None, None, None]
    assert out.column("mk").to_pylist() == [[1, 2], [], None, [3]]
    assert out.column("mv").to_pylist() == [[10.5, 20.5], [], None, [None]]
    assert out.column("sz").to_pylist() == [2, 0, -1, 1]
    # map round-trip incl. a null value entry
    rt = df.collect(device=True).column("m").to_pylist()
    assert rt == t.column("m").to_pylist()


def test_create_map_device_last_win(session):
    t = pa.table({"a": [1, 2], "b": [10.0, 20.0]},
                 schema=pa.schema([pa.field("a", pa.int64(), nullable=False),
                                   pa.field("b", pa.float64(),
                                            nullable=False)]))
    sess_lw = type(session)({"spark.rapids.tpu.batchRowsMinBucket": 64,
                             "spark.sql.mapKeyDedupPolicy": "last_win"})
    df = sess_lw.create_dataframe(t)
    q = df.select(F.create_map(col("a"), col("b"),
                               col("a"), col("b") + lit(1.0),
                               lit(99), col("b")).alias("m"))
    ex = q.explain("tpu")
    assert "CpuProjectExec will run on TPU" in ex, ex
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    # duplicate key "a": first position, last value (dict semantics)
    assert out.column("m").to_pylist() == \
        [[(1, 11.0), (99, 10.0)], [(2, 21.0), (99, 20.0)]]


def test_string_key_maps_fall_back(session):
    t = pa.table({"m": pa.array([[("k", 1)]],
                                type=pa.map_(pa.string(), pa.int64()))})
    df = session.create_dataframe(t)
    q = df.select(F.size(col("m")).alias("sz"))
    ex = q.explain("tpu")
    assert "map key" in ex, ex          # host fallback reason recorded
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    assert out.column("sz").to_pylist() == [1]


def test_struct_groupby_keys_on_device(session):
    """Struct group-by keys flatten field planes into the sort-key word
    list (round-4 VERDICT item 5; reference: TypeChecks.scala:166)."""
    t = pa.table({
        "s": pa.array([{"a": 1, "b": "x"}, {"a": 1, "b": "x"},
                       {"a": 2, "b": None}, None, {"a": 2, "b": None},
                       {"a": 1, "b": "y"}] * 3,
                      type=pa.struct([("a", pa.int64()),
                                      ("b", pa.string())])),
        "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0] * 3,
    })
    df = session.create_dataframe(t, num_partitions=3)
    q = df.group_by("s").agg(F.sum(col("v")).alias("sv"),
                             F.count(col("v")).alias("c"))
    ex = q.explain("tpu")
    assert "group-by key" not in ex, ex     # no struct-key fallback
    out = assert_tpu_cpu_equal(q)
    assert out.num_rows == 4                # 3 structs + the null row
