"""Event-log persistence + replay tests (reference: Profiler.scala event-log
analytics; here the engine writes its own JSONL log, tools/eventlog.py
replays it post-hoc)."""
import glob
import os

import numpy as np
import pandas as pd

from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.tools.eventlog import load_event_log
from spark_rapids_tpu.expr.functions import col, sum as f_sum


def _run_app(tmp_path):
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.shuffle.partitions": 4,
        "spark.rapids.tpu.shuffle.mode": "host",
    })
    rng = np.random.default_rng(1)
    df = sess.create_dataframe(pd.DataFrame({
        "g": rng.integers(0, 10, 2000).astype(np.int64),
        "x": rng.normal(size=2000),
    }), num_partitions=3)
    df.filter(col("x") > 0).select("g", "x").collect()
    df.group_by("g").agg(f_sum(col("x")).alias("sx")).collect()
    sess.close()
    (path,) = glob.glob(os.path.join(str(tmp_path), "*.jsonl"))
    return path


def test_event_log_round_trip(tmp_path):
    path = _run_app(tmp_path)
    app = load_event_log(path)
    assert len(app.queries) == 2
    assert app.conf, "conf snapshot missing"
    q1 = app.query(1)
    assert q1.wall_s > 0
    assert q1.nodes, "no node events"
    names = {n["name"] for n in q1.nodes}
    assert any("Filter" in n or "WholeStage" in n or "Fused" in n
               for n in names), names
    # every node carries timing + row counts
    assert all("wall_s" in n and "rows" in n for n in q1.nodes)
    assert "query 1" in q1.summary()


def test_event_log_aqe_events_and_final_plan(tmp_path):
    path = _run_app(tmp_path)
    app = load_event_log(path)
    q2 = app.query(2)  # the group-by runs through AQE
    assert q2.final_plan, "final plan missing"
    assert any("materialized stage" in e for e in q2.aqe_events), \
        q2.aqe_events


def test_timeline_svg_and_dot(tmp_path):
    path = _run_app(tmp_path)
    app = load_event_log(path)
    q = app.query(2)
    svg = q.timeline_svg()
    assert svg.startswith("<svg") and "<rect" in svg
    dot = q.to_dot()
    assert dot.startswith("digraph") and "->" in dot
    # app-level report aggregates operators across queries
    s = app.summary()
    assert "hottest operators" in s and "2 queries" in s
    assert isinstance(app.health_check(), list)


def test_event_log_disabled_by_default(tmp_path):
    sess = TpuSession({"spark.rapids.tpu.shuffle.mode": "host"})
    df = sess.create_dataframe(pd.DataFrame({"a": [1, 2, 3]}))
    df.collect()
    assert sess._event_logger() is None


def test_profile_query_xla_trace(tmp_path):
    """NvtxWithMetrics analogue: profiling under jax.profiler.trace with
    per-operator annotations produces a TensorBoard trace dir."""
    from spark_rapids_tpu.tools.profiler import profile_query
    sess = TpuSession({"spark.rapids.tpu.shuffle.mode": "host"})
    df = sess.create_dataframe(pd.DataFrame({
        "a": np.arange(100, dtype=np.int64)}))
    q = df.filter(col("a") % 2 == 0)
    trace_dir = str(tmp_path / "xla_trace")
    prof = profile_query(q, device=True, xla_trace_dir=trace_dir)
    assert prof.total_s > 0
    assert os.path.isdir(trace_dir)
    assert glob.glob(os.path.join(trace_dir, "**", "*"), recursive=True)


def test_qualify_event_log(session, tmp_path):
    """Offline qualification from a recorded JSONL app (round-4 VERDICT
    item 10; reference: Qualification.scala:34 scores recorded apps)."""
    import os
    import pyarrow as pa
    import spark_rapids_tpu.expr.functions as F
    from spark_rapids_tpu.expr.functions import col
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.tools.qualification import qualify_event_log

    d = str(tmp_path / "evt")
    sess = TpuSession({"spark.rapids.tpu.batchRowsMinBucket": 64,
                       "spark.rapids.tpu.eventLog.dir": d})
    t = pa.table({"k": [1, 2, 3] * 20, "v": [1.5] * 60})
    df = sess.create_dataframe(t, num_partitions=2)
    df.group_by("k").agg(F.sum(col("v")).alias("sv")).collect(device=True)
    sess.close()
    logs = [os.path.join(d, f) for f in os.listdir(d)]
    rep = qualify_event_log(logs[0])
    assert rep.queries and 0.0 <= rep.score <= 1.0
    assert rep.estimated_speedup >= 1.0
    assert "qualification" in rep.summary()


def test_event_log_shuffle_skew_records_v7(tmp_path):
    """The v7 record: every materialized exchange in a logged app emits
    one shuffle_skew record whose headline imbalance is max/mean of its
    own per-partition row counts, and replay surfaces them per query."""
    import json

    from spark_rapids_tpu.tools.eventlog import (RECORD_TYPES,
                                                 SCHEMA_VERSION)
    assert SCHEMA_VERSION == 12 and RECORD_TYPES["shuffle_skew"] == 7
    path = _run_app(tmp_path)  # host-tier group-by shuffle, 4 partitions
    records = [json.loads(line) for line in open(path, encoding="utf-8")]
    skews = [r for r in records if r["event"] == "shuffle_skew"]
    assert skews, "no shuffle_skew records in a shuffling app"
    for rec in skews:
        per = rec["per_partition_rows"]
        assert rec["partitions"] == len(per) == 4
        assert rec["rows"]["min"] == min(per)
        assert rec["rows"]["max"] == max(per)
        mean = sum(per) / len(per)
        assert abs(rec["rows"]["imbalance"] - max(per) / mean) < 1e-9
        assert rec["bytes"]["imbalance"] >= 1.0
    # replay: the records land on the query that ran the exchange
    app = load_event_log(path)
    assert any(q.shuffle_skew for q in app.queries.values())
    for q in app.queries.values():
        for rec in q.shuffle_skew:
            assert {"event", "query_id", "node_id", "name", "partitions",
                    "rows", "bytes", "per_partition_rows"} <= set(rec)
