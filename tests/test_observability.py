"""Observability subsystem tests: span tracer + Chrome trace export,
tdigest-backed histograms, the process StatsRegistry + Prometheus dump,
event-log schema stability (versioned), and the run-compare tool.

The schema-stability test is the tier-1 guard: future PRs changing the
event-log record shape must bump SCHEMA_VERSION (with a migration note in
docs/observability.md) or this fails."""
import json
import re
import threading

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_tpu.utils.metrics import (Histogram, StatsRegistry,
                                            get_stats)
from spark_rapids_tpu.utils.tracing import Tracer


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------
def test_span_nesting_depth_and_containment():
    tr = Tracer(capacity=100, enabled=True)
    with tr.span("outer", "query", query_id=1):
        with tr.span("inner", "operator"):
            pass
    inner, outer = tr.events()  # children pop (and record) first
    assert inner.name == "inner" and outer.name == "outer"
    assert outer.depth == 0 and inner.depth == 1
    # time containment: the child span lies within the parent span
    assert outer.ts <= inner.ts
    assert inner.ts + inner.dur <= outer.ts + outer.dur + 1.0  # 1us slack
    assert outer.args == {"query_id": 1}


def test_chrome_trace_json_schema():
    tr = Tracer(capacity=100, enabled=True)
    with tr.span("q", "query"):
        pass
    tr.instant("oom", "spill", context="test")
    text = json.dumps(tr.to_chrome_trace())
    obj = json.loads(text)  # must be valid JSON
    evs = obj["traceEvents"]
    assert len(evs) == 2
    for ev in evs:
        assert {"name", "cat", "ph", "ts", "pid", "tid", "args"} <= set(ev)
    spans = [e for e in evs if e["ph"] == "X"]
    assert spans and all("dur" in e for e in spans)
    instants = [e for e in evs if e["ph"] == "i"]
    assert instants and instants[0]["args"]["context"] == "test"
    assert obj["otherData"]["dropped_events"] == 0


def test_ring_buffer_bounds_memory():
    tr = Tracer(capacity=10, enabled=True)
    for i in range(25):
        tr.instant(f"e{i}", "misc")
    evs = tr.events()
    assert len(evs) == 10
    assert tr.dropped == 15
    # the NEWEST events are retained
    assert [e.name for e in evs] == [f"e{i}" for i in range(15, 25)]
    assert tr.to_chrome_trace()["otherData"]["dropped_events"] == 15


def test_disabled_tracer_records_nothing():
    tr = Tracer(capacity=10, enabled=False)
    with tr.span("x", "query"):
        tr.instant("y")
        tr.complete("z", "operator", 0.0, 1.0)
    assert tr.events() == []


def test_tracer_thread_safety():
    tr = Tracer(capacity=10_000, enabled=True)

    def work():
        for i in range(200):
            with tr.span("s", "task", i=i):
                pass

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(tr.events()) == 800
    assert all(e.depth == 0 for e in tr.events())  # per-thread stacks


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------
def test_histogram_quantiles_match_numpy(rng):
    vals = rng.normal(loc=10.0, scale=2.0, size=20_000)
    h = Histogram("lat")
    for v in vals:
        h.observe(v)  # > FLUSH_AT, so the digest merge path runs
    q50, q90, q99 = h.quantiles([0.5, 0.9, 0.99])
    e50, e90, e99 = np.quantile(vals, [0.5, 0.9, 0.99])
    spread = vals.max() - vals.min()
    assert abs(q50 - e50) < 0.02 * spread
    assert abs(q90 - e90) < 0.02 * spread
    assert abs(q99 - e99) < 0.05 * spread
    snap = h.snapshot()
    assert snap["count"] == 20_000
    assert snap["min"] == pytest.approx(vals.min())
    assert snap["max"] == pytest.approx(vals.max())
    assert snap["sum"] == pytest.approx(vals.sum(), rel=1e-9)
    assert {"p50", "p90", "p99"} <= set(snap)


def test_empty_histogram_snapshot():
    assert Histogram("empty").snapshot() == {"count": 0, "sum": 0.0}


def test_metric_registry_histograms_serialize():
    from spark_rapids_tpu.utils.metrics import MetricRegistry
    reg = MetricRegistry()
    reg.add("numOutputRows", 5)
    for v in (1, 2, 3):
        reg.observe("batchRows", v)
    snap = reg.snapshot()
    assert snap["numOutputRows"] == 5
    assert snap["batchRows"]["count"] == 3
    json.dumps(snap)  # event-log records must stay JSON-serializable


# ---------------------------------------------------------------------------
# stats registry + prometheus
# ---------------------------------------------------------------------------
def test_stats_registry_flatten_collect_delta():
    reg = StatsRegistry()
    reg.add("my_counter", 2)
    reg.add("my_counter")
    reg.register_source("src", lambda: {"a": 1, "nested": {"b": 2.5},
                                        "skip": "strings-dropped"})
    c = reg.collect()
    assert c["my_counter"] == 3
    assert c["src_a"] == 1
    assert c["src_nested_b"] == 2.5
    assert "src_skip" not in c
    before = dict(c)
    reg.add("my_counter", 4)
    d = StatsRegistry.delta(reg.collect(), before)
    assert d["my_counter"] == 4 and d["src_a"] == 0


def test_stats_registry_broken_source_skipped():
    reg = StatsRegistry()
    reg.add("ok", 1)
    reg.register_source("bad", lambda: 1 / 0)
    assert reg.collect() == {"ok": 1}


_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{quantile=\"0\.\d+\"\})? -?\d")


def test_prometheus_text_exposition():
    reg = StatsRegistry()
    reg.add("requests_total", 7)
    reg.register_source("cache", lambda: {"hits": 3, "bytes": 1.5})
    for v in range(100):
        reg.observe("latency_seconds", v / 100.0)
    text = reg.prometheus_text()
    lines = text.strip().split("\n")
    assert lines, text
    for line in lines:
        if line.startswith("#"):
            assert re.match(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                            r"(gauge|summary)$", line), line
        else:
            assert _PROM_LINE.match(line), line
    assert "spark_rapids_tpu_requests_total 7" in text
    assert "spark_rapids_tpu_cache_hits 3" in text
    assert 'spark_rapids_tpu_latency_seconds{quantile="0.5"}' in text
    assert "spark_rapids_tpu_latency_seconds_count 100" in text


def test_global_stats_has_all_subsystem_sources(session):
    # touch the subsystems so every default source reports (the semaphore
    # source deliberately reports nothing until a semaphore exists)
    from spark_rapids_tpu.memory.semaphore import get_semaphore
    get_semaphore()
    df = session.create_dataframe(pa.table({"a": [1.0, 2.0, 3.0]}))
    df.collect(device=True)
    keys = set(get_stats().collect())
    for family in ("compile_cache_", "upload_cache_", "shuffle_",
                   "semaphore_"):
        assert any(k.startswith(family) for k in keys), (family, keys)


# ---------------------------------------------------------------------------
# upload-cache race fix (satellite: exec/transitions.py)
# ---------------------------------------------------------------------------
def test_upload_cache_concurrent_bookkeeping():
    from spark_rapids_tpu.columnar.host import HostTable
    from spark_rapids_tpu.exec import transitions as T

    class _Src:
        """Minimal child: re-yields the same decoded host batches."""

        def __init__(self, batches):
            self._batches = batches
            self.schema = None
            self.children = ()

        @property
        def num_partitions(self):
            return 1

        def execute(self, pidx):
            return iter(self._batches)

    T.clear_upload_cache()
    batches = [HostTable.from_arrow(pa.table(
        {"a": np.arange(64, dtype=np.int64) + 64 * i})) for i in range(4)]
    h2d = T.HostToDeviceExec(_Src(batches), min_bucket=8,
                             cache_max_bytes=1 << 30)
    errs = []

    def drain():
        try:
            for _ in range(5):
                assert len(list(h2d.execute_columnar(0))) == 4
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=drain) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    stats = T.upload_cache_stats()
    assert stats["entries"] == 4
    assert stats["hits"] > 0
    # the running byte counter must equal a full recount of the cache
    with T._UPLOAD_LOCK:
        recount = sum(dt.nbytes() for _, per in T._UPLOAD_CACHE.values()
                      for dt in per.values())
    assert stats["bytes"] == recount > 0
    freed = T.clear_upload_cache()
    assert freed == recount
    assert T.upload_cache_stats()["bytes"] == 0


def test_upload_cache_entry_dies_with_batch():
    from spark_rapids_tpu.columnar.host import HostTable
    from spark_rapids_tpu.exec import transitions as T

    class _Src:
        def __init__(self, batches):
            self._batches = batches
            self.schema = None
            self.children = ()

        @property
        def num_partitions(self):
            return 1

        def execute(self, pidx):
            return iter(self._batches)

    T.clear_upload_cache()
    batch = HostTable.from_arrow(pa.table({"a": np.arange(32)}))
    src = _Src([batch])
    h2d = T.HostToDeviceExec(src, min_bucket=8, cache_max_bytes=1 << 30)
    list(h2d.execute_columnar(0))
    assert T.upload_cache_stats()["entries"] == 1
    del batch
    src._batches = []  # drop the last strong reference
    import gc
    gc.collect()
    stats = T.upload_cache_stats()
    assert stats["entries"] == 0
    assert stats["bytes"] == 0  # running counter followed the eviction


# ---------------------------------------------------------------------------
# catalog satellites: OOM-callback logging + external-bytes accounting
# ---------------------------------------------------------------------------
def test_oom_callback_exception_is_logged():
    from spark_rapids_tpu.memory.catalog import BufferCatalog

    cat = BufferCatalog(device_limit=1 << 20, host_limit=1 << 20)

    def bad_callback():
        raise RuntimeError("boom from cache dropper")

    cat.register_oom_callback(bad_callback)
    with pytest.warns(RuntimeWarning, match="OOM callback .* failed"):
        cat.handle_device_oom("unit test")
    # the empty catalog had nothing to spill, so if the sticky process
    # global memory profiler is active this queued a postmortem — drain
    # it so it can't leak into the next logged app's event log
    from spark_rapids_tpu.utils.memprof import active
    mp = active()
    if mp is not None:
        mp.drain_postmortems()
    assert cat.oom_callback_errors == 1
    assert any("boom from cache dropper" in d for d in cat.diagnostics)
    assert cat.counters()["oom_callback_errors"] == 1
    assert cat.stats()["oom_callback_errors"] == 1
    # the failure shows up in the OOM dump diagnostics too
    assert "boom from cache dropper" in cat.oom_dump()


def test_catalog_accounts_external_device_bytes():
    from spark_rapids_tpu.memory.catalog import BufferCatalog

    cat = BufferCatalog(device_limit=1 << 20, host_limit=1 << 20)
    cat.register_external_bytes("upload_cache_test", lambda: 1234)
    assert cat.external_device_bytes() == 1234
    assert cat.device_in_use_bytes() == cat.device.used_bytes + 1234
    assert cat.peak_device_bytes >= 1234
    assert cat.stats()["external_bytes"]["upload_cache_test"] == 1234
    assert "upload_cache_test=1234" in cat.oom_dump()
    # a broken source reports 0, never raises
    cat.register_external_bytes("broken", lambda: 1 / 0)
    assert cat.external_device_bytes() == 1234


# ---------------------------------------------------------------------------
# event-log schema stability (versioned) + per-query counter deltas
# ---------------------------------------------------------------------------
_REQUIRED_KEYS = {
    "app_start": {"event", "app_id", "schema_version", "ts", "conf"},
    # v5: queries carry their distributed trace identity
    "query_start": {"event", "query_id", "ts", "plan", "trace_id"},
    "node": {"event", "query_id", "node_id", "parent_id", "name", "desc",
             "depth", "wall_s", "rows", "batches", "t_first", "t_last",
             "metrics", "peak_device_bytes"},  # peak_device_bytes: v6
    # v3: one record per XLA program the query touched (kernel table)
    "kernel": {"event", "query_id", "first_query_id", "signature",
               "node_name", "node_id", "hits", "misses", "compiles",
               "compile_s", "cost", "memory"},
    "query_end": {"event", "query_id", "ts", "wall_s", "final_plan",
                  "aqe_events", "spill_count", "semaphore_wait_s", "stats",
                  "trace_id", "critical_path"},
    # v6: per-query memory flight-recorder summary, ALWAYS written
    # (summary is null when profiling is off) so the record set is
    # stable; oom_postmortem records appear only on an actual OOM and
    # are pinned separately (test_eventlog_oom_postmortem_record_keys
    # in tests/test_memprof.py)
    "memory_summary": {"event", "query_id", "ts", "summary"},
    # v7: per-exchange output-partition row/byte distribution — one per
    # exchange node that materialized (the host-tier group-by shuffle in
    # _run_logged_app below guarantees at least one)
    "shuffle_skew": {"event", "query_id", "node_id", "name", "partitions",
                     "rows", "bytes", "per_partition_rows"},
    # v8: per-query recovery-ledger delta, ALWAYS written (recovery is
    # null when the query needed no recovery — the zero-overhead pin);
    # fault records appear only when injection actually fired and are
    # pinned separately in tests/test_faults.py
    "recovery": {"event", "query_id", "ts", "recovery"},
    # v10: fallback records appear only when a batch actually re-executed
    # on the host engine and are pinned separately
    # (test_eventlog_v10_fallback_records in tests/test_fallback.py)
    # v11: per-query data-movement summary, ALWAYS written (movement is
    # null when the observatory is off, as in this run) so the record
    # set is stable; the populated shape is pinned in
    # tests/test_movement.py
    "movement_summary": {"event", "query_id", "ts", "movement"},
    # v12: per-query shuffle-observatory summary, ALWAYS written
    # (shuffle is null when the observatory is off, as in this run) so
    # the record set is stable; the populated shape is pinned in
    # tests/test_shuffle_observatory.py
    "shuffle_summary": {"event", "query_id", "ts", "shuffle"},
    "app_end": {"event", "ts"},
}


def _run_logged_app(tmp_path):
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.expr.functions import col, sum as f_sum
    from spark_rapids_tpu.utils.memprof import active
    mp = active()
    if mp is not None:
        # postmortems queued by earlier tests against the sticky process
        # global profiler would otherwise drain into THIS app's log and
        # break the exact record-type-set assertion below
        mp.drain_postmortems()
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.shuffle.partitions": 2,
        "spark.rapids.tpu.shuffle.mode": "host",
    })
    rng = np.random.default_rng(7)
    df = sess.create_dataframe(pd.DataFrame({
        "g": rng.integers(0, 5, 400).astype(np.int64),
        "x": rng.normal(size=400)}), num_partitions=2)
    df.group_by("g").agg(f_sum(col("x")).alias("sx")).collect(device=True)
    sess.close()
    import glob
    import os
    (path,) = glob.glob(os.path.join(str(tmp_path), "*.jsonl"))
    return path


def test_eventlog_schema_version_and_required_keys(tmp_path):
    from spark_rapids_tpu.tools.eventlog import SCHEMA_VERSION
    path = _run_logged_app(tmp_path)
    records = [json.loads(line) for line in open(path, encoding="utf-8")]
    by_type = {}
    for rec in records:
        by_type.setdefault(rec["event"], []).append(rec)
    assert set(by_type) == set(_REQUIRED_KEYS)
    # the pinned version: bump SCHEMA_VERSION (and this test + the docs)
    # when the record shape changes. v4 added heartbeat records (health
    # monitor off in this run, so none appear here; tests/test_health.py
    # pins the heartbeat record keys). v5 adds the distributed-trace
    # identity: trace_id on query_start/query_end, critical_path on
    # query_end (null when tracing is off, as here). v6 adds the memory
    # flight recorder: per-query memory_summary, peak_device_bytes on
    # node records, oom_postmortem records on OOM. v7 adds shuffle_skew:
    # per-exchange output-partition distribution records. v8 adds the
    # fault-injection/recovery telemetry: an always-written per-query
    # recovery record (null payload here — no faults, no recovery) plus
    # fault records when injection fires. v9 adds oom_retry records —
    # one per retry scope that engaged the device-OOM escalation ladder
    # (none in this pressure-free run; pinned in tests/test_oom_retry.py).
    # v10 adds fallback records — one per batch re-executed through the
    # host engine after a terminal device failure (none on a healthy
    # device; pinned in tests/test_fallback.py). v11 adds the
    # always-written per-query movement_summary (null payload here —
    # observatory off; populated shape pinned in tests/test_movement.py).
    # v12 adds the always-written per-query shuffle_summary (null payload
    # here — shuffle observatory off; populated shape pinned in
    # tests/test_shuffle_observatory.py)
    assert SCHEMA_VERSION == 12
    assert by_type["app_start"][0]["schema_version"] == SCHEMA_VERSION
    for kind, required in _REQUIRED_KEYS.items():
        for rec in by_type[kind]:
            missing = required - set(rec)
            assert not missing, (kind, missing)


def test_eventlog_v3_kernel_records_and_node_metrics(tmp_path):
    """v3: kernel records key XLA programs back to nodes; node metric
    snapshots carry the per-node byte/compile attribution."""
    from spark_rapids_tpu.tools.eventlog import load_event_log
    path = _run_logged_app(tmp_path)
    app = load_event_log(path)
    q = app.query(1)
    assert q.kernels, "no kernel records in a device query"
    for k in q.kernels:
        assert k["signature"]
        assert k["compiles"] + k["hits"] + k["misses"] > 0
        assert isinstance(k["cost"], dict)
    # instrumented runs attribute each program to its requesting operator
    assert any(k.get("node_name") for k in q.kernels), q.kernels
    # programs first compiled by THIS query record it as their origin
    compiled_here = [k for k in q.kernels if k["compiles"]]
    assert all(k["first_query_id"] == 1 for k in compiled_here), q.kernels
    # per-node metric snapshots include transition byte accounting
    all_metrics = {m for n in q.nodes for m in (n.get("metrics") or {})}
    assert "hostToDeviceBytes" in all_metrics, sorted(all_metrics)
    assert "deviceToHostBytes" in all_metrics, sorted(all_metrics)
    # and per-node compile-cache attribution (hits or misses, run-order
    # dependent: the plan's programs may already be cached process-wide)
    assert all_metrics & {"xlaCacheHits", "xlaCacheMisses"}, \
        sorted(all_metrics)


def test_kernel_table_capture(session):
    """utils/compile_cache.py kernel table: cost analysis captured per
    plan signature, hits accumulate on reuse."""
    from spark_rapids_tpu.expr.functions import col
    from spark_rapids_tpu.utils.compile_cache import (kernel_seq,
                                                      kernels_since)
    rng = np.random.default_rng(5)
    df = session.create_dataframe(
        pa.table({"x": rng.normal(size=300)})).filter(col("x") > 0.0)
    s0 = kernel_seq()
    df.collect(device=True)
    touched = kernels_since(s0)
    assert touched, "device query touched no kernel-table entries"
    entry = max(touched, key=lambda e: e["compile_s"] + e["hits"]
                + e["misses"])
    assert entry["signature"]
    # the default 'lowered' introspection captures HLO cost analysis the
    # first time a program compiles in this process
    compiled_here = [e for e in touched if e["compiles"]]
    for e in compiled_here:
        assert e["cost"].get("bytes accessed", 0) >= 0  # present & numeric
    s1 = kernel_seq()
    df.collect(device=True)  # steady state: pure hits
    again = kernels_since(s1)
    assert again and all(e["hits"] >= 1 for e in again)


def test_kernel_table_eviction_keeps_newest():
    """At capacity the LEAST-recently-touched entry is dropped — never the
    entry being inserted (regression: a fresh entry carried the minimum
    touch stamp and evicted itself, freezing the table)."""
    from spark_rapids_tpu.utils import compile_cache as cc
    with cc._LOCK:
        saved = dict(cc._KERNELS)
        cc._KERNELS.clear()
    old_max = cc._KERNEL_TABLE_MAX
    cc._KERNEL_TABLE_MAX = 2
    try:
        with cc._LOCK:
            for key in ("sig_a", "sig_b", "sig_c"):
                cc._kernel_entry_locked(key)
            assert set(cc._KERNELS) == {"sig_b", "sig_c"}
    finally:
        cc._KERNEL_TABLE_MAX = old_max
        with cc._LOCK:
            cc._KERNELS.clear()
            cc._KERNELS.update(saved)


def test_explain_analyze_output(session):
    """df.explain('analyze') executes and renders per-node wall/rows with
    %-of-wall annotations; self times must cover >= 90% of query wall."""
    import re as _re
    from spark_rapids_tpu.expr.functions import col, sum as f_sum
    from spark_rapids_tpu.utils.compile_cache import clear_cache
    # cold cache: compile wall (node-attributed) dominates, so the >=90%
    # coverage bound is deterministic regardless of test ordering; warm
    # micro-queries legitimately sit lower (driver glue is not operator
    # time) while real TPC-H-scale queries stay >=90% either way
    clear_cache()
    rng = np.random.default_rng(9)
    df = session.create_dataframe(pa.table({
        "k": rng.integers(0, 3, 400), "v": rng.normal(size=400)}),
        num_partitions=2)
    text = df.group_by("k").agg(f_sum(col("v")).alias("s")) \
        .explain("analyze")
    assert "EXPLAIN ANALYZE" in text
    assert "rows" in text and "batches" in text
    assert _re.search(r"\(\s*\d+\.\d%\)", text), text
    m = _re.search(r"self times cover (\d+)% of wall", text)
    assert m, text
    assert int(m.group(1)) >= 90, text
    # the executed (post-override) tree shows device operators
    assert "Tpu" in text


def test_profile_summary_timeline_column(session):
    from spark_rapids_tpu.expr.functions import col
    from spark_rapids_tpu.tools.profiler import profile_query
    rng = np.random.default_rng(13)
    df = session.create_dataframe(
        pa.table({"x": rng.normal(size=200)})).filter(col("x") > 0)
    prof = profile_query(df, device=True)
    s = prof.summary()
    assert "timeline" in s
    # at least one operator shows an activity bar scaled into the window
    assert "=" in s.split("timeline", 1)[1]
    for n in prof.nodes:
        if n.batches:
            bar = prof._timeline(n)
            assert len(bar) == prof.TIMELINE_WIDTH
            assert "=" in bar


def test_explain_analyze_renders_from_eventlog_records(tmp_path):
    """render_analyzed_plan accepts replayed node dicts too (same keys)."""
    from spark_rapids_tpu.plan.meta import render_analyzed_plan
    from spark_rapids_tpu.tools.eventlog import load_event_log
    path = _run_logged_app(tmp_path)
    q = load_event_log(path).query(1)
    text = render_analyzed_plan(q.nodes, q.wall_s, kernels=q.kernels)
    assert "EXPLAIN ANALYZE" in text and "XLA kernels" in text


# ---------------------------------------------------------------------------
# tier-1 metric lint: every Tpu*Exec ships observable (satellite 6)
# ---------------------------------------------------------------------------
def test_every_tpu_exec_registers_and_updates_core_metrics():
    """Every concrete device operator must (a) pre-register the core metric
    set and (b) actually touch its registry in its execution path — a new
    operator that ships without metrics fails HERE, not in production."""
    import importlib
    import inspect
    import pkgutil

    import spark_rapids_tpu.exec as exec_pkg
    import spark_rapids_tpu.plan.aqe  # registers TpuStageReaderExec
    import spark_rapids_tpu.udf.python_exec  # device exec outside exec/
    from spark_rapids_tpu.exec.base import TpuExec
    from spark_rapids_tpu.utils.metrics import CORE_NODE_METRICS

    for m in pkgutil.iter_modules(exec_pkg.__path__):
        importlib.import_module(f"spark_rapids_tpu.exec.{m.name}")

    def subclasses(c):
        for s in c.__subclasses__():
            yield s
            yield from subclasses(s)

    checked = 0
    offenders = []
    for cls in sorted(set(subclasses(TpuExec)), key=lambda c: c.__name__):
        # declared extra metrics must be metric-name strings
        assert all(isinstance(x, str) for x in cls.EXTRA_METRICS), cls
        if "execute_columnar" not in cls.__dict__ \
                and "_materialize" not in cls.__dict__:
            continue  # inherits an already-linted execution path
        if getattr(cls, "_metrics_exempt", None):
            continue  # explicit opt-out with a recorded reason
        checked += 1
        src = inspect.getsource(cls)
        if "self.metrics." not in src and "self.account_batch(" not in src:
            offenders.append(cls.__name__)
    assert checked >= 15, f"lint only saw {checked} exec classes"
    assert not offenders, (
        f"device execs with no metric accounting in their execution path: "
        f"{offenders} — register/update the core set (exec/base.py "
        f"account_batch) or set _metrics_exempt = '<reason>'")
    # registration side: the base constructor pre-creates the core set
    # (plus declared extras) on every instance

    class _Probe(TpuExec):
        EXTRA_METRICS = ("probeTime",)

        def __init__(self):
            super().__init__()

    reg = _Probe().metrics
    for name in CORE_NODE_METRICS + ("probeTime",):
        assert name in reg._metrics, name


def test_eventlog_query_stats_cover_all_subsystems(tmp_path):
    from spark_rapids_tpu.tools.eventlog import load_event_log
    path = _run_logged_app(tmp_path)
    app = load_event_log(path)
    assert app.schema_version == 12
    q = app.query(1)
    assert q.stats, "query_end stats delta missing"
    for family in ("compile_cache_", "upload_cache_", "shuffle_",
                   "semaphore_", "catalog_"):
        assert any(k.startswith(family) for k in q.stats), \
            (family, sorted(q.stats))
    # replayed node metrics keep the operator metric snapshots
    assert any(n.get("metrics") for n in q.nodes)


def test_profile_query_reports_all_counter_families(session):
    from spark_rapids_tpu.expr.functions import col, sum as f_sum
    from spark_rapids_tpu.tools.profiler import profile_query
    rng = np.random.default_rng(11)
    df = session.create_dataframe(pa.table({
        "k": rng.integers(0, 4, 500), "v": rng.normal(size=500)}),
        num_partitions=2)
    q = df.group_by("k").agg(f_sum(col("v")).alias("s"))
    prof = profile_query(q, device=True)
    for family in ("compile_cache_", "upload_cache_", "shuffle_",
                   "semaphore_", "catalog_"):
        assert any(k.startswith(family) for k in prof.stats), \
            (family, sorted(prof.stats))
    assert "counters (this query):" in prof.summary()
    json.loads(prof.to_json())


# ---------------------------------------------------------------------------
# end-to-end: query trace has the span hierarchy
# ---------------------------------------------------------------------------
def test_query_chrome_trace_has_span_categories(tmp_path):
    import glob
    import os
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.expr.functions import col, sum as f_sum
    from spark_rapids_tpu.utils.tracing import get_tracer
    trace_dir = str(tmp_path / "traces")
    sess = TpuSession({
        "spark.rapids.tpu.trace.enabled": True,
        "spark.rapids.tpu.trace.dir": trace_dir,
        "spark.rapids.tpu.eventLog.dir": str(tmp_path / "evt"),
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.shuffle.partitions": 2,
        "spark.rapids.tpu.shuffle.mode": "host",
    })
    try:
        rng = np.random.default_rng(3)
        df = sess.create_dataframe(pa.table({
            "k": rng.integers(0, 4, 600), "v": rng.normal(size=600)}),
            num_partitions=2)
        df.group_by("k").agg(f_sum(col("v")).alias("s")).collect(device=True)
        sess.close()
    finally:
        get_tracer().enabled = False  # don't leak tracing into other tests
    (path,) = glob.glob(os.path.join(trace_dir, "*.json"))
    with open(path, encoding="utf-8") as f:
        obj = json.load(f)  # loadable Chrome trace-event JSON
    evs = obj["traceEvents"]
    cats = {e["cat"] for e in evs}
    # the acceptance bar: >= 3 distinct span categories in one query trace
    assert len(cats) >= 3, cats
    assert "query" in cats and "task" in cats and "operator" in cats, cats
    assert any(e["ph"] == "X" and e["dur"] >= 0 for e in evs)


# ---------------------------------------------------------------------------
# compare tool
# ---------------------------------------------------------------------------
def _fabricate_log(path, op_walls, wall_scale=1.0, stats=None):
    """Write a synthetic event log: one query, given per-op wall times."""
    records = [{"event": "app_start", "app_id": path.stem,
                "schema_version": 3, "ts": 0.0, "conf": {}}]
    records.append({"event": "query_start", "query_id": 1, "ts": 0.0,
                    "plan": "plan"})
    for i, (name, wall) in enumerate(op_walls):
        records.append({
            "event": "node", "query_id": 1, "node_id": i,
            "parent_id": i - 1, "name": name, "desc": "", "depth": i,
            "wall_s": wall, "rows": 1000, "batches": 2,
            "t_first": 0.0, "t_last": wall, "metrics": {}})
    records.append({
        "event": "query_end", "query_id": 1, "ts": 1.0,
        "wall_s": sum(w for _, w in op_walls) * wall_scale,
        "final_plan": "plan", "aqe_events": [],
        "spill_count": {}, "semaphore_wait_s": 0.0,
        "stats": stats or {}})
    records.append({"event": "app_end", "ts": 1.0})
    with open(path, "w", encoding="utf-8") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return str(path)


def test_compare_flags_injected_operator_regression(tmp_path):
    from spark_rapids_tpu.tools.compare import compare_event_logs
    base = _fabricate_log(
        tmp_path / "base.jsonl",
        [("TpuScanExec", 0.10), ("TpuFilterExec", 0.05),
         ("TpuHashAggregateExec", 0.20)],
        stats={"compile_cache_misses": 3, "shuffle_bytes_fetched": 100})
    # inject a 10x regression into the filter only
    cand = _fabricate_log(
        tmp_path / "cand.jsonl",
        [("TpuScanExec", 0.10), ("TpuFilterExec", 0.50),
         ("TpuHashAggregateExec", 0.21)],
        stats={"compile_cache_misses": 9, "shuffle_bytes_fetched": 100})
    rep = compare_event_logs(base, cand, threshold=0.5)
    regs = rep.regressions()
    assert [r.name for r in regs] == ["TpuFilterExec"]
    assert regs[0].ratio == pytest.approx(10.0)
    assert regs[0].delta_s == pytest.approx(0.45)
    (q,) = rep.queries
    assert q.regressed  # 0.35s -> 0.81s overall
    assert q.metric_deltas["compile_cache_misses"] == 6
    assert q.metric_deltas["shuffle_bytes_fetched"] == 0
    s = rep.summary()
    assert "REGRESSED" in s and "TpuFilterExec" in s
    assert "compile_cache_misses=+6" in s


def test_compare_handles_missing_ops_and_queries(tmp_path):
    from spark_rapids_tpu.tools.compare import compare_event_logs
    base = _fabricate_log(tmp_path / "a.jsonl",
                          [("TpuScanExec", 0.1), ("TpuSortExec", 0.2)])
    cand = _fabricate_log(tmp_path / "b.jsonl",
                          [("TpuScanExec", 0.1), ("TpuProjectExec", 0.05)])
    rep = compare_event_logs(base, cand, threshold=0.2)
    (q,) = rep.queries
    only = {op.name: op.only_in for op in q.ops if op.only_in}
    assert only == {"TpuSortExec": "a", "TpuProjectExec": "b"}
    assert not rep.regressions()  # ops missing on one side never flag


def test_compare_real_event_logs_round_trip(tmp_path):
    """Two real runs of the same workload align with no false regressions
    at a generous threshold."""
    from spark_rapids_tpu.tools.compare import compare_event_logs
    a = _run_logged_app(tmp_path / "runA")
    b = _run_logged_app(tmp_path / "runB")
    rep = compare_event_logs(a, b, threshold=1000.0)
    assert rep.queries and not rep.only_in_a and not rep.only_in_b
    (q,) = rep.queries
    assert q.ops and all(not op.only_in for op in q.ops)
    assert q.metric_deltas  # counter deltas came from the stats records
    assert "query 1" in rep.summary()


def test_compare_bench_results(tmp_path):
    from spark_rapids_tpu.tools.compare import compare_bench_results
    a = tmp_path / "bench_a.json"
    b = tmp_path / "bench_b.json"
    # smoke and tpch phases both name q1/q6 (different scale factors);
    # they must align per phase, never shadow or cross-compare
    a.write_text(json.dumps({
        "smoke": {"q6": {"dev_s": 0.01, "cpu_s": 0.02, "speedup": 2.0}},
        "tpch": {"q1": {"dev_s": 1.0, "cpu_s": 4.0, "speedup": 4.0},
                 "q6": {"dev_s": 0.5, "cpu_s": 2.0, "speedup": 4.0}}},
        indent=1))  # pretty-printed, like BENCH_partial.json
    b.write_text(json.dumps({
        "smoke": {"q6": {"dev_s": 0.10, "cpu_s": 0.02, "speedup": 0.2}},
        "tpch": {"q1": {"dev_s": 1.05, "cpu_s": 4.0, "speedup": 3.8},
                 "q6": {"dev_s": 1.5, "cpu_s": 2.0, "speedup": 1.3}}},
        indent=1))
    rep = compare_bench_results(str(a), str(b), threshold=0.2)
    regressed = [q.query_id for q in rep.regressed_queries()]
    assert regressed == ["smoke:q6", "tpch:q6"]
    assert "REGRESSED" in rep.summary()
    # the CLI sniffs pretty-printed bench JSON correctly
    from spark_rapids_tpu.tools.compare import _sniff
    assert _sniff(str(a)) == "bench"


def test_compare_cli(tmp_path, capsys):
    from spark_rapids_tpu.tools.compare import main
    base = _fabricate_log(tmp_path / "a.jsonl", [("TpuScanExec", 0.1)])
    cand = _fabricate_log(tmp_path / "b.jsonl", [("TpuScanExec", 0.9)])
    rc = main([base, cand, "--threshold", "0.5"])
    out = capsys.readouterr().out
    assert rc == 1 and "REGRESSED" in out
    rc = main([base, base])
    assert rc == 0
