"""Device parquet decode tests (reference: GpuParquetScanBase.scala:995,1194
device decode; this path is io/parquet_thrift.py + io/parquet_device.py +
exec/scan.py TpuParquetScanExec)."""
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.expr.functions import col, sum as f_sum

from harness import assert_tables_equal, assert_tpu_cpu_equal


def _write(tmp_path, n=4000, codec="snappy", use_dictionary=True,
           row_group_size=1500, nulls=True, with_strings=True):
    rng = np.random.default_rng(7)
    data = {
        "i64": pa.array(rng.integers(-10**12, 10**12, n), type=pa.int64()),
        "i32": pa.array(rng.integers(-2**30, 2**30, n).astype(np.int32)),
        "f64": pa.array(rng.normal(size=n)),
        "f32": pa.array(rng.normal(size=n).astype(np.float32)),
        "b": pa.array(rng.integers(0, 2, n).astype(bool)),
        "lowcard": pa.array(rng.integers(0, 40, n), type=pa.int64()),
        "date": pa.array(rng.integers(0, 20000, n).astype(np.int32)).cast(
            pa.date32()),
        "ts": pa.array(rng.integers(0, 2**48, n), type=pa.int64()).cast(
            pa.timestamp("us")),
    }
    if with_strings:
        data["s"] = pa.array([f"str{i % 11}" for i in range(n)])
    t = pa.table(data)
    if nulls:
        cols = {}
        for name in t.column_names:
            mask = rng.random(n) < 0.12
            arr = t.column(name).combine_chunks()
            cols[name] = pa.array(arr.to_pylist(), type=arr.type, mask=mask)
        t = pa.table(cols)
    p = str(tmp_path / "data.parquet")
    pq.write_table(t, p, row_group_size=row_group_size, compression=codec,
                   use_dictionary=use_dictionary)
    return p, t


@pytest.fixture
def sess():
    return TpuSession({"spark.rapids.tpu.shuffle.mode": "host",
                       "spark.rapids.tpu.batchRowsMinBucket": 64})


@pytest.mark.parametrize("codec,use_dict", [("snappy", True),
                                            ("none", False),
                                            ("zstd", True),
                                            ("gzip", False)])
def test_device_scan_differential(sess, tmp_path, codec, use_dict):
    p, t = _write(tmp_path, codec=codec, use_dictionary=use_dict)
    df = sess.read_parquet(p)
    dev = df.collect(device=True)
    cpu = df.collect(device=False)
    assert_tables_equal(dev, cpu, ignore_order=False)
    assert_tables_equal(dev, t, ignore_order=False)


def test_device_scan_in_plan_and_kill_switch(sess, tmp_path):
    p, _ = _write(tmp_path)
    df = sess.read_parquet(p)
    plan = sess._physical(df.logical, True)
    assert "TpuParquetScanExec" in plan.tree_string(), plan.tree_string()
    off = TpuSession({
        "spark.rapids.tpu.shuffle.mode": "host",
        "spark.rapids.tpu.parquet.deviceDecode.enabled": False,
    })
    plan2 = off._physical(off.read_parquet(p).logical, True)
    assert "TpuParquetScanExec" not in plan2.tree_string()
    assert_tables_equal(off.read_parquet(p).collect(device=True),
                        off.read_parquet(p).collect(device=False),
                        ignore_order=False)


def test_pushed_filter_keeps_host_reader(sess, tmp_path):
    """Row-group statistics pruning lives in the host reader; a pushed
    filter therefore keeps the scan there (and stays correct)."""
    p, _ = _write(tmp_path, with_strings=False, nulls=False)
    df = sess.read_parquet(p)
    q = df.filter(col("i64") > 0)
    plan = sess._physical(q.logical, True)
    text = plan.tree_string()
    assert "TpuParquetScanExec" not in text, text
    assert_tpu_cpu_equal(q)


def test_device_scan_feeds_aggregate(sess, tmp_path):
    p, t = _write(tmp_path)
    df = sess.read_parquet(p)
    q = df.group_by("lowcard").agg(f_sum(col("f64")).alias("sf"))
    out = assert_tpu_cpu_equal(q, rel_tol=1e-9)
    pdf = t.to_pandas()
    exp = pdf.groupby("lowcard", dropna=False).f64.sum()
    assert out.num_rows == len(exp)


def _find_scan(plan):
    from spark_rapids_tpu.exec.scan import TpuParquetScanExec

    def find(n):
        if isinstance(n, TpuParquetScanExec):
            return n
        for c in n.children:
            r = find(c)
            if r is not None:
                return r
        return None
    return find(plan)


def test_string_columns_decode_on_device(sess, tmp_path):
    """BYTE_ARRAY columns decode on device too (round-2 missing #1;
    reference: GpuParquetScanBase.scala:995,1194) — every column of the
    scan lands in the device-decoded metric, none ride the fallback."""
    p, t = _write(tmp_path)
    df = sess.read_parquet(p)
    plan = sess._physical(df.logical, True)
    scan = _find_scan(plan)
    assert scan is not None
    batches = list(scan.execute_columnar(0))
    assert batches
    snap = scan.metrics.snapshot()
    # ALL 9 columns (incl. the string one) decode on device per row group
    assert snap.get("deviceDecodedColumns", 0) == 9 * len(batches)
    got = pa.concat_tables([b.to_host().to_arrow() for b in batches])
    assert got.column("s").to_pylist() == \
        t.column("s").to_pylist()[:got.num_rows]


def test_column_pruning_through_device_scan(sess, tmp_path):
    p, t = _write(tmp_path)
    df = sess.read_parquet(p).select("i64", "f64")
    dev = df.collect(device=True)
    assert dev.column_names == ["i64", "f64"]
    assert_tables_equal(dev, df.collect(device=False), ignore_order=False)


def test_mixed_width_dictionary_pages(sess, tmp_path):
    """A growing dictionary makes successive pages bit-pack at DIFFERENT
    widths; the run table records width per run (a single chunk-wide width
    silently corrupted 60%+ of values)."""
    rng = np.random.default_rng(11)
    n = 200_000
    # values appear progressively so the dictionary (and index width) grows
    vals = np.minimum(rng.integers(0, 200, n).cumsum() % 120,
                      np.arange(n) // 500)
    t = pa.table({"v": pa.array(vals, type=pa.int64())})
    p = str(tmp_path / "growdict.parquet")
    pq.write_table(t, p, row_group_size=n, data_page_size=8 * 1024,
                   compression="snappy")
    df = sess.read_parquet(p)
    plan = sess._physical(df.logical, True)
    assert "TpuParquetScanExec" in plan.tree_string()
    dev = df.collect(device=True)
    assert dev.column("v").to_pylist() == t.column("v").to_pylist()


def test_unsupported_codec_falls_back_to_host(sess, tmp_path):
    """Hadoop-framed LZ4 is unreadable by pa.decompress; the device decoder
    must fall back per column, never crash (host pyarrow reads it fine)."""
    t = pa.table({"a": pa.array(np.arange(5000, dtype=np.int64)),
                  "b": pa.array(np.random.default_rng(1).normal(size=5000))})
    p = str(tmp_path / "lz4.parquet")
    pq.write_table(t, p, compression="lz4")
    df = sess.read_parquet(p)
    dev = df.collect(device=True)
    cpu = df.collect(device=False)
    assert_tables_equal(dev, cpu, ignore_order=False)
    assert_tables_equal(dev, t, ignore_order=False)


def test_empty_and_single_row_groups(sess, tmp_path):
    t = pa.table({"a": pa.array([], type=pa.int64()),
                  "b": pa.array([], type=pa.float64())})
    p = str(tmp_path / "empty.parquet")
    pq.write_table(t, p)
    df = sess.read_parquet(p)
    assert df.collect(device=True).num_rows == 0
    t2 = pa.table({"a": pa.array([42], type=pa.int64())})
    p2 = str(tmp_path / "one.parquet")
    pq.write_table(t2, p2)
    out = sess.read_parquet(p2).collect(device=True)
    assert out.column("a").to_pylist() == [42]


@pytest.mark.parametrize("label,kw", [
    ("plain-v1", dict(use_dictionary=False)),
    ("mixed-v1", dict(use_dictionary=True,
                      dictionary_pagesize_limit=4096, data_page_size=2048)),
    ("dict-v2", dict(data_page_version="2.0")),
    ("plain-v2", dict(use_dictionary=False, data_page_version="2.0")),
    ("mixed-v2", dict(use_dictionary=True, dictionary_pagesize_limit=4096,
                      data_page_size=2048, data_page_version="2.0")),
])
def test_string_and_v2_page_matrix(sess, tmp_path, label, kw):
    """Strings + numerics across PLAIN / dictionary-overflow-mixed chunks
    and data-page v1/v2 — all decode on DEVICE, bit-identical to host
    (reference: GpuParquetScanBase.scala:995 handles the same page matrix)."""
    import io as _io
    from spark_rapids_tpu.io.parquet_device import decode_row_group
    rng = np.random.default_rng(5)
    n = 4000
    raw_s = ["s" + str(rng.integers(0, 10**9)) * rng.integers(1, 4)
             for _ in range(n)]
    mask = rng.random(n) < 0.1
    t = pa.table({
        "s": pa.array(raw_s, type=pa.string(), mask=mask),
        "i": pa.array(rng.integers(-2**40, 2**40, n), type=pa.int64()),
        "f": pa.array(rng.normal(size=n)),
    })
    buf = _io.BytesIO()
    pq.write_table(t, buf, row_group_size=n, compression="snappy", **kw)
    raw = buf.getvalue()
    pf = pq.ParquetFile(_io.BytesIO(raw))
    dt_, ndev = decode_row_group(raw, pf.metadata, 0, pf.schema_arrow,
                                 ["s", "i", "f"], 64)
    assert ndev == 3, f"{label}: only {ndev}/3 columns decoded on device"
    got = dt_.to_host().to_arrow()
    host = pf.read_row_group(0)
    for c in ("s", "i", "f"):
        assert got.column(c).to_pylist() == host.column(c).to_pylist(), \
            f"{label}: column {c} diverged"


def test_tpch_lineitem_orders_full_device_decode(sess, tmp_path):
    """The round-2 'done' criterion: every column of TPC-H lineitem and
    orders (strings included) decodes on device, differential vs host."""
    import io as _io
    from spark_rapids_tpu.io.parquet_device import decode_row_group
    from spark_rapids_tpu.tools import tpch
    tables = tpch.gen_all(0.01)
    for tname in ("lineitem", "orders"):
        t = tables[tname]
        buf = _io.BytesIO()
        pq.write_table(t, buf, row_group_size=t.num_rows,
                       compression="snappy")
        raw = buf.getvalue()
        pf = pq.ParquetFile(_io.BytesIO(raw))
        names = list(t.column_names)
        dt_, ndev = decode_row_group(raw, pf.metadata, 0, pf.schema_arrow,
                                     names, 64)
        assert ndev == len(names), \
            f"{tname}: {ndev}/{len(names)} columns on device"
        got = dt_.to_host().to_arrow()
        host = pf.read_row_group(0)
        for c in names:
            assert got.column(c).to_pylist() == host.column(c).to_pylist(), \
                f"{tname}.{c} diverged"


def test_per_type_device_decode_gates(sess, tmp_path):
    """Per-type kill switches (reference: per-type read enables,
    RapidsConf.scala:877-917): strings/booleans can be forced back to the
    host column decode independently."""
    from spark_rapids_tpu.conf import RapidsConf
    import io as _io
    from spark_rapids_tpu.io.parquet_device import decode_row_group
    t = pa.table({"s": pa.array(["a", "bb", "ccc"] * 10),
                  "b": pa.array([True, False, True] * 10),
                  "i": pa.array(np.arange(30, dtype=np.int64))})
    buf = _io.BytesIO()
    pq.write_table(t, buf, compression="none")
    raw = buf.getvalue()
    pf = pq.ParquetFile(_io.BytesIO(raw))
    base = RapidsConf()
    dt_, nd = decode_row_group(raw, pf.metadata, 0, pf.schema_arrow,
                               ["s", "b", "i"], 8, conf=base)
    assert nd == 3
    off = RapidsConf({
        "spark.rapids.tpu.parquet.deviceDecode.strings.enabled": False,
        "spark.rapids.tpu.parquet.deviceDecode.booleans.enabled": False})
    dt2, nd2 = decode_row_group(raw, pf.metadata, 0, pf.schema_arrow,
                                ["s", "b", "i"], 8, conf=off)
    assert nd2 == 1  # only the int column stayed on device
    assert dt2.to_host().to_arrow().column("s").to_pylist() == \
        t.column("s").to_pylist()
