"""IO depth tests: scan predicate pushdown, ORC multithread+pushdown, CSV
per-type flags, debug dumps, compressed host cache (reference:
GpuParquetScanBase pushdown, OrcFilters, RapidsConf csv flags, DumpUtils,
ParquetCachedBatchSerializer)."""
import glob
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.orc as paorc
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.expr.functions import col

from harness import assert_tpu_cpu_equal


@pytest.fixture
def sess():
    return TpuSession({"spark.rapids.tpu.shuffle.mode": "host"})


def _write_parquet(tmp_path, n=2000, files=2):
    rng = np.random.default_rng(3)
    paths = []
    for i in range(files):
        t = pa.table({
            "a": pa.array(np.arange(i * n, (i + 1) * n, dtype=np.int64)),
            "b": pa.array(rng.normal(size=n)),
            "s": pa.array([f"x{j % 50}" for j in range(n)]),
        })
        p = str(tmp_path / f"part-{i}.parquet")
        pq.write_table(t, p, row_group_size=256)
        paths.append(p)
    return paths


def test_parquet_filter_pushdown_attaches_and_is_correct(sess, tmp_path):
    paths = _write_parquet(tmp_path)
    df = sess.read_parquet(paths)
    q = df.filter((col("a") >= 100) & (col("a") < 300)).select("a", "b")
    plan = sess._physical(q.logical, False)

    def find_scan(p):
        from spark_rapids_tpu.plan.physical import CpuScanExec
        if isinstance(p, CpuScanExec):
            return p
        for c in p.children:
            s = find_scan(c)
            if s is not None:
                return s
        return None

    scan = find_scan(plan)
    assert scan is not None and scan.source.filter_expr is not None
    out = q.collect(device=False)
    assert out.num_rows == 200
    assert sorted(out.column("a").to_pylist()) == list(range(100, 300))
    # the shared DataFrame source must NOT have accumulated the filter
    assert df.session is sess
    base_scan_count = df.count()
    assert base_scan_count == 4000
    assert_tpu_cpu_equal(q)


def test_pushdown_handles_or_in_isnull(sess, tmp_path):
    paths = _write_parquet(tmp_path, n=500, files=1)
    df = sess.read_parquet(paths)
    q = df.filter((col("a") < 10) | (col("a") > 490))
    assert q.collect(device=False).num_rows == 19
    q2 = df.filter(col("s").isin("x1", "x2") & (col("a") < 100))
    got = q2.collect(device=False)
    assert got.num_rows == 4
    assert_tpu_cpu_equal(q2)


def test_pushdown_never_strips_narrowing_casts(sess, tmp_path):
    """filter(col('v').cast(INT) == 3) keeps 3.7 (truncation); the pushed
    filter must NOT become v == 3 (exact row-level pyarrow filtering would
    drop 3.7)."""
    from spark_rapids_tpu.columnar import dtypes as dt
    p = str(tmp_path / "narrow.parquet")
    pq.write_table(pa.table({"v": [3.7, 3.0, 4.2]}), p)
    df = sess.read_parquet(p)
    q = df.filter(col("v").cast(dt.INT) == 3)
    got = sorted(q.collect(device=False).column("v").to_pylist())
    assert got == [3.0, 3.7], got
    assert_tpu_cpu_equal(q)


def test_pushdown_not_over_partial_and_is_not_pushed(sess, tmp_path):
    """~(A & B) with only A translatable must not push ~A (it would drop
    rows where A holds but B fails)."""
    p = str(tmp_path / "notand.parquet")
    pq.write_table(pa.table({"v": [1.0, 3.4, 10.0]}), p)
    df = sess.read_parquet(p)
    q = df.filter(~((col("v") > 3.0) & (col("v") * 2 > 7.0)))
    got = sorted(q.collect(device=False).column("v").to_pylist())
    assert got == [1.0, 3.4], got
    assert_tpu_cpu_equal(q)


def test_compressed_cache_falls_back_on_unserializable(monkeypatch):
    """Any serializer failure (exotic column repr) must degrade to live-
    table caching, never crash the query."""
    import spark_rapids_tpu.shuffle.serializer as ser
    s = TpuSession({
        "spark.rapids.tpu.shuffle.mode": "host",
        "spark.rapids.tpu.cache.compressionCodec": "zlib",
    })
    def boom(table, codec="none"):
        raise ValueError("cannot create an OBJECT array from memory buffer")
    monkeypatch.setattr(ser, "serialize_table", boom)
    df = s.create_dataframe(pd.DataFrame({"a": [1, 2, 3]})).cache()
    first = df.collect(device=False)
    second = df.collect(device=False)
    assert first.equals(second) and first.num_rows == 3
    storage = df.logical.storage
    assert storage.host and not storage.host_blobs  # live-table fallback


def test_orc_pushdown_and_multithread(sess, tmp_path):
    rng = np.random.default_rng(5)
    paths = []
    for i in range(3):
        t = pa.table({
            "k": pa.array(np.arange(i * 100, (i + 1) * 100, dtype=np.int64)),
            "v": pa.array(rng.normal(size=100)),
        })
        p = str(tmp_path / f"f{i}.orc")
        paorc.write_table(t, p)
        paths.append(p)
    df = sess.read_orc(paths)
    q = df.filter(col("k") >= 250)
    out = q.collect(device=False)
    assert sorted(out.column("k").to_pylist()) == list(range(250, 300))
    plan = sess._physical(q.logical, False)
    text = plan.tree_string()
    assert "ORC" in text
    assert_tpu_cpu_equal(q)


def test_csv_type_flag_demotes_to_string(tmp_path):
    p = str(tmp_path / "t.csv")
    pd.DataFrame({"f": [1.5, 2.5, 3.5], "i": [1, 2, 3]}).to_csv(
        p, index=False)
    on = TpuSession({"spark.rapids.tpu.shuffle.mode": "host"})
    t1 = on.read_csv(p).collect()
    assert pa.types.is_float64(t1.schema.field("f").type)
    off = TpuSession({
        "spark.rapids.tpu.shuffle.mode": "host",
        "spark.rapids.sql.csv.read.double.enabled": False,
    })
    t2 = off.read_csv(p).collect()
    assert pa.types.is_string(t2.schema.field("f").type)
    assert t2.column("f").to_pylist() == ["1.5", "2.5", "3.5"]
    assert pa.types.is_int64(t2.schema.field("i").type)  # ints still parsed


def test_debug_dump_scan_batches(tmp_path):
    dump_dir = str(tmp_path / "dumps")
    sess = TpuSession({
        "spark.rapids.tpu.shuffle.mode": "host",
        "spark.rapids.tpu.debug.dumpPath": dump_dir,
    })
    src = str(tmp_path / "in.parquet")
    t = pa.table({"a": list(range(50))})
    pq.write_table(t, src)
    out = sess.read_parquet(src).filter(col("a") < 10).collect()
    assert out.num_rows == 10
    dumps = glob.glob(os.path.join(dump_dir, "scan-*.parquet"))
    assert dumps, "no dump files written"
    dumped = pq.read_table(dumps[0])
    assert dumped.num_rows > 0
    assert "a" in dumped.column_names


def test_compressed_host_cache(sess):
    sess2 = TpuSession({
        "spark.rapids.tpu.shuffle.mode": "host",
        "spark.rapids.tpu.cache.compressionCodec": "zlib",
    })
    rng = np.random.default_rng(9)
    df = sess2.create_dataframe(pd.DataFrame({
        "a": rng.integers(0, 100, 1000).astype(np.int64),
        "s": [f"str{i % 17}" for i in range(1000)],
    }), num_partitions=2).cache()
    first = df.collect(device=False)
    second = df.collect(device=False)
    assert first.equals(second)
    storage = df.logical.storage
    assert storage.host_blobs and not storage.host
    blob_bytes = sum(len(b) for blobs in storage.host_blobs.values()
                     for b in blobs)
    assert blob_bytes > 0
