"""Example-UDF tests (reference: udf-examples/ URLDecode/URLEncode Scala
UDFs + StringWordCount/CosineSimilarity native kernels)."""
import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.udf.examples import (cosine_similarity, pallas_axpy,
                                           url_decode, url_encode, word_count)
from spark_rapids_tpu.expr.functions import col

from harness import assert_tpu_cpu_equal


@pytest.fixture
def session():
    return TpuSession({"spark.rapids.tpu.shuffle.mode": "host"})


def test_url_decode_encode(session):
    strs = ["hello%20world", "a%2Bb%3Dc", "plain", "sp+ace", ""]
    df = session.create_dataframe(pa.table({"s": strs}))
    out = assert_tpu_cpu_equal(
        df.select(url_decode(col("s")).alias("dec")), ignore_order=False)
    from urllib.parse import unquote_plus
    assert out.column("dec").to_pylist() == [unquote_plus(s) for s in strs]

    rt = df.select(url_encode(url_decode(col("s"))).alias("rt"))
    got = rt.collect().column("rt").to_pylist()
    # round trip normalizes %20 vs + but preserves the decoded value
    assert [unquote_plus(g) for g in got] == [unquote_plus(s) for s in strs]


def test_word_count_device_kernel(session):
    strs = ["one", "two words", "a b c d", "", "trailing space "]
    df = session.create_dataframe(pa.table({"s": strs}), num_partitions=2)
    q = df.select(word_count(col("s")).alias("wc"))
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    assert out.column("wc").to_pylist() == [1, 2, 4, 0, 3]
    # the device rule accepts it (jax byte-matrix kernel, not a fallback)
    plan = session._physical(q.logical, True)
    assert "Tpu" in plan.tree_string() or "Fused" in plan.tree_string()


def test_cosine_similarity(session):
    a = [[1.0, 0.0], [1.0, 1.0], [0.0, 0.0]]
    b = [[1.0, 0.0], [1.0, 0.0], [1.0, 0.0]]
    df = session.create_dataframe(pa.table({
        "a": pa.array(a, type=pa.list_(pa.float64())),
        "b": pa.array(b, type=pa.list_(pa.float64())),
    }))
    out = df.select(cosine_similarity(col("a"), col("b")).alias("cs")) \
        .collect()
    got = out.column("cs").to_pylist()
    assert got[0] == pytest.approx(1.0)
    assert got[1] == pytest.approx(1.0 / np.sqrt(2))
    assert np.isnan(got[2])


def test_pallas_axpy(session):
    rng = np.random.default_rng(2)
    df = session.create_dataframe(pd.DataFrame({
        "a": rng.normal(size=64).astype(np.float32),
        "x": rng.normal(size=64).astype(np.float32),
        "y": rng.normal(size=64).astype(np.float32),
    }), num_partitions=2)
    q = df.select(pallas_axpy(col("a"), col("x"), col("y")).alias("r"))
    out = assert_tpu_cpu_equal(q, rel_tol=1e-5)
    pdf = df.collect().to_pandas()
    expect = pdf.a * pdf.x + pdf.y
    got = np.sort(np.asarray(out.column("r").to_pylist(), dtype=np.float32))
    assert np.allclose(got, np.sort(expect.to_numpy()), rtol=1e-5)
