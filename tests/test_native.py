"""Native C++ runtime library tests: LZ4 round trips, hash kernels vs the
device-path implementations (host and device murmur3 must agree bit-for-bit
— they feed the same shuffle partitioning), priority queue, arena allocator.
"""
import numpy as np
import pytest

from spark_rapids_tpu import native


requires_native = pytest.mark.skipif(not native.available(),
                                     reason="g++ unavailable")


@requires_native
def test_lz4_roundtrip_compressible():
    data = (b"the quick brown fox jumps over the lazy dog; " * 4096)
    comp = native.lz4_compress(data)
    assert len(comp) < len(data) // 10
    assert native.lz4_decompress(comp, len(data)) == data


@requires_native
def test_lz4_roundtrip_random():
    rng = np.random.default_rng(1)
    for n in (0, 1, 5, 12, 13, 64, 1000, 65_536, 1 << 20):
        data = rng.bytes(n)
        comp = native.lz4_compress(data)
        assert native.lz4_decompress(comp, n) == data


@requires_native
def test_lz4_roundtrip_patterns():
    for data in (b"", b"a", b"ab" * 10_000, b"abcabcabcabc" * 1000,
                 bytes(range(256)) * 256,
                 b"x" * 70_000):  # long literal/match extension paths
        comp = native.lz4_compress(data)
        assert native.lz4_decompress(comp, len(data)) == data


@requires_native
def test_xxhash64_known_vectors():
    # Public xxh64 test vectors (seed 0 / prime seed)
    assert native.xxhash64(b"") == 0xEF46DB3751D8E999
    assert native.xxhash64(b"a") == 0xD24EC4F1A98C6E5B
    assert native.xxhash64(b"abc") == 0x44BC2CF5AD770999
    assert native.xxhash64(b"Hello, world!", seed=0) \
        == native.xxhash64(b"Hello, world!", seed=0)
    assert native.xxhash64(b"abc", 1) != native.xxhash64(b"abc", 2)


@requires_native
def test_murmur3_matches_device_path():
    """Native murmur3 must agree with the JAX/host implementation used by the
    device engine (they feed the same shuffle bucket choice)."""
    from spark_rapids_tpu.expr.base import AttributeReference, EvalContext
    from spark_rapids_tpu.expr.hashing import Murmur3Hash
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.host import HostColumn, HostTable

    rng = np.random.default_rng(2)
    n = 500
    longs = rng.integers(-1 << 40, 1 << 40, n)
    ints = rng.integers(-1 << 30, 1 << 30, n).astype(np.int32)
    dbls = rng.normal(size=n)
    dbls[::17] = 0.0
    dbls[::23] = -0.0
    flts = rng.normal(size=n).astype(np.float32)
    flts[::13] = np.float32(0.0)
    flts[::19] = np.float32(-0.0)
    flts[::29] = np.float32("nan")
    strs = np.array([f"row-{i}-{'x' * (i % 9)}" for i in range(n)],
                    dtype=object)

    table = HostTable(
        ["l", "i", "d", "f", "s"],
        [HostColumn(dt.LONG, longs), HostColumn(dt.INT, ints),
         HostColumn(dt.DOUBLE, dbls), HostColumn(dt.FLOAT, flts),
         HostColumn(dt.STRING, strs)])
    expr = Murmur3Hash(AttributeReference("l", dt.LONG),
                       AttributeReference("i", dt.INT),
                       AttributeReference("d", dt.DOUBLE),
                       AttributeReference("f", dt.FLOAT),
                       AttributeReference("s", dt.STRING))
    host = expr.eval(EvalContext.for_host(table)).values.astype(np.uint32)

    nat = native.murmur3_columns(
        [(longs, None), (ints, None), (dbls, None), (flts, None),
         (strs, None)], seed=42)
    np.testing.assert_array_equal(nat, host)


@requires_native
def test_murmur3_null_chaining():
    longs = np.array([1, 2, 3, 4], dtype=np.int64)
    validity = np.array([True, False, True, False])
    ints = np.array([9, 9, 9, 9], dtype=np.int32)
    h = native.murmur3_columns([(longs, validity), (ints, None)])
    # null rows skip the first column: row1 == hash(seed->9), row3 same
    h_ref = native.murmur3_columns([(ints[:1], None)])
    assert h[1] == h[3] == h_ref[0]
    assert h[0] != h[1]


def test_hash_partition_stable_grouping():
    rng = np.random.default_rng(3)
    hashes = rng.integers(0, 1 << 32, 10_000, dtype=np.uint64).astype(np.uint32)
    pids, counts, order = native.hash_partition(hashes, 7)
    assert counts.sum() == len(hashes)
    # signed mod matches Spark's Pmod(hash, p) on int32
    expected_pids = (hashes.view(np.int32).astype(np.int64) % 7 + 7) % 7
    np.testing.assert_array_equal(pids, expected_pids.astype(np.int32))
    # order is stable within partitions and contiguous by partition
    sorted_pids = pids[order]
    assert (np.diff(sorted_pids) >= 0).all()
    for p in range(7):
        rows = order[sorted_pids == p]
        assert (np.diff(rows) > 0).all()  # stability


def test_priority_queue():
    q = native.HashedPriorityQueue()
    h1 = q.push(50, 100)
    h2 = q.push(10, 200)
    h3 = q.push(30, 300)
    assert len(q) == 3
    assert q.pop() == (10, 200)
    assert q.update(h1, 5)
    assert q.pop() == (5, 100)
    assert not q.update(h2, 1)  # already popped
    assert q.remove(h3)
    assert q.pop() is None
    assert len(q) == 0


def test_priority_queue_tie_order():
    q = native.HashedPriorityQueue()
    q.push(7, 1)
    q.push(7, 2)
    q.push(7, 3)
    assert [q.pop()[1] for _ in range(3)] == [1, 2, 3]


def test_arena_alloc_free_coalesce():
    a = native.HostArena(1 << 16)
    offs = [a.alloc(1000) for _ in range(30)]
    assert all(o is not None for o in offs)
    used_before = a.used
    assert used_before >= 30 * 1000
    for o in offs[::2]:
        assert a.free(o)
    # freed alternating blocks can't satisfy a large alloc (fragmented)...
    big = a.alloc(30_000)
    # ...but freeing the rest coalesces everything
    for o in offs[1::2]:
        assert a.free(o)
    if big is not None:
        a.free(big)
    assert a.used == 0
    assert a.alloc(60_000) is not None


def test_arena_oom_returns_none():
    a = native.HostArena(4096)
    assert a.alloc(100_000) is None  # caller runs spill path
    o = a.alloc(1024)
    assert o is not None


def test_arena_read_write():
    a = native.HostArena(1 << 12)
    o = a.alloc(256)
    a.write(o, b"hello spill world")
    assert a.read(o, 17) == b"hello spill world"


@requires_native
def test_serializer_lz4_codec():
    import pyarrow as pa
    from spark_rapids_tpu.columnar.host import HostTable
    from spark_rapids_tpu.shuffle.serializer import (deserialize_table,
                                                     serialize_table)
    t = pa.table({"a": list(range(1000)),
                  "b": [f"s{i % 17}" for i in range(1000)],
                  "c": [float(i) * 0.5 if i % 7 else None for i in range(1000)]})
    ht = HostTable.from_arrow(t)
    blob = serialize_table(ht, codec="lz4")
    rt = deserialize_table(blob)
    assert rt.to_arrow().equals(t)
    raw = serialize_table(ht, codec="none")
    assert len(blob) < len(raw)


def test_pq_fallback_python(monkeypatch):
    monkeypatch.setattr(native, "get_lib", lambda: None)
    q = native.HashedPriorityQueue()
    h1 = q.push(5, 10)
    q.push(1, 20)
    q.update(h1, 0)
    assert q.pop() == (0, 10)
    assert q.pop() == (1, 20)
    assert q.pop() is None


def test_arena_fallback_python(monkeypatch):
    monkeypatch.setattr(native, "get_lib", lambda: None)
    a = native.HostArena(1 << 12)
    o1 = a.alloc(100)
    o2 = a.alloc(100)
    a.write(o2, b"abc")
    assert a.read(o2, 3) == b"abc"
    assert a.free(o1) and a.free(o2)
    assert a.used == 0
