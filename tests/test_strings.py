"""String expression tests: device kernels vs host engine vs plain Python
(reference analogue: StringOperatorsSuite / string tests in integration_tests)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr.functions import (
    col, lit, upper, lower, initcap, length, octet_length, substring,
    substring_index, concat, concat_ws, trim, ltrim, rtrim, lpad, rpad,
    repeat, reverse, replace, locate, instr, ascii, regexp_extract,
    regexp_replace)
from harness import assert_tpu_cpu_equal


ASCII_WORDS = ["", "a", "AB", "abc", "tpu", "Spark", "RAPIDS", "xyzzy",
               "  padded  ", "MixedCase", "longer string value", "a b c",
               "%special_", "trailing  ", "  leading"]
UNICODE_WORDS = ["", "é", "héllo", "日本語", "mix日ed", "ünïcode", "a日b"]


@pytest.fixture
def sdf(session, rng):
    n = 120
    words = [ASCII_WORDS[i] for i in rng.integers(0, len(ASCII_WORDS), n)]
    mask = rng.random(n) < 0.1
    arr = pa.array(words, mask=mask)
    other = pa.array([ASCII_WORDS[i] for i in rng.integers(0, len(ASCII_WORDS), n)])
    return session.create_dataframe(pa.table({"s": arr, "t": other}))


@pytest.fixture
def udf_(session, rng):
    n = 60
    words = [UNICODE_WORDS[i] for i in rng.integers(0, len(UNICODE_WORDS), n)]
    return session.create_dataframe(pa.table({"s": pa.array(words)}))


def test_case_mapping(sdf):
    assert_tpu_cpu_equal(sdf.select(
        upper(col("s")).alias("u"),
        lower(col("s")).alias("l"),
        initcap(col("s")).alias("ic"),
    ))


def test_length_family_unicode(udf_):
    # length is characters, octet_length is bytes — exact for UTF-8 on device
    out = assert_tpu_cpu_equal(udf_.select(
        col("s").alias("s"),
        length(col("s")).alias("chars"),
        octet_length(col("s")).alias("bytes"),
    ))
    for s, c, b in zip(out.column("s").to_pylist(),
                       out.column("chars").to_pylist(),
                       out.column("bytes").to_pylist()):
        assert c == len(s)
        assert b == len(s.encode())


def test_substring_ascii(sdf):
    assert_tpu_cpu_equal(sdf.select(
        substring(col("s"), 1, 3).alias("pre"),
        substring(col("s"), 3, 2).alias("mid"),
        substring(col("s"), -3, 2).alias("neg"),
        substring(col("s"), 0, 4).alias("zero"),
        col("s").substr(2, 100).alias("tail"),
    ))


def test_substring_unicode_charwise(udf_):
    out = assert_tpu_cpu_equal(udf_.select(
        col("s").alias("s"),
        substring(col("s"), 2, 2).alias("sub"),
    ))
    for s, sub in zip(out.column("s").to_pylist(),
                      out.column("sub").to_pylist()):
        assert sub == s[1:3]


def test_reverse_unicode(udf_):
    out = assert_tpu_cpu_equal(udf_.select(
        col("s").alias("s"), reverse(col("s")).alias("r")))
    for s, r in zip(out.column("s").to_pylist(), out.column("r").to_pylist()):
        assert r == s[::-1]


def test_predicates(sdf):
    assert_tpu_cpu_equal(sdf.select(
        col("s").startswith(lit("a")).alias("sw"),
        col("s").endswith(lit("g")).alias("ew"),
        col("s").contains(lit("ar")).alias("ct"),
        col("s").startswith(col("t")).alias("sw_col"),
        col("s").endswith(col("t")).alias("ew_col"),
    ))


def test_like(sdf):
    assert_tpu_cpu_equal(sdf.select(
        col("s").like("a%").alias("pre"),
        col("s").like("%g").alias("suf"),
        col("s").like("%ar%").alias("ct"),
        col("s").like("abc").alias("eq"),
        col("s").like("a_c").alias("underscore"),
        col("s").like("%a_c%").alias("general"),
    ))


def test_concat_trim_pad(sdf):
    assert_tpu_cpu_equal(sdf.select(
        concat(col("s"), lit("-"), col("t")).alias("cc"),
        trim(col("s")).alias("tr"),
        ltrim(col("s")).alias("ltr"),
        rtrim(col("s")).alias("rtr"),
        lpad(col("s"), 8, "*").alias("lp"),
        rpad(col("s"), 8, "xy").alias("rp"),
        repeat(col("s"), 2).alias("rep"),
    ))


def test_concat_ws_and_replace(sdf):
    assert_tpu_cpu_equal(sdf.select(
        concat_ws(",", col("s"), col("t")).alias("cw"),
        replace(col("s"), "a", "_").alias("rep"),
        substring_index(col("s"), " ", 1).alias("si"),
    ))


def test_locate_instr_ascii_fn(sdf):
    assert_tpu_cpu_equal(sdf.select(
        locate("a", col("s")).alias("loc"),
        locate("a", col("s"), 2).alias("loc2"),
        instr(col("s"), "ar").alias("ins"),
        ascii(col("s")).alias("asc"),
    ))


def test_rlike_device_nfa(sdf):
    assert_tpu_cpu_equal(sdf.select(
        col("s").rlike("^[A-Z]").alias("anch"),
        col("s").rlike("a.c").alias("dot"),
        col("s").rlike("ing$").alias("end"),
        col("s").rlike("[0-9]+|[a-z]{3}").alias("alt"),
        col("s").rlike("Spa?rk").alias("opt"),
    ))


def test_regexp_extract_replace(sdf):
    assert_tpu_cpu_equal(sdf.select(
        regexp_extract(col("s"), "([a-z]+)", 1).alias("ex"),
        regexp_replace(col("s"), "[aeiou]", "#").alias("rr"),
    ))


def test_string_fallback_reasons(session):
    """Host-only exprs must tag not-device with a recorded reason."""
    df = session.create_dataframe(pa.table({"s": ["a-b", "c-d"]}))
    q = df.select(regexp_replace(col("s"), "-", "+").alias("r"))
    txt = q.explain("tpu")
    assert "cannot run" in txt


def test_device_regex_subset_detection():
    from spark_rapids_tpu.expr.regex import compile_device_nfa, transpile, \
        RegexUnsupported
    assert compile_device_nfa("abc") is not None
    assert compile_device_nfa("^a[bc]+d?$") is not None
    assert compile_device_nfa("(ab|cd)*x") is not None
    assert compile_device_nfa(r"\d{2,4}") is not None
    # rejected: backreference, lookahead, \p class, word boundary
    assert compile_device_nfa(r"(a)\1") is None
    assert compile_device_nfa(r"a(?=b)") is None
    assert compile_device_nfa(r"\p{Alpha}") is None
    assert compile_device_nfa(r"a\b") is None
    with pytest.raises(RegexUnsupported):
        transpile(r"(a)\1")


def test_rlike_unicode_char_exact(session):
    """Device NFA steps per character: '.', negated classes, and $ anchors
    must agree with the host engine on multi-byte UTF-8 input."""
    df = session.create_dataframe(pa.table({
        "s": ["xé", "é", "ab", "日本語", "aé日", ""]}))
    assert_tpu_cpu_equal(df.select(
        col("s").alias("s"),
        col("s").rlike("x.").alias("dot"),
        col("s").rlike("^.$").alias("one"),
        col("s").rlike("^[^a]+$").alias("neg"),
        col("s").rlike("a.$").alias("end"),
    ), ignore_order=False)


def test_rand_statistics(session):
    from spark_rapids_tpu.expr.functions import rand
    df = session.create_dataframe(
        pa.table({"x": np.arange(2000, dtype=np.int64)}))
    out = df.select(rand().alias("a"), rand().alias("b")).collect(device=True)
    a = np.asarray(out.column("a").to_pylist())
    b = np.asarray(out.column("b").to_pylist())
    assert 0.0 <= a.min() and a.max() < 1.0
    assert abs(a.mean() - 0.5) < 0.05
    assert not np.array_equal(a, b)     # independent streams per rand() call


def test_malformed_regex_falls_back(session):
    """Malformed {m,n} must reject from the device subset, not crash planning."""
    from spark_rapids_tpu.expr.regex import compile_device_nfa
    assert compile_device_nfa("a{2") is None
    assert compile_device_nfa("a{b}") is None


def test_pad_edge_cases(session):
    df = session.create_dataframe(pa.table({"s": ["abc", "x", ""]}))
    out = assert_tpu_cpu_equal(df.select(
        rpad(col("s"), 0, "*").alias("z"),
        lpad(col("s"), 2, "*").alias("trunc_l"),
    ), ignore_order=False)
    assert out.column("z").to_pylist() == ["", "", ""]
    assert out.column("trunc_l").to_pylist() == ["ab", "*x", "**"]


def test_device_replace_and_regex_spans(session):
    """StringReplace / RegExpReplace / RegExpExtract(0) lower to the device
    span kernels (regex.py match_ends + replace_by_spans) for literal and
    NFA-subset patterns; UTF-8 subjects stay byte-aligned."""
    import pyarrow as pa
    t = pa.table({"s": ["hello world", "aaa", "", "ab-12-xy", None,
                        "nums 123 456", "héllo wörld", "aa11bb22"]})
    df = session.create_dataframe(t)
    q = df.select(
        replace(col("s"), "l", "LL").alias("lit_grow"),
        replace(col("s"), "aa", "").alias("lit_shrink"),
        regexp_replace(col("s"), "[0-9]+", "#").alias("re_num"),
        regexp_replace(col("s"), "l+o?", "L").alias("re_greedy"),
        regexp_extract(col("s"), "[0-9]+", 0).alias("ex0"),
    )
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    assert out.column("re_num").to_pylist()[5] == "nums # #"
    assert out.column("ex0").to_pylist()[7] == "11"
    # explain: these expressions must NOT fall back
    bad = [l for l in q.explain("tpu").splitlines()
           if "!" in l and ("replace" in l.lower() or "regexp" in l.lower())]
    assert not bad, bad


def test_regex_span_fallbacks_gate(session):
    """Alternation / lazy / group-reference patterns stay on host with a
    recorded reason (reference: CudfRegexTranspiler reject-and-fallback)."""
    import pyarrow as pa
    df = session.create_dataframe(pa.table({"s": ["ab 12", "zz"]}))
    for q in [
        df.select(regexp_replace(col("s"), "a|b", "#").alias("r")),
        df.select(regexp_replace(col("s"), "[0-9]+?", "#").alias("r")),
        df.select(regexp_replace(col("s"), "([0-9])", "$1!").alias("r")),
        df.select(regexp_extract(col("s"), "([a-z]+)", 1).alias("r")),
    ]:
        text = q.explain("tpu")
        assert "cannot run on TPU" in text, text
        assert_tpu_cpu_equal(q, ignore_order=False)  # falls back correctly


def test_concat_ws_substring_index_chr_on_device(session):
    """Round-2 gap: ConcatWs/SubstringIndex/Chr ran host-only; now their
    device kernels must be SELECTED (not just correct via fallback)."""
    from spark_rapids_tpu.expr.functions import char
    t = pa.table({"s": pa.array(["a,b,c", "", "x", "no-delim", None,
                                 "a,,b", ",lead", "trail,"] * 4),
                  "u": pa.array(["α,β", "日,本,語", "a日,b", "é"] * 8),
                  "n": pa.array([65, 0, 200, 255, -1, 128, 1000, 10] * 4,
                                type=pa.int64())})
    df = session.create_dataframe(t)
    q = df.select(
        concat_ws("|", col("s"), col("u")).alias("cw"),
        substring_index(col("s"), ",", 2).alias("si2"),
        substring_index(col("s"), ",", -1).alias("sim1"),
        char(col("n")).alias("ch"),
    )
    ex = df.select(concat_ws("|", col("s"), col("u")).alias("cw")) \
        .explain("tpu")
    assert "CpuProjectExec will run on TPU" in ex, ex
    assert "ConcatWs" not in ex, ex  # no fallback reason names it
    got = assert_tpu_cpu_equal(q)
    # independent python check
    pdf = t.to_pandas()
    for i, (s, u, n) in enumerate(zip(pdf.s, pdf.u, pdf.n)):
        parts = [p for p in (s, u) if isinstance(p, str)]
        assert got.column("cw")[i].as_py() == "|".join(parts)
        if isinstance(s, str):
            assert got.column("si2")[i].as_py() == \
                ",".join(s.split(",")[:2])
            assert got.column("sim1")[i].as_py() == s.split(",")[-1]
        assert got.column("ch")[i].as_py() == \
            (chr(int(n) & 0xFF) if n >= 0 else "")


def test_substring_index_multibyte_delim_overlap(session):
    """Multi-byte delimiters must match non-overlapping left-to-right
    (the lax.scan path): 'aaaa' split by 'aa' has exactly 2 occurrences."""
    t = pa.table({"s": pa.array(["aaaa", "aaa", "abababa", "xaax", "aa",
                                 "", "ab日ab日ab"] * 4)})
    df = session.create_dataframe(t)
    for cnt in (1, 2, -1, -2, 3, 0):
        q = df.select(substring_index(col("s"), "aa", cnt).alias("a"),
                      substring_index(col("s"), "ab", cnt).alias("b"),
                      substring_index(col("s"), "ab日", cnt).alias("c"))
        got = assert_tpu_cpu_equal(q)
        pdf = t.to_pandas()
        for i, s in enumerate(pdf.s):
            for cname, d in (("a", "aa"), ("b", "ab"), ("c", "ab日")):
                if cnt == 0:
                    exp = ""
                elif cnt > 0:
                    exp = d.join(s.split(d)[:cnt])
                else:
                    exp = d.join(s.split(d)[cnt:])
                assert got.column(cname)[i].as_py() == exp, \
                    (s, d, cnt, got.column(cname)[i].as_py(), exp)


def test_concat_ws_all_null_and_empty(session):
    t = pa.table({"a": pa.array([None, None, "x"], type=pa.string()),
                  "b": pa.array([None, "y", None], type=pa.string())})
    df = session.create_dataframe(t)
    got = assert_tpu_cpu_equal(
        df.select(concat_ws("-", col("a"), col("b")).alias("c")))
    assert got.column("c").to_pylist() == ["", "y", "x"]


def test_regexp_extract_capture_groups_on_device(session):
    """Round-2 gap #4: capture groups (idx>0) extract on device for the
    deterministic linearizable subset (reference: RegexParser.scala:414
    transpiles capture groups; cuDF extracts natively)."""
    import re as _re
    strs = ["ab 12-345 x", "7-8", "no match", "-", "99-", "1-2-3",
            "mail bob@site.com x", "v12.34 v999.1", "key:123", ""] * 3
    t = pa.table({"s": pa.array(strs)})
    df = session.create_dataframe(t)
    cases = [(r"(\d+)-(\d+)", 1), (r"(\d+)-(\d+)", 2),
             (r"([a-z]+)@([a-z]+)\.com", 2), (r"v(\d{1,3})\.(\d+)", 1)]
    for pat, gi in cases:
        q = df.select(regexp_extract(col("s"), pat, gi).alias("g"))
        ex = q.explain("tpu")
        assert "RegExpExtract" not in ex, (pat, gi, ex)  # no fallback
        got = assert_tpu_cpu_equal(q)
        for i, s in enumerate(strs):
            m = _re.search(pat, s)
            exp = m.group(gi) if m and m.group(gi) is not None else ""
            assert got.column("g")[i].as_py() == exp, (pat, gi, s)
    # outside the subset -> falls back (still correct)
    q = df.select(regexp_extract(col("s"), r"(\d+)(\d*)", 1).alias("g"))
    ex = q.explain("tpu")
    assert "RegExpExtract" in ex and "capture-group subset" in ex, ex
    assert_tpu_cpu_equal(q)
