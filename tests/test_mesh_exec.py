"""Mesh-parallel stage execution (exec/mesh.py): the post-exchange
operator chain runs as ONE shard_map program over the dp axis, consuming
the ICI exchange's output still sharded (reference analogue: partitioned
operators running on all executors at once, SURVEY §2.7).

Covers the planner rewrite, byte-parity against the per-partition path,
the keep-sharded exchange contract, the unshard-boundary/fault fallback
semantics, and the observatory's mesh_stage/compile phases."""
import glob
import os

import jax
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.utils import faults

from harness import assert_tables_equal


def _mesh_session(n=8, **extra):
    from spark_rapids_tpu.parallel.mesh import virtual_cpu_mesh
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} virtual devices")
    sess = TpuSession({
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.shuffle.partitions": 4,
        # pin the STATIC plan shape (mesh-stage nodes in the tree); AQE
        # replaces exchanges with materialized stages
        "spark.rapids.tpu.aqe.enabled": False,
        **extra,
    })
    sess.attach_mesh(virtual_cpu_mesh(n))
    return sess


def _frame(sess, rows=64, num_partitions=2, seed=0, prefix=""):
    rng = np.random.default_rng(seed)
    t = pa.table({
        prefix + "k": rng.integers(0, 9, rows),
        prefix + "v": rng.random(rows),
        prefix + "w": rng.integers(-50, 50, rows),
    })
    return sess.create_dataframe(t, num_partitions=num_partitions)


def _agg_query(df, prefix=""):
    from spark_rapids_tpu.expr.functions import col, count, sum as fsum
    return df.group_by(prefix + "k").agg(
        fsum(col(prefix + "v")).alias("s"),
        count(col(prefix + "w")).alias("c"))


def _find(plan, cls):
    if isinstance(plan, cls):
        return plan
    for c in plan.children:
        r = _find(c, cls)
        if r is not None:
            return r
    return None


@pytest.fixture(autouse=True)
def _pristine_state():
    """Fault injection and the degradation ledger are process-global by
    design; the fallback tests below bump both."""
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.exec.fallback import (configure_fallback,
                                                reset_fallback_state)
    faults.reset_faults()
    reset_fallback_state()
    yield
    faults.reset_faults()
    reset_fallback_state()
    configure_fallback(RapidsConf({}))


# ---------------------------------------------------------------------------
# planner rewrite
# ---------------------------------------------------------------------------
def test_planner_lifts_exchange_consumer_onto_the_mesh():
    """Exchange -> final-aggregate(+fused stage above) rewrites into one
    TpuMeshStageExec whose child is the keep-sharded exchange; the conf
    kill-switch restores the per-partition plan."""
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.exec.mesh import TpuMeshStageExec
    from spark_rapids_tpu.expr.functions import col

    sess = _mesh_session()
    q = _agg_query(_frame(sess)).select(
        (col("s") + col("c")).alias("t"), col("k"))
    plan = sess._physical(q.logical, device=True)
    node = _find(plan, TpuMeshStageExec)
    assert node is not None, plan.tree_string()
    assert isinstance(node.exchange, TpuShuffleExchangeExec)
    assert node.exchange._keep_sharded
    # the chain absorbed everything above the exchange: the final-mode
    # aggregate AND the projection stage over it
    assert len(node.chain) >= 2, node.node_name()
    assert node._has_final_agg()
    # fallback topology intact: chain links run exchange -> ... -> top
    assert node.chain[0].children == (node.exchange,)
    for below, above in zip(node.chain, node.chain[1:]):
        assert above.children == (below,)

    off = _mesh_session(
        **{"spark.rapids.tpu.mesh.stageExecution.enabled": False})
    plan_off = off._physical(_agg_query(_frame(off)).logical, device=True)
    assert _find(plan_off, TpuMeshStageExec) is None, plan_off.tree_string()


# ---------------------------------------------------------------------------
# parity with the per-partition path
# ---------------------------------------------------------------------------
def _parity(mk_query, n=8, seed=0, rows=64, **on_extra):
    sess_on = _mesh_session(n, **on_extra)
    got = mk_query(_frame(sess_on, rows=rows, seed=seed)).collect(device=True)
    sess_off = _mesh_session(
        n, **{"spark.rapids.tpu.mesh.stageExecution.enabled": False})
    exp = mk_query(_frame(sess_off, rows=rows, seed=seed)).collect(device=True)
    assert_tables_equal(got, exp)
    return got


def test_parity_final_aggregate():
    out = _parity(_agg_query)
    assert out.num_rows == 9  # every key present


def test_mesh_does_not_add_host_syncs():
    """Download-count parity: empty shards yield nothing (exactly like
    the split path's non-empty-only registration + the keyed aggregate's
    skip of input-less partitions), so the mesh path must not grow the
    deliberate-D2H funnel count the history sentinel gates on."""
    from spark_rapids_tpu.columnar.device import host_sync_stats

    def syncs(mesh_on):
        sess = _mesh_session(**{
            "spark.rapids.tpu.mesh.stageExecution.enabled": mesh_on})
        q = _agg_query(_frame(sess, rows=24))  # several empty shards
        before = host_sync_stats()["d2h_count"]
        q.collect(device=True)
        return host_sync_stats()["d2h_count"] - before

    assert syncs(True) <= syncs(False)


def test_parity_projection_and_filter_above_aggregate():
    from spark_rapids_tpu.expr.functions import col

    def q(df):
        return (_agg_query(df)
                .select(col("k"), (col("s") * 2.0).alias("s2"), col("c"))
                .filter(col("c") > 2))

    _parity(q, seed=3)


def test_parity_on_tiny_two_device_mesh():
    """The rewrite is extent-agnostic: same bytes on a 2-device mesh."""
    _parity(_agg_query, n=2, seed=5)


# ---------------------------------------------------------------------------
# keep-sharded exchange contract
# ---------------------------------------------------------------------------
def test_keep_sharded_exchange_skips_per_shard_registration():
    """In keep-sharded mode the exchange holds whole sharded chunks (no
    per-shard split/spill registration); a later per-partition consumer
    late-splits via _ensure_split and drains the identical rows."""
    from spark_rapids_tpu.columnar.host import HostTable
    from spark_rapids_tpu.exec.mesh import TpuMeshStageExec

    sess = _mesh_session()
    q = _agg_query(_frame(sess))
    plan = sess._physical(q.logical, device=True)
    node = _find(plan, TpuMeshStageExec)
    assert node is not None
    got = plan.collect().to_arrow()
    assert not node._fell_back
    ex = node.exchange
    assert ex._shards is None           # nothing was split/registered
    assert ex._sharded_chunks           # the kept whole-sharded chunks
    pairs = ex.sharded_chunks()         # still available to mesh consumers
    assert pairs
    # each chunk rides with its per-shard input row counts (host ints)
    for _chunk, shard_rows in pairs:
        assert len(shard_rows) == ex.num_partitions
        assert all(isinstance(r, int) for r in shard_rows)
    # late conversion for a per-partition consumer: splits once, then the
    # sharded view is gone and the partition drain serves the same rows
    rows = 0
    for p in range(ex.num_partitions):
        rows += sum(t.num_rows for t in ex.execute(p))
    assert ex._shards is not None
    assert ex.sharded_chunks() is None
    total_in = sum(int(c.num_rows) for c in
                   (HostTable.concat(list(ex.child.execute(p)))
                    for p in range(ex.child.num_partitions)))
    assert rows == total_in
    assert got.num_rows == 9


# ---------------------------------------------------------------------------
# fallback semantics
# ---------------------------------------------------------------------------
def test_injected_dispatch_failure_degrades_with_parity():
    """A classified (INTERNAL) failure in the mesh program quarantines the
    stage and falls back to the per-partition path — same bytes out."""
    from spark_rapids_tpu.exec.fallback import fallback_stats
    from spark_rapids_tpu.exec.mesh import TpuMeshStageExec

    sess = _mesh_session(**{
        "spark.rapids.tpu.faults.enabled": True,
        "spark.rapids.tpu.faults.seed": 7,
        "spark.rapids.tpu.faults.spec": "mesh.dispatch:action=raise",
    })
    q = _agg_query(_frame(sess))
    plan = sess._physical(q.logical, device=True)
    node = _find(plan, TpuMeshStageExec)
    assert node is not None
    got = plan.collect().to_arrow()
    assert node._fell_back
    assert fallback_stats()["quarantine_notes"] >= 1

    faults.reset_faults()
    sess_off = _mesh_session(
        **{"spark.rapids.tpu.mesh.stageExecution.enabled": False})
    exp = _agg_query(_frame(sess_off)).collect(device=True)
    assert_tables_equal(got, exp)


def test_unclassified_failure_propagates(monkeypatch):
    """An error with no XLA status marker -> classify_failure returns
    None -> the mesh stage must NOT mask it as a degrade (that would
    hide real bugs behind a silent per-partition re-run)."""
    from spark_rapids_tpu.exec.mesh import TpuMeshStageExec

    sess = _mesh_session()
    plan = sess._physical(_agg_query(_frame(sess)).logical, device=True)
    node = _find(plan, TpuMeshStageExec)
    assert node is not None

    def boom(chunk):
        raise ValueError("not an XLA status")

    monkeypatch.setattr(node, "_dispatch_chunk", boom)
    with pytest.raises(ValueError, match="not an XLA status"):
        plan.collect()
    assert not node._fell_back


def test_multi_chunk_exchange_hits_the_unshard_boundary():
    """With chunked exchange streaming (>1 chunk) a final-mode aggregate
    can't merge per-shard (each shard holds only a chunk's slice of its
    hash partition) — the stage falls back, with parity."""
    from spark_rapids_tpu.exec.mesh import TpuMeshStageExec

    chunked = {"spark.rapids.tpu.shuffle.exchangeChunkRows": 256}
    sess = _mesh_session(**chunked)
    q = _agg_query(_frame(sess, rows=2048))
    plan = sess._physical(q.logical, device=True)
    node = _find(plan, TpuMeshStageExec)
    assert node is not None
    got = plan.collect().to_arrow()
    assert node._fell_back

    sess_off = _mesh_session(**{
        "spark.rapids.tpu.mesh.stageExecution.enabled": False, **chunked})
    exp = _agg_query(_frame(sess_off, rows=2048)).collect(device=True)
    assert_tables_equal(got, exp)


# ---------------------------------------------------------------------------
# observatory phases
# ---------------------------------------------------------------------------
def test_mesh_stage_and_compile_phases_in_shuffle_summary(tmp_path):
    """The one SPMD dispatch notes a mesh_stage phase and the one-time XLA
    build a compile phase on the ici tier of the query's shuffle
    summary (distinct columns -> guaranteed program-cache miss)."""
    from spark_rapids_tpu.shuffle.telemetry import reset_shuffle_telemetry
    from spark_rapids_tpu.tools.eventlog import load_event_log

    logdir = str(tmp_path / "evl")
    sess = _mesh_session(**{
        "spark.rapids.tpu.eventLog.dir": logdir,
        "spark.rapids.tpu.shuffle.telemetry.enabled": True,
    })
    out = _agg_query(_frame(sess, prefix="ph_"), prefix="ph_") \
        .collect(device=True)
    assert out.num_rows == 9
    sess.close()
    reset_shuffle_telemetry()
    (path,) = glob.glob(os.path.join(logdir, "*.jsonl"))
    (q,) = load_event_log(path).queries.values()
    (ici,) = [t for t in q.shuffle_summary["tiers"] if t["tier"] == "ici"]
    for phase in ("dispatch", "compile", "mesh_stage"):
        assert phase in ici["phases"], ici["phases"]


# ---------------------------------------------------------------------------
# slow tier: real TPC-H shapes on the 8-device mesh
# ---------------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.parametrize("query", ["q3", "q5"])
def test_tpch_parity_on_mesh(query):
    from spark_rapids_tpu.tools import tpch

    def run(**extra):
        sess = _mesh_session(**{
            "spark.rapids.tpu.autoBroadcastJoinThreshold": -1, **extra})
        tables = tpch.gen_all(0, tiny=True)
        dfs = tpch.build_dataframes(sess, tables, num_partitions=2)
        out = getattr(tpch, query)(dfs).collect(device=True)
        sess.close()
        return out

    got = run()
    exp = run(**{"spark.rapids.tpu.mesh.stageExecution.enabled": False})
    assert got.num_rows > 0
    assert_tables_equal(got, exp)
