"""Compile-time amortization (ISSUE 7): canonical shape-bucket ladder,
persistent compile tier, and the warm-pool precompiler.

Covers the acceptance contract:
- bucket-ladder unit tests (monotonic, covering, bounded waste, conf
  round-trip through a session),
- persistent manifest + export save/load across a REAL subprocess
  boundary, pinning the zero-compiles-on-second-run criterion,
- corrupted-cache-dir tolerance (bad manifest, bad export file),
- warm pool precompiles-then-hits in-process,
- no-leaked-threads after session close.
"""
import json
import os
import pathlib
import subprocess
import sys
import threading

import jax.numpy as jnp
import pytest

from spark_rapids_tpu.columnar.device import (BucketPolicy, bucket_rows,
                                              configure_buckets,
                                              current_bucket_policy,
                                              resolve_min_bucket)
from spark_rapids_tpu.conf import RapidsConf

REPO = str(pathlib.Path(__file__).resolve().parent.parent)


# ---------------------------------------------------------------------------
# bucket ladder
# ---------------------------------------------------------------------------
def test_default_policy_is_power_of_two_ladder():
    """growth=2.0 / maxWasteFrac=0.5 must reproduce the original ladder
    bit-for-bit — existing deployments see identical shapes."""
    for base in (8, 256, 1024):
        for n in (1, base - 1, base, base + 1, 3 * base, 10_000):
            cap = base
            while cap < n:
                cap *= 2
            assert bucket_rows(n, base) == cap, (n, base)


def test_bucket_ladder_monotonic_and_covering():
    for pol in (BucketPolicy(1024, 2.0, 0.5), BucketPolicy(512, 2.0, 0.25),
                BucketPolicy(1024, 1.5, 0.5), BucketPolicy(64, 3.0, 0.2)):
        prev = 0
        for n in range(1, 50_000, 17):
            cap = pol.bucket(n)
            assert cap >= n, (pol, n, cap)
            assert cap >= prev, f"non-monotonic: {pol} {n}"
            prev = cap


def test_bucket_ladder_bounded_waste_and_shape_count():
    """Padding waste stays below growth*maxWasteFrac once past the floor,
    and the shape set stays logarithmic in the row range."""
    pol = BucketPolicy(min_rows=256, growth=2.0, max_waste_frac=0.25)
    caps = set()
    for n in range(257, 200_000, 13):
        cap = pol.bucket(n)
        caps.add(cap)
        waste = (cap - n) / cap
        assert waste < 2.0 * 0.25 + 1e-9, (n, cap, waste)
    # ~log2(200000/256) decades x at most 1/maxWasteFrac rungs each
    assert len(caps) <= 4 * 12, len(caps)


def test_bucket_conf_round_trip():
    """spark.rapids.tpu.shapeBuckets.* flows through configure_buckets
    into bucket_rows()/resolve_min_bucket(), and minRows=0 inherits
    batchRowsMinBucket."""
    try:
        configure_buckets(RapidsConf({
            "spark.rapids.tpu.shapeBuckets.minRows": 2048,
            "spark.rapids.tpu.shapeBuckets.growth": 1.5,
            "spark.rapids.tpu.shapeBuckets.maxWasteFrac": 0.25,
        }))
        pol = current_bucket_policy()
        assert (pol.min_rows, pol.growth, pol.max_waste_frac) \
            == (2048, 1.5, 0.25)
        assert resolve_min_bucket(None) == 2048
        assert bucket_rows(1) == 2048
        assert bucket_rows(1, 8) == 8          # explicit floor still wins
        # minRows=0 -> inherit the legacy batchRowsMinBucket key
        conf = RapidsConf({"spark.rapids.tpu.batchRowsMinBucket": 512})
        assert conf.min_bucket_rows == 512
        conf2 = RapidsConf({"spark.rapids.tpu.batchRowsMinBucket": 512,
                            "spark.rapids.tpu.shapeBuckets.minRows": 4096})
        assert conf2.min_bucket_rows == 4096
        with pytest.raises(ValueError):
            RapidsConf({"spark.rapids.tpu.shapeBuckets.growth": 1.0})
        with pytest.raises(ValueError):
            RapidsConf({"spark.rapids.tpu.shapeBuckets.maxWasteFrac": 0.0})
    finally:
        configure_buckets(RapidsConf())
    assert resolve_min_bucket(None) == 1024


# ---------------------------------------------------------------------------
# persistent tier helpers
# ---------------------------------------------------------------------------
def _reset_tier():
    from spark_rapids_tpu.utils.compile_cache import (clear_cache,
                                                      configure_compile_cache,
                                                      stop_warm_pool)
    stop_warm_pool()
    configure_compile_cache(RapidsConf())
    clear_cache()


@pytest.fixture
def tier_reset():
    _reset_tier()
    yield
    _reset_tier()


# one tiny jitted computation exercised through cached_jit, signature-stable
_SCRIPT = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, {repo!r})
cache_dir, phase = sys.argv[1], sys.argv[2]
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.tools import tpch
from spark_rapids_tpu.utils.compile_cache import cache_stats, warm_pool_wait

sess = TpuSession({{
    "spark.rapids.tpu.batchRowsMinBucket": 128,
    "spark.rapids.tpu.compile.cacheDir": cache_dir,
}})
if phase == "warm":
    assert warm_pool_wait(120), "warm pool did not settle"
lineitem = tpch.gen_lineitem(0.001, seed=0, rows=1500)
df = sess.create_dataframe(lineitem, num_partitions=1).cache()
q = tpch.q6({{"lineitem": df}})
res = q.collect(device=True)
out = {{"revenue": res.column("revenue")[0].as_py(), "stats": cache_stats()}}
sess.close()
print("RESULT " + json.dumps(out))
"""


def _run_subprocess(cache_dir: str, phase: str) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "-c", _SCRIPT.format(repo=REPO), cache_dir, phase],
        capture_output=True, text=True, timeout=480, env=env, cwd=REPO)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    line = next(ln for ln in r.stdout.splitlines()
                if ln.startswith("RESULT "))
    return json.loads(line[len("RESULT "):])


def test_persistent_tier_zero_compiles_across_processes(tmp_path):
    """THE acceptance pin: a TPC-H query in a fresh process after a prior
    warmed run executes with compiles == 0 in cache_stats()."""
    cache_dir = str(tmp_path / "tier")
    cold = _run_subprocess(cache_dir, "cold")
    assert cold["stats"]["compiles"] > 0
    # the tier persisted a manifest with this process's signatures
    import glob
    manifests = glob.glob(os.path.join(cache_dir, "*", "manifest.json"))
    assert len(manifests) == 1
    with open(manifests[0]) as f:
        manifest = json.load(f)
    assert manifest["entries"]
    assert any(e["exports"] for e in manifest["entries"].values())
    exports = glob.glob(os.path.join(cache_dir, "*", "exports", "*"))
    assert exports

    warm = _run_subprocess(cache_dir, "warm")
    assert warm["revenue"] == pytest.approx(cold["revenue"], rel=1e-9)
    assert warm["stats"]["compiles"] == 0, warm["stats"]
    assert warm["stats"]["persist_warmed_entries"] > 0
    assert warm["stats"]["persist_hits"] > 0
    # cumulative cross-process hit counts merged on close
    with open(manifests[0]) as f:
        merged = json.load(f)
    assert sum(e["hits"] for e in merged["entries"].values()) \
        > sum(e["hits"] for e in manifest["entries"].values())


def test_warm_pool_precompiles_then_hits(tmp_path, tier_reset):
    """In-process round trip: session 1 compiles + persists; after a full
    cache clear, session 2's warm pool replays the export and the same
    signature dispatches with zero compiles."""
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.utils.compile_cache import (cache_stats,
                                                      cached_jit,
                                                      clear_cache,
                                                      warm_pool_wait)

    def builder():
        def fn(x):
            return (x * 2.0 + 1.0).sum()
        return fn

    x = jnp.arange(64, dtype=jnp.float32)
    sess1 = TpuSession(
        {"spark.rapids.tpu.compile.cacheDir": str(tmp_path)})
    fn = cached_jit("test|warmpool|v1", builder)
    expect = float(fn(x))
    assert cache_stats()["compiles"] == 1
    sess1.close()           # exports + manifest land on disk
    clear_cache()           # forget everything in-process

    sess2 = TpuSession(
        {"spark.rapids.tpu.compile.cacheDir": str(tmp_path)})
    assert warm_pool_wait(60)
    stats = cache_stats()
    assert stats["persist_warmed_entries"] == 1, stats
    assert stats["persist_warm_compiles"] == 1
    fn2 = cached_jit("test|warmpool|v1", builder)
    assert float(fn2(x)) == expect
    stats = cache_stats()
    assert stats["compiles"] == 0, stats
    assert stats["hits"] == 1
    assert stats["persist_hits"] == 1
    # an UNSEEN shape falls back to a live compile (counted), still correct
    y = jnp.arange(128, dtype=jnp.float32)
    assert float(fn2(y)) == float((y * 2.0 + 1.0).sum())
    stats = cache_stats()
    assert stats["compiles"] == 1
    assert stats["persist_misses"] == 1
    sess2.close()


def test_persist_merges_deltas_not_raw_totals(tmp_path, tier_reset):
    """A process cycling sessions (or a double close) must not re-merge
    counts it already persisted into the cumulative manifest."""
    import glob
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.utils.compile_cache import (cached_jit,
                                                      persist_compile_cache,
                                                      warm_pool_wait)

    def builder():
        return lambda x: x * 3.0

    x = jnp.ones(16)
    sess = TpuSession({"spark.rapids.tpu.compile.cacheDir": str(tmp_path)})
    cached_jit("test|delta|v1", builder)(x)
    sess.close()

    def entry():
        (m,) = glob.glob(os.path.join(str(tmp_path), "*", "manifest.json"))
        with open(m) as f:
            return json.load(f)["entries"]["test|delta|v1"]

    assert (entry()["compiles"], entry()["hits"]) == (1, 0)
    persist_compile_cache()                   # double close: no growth
    assert (entry()["compiles"], entry()["hits"]) == (1, 0)
    # a second session in the SAME process adds only its own delta
    sess2 = TpuSession({"spark.rapids.tpu.compile.cacheDir": str(tmp_path)})
    warm_pool_wait(60)
    cached_jit("test|delta|v1", builder)(x)   # in-process hit
    sess2.close()
    assert (entry()["compiles"], entry()["hits"]) == (1, 1)


def test_corrupted_manifest_is_dropped_not_fatal(tmp_path, tier_reset):
    from spark_rapids_tpu.utils.compile_cache import (cache_stats,
                                                      configure_compile_cache,
                                                      machine_fingerprint,
                                                      persistent_cache_dir)
    import jax as _jax
    tier = os.path.join(
        str(tmp_path), f"{machine_fingerprint()}-jax{_jax.__version__}")
    os.makedirs(tier, exist_ok=True)
    with open(os.path.join(tier, "manifest.json"), "w") as f:
        f.write("{ this is not json")
    conf = RapidsConf({"spark.rapids.tpu.compile.cacheDir": str(tmp_path)})
    assert configure_compile_cache(conf) == tier   # no raise
    assert persistent_cache_dir() == tier
    stats = cache_stats()
    assert stats["persist_dropped_entries"] == 1
    assert stats["persist_manifest_entries"] == 0


def test_corrupted_entries_and_exports_are_skipped(tmp_path, tier_reset):
    """A bad manifest entry is dropped entry-wise; a manifest pointing at
    a garbage export file makes the warm pool skip (warm_errors), never
    raise."""
    from spark_rapids_tpu.utils.compile_cache import (cache_stats,
                                                      configure_compile_cache,
                                                      machine_fingerprint,
                                                      warm_pool_wait)
    import jax as _jax
    tier = os.path.join(
        str(tmp_path), f"{machine_fingerprint()}-jax{_jax.__version__}")
    os.makedirs(os.path.join(tier, "exports"), exist_ok=True)
    with open(os.path.join(tier, "exports", "bad.jaxexport"), "wb") as f:
        f.write(b"definitely not a serialized export")
    manifest = {"version": 1, "entries": {
        "good|sig": {"hits": 5, "compiles": 1, "compile_s": 0.1,
                     "exports": [{"file": "bad.jaxexport",
                                  "aval_sig": "abc"}]},
        "bad-entry": {"hits": "NaN-ish"},
        "also-bad": ["not", "a", "dict"],
    }}
    with open(os.path.join(tier, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    conf = RapidsConf({"spark.rapids.tpu.compile.cacheDir": str(tmp_path)})
    configure_compile_cache(conf)
    assert warm_pool_wait(60)
    stats = cache_stats()
    assert stats["persist_manifest_entries"] == 1   # only the good entry
    assert stats["persist_dropped_entries"] == 2
    assert stats["persist_warm_errors"] == 1        # bad export skipped
    assert stats["persist_warmed_entries"] == 0


def test_no_leaked_warm_pool_threads(tmp_path, tier_reset):
    """Session close reaps the warm pool: no tpu-warm-pool* /
    warm-pool worker threads survive (no-leaked-threads contract)."""
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.utils.compile_cache import cached_jit, clear_cache

    def builder():
        return lambda x: x + 1.0

    sess = TpuSession({"spark.rapids.tpu.compile.cacheDir": str(tmp_path)})
    cached_jit("test|leak|v1", builder)(jnp.ones(8))
    sess.close()
    clear_cache()
    sess2 = TpuSession({"spark.rapids.tpu.compile.cacheDir": str(tmp_path)})
    sess2.close()
    leaked = [t.name for t in threading.enumerate()
              if "warm-pool" in t.name and t.is_alive()]
    assert not leaked, leaked
