"""Shuffle subsystem tests (reference analogues: RapidsShuffleClientSuite /
ServerSuite driving protocol state machines with mock transports,
RapidsShuffleTestHelper — SURVEY §4.2)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import DeviceTable, HostTable
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.shuffle.manager import (HeartbeatManager, ShuffleManager,
                                              device_partition_ids)
from spark_rapids_tpu.shuffle.serializer import (deserialize_table,
                                                 serialize_table)
from spark_rapids_tpu.shuffle.transport import (BlockId, LocalShuffleTransport,
                                                ShuffleTransport,
                                                load_transport)


def _host_table(n=100, seed=0):
    rng = np.random.default_rng(seed)
    return HostTable.from_arrow(pa.table({
        "k": pa.array(rng.integers(0, 10, n)),
        "v": pa.array(rng.uniform(0, 1, n)),
        "s": pa.array([f"s{i % 7}" if i % 11 else None for i in range(n)]),
    }))


def test_serializer_roundtrip():
    t = _host_table()
    for codec in ("none", "zlib"):
        data = serialize_table(t, codec)
        back = deserialize_table(data)
        assert back.to_arrow().equals(t.to_arrow())


def test_serializer_empty_and_nulls():
    t = HostTable.from_arrow(pa.table({
        "a": pa.array([], type=pa.int64()),
        "s": pa.array([], type=pa.string())}))
    assert deserialize_table(serialize_table(t)).to_arrow().equals(t.to_arrow())
    t2 = HostTable.from_arrow(pa.table({
        "a": pa.array([None, None], type=pa.int64())}))
    assert deserialize_table(serialize_table(t2)).to_arrow().equals(t2.to_arrow())


def test_serializer_nested_types_roundtrip():
    """Nested columns ship as embedded Arrow IPC (offsets + child buffers —
    JCudfSerialization nested layout analogue), so collect_list/set partial
    states survive a real cross-process shuffle."""
    t = HostTable.from_arrow(pa.table({
        "k": pa.array([1, 2, 3, 4], type=pa.int64()),
        "arr": pa.array([[1, 2], [], None, [5, None, 7]],
                        type=pa.list_(pa.int64())),
        "st": pa.array([{"a": 1, "b": "x"}, {"a": 2, "b": None},
                        None, {"a": 4, "b": "w"}],
                       type=pa.struct([("a", pa.int64()), ("b", pa.string())])),
        "m": pa.array([[("k1", 1.5)], [], None, [("k2", 2.5), ("k3", 3.5)]],
                      type=pa.map_(pa.string(), pa.float64())),
    }))
    for codec in ("none", "zlib"):
        back = deserialize_table(serialize_table(t, codec))
        assert back.column("arr").values.tolist()[0] == [1, 2]
        assert back.to_arrow().equals(t.to_arrow()), codec


def test_serializer_nested_deep():
    t = HostTable.from_arrow(pa.table({
        "nested": pa.array([[[1], [2, 3]], None, [[4]]],
                           type=pa.list_(pa.list_(pa.int64()))),
    }))
    back = deserialize_table(serialize_table(t))
    assert back.to_arrow().equals(t.to_arrow())


def test_transport_reflective_load():
    conf = RapidsConf()
    tr = load_transport(conf)
    assert isinstance(tr, LocalShuffleTransport)


class MockFlakyTransport(ShuffleTransport):
    """Returns blocks out of order and drops nothing (protocol mock)."""

    def __init__(self, conf=None):
        self.inner = LocalShuffleTransport()
        self.fetch_calls = 0

    def publish(self, block, payload):
        self.inner.publish(block, payload)

    def fetch(self, blocks):
        self.fetch_calls += 1
        yield from self.inner.fetch(list(reversed(blocks)))

    def remove_shuffle(self, sid):
        self.inner.remove_shuffle(sid)


def test_manager_write_read_roundtrip():
    mgr = ShuffleManager(transport=MockFlakyTransport())
    nparts = 4
    t = _host_table(200, seed=1)
    dt_ = DeviceTable.from_host(t, min_bucket=8)
    sid = mgr.new_shuffle_id()
    sizes = mgr.write_partition(sid, map_id=0, batches=iter([dt_]),
                                key_names=["k"], num_parts=nparts)
    assert sum(1 for s in sizes if s > 0) >= 2
    rows = 0
    seen_keys = {}
    for p in range(nparts):
        for batch in mgr.read_partition(sid, num_maps=1, reduce_id=p,
                                        min_bucket=8):
            ht = batch.to_host()
            rows += ht.num_rows
            for kv in ht.column("k").values:
                seen_keys.setdefault(int(kv), set()).add(p)
    assert rows == 200
    # every key lands in exactly one partition
    assert all(len(parts) == 1 for parts in seen_keys.values())


def test_device_partitioner_matches_host():
    from spark_rapids_tpu.plan.physical import murmur_hash_columns
    t = _host_table(128, seed=2)
    dt_ = DeviceTable.from_host(t, min_bucket=8)
    dev = np.asarray(device_partition_ids(dt_, ["k"], 8))[:128]
    host = (murmur_hash_columns(t, ["k"]) % np.uint32(8)).astype(np.int32)
    np.testing.assert_array_equal(dev, host)


def test_heartbeats():
    hb = HeartbeatManager(timeout_s=0.05)
    hb.register(1)
    hb.register(2)
    assert hb.live_peers() == [1, 2]
    import time
    time.sleep(0.06)
    hb.heartbeat(2)
    assert hb.live_peers() == [2]


def test_ici_exchange_cpu_mesh():
    import jax
    from jax.sharding import Mesh
    from spark_rapids_tpu.shuffle.ici import (ici_all_to_all_exchange,
                                              shard_table, unshard_table)
    devices = np.array(jax.devices()[:8])
    if len(devices) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = Mesh(devices, ("dp",))
    t = _host_table(256, seed=3)
    dt_ = DeviceTable.from_host(t, min_bucket=8, capacity=256)
    sharded = shard_table(dt_, mesh)
    out = ici_all_to_all_exchange(sharded, ["k"], mesh)
    assert int(out.num_rows) == 256
    merged = unshard_table(out).to_host()
    # same multiset of rows
    got = sorted(zip(merged.column("k").values.tolist(),
                     np.round(merged.column("v").values, 9).tolist()))
    exp = sorted(zip(t.column("k").values.tolist(),
                     np.round(t.column("v").values, 9).tolist()))
    assert got == exp
    # keys co-located per shard: rows for one key stay in one shard block
    n = 8
    per = out.capacity // n
    kvals = np.asarray(merged.column("k").values)
    mask = np.asarray(out.row_mask)
    shard_of = np.repeat(np.arange(n), per)
    key_shards = {}
    flat_k = np.asarray(unshard_table(out).columns[0].data)
    for i in np.nonzero(mask)[0]:
        key_shards.setdefault(int(flat_k[i]), set()).add(int(shard_of[i]))
    assert all(len(s) == 1 for s in key_shards.values())


def test_dcn_mock_transport_device_to_device():
    """Cross-host accelerated tier, mocked (round-2 missing #6; reference:
    UCX.scala:69 device-to-device block movement; protocol testing via
    mocks as in RapidsShuffleTestHelper): blocks stay device-resident,
    fetch lands them on the consumer's device, per-link bytes are
    accounted, and a missing block raises fetch-failed."""
    import jax
    import numpy as np
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.device import DeviceTable
    from spark_rapids_tpu.columnar.host import HostColumn, HostTable
    from spark_rapids_tpu.shuffle.dcn import DcnShuffleTransport, \
        MockDcnFabric
    from spark_rapids_tpu.shuffle.transport import BlockId, \
        ShuffleFetchFailedException
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >=2 virtual devices")
    fabric = MockDcnFabric()
    a = DcnShuffleTransport(fabric, "host-a", device=devs[0])
    b = DcnShuffleTransport(fabric, "host-b", device=devs[1])
    rng = np.random.default_rng(0)
    t = DeviceTable.from_host(HostTable(
        ["k", "v"], [HostColumn(dt.LONG, rng.integers(0, 9, 64)),
                     HostColumn(dt.DOUBLE, rng.normal(size=64))]), 8)
    t = jax.device_put(t, devs[0])
    a.publish_table(BlockId(1, 0, 0), t)
    got = dict(b.fetch_tables([BlockId(1, 0, 0)]))[BlockId(1, 0, 0)]
    # landed on the CONSUMER's device, no host serialization in between
    assert devs[1] in got.row_mask.devices()
    assert got.to_host().column("v").values.tolist() == \
        t.to_host().column("v").values.tolist()
    assert fabric.link_bytes[("host-a", "host-b")] > 0
    with pytest.raises(ShuffleFetchFailedException):
        list(b.fetch_tables([BlockId(1, 9, 9)]))
    # failure injection hook (transport-mock testing surface)
    calls = []
    def fault(src, dst, blk):
        calls.append(blk)
        raise ShuffleFetchFailedException(blk, "injected DCN fault")
    fabric.fault = fault
    with pytest.raises(ShuffleFetchFailedException, match="injected"):
        list(b.fetch_tables([BlockId(1, 0, 0)]))
    assert calls == [BlockId(1, 0, 0)]
