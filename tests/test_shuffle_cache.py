"""Shuffle buffer catalog tests (reference: RapidsCachingWriter +
ShuffleBufferCatalog — device-resident shuffle blocks, spillable, freed on
unregisterShuffle)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar.device import DeviceTable
from spark_rapids_tpu.columnar.host import HostTable
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.memory.catalog import (BufferCatalog, get_catalog,
                                             set_catalog)
from spark_rapids_tpu.shuffle.manager import ShuffleManager
from spark_rapids_tpu.shuffle.transport import (BlockId,
                                                LocalShuffleTransport,
                                                ShuffleFetchFailedException)


def _table(vals, keys):
    return HostTable.from_arrow(pa.table({
        "k": pa.array(np.asarray(keys, dtype=np.int64)),
        "v": pa.array(np.asarray(vals, dtype=np.int64)),
    }))


class _ExplodingTransport(LocalShuffleTransport):
    """Proves reads never touch the transport when blocks are cached."""

    def fetch(self, blocks):
        raise AssertionError("transport fetch used despite cached blocks")


def _write(mgr, sid, n_maps=2, n_parts=3):
    inputs = {}
    for m in range(n_maps):
        t = _table(np.arange(m * 100, m * 100 + 20), np.arange(20) % 7)
        inputs[m] = t
        mgr.write_partition(sid, m, iter([DeviceTable.from_host(t, 8)]),
                            ["k"], n_parts)
    return inputs


def test_cached_write_read_skips_transport():
    mgr = ShuffleManager(transport=_ExplodingTransport())
    assert mgr.cache_writes  # auto mode: on for the in-process transport
    sid = mgr.new_shuffle_id()
    inputs = _write(mgr, sid)
    got = []
    for r in range(3):
        for t in mgr.read_partition(sid, 2, r, min_bucket=8):
            ht = t.to_host()
            got.extend(ht.column("v").values.tolist())
    expect = sorted(v for t in inputs.values()
                    for v in t.column("v").values.tolist())
    assert sorted(got) == expect
    assert mgr.buffer_catalog.stats()["blocks"] == 6


def test_cached_blocks_spill_and_restore():
    prev = get_catalog()
    small = BufferCatalog(RapidsConf(), device_limit=6000, host_limit=1 << 20)
    set_catalog(small)
    try:
        mgr = ShuffleManager(transport=LocalShuffleTransport())
        sid = mgr.new_shuffle_id()
        inputs = _write(mgr, sid, n_maps=4)
        assert sum(small.spill_count.values()) > 0, small.stats()
        got = []
        for r in range(3):
            for t in mgr.read_partition(sid, 4, r, min_bucket=8):
                got.extend(t.to_host().column("v").values.tolist())
        expect = sorted(v for t in inputs.values()
                        for v in t.column("v").values.tolist())
        assert sorted(got) == expect
    finally:
        set_catalog(prev)


def test_cached_missing_block_fetch_failed_and_recompute():
    mgr = ShuffleManager(transport=LocalShuffleTransport())
    sid = mgr.new_shuffle_id()
    inputs = _write(mgr, sid)
    # sabotage: drop map 1's block for reduce partition 0
    mgr.buffer_catalog.remove_shuffle(sid + 1000)  # no-op on other shuffles
    handle = mgr.buffer_catalog._blocks.pop((sid, 1, 0))
    handle.close()
    with pytest.raises(ShuffleFetchFailedException):
        list(mgr.read_partition(sid, 2, 0, min_bucket=8))

    recomputed = []

    def recompute(map_id):
        recomputed.append(map_id)
        mgr.write_partition(sid, map_id, iter([DeviceTable.from_host(
            inputs[map_id], 8)]), ["k"], 3)

    out = list(mgr.read_partition(sid, 2, 0, min_bucket=8, recompute=recompute))
    assert recomputed == [1] and out


def test_remove_shuffle_frees_catalog_entries():
    prev = get_catalog()
    cat = BufferCatalog(RapidsConf(), device_limit=1 << 24)
    set_catalog(cat)
    try:
        mgr = ShuffleManager(transport=LocalShuffleTransport())
        sid = mgr.new_shuffle_id()
        _write(mgr, sid)
        before = cat.stats()["buffers"]
        assert before >= 6
        freed = mgr.buffer_catalog.remove_shuffle(sid)
        assert freed == 6
        assert cat.stats()["buffers"] == before - 6
    finally:
        set_catalog(prev)


def test_cache_writes_off_uses_transport():
    mgr = ShuffleManager(RapidsConf(
        {"spark.rapids.tpu.shuffle.cacheWrites": "off"}),
        transport=LocalShuffleTransport())
    assert not mgr.cache_writes
    sid = mgr.new_shuffle_id()
    inputs = _write(mgr, sid)
    assert BlockId(sid, 0, 0) in mgr.transport._blocks
    got = []
    for r in range(3):
        for t in mgr.read_partition(sid, 2, r, min_bucket=8):
            got.extend(t.to_host().column("v").values.tolist())
    expect = sorted(v for t in inputs.values()
                    for v in t.column("v").values.tolist())
    assert sorted(got) == expect
