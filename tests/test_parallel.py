"""parallel/ package: mesh builders on the virtual CPU mesh, failure
detection (reference: RapidsShuffleHeartbeatManager), and local-cluster
multi-executor execution (reference: Spark local-cluster mode tests)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.parallel import (DriverRuntime, ExecutorContext,
                                       FailureDetector, LocalCluster,
                                       MeshTopology, data_parallel_mesh,
                                       grid_mesh, virtual_cpu_mesh)


def test_topology_detect():
    topo = MeshTopology.detect()
    assert topo.n_devices >= 8  # conftest forces 8 cpu devices
    assert topo.process_count == 1
    assert not topo.multi_host


def test_mesh_builders():
    m = data_parallel_mesh(8)
    assert m.shape == {"dp": 8}
    g = grid_mesh(2, 4)
    assert g.shape == {"dp": 2, "ici": 4}
    v = virtual_cpu_mesh(4)
    assert v.shape == {"dp": 4}
    with pytest.raises(ValueError):
        grid_mesh(100, 100)


def test_failure_detector_clock():
    t = [0.0]
    fd = FailureDetector(timeout_s=10.0, clock=lambda: t[0])
    lost = []
    fd.on_peer_lost(lost.append)
    fd.heartbeat(1)
    fd.heartbeat(2)
    assert fd.live() == [1, 2]
    t[0] = 5.0
    fd.heartbeat(2)
    t[0] = 11.0
    assert fd.check() == [1]
    assert lost == [1]
    assert fd.live() == [2]
    assert fd.dead() == [1]
    # peer 1 comes back (new executor with reused id): recovered
    fd.heartbeat(1)
    assert fd.live() == [1, 2]
    # repeated checks don't re-fire listeners
    t[0] = 30.0
    assert set(fd.check()) == {1, 2}
    t[0] = 31.0
    assert fd.check() == []


def test_listener_errors_swallowed():
    t = [0.0]
    fd = FailureDetector(timeout_s=1.0, clock=lambda: t[0])
    calls = []
    fd.on_peer_lost(lambda e: 1 / 0)
    fd.on_peer_lost(calls.append)
    fd.heartbeat(7)
    t[0] = 2.0
    assert fd.check() == [7]
    assert calls == [7]


def test_driver_runtime_registration():
    drv = DriverRuntime(heartbeat_timeout_s=60.0)
    e0 = ExecutorContext(drv.next_executor_id())
    e1 = ExecutorContext(drv.next_executor_id())
    assert (e0.executor_id, e1.executor_id) == (0, 1)
    drv.register_executor(e0)
    drv.register_executor(e1)
    assert drv.live_executors() == [0, 1]


@pytest.fixture(scope="module")
def cluster_data():
    rng = np.random.default_rng(11)
    return pa.table({
        "k": rng.integers(0, 20, 5000),
        "v": rng.normal(size=5000),
    })


@pytest.mark.parametrize("device", [False, True])
def test_local_cluster_query(session, cluster_data, device):
    from spark_rapids_tpu.expr.functions import col, sum as fsum
    df = session.create_dataframe(cluster_data, num_partitions=4)
    q = df.group_by("k").agg(fsum(col("v")).alias("s"))
    with LocalCluster(3, device=device) as cluster:
        got = cluster.run(q)
    exp = q.collect(device=False)
    got = got.sort_by([("k", "ascending")])
    exp = exp.sort_by([("k", "ascending")])
    assert got.column("k").to_pylist() == exp.column("k").to_pylist()
    np.testing.assert_allclose(
        got.column("s").to_numpy(zero_copy_only=False),
        exp.column("s").to_numpy(zero_copy_only=False), rtol=1e-9)


def test_local_cluster_semaphore_serializes_device_work(session, cluster_data):
    from spark_rapids_tpu.expr.functions import col, lit
    df = session.create_dataframe(cluster_data, num_partitions=6)
    q = df.filter(col("v") > lit(0.0))
    with LocalCluster(2) as cluster:
        got = cluster.run(q)
        waits = [ctx.semaphore.acquire_count for ctx in cluster.executors]
    assert sum(waits) == 6  # every partition acquired its executor's chip
    exp = q.collect(device=False)
    assert got.num_rows == exp.num_rows
