"""Async-first execution: device-resident hot path with deferred D2H
and nonblocking row counts (ISSUE 18).

Covers the acceptance contract:
- byte-identical results on TPC-H q1/q3/q5/q6 between the async default
  and the sync-forcing debug mode (``spark.rapids.tpu.async.enabled=
  false``) — the deferral must never change an answer,
- the movement ledger sees the win: zero host round trips either way,
  and a multi-batch output drain costs ONE blocking crossing async
  (``to_host_batched``) where the sync-forced mode pays one per batch,
- ``DataFrame.collect`` issues at most one bulk ``jax.device_get`` per
  output drain (the ``bulk_download_stats`` pin) — the deferred-D2H
  tentpole's load-bearing property,
- ``resolve_scalars`` batches N scalar decisions into one ledgered
  crossing async, and honestly reports N crossings when sync-forced.

Sessions here configure the process-global async flag on init, so each
test that flips it restores the default before leaving (the
``_async_default`` fixture) — later modules assume async-on.
"""
import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.utils import movement

QUERIES = ("q1", "q3", "q5", "q6")


@pytest.fixture(autouse=True)
def _async_default():
    """Every test leaves the process-global flag back at the default
    (async on) no matter which mode its sessions configured last."""
    yield
    from spark_rapids_tpu.columnar.device import configure_async
    configure_async(RapidsConf())


def _session(async_on, **extra):
    from spark_rapids_tpu.session import TpuSession
    return TpuSession({
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.shuffle.partitions": 2,
        "spark.rapids.tpu.movement.enabled": True,
        "spark.rapids.tpu.async.enabled": async_on,
        **extra,
    })


def _run_tpch(async_on):
    """(answers, per-query ledger deltas, per-query bulk-call deltas)
    for q1/q3/q5/q6 in one session of the given mode."""
    from spark_rapids_tpu.columnar.device import bulk_download_stats
    from spark_rapids_tpu.tools import tpch
    sess = _session(async_on)
    try:
        tables = tpch.gen_all(0, tiny=True)
        dfs = tpch.build_dataframes(sess, tables, num_partitions=2)
        answers, ledger, bulk = {}, {}, {}
        for name in QUERIES:
            m0 = dict(movement.movement_stats())
            b0 = dict(bulk_download_stats())
            answers[name] = getattr(tpch, name)(dfs).collect(device=True)
            m1 = dict(movement.movement_stats())
            b1 = dict(bulk_download_stats())
            ledger[name] = {k: m1[k] - m0[k]
                            for k in ("blocking_count", "round_trips",
                                      "d2h_bytes")}
            bulk[name] = b1["calls"] - b0["calls"]
        return answers, ledger, bulk
    finally:
        sess.close()


@pytest.fixture(scope="module")
def tpch_both_modes():
    """q1/q3/q5/q6 once async, once sync-forced (fresh session each)."""
    a = _run_tpch(True)
    s = _run_tpch(False)
    from spark_rapids_tpu.columnar.device import configure_async
    configure_async(RapidsConf())
    movement.reset_movement()
    return a, s


# ---------------------------------------------------------------------------
# parity: the deferral must never change an answer
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("name", QUERIES)
def test_tpch_async_parity(tpch_both_modes, name):
    """Byte-identical arrow tables between async and sync-forced — the
    sync-forcing mode exists exactly so a wrong answer bisects to the
    deferral, which requires the clean run to match it bit for bit."""
    (ans_a, _, _), (ans_s, _, _) = tpch_both_modes
    assert ans_a[name].equals(ans_s[name]), name


# ---------------------------------------------------------------------------
# the ledger sees the win
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("name", QUERIES)
def test_tpch_zero_round_trips(tpch_both_modes, name):
    """Device residency end to end: no query batch may bounce host->
    device within a query in either mode."""
    (_, led_a, _), (_, led_s, _) = tpch_both_modes
    assert led_a[name]["round_trips"] == 0
    assert led_s[name]["round_trips"] == 0


@pytest.mark.parametrize("name", QUERIES)
def test_tpch_async_blocking_never_worse(tpch_both_modes, name):
    """Async mode must not ADD blocking crossings over the sync-forced
    mode (at tiny scale many funnels batch a single scalar, so equality
    is common — the strict reduction is pinned on the multi-batch drain
    below)."""
    (_, led_a, _), (_, led_s, _) = tpch_both_modes
    assert led_a[name]["blocking_count"] <= led_s[name]["blocking_count"]


def test_multibatch_drain_reduces_blocking_syncs():
    """The deferred-D2H tentpole, measured: a 4-partition projection
    drains 4 device batches, so the sync-forced mode pays 4 blocking
    downloads where async pays ONE bulk crossing — and the answers
    still match exactly."""
    from spark_rapids_tpu.expr.functions import col

    def run(async_on):
        sess = _session(async_on)
        try:
            df = sess.create_dataframe(pd.DataFrame({
                "a": np.arange(4000, dtype=np.int64),
                "b": np.arange(4000, dtype=np.int64) % 13,
            }), num_partitions=4)
            m0 = dict(movement.movement_stats())
            out = df.filter(col("b") > 3).select("a").collect(device=True)
            m1 = dict(movement.movement_stats())
            return out, {k: m1[k] - m0[k]
                         for k in ("blocking_count", "round_trips")}
        finally:
            sess.close()

    out_a, led_a = run(True)
    out_s, led_s = run(False)
    assert out_a.equals(out_s)
    assert led_a["round_trips"] == 0 and led_s["round_trips"] == 0
    assert led_a["blocking_count"] < led_s["blocking_count"], (led_a, led_s)


# ---------------------------------------------------------------------------
# the bulk-download pin: at most one device_get per output drain
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("name", QUERIES)
def test_collect_one_bulk_device_get_per_drain(tpch_both_modes, name):
    """Each async collect funnels its whole output through EXACTLY one
    ``to_host_batched`` bulk ``jax.device_get``; the sync-forced mode
    never uses the bulk path (per-batch ``to_host`` instead)."""
    (_, _, bulk_a), (_, _, bulk_s) = tpch_both_modes
    assert bulk_a[name] == 1, name
    assert bulk_s[name] == 0, name


# ---------------------------------------------------------------------------
# resolve_scalars: the batched-scalar funnel
# ---------------------------------------------------------------------------
def test_resolve_scalars_batches_ledger_entries():
    """N device scalars cost ONE ledgered crossing async and N crossings
    sync-forced (each honestly reported — the blocking_count delta IS
    the measured win at real decision boundaries like the sort merge's
    emit+carry pair and the exchange drain's per-batch counts)."""
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.device import (configure_async,
                                                  resolve_scalars)
    led = movement.configure_movement(RapidsConf(
        {"spark.rapids.tpu.movement.enabled": True}))
    try:
        scalars = [jnp.asarray(i, jnp.int32) for i in range(5)]
        configure_async(RapidsConf())     # async default
        before = led.totals()["d2h_count"]
        assert resolve_scalars(*scalars) == (0, 1, 2, 3, 4)
        assert led.totals()["d2h_count"] - before == 1
        configure_async(RapidsConf(
            {"spark.rapids.tpu.async.enabled": False}))
        before = led.totals()["d2h_count"]
        assert resolve_scalars(*scalars) == (0, 1, 2, 3, 4)
        assert led.totals()["d2h_count"] - before == 5
    finally:
        movement.reset_movement()


def test_deferred_scalar_lazy_async_eager_sync():
    """DeferredScalar stays unresolved until the host branches on it
    (async), and resolves at construction when sync-forced — the debug
    mode's whole point is that every stall happens AT its site."""
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.device import (DeferredScalar,
                                                  configure_async)
    configure_async(RapidsConf())
    d = DeferredScalar(jnp.asarray(7, jnp.int32))
    assert not d.is_resolved
    assert int(d) == 7 and d.is_resolved
    assert DeferredScalar(3).is_resolved          # host values pass through
    configure_async(RapidsConf(
        {"spark.rapids.tpu.async.enabled": False}))
    assert DeferredScalar(jnp.asarray(9, jnp.int32)).is_resolved
