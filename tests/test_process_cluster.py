"""Cross-process shuffle: TCP transport + ProcessCluster + fetch-failed
semantics (reference: RapidsShuffleServer/Client crossing executors,
RapidsShuffleFetchFailedException -> stage retry)."""
import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import HostColumn, HostTable
from spark_rapids_tpu.shuffle.serializer import deserialize_table, \
    serialize_table
from spark_rapids_tpu.shuffle.transport import (BlockId,
                                                LocalShuffleTransport,
                                                ShuffleFetchFailedException)


def _table(vals, keys=None):
    cols = [HostColumn(dt.LONG, np.asarray(vals, dtype=np.int64))]
    names = ["v"]
    if keys is not None:
        cols.insert(0, HostColumn(dt.LONG, np.asarray(keys, dtype=np.int64)))
        names.insert(0, "k")
    return HostTable(names, cols)


def test_local_transport_missing_block_raises():
    t = LocalShuffleTransport()
    t.publish(BlockId(0, 0, 0), b"x")
    with pytest.raises(ShuffleFetchFailedException):
        list(t.fetch([BlockId(0, 0, 0), BlockId(0, 1, 0)]))


def test_tcp_transport_roundtrip_and_fetch_failed():
    from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport
    a = TcpShuffleTransport()
    b = TcpShuffleTransport()
    try:
        b.add_peer(*a.address)
        payload = serialize_table(_table([1, 2, 3]))
        a.publish(BlockId(7, 0, 0), payload)
        b.publish(BlockId(7, 1, 0), serialize_table(_table([4])))
        got = dict(b.fetch([BlockId(7, 0, 0), BlockId(7, 1, 0)]))
        assert deserialize_table(got[BlockId(7, 0, 0)]) \
            .column("v").values.tolist() == [1, 2, 3]
        with pytest.raises(ShuffleFetchFailedException):
            list(b.fetch([BlockId(7, 9, 9)]))
    finally:
        a.close()
        b.close()


def test_manager_recompute_hook():
    """A dropped block fails loudly, then recovers via the recompute hook."""
    import jax
    from spark_rapids_tpu.columnar.device import DeviceTable
    from spark_rapids_tpu.shuffle.manager import ShuffleManager
    from spark_rapids_tpu.conf import RapidsConf
    transport = LocalShuffleTransport()
    # this test exercises the TRANSPORT tier; device-store caching would
    # short-circuit it (covered by test_shuffle_cache.py)
    mgr = ShuffleManager(RapidsConf(
        {"spark.rapids.tpu.shuffle.cacheWrites": "off"}), transport=transport)
    sid = mgr.new_shuffle_id()
    tables = {m: _table(np.arange(m * 10, m * 10 + 10),
                        keys=np.arange(10) % 3) for m in range(2)}
    for m, t in tables.items():
        mgr.write_partition(sid, m, iter([DeviceTable.from_host(
            t, min_bucket=8)]), ["k"], 3)
    # sabotage: drop one block
    del transport._blocks[BlockId(sid, 1, 0)]
    with pytest.raises(ShuffleFetchFailedException):
        list(mgr.read_partition(sid, 2, 0, min_bucket=8))
    # with the recompute hook the read succeeds
    recomputed = []

    def recompute(map_id):
        recomputed.append(map_id)
        mgr.write_partition(sid, map_id, iter([DeviceTable.from_host(
            tables[map_id], min_bucket=8)]), ["k"], 3)

    list(mgr.read_partition(sid, 2, 0, min_bucket=8, recompute=recompute))
    assert recomputed == [1]
    # verify the union of all reduce partitions equals the input multiset
    all_rows = []
    for r in range(3):
        for d in mgr.read_partition(sid, 2, r, min_bucket=8,
                                    recompute=recompute):
            all_rows.extend(d.to_host().column("v").values.tolist())
    exp = sorted(v for t in tables.values()
                 for v in t.column("v").values.tolist())
    assert sorted(all_rows) == exp


@pytest.mark.slow
def test_process_cluster_shuffle_and_recovery():
    from spark_rapids_tpu.parallel.runtime import (
        ProcessCluster, shuffle_read_recompute_task, shuffle_read_task,
        shuffle_write_task)
    rng = np.random.default_rng(0)
    n_maps, n_parts = 2, 3
    payloads = {}
    expected_rows = []
    for m in range(n_maps):
        keys = rng.integers(0, 50, 200)
        vals = rng.integers(0, 10_000, 200)
        expected_rows.extend(vals.tolist())
        payloads[m] = serialize_table(_table(vals, keys=keys))
    with ProcessCluster(3) as cluster:
        sid = 0
        # map tasks on workers 0 and 1
        for m in range(n_maps):
            cluster.run_on(m, shuffle_write_task, sid, m, payloads[m],
                           ["k"], n_parts)
        # reduce on worker 2, fetching across processes over TCP
        got_rows = []
        for r in range(n_parts):
            out = cluster.run_on(2, shuffle_read_task, sid, n_maps, r)
            if out is not None:
                got_rows.extend(
                    deserialize_table(out).column("v").values.tolist())
        assert sorted(got_rows) == sorted(expected_rows)

        # failure injection: kill worker 0 (holds map 0's blocks).
        cluster.kill(0)
        # loud failure without recovery
        with pytest.raises(RuntimeError, match="ShuffleFetchFailed"):
            cluster.run_on(2, shuffle_read_task, sid, n_maps, 0)
        # recovery: reduce worker recomputes map 0 from lineage, then reads
        got_rows = []
        for r in range(n_parts):
            out = cluster.run_on(2, shuffle_read_recompute_task, sid,
                                 n_maps, r, payloads, ["k"], n_parts)
            if out is not None:
                got_rows.extend(
                    deserialize_table(out).column("v").values.tolist())
        assert sorted(got_rows) == sorted(expected_rows)


@pytest.mark.slow
def test_cross_process_broadcast_single_build():
    """The build side materializes ONCE and other workers re-materialize
    from the transport — never re-executing the build (round-2 missing #5;
    reference: GpuBroadcastExchangeExec.scala:336-345,
    SerializeConcatHostBuffersDeserializeBatch)."""
    from spark_rapids_tpu.parallel.runtime import (ProcessCluster,
                                                   broadcast_build_task,
                                                   broadcast_probe_task)
    rng = np.random.default_rng(3)
    build = _table(np.arange(0, 40, 2), keys=np.arange(0, 40, 2))
    probes = {w: _table(rng.integers(0, 40, 30),
                        keys=rng.integers(0, 40, 30)) for w in range(2)}
    with ProcessCluster(2) as cluster:
        builds, fetches = cluster.run_on(
            0, broadcast_build_task, 99, serialize_table(build))
        assert (builds, fetches) == (1, 0)
        totals = {}
        for w in range(2):
            payload, b, f = cluster.run_on(
                w, broadcast_probe_task, 99,
                serialize_table(probes[w]), "k")
            totals[w] = (deserialize_table(payload), b, f)
        # worker 0 built once and never fetched; worker 1 only fetched
        assert totals[0][1:] == (1, 0)
        assert totals[1][1:] == (0, 1)
        build_keys = set(build.column("k").values.tolist())
        for w in range(2):
            got = totals[w][0].column("k").values.tolist()
            exp = [k for k in probes[w].column("k").values.tolist()
                   if k in build_keys]
            assert got == exp


def test_tcp_chunked_spill_backed_serving():
    """Large blocks under a small host budget: publishes spill to disk and
    are served back in fixed windows; the receive-inflight cap bounds
    fetched-but-unconsumed bytes (round-2 weak #4; reference:
    RapidsShuffleServer.scala:70 BufferSendState windows + the
    maxReceiveInflightBytes throttle, RapidsConf.scala:1064)."""
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport
    conf = RapidsConf({
        "spark.rapids.tpu.shuffle.tcp.chunkBytes": 64 * 1024,
        "spark.rapids.tpu.shuffle.host.storeBytes": 300 * 1024,
        "spark.rapids.shuffle.transport.maxReceiveInflightBytes": 700 * 1024,
    })
    a = TcpShuffleTransport(conf)
    b = TcpShuffleTransport(conf)
    try:
        b.add_peer(*a.address)
        rng = np.random.default_rng(0)
        payloads = {m: rng.integers(0, 256, 256 * 1024, dtype=np.uint8)
                    .tobytes() for m in range(6)}  # 1.5MB >> 300KB budget
        for m, p in payloads.items():
            a.publish(BlockId(5, m, 0), p)
        # the store kept at most its budget in memory; the rest hit disk
        assert a.store.spilled_blocks >= 4, a.store.spilled_blocks
        assert a.store.mem_bytes <= 300 * 1024 + 256 * 1024
        got = dict(b.fetch([BlockId(5, m, 0) for m in range(6)]))
        for m, p in payloads.items():
            assert got[BlockId(5, m, 0)] == p, f"block {m} corrupted"
        # throttle: in-flight reservations never exceeded the cap
        assert 0 < b.inflight.peak <= 700 * 1024, b.inflight.peak
        # spilled blocks serve correctly after removal of another shuffle
        a.publish(BlockId(6, 0, 0), b"tiny")
        a.remove_shuffle(5)
        with pytest.raises(ShuffleFetchFailedException):
            list(b.fetch([BlockId(5, 0, 0)]))
        assert dict(b.fetch([BlockId(6, 0, 0)]))[BlockId(6, 0, 0)] == b"tiny"
    finally:
        a.close()
        b.close()


def test_tcp_fetch_failed_releases_inflight_budget():
    """A fetch-failed mid-list must not leak inflight reservations for
    already-prefetched blocks (a leak would deadlock the retry fetch)."""
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport
    conf = RapidsConf({
        "spark.rapids.tpu.shuffle.tcp.chunkBytes": 8 * 1024,
        "spark.rapids.shuffle.transport.maxReceiveInflightBytes": 64 * 1024,
    })
    a = TcpShuffleTransport(conf)
    b = TcpShuffleTransport(conf)
    try:
        b.add_peer(*a.address)
        a.publish(BlockId(3, 1, 0), b"x" * 30000)
        with pytest.raises(ShuffleFetchFailedException):
            # missing block first; block 1's prefetch completes and holds
            # a reservation that MUST be released on abandonment
            list(b.fetch([BlockId(3, 0, 0), BlockId(3, 1, 0)]))
        import time
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with b.inflight._cv:
                if b.inflight._used == 0:
                    break
            time.sleep(0.05)
        with b.inflight._cv:
            assert b.inflight._used == 0, b.inflight._used
        # the retry fetch works (no poisoned budget)
        got = dict(b.fetch([BlockId(3, 1, 0)]))
        assert got[BlockId(3, 1, 0)] == b"x" * 30000
    finally:
        a.close()
        b.close()


def test_process_cluster_dcn_tier_and_fetch_failure():
    """The REAL cross-process DCN tier (round-4 VERDICT item 9; reference:
    UCXShuffleTransport.scala:47): blocks published device-resident on one
    worker move to another worker's device with host bytes only on the
    wire; a killed publisher surfaces ShuffleFetchFailed."""
    from spark_rapids_tpu.parallel.runtime import (
        ProcessCluster, dcn_add_peer_task, dcn_address_task,
        dcn_fetch_task, dcn_publish_task)
    rng = np.random.default_rng(1)
    vals = rng.integers(0, 10_000, 300)
    payload = serialize_table(_table(vals))
    vals2 = rng.integers(0, 10_000, 100)
    payload2 = serialize_table(_table(vals2))
    with ProcessCluster(3) as cluster:
        addrs = {w: cluster.run_on(w, dcn_address_task) for w in range(3)}
        for w in range(3):
            for peer, (host, port) in addrs.items():
                if peer != w:
                    cluster.run_on(w, dcn_add_peer_task, host, port)
        n = cluster.run_on(0, dcn_publish_task, 7, 0, 0, payload)
        assert n == 300
        cluster.run_on(1, dcn_publish_task, 7, 1, 0, payload2)
        # worker 2 fetches both over the wire
        got = deserialize_table(cluster.run_on(2, dcn_fetch_task, 7, 0, 0))
        assert sorted(got.column("v").values.tolist()) == sorted(vals.tolist())
        got2 = deserialize_table(cluster.run_on(2, dcn_fetch_task, 7, 1, 0))
        assert sorted(got2.column("v").values.tolist()) == \
            sorted(vals2.tolist())
        # failure injection: kill the publisher of block (7,0,0); a fresh
        # fetch of a NEVER-materialized block must fail loudly
        cluster.run_on(0, dcn_publish_task, 8, 0, 0, payload)
        cluster.kill(0)
        with pytest.raises(RuntimeError, match="ShuffleFetchFailed"):
            cluster.run_on(2, dcn_fetch_task, 8, 0, 0)
