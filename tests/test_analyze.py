"""srtpu-analyze static-analysis suite (spark_rapids_tpu/tools/analyze).

Covers the ISSUE 6 acceptance contract:
- fixture snippets trip each of the four checkers (sync / lock /
  thread / jit) and the known-clean variants stay clean,
- suppression syntax + baseline round-trip (sticky initial_inventory,
  regression detection on a seeded new violation),
- the tier-1 gate: the full package analyzes CLEAN against the
  committed baseline, a seeded violation in ANY checker category is
  flagged as new, and the host-sync baseline is strictly below the
  initial inventory (real fixes landed, not just suppressions).
"""
import json
import pathlib
import textwrap

import pytest

from spark_rapids_tpu.tools.analyze import (analyze_paths, baseline_summary,
                                            compare_to_baseline,
                                            default_baseline_path,
                                            load_baseline, severity_for,
                                            write_baseline)

PKG = pathlib.Path(__file__).resolve().parent.parent / "spark_rapids_tpu"


def _write(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def _rules(report, check=None):
    return sorted({f.rule for f in report.findings
                   if check is None or f.check == check})


# ---------------------------------------------------------------------------
# checker fixtures: each rule trips on a minimal snippet
# ---------------------------------------------------------------------------
def test_sync_checker_rules(tmp_path):
    path = _write(tmp_path, "sync_fixture.py", """\
        import numpy as np
        import jax
        import jax.numpy as jnp

        def hot_path(table, col):
            n = col.sum().item()
            host = np.asarray(col)
            got = jax.device_get(col)
            col.block_until_ready()
            rows = int(table.num_rows)
            total = int(jnp.sum(table.row_mask))
            return n, host, got, rows, total

        def fine(col, rows):
            dev = jnp.asarray(rows)        # stays on device: NOT a sync
            arr = np.array([1, 2, 3])      # host literal: NOT flagged
            return dev, arr, int(rows)     # plain int on host value
        """)
    report = analyze_paths([path], checks=["sync"])
    assert _rules(report) == ["sync-asarray", "sync-block-until-ready",
                              "sync-device-get", "sync-int-scalar",
                              "sync-item"]
    assert report.count("sync") == 6  # int() hits twice (num_rows + jnp)
    assert all(f.symbol == "hot_path" for f in report.findings)


def test_movement_unledgered_rule(tmp_path):
    """Direct device_get/.item() in a HOT package file that never talks
    to the movement ledger flags movement-unledgered; the same sync in a
    scope that notes the crossing (a funnel) is covered, and loose
    fixture files (hot by policy, no ledger obligation) never flag."""
    hot = tmp_path / "spark_rapids_tpu" / "exec"
    hot.mkdir(parents=True)
    (hot / "bypass.py").write_text(textwrap.dedent("""\
        import jax
        from ..utils import movement

        _SITE = "spark_rapids_tpu/exec/bypass.py::funnel"

        def funnel(col):
            t0 = movement.clock()
            host = jax.device_get(col)
            movement.note_d2h(_SITE, host.nbytes, t0)
            return host

        def bypass(col):
            return jax.device_get(col)

        def bypass_item(col):
            return col.sum().item()
        """))
    report = analyze_paths([str(tmp_path)], checks=["sync"])
    mv = [f for f in report.findings if f.rule == "movement-unledgered"]
    assert sorted(f.symbol for f in mv) == ["bypass", "bypass_item"]
    # the ledgered funnel still carries its plain sync finding, but no
    # movement-unledgered one
    assert not any(f.symbol == "funnel" for f in mv)
    # loose file outside the package tree: plain sync rules only
    loose = _write(tmp_path, "loose.py", """\
        import jax

        def f(col):
            return jax.device_get(col)
        """)
    loose_report = analyze_paths([loose], checks=["sync"])
    assert _rules(loose_report) == ["sync-device-get"]


def test_movement_unledgered_suppression(tmp_path):
    """sync-ok covers movement-unledgered too — one annotation per
    deliberate sync site, not one per rule."""
    hot = tmp_path / "spark_rapids_tpu" / "columnar"
    hot.mkdir(parents=True)
    (hot / "ok.py").write_text(
        "import jax\n\ndef f(col):\n"
        "    return jax.device_get(col)"
        "  # srtpu: sync-ok(cold scalar, once per query)\n")
    report = analyze_paths([str(tmp_path)], checks=["sync"])
    assert report.count("sync") == 0
    assert {f.rule for f in report.suppressed} \
        == {"sync-device-get", "movement-unledgered"}


def test_mesh_checker_rules(tmp_path):
    """mesh-shard-loop trips on a per-shard Python loop over the mesh
    extent in a hot exec scope; a scope that enters shard_map, a
    comprehension, and a mesh-ok'd site all stay clean."""
    hot = tmp_path / "spark_rapids_tpu" / "exec"
    hot.mkdir(parents=True)
    (hot / "serial.py").write_text(textwrap.dedent("""\
        def drain(node, mesh, axis):
            out = []
            for i in range(mesh.shape[axis]):
                out.append(node.dispatch(i))
            return out

        def drain_parts(node):
            n = node.num_partitions
            for p in range(n):
                node.dispatch(p)

        def spmd(mesh, cols, run):
            from spark_rapids_tpu.parallel.shard_compat import shard_map
            for i in range(mesh.shape["dp"]):
                prime(i)   # spec plumbing around the collective: exempt
            return shard_map(run, mesh=mesh, in_specs=None,
                             out_specs=None)(cols)

        def alloc(node):
            return [[] for _ in range(node.num_partitions)]

        def ok(node):
            for p in range(node.num_partitions):  # srtpu: mesh-ok(input drain, not per-shard compute)
                node.pull(p)
        """))
    report = analyze_paths([str(tmp_path)], checks=["mesh"])
    assert _rules(report) == ["mesh-shard-loop"]
    assert sorted(f.symbol for f in report.findings) \
        == ["drain", "drain_parts"]
    assert [f.rule for f in report.suppressed] == ["mesh-shard-loop"]
    # outside the exec/shuffle packages the rule never fires
    loose = _write(tmp_path, "loose.py", """\
        def drain(node):
            for p in range(node.num_partitions):
                node.dispatch(p)
        """)
    assert analyze_paths([loose], checks=["mesh"]).count("mesh") == 0


def test_sync_checker_computed_receivers(tmp_path):
    """.item()/.block_until_ready() on computed expressions — the
    receiver has no qualifiable name but the sync is just as blocking."""
    path = _write(tmp_path, "computed.py", """\
        def f(a, b, mask, valid):
            n = (a - b).item()
            (mask & valid).block_until_ready()
            return n
        """)
    report = analyze_paths([path], checks=["sync"])
    assert _rules(report) == ["sync-block-until-ready", "sync-item"]


def test_sync_checker_skips_cold_packages(tmp_path):
    cold = tmp_path / "spark_rapids_tpu" / "tools"
    cold.mkdir(parents=True)
    (cold / "coldmod.py").write_text(
        "import numpy as np\n\ndef f(x):\n    return np.asarray(x)\n")
    report = analyze_paths([str(tmp_path)], checks=["sync"])
    assert report.count("sync") == 0
    assert severity_for(str(cold / "coldmod.py")) == "cold"
    assert severity_for(str(PKG / "exec" / "exchange.py")) == "hot"
    assert severity_for(str(PKG / "plan" / "aqe.py")) == "warm"


def test_lock_checker_deadlock_class(tmp_path):
    path = _write(tmp_path, "lock_fixture.py", """\
        class Node:
            def _materialize(self):
                with self._mat_lock:
                    self._materialize_locked()   # BAD: reaches semaphore

            def _materialize_locked(self):
                with self.sem.task_scope():
                    pass

        class GoodNode:
            def _materialize(self):
                with self._mat_lock:
                    with exempt_admission():
                        self._materialize_locked()

            def _materialize_locked(self):
                with self.sem.task_scope():
                    pass
        """)
    report = analyze_paths([path], checks=["lock"])
    hits = [f for f in report.findings
            if f.rule == "lock-sem-under-materialize"]
    assert len(hits) == 1
    assert hits[0].symbol == "Node._materialize"


def test_lock_checker_call_graph_is_transitive(tmp_path):
    path = _write(tmp_path, "lock_transitive.py", """\
        def leaf(sem):
            sem.acquire_if_necessary()

        def middle(sem):
            leaf(sem)

        def bad(self, sem):
            with self._mat_lock:
                middle(sem)

        def also_bad(self, sem):
            with self._mat_lock:
                run_tasks(middle)    # function reference, not a call
        """)
    report = analyze_paths([path], checks=["lock"])
    syms = sorted(f.symbol for f in report.findings
                  if f.rule == "lock-sem-under-materialize")
    assert syms == ["also_bad", "bad"]


def test_lock_checker_misuse_rules(tmp_path):
    path = _write(tmp_path, "lock_misuse.py", """\
        def bare(sem):
            sem.task_scope()          # never entered: does nothing

        def release_inside(sem):
            with sem.held():
                sem.release_all()     # drops the scope's own hold
        """)
    report = analyze_paths([path], checks=["lock"])
    assert _rules(report) == ["lock-bare-contextmanager",
                              "lock-release-all-in-scope"]


def test_thread_checker_rules(tmp_path):
    path = _write(tmp_path, "thread_fixture.py", """\
        import queue
        import threading
        import time
        from concurrent.futures import ThreadPoolExecutor

        q1 = queue.Queue()                       # unbounded
        q2 = queue.SimpleQueue()                 # unbounded by design
        q3 = queue.Queue(maxsize=4)              # fine
        q4 = queue.Queue(8)                      # fine (positional bound)
        t1 = threading.Thread(target=print)      # unnamed + non-daemon
        t2 = threading.Thread(target=print, name="x", daemon=True)  # fine
        p1 = ThreadPoolExecutor(max_workers=2)   # unnamed workers
        p2 = ThreadPoolExecutor(max_workers=2, thread_name_prefix="x")

        def poll():
            time.sleep(0.1)                      # engine sleep
        """)
    report = analyze_paths([path], checks=["thread"])
    rules = [f.rule for f in report.findings]
    assert rules.count("thread-unbounded-queue") == 2
    assert rules.count("thread-unnamed") == 2
    assert rules.count("thread-non-daemon") == 1
    assert rules.count("thread-sleep") == 1


def test_jit_checker_side_effects(tmp_path):
    path = _write(tmp_path, "jit_fixture.py", """\
        from spark_rapids_tpu.utils.compile_cache import cached_jit

        class Op:
            def batch_fn(self):
                conf_val = self.conf.get(KEY)     # build-time: fine
                def run(table):
                    print("tracing")              # BAD: effect in trace
                    self.metrics.add("rows", 1)   # BAD
                    return table.scale(conf_val)
                return run

            def execute(self):
                fn = cached_jit(self.plan_signature(), self.batch_fn)
                return fn
        """)
    report = analyze_paths([path], checks=["jit"])
    effects = [f for f in report.findings if f.rule == "jit-side-effect"]
    assert len(effects) == 2
    msgs = " ".join(f.message for f in effects)
    assert "print" in msgs and "metric registry" in msgs


def test_jit_checker_use_after_donate(tmp_path):
    path = _write(tmp_path, "donate_fixture.py", """\
        from spark_rapids_tpu.utils.compile_cache import cached_jit

        def bad(batch, build):
            fn = cached_jit("k|donate", build, donate_argnums=(0,))
            out = fn(batch)
            return batch.nbytes()     # BAD: donated buffers may be dead

        def good(batch, build):
            fn = cached_jit("k|donate", build, donate_argnums=(0,))
            size = batch.nbytes()     # before the call: fine
            if size:
                out = fn(batch)
            else:
                out = other(batch)    # sibling branch: fine
            return out
        """)
    report = analyze_paths([path], checks=["jit"])
    hits = [f for f in report.findings if f.rule == "jit-use-after-donate"]
    assert len(hits) == 1
    assert hits[0].symbol == "bad"


def test_jit_checker_donation_scopes_do_not_leak(tmp_path):
    """A donating call inside a nested def belongs to THAT scope: the
    outer function's same-named variable must not be flagged."""
    path = _write(tmp_path, "donate_nested.py", """\
        from spark_rapids_tpu.utils.compile_cache import cached_jit

        def outer(batch, build):
            def helper(batch):
                fn = cached_jit("k", build, donate_argnums=(0,))
                return fn(batch)
            out = helper(batch)
            return batch.nbytes()     # helper's param, not a donation
        """)
    report = analyze_paths([path], checks=["jit"])
    assert not [f for f in report.findings
                if f.rule == "jit-use-after-donate"]


def test_bucket_checker_rules(tmp_path):
    path = _write(tmp_path, "bucket_fixture.py", """\
        from spark_rapids_tpu.columnar.device import (DeviceTable,
                                                      bucket_rows,
                                                      resolve_min_bucket)

        def bad_call(n, host):
            cap = bucket_rows(n, 256)                     # literal floor
            t = DeviceTable.from_host(host, min_bucket=8)  # literal kw
            return cap, t

        class BadNode:
            def __init__(self, child, min_bucket: int = 1024):  # ad-hoc
                self.min_bucket = min_bucket

        class GoodNode:
            def __init__(self, child, min_bucket=None):
                self.min_bucket = resolve_min_bucket(min_bucket)

        def good_call(n, conf, host):
            cap = bucket_rows(n)                      # policy default
            cap2 = bucket_rows(n, conf.min_bucket_rows)  # conf-threaded
            return cap, cap2, DeviceTable.from_host(host)
        """)
    report = analyze_paths([path], checks=["bucket"])
    rules = [f.rule for f in report.findings]
    assert rules.count("bucket-literal") == 2
    assert rules.count("bucket-adhoc-default") == 1
    syms = {f.symbol for f in report.findings}
    assert syms == {"bad_call", "BadNode.__init__"}


def test_trace_checker_rules(tmp_path):
    path = _write(tmp_path, "trace_fixture.py", """\
        from spark_rapids_tpu.utils.tracing import get_tracer

        class Cluster:
            def _submit(self, w, envelope):
                self._task_qs[w].put(envelope)        # the chokepoint

            def sneaky(self, w, envelope):
                self._task_qs[w].put(envelope)        # bypasses _submit

            def sentinel(self, w):
                self._task_qs[w].put(None)  # srtpu: trace-ok(shutdown)

        def good(host):
            with get_tracer().span("upload", "upload"):
                return host

        def bad(tracer):
            tracer.span("upload", "upload")           # bare call: no-op

        def not_a_tracer(df):
            return df.span("2020", "2021")            # unrelated .span
        """)
    report = analyze_paths([path], checks=["trace"])
    rules = [f.rule for f in report.findings]
    assert rules.count("trace-span-no-with") == 1
    assert rules.count("trace-ctx-bypass") == 1
    assert {f.symbol for f in report.findings} == {"Cluster.sneaky", "bad"}
    assert len(report.suppressed) == 1


def test_memtrack_checker_rules(tmp_path):
    path = _write(tmp_path, "memtrack_fixture.py", """\
        from spark_rapids_tpu.columnar import DeviceTable

        def leaky(host):
            return DeviceTable.from_host(host, min_bucket=8)

        def accounted(host, catalog):
            t = DeviceTable.from_host(host, min_bucket=8)
            return catalog.register(t)

        def closure_accounted(host, catalog):
            def upload():
                return DeviceTable.from_host(host, min_bucket=8)
            return catalog.register(upload())

        def helper(host):
            return DeviceTable.from_host(host, 8)  # srtpu: memtrack-ok(caller registers)

        def derived(cols, mask):
            return DeviceTable(cols, mask)          # view: no new HBM
        """)
    report = analyze_paths([path], checks=["memtrack"])
    assert [f.rule for f in report.findings] == \
        ["memtrack-unregistered-upload"]
    assert {f.symbol for f in report.findings} == {"leaky"}
    assert len(report.suppressed) == 1


def test_retry_checker_rules(tmp_path):
    path = _write(tmp_path, "retry_fixture.py", """\
        from spark_rapids_tpu.columnar import DeviceTable
        from spark_rapids_tpu.memory.retry import (split_device_rows,
                                                   with_retry_split)
        from spark_rapids_tpu.utils.compile_cache import cached_jit

        def unguarded_dispatch(batch, build):
            fn = cached_jit('k', build)
            return fn(batch)

        def unguarded_upload(host):
            return DeviceTable.from_host(host, min_bucket=8)

        def guarded_dispatch(batch, build):
            fn = cached_jit('k', build)
            return with_retry_split(fn, batch,
                                    splitter=split_device_rows,
                                    scope='fixture')

        def guarded_closure(batch, build):
            fn = cached_jit('k', build)
            def dispatch(b):
                return fn(b)
            return with_retry_split(dispatch, batch,
                                    splitter=split_device_rows,
                                    scope='fixture')

        def merge_only(merged, build):
            fn = cached_jit('m', build)
            return fn(merged)  # srtpu: retry-ok(merge inputs cannot split)

        def plain_call(helper, batch):
            return helper(batch)   # not cached_jit-bound: never flagged
        """)
    report = analyze_paths([path], checks=["retry"])
    assert sorted(f.rule for f in report.findings) == [
        "retry-unguarded-dispatch", "retry-unguarded-upload"]
    assert {f.symbol for f in report.findings} == \
        {"unguarded_dispatch", "unguarded_upload"}
    assert len(report.suppressed) == 1


def test_retry_checker_skips_warm_packages(tmp_path):
    warm = tmp_path / "spark_rapids_tpu" / "parallel"
    warm.mkdir(parents=True)
    (warm / "warmmod.py").write_text(
        "from spark_rapids_tpu.columnar import DeviceTable\n\n"
        "def f(host):\n"
        "    return DeviceTable.from_host(host, min_bucket=8)\n")
    report = analyze_paths([str(tmp_path)], checks=["retry"])
    assert report.count("retry") == 0


def test_net_checker_rules(tmp_path):
    path = _write(tmp_path, "net_fixture.py", """\
        import socket

        def no_deadline(addr):
            s = socket.create_connection(addr)
            return s.recv(4)

        def with_deadline(addr):
            s = socket.create_connection(addr, timeout=5.0)
            s.settimeout(5.0)
            return s.recv(4)

        def positional_deadline(addr):
            with socket.create_connection(addr, 5.0) as s:
                return s.recv(4)

        def helper_recv(s):
            return s.recv(4)  # srtpu: net-ok(every caller sets the deadline before handing the socket here)

        def swallow(sock):
            try:
                sock.sendall(b"x")
            except Exception:
                pass

        def typed_handler(sock):
            try:
                sock.sendall(b"x")
            except OSError:
                return None
        """)
    report = analyze_paths([path], checks=["net"])
    assert sorted(f.rule for f in report.findings) == [
        "net-bare-except-pass", "net-connect-no-timeout",
        "net-socket-no-timeout"]
    assert {f.symbol for f in report.findings} == {"no_deadline", "swallow"}
    assert len(report.suppressed) == 1


def test_degrade_checker_rules(tmp_path):
    path = _write(tmp_path, "degrade_fixture.py", """\
        from spark_rapids_tpu.exec.fallback import (quarantine_on_failure,
                                                    with_host_fallback)
        from spark_rapids_tpu.memory.retry import (DeviceOomError,
                                                   with_retry_split)
        from spark_rapids_tpu.utils.compile_cache import cached_jit

        def unguarded(batch, build):
            fn = cached_jit('k', build)
            return fn(batch)

        def retry_guarded(batch, build):
            fn = cached_jit('k', build)
            return with_retry_split(fn, batch, scope='fixture')

        def fallback_guarded(node, batch, build, host_fn):
            fn = cached_jit('k', build)
            return with_host_fallback(node, fn, host_fn)(batch)

        def note_only_guarded(node, batch, build):
            fn = cached_jit('k', build)
            with quarantine_on_failure(node):
                return fn(batch)

        def swallows_everything(batch, fn):
            try:
                return fn(batch)
            except Exception:
                return None

        def swallows_structured(batch, fn):
            try:
                return fn(batch)
            except DeviceOomError:
                return None

        def reraises(batch, fn):
            try:
                return fn(batch)
            except Exception:
                raise

        def forwards(q, batch, fn):
            try:
                return fn(batch)
            except Exception:  # srtpu: degrade-ok(forwarded to the consumer queue)
                q.put(None)

        def typed_cleanup(handle):
            try:
                handle.close()
            except OSError:
                return None
        """)
    report = analyze_paths([path], checks=["degrade"])
    assert sorted(f.rule for f in report.findings) == [
        "degrade-swallowed-failure", "degrade-swallowed-failure",
        "degrade-unguarded-dispatch"]
    assert {f.symbol for f in report.findings} == \
        {"unguarded", "swallows_everything", "swallows_structured"}
    assert len(report.suppressed) == 1
    # the structured-error message names what was caught
    (structured,) = [f for f in report.findings
                     if f.symbol == "swallows_structured"]
    assert "DeviceOomError" in structured.message


def test_degrade_checker_skips_cold_packages(tmp_path):
    cold = tmp_path / "spark_rapids_tpu" / "tools"
    cold.mkdir(parents=True)
    (cold / "coldmod.py").write_text(
        "def f(x, fn):\n"
        "    try:\n"
        "        return fn(x)\n"
        "    except Exception:\n"
        "        return None\n")
    report = analyze_paths([str(tmp_path)], checks=["degrade"])
    assert report.count("degrade") == 0


def test_degrade_swallow_rule_covers_warm_packages(tmp_path):
    warm = tmp_path / "spark_rapids_tpu" / "parallel"
    warm.mkdir(parents=True)
    (warm / "warmmod.py").write_text(
        "from spark_rapids_tpu.utils.compile_cache import cached_jit\n\n"
        "def swallow(x, fn):\n"
        "    try:\n"
        "        return fn(x)\n"
        "    except Exception:\n"
        "        return None\n\n"
        "def dispatch(batch, build):\n"
        "    fn = cached_jit('k', build)\n"
        "    return fn(batch)\n")
    report = analyze_paths([str(tmp_path)], checks=["degrade"])
    # swallow rule reaches warm; the dispatch rule stays hot-only
    assert [f.rule for f in report.findings] == ["degrade-swallowed-failure"]


def test_net_checker_skips_cold_packages(tmp_path):
    cold = tmp_path / "spark_rapids_tpu" / "tools"
    cold.mkdir(parents=True)
    (cold / "coldnet.py").write_text(
        "import socket\n\ndef f(addr):\n"
        "    return socket.create_connection(addr)\n")
    report = analyze_paths([str(tmp_path)], checks=["net"])
    assert report.count("net") == 0


def test_bucket_checker_skips_cold_packages(tmp_path):
    cold = tmp_path / "spark_rapids_tpu" / "tools"
    cold.mkdir(parents=True)
    (cold / "coldmod.py").write_text(
        "def f(n):\n    return bucket_rows(n, 64)\n")
    report = analyze_paths([str(tmp_path)], checks=["bucket"])
    assert report.count("bucket") == 0


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------
def test_suppression_same_line_and_standalone(tmp_path):
    path = _write(tmp_path, "supp.py", """\
        import numpy as np

        def f(col):
            a = np.asarray(col)  # srtpu: sync-ok(host-only helper)
            # srtpu: sync-ok(cold error path)
            b = np.asarray(col)
            c = np.asarray(col)
            return a, b, c
        """)
    report = analyze_paths([path], checks=["sync"])
    assert report.count("sync") == 1          # only the unsuppressed one
    assert len(report.suppressed) == 2
    assert {f.line for f in report.findings} == {7}


def test_suppression_requires_reason(tmp_path):
    path = _write(tmp_path, "supp_empty.py", """\
        import numpy as np

        def f(col):
            return np.asarray(col)  # srtpu: sync-ok()
        """)
    report = analyze_paths([path], checks=["sync"])
    # empty reason: suppression inert AND reported as a meta finding
    assert report.count("sync") == 1
    assert any(f.rule == "meta-empty-suppression-reason"
               for f in report.findings)


def test_suppression_is_check_scoped(tmp_path):
    path = _write(tmp_path, "supp_scope.py", """\
        import queue

        q = queue.Queue()  # srtpu: sync-ok(wrong check name)
        """)
    report = analyze_paths([path], checks=["thread"])
    assert report.count("thread") == 1        # sync-ok does not cover it


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------
def test_baseline_roundtrip_and_regression(tmp_path):
    src = _write(tmp_path, "base.py", """\
        import numpy as np

        def f(col):
            return np.asarray(col)
        """)
    report = analyze_paths([src], checks=["sync"])
    assert report.count("sync") == 1
    bl_path = str(tmp_path / "baseline.json")
    write_baseline(report, bl_path)
    # clean against its own baseline
    assert compare_to_baseline(report, load_baseline(bl_path)) == []
    # a second occurrence in the SAME function is a new violation
    pathlib.Path(src).write_text(pathlib.Path(src).read_text().replace(
        "return np.asarray(col)",
        "x = np.asarray(col)\n    return np.asarray(x)"))
    grown = analyze_paths([src], checks=["sync"])
    regs = compare_to_baseline(grown, load_baseline(bl_path))
    assert len(regs) == 1 and regs[0].rule == "sync-asarray"
    # initial_inventory is sticky across regeneration
    first = load_baseline(bl_path)["initial_inventory"]
    write_baseline(grown, bl_path)
    again = load_baseline(bl_path)
    assert again["initial_inventory"] == first
    assert again["counts"][regs[0].key()]["count"] == 2


def test_baseline_key_survives_line_drift(tmp_path):
    src = _write(tmp_path, "drift.py", """\
        import numpy as np

        def f(col):
            return np.asarray(col)
        """)
    report = analyze_paths([src], checks=["sync"])
    bl_path = str(tmp_path / "baseline.json")
    write_baseline(report, bl_path)
    # unrelated code above shifts the line; the key (path+rule+symbol)
    # still matches, so no new violation is reported
    pathlib.Path(src).write_text(
        "import numpy as np\n\nPAD = 1\nPAD2 = 2\n\n\ndef f(col):\n"
        "    return np.asarray(col)\n")
    drifted = analyze_paths([src], checks=["sync"])
    assert compare_to_baseline(drifted, load_baseline(bl_path)) == []


# ---------------------------------------------------------------------------
# tier-1 gate: the package is clean vs the committed baseline
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def package_report():
    return analyze_paths([str(PKG)])


def test_tier1_package_clean_vs_committed_baseline(package_report):
    baseline = load_baseline(default_baseline_path())
    regressions = compare_to_baseline(package_report, baseline)
    assert not regressions, (
        "NEW static-analysis violation(s) — fix the site, suppress with "
        "'# srtpu: <check>-ok(reason)', or (for accepted debt) regenerate "
        "via python -m spark_rapids_tpu.tools.analyze --write-baseline:\n"
        + "\n".join(f.render() for f in regressions))


def test_tier1_seeded_violation_fails_each_category(tmp_path,
                                                    package_report):
    """A new violation in ANY checker category must be flagged as new
    against the committed baseline. The seeded file's keys are absent
    from the baseline, so analyzing it alone yields exactly the delta —
    the package-matches-baseline half is pinned by the tier-1 gate tests
    above, which lets this loop skip nine full-package re-scans."""
    seeds = {
        "sync": "import numpy as np\n\ndef f(c):\n"
                "    return np.asarray(c)\n",
        "lock": "def f(self, sem):\n    with self._mat_lock:\n"
                "        with sem.task_scope():\n            pass\n",
        "thread": "import queue\n\nq = queue.Queue()\n",
        "jit": "from spark_rapids_tpu.utils.compile_cache import "
               "cached_jit\n\ndef f(x, build):\n"
               "    fn = cached_jit('k', build, donate_argnums=(0,))\n"
               "    out = fn(x)\n    return x.sum()\n",
        "bucket": "from spark_rapids_tpu.columnar.device import "
                  "bucket_rows\n\ndef f(n):\n"
                  "    return bucket_rows(n, 512)\n",
        "trace": "def f(tracer):\n"
                 "    tracer.span('q', 'query')\n    return 1\n",
        "memtrack": "from spark_rapids_tpu.columnar import DeviceTable\n\n"
                    "def f(host):\n"
                    "    return DeviceTable.from_host(host, min_bucket=8)\n",
        "net": "def f(sock):\n    try:\n        sock.sendall(b'x')\n"
               "    except Exception:\n        pass\n",
        "retry": "from spark_rapids_tpu.utils.compile_cache import "
                 "cached_jit\n\ndef f(x, build):\n"
                 "    fn = cached_jit('k', build)\n"
                 "    return fn(x)\n",
    }
    baseline = load_baseline(default_baseline_path())
    for check, body in seeds.items():
        seeded_file = _write(tmp_path, f"seed_{check}.py", body)
        report = analyze_paths([seeded_file], checks=[check])
        regs = compare_to_baseline(report, baseline)
        assert regs and all(f.check == check for f in regs), \
            f"seeded {check} violation not detected"
        pathlib.Path(seeded_file).unlink()


def test_tier1_sync_debt_strictly_below_initial_inventory(package_report):
    """The acceptance criterion that forbids pure baselining: the live
    sync count must be strictly below the initial (pre-fix) inventory
    recorded when the analyzer first ran (137 sites)."""
    baseline = load_baseline(default_baseline_path())
    initial = baseline["initial_inventory"]["sync"]
    assert package_report.count("sync") < initial
    assert baseline["summary"]["checks"]["sync"]["total"] < initial


def test_tier1_thread_and_lock_and_jit_clean(package_report):
    """Conventions the engine already follows stay absolutely clean —
    these checks carry no baseline allowance at all."""
    assert package_report.count("thread") == 0
    assert package_report.count("lock") == 0
    assert package_report.count("jit") == 0
    assert package_report.count("meta") == 0
    # the shape-bucket policy refactor drove literal floors out of the
    # engine; the only survivors are reasoned bucket-ok suppressions
    # (cross-process wire-protocol constants)
    assert package_report.count("bucket") == 0
    # the trace-context contract is enforced from day one: every span is
    # with-scoped and every envelope goes through _submit (the one
    # shutdown-sentinel put carries a reasoned trace-ok suppression)
    assert package_report.count("trace") == 0


def test_baseline_summary_matches_committed_file(package_report):
    """bench.py copies baseline_summary() into the bench JSON; it must
    agree with a live analyzer run so the trajectory metric is honest."""
    info = baseline_summary()
    assert info, "committed baseline missing"
    live = package_report.summary()["checks"].get("sync", {})
    committed = info["summary"]["checks"].get("sync", {})
    assert committed == live, (
        "committed baseline is stale — regenerate with "
        "python -m spark_rapids_tpu.tools.analyze --write-baseline")


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def test_cli_json_and_exit_codes(tmp_path, capsys):
    from spark_rapids_tpu.tools.analyze.__main__ import main

    src = _write(tmp_path, "climod.py",
                 "import numpy as np\n\ndef f(c):\n"
                 "    return np.asarray(c)\n")
    bl = str(tmp_path / "bl.json")
    # no baseline yet -> exit 2
    assert main([src, "--baseline", bl]) == 2
    capsys.readouterr()
    assert main([src, "--baseline", bl, "--write-baseline"]) == 0
    assert main([src, "--baseline", bl]) == 0
    out = capsys.readouterr().out
    assert "clean vs baseline" in out
    # grow a violation -> exit 1
    pathlib.Path(src).write_text(
        "import numpy as np\n\ndef f(c):\n"
        "    a = np.asarray(c)\n    return np.asarray(a)\n")
    assert main([src, "--baseline", bl]) == 1
    capsys.readouterr()
    # JSON mode round-trips
    assert main([src, "--baseline", bl, "--json", "--no-baseline"]) == 0
    obj = json.loads(capsys.readouterr().out)
    assert obj["summary"]["checks"]["sync"]["total"] == 2


def test_diagnose_renders_sync_debt(tmp_path):
    """tools/diagnose.py cross-references the committed baseline."""
    from spark_rapids_tpu.tools.diagnose import diagnose_path

    records = [
        {"event": "app_start", "app_id": "a", "schema_version": 3,
         "ts": 0.0, "conf": {}},
        {"event": "query_start", "query_id": 1, "ts": 0.0, "plan": "p"},
        {"event": "query_end", "query_id": 1, "ts": 1.0, "wall_s": 1.0,
         "final_plan": "p", "aqe_events": [], "spill_count": {},
         "semaphore_wait_s": 0.0, "stats": {}},
        {"event": "app_end", "ts": 1.0},
    ]
    p = tmp_path / "log.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in records) + "\n")
    rep = diagnose_path(str(p))
    text = rep.summary()
    assert "static sync-site debt" in text
    assert "initial inventory 137" in text
    obj = json.loads(rep.to_json())
    assert obj["sync_debt"]["initial_inventory"]["sync"] == 137
