"""UDF subsystem tests (reference analogues: udf-compiler OpcodeSuite.scala,
udf-examples, GpuArrowEvalPythonExec integration tests)."""
import math

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expr.base import AttributeReference
from spark_rapids_tpu.expr.functions import col, lit
from spark_rapids_tpu.udf import (UdfCompileError, columnar_udf, compile_udf,
                                  udf)
from spark_rapids_tpu.udf.python_exec import PythonUDF, TpuArrowEvalPythonExec

from harness import assert_tpu_cpu_equal, data_gen


@pytest.fixture
def df(session, rng):
    t = data_gen(rng, 150, {
        "a": "float64", "b": "float64", "i": "int32", "s": "string",
    })
    return session.create_dataframe(t)


# ---------------------------------------------------------------------------
# Tier 1: bytecode compiler (reference: udf-compiler OpcodeSuite)
# ---------------------------------------------------------------------------
def test_compile_arithmetic(df):
    @udf(return_type=dt.DOUBLE)
    def fma(x, y):
        return x * 2.0 + y / 3.0

    out = df.select(fma(col("a"), col("b")).alias("r"))
    _assert_compiled(out)
    assert_tpu_cpu_equal(out)


def test_compile_branches(df):
    @udf(return_type=dt.DOUBLE)
    def tiered(x):
        if x > 50.0:
            return x * 0.8
        elif x > 0.0:
            return x * 0.9
        else:
            return 0.0

    out = df.select(tiered(col("a")).alias("r"))
    _assert_compiled(out)
    assert_tpu_cpu_equal(out)


def test_compile_ternary_and_bool(df):
    @udf(return_type=dt.DOUBLE)
    def sign(x):
        return 1.0 if x >= 0 else -1.0

    assert_tpu_cpu_equal(df.select(sign(col("b")).alias("r")))


def test_compile_math_calls(df):
    @udf(return_type=dt.DOUBLE)
    def wave(x):
        return math.sin(x) + math.sqrt(abs(x))

    out = df.select(wave(col("a")).alias("r"))
    _assert_compiled(out)
    assert_tpu_cpu_equal(out, rel_tol=1e-6)


def test_compile_clamp_min_max(df):
    @udf(return_type=dt.DOUBLE)
    def clamp(x):
        return min(max(x, -10.0), 10.0)

    assert_tpu_cpu_equal(df.select(clamp(col("b")).alias("r")))


def test_compile_string_methods(session):
    # ASCII-only input: device case mapping is ASCII-only by design
    # (see the Upper/Lower ps_note in plan/overrides.py)
    import pyarrow as pa
    t = pa.table({"s": pa.array(["  spark  ", "RAPIDS", "tpu", "", None,
                                 " Mixed Case "])})
    df = session.create_dataframe(t)

    @udf(return_type=dt.STRING)
    def shout(s):
        return s.upper().strip()

    out = df.select(shout(col("s")).alias("r"))
    _assert_compiled(out)
    assert_tpu_cpu_equal(out)


def test_compile_local_variables(df):
    @udf(return_type=dt.DOUBLE)
    def poly(x):
        a = x * x
        b = a + x
        return b * 0.5

    out = df.select(poly(col("a")).alias("r"))
    _assert_compiled(out)
    assert_tpu_cpu_equal(out)


def test_compiler_rejects_loops():
    def total(x):
        out = 0.0
        for _ in range(3):
            out += x
        return out

    with pytest.raises(UdfCompileError):
        compile_udf(total, [AttributeReference("a")], dt.DOUBLE)


def test_compiler_rejects_unknown_calls():
    table = {1: "x"}

    def lookup(x):
        return table.get(x)

    with pytest.raises(UdfCompileError):
        compile_udf(lookup, [AttributeReference("a")], dt.STRING)


# ---------------------------------------------------------------------------
# Tier 2: columnar (jax-traceable) UDFs — the RapidsUDF / udf-examples analogue
# ---------------------------------------------------------------------------
def test_columnar_udf_device(df):
    @columnar_udf(dt.DOUBLE)
    def rsq(x, y):
        return x * x + y * y

    assert_tpu_cpu_equal(df.select(rsq(col("a"), col("b")).alias("r")))


def test_columnar_udf_cosine_similarity(session, rng):
    # the udf-examples/src/main/cpp/src/cosine_similarity.cu analogue:
    # a user batch kernel, expressed directly in jnp, fusing on device
    import pyarrow as pa
    n = 64
    t = pa.table({
        "x1": rng.normal(size=n), "y1": rng.normal(size=n),
        "x2": rng.normal(size=n), "y2": rng.normal(size=n),
    })

    @columnar_udf(dt.DOUBLE, name="cosine2d")
    def cos2d(x1, y1, x2, y2):
        num = x1 * x2 + y1 * y2
        den = ((x1 * x1 + y1 * y1) ** 0.5) * ((x2 * x2 + y2 * y2) ** 0.5)
        return num / den

    df = session.create_dataframe(t)
    assert_tpu_cpu_equal(
        df.select(cos2d(col("x1"), col("y1"), col("x2"), col("y2"))
                  .alias("cos")), rel_tol=1e-6)


def test_columnar_udf_device_ok_false_falls_back(df, session):
    @columnar_udf(dt.DOUBLE, device_ok=False)
    def hostonly(x):
        return np.asarray(x) * 3.0

    out = df.select(hostonly(col("a")).alias("r"))
    plan = session._physical(out.logical, device=True)
    # the project must have fallen back to the CPU engine
    assert "CpuProjectExec" in _device_nodes(plan), plan.tree_string()
    assert not any(type(n).__name__ == "TpuProjectExec" for n in _walk(plan))
    assert_tpu_cpu_equal(out)


# ---------------------------------------------------------------------------
# Tier 3: interpreted Python / pandas UDFs through the Arrow eval operator
# ---------------------------------------------------------------------------
def test_python_udf_fallback_runs_arrow_exec(df, session):
    lut = {0: 10.0, 1: 20.0}

    @udf(return_type=dt.DOUBLE)
    def opaque(i):
        if i is None:
            return None
        return lut.get(int(i) % 2, 0.0)

    out = df.select(opaque(col("i")).alias("r"))
    assert _has_python_udf(out.logical.exprs[0])  # compiler bailed out
    plan = session._physical(out.logical, device=True)
    assert any(isinstance(n, TpuArrowEvalPythonExec) for n in _walk(plan)), \
        plan.tree_string()
    assert_tpu_cpu_equal(out)


def test_pandas_udf(df):
    @udf(return_type=dt.DOUBLE, kind="pandas", try_compile=False)
    def zscoreish(s):
        return (s - 1.0) * 2.0

    assert_tpu_cpu_equal(df.select(zscoreish(col("a")).alias("r")))


def test_python_udf_null_handling(session):
    import pyarrow as pa
    t = pa.table({"v": pa.array([1.0, None, 3.0, None, 5.0])})
    df = session.create_dataframe(t)

    @udf(return_type=dt.DOUBLE, try_compile=False)
    def plus1(v):
        return None if v is None else v + 1.0

    assert_tpu_cpu_equal(df.select(plus1(col("v")).alias("r")))


def test_udf_mixed_with_exprs(df):
    @udf(return_type=dt.DOUBLE)
    def halve(x):
        return x / 2.0

    assert_tpu_cpu_equal(
        df.select((halve(col("a")) + col("b") * 2.0).alias("r"),
                  col("i")))


def test_udf_in_filter(df):
    @udf(return_type=dt.BOOLEAN)
    def positive(x):
        return x > 0.0

    assert_tpu_cpu_equal(df.filter(positive(col("a"))).select(col("a")))


def test_interpreted_udf_in_filter_falls_back(df, session):
    # non-compilable UDF in a filter condition: no Arrow bridge exists for
    # filters, so the whole filter must fall back to the CPU engine instead
    # of crashing inside a device computation
    flip = {True: True, False: False}

    @udf(return_type=dt.BOOLEAN)
    def opaque_pred(x):
        return x is not None and flip.get(x > 0.0, False)

    out = df.filter(opaque_pred(col("a"))).select(col("a"))
    plan = session._physical(out.logical, device=True)
    assert any(type(n).__name__ == "CpuFilterExec" for n in _walk(plan)), \
        plan.tree_string()
    assert_tpu_cpu_equal(out)


def test_udf_compiler_conf_disables_compilation(df, rng):
    from spark_rapids_tpu.session import TpuSession
    import pyarrow as pa
    sess = TpuSession({
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.sql.udfCompiler.enabled": False,
    })
    t = pa.table({"a": rng.normal(size=32)})
    df2 = sess.create_dataframe(t)

    @udf(return_type=dt.DOUBLE)
    def double_it(x):
        return x * 2.0

    out = df2.select(double_it(col("a")).alias("r"))
    plan = sess._physical(out.logical, device=True)
    # session conf off -> stays interpreted through the Arrow bridge
    assert any(isinstance(n, TpuArrowEvalPythonExec) for n in _walk(plan)), \
        plan.tree_string()
    assert_tpu_cpu_equal(out)


def test_compiled_min_max_nan_matches_python(session):
    import pyarrow as pa
    t = pa.table({"v": pa.array([float("nan"), 1.0, -20.0, 20.0, 0.5])})
    df = session.create_dataframe(t)

    def clamp(x):
        return min(max(x, -10.0), 10.0)

    cudf = udf(clamp, return_type=dt.DOUBLE)
    out = df.select(cudf(col("v")).alias("r"))
    _assert_compiled(out)
    got = {i: v for i, v in enumerate(out.collect(device=True)
                                      .column("r").to_pylist())}
    expect = [clamp(v) for v in [float("nan"), 1.0, -20.0, 20.0, 0.5]]
    assert math.isnan(got[0]) == math.isnan(expect[0])  # NaN passes through
    for i in (1, 2, 3, 4):
        assert got[i] == expect[i]


# ---------------------------------------------------------------------------
def _assert_compiled(df_out):
    """Assert the planner compiled every Python UDF (no Arrow bridge left)."""
    plan = df_out.session._physical(df_out.logical, device=True)
    assert not any(isinstance(n, TpuArrowEvalPythonExec) for n in _walk(plan)), \
        plan.tree_string()


def _has_python_udf(e):
    if isinstance(e, PythonUDF):
        return True
    return any(_has_python_udf(c) for c in e.children)


def _walk(plan):
    yield plan
    for c in plan.children:
        yield from _walk(c)


def _device_nodes(plan):
    names = set()
    for n in _walk(plan):
        names.add(type(n).__name__)
        for e in getattr(n, "exprs", []):
            _expr_names(e, names)
    return names


def _expr_names(e, out):
    out.add(type(e).__name__)
    for c in e.children:
        _expr_names(c, out)
