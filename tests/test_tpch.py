"""TPC-H mini-scale tests: our engine (device + CPU paths) vs an independent
pandas implementation (reference analogue: mortgage/qa_nightly benchmark-ish
suites used as correctness nets)."""
import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.tools import tpch
from harness import assert_tpu_cpu_equal


ROWS = 20_000


@pytest.fixture(autouse=True)
def _no_leaked_buffers():
    """Tier-1 leak gate (memory flight recorder ISSUE): every TPC-H query
    must return the buffer catalog to its pre-query registration set — a
    buffer that outlives its query is retained HBM the next query pays
    for. The plain collect() here runs uninstrumented (no event log), so
    the gate snapshots the catalog registry directly instead of relying
    on the profiler's query_end scan."""
    from spark_rapids_tpu.memory.catalog import peek_catalog
    cat = peek_catalog()
    before = set(cat._buffers) if cat is not None else set()
    yield
    cat = peek_catalog()
    after = set(cat._buffers) if cat is not None else set()
    leaked = after - before
    if leaked:
        with cat._lock:
            detail = "; ".join(
                f"buffer {bid}: {cat._buffers[bid].size_bytes} bytes "
                f"tier={cat._buffers[bid].tier}"
                for bid in sorted(leaked) if bid in cat._buffers)
        pytest.fail(f"{len(leaked)} buffer(s) still registered after the "
                    f"query: {detail}")


@pytest.fixture(scope="module")
def lineitem():
    return tpch.gen_lineitem(0, seed=7, rows=ROWS)


@pytest.fixture(scope="module")
def orders():
    return tpch.gen_orders(0, seed=8, rows=5_000)


@pytest.fixture(scope="module")
def customer():
    return tpch.gen_customer(0, seed=9, rows=1_000)


def test_q6(session, lineitem):
    df = session.create_dataframe(lineitem, num_partitions=2)
    out = assert_tpu_cpu_equal(tpch.q6({"lineitem": df}), rel_tol=1e-9)
    # independent pandas check
    pdf = lineitem.to_pandas()
    import pyarrow as pa
    sd = pd.Series(lineitem.column("l_shipdate").combine_chunks().cast(pa.int32()).to_numpy())
    m = ((sd >= 8766) & (sd < 9131)
         & (pdf["l_discount"] >= 0.05) & (pdf["l_discount"] <= 0.07)
         & (pdf["l_quantity"] < 24.0))
    expected = (pdf.loc[m, "l_extendedprice"] * pdf.loc[m, "l_discount"]).sum()
    got = out.column("revenue")[0].as_py()
    assert got == pytest.approx(expected, rel=1e-9)


def test_q1(session, lineitem):
    df = session.create_dataframe(lineitem, num_partitions=2)
    out = assert_tpu_cpu_equal(tpch.q1({"lineitem": df}), ignore_order=False, rel_tol=1e-9)
    pdf = lineitem.to_pandas()
    import pyarrow as pa
    sd = pd.Series(lineitem.column("l_shipdate").combine_chunks().cast(pa.int32()).to_numpy())
    sub = pdf[sd <= 10471]
    grouped = sub.groupby(["l_returnflag", "l_linestatus"])
    assert out.num_rows == len(grouped)
    exp_qty = grouped["l_quantity"].sum().sort_index()
    got = out.to_pandas().set_index(["l_returnflag", "l_linestatus"]) \
        .sort_index()["sum_qty"]
    np.testing.assert_allclose(got.to_numpy(), exp_qty.to_numpy(), rtol=1e-9)


def test_q3(session, lineitem, orders, customer):
    li = session.create_dataframe(lineitem, num_partitions=2)
    od = session.create_dataframe(orders, num_partitions=2)
    cu = session.create_dataframe(customer)
    out = tpch.q3({"lineitem": li, "orders": od, "customer": cu})
    device = out.collect(device=True)
    cpu = out.collect(device=False)
    # top-10 by revenue with ties: compare the revenue column
    np.testing.assert_allclose(
        np.sort(device.column("revenue").to_numpy(zero_copy_only=False)),
        np.sort(cpu.column("revenue").to_numpy(zero_copy_only=False)),
        rtol=1e-9)
    # independent pandas check of the top revenue value
    pdf_l = lineitem.to_pandas()
    pdf_o = orders.to_pandas()
    pdf_c = customer.to_pandas()
    sd_l = lineitem.column("l_shipdate").combine_chunks().cast(__import__("pyarrow").int32()).to_numpy()
    pdf_l = pdf_l[sd_l > 9204]
    od_o = orders.column("o_orderdate").combine_chunks().cast(__import__("pyarrow").int32()).to_numpy()
    pdf_o = pdf_o[od_o < 9204]
    pdf_c = pdf_c[pdf_c["c_mktsegment"] == "BUILDING"]
    j = pdf_c.merge(pdf_o, left_on="c_custkey", right_on="o_custkey") \
             .merge(pdf_l, left_on="o_orderkey", right_on="l_orderkey")
    j["revenue"] = j["l_extendedprice"] * (1.0 - j["l_discount"])
    exp = j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])["revenue"] \
        .sum().sort_values(ascending=False)
    if len(exp):
        assert device.column("revenue")[0].as_py() == \
            pytest.approx(exp.iloc[0], rel=1e-9)
