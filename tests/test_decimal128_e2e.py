"""End-to-end decimal128: device engine vs host engine vs exact python
Decimal, across arithmetic, casts, comparisons, sort, group-by (values AND
keys), and joins (reference: the DECIMAL_128 tier — decimalExpressions.scala,
GpuCast.scala:1513, TypeChecks.scala:465)."""
from decimal import Decimal

import numpy as np
import pyarrow as pa
import pytest

from harness import assert_tpu_cpu_equal

from spark_rapids_tpu.expr.functions import col, lit
from spark_rapids_tpu.expr.functions import sum as fsum
from spark_rapids_tpu.expr.functions import count as fcount


def _dec_table(rng, n=400, with_nulls=True):
    price = [None if with_nulls and rng.random() < 0.06
             else Decimal(int(rng.integers(-10**11, 10**11))).scaleb(-2)
             for _ in range(n)]
    disc = [None if with_nulls and rng.random() < 0.06
            else Decimal(int(rng.integers(0, 101))).scaleb(-2)
            for _ in range(n)]
    wide = [None if with_nulls and rng.random() < 0.06
            else Decimal(int(rng.integers(-10**17, 10**17)) * 10**7).scaleb(-4)
            for _ in range(n)]
    return pa.table({
        "k": rng.integers(0, 7, n),
        "price": pa.array(price, type=pa.decimal128(12, 2)),
        "disc": pa.array(disc, type=pa.decimal128(12, 2)),
        "wide": pa.array(wide, type=pa.decimal128(28, 4)),
    })


@pytest.fixture
def rng():
    return np.random.default_rng(77)


def test_d128_arithmetic_chain(session, rng):
    df = session.create_dataframe(_dec_table(rng), num_partitions=2)
    q = df.select(
        (col("price") * (lit(Decimal("1.00")) - col("disc"))).alias("dp"),
        (col("wide") + col("wide")).alias("w2"),
        (col("wide") - col("price")).alias("wm"),
        (-col("wide")).alias("neg"),
    )
    assert_tpu_cpu_equal(q)


def test_d128_q1_style_device_plan(session, rng):
    """The Q1 money pipeline must actually LOWER to the device."""
    df = session.create_dataframe(_dec_table(rng), num_partitions=2)
    q = (df.with_column("dp", col("price") * (lit(Decimal("1.00")) - col("disc")))
           .group_by("k").agg(fsum(col("dp")).alias("rev"),
                              fsum(col("price")).alias("base"),
                              fcount(col("price")).alias("n")))
    out = assert_tpu_cpu_equal(q)
    # independent exact check
    t = _dec_table(np.random.default_rng(77))
    exp = {}
    for k, p, d in zip(t["k"].to_pylist(), t["price"].to_pylist(),
                       t["disc"].to_pylist()):
        e = exp.setdefault(k, [Decimal(0), False])
        if p is not None and d is not None:
            e[0] += p * (Decimal("1.00") - d)
            e[1] = True
    got = dict(zip(out.column("k").to_pylist(),
                   out.column("rev").to_pylist()))
    for k, (v, any_) in exp.items():
        if any_:
            assert got[k] == v, (k, got[k], v)
    # plan check: aggregate + project run on device
    from spark_rapids_tpu.plan.aqe import AdaptiveExec
    plan = session._physical(q.logical, device=True)
    text = plan.final_plan().tree_string() \
        if isinstance(plan, AdaptiveExec) else plan.tree_string()
    assert "TpuHashAggregate" in text or "WholeStage" in text, text


def test_d128_compare_filter_sort(session, rng):
    df = session.create_dataframe(_dec_table(rng), num_partitions=2)
    q = df.filter(col("wide") > lit(Decimal("0.0000"))) \
          .select(col("wide"), col("k")).sort(col("wide").desc())
    assert_tpu_cpu_equal(q, ignore_order=False)
    q2 = df.filter(col("wide") == col("wide")).select(col("k"))
    assert_tpu_cpu_equal(q2)


def test_d128_group_by_decimal_key(session, rng):
    t = _dec_table(rng, n=300)
    df = session.create_dataframe(t, num_partitions=2)
    q = df.group_by("wide").agg(fcount(col("k")).alias("n"))
    assert_tpu_cpu_equal(q)


def test_d128_casts(session, rng):
    from spark_rapids_tpu.columnar import dtypes as dt
    df = session.create_dataframe(_dec_table(rng), num_partitions=2)
    q = df.select(
        col("wide").cast(dt.DecimalType(38, 6)).alias("up"),
        col("wide").cast(dt.DecimalType(20, 1)).alias("down"),
        col("wide").cast(dt.DecimalType(10, 2)).alias("narrow"),  # overflow
        col("price").cast(dt.DecimalType(30, 6)).alias("widen"),
        col("wide").cast(dt.DOUBLE).alias("dbl"),
        col("k").cast(dt.DecimalType(25, 3)).alias("from_int"),
    )
    dev = q.collect(device=True).to_pandas()
    cpu = q.collect(device=False).to_pandas()
    for c in ("up", "down", "narrow", "widen", "from_int"):
        assert list(dev[c]) == list(cpu[c]), c
    assert np.allclose(dev.dbl.astype(float), cpu.dbl.astype(float),
                       rtol=1e-12, equal_nan=True)
    # HALF_UP semantics on downscale, exact vs python Decimal
    t = _dec_table(np.random.default_rng(77))
    for got, w in zip(dev["down"], t["wide"].to_pylist()):
        if w is None:
            continue
        expect = w.quantize(Decimal("0.1"), rounding="ROUND_HALF_UP")
        if abs(int(expect.scaleb(1))) >= 10 ** 20:
            expect = None  # overflows decimal(20,1): null (CheckOverflow)
        assert got == expect, (got, expect)


def test_d128_overflow_nulls(session):
    big = Decimal(10**33).scaleb(-2)
    t = pa.table({"a": pa.array([big, -big, Decimal("5.00")],
                                type=pa.decimal128(38, 2))})
    df = session.create_dataframe(t)
    q = df.select(((col("a") * col("a"))).alias("sq"))
    dev = q.collect(device=True).to_pandas()
    cpu = q.collect(device=False).to_pandas()
    # 10^31 * 10^31 = 10^62 overflows decimal(38): null on both engines
    assert dev.sq[0] is None and dev.sq[1] is None
    assert list(dev.sq) == list(cpu.sq)


def test_d128_join_key(session, rng):
    n = 200
    vals = [Decimal(int(rng.integers(0, 40)) * 10**19).scaleb(-2)
            for _ in range(n)]
    left = pa.table({"a": pa.array(vals, type=pa.decimal128(25, 2)),
                     "x": rng.integers(0, 100, n)})
    rvals = [Decimal(int(v) * 10**19).scaleb(-2) for v in range(40)]
    right = pa.table({"b": pa.array(rvals, type=pa.decimal128(25, 2)),
                      "y": np.arange(40)})
    ldf = session.create_dataframe(left, num_partitions=2)
    rdf = session.create_dataframe(right, num_partitions=1)
    q = ldf.join(rdf, condition=(col("a") == col("b")), how="inner") \
           .select(col("x"), col("y"))
    assert_tpu_cpu_equal(q)


def test_d128_sum_overflow_to_null(session):
    # sum state decimal(38,0): values that overflow it in aggregate
    big = Decimal(5 * 10**37)
    t = pa.table({"k": pa.array([1, 1, 1, 2], type=pa.int64()),
                  "v": pa.array([big, big, big, Decimal(7)],
                                type=pa.decimal128(38, 0))})
    df = session.create_dataframe(t)
    q = df.group_by("k").agg(fsum(col("v")).alias("s"))
    dev = {r["k"]: r["s"] for r in q.collect(device=True).to_pandas()
           .to_dict("records")}
    cpu = {r["k"]: r["s"] for r in q.collect(device=False).to_pandas()
           .to_dict("records")}
    assert dev[2] == Decimal(7) == cpu[2]
    assert dev[1] is None and cpu[1] is None  # 1.5e38 >= 10^38


@pytest.mark.slow
def test_decimal_tpch_q1_q6(session):
    """Q1/Q6 over DECIMAL(12,2) lineitem: device vs host vs exact Decimal.
    Slow tier (~15s of compiles); tier-1 keeps the cheaper
    test_d128_q1_style_device_plan pin on the same decimal agg lowering."""
    from decimal import Decimal as D

    from spark_rapids_tpu.tools import tpch
    li = tpch.decimal_lineitem(tpch.gen_lineitem(0, seed=11, rows=3000))
    df = session.create_dataframe(li, num_partitions=2)
    t = {"lineitem": df}
    out1 = assert_tpu_cpu_equal(tpch.q1_decimal(t), ignore_order=False)
    out6 = assert_tpu_cpu_equal(tpch.q6_decimal(t))
    # independent exact Q6
    sd = li.column("l_shipdate").to_pylist()
    lo = (np.datetime64("1994-01-01") - np.datetime64("1970-01-01")).astype(int)
    hi = (np.datetime64("1995-01-01") - np.datetime64("1970-01-01")).astype(int)
    exp = D(0)
    for d, disc, qty, price in zip(sd, li.column("l_discount").to_pylist(),
                                   li.column("l_quantity").to_pylist(),
                                   li.column("l_extendedprice").to_pylist()):
        days = (d - __import__("datetime").date(1970, 1, 1)).days
        if lo <= days < hi and D("0.05") <= disc <= D("0.07") and qty < D(24):
            exp += price * disc
    got = out6.column("revenue")[0].as_py()
    assert got == exp, (got, exp)
    # Q1 charge column is decimal(38,6): verify one group exactly
    groups = {}
    for i in range(li.num_rows):
        days = (sd[i] - __import__("datetime").date(1970, 1, 1)).days
        if days > 10471:
            continue
        key = (li.column("l_returnflag")[i].as_py(),
               li.column("l_linestatus")[i].as_py())
        price = li.column("l_extendedprice")[i].as_py()
        disc = li.column("l_discount")[i].as_py()
        tax = li.column("l_tax")[i].as_py()
        dp = price * (D("1.00") - disc)
        groups.setdefault(key, D(0))
        groups[key] += dp * (D("1.00") + tax)
    rows = out1.to_pandas()
    for _, r in rows.iterrows():
        assert r["sum_charge"] == groups[(r["l_returnflag"],
                                          r["l_linestatus"])]


def test_d128_group_by_key_over_ici_mesh():
    """decimal128 group-by keys through the ICI exchange tier: the device
    partition-id hash must handle two-limb columns (shuffle/manager.py)."""
    from spark_rapids_tpu.parallel.mesh import virtual_cpu_mesh
    from spark_rapids_tpu.session import TpuSession
    sess = TpuSession({"spark.rapids.tpu.batchRowsMinBucket": 8,
                       "spark.rapids.tpu.shuffle.partitions": 4})
    sess.attach_mesh(virtual_cpu_mesh(4))
    rng = np.random.default_rng(3)
    vals = [Decimal(int(v) * 10**19).scaleb(-2)
            for v in rng.integers(0, 9, 120)]
    t = pa.table({"k": pa.array(vals, type=pa.decimal128(25, 2)),
                  "v": rng.normal(0, 1, 120)})
    df = sess.create_dataframe(t, num_partitions=4)
    q = df.group_by("k").agg(fsum(col("v")).alias("s"))
    dev = q.collect(device=True).to_pandas().sort_values("k").reset_index(drop=True)
    cpu = q.collect(device=False).to_pandas().sort_values("k").reset_index(drop=True)
    assert list(dev.k) == list(cpu.k)
    assert np.allclose(dev.s, cpu.s, rtol=1e-9)
