"""api_validation tool tests (reference: api_validation/ ApiValidation.scala
signature-drift checks)."""
from spark_rapids_tpu.tools.api_validation import (KNOWN_HOST_ONLY_EXECS,
                                                   report, validate)


def test_no_violations():
    assert validate() == []


def test_report_accounts_for_every_exec():
    r = report()
    assert "violations: 0" in r
    assert "MISSING" not in r
    # a documented host-only exec appears with its reason
    assert "CpuMapInPandasExec" in r and "Python bridge" in r
    # CpuGenerateExec gained a device rule in round 3 (TpuGenerateExec)
    assert "CpuGenerateExec" in r


def test_detects_unregistered_exec():
    """A Cpu exec with no rule and no documented reason is a violation."""
    removed = KNOWN_HOST_ONLY_EXECS.pop("CpuMapInPandasExec")
    try:
        v = validate()
        assert any("CpuMapInPandasExec" in x for x in v), v
    finally:
        KNOWN_HOST_ONLY_EXECS["CpuMapInPandasExec"] = removed
