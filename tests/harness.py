"""Differential test harness (reference: SparkQueryCompareTestSuite /
integration_tests asserts.py:499 assert_gpu_and_cpu_are_equal_collect).

Runs the same DataFrame on the device path and the CPU fallback path and
asserts row-equality with float tolerance.
"""
from __future__ import annotations

import math

import numpy as np
import pyarrow as pa

__all__ = ["assert_tpu_cpu_equal", "assert_tables_equal", "data_gen"]


def _sort_table(t: pa.Table) -> pa.Table:
    if t.num_rows <= 1 or t.num_columns == 0:
        return t
    # nested columns aren't sortable; order by the scalar columns only
    keys = [(f.name, "ascending") for f in t.schema
            if not pa.types.is_nested(f.type)]
    if not keys:
        return t
    try:
        return t.sort_by(keys)
    except (pa.ArrowInvalid, pa.ArrowTypeError):
        return t


def assert_tables_equal(actual: pa.Table, expected: pa.Table,
                        ignore_order: bool = True, rel_tol: float = 1e-9):
    assert actual.column_names == expected.column_names, \
        f"column names differ: {actual.column_names} vs {expected.column_names}"
    assert actual.num_rows == expected.num_rows, \
        f"row count differs: {actual.num_rows} vs {expected.num_rows}"
    if ignore_order:
        actual = _sort_table(actual)
        expected = _sort_table(expected)
    for name in actual.column_names:
        a = actual.column(name).to_pylist()
        e = expected.column(name).to_pylist()
        for i, (av, ev) in enumerate(zip(a, e)):
            if av is None or ev is None:
                assert av is None and ev is None, \
                    f"{name}[{i}]: {av!r} vs {ev!r}"
            elif isinstance(av, float) and isinstance(ev, float):
                if math.isnan(av) or math.isnan(ev):
                    assert math.isnan(av) and math.isnan(ev), \
                        f"{name}[{i}]: {av!r} vs {ev!r}"
                else:
                    assert math.isclose(av, ev, rel_tol=rel_tol, abs_tol=1e-9), \
                        f"{name}[{i}]: {av!r} vs {ev!r}"
            else:
                assert av == ev, f"{name}[{i}]: {av!r} vs {ev!r}"


def assert_tpu_cpu_equal(df, ignore_order: bool = True, rel_tol: float = 1e-9):
    device = df.collect(device=True)
    cpu = df.collect(device=False)
    assert_tables_equal(device, cpu, ignore_order, rel_tol)
    return device


# ---------------------------------------------------------------------------
# Random data generation (reference: integration_tests data_gen.py)
# ---------------------------------------------------------------------------
def data_gen(rng, n: int, spec: dict, null_prob: float = 0.15) -> pa.Table:
    """spec: name -> one of int8,int16,int32,int64,float32,float64,bool,string,
    date,timestamp or ('int64', lo, hi) tuples."""
    cols = {}
    for name, kind in spec.items():
        lo, hi = None, None
        if isinstance(kind, tuple):
            kind, lo, hi = kind
        if kind.startswith("int"):
            bits = int(kind[3:])
            lo = lo if lo is not None else -(2 ** (bits - 2))
            hi = hi if hi is not None else 2 ** (bits - 2)
            vals = rng.integers(lo, hi, size=n, dtype=np.int64).astype(f"int{bits}")
            arr = pa.array(vals)
        elif kind == "float32" or kind == "float64":
            vals = rng.normal(0, 100, size=n)
            # sprinkle special values like the reference's generators
            special = rng.random(n)
            vals = np.where(special < 0.02, np.inf, vals)
            vals = np.where((special >= 0.02) & (special < 0.04), -np.inf, vals)
            vals = np.where((special >= 0.04) & (special < 0.06), np.nan, vals)
            vals = np.where((special >= 0.06) & (special < 0.08), -0.0, vals)
            arr = pa.array(vals.astype(kind))
        elif kind == "bool":
            arr = pa.array(rng.integers(0, 2, size=n).astype(bool))
        elif kind == "string":
            words = ["", "a", "ab", "abc", "tpu", "Spark", "RAPIDS", "xyzzy",
                     "longer string value", "ünïcode"]
            arr = pa.array([words[i] for i in rng.integers(0, len(words), size=n)])
        elif kind == "date":
            arr = pa.array(rng.integers(0, 20000, size=n).astype("int32"),
                           type=pa.int32()).cast(pa.date32())
        elif kind == "timestamp":
            arr = pa.array(rng.integers(0, 2 ** 48, size=n),
                           type=pa.int64()).cast(pa.timestamp("us"))
        else:
            raise ValueError(kind)
        if null_prob > 0:
            mask = rng.random(n) < null_prob
            arr = pa.array(arr.to_pylist(), type=arr.type,
                           mask=mask)
        cols[name] = arr
    return pa.table(cols)
