"""Adaptive query execution tests (reference: GpuOverrides.scala:4010
AQE re-entry + GpuCustomShuffleReaderExec coalesce/skew specs).

Each scenario runs the same query on the host engine and through AQE on the
device engine and compares, then asserts the specific adaptive event fired.
"""
import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.plan.aqe import AdaptiveExec
from spark_rapids_tpu.session import TpuSession

from harness import assert_tables_equal


def _session(**extra):
    conf = {
        "spark.rapids.tpu.shuffle.partitions": 6,
        "spark.rapids.tpu.shuffle.mode": "host",
    }
    conf.update(extra)
    return TpuSession(conf)


def _expected(df):
    return df.collect(device=False)


def _adaptive_plan(df):
    plan = df.session._physical(df.logical, True)
    assert isinstance(plan, AdaptiveExec), type(plan).__name__
    return plan


def _tables(sess, n_left=4000, n_right=40):
    rng = np.random.default_rng(7)
    left = pd.DataFrame({
        "k": rng.integers(0, n_right, n_left).astype(np.int64),
        "v": rng.normal(size=n_left),
    })
    right = pd.DataFrame({
        "k": np.arange(n_right, dtype=np.int64),
        "name": [f"name_{i}" for i in range(n_right)],
    })
    return (sess.create_dataframe(left, num_partitions=3),
            sess.create_dataframe(right, num_partitions=2))


# ---------------------------------------------------------------------------
# join demotion
# ---------------------------------------------------------------------------
def test_join_demotes_to_broadcast_and_strips_probe_exchange():
    sess = _session(**{
        # static planner must NOT broadcast (else AQE has nothing to do)
        "spark.rapids.tpu.autoBroadcastJoinThreshold": -1,
        "spark.rapids.tpu.aqe.autoBroadcastJoinThreshold": 10 << 20,
    })
    ldf, rdf = _tables(sess)
    q = ldf.join(rdf, on="k").select("k", "v", "name")
    expected = _expected(q)

    plan = _adaptive_plan(q)
    got = plan.collect().to_arrow()
    assert_tables_equal(got, expected)
    assert any("demoted" in e for e in plan.events), plan.events
    assert any("removed probe-side exchange" in e for e in plan.events), \
        plan.events
    final = plan.final_plan().tree_string()
    assert "BroadcastHashJoin" in final, final
    assert "ShuffledHashJoin" not in final, final


def test_join_demotion_side_swap_right_join():
    sess = _session(**{
        "spark.rapids.tpu.autoBroadcastJoinThreshold": -1,
        "spark.rapids.tpu.aqe.autoBroadcastJoinThreshold": 10 << 20,
        # keep the left side the small one -> swap path
    })
    rng = np.random.default_rng(3)
    small = pd.DataFrame({"k": np.arange(30, dtype=np.int64),
                          "s": rng.normal(size=30)})
    big = pd.DataFrame({"k": rng.integers(0, 30, 5000).astype(np.int64),
                        "v": rng.normal(size=5000)})
    sdf = sess.create_dataframe(small, num_partitions=2)
    bdf = sess.create_dataframe(big, num_partitions=3)
    q = sdf.join(bdf, on="k", how="right").select("k", "s", "v")
    expected = _expected(q)
    plan = _adaptive_plan(q)
    got = plan.collect().to_arrow()
    assert_tables_equal(got, expected)
    assert any("side swap" in e for e in plan.events), plan.events


def test_no_demotion_when_build_side_large():
    sess = _session(**{
        "spark.rapids.tpu.autoBroadcastJoinThreshold": -1,
        "spark.rapids.tpu.aqe.autoBroadcastJoinThreshold": 64,  # tiny
    })
    ldf, rdf = _tables(sess)
    q = ldf.join(rdf, on="k").select("k", "v", "name")
    expected = _expected(q)
    plan = _adaptive_plan(q)
    got = plan.collect().to_arrow()
    assert_tables_equal(got, expected)
    assert not any("demoted" in e for e in plan.events), plan.events


# ---------------------------------------------------------------------------
# partition coalescing
# ---------------------------------------------------------------------------
def test_groupby_partitions_coalesce():
    sess = _session()
    rng = np.random.default_rng(11)
    df = sess.create_dataframe(pd.DataFrame({
        "g": rng.integers(0, 50, 3000).astype(np.int64),
        "x": rng.normal(size=3000),
    }), num_partitions=4)
    from spark_rapids_tpu.expr.functions import col, sum as f_sum
    q = df.group_by("g").agg(f_sum(col("x")).alias("sx"))
    expected = _expected(q)
    plan = _adaptive_plan(q)
    got = plan.collect().to_arrow()
    assert_tables_equal(got, expected)
    assert any("coalesced" in e for e in plan.events), plan.events
    # tiny data under a 64MB advisory size -> everything merges to 1 read
    assert plan.final_plan().num_partitions == 1


def test_coalescing_respects_min_partition_num():
    sess = _session(**{
        "spark.rapids.tpu.aqe.coalescePartitions.minPartitionNum": 3,
    })
    rng = np.random.default_rng(13)
    df = sess.create_dataframe(pd.DataFrame({
        "g": rng.integers(0, 50, 3000).astype(np.int64),
        "x": rng.normal(size=3000),
    }), num_partitions=4)
    from spark_rapids_tpu.expr.functions import col, sum as f_sum
    q = df.group_by("g").agg(f_sum(col("x")).alias("sx"))
    plan = _adaptive_plan(q)
    expected = _expected(q)
    got = plan.collect().to_arrow()
    assert_tables_equal(got, expected)
    assert plan.final_plan().num_partitions >= 3


def test_join_coalescing_keeps_co_partitioning():
    sess = _session(**{
        "spark.rapids.tpu.autoBroadcastJoinThreshold": -1,
        "spark.rapids.tpu.aqe.autoBroadcastJoinThreshold": -1,  # no demotion
    })
    ldf, rdf = _tables(sess, n_left=3000, n_right=500)
    q = ldf.join(rdf, on="k").select("k", "v", "name")
    expected = _expected(q)
    plan = _adaptive_plan(q)
    got = plan.collect().to_arrow()
    assert_tables_equal(got, expected)
    assert any("coalesced join inputs" in e for e in plan.events), plan.events


# ---------------------------------------------------------------------------
# skew split
# ---------------------------------------------------------------------------
def test_skew_join_splits_oversized_partition():
    sess = _session(**{
        "spark.rapids.tpu.autoBroadcastJoinThreshold": -1,
        "spark.rapids.tpu.aqe.autoBroadcastJoinThreshold": -1,
        "spark.rapids.tpu.aqe.coalescePartitions.enabled": False,
        "spark.rapids.tpu.aqe.skewJoin.skewedPartitionThresholdBytes": 2048,
        "spark.rapids.tpu.aqe.skewJoin.skewedPartitionFactor": 2,
        "spark.rapids.tpu.aqe.advisoryPartitionSizeBytes": 2048,
    })
    rng = np.random.default_rng(5)
    # one giant key -> one skewed partition
    k = np.concatenate([np.zeros(8000, dtype=np.int64),
                        rng.integers(1, 40, 500).astype(np.int64)])
    left = pd.DataFrame({"k": k, "v": rng.normal(size=len(k))})
    right = pd.DataFrame({"k": np.arange(40, dtype=np.int64),
                          "w": rng.normal(size=40)})
    ldf = sess.create_dataframe(left, num_partitions=3)
    rdf = sess.create_dataframe(right, num_partitions=2)
    q = ldf.join(rdf, on="k").select("k", "v", "w")
    expected = _expected(q)
    plan = _adaptive_plan(q)
    got = plan.collect().to_arrow()
    assert_tables_equal(got, expected)
    assert any("skew split" in e for e in plan.events), plan.events


def test_skew_split_left_outer():
    sess = _session(**{
        "spark.rapids.tpu.autoBroadcastJoinThreshold": -1,
        "spark.rapids.tpu.aqe.autoBroadcastJoinThreshold": -1,
        "spark.rapids.tpu.aqe.coalescePartitions.enabled": False,
        "spark.rapids.tpu.aqe.skewJoin.skewedPartitionThresholdBytes": 2048,
        "spark.rapids.tpu.aqe.skewJoin.skewedPartitionFactor": 2,
        "spark.rapids.tpu.aqe.advisoryPartitionSizeBytes": 2048,
    })
    rng = np.random.default_rng(9)
    k = np.concatenate([np.zeros(6000, dtype=np.int64),
                        rng.integers(1, 60, 400).astype(np.int64)])
    left = pd.DataFrame({"k": k, "v": rng.normal(size=len(k))})
    # right side misses half the keys -> exercises unmatched-left emission
    right = pd.DataFrame({"k": np.arange(0, 60, 2, dtype=np.int64),
                          "w": rng.normal(size=30)})
    ldf = sess.create_dataframe(left, num_partitions=3)
    rdf = sess.create_dataframe(right, num_partitions=2)
    q = ldf.join(rdf, on="k", how="left").select("k", "v", "w")
    expected = _expected(q)
    plan = _adaptive_plan(q)
    got = plan.collect().to_arrow()
    assert_tables_equal(got, expected)
    assert any("skew split" in e for e in plan.events), plan.events


# ---------------------------------------------------------------------------
# toggles & integration
# ---------------------------------------------------------------------------
def test_aqe_disabled_returns_plain_plan():
    sess = _session(**{"spark.rapids.tpu.aqe.enabled": False})
    ldf, rdf = _tables(sess)
    q = ldf.join(rdf, on="k").select("k", "v", "name")
    plan = sess._physical(q.logical, True)
    assert not isinstance(plan, AdaptiveExec)


def test_aqe_on_device_stage_tier():
    """Under a mesh, stages materialize on the ICI tier and downstream device
    operators read the shards without a host bounce."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    from spark_rapids_tpu.parallel.mesh import data_parallel_mesh
    sess = TpuSession({
        "spark.rapids.tpu.shuffle.partitions": 8,
        "spark.rapids.tpu.shuffle.mode": "auto",
    })
    sess.attach_mesh(data_parallel_mesh())
    rng = np.random.default_rng(17)
    df = sess.create_dataframe(pd.DataFrame({
        "g": rng.integers(0, 30, 4000).astype(np.int64),
        "x": rng.normal(size=4000),
    }), num_partitions=2)
    from spark_rapids_tpu.expr.functions import col, sum as f_sum
    q = df.group_by("g").agg(f_sum(col("x")).alias("sx"))
    expected = _expected(q)
    plan = _adaptive_plan(q)
    got = plan.collect().to_arrow()
    assert_tables_equal(got, expected)
    final = plan.final_plan().tree_string()
    assert "TpuStageReaderExec" in final or "ShuffleStageExec" in final, final


def test_aqe_multi_stage_query():
    """groupby -> join -> sort: three exchange layers materialize in
    dependency order."""
    sess = _session(**{
        "spark.rapids.tpu.autoBroadcastJoinThreshold": -1,
    })
    ldf, rdf = _tables(sess, n_left=2500, n_right=80)
    from spark_rapids_tpu.expr.functions import col, sum as f_sum
    agg = ldf.group_by("k").agg(f_sum(col("v")).alias("sv"))
    q = agg.join(rdf, on="k").sort("sv").select("k", "sv", "name")
    expected = q.collect(device=False)
    plan = _adaptive_plan(q)
    got = plan.collect().to_arrow()
    assert_tables_equal(got, expected, ignore_order=False)
    assert sum("materialized stage" in e for e in plan.events) >= 2, \
        plan.events
