"""Version shim layer tests (reference: ShimLoader.scala per-version shim
resolution)."""
import numpy as np

import spark_rapids_tpu.shims as shims
from spark_rapids_tpu.shims import (HostLibShims, LegacyJaxShims,
                                    LegacyPandasShims, ShimVersions,
                                    detect_versions, get_shims,
                                    select_provider)


def _v(pandas=(2, 2), numpy=(1, 26), pyarrow=(15, 0), jax=(0, 4, 30)):
    return ShimVersions(pandas, numpy, pyarrow, jax)


def test_detect_and_active_shims():
    versions = detect_versions()
    assert len(versions.pandas) >= 2 and len(versions.jax) >= 2
    active = get_shims()
    assert isinstance(active, HostLibShims)
    # probed once: same instance on re-query (ShimLoader caching)
    assert get_shims() is active


def test_provider_selection_by_version():
    assert select_provider(_v()) is HostLibShims
    assert select_provider(_v(pandas=(1, 4))) is LegacyPandasShims
    assert select_provider(_v(jax=(0, 4, 20))) is LegacyJaxShims
    # first match wins: old pandas AND old jax -> pandas shim (list order)
    assert select_provider(_v(pandas=(1, 3), jax=(0, 3))) is LegacyPandasShims


def test_shim_methods_functional():
    s = get_shims()
    codes, uniques = s.factorize(np.array(["b", "a", "b"], dtype=object))
    assert codes.tolist() == [0, 1, 0]
    uniq, first, inv = s.unique_rows(np.array([[1, 2], [3, 4], [1, 2]]))
    assert inv.ndim == 1 and inv.tolist() == [0, 1, 0]
    assert not s.is_tracer(np.int32(3))
    import jax
    traced = {"seen": None}

    def probe(x):
        traced["seen"] = s.is_tracer(x)
        return x

    jax.jit(probe)(np.float32(1.0))
    assert traced["seen"] is True
    assert s.tree_map(lambda a, b: a + b, {"x": 1}, {"x": 2}) == {"x": 3}


def test_register_custom_provider():
    class QuirkShims(HostLibShims):
        shim_name = "quirk"

    shims.register_shim_provider(lambda v: v.pyarrow >= (999,), QuirkShims)
    try:
        assert select_provider(_v(pyarrow=(999, 1))) is QuirkShims
        assert select_provider(_v()) is HostLibShims
    finally:
        shims._PROVIDERS.pop(0)
        shims._ACTIVE = None
