"""Cast matrix differential tests (reference: GpuCast.scala:1513 +
CastOpSuite; device kernels in expr/cast_kernels.py)."""
import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu.expr.functions as F
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expr.functions import col
from harness import assert_tpu_cpu_equal


def _assert_col(session, table, expr, expected):
    df = session.create_dataframe(table)
    q = df.select(expr.alias("out"))
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    assert out.column("out").to_pylist() == expected, \
        out.column("out").to_pylist()
    return q


def test_int_to_string_device(session):
    t = pa.table({"i": [0, 7, -13, 123456789, None,
                        -9223372036854775808, 9223372036854775807]})
    q = _assert_col(session, t, col("i").cast(dt.STRING),
                    ["0", "7", "-13", "123456789", None,
                     "-9223372036854775808", "9223372036854775807"])
    bad = [l for l in q.explain("tpu").splitlines()
           if "!" in l and "cast" in l.lower()]
    assert not bad, bad


def test_bool_date_to_string(session):
    t = pa.table({"b": [True, False, None],
                  "d": pa.array([0, 18628, -719162], type=pa.date32())})
    _assert_col(session, t, col("b").cast(dt.STRING),
                ["true", "false", None])
    _assert_col(session, t, col("d").cast(dt.STRING),
                ["1970-01-01", "2021-01-01", "0001-01-01"])


def test_string_to_integrals(session):
    t = pa.table({"s": ["42", " -17 ", "+8", "12.9", "abc", "", None,
                        "9223372036854775807", "9223372036854775808",
                        "007", ".5", "1e3", "300"]})
    _assert_col(session, t, col("s").cast(dt.LONG),
                [42, -17, 8, 12, None, None, None,
                 9223372036854775807, None, 7, None, None, 300])
    # overflow to narrower types -> null
    _assert_col(session, t, col("s").cast(dt.BYTE),
                [42, -17, 8, 12, None, None, None, None, None, 7, None,
                 None, None])


def test_string_to_floats(session):
    t = pa.table({"s": ["3.5", "-2e3", " 1.5E-2 ", "Infinity", "-infinity",
                        "NaN", "x", "1.", ".5", "1e", "+4", None]})
    df = session.create_dataframe(t)
    q = df.select(col("s").cast(dt.DOUBLE).alias("out"))
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    got = out.column("out").to_pylist()
    assert got[:3] == [3.5, -2000.0, 0.015]
    assert got[3] == float("inf") and got[4] == float("-inf")
    assert got[5] != got[5]              # NaN
    assert got[6] is None and got[9] is None and got[11] is None
    assert got[7] == 1.0 and got[8] == 0.5 and got[10] == 4.0


def test_string_to_bool_and_date(session):
    t = pa.table({"s": ["true", "FALSE", " Y ", "0", "maybe", None]})
    _assert_col(session, t, col("s").cast(dt.BOOLEAN),
                [True, False, True, False, None, None])
    t2 = pa.table({"s": ["2021-01-01", "1970-1-1", "2020-02-29",
                         "2019-02-29", "2021", "2021-7", "2021-13-01",
                         "01-01-2021", "x", None]})
    import datetime
    _assert_col(session, t2, col("s").cast(dt.DATE),
                [datetime.date(2021, 1, 1), datetime.date(1970, 1, 1),
                 datetime.date(2020, 2, 29), None, datetime.date(2021, 1, 1),
                 datetime.date(2021, 7, 1), None, None, None, None])


def test_decimal_to_string(session):
    t = pa.table({"x": pa.array([1.20, -0.05, 0.0, 10.0])})
    df = session.create_dataframe(t)
    q = df.select(col("x").cast(dt.DecimalType(9, 2)).cast(dt.STRING)
                  .alias("out"))
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    assert out.column("out").to_pylist() == ["1.20", "-0.05", "0.00", "10.00"]


def test_roundtrip_long_string_long(session, rng):
    vals = rng.integers(-1 << 62, 1 << 62, 200)
    t = pa.table({"i": vals})
    df = session.create_dataframe(t)
    q = df.select(col("i").cast(dt.STRING).cast(dt.LONG).alias("out"))
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    assert out.column("out").to_pylist() == vals.tolist()


def test_host_only_directions_fall_back(session):
    t = pa.table({"f": [1.5, None], "s": ["2021-01-01 10:30:00", None]})
    df = session.create_dataframe(t)
    q = df.select(col("f").cast(dt.STRING).alias("f2s"),
                  col("s").cast(dt.TIMESTAMP).alias("s2t"))
    text = q.explain("tpu")
    assert "cannot run on TPU" in text, text
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    assert out.column("f2s").to_pylist() == ["1.5", None]
    import datetime
    assert out.column("s2t").to_pylist() == \
        [datetime.datetime(2021, 1, 1, 10, 30), None]


def test_float_to_int_cast_spark_semantics(session):
    """cast(double as int/long): truncate toward zero, SATURATE at the
    target range, NaN -> 0 (Scala Double.toInt semantics; raw astype is
    platform-dependent — numpy maps NaN to INT_MIN, jax to 0)."""
    t = pa.table({"v": [3.7, -3.7, float("nan"), float("inf"),
                        float("-inf"), 1e18, -1e18, 0.0]})
    df = session.create_dataframe(t)
    q = df.select(col("v").cast(dt.INT).alias("i"),
                  col("v").cast(dt.LONG).alias("l"),
                  col("v").cast(dt.SHORT).alias("sh"))
    out = assert_tpu_cpu_equal(q, ignore_order=False)
    imin, imax = -2**31, 2**31 - 1
    assert out.column("i").to_pylist() == [3, -3, 0, imax, imin, imax,
                                           imin, 0]
    lmax = 2**63 - 1
    got_l = out.column("l").to_pylist()
    assert got_l[2] == 0 and got_l[3] == lmax and got_l[4] == -2**63
    # SHORT goes through toInt then BIT-TRUNCATES (Scala Double.toShort ==
    # toInt.toShort): inf -> INT_MAX -> low 16 bits -> -1
    assert out.column("sh").to_pylist()[3] == -1
    q2 = df.select(col("v").cast(dt.SHORT).alias("sh2"))
    big = session.create_dataframe(pa.table({"v": [1e9]})) \
        .select(col("v").cast(dt.SHORT).alias("sh")).collect(device=False)
    assert big.column("sh").to_pylist() == [-13824]  # 1e9.toInt.toShort
