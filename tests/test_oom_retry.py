"""Structured OOM retry: the escalation ladder, split-and-retry, HBM
pressure arbitration, and the v9 telemetry trail (PR-14).

The contract under test (docs/fault_tolerance.md "Device OOM retry"):
a device allocation failure walks spill → retry → split-and-retry and
either recovers to exactly the unpressured answer or fails with a
structured DeviceOomError carrying the ladder's forensics; while a
retrier is engaged, new admissions park on the arbitration gate so two
concurrent pipeline tasks cannot spill each other into a mutual-OOM
livelock; every completed ladder leaves an ``oom_retry`` record in the
schema-v9 event log.
"""
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.memory import retry as retry_mod
from spark_rapids_tpu.memory.retry import (DeviceOomError, arbiter_snapshot,
                                           configure_oom_retry,
                                           drain_oom_retry_records,
                                           is_retryable_oom,
                                           oom_admission_gate,
                                           reset_retry_state, retry_stats,
                                           split_device_rows, split_host_rows,
                                           with_retry, with_retry_split)
from spark_rapids_tpu.utils import faults
from spark_rapids_tpu.utils.faults import configure_faults


@pytest.fixture(autouse=True)
def _pristine_ladder():
    """Counters, pending records and the arbiter are process-global by
    design; every test starts and ends zeroed, with the production
    defaults for the sticky oom.* config and injection off."""
    reset_retry_state()
    configure_oom_retry(RapidsConf({}))
    faults.reset_faults()
    faults.reset_recovery()
    yield
    reset_retry_state()
    configure_oom_retry(RapidsConf({}))
    faults.reset_faults()
    faults.reset_recovery()


def _fake_spill(freed):
    """Stand-in for _Ladder.spill so ladder control flow is tested
    deterministically (the real rung drains the buffer catalog)."""
    def spill(self):
        self.spilled_bytes += freed
        return freed
    return spill


class _OomAfter:
    """Callable failing with a runtime-OOM string for its first N calls."""

    def __init__(self, failures, result="ok"):
        self.failures = failures
        self.calls = 0
        self.result = result

    def __call__(self, *args):
        self.calls += 1
        if self.calls <= self.failures:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory while "
                               "allocating 1234 bytes")
        return self.result


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------
def test_is_retryable_oom_classification():
    assert is_retryable_oom(RuntimeError("RESOURCE_EXHAUSTED: oom"))
    assert is_retryable_oom(RuntimeError("XLA: Out of memory allocating"))
    # the strict-pool MemoryError from BufferCatalog.register
    assert is_retryable_oom(MemoryError(
        "strict pool mode: 999 bytes cannot fit in pool"))
    # a nested ladder's structured error is retryable at the OUTER scope
    assert is_retryable_oom(DeviceOomError("inner ladder exhausted"))
    # non-OOM runtime errors and OOM-ish strings on other types are not
    assert not is_retryable_oom(RuntimeError("shape mismatch"))
    assert not is_retryable_oom(ValueError("RESOURCE_EXHAUSTED"))
    assert not is_retryable_oom(KeyError("out of memory"))


def test_is_retryable_oom_xla_internal_alloc_variants():
    """XLA allocation failures surfacing under an INTERNAL banner are
    still OOM — they must walk the ladder (spill/retry/split), NOT the
    non-retryable host-fallback path. Pins the marker set against the
    real TPU runtime message shapes."""
    assert is_retryable_oom(RuntimeError(
        "INTERNAL: Failed to allocate 4294967296 bytes for buffer"))
    assert is_retryable_oom(RuntimeError(
        "INTERNAL: failed to allocate region of 1073741824 bytes"))
    assert is_retryable_oom(RuntimeError(
        "Out of memory allocating 123456 bytes (allocated so far: 0)"))
    # a bare INTERNAL with no allocation marker is a real XLA bug, not
    # memory pressure — non-retryable (host-fallback territory)
    assert not is_retryable_oom(RuntimeError(
        "INTERNAL: during context [hlo verifier]: mismatched shapes"))
    # exec/fallback.py's classifier must agree: alloc-INTERNAL walks the
    # ladder; bare INTERNAL classifies for host fallback
    from spark_rapids_tpu.exec.fallback import classify_failure
    assert classify_failure(RuntimeError(
        "INTERNAL: unexpected HLO pass failure")) == "xla_internal"
    assert classify_failure(RuntimeError(
        "INVALID_ARGUMENT: buffer donated twice")) == "xla_invalid_argument"
    assert classify_failure(
        DeviceOomError("ladder exhausted")) == "oom_exhausted"


# ---------------------------------------------------------------------------
# spill-and-retry rung (with_retry)
# ---------------------------------------------------------------------------
def test_with_retry_recovers_after_spill(monkeypatch):
    monkeypatch.setattr(retry_mod._Ladder, "spill", _fake_spill(1024))
    fn = _OomAfter(2)
    assert with_retry(fn, scope="jit") == "ok"
    assert fn.calls == 3
    s = retry_stats()
    assert s["oom_retries"] == 2
    assert s["oom_recoveries"] == 1 and s["oom_failures"] == 0
    (rec,) = drain_oom_retry_records()
    assert rec["scope"] == "jit" and rec["outcome"] == "recovered"
    assert rec["attempts"] == 2 and rec["splits"] == 0


def test_with_retry_exhaustion_is_structured(monkeypatch):
    monkeypatch.setattr(retry_mod._Ladder, "spill", _fake_spill(512))
    fn = _OomAfter(99)
    with pytest.raises(DeviceOomError) as ei:
        with_retry(fn, scope="join-build", context="hash build",
                   max_retries=2)
    e = ei.value
    # forensics: 1 initial + 2 retries, all bytes the ladder spilled
    assert e.scope == "join-build"
    assert e.attempts == 3 and e.splits == 0
    assert e.spilled_bytes == 3 * 512
    assert "survived the retry ladder" in str(e)
    s = retry_stats()
    assert s["oom_failures"] == 1 and s["oom_recoveries"] == 0
    (rec,) = drain_oom_retry_records()
    assert rec["outcome"] == "failed"


def test_with_retry_zero_byte_spill_fails_fast(monkeypatch):
    """Retrying identical work after a spill that freed nothing cannot
    succeed — the ladder must not burn its retry budget spinning."""
    monkeypatch.setattr(retry_mod._Ladder, "spill", _fake_spill(0))
    fn = _OomAfter(99)
    with pytest.raises(DeviceOomError):
        with_retry(fn, scope="jit")
    assert fn.calls == 1
    assert retry_stats()["oom_retries"] == 0


def test_with_retry_non_oom_passes_through(monkeypatch):
    monkeypatch.setattr(retry_mod._Ladder, "spill", _fake_spill(1024))

    def boom():
        raise ValueError("not an OOM")

    with pytest.raises(ValueError, match="not an OOM"):
        with_retry(boom, scope="jit")
    s = retry_stats()
    assert s["oom_retries"] == 0 and s["oom_failures"] == 0
    assert drain_oom_retry_records() == []


# ---------------------------------------------------------------------------
# split-and-retry rung (with_retry_split)
# ---------------------------------------------------------------------------
def _list_splitter(batch):
    if len(batch) <= 1:
        return None
    half = len(batch) // 2
    return batch[:half], batch[half:]


def _list_combine(outs):
    return [x for o in outs for x in o]


def test_split_ladder_recovers_and_preserves_order(monkeypatch):
    """A batch too big for the device is halved (recursively) and the
    half-results recombine to exactly the unsplit answer."""
    monkeypatch.setattr(retry_mod._Ladder, "spill", _fake_spill(0))
    ran = []

    def fn(batch):
        if len(batch) > 2:
            raise RuntimeError("RESOURCE_EXHAUSTED: batch too big")
        ran.append(list(batch))
        return [x * 10 for x in batch]

    batch = list(range(8))
    out = with_retry_split(fn, batch, splitter=_list_splitter,
                           combiner=_list_combine, scope="project")
    assert out == [x * 10 for x in batch]
    assert ran == [[0, 1], [2, 3], [4, 5], [6, 7]]
    # 8 -> 4+4 (1 split), each 4 -> 2+2 (2 more); the budget is scoped
    # to the whole ladder, not per recursion level
    s = retry_stats()
    assert s["oom_splits"] == 3 and s["oom_recoveries"] == 1
    (rec,) = drain_oom_retry_records()
    assert rec["splits"] == 3 and rec["outcome"] == "recovered"


def test_split_budget_is_bounded(monkeypatch):
    monkeypatch.setattr(retry_mod._Ladder, "spill", _fake_spill(0))

    def fn(batch):
        raise RuntimeError("RESOURCE_EXHAUSTED: never fits")

    with pytest.raises(DeviceOomError) as ei:
        with_retry_split(fn, list(range(64)), splitter=_list_splitter,
                         combiner=_list_combine, scope="sort", max_splits=1)
    assert ei.value.splits == 1
    assert retry_stats()["oom_splits"] == 1


def test_split_without_splitter_is_spill_only(monkeypatch):
    monkeypatch.setattr(retry_mod._Ladder, "spill", _fake_spill(0))
    fn = _OomAfter(99)
    with pytest.raises(DeviceOomError) as ei:
        with_retry_split(fn, [1, 2, 3], splitter=None, scope="agg-merge")
    assert ei.value.splits == 0


def test_nested_ladder_escalates_straight_to_split(monkeypatch):
    """A DeviceOomError from an inner (jit-level) ladder must not be
    plain-retried by the outer scope — the inner ladder already
    exhausted its retries, so the outer escalates straight to split."""
    monkeypatch.setattr(retry_mod._Ladder, "spill", _fake_spill(4096))
    inner_calls = []

    def fn(batch):
        inner_calls.append(len(batch))
        if len(batch) > 2:
            # what wrap_jit raises after ITS retries are spent
            raise DeviceOomError("inner jit ladder exhausted", scope="jit")
        return list(batch)

    out = with_retry_split(fn, [1, 2, 3, 4], splitter=_list_splitter,
                           combiner=_list_combine, scope="wholestage")
    assert out == [1, 2, 3, 4]
    # 4-row batch tried once, then split; no identical-work plain retry
    assert inner_calls == [4, 2, 2]
    assert retry_stats()["oom_retries"] == 0
    assert retry_stats()["oom_splits"] == 1


# ---------------------------------------------------------------------------
# splitters: real device/host tables round-trip
# ---------------------------------------------------------------------------
def test_split_device_rows_roundtrip(session):
    from spark_rapids_tpu.columnar.device import DeviceTable
    from spark_rapids_tpu.columnar.host import HostTable
    t = pa.table({"a": pa.array(np.arange(12), type=pa.int64()),
                  "b": pa.array(np.arange(12) * 0.5, type=pa.float64())})
    dev = DeviceTable.from_host(HostTable.from_arrow(t), 8)
    halves = split_device_rows(dev)
    assert halves is not None and len(halves) == 2
    back = retry_mod._concat_combine(list(halves))
    got = back.to_host().to_arrow()
    assert got.sort_by("a").equals(t.sort_by("a"))


def test_split_device_rows_refuses_capacity_one(session):
    from spark_rapids_tpu.columnar.device import DeviceTable
    from spark_rapids_tpu.columnar.host import HostTable
    t = pa.table({"a": pa.array([7], type=pa.int64())})
    dev = DeviceTable.from_host(HostTable.from_arrow(t), 1)
    assert dev.capacity == 1
    assert split_device_rows(dev) is None


def test_split_host_rows_roundtrip():
    from spark_rapids_tpu.columnar.host import HostTable
    t = pa.table({"a": pa.array(np.arange(11), type=pa.int64())})
    ht = HostTable.from_arrow(t)
    a, b = split_host_rows(ht)
    assert a.num_rows + b.num_rows == 11
    got = pa.concat_tables([a.to_arrow(), b.to_arrow()])
    assert got.equals(t)
    single = HostTable.from_arrow(t.slice(0, 1))
    assert split_host_rows(single) is None


# ---------------------------------------------------------------------------
# HBM pressure arbitration
# ---------------------------------------------------------------------------
def test_admission_gate_parks_until_retriers_disengage():
    configure_oom_retry(RapidsConf(
        {"spark.rapids.tpu.oom.arbitration.maxWaitSeconds": "10"}))
    retry_mod._ARBITER.engage()
    try:
        assert retry_mod._GATE_ACTIVE
        assert arbiter_snapshot()["gate_active"]
        waited = {}

        def admit():
            t0 = time.monotonic()
            oom_admission_gate()
            waited["s"] = time.monotonic() - t0

        t = threading.Thread(target=admit, daemon=True)
        t.start()
        time.sleep(0.4)
        assert t.is_alive(), "admission should park while a retrier is engaged"
    finally:
        retry_mod._ARBITER.disengage()
    t.join(5)
    assert not t.is_alive() and waited["s"] >= 0.3
    assert not retry_mod._GATE_ACTIVE
    assert retry_stats()["gate_waits"] == 1


def test_admission_gate_is_a_pressure_valve_not_a_lock():
    """A wedged retrier must not deadlock the task pool: the gate wait
    is bounded by oom.arbitration.maxWaitSeconds."""
    configure_oom_retry(RapidsConf(
        {"spark.rapids.tpu.oom.arbitration.maxWaitSeconds": "0.3"}))
    retry_mod._ARBITER.engage()
    try:
        t = threading.Thread(target=oom_admission_gate, daemon=True)
        t.start()
        t.join(5)
        assert not t.is_alive(), "bounded gate wait must return"
    finally:
        retry_mod._ARBITER.disengage()


def test_retrier_never_gates_itself():
    retry_mod._ARBITER.engage()
    try:
        t0 = time.monotonic()
        oom_admission_gate()  # this thread IS the retrier
        assert time.monotonic() - t0 < 0.1
    finally:
        retry_mod._ARBITER.disengage()


def test_gate_is_zero_overhead_when_idle():
    assert not retry_mod._GATE_ACTIVE
    t0 = time.monotonic()
    for _ in range(10_000):
        oom_admission_gate()
    assert time.monotonic() - t0 < 0.5
    assert retry_stats()["gate_waits"] == 0


def test_concurrent_retriers_no_mutual_oom_livelock(monkeypatch):
    """Acceptance pin: two pipeline tasks whose batches fit HBM alone
    but not together. Both first attempts overlap and OOM; arbitration
    serializes the retries on the exclusive token so each retry runs
    with the (fake) HBM to itself and BOTH recover — no livelock where
    each retry is re-failed by the other's resident batch."""
    monkeypatch.setattr(retry_mod._Ladder, "spill", _fake_spill(1))
    cap, state, lk = 100, {"used": 0}, threading.Lock()
    barrier = threading.Barrier(2)

    def make_task():
        st = {"first": True}

        def fn():
            if st["first"]:
                st["first"] = False
                barrier.wait(timeout=10)
                with lk:
                    state["used"] += 60
                barrier.wait(timeout=10)  # both resident: 120 > cap
                with lk:
                    state["used"] -= 60
                # both must have rolled back before either retries, or a
                # fast thread's retry races the peer's dying first attempt
                barrier.wait(timeout=10)
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: concurrent batches exceed HBM")
            with lk:
                state["used"] += 60
                over = state["used"] > cap
            if over:
                with lk:
                    state["used"] -= 60
                raise RuntimeError("RESOURCE_EXHAUSTED: still contended")
            time.sleep(0.02)  # hold while a non-serialized peer would retry
            with lk:
                state["used"] -= 60
            return "ok"
        return fn

    results = {}

    def run(key):
        try:
            results[key] = with_retry(make_task(), scope=f"pipeline-{key}",
                                      max_retries=3)
        except BaseException as e:  # pragma: no cover - failure forensics
            results[key] = e

    threads = [threading.Thread(target=run, args=(k,), daemon=True)
               for k in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(15)
    assert not any(t.is_alive() for t in threads), "mutual-OOM livelock"
    assert results == {"a": "ok", "b": "ok"}
    s = retry_stats()
    assert s["arbitrations"] == 2 and s["oom_recoveries"] == 2
    # both ladders closed: gate down, token released, no retrier leaked
    snap = arbiter_snapshot()
    assert snap == {"active_retriers": 0, "gate_active": False,
                    "token_held": False}
    assert not retry_mod._GATE_ACTIVE


def test_arbitration_disabled_never_engages(monkeypatch):
    configure_oom_retry(RapidsConf(
        {"spark.rapids.tpu.oom.arbitration.enabled": "false"}))
    monkeypatch.setattr(retry_mod._Ladder, "spill", _fake_spill(1024))
    assert with_retry(_OomAfter(1), scope="jit") == "ok"
    assert retry_stats()["arbitrations"] == 0
    assert not retry_mod._GATE_ACTIVE


# ---------------------------------------------------------------------------
# TPC-H parity under injected device OOM (action=oom)
# ---------------------------------------------------------------------------
def _oom_spec(spec):
    return RapidsConf({"spark.rapids.tpu.faults.enabled": "true",
                       "spark.rapids.tpu.faults.seed": "7",
                       "spark.rapids.tpu.faults.spec": spec})


# q3 (the join shape, ~13s of compile) runs in the slow tier; the
# ladder recovery under test is shape-independent and q1/q6 stay tier-1
@pytest.mark.parametrize(
    "query", ["q1", pytest.param("q3", marks=pytest.mark.slow), "q6"])
def test_tpch_parity_under_injected_oom(session, query):
    """Acceptance pin: a query whose jit dispatches OOM (injected
    alloc.jit, action=oom) recovers through the ladder to exactly the
    uninjected answer, and the recovery ledger proves the ladder ran."""
    from spark_rapids_tpu.tools import tpch
    tables = tpch.gen_all(0, tiny=True)
    dfs = tpch.build_dataframes(session, tables, num_partitions=2)
    q = getattr(tpch, query)(dfs)
    ref = q.collect(device=True)

    configure_faults(_oom_spec("alloc.jit:after=1:times=2:action=oom"))
    got = q.collect(device=True)
    faults.reset_faults()

    assert got.num_rows == ref.num_rows
    for name in ref.column_names:
        g, r = got.column(name).to_pylist(), ref.column(name).to_pylist()
        if ref.column(name).type in (pa.float64(), pa.float32()):
            np.testing.assert_allclose(np.array(g, dtype=float),
                                       np.array(r, dtype=float), rtol=1e-9)
        else:
            assert g == r
    s = retry_stats()
    assert s["oom_retries"] + s["oom_splits"] >= 1
    led = faults.recovery_counters()
    assert led.get("oom_retries", 0) + led.get("oom_splits", 0) >= 1


def test_upload_oom_splits_host_batch(session):
    """alloc.upload pressure on the H2D path: the upload scope splits
    the HOST batch (halving the transfer's device footprint) and the
    query still reaches the right answer."""
    from spark_rapids_tpu.tools import tpch
    tables = tpch.gen_all(0, tiny=True)
    dfs = tpch.build_dataframes(session, tables, num_partitions=2)
    q = getattr(tpch, "q6")(dfs)
    ref = q.collect(device=True)

    configure_faults(_oom_spec("alloc.upload:times=3:action=oom"))
    # fresh dataframes: the first run's uploads are cached, and a cache
    # hit never reaches the H2D fault point
    dfs = tpch.build_dataframes(session, tables, num_partitions=2)
    got = getattr(tpch, "q6")(dfs).collect(device=True)
    faults.reset_faults()
    np.testing.assert_allclose(got.column("revenue").to_numpy(),
                               ref.column("revenue").to_numpy(), rtol=1e-9)
    s = retry_stats()
    assert s["oom_retries"] + s["oom_splits"] >= 1


# ---------------------------------------------------------------------------
# v9 event log: oom_retry records, health check, diagnose
# ---------------------------------------------------------------------------
class _Plan:
    children = ()

    def tree_string(self):
        return "plan"

    def release_spill_handles(self):
        pass


def test_eventlog_v9_oom_retry_records(tmp_path, monkeypatch):
    from spark_rapids_tpu.tools.eventlog import (RECORD_TYPES,
                                                 SCHEMA_VERSION,
                                                 EventLogWriter,
                                                 load_event_log)
    assert SCHEMA_VERSION == 12 and RECORD_TYPES["oom_retry"] == 9
    monkeypatch.setattr(retry_mod._Ladder, "spill", _fake_spill(2048))

    w = EventLogWriter(str(tmp_path), "app-oom", {})
    w.run_query(_Plan(), lambda: with_retry(_OomAfter(1), scope="jit",
                                            context="q1 wholestage"))

    # error path: the ladder trail is persisted before the raise
    def exhausted():
        return with_retry(_OomAfter(99), scope="join-build", max_retries=1)

    with pytest.raises(DeviceOomError):
        w.run_query(_Plan(), exhausted)
    w.close()

    app = load_event_log(w.path)
    assert app.schema_version == 12
    (rec,) = app.query(1).oom_retries
    assert rec["event"] == "oom_retry" and rec["query_id"] == 1
    # the full v9 record shape — renaming any of these is a schema break
    for key in ("ts", "scope", "context", "attempts", "splits",
                "rematerializations", "spilled_bytes", "outcome"):
        assert key in rec, f"v9 oom_retry record lost key {key!r}"
    assert rec["scope"] == "jit" and rec["outcome"] == "recovered"
    assert rec["attempts"] == 1 and rec["spilled_bytes"] == 2048
    q2 = app.query(2)
    assert q2.error is not None
    (rec2,) = q2.oom_retries
    assert rec2["outcome"] == "failed" and rec2["scope"] == "join-build"


def test_health_check_flags_split_storms(tmp_path, monkeypatch):
    from spark_rapids_tpu.tools.diagnose import diagnose_path
    from spark_rapids_tpu.tools.eventlog import (EventLogWriter,
                                                 load_event_log)
    monkeypatch.setattr(retry_mod._Ladder, "spill", _fake_spill(0))

    def storm():
        def fn(batch):
            if len(batch) > 2:
                raise RuntimeError("RESOURCE_EXHAUSTED: storm")
            return batch
        # 8 rows at a 2-row ceiling: 3 splits, inside the default budget
        # of 4 and over the health checker's storm threshold of 2
        return with_retry_split(fn, list(range(8)),
                                splitter=_list_splitter,
                                combiner=_list_combine, scope="project")

    w = EventLogWriter(str(tmp_path), "app-storm", {})
    w.run_query(_Plan(), storm)
    w.close()
    app = load_event_log(w.path)
    warnings = app.health_check()
    assert any("split storm" in s and "batchSizeBytes" in s
               for s in warnings), warnings
    # diagnose ranks the same signal as a finding with a conf suggestion
    rep = diagnose_path(w.path)
    metrics = [f.metric for q in rep.queries for f in q.findings]
    assert "oomSplitStorm" in metrics


def test_single_recovered_retry_is_not_a_health_warning(tmp_path,
                                                        monkeypatch):
    """One spill-and-retry that recovered is the ladder doing its job —
    health_check stays quiet (split storms are the pathology)."""
    from spark_rapids_tpu.tools.eventlog import (EventLogWriter,
                                                 load_event_log)
    monkeypatch.setattr(retry_mod._Ladder, "spill", _fake_spill(1024))
    w = EventLogWriter(str(tmp_path), "app-quiet", {})
    w.run_query(_Plan(), lambda: with_retry(_OomAfter(1), scope="jit"))
    w.close()
    app = load_event_log(w.path)
    assert not any("OOM" in s for s in app.health_check())


# ---------------------------------------------------------------------------
# stats registry + leak gates
# ---------------------------------------------------------------------------
def test_retry_stats_feed_metrics_endpoint():
    s = retry_stats()
    for key in ("oom_retries", "oom_splits", "oom_rematerializations",
                "oom_recoveries", "oom_failures", "oom_spilled_bytes",
                "arbitrations", "gate_waits", "active_retriers",
                "gate_active"):
        assert key in s


def test_no_leaked_threads_or_arbiter_state():
    """The ladder spawns no threads and every exit path disengages the
    arbiter — a leaked retrier would gate all future admissions for
    maxWaitSeconds each."""
    from spark_rapids_tpu.parallel.pipeline import active_workers
    before = {t.ident for t in threading.enumerate()}
    with pytest.raises(DeviceOomError):
        with_retry(_OomAfter(99), scope="jit")  # real spill: frees 0
    assert with_retry(lambda: 1, scope="jit") == 1
    after = {t.ident for t in threading.enumerate()}
    assert after <= before
    assert active_workers() == 0
    snap = arbiter_snapshot()
    assert snap["active_retriers"] == 0 and not snap["gate_active"]
    assert not retry_mod._GATE_ACTIVE
