"""Expression differential tests: device vs CPU over random typed data
(reference analogues: arithmetic/predicate/conditional op integration tests)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expr.functions import (coalesce, col, lit, when, sqrt,
                                             abs as fabs, round as fround,
                                             floor, ceil, pow as fpow)
from harness import assert_tpu_cpu_equal, data_gen


@pytest.fixture
def df(session, rng):
    t = data_gen(rng, 200, {
        "i32": "int32", "i64": "int64", "f64": "float64", "f32": "float32",
        "b": "bool", "s": "string",
    })
    return session.create_dataframe(t)


def test_arithmetic(df):
    assert_tpu_cpu_equal(df.select(
        (col("i32") + col("i64")).alias("add"),
        (col("i32") - lit(7)).alias("sub"),
        (col("i64") * col("i32")).alias("mul"),
        (-col("i32")).alias("neg"),
        fabs(col("i32")).alias("abs"),
    ))


def test_division_and_remainder(df):
    assert_tpu_cpu_equal(df.select(
        (col("f64") / col("i32")).alias("div"),
        (col("i64") / lit(0)).alias("div0"),
        (col("i32") % lit(7)).alias("mod"),
        (col("i32") // lit(3)).alias("intdiv"),
    ))


def test_comparisons(df):
    assert_tpu_cpu_equal(df.select(
        (col("i32") > lit(0)).alias("gt"),
        (col("i32") <= col("i64")).alias("le"),
        (col("f64") == col("f64")).alias("eq"),
        col("i32").eq_null_safe(col("i64")).alias("nseq"),
        (col("s") == lit("tpu")).alias("streq"),
        (col("s") < lit("b")).alias("strlt"),
    ))


def test_boolean_logic_kleene(df):
    a = col("i32") > lit(0)
    b = col("f64") > lit(0.0)
    assert_tpu_cpu_equal(df.select(
        (a & b).alias("and"), (a | b).alias("or"), (~a).alias("not"),
    ))


def test_null_predicates(df):
    assert_tpu_cpu_equal(df.select(
        col("i32").is_null().alias("isn"),
        col("s").is_not_null().alias("nn"),
        col("f64").is_nan().alias("nan"),
    ))


def test_conditional(df):
    assert_tpu_cpu_equal(df.select(
        when(col("i32") > 0, col("i64")).otherwise(lit(-1)).alias("w"),
        when(col("b"), lit(1)).when(col("i32") > 10, lit(2)).otherwise(lit(3))
            .alias("case"),
        coalesce(col("i32"), col("i64"), lit(0)).alias("coal"),
    ))


def test_in_and_between(df):
    assert_tpu_cpu_equal(df.select(
        col("i32").isin(1, 2, 3, 100).alias("in"),
        col("i32").between(-10, 10).alias("btw"),
    ))


def test_math(df):
    assert_tpu_cpu_equal(df.select(
        sqrt(fabs(col("f64"))).alias("sqrt"),
        floor(col("f64")).alias("fl"),
        ceil(col("f64")).alias("ce"),
        fround(col("f64"), 2).alias("rnd"),
        fpow(col("f32"), lit(2.0)).alias("pw"),
    ), rel_tol=1e-6)


def test_casts(df):
    assert_tpu_cpu_equal(df.select(
        col("i32").cast(dt.LONG).alias("to_long"),
        col("i64").cast(dt.INT).alias("to_int"),
        col("f64").cast(dt.FLOAT).alias("to_f32"),
        col("i32").cast(dt.DOUBLE).alias("to_f64"),
        col("b").cast(dt.INT).alias("b_int"),
        col("i32").cast(dt.BOOLEAN).alias("i_bool"),
    ))
