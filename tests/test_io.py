"""IO format tests (reference analogues: csv_test.py, json_test.py,
orc_test.py, parquet_write_test.py in integration_tests/)."""
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.expr.functions import col, lit, sum as fsum
from harness import assert_tables_equal, assert_tpu_cpu_equal, data_gen


@pytest.fixture
def table(rng):
    return data_gen(rng, 300, {"k": ("int32", 0, 5), "i": "int64",
                               "f": "float64", "s": "string"})


def test_parquet_roundtrip(session, table, tmp_path):
    df = session.create_dataframe(table)
    df.write_parquet(str(tmp_path / "out"))
    assert os.path.exists(tmp_path / "out" / "_SUCCESS")
    back = session.read_parquet(str(tmp_path / "out"))
    assert_tables_equal(back.collect(), table.select(back.columns))


def test_parquet_partitioned_write(session, table, tmp_path):
    df = session.create_dataframe(table)
    from spark_rapids_tpu.io.writer import write_parquet
    stats = write_parquet(df, str(tmp_path / "p"), partition_by=["k"])
    assert stats.num_rows == 300
    assert len(stats.partitions) >= 2
    dirs = [d for d in os.listdir(tmp_path / "p") if d.startswith("k=")]
    assert len(dirs) == len(stats.partitions)
    # read one partition dir back
    one = session.read_parquet(str(tmp_path / "p" / dirs[0]))
    assert "i" in one.columns and "k" not in one.columns


def test_parquet_query_multifile(session, table, tmp_path):
    os.makedirs(tmp_path / "mf")
    pq.write_table(table.slice(0, 100), tmp_path / "mf" / "a.parquet")
    pq.write_table(table.slice(100), tmp_path / "mf" / "b.parquet")
    df = session.read_parquet(str(tmp_path / "mf"))
    q = df.filter(col("i") > lit(0)).group_by("k").agg(
        fsum(col("f")).alias("sf"))
    assert_tpu_cpu_equal(q, rel_tol=1e-6)


@pytest.mark.parametrize("reader", ["PERFILE", "COALESCING", "MULTITHREADED"])
def test_parquet_reader_types(session, table, tmp_path, reader):
    os.makedirs(tmp_path / "rt")
    for i in range(4):
        pq.write_table(table.slice(i * 75, 75), tmp_path / "rt" / f"{i}.parquet")
    s2 = type(session)(session.conf.set(
        "spark.rapids.sql.format.parquet.reader.type", reader))
    df = s2.read_parquet(str(tmp_path / "rt"))
    out = df.collect()
    assert out.num_rows == 300


def test_csv_roundtrip(session, tmp_path):
    t = pa.table({"a": [1, 2, 3], "b": [1.5, 2.5, None], "s": ["x", "y", "z"]})
    df = session.create_dataframe(t)
    df.write_csv(str(tmp_path / "c"))
    back = session.read_csv(str(tmp_path / "c") + "/*.csv")
    out = back.collect()
    assert out.column("a").to_pylist() == [1, 2, 3]
    assert out.column("b").to_pylist() == [1.5, 2.5, None]


def test_orc_roundtrip(session, table, tmp_path):
    df = session.create_dataframe(table)
    df.write_orc(str(tmp_path / "o"))
    back = session.read_orc(str(tmp_path / "o") + "/*.orc")
    assert_tables_equal(back.collect(), table.select(back.columns))


def test_json_read(session, tmp_path):
    path = tmp_path / "j.jsonl"
    with open(path, "w") as f:
        f.write('{"a": 1, "b": "x"}\n{"a": 2, "b": null}\n')
    df = session.read_json(str(path))
    out = df.collect()
    assert out.column("a").to_pylist() == [1, 2]
    assert out.column("b").to_pylist() == ["x", None]


def test_write_mode_error_and_overwrite(session, table, tmp_path):
    df = session.create_dataframe(table)
    df.write_parquet(str(tmp_path / "m"))
    with pytest.raises(FileExistsError):
        df.write_parquet(str(tmp_path / "m"))
    from spark_rapids_tpu.io.writer import write_parquet
    stats = write_parquet(df, str(tmp_path / "m"), mode="overwrite")
    assert stats.num_rows == 300


def test_device_parquet_write_roundtrip(session, tmp_path):
    """Device write path (round-2 missing #7; reference:
    GpuParquetFileFormat.scala:351): device packs dense column chunks,
    host assembles PLAIN v1 pages + thrift framing; pyarrow reads the
    file back bit-identical, incl. nulls/strings/dates/timestamps."""
    import numpy as np
    import pyarrow.parquet as pq
    rng = np.random.default_rng(4)
    n = 3000
    mask = rng.random(n) < 0.2
    t = pa.table({
        "i": pa.array(rng.integers(-2**40, 2**40, n), type=pa.int64(),
                      mask=mask),
        "f": pa.array(rng.normal(size=n)),
        "b": pa.array(rng.integers(0, 2, n).astype(bool)),
        "s": pa.array([None if m else f"v{rng.integers(0, 10**6)}"
                       for m in mask]),
        "d": pa.array(rng.integers(0, 20000, n).astype(np.int32)).cast(
            pa.date32()),
        "ts": pa.array(rng.integers(0, 2**48, n), type=pa.int64()).cast(
            pa.timestamp("us")),
    })
    df = session.create_dataframe(t, num_partitions=2)
    out = str(tmp_path / "devwrite")
    df.write_parquet(out)
    back = pq.read_table(out).combine_chunks()
    # written across partitions: compare as multisets keyed by row tuple
    def rows(tab):
        return sorted(zip(*[tab.column(c).to_pylist()
                            for c in t.column_names]),
                      key=lambda r: (str(r),))
    assert rows(back) == rows(t)
    import os
    assert os.path.exists(os.path.join(out, "_SUCCESS"))
    # the device writer ran (files carry its created_by marker)
    one = [f for f in os.listdir(out) if f.endswith(".parquet")][0]
    meta = pq.ParquetFile(os.path.join(out, one)).metadata
    assert b"device writer" in meta.created_by.encode() or \
        "device writer" in meta.created_by


def test_device_write_falls_back_for_unsupported_schema(session, tmp_path):
    """Decimal columns stay on the pyarrow writer (and stay correct)."""
    import decimal
    t = pa.table({"x": pa.array([decimal.Decimal("1.23"),
                                 decimal.Decimal("4.56")],
                                type=pa.decimal128(10, 2))})
    df = session.create_dataframe(t)
    out = str(tmp_path / "fallback")
    from spark_rapids_tpu.io.writer import write_parquet
    write_parquet(df, out)
    import pyarrow.parquet as pq
    back = pq.read_table(out)
    assert back.column("x").to_pylist() == t.column("x").to_pylist()
    import os
    one = [f for f in os.listdir(out) if f.endswith(".parquet")][0]
    meta = pq.ParquetFile(os.path.join(out, one)).metadata
    assert "device writer" not in (meta.created_by or "")


# ---------------------------------------------------------------------------
# device CSV decode (round-4 VERDICT item 7; reference:
# GpuTextBasedPartitionReader.scala:44)
# ---------------------------------------------------------------------------

def _write_csv(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def test_csv_device_decode_differential(session, tmp_path):
    p = _write_csv(tmp_path, "t.csv",
                   "a,b,c,d,e\n"
                   "1,2.5,true,2021-03-04,hello\n"
                   "-7,NaN,false,2021-12-31,\n"
                   ",,,,x\n"
                   "999999999999,-1e3,TRUE,2021-01-02,world\n")
    df = session.read_csv(p)
    ex = df.explain("tpu")
    assert "CpuScanExec will run on TPU" in ex, ex
    dev = df.collect(device=True).to_pylist()
    cpu = df.collect(device=False).to_pylist()
    assert str(dev) == str(cpu)
    assert dev[1]["b"] is None          # 'NaN' is a pyarrow null token
    assert dev[0]["a"] == 1 and dev[0]["c"] is True
    assert str(dev[0]["d"]) == "2021-03-04"


def test_csv_device_decode_downstream_agg(session, tmp_path):
    import spark_rapids_tpu.expr.functions as F
    from spark_rapids_tpu.expr.functions import col, lit
    rows = "\n".join(f"{i%5},{i*1.5},k{i%3}" for i in range(500))
    p = _write_csv(tmp_path, "big.csv", "k,v,s\n" + rows + "\n")
    df = session.read_csv(p)
    q = df.filter(col("k") > lit(0)) \
        .group_by("s").agg(F.sum(col("v")).alias("sv"))
    dev = sorted(map(str, q.collect(device=True).to_pylist()))
    cpu = sorted(map(str, q.collect(device=False).to_pylist()))
    assert dev == cpu


def test_csv_quoted_falls_back(session, tmp_path):
    p = _write_csv(tmp_path, "q.csv",
                   'a,b\n1,"x,y"\n2,plain\n')
    df = session.read_csv(p)
    ex = df.explain("tpu")
    assert "quoted fields" in ex, ex
    dev = df.collect(device=True).to_pylist()
    cpu = df.collect(device=False).to_pylist()
    assert str(dev) == str(cpu)
    assert dev[0]["b"] == "x,y"


def test_csv_device_decode_disable_conf(tmp_path):
    import jax
    jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.session import TpuSession
    p = _write_csv(tmp_path, "c.csv", "a\n1\n2\n")
    sess = TpuSession({"spark.rapids.tpu.csv.deviceDecode.enabled": False,
                       "spark.rapids.tpu.batchRowsMinBucket": 64})
    df = sess.read_csv(p)
    ex = df.explain("tpu")
    assert "device csv decode disabled" in ex, ex
    assert df.collect(device=True).column("a").to_pylist() == [1, 2]


def test_csv_quotes_in_second_file_fall_back_per_file(session, tmp_path):
    """The tag-time quote sniff only sees the first file's head; a quoted
    field in a LATER file must still parse correctly (per-file host
    fallback inside the device scan)."""
    _write_csv(tmp_path, "a_plain.csv", "a,b\n1,x\n2,y\n")
    _write_csv(tmp_path, "b_quoted.csv", 'a,b\n3,"p,q"\n4,z\n')
    df = session.read_csv(str(tmp_path))
    dev = sorted(map(str, df.collect(device=True).to_pylist()))
    cpu = sorted(map(str, df.collect(device=False).to_pylist()))
    assert dev == cpu
    assert any("p,q" in r for r in dev)


def test_orc_reader_strategies(session, tmp_path):
    """PERFILE (stripe-at-a-time) / MULTITHREADED / COALESCING all return
    identical rows (round-4 VERDICT items 5-6; reference:
    GpuOrcScanBase.scala readers, GpuMultiFileReader.scala:126)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import pyarrow as pa
    import pyarrow.orc as paorc
    from spark_rapids_tpu.session import TpuSession
    for i in range(3):
        paorc.write_table(
            pa.table({"a": list(range(i * 10, i * 10 + 10)),
                      "b": [float(x) for x in range(10)]}),
            str(tmp_path / f"f{i}.orc"))
    expected = None
    for rt in ("PERFILE", "MULTITHREADED", "COALESCING"):
        sess = TpuSession({"spark.rapids.sql.format.orc.reader.type": rt,
                           "spark.rapids.tpu.batchRowsMinBucket": 64})
        df = sess.read_orc(str(tmp_path))
        got = sorted(df.collect(device=False).column("a").to_pylist())
        if expected is None:
            expected = got
        assert got == expected == sorted(range(30)), (rt, got)


def test_csv_reader_strategies(session, tmp_path):
    import jax
    jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.session import TpuSession
    for i in range(3):
        (tmp_path / f"f{i}.csv").write_text(
            "a\n" + "\n".join(str(x) for x in range(i * 5, i * 5 + 5)) + "\n")
    for rt in ("PERFILE", "MULTITHREADED", "COALESCING"):
        sess = TpuSession({"spark.rapids.sql.format.csv.reader.type": rt,
                           "spark.rapids.tpu.batchRowsMinBucket": 64})
        df = sess.read_csv(str(tmp_path))
        got = sorted(df.collect(device=True).column("a").to_pylist())
        assert got == sorted(range(15)), (rt, got)


def test_csv_crlf_blank_lines_and_ragged_rows(session, tmp_path):
    """CRLF blank lines are skipped like pyarrow; ragged rows route the
    file to the host parser so both placements fail identically."""
    p = tmp_path / "crlf.csv"
    p.write_bytes(b"a,b\r\n1,x\r\n\r\n2,y\r\n")
    df = session.read_csv(str(p))
    dev = df.collect(device=True).to_pylist()
    cpu = df.collect(device=False).to_pylist()
    assert str(dev) == str(cpu) and len(dev) == 2
    # ragged: extra column appears past the schema-inference sample -> the
    # sample passes but the full read raises; the device path must route
    # the file to the host parser so BOTH placements raise identically
    p2 = tmp_path / "ragged.csv"
    rows = "\n".join(f"{i},x" for i in range(1001))
    p2.write_text("a,b\n" + rows + "\n9999,y,z\n")
    df2 = session.read_csv(str(p2))
    import pytest as _pt
    with _pt.raises(Exception, match="columns"):
        df2.collect(device=False)
    with _pt.raises(Exception, match="columns"):
        df2.collect(device=True)


def test_json_device_decode_differential(session, tmp_path):
    """Device JSON-lines decode (reference: GpuJsonScan.scala): quote-
    parity span extraction + typed parse; keys in any order, delimiters
    inside strings, null literals, missing keys."""
    p = tmp_path / "t.jsonl"
    p.write_text(
        '{"a": 1, "b": 2.5, "c": true, "s": "hello"}\n'
        '{"a": -7, "b": null, "c": false, "s": ""}\n'
        '{"b": 1e3, "a": 99, "s": "swap, order", "c": true}\n'
        '{"a": null, "s": null}\n'
        '{"s": "brace } in str", "a": 5, "b": 0.25, "c": false}\n')
    df = session.read_json(str(p))
    ex = df.explain("tpu")
    assert "CpuScanExec will run on TPU" in ex, ex
    dev = df.collect(device=True).to_pylist()
    cpu = df.collect(device=False).to_pylist()
    assert [str(r) for r in dev] == [str(r) for r in cpu]
    assert dev[2]["s"] == "swap, order" and dev[4]["s"] == "brace } in str"


def test_json_whitespace_and_value_shadowing(session, tmp_path):
    """Arbitrary space/tab runs around colons; a string VALUE equal to a
    key token must not shadow the real key (every candidate validates
    next-non-space == ':')."""
    p = tmp_path / "w.jsonl"
    p.write_text('{"a"  :  1, "s": "x"}\n'
                 '{"s"\t: "a", "a": 2}\n'
                 '{ "a":3 ,"s" : "y" }\n')
    df = session.read_json(str(p))
    assert "will run on TPU" in df.explain("tpu")
    dev = df.collect(device=True).to_pylist()
    cpu = df.collect(device=False).to_pylist()
    assert [str(r) for r in dev] == [str(r) for r in cpu]
    assert dev[1]["a"] == 2 and dev[1]["s"] == "a"


def test_json_escapes_fall_back(session, tmp_path):
    p = tmp_path / "esc.jsonl"
    p.write_text('{"s": "he said \\"hi\\"", "a": 1}\n{"s": "x", "a": 2}\n')
    df = session.read_json(str(p))
    ex = df.explain("tpu")
    assert "escaped strings" in ex, ex
    dev = df.collect(device=True).to_pylist()
    cpu = df.collect(device=False).to_pylist()
    assert [str(r) for r in dev] == [str(r) for r in cpu]
    assert dev[0]["s"] == 'he said "hi"'
