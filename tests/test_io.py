"""IO format tests (reference analogues: csv_test.py, json_test.py,
orc_test.py, parquet_write_test.py in integration_tests/)."""
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.expr.functions import col, lit, sum as fsum
from harness import assert_tables_equal, assert_tpu_cpu_equal, data_gen


@pytest.fixture
def table(rng):
    return data_gen(rng, 300, {"k": ("int32", 0, 5), "i": "int64",
                               "f": "float64", "s": "string"})


def test_parquet_roundtrip(session, table, tmp_path):
    df = session.create_dataframe(table)
    df.write_parquet(str(tmp_path / "out"))
    assert os.path.exists(tmp_path / "out" / "_SUCCESS")
    back = session.read_parquet(str(tmp_path / "out"))
    assert_tables_equal(back.collect(), table.select(back.columns))


def test_parquet_partitioned_write(session, table, tmp_path):
    df = session.create_dataframe(table)
    from spark_rapids_tpu.io.writer import write_parquet
    stats = write_parquet(df, str(tmp_path / "p"), partition_by=["k"])
    assert stats.num_rows == 300
    assert len(stats.partitions) >= 2
    dirs = [d for d in os.listdir(tmp_path / "p") if d.startswith("k=")]
    assert len(dirs) == len(stats.partitions)
    # read one partition dir back
    one = session.read_parquet(str(tmp_path / "p" / dirs[0]))
    assert "i" in one.columns and "k" not in one.columns


def test_parquet_query_multifile(session, table, tmp_path):
    os.makedirs(tmp_path / "mf")
    pq.write_table(table.slice(0, 100), tmp_path / "mf" / "a.parquet")
    pq.write_table(table.slice(100), tmp_path / "mf" / "b.parquet")
    df = session.read_parquet(str(tmp_path / "mf"))
    q = df.filter(col("i") > lit(0)).group_by("k").agg(
        fsum(col("f")).alias("sf"))
    assert_tpu_cpu_equal(q, rel_tol=1e-6)


@pytest.mark.parametrize("reader", ["PERFILE", "COALESCING", "MULTITHREADED"])
def test_parquet_reader_types(session, table, tmp_path, reader):
    os.makedirs(tmp_path / "rt")
    for i in range(4):
        pq.write_table(table.slice(i * 75, 75), tmp_path / "rt" / f"{i}.parquet")
    s2 = type(session)(session.conf.set(
        "spark.rapids.sql.format.parquet.reader.type", reader))
    df = s2.read_parquet(str(tmp_path / "rt"))
    out = df.collect()
    assert out.num_rows == 300


def test_csv_roundtrip(session, tmp_path):
    t = pa.table({"a": [1, 2, 3], "b": [1.5, 2.5, None], "s": ["x", "y", "z"]})
    df = session.create_dataframe(t)
    df.write_csv(str(tmp_path / "c"))
    back = session.read_csv(str(tmp_path / "c") + "/*.csv")
    out = back.collect()
    assert out.column("a").to_pylist() == [1, 2, 3]
    assert out.column("b").to_pylist() == [1.5, 2.5, None]


def test_orc_roundtrip(session, table, tmp_path):
    df = session.create_dataframe(table)
    df.write_orc(str(tmp_path / "o"))
    back = session.read_orc(str(tmp_path / "o") + "/*.orc")
    assert_tables_equal(back.collect(), table.select(back.columns))


def test_json_read(session, tmp_path):
    path = tmp_path / "j.jsonl"
    with open(path, "w") as f:
        f.write('{"a": 1, "b": "x"}\n{"a": 2, "b": null}\n')
    df = session.read_json(str(path))
    out = df.collect()
    assert out.column("a").to_pylist() == [1, 2]
    assert out.column("b").to_pylist() == ["x", None]


def test_write_mode_error_and_overwrite(session, table, tmp_path):
    df = session.create_dataframe(table)
    df.write_parquet(str(tmp_path / "m"))
    with pytest.raises(FileExistsError):
        df.write_parquet(str(tmp_path / "m"))
    from spark_rapids_tpu.io.writer import write_parquet
    stats = write_parquet(df, str(tmp_path / "m"), mode="overwrite")
    assert stats.num_rows == 300


def test_device_parquet_write_roundtrip(session, tmp_path):
    """Device write path (round-2 missing #7; reference:
    GpuParquetFileFormat.scala:351): device packs dense column chunks,
    host assembles PLAIN v1 pages + thrift framing; pyarrow reads the
    file back bit-identical, incl. nulls/strings/dates/timestamps."""
    import numpy as np
    import pyarrow.parquet as pq
    rng = np.random.default_rng(4)
    n = 3000
    mask = rng.random(n) < 0.2
    t = pa.table({
        "i": pa.array(rng.integers(-2**40, 2**40, n), type=pa.int64(),
                      mask=mask),
        "f": pa.array(rng.normal(size=n)),
        "b": pa.array(rng.integers(0, 2, n).astype(bool)),
        "s": pa.array([None if m else f"v{rng.integers(0, 10**6)}"
                       for m in mask]),
        "d": pa.array(rng.integers(0, 20000, n).astype(np.int32)).cast(
            pa.date32()),
        "ts": pa.array(rng.integers(0, 2**48, n), type=pa.int64()).cast(
            pa.timestamp("us")),
    })
    df = session.create_dataframe(t, num_partitions=2)
    out = str(tmp_path / "devwrite")
    df.write_parquet(out)
    back = pq.read_table(out).combine_chunks()
    # written across partitions: compare as multisets keyed by row tuple
    def rows(tab):
        return sorted(zip(*[tab.column(c).to_pylist()
                            for c in t.column_names]),
                      key=lambda r: (str(r),))
    assert rows(back) == rows(t)
    import os
    assert os.path.exists(os.path.join(out, "_SUCCESS"))
    # the device writer ran (files carry its created_by marker)
    one = [f for f in os.listdir(out) if f.endswith(".parquet")][0]
    meta = pq.ParquetFile(os.path.join(out, one)).metadata
    assert b"device writer" in meta.created_by.encode() or \
        "device writer" in meta.created_by


def test_device_write_falls_back_for_unsupported_schema(session, tmp_path):
    """Decimal columns stay on the pyarrow writer (and stay correct)."""
    import decimal
    t = pa.table({"x": pa.array([decimal.Decimal("1.23"),
                                 decimal.Decimal("4.56")],
                                type=pa.decimal128(10, 2))})
    df = session.create_dataframe(t)
    out = str(tmp_path / "fallback")
    from spark_rapids_tpu.io.writer import write_parquet
    write_parquet(df, out)
    import pyarrow.parquet as pq
    back = pq.read_table(out)
    assert back.column("x").to_pylist() == t.column("x").to_pylist()
    import os
    one = [f for f in os.listdir(out) if f.endswith(".parquet")][0]
    meta = pq.ParquetFile(os.path.join(out, one)).metadata
    assert "device writer" not in (meta.created_by or "")
