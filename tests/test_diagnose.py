"""tools/diagnose.py — the auto-diagnosis (AutoTuner analogue).

A canned schema-v3 event log with engineered bottlenecks pins the report:
the ranked (node, metric) pairs, the recompile-churn detection from kernel
records, and the query-level signals (compile cache, semaphore, spills).
"""
import json

import numpy as np
import pandas as pd
import pytest


def _write_log(path, nodes, kernels=(), wall_s=1.0, stats=None,
               spill_count=None, semaphore_wait_s=0.0):
    """Fabricate one-query schema-v3 event log. ``nodes`` entries:
    (name, depth, parent_id, wall_s, metrics)."""
    records = [
        {"event": "app_start", "app_id": path.stem, "schema_version": 3,
         "ts": 0.0, "conf": {}},
        {"event": "query_start", "query_id": 1, "ts": 0.0, "plan": "p"},
    ]
    for i, (name, depth, parent, wall, metrics) in enumerate(nodes):
        records.append({
            "event": "node", "query_id": 1, "node_id": i,
            "parent_id": parent, "name": name, "desc": "", "depth": depth,
            "wall_s": wall, "rows": 1000, "batches": 2,
            "t_first": 0.0, "t_last": wall, "metrics": metrics})
    for k in kernels:
        records.append({
            "event": "kernel", "query_id": 1, "first_query_id": 1,
            "signature": k["signature"], "node_name": k.get("node_name"),
            "node_id": k.get("node_id"), "hits": k.get("hits", 0),
            "misses": k.get("misses", 1), "compiles": k.get("compiles", 1),
            "compile_s": k.get("compile_s", 0.0), "cost": k.get("cost", {}),
            "memory": k.get("memory", {})})
    records.append({
        "event": "query_end", "query_id": 1, "ts": 1.0, "wall_s": wall_s,
        "final_plan": "p", "aqe_events": [],
        "spill_count": spill_count or {},
        "semaphore_wait_s": semaphore_wait_s, "stats": stats or {}})
    records.append({"event": "app_end", "ts": 1.0})
    with open(path, "w", encoding="utf-8") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return str(path)


def _golden_log(tmp_path):
    """One query, wall 2.0s: host shuffle dominates (61%), the aggregate
    takes 20%, the upload 10%; one operator shows recompile churn."""
    return _write_log(
        tmp_path / "golden.jsonl",
        nodes=[
            ("DeviceToHostExec", 0, -1, 1.98,
             {"deviceToHostTime": 0.01, "deviceToHostBytes": 1 << 20}),
            ("ShuffleExchangeExec", 1, 0, 1.96,
             {"shufflePartitionTime": 1.1, "shuffleBytes": 1 << 26}),
            ("TpuHashAggregateExec", 2, 1, 0.74,
             {"computeAggTime": 0.38, "xlaCompileTime": 0.3,
              "xlaCacheMisses": 6}),
            ("HostToDeviceExec", 3, 2, 0.34,
             {"hostToDeviceTime": 0.2, "hostToDeviceBytes": 1 << 24}),
            ("CpuScanExec", 4, 3, 0.14, {}),
        ],
        kernels=[
            {"signature": f"HashAggC|partial|cap{1 << (10 + i)}",
             "node_name": "TpuHashAggregateExec", "node_id": 2,
             "compiles": 1, "compile_s": 0.12,
             "cost": {"flops": 1e6, "bytes accessed": 2e6}}
            for i in range(5)
        ],
        wall_s=2.0,
        stats={"compile_cache_compile_seconds": 0.9,
               "compile_cache_misses": 6},
        spill_count={"StorageTier.HOST": 3},
        semaphore_wait_s=0.6,
    )


def test_golden_diagnose_report(tmp_path):
    from spark_rapids_tpu.tools.diagnose import diagnose_path
    rep = diagnose_path(_golden_log(tmp_path))
    (q,) = rep.queries
    assert q.query_id == 1 and q.wall_s == pytest.approx(2.0)

    # the top-3 (node, metric) pairs, ranked by share of wall
    top = q.top(3)
    assert [(f.node, f.metric) for f in top] == [
        ("ShuffleExchangeExec", "wall"),
        ("ShuffleExchangeExec", "shufflePartitionTime"),
        ("(query)", "xlaCompileSeconds"),
    ]
    # the host-shuffle finding carries its share and the tier suggestion
    assert top[0].fraction == pytest.approx((1.96 - 0.74) / 2.0, abs=0.01)
    assert "shuffle.mode" in top[0].suggestion

    byname = {(f.node, f.metric): f for f in q.findings}
    # recompile churn detected from the kernel records
    churn = byname[("TpuHashAggregateExec", "recompiles")]
    assert "5 unique signatures" in churn.detail
    assert "batchRowsMinBucket" in churn.suggestion
    # upload + semaphore + spill findings all present
    assert ("HostToDeviceExec", "hostToDeviceTime") in byname
    assert ("(query)", "semaphoreWaitTime") in byname
    assert ("(query)", "spills") in byname

    s = rep.summary()
    assert "top bottlenecks" in s
    assert "(ShuffleExchangeExec, wall) 61% of wall" in s
    assert "suggest:" in s
    # machine-readable form round-trips
    obj = json.loads(rep.to_json())
    assert obj["queries"][0]["findings"][0]["node"] == \
        "ShuffleExchangeExec"


def test_bucket_churn_section(tmp_path):
    """Kernel-table signatures that differ only in shape for one operator
    are reported as bucket churn (ISSUE 7 satellite); operators whose
    signatures differ structurally are not."""
    from spark_rapids_tpu.tools.diagnose import diagnose_path
    path = _write_log(
        tmp_path / "churn.jsonl",
        nodes=[("TpuSortExec", 0, -1, 0.9, {}),
               ("TpuProjectExec", 1, 0, 0.1, {})],
        kernels=[
            # same computation, three capacities -> churn
            *({"signature": f"Sort|keys=[a]|cap{c}",
               "node_name": "TpuSortExec", "node_id": 0,
               "compiles": 1, "compile_s": 0.2}
              for c in (1024, 2048, 4096)),
            # structurally different signatures -> NOT churn
            {"signature": "Project|exprs=[a+b]|cap1024",
             "node_name": "TpuProjectExec", "node_id": 1,
             "compiles": 1, "compile_s": 0.1},
            {"signature": "Project|exprs=[a*b,c]|cap1024",
             "node_name": "TpuProjectExec", "node_id": 1,
             "compiles": 1, "compile_s": 0.1},
        ],
        wall_s=1.0)
    (q,) = diagnose_path(path).queries
    byname = {(f.node, f.metric): f for f in q.findings}
    churn = byname[("TpuSortExec", "bucketChurn")]
    assert "3 signatures" in churn.detail
    assert "shapeBuckets" in churn.suggestion
    assert churn.seconds == pytest.approx(0.6)
    assert ("TpuProjectExec", "bucketChurn") not in byname


def test_diagnose_errors_and_empty_queries_skipped(tmp_path):
    from spark_rapids_tpu.tools.diagnose import diagnose_path
    path = tmp_path / "err.jsonl"
    records = [
        {"event": "app_start", "app_id": "e", "schema_version": 3,
         "ts": 0.0, "conf": {}},
        {"event": "query_start", "query_id": 1, "ts": 0.0, "plan": "p"},
        {"event": "query_end", "query_id": 1, "ts": 1.0, "wall_s": 0.5,
         "final_plan": "p", "aqe_events": [], "spill_count": {},
         "semaphore_wait_s": 0.0, "stats": {}, "error": "boom"},
        {"event": "app_end", "ts": 1.0},
    ]
    with open(path, "w", encoding="utf-8") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    rep = diagnose_path(str(path))
    assert rep.queries == []
    assert "no completed queries" in rep.summary()


def test_diagnose_v2_log_without_kernels(tmp_path):
    """Backwards compatible: a v2 log (no kernel records, no node metric
    attribution) still yields the wall ranking + query-level findings."""
    from spark_rapids_tpu.tools.diagnose import diagnose_path
    path = _write_log(
        tmp_path / "v2.jsonl",
        nodes=[("TpuSortExec", 0, -1, 0.9, {}),
               ("CpuScanExec", 1, 0, 0.05, {})],
        wall_s=1.0,
        stats={"compile_cache_compile_seconds": 0.5})
    rep = diagnose_path(path)
    (q,) = rep.queries
    assert q.findings[0].node == "TpuSortExec"
    assert q.findings[0].metric == "wall"
    assert any(f.metric == "xlaCompileSeconds" for f in q.findings)


def test_diagnose_cli(tmp_path, capsys):
    from spark_rapids_tpu.tools.diagnose import main
    path = _golden_log(tmp_path)
    rc = main([path])
    out = capsys.readouterr().out
    assert rc == 0 and "top bottlenecks" in out
    # --json emits valid JSON; directory arguments expand to *.jsonl
    rc = main([str(tmp_path), "--json", "--top", "2",
               "--out", str(tmp_path / "rep.json")])
    out = capsys.readouterr().out
    assert rc == 0
    obj = json.loads(out)
    assert len(obj["queries"][0]["findings"]) == 2
    assert (tmp_path / "rep.json").exists()
    empty = tmp_path / "nope_dir_empty"
    empty.mkdir()
    rc = main([str(empty)])
    assert rc == 2


def test_diagnose_real_event_log(tmp_path):
    """End-to-end: a real device run produces a diagnosable v3 log whose
    top findings name actual plan operators."""
    import glob
    import os
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.expr.functions import col, sum as f_sum
    from spark_rapids_tpu.tools.diagnose import diagnose_path
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.shuffle.partitions": 2,
        "spark.rapids.tpu.shuffle.mode": "host",
    })
    rng = np.random.default_rng(21)
    df = sess.create_dataframe(pd.DataFrame({
        "g": rng.integers(0, 5, 300).astype(np.int64),
        "x": rng.normal(size=300)}), num_partitions=2)
    df.group_by("g").agg(f_sum(col("x")).alias("sx")).collect(device=True)
    sess.close()
    (path,) = glob.glob(os.path.join(str(tmp_path), "*.jsonl"))
    rep = diagnose_path(path)
    (q,) = rep.queries
    assert q.findings, "real run produced no findings"
    # every finding names a real (node, metric) pair with a suggestion
    for f in q.top(3):
        assert f.node and f.metric and f.suggestion
    assert "top bottlenecks" in rep.summary()
