"""Aggregation differential tests (reference: HashAggregatesSuite +
hash_aggregate_test.py)."""
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr.functions import (avg, col, count, count_star,
                                             first, last, lit, max as fmax,
                                             min as fmin, stddev_pop,
                                             stddev_samp, sum as fsum,
                                             var_pop, var_samp)
from harness import assert_tpu_cpu_equal, data_gen


@pytest.fixture
def df(session, rng):
    t = data_gen(rng, 500, {
        "k1": ("int32", 0, 5), "k2": ("int64", 0, 3), "fk": "float64",
        "i": "int64", "f": "float64", "b": "bool",
    })
    return session.create_dataframe(t, num_partitions=3)


def test_grand_aggregate(df):
    assert_tpu_cpu_equal(df.agg(
        fsum(col("i")).alias("s"), count(col("i")).alias("c"),
        count_star().alias("n"), fmin(col("i")).alias("mn"),
        fmax(col("i")).alias("mx"), avg(col("i")).alias("av"),
    ), rel_tol=1e-6)


def test_grouped_single_key(df):
    assert_tpu_cpu_equal(df.group_by("k1").agg(
        fsum(col("i")).alias("s"), count(col("i")).alias("c"),
        fmin(col("f")).alias("mn"), fmax(col("f")).alias("mx"),
        avg(col("f")).alias("av"),
    ), rel_tol=1e-6)


def test_grouped_multi_key(df):
    assert_tpu_cpu_equal(df.group_by("k1", "k2").agg(
        fsum(col("i")).alias("s"), count_star().alias("n"),
    ))


def test_grouped_float_key_nan_zero(df):
    # float keys: NaN==NaN grouping, -0.0 == 0.0 normalization
    assert_tpu_cpu_equal(df.group_by("fk").agg(count_star().alias("n")))


def test_group_by_expression(df, session):
    assert_tpu_cpu_equal(
        df.group_by((col("k1") % lit(2)).alias("parity"))
          .agg(fsum(col("i")).alias("s")))


def test_sum_empty_and_all_null(session):
    t = pa.table({"k": pa.array([], type=pa.int32()),
                  "v": pa.array([], type=pa.int64())})
    df = session.create_dataframe(t)
    assert_tpu_cpu_equal(df.agg(fsum(col("v")).alias("s"),
                                count_star().alias("n")))
    t2 = pa.table({"k": [1, 1, 2], "v": pa.array([None, None, None],
                                                 type=pa.int64())})
    df2 = session.create_dataframe(t2)
    assert_tpu_cpu_equal(df2.group_by("k").agg(fsum(col("v")).alias("s"),
                                               count(col("v")).alias("c")))


def test_null_group_key(session):
    t = pa.table({"k": [1, None, 1, None, 2], "v": [1, 2, 3, 4, 5]})
    df = session.create_dataframe(t)
    assert_tpu_cpu_equal(df.group_by("k").agg(fsum(col("v")).alias("s")))


def test_first_last(df):
    # first/last need deterministic order per group: use single partition input
    assert_tpu_cpu_equal(df.group_by("k1").agg(
        count_star().alias("n")))


def test_variance_stddev(df):
    assert_tpu_cpu_equal(df.group_by("k1").agg(
        var_pop(col("f")).alias("vp"), var_samp(col("f")).alias("vs"),
        stddev_pop(col("f")).alias("sp"), stddev_samp(col("f")).alias("ss"),
    ), rel_tol=1e-5)


def test_avg_over_filter(df):
    assert_tpu_cpu_equal(
        df.filter(col("i") > lit(0)).group_by("k2")
          .agg(avg(col("i")).alias("av"), fsum(col("f")).alias("s")),
        rel_tol=1e-6)


@pytest.mark.parametrize("strategy", ["sort", "hash"])
def test_groupby_strategy_differential(strategy):
    """The sort-free hash grouping (bucket-resolve rounds, no lax.sort —
    spark.rapids.tpu.groupby.strategy) matches the sort path and the host
    engine exactly, incl. null/NaN keys and string keys."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import pyarrow as pa
    import spark_rapids_tpu.expr.functions as F
    from spark_rapids_tpu.expr.functions import col
    from spark_rapids_tpu.session import TpuSession
    rng = np.random.default_rng(11)
    n = 5000
    fv = rng.normal(size=n).round(2)
    fv[::17] = np.nan
    fmask = np.ones(n, bool)
    fmask[::23] = False
    t = pa.table({
        "k1": rng.integers(0, 40, n),
        "k2": rng.choice(["aa", "bb", None, "ab\x00"], n),
        "f": pa.array(fv, mask=~fmask),
        "v": rng.normal(size=n),
    })
    sess = TpuSession({"spark.rapids.tpu.batchRowsMinBucket": 512,
                       "spark.rapids.tpu.groupby.strategy": strategy})
    df = sess.create_dataframe(t, num_partitions=2)
    q = df.group_by("k1", "k2", "f").agg(
        F.sum(col("v")).alias("sv"), F.count(col("v")).alias("c"),
        F.min(col("v")).alias("mn"), F.first(col("v")).alias("fst"))
    dev = sorted(map(str, q.collect(device=True).to_pylist()))
    cpu = sorted(map(str, q.collect(device=False).to_pylist()))
    assert dev == cpu
