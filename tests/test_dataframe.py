"""DataFrame API / plan-level differential tests (sort, limit, union, range,
joins) — reference analogues: sort_test.py, limit_test.py, union, join_test.py."""
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr.functions import col, lit, sum as fsum
from harness import assert_tpu_cpu_equal, assert_tables_equal, data_gen


@pytest.fixture
def df(session, rng):
    t = data_gen(rng, 300, {"k": ("int32", 0, 10), "i": "int64", "f": "float64",
                            "s": "string"})
    return session.create_dataframe(t, num_partitions=2)


def test_sort_asc_desc(df):
    out = df.sort(col("i").asc()).collect(device=True)
    cpu = df.sort(col("i").asc()).collect(device=False)
    assert_tables_equal(out, cpu, ignore_order=False)
    out = df.sort(col("f").desc(), col("i").asc()).collect(device=True)
    cpu = df.sort(col("f").desc(), col("i").asc()).collect(device=False)
    # f has NaN/null ties: compare the sorted key columns positionally,
    # the full rows modulo tie order
    assert_tables_equal(out.select(["f"]), cpu.select(["f"]),
                        ignore_order=False)
    assert_tables_equal(out, cpu, ignore_order=True)


def test_limit(df):
    assert df.limit(17).collect(device=True).num_rows == 17
    assert df.limit(0).collect(device=True).num_rows == 0
    assert df.limit(10**6).collect(device=True).num_rows == 300


def test_union(df, session, rng):
    t2 = data_gen(rng, 50, {"k": ("int32", 0, 10), "i": "int64",
                            "f": "float64", "s": "string"})
    other = session.create_dataframe(t2)
    assert_tpu_cpu_equal(df.union(other))


def test_range(session):
    df = session.range(0, 1000, 3, num_partitions=2)
    out = df.collect(device=True)
    assert out.column("id").to_pylist() == list(range(0, 1000, 3))
    assert_tpu_cpu_equal(df.filter(col("id") % lit(7) == lit(0)))


def test_with_column(df):
    assert_tpu_cpu_equal(df.with_column("i2", col("i") * 2))


def test_count(df):
    assert df.count() == 300


def test_inner_join(session, rng):
    lt = data_gen(rng, 120, {"k": ("int32", 0, 20), "a": "int64"})
    rt = data_gen(rng, 80, {"k": ("int32", 0, 20), "b": "float64"})
    l = session.create_dataframe(lt, num_partitions=2)
    r = session.create_dataframe(rt, num_partitions=2)
    assert_tpu_cpu_equal(l.join(r, on="k"))


@pytest.mark.parametrize("how", ["left", "right", "full", "left_semi",
                                 "left_anti"])
def test_outer_semi_anti_joins(session, rng, how):
    lt = data_gen(rng, 60, {"k": ("int32", 0, 15), "a": "int64"})
    rt = data_gen(rng, 40, {"k": ("int32", 0, 15), "b": "float64"})
    l = session.create_dataframe(lt)
    r = session.create_dataframe(rt)
    assert_tpu_cpu_equal(l.join(r, on="k", how=how))


def test_join_vs_pandas(session):
    lt = pa.table({"k": [1, 2, None, 3], "a": [10, 20, 30, 40]})
    rt = pa.table({"k": [2, 3, None, 4], "b": [1.0, 2.0, 3.0, 4.0]})
    l = session.create_dataframe(lt)
    r = session.create_dataframe(rt)
    out = l.join(r, on="k").collect()
    # null keys never match
    assert sorted(out.column("k").to_pylist()) == [2, 3]
    out_full = l.join(r, on="k", how="full").collect()
    assert out_full.num_rows == 6  # 2 matches + 2 left-only(None,1) + 2 right-only


def test_cross_join(session):
    l = session.create_dataframe(pa.table({"a": [1, 2]}))
    r = session.create_dataframe(pa.table({"b": ["x", "y", "z"]}))
    out = l.cross_join(r).collect()
    assert out.num_rows == 6


def test_chained_query(df):
    q = (df.filter(col("i") > lit(0))
           .with_column("v", col("i") * col("f"))
           .group_by("k")
           .agg(fsum(col("v")).alias("sv"))
           .sort("k"))
    assert_tpu_cpu_equal(q, rel_tol=1e-6)


def test_multi_key_sort_tied_float_defers_to_later_keys(session):
    """A tied float PRIMARY key must defer to the secondary keys (dense
    equal-value codes; per-row argsort ranks silently ignored every key
    after a tied float — found by the plan fuzzer)."""
    t = pa.table({
        "f": pa.array([1.5, 1.5, 1.5, 0.5, 0.5, float("nan"), None]),
        "i": pa.array([3, 1, 2, 9, 8, 1, 2], type=pa.int64()),
    })
    df = session.create_dataframe(t, num_partitions=2)
    q = df.sort(col("f").asc(), col("i").asc())
    for device in (False, True):
        out = q.collect(device=device)
        assert out.column("i").to_pylist() == [2, 8, 9, 1, 2, 3, 1], \
            (device, out.column("i").to_pylist())
        # null f first, then 0.5s (i asc), then 1.5s (i asc), NaN last
        fs = out.column("f").to_pylist()
        assert fs[0] is None and fs[-1] != fs[-1]
