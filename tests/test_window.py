"""Window function tests (reference analogues: WindowFunctionSuite +
window_function_test.py)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr.functions import avg, col, count_star, max as fmax, \
    min as fmin, sum as fsum
from spark_rapids_tpu.expr.window import (Window, dense_rank, lag, lead,
                                          ntile, rank, row_number)
from harness import assert_tables_equal, assert_tpu_cpu_equal, data_gen


@pytest.fixture
def df(session, rng):
    t = data_gen(rng, 200, {"k": ("int32", 0, 6), "v": ("int64", 0, 50),
                            "x": "float64"}, null_prob=0.1)
    return session.create_dataframe(t, num_partitions=2)


def _w():
    return Window.partition_by("k").order_by(col("v").asc(), col("x").asc())


def test_row_number(df):
    q = df.with_column("rn", row_number().over(_w()))
    assert_tpu_cpu_equal(q)
    out = q.collect()
    pdf = out.to_pandas()
    for k, grp in pdf.groupby("k", dropna=False):
        assert sorted(grp["rn"]) == list(range(1, len(grp) + 1))


def test_rank_dense_rank(session):
    t = pa.table({"k": [1, 1, 1, 1, 2, 2, 2],
                  "v": [10, 10, 20, 30, 5, 5, 5]})
    df = session.create_dataframe(t)
    w = Window.partition_by("k").order_by(col("v").asc())
    q = df.with_column("r", rank().over(w)).with_column(
        "dr", dense_rank().over(w)).sort("k", "v")
    out = assert_tpu_cpu_equal(q, ignore_order=True)
    pdf = out.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    assert pdf[pdf.k == 1]["r"].tolist() == [1, 1, 3, 4]
    assert pdf[pdf.k == 1]["dr"].tolist() == [1, 1, 2, 3]
    assert pdf[pdf.k == 2]["r"].tolist() == [1, 1, 1]


def test_lag_lead(df):
    w = _w()
    q = (df.with_column("lg", lag(col("v"), 1).over(w))
           .with_column("ld", lead(col("v"), 2).over(w))
           .with_column("lgd", lag(col("v"), 1, default=-1).over(w)))
    assert_tpu_cpu_equal(q)


def test_running_sum_rows(df):
    w = _w().rows_between(None, 0)
    q = df.with_column("rs", fsum(col("v")).over(w))
    assert_tpu_cpu_equal(q)


def test_running_range_with_peers(session):
    # RANGE UNBOUNDED..CURRENT includes peer rows (ties)
    t = pa.table({"k": [1, 1, 1, 1], "v": [10, 10, 20, 30],
                  "x": [1.0, 2.0, 3.0, 4.0]})
    df = session.create_dataframe(t)
    w = Window.partition_by("k").order_by(col("v").asc())
    q = df.with_column("s", fsum(col("x")).over(w)).sort("v", "x")
    out = assert_tpu_cpu_equal(q, ignore_order=True)
    pdf = out.to_pandas().sort_values(["v", "x"])
    assert pdf["s"].tolist() == [3.0, 3.0, 6.0, 10.0]


def test_entire_partition_agg(df):
    w = Window.partition_by("k")
    q = (df.with_column("s", fsum(col("v")).over(w))
           .with_column("mn", fmin(col("x")).over(w))
           .with_column("mx", fmax(col("x")).over(w))
           .with_column("n", count_star().over(w))
           .with_column("av", avg(col("v")).over(w)))
    assert_tpu_cpu_equal(q, rel_tol=1e-6)


def test_bounded_rows_frame(df):
    w = _w().rows_between(-2, 1)
    q = (df.with_column("s", fsum(col("v")).over(w))
           .with_column("n", count_star().over(w))
           .with_column("av", avg(col("x")).over(w)))
    assert_tpu_cpu_equal(q, rel_tol=1e-6)


def test_ntile(df):
    q = df.with_column("nt", ntile(3).over(_w()))
    assert_tpu_cpu_equal(q)


def test_window_device_in_plan(session, df):
    q = df.with_column("rn", row_number().over(_w()))
    plan = session._physical(q.logical, True)

    def has(p, name):
        return type(p).__name__ == name or any(has(c, name) for c in p.children)
    assert has(plan, "TpuWindowExec"), plan.tree_string()


def test_multiple_specs_stack(df):
    w1 = Window.partition_by("k").order_by(col("v").asc(), col("x").asc())
    w2 = Window.partition_by("k")
    q = (df.with_column("rn", row_number().over(w1))
           .with_column("tot", fsum(col("v")).over(w2)))
    assert_tpu_cpu_equal(q)


def test_with_column_overwrites_existing_with_window(session):
    # regression: window column replacing an existing column of the same name
    t = pa.table({"k": [1, 1, 2], "x": [10, 20, 30]})
    df = session.create_dataframe(t)
    w = Window.partition_by("k").order_by(col("x").asc())
    out = df.with_column("x", row_number().over(w)).collect()
    assert sorted(out.column("x").to_pylist()) == [1, 1, 2]


def test_bounded_rows_minmax_cpu_fallback(session, rng):
    t = data_gen(rng, 150, {"k": ("int32", 0, 4), "v": ("int64", 0, 40),
                            "x": "float64"}, null_prob=0.1)
    df = session.create_dataframe(t)
    w = Window.partition_by("k").order_by(col("v").asc(), col("x").asc()) \
        .rows_between(-3, 2)
    q = (df.with_column("mn", fmin(col("x")).over(w))
           .with_column("mx", fmax(col("x")).over(w)))
    assert_tpu_cpu_equal(q)


def test_cache_under_limit_no_leak(session):
    # regression: abandoning a cached scan mid-stream must not leak buffers
    from spark_rapids_tpu.memory import get_catalog
    t = pa.table({"a": list(range(100))})
    df = session.create_dataframe(t).cache()
    before = get_catalog().stats()["buffers"]
    df.limit(5).collect(device=True)
    after = get_catalog().stats()["buffers"]
    assert after - before <= 1  # at most the fully-drained cache entry
