"""Window function tests (reference analogues: WindowFunctionSuite +
window_function_test.py)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr.functions import avg, col, count_star, max as fmax, \
    min as fmin, sum as fsum
from spark_rapids_tpu.expr.window import (Window, dense_rank, lag, lead,
                                          ntile, rank, row_number)
from harness import assert_tables_equal, assert_tpu_cpu_equal, data_gen


@pytest.fixture
def df(session, rng):
    t = data_gen(rng, 200, {"k": ("int32", 0, 6), "v": ("int64", 0, 50),
                            "x": "float64"}, null_prob=0.1)
    return session.create_dataframe(t, num_partitions=2)


def _w():
    return Window.partition_by("k").order_by(col("v").asc(), col("x").asc())


def test_row_number(df):
    q = df.with_column("rn", row_number().over(_w()))
    assert_tpu_cpu_equal(q)
    out = q.collect()
    pdf = out.to_pandas()
    for k, grp in pdf.groupby("k", dropna=False):
        assert sorted(grp["rn"]) == list(range(1, len(grp) + 1))


def test_rank_dense_rank(session):
    t = pa.table({"k": [1, 1, 1, 1, 2, 2, 2],
                  "v": [10, 10, 20, 30, 5, 5, 5]})
    df = session.create_dataframe(t)
    w = Window.partition_by("k").order_by(col("v").asc())
    q = df.with_column("r", rank().over(w)).with_column(
        "dr", dense_rank().over(w)).sort("k", "v")
    out = assert_tpu_cpu_equal(q, ignore_order=True)
    pdf = out.to_pandas().sort_values(["k", "v"]).reset_index(drop=True)
    assert pdf[pdf.k == 1]["r"].tolist() == [1, 1, 3, 4]
    assert pdf[pdf.k == 1]["dr"].tolist() == [1, 1, 2, 3]
    assert pdf[pdf.k == 2]["r"].tolist() == [1, 1, 1]


def test_lag_lead(df):
    w = _w()
    q = (df.with_column("lg", lag(col("v"), 1).over(w))
           .with_column("ld", lead(col("v"), 2).over(w))
           .with_column("lgd", lag(col("v"), 1, default=-1).over(w)))
    assert_tpu_cpu_equal(q)


def test_running_sum_rows(df):
    w = _w().rows_between(None, 0)
    q = df.with_column("rs", fsum(col("v")).over(w))
    assert_tpu_cpu_equal(q)


def test_running_range_with_peers(session):
    # RANGE UNBOUNDED..CURRENT includes peer rows (ties)
    t = pa.table({"k": [1, 1, 1, 1], "v": [10, 10, 20, 30],
                  "x": [1.0, 2.0, 3.0, 4.0]})
    df = session.create_dataframe(t)
    w = Window.partition_by("k").order_by(col("v").asc())
    q = df.with_column("s", fsum(col("x")).over(w)).sort("v", "x")
    out = assert_tpu_cpu_equal(q, ignore_order=True)
    pdf = out.to_pandas().sort_values(["v", "x"])
    assert pdf["s"].tolist() == [3.0, 3.0, 6.0, 10.0]


def test_entire_partition_agg(df):
    w = Window.partition_by("k")
    q = (df.with_column("s", fsum(col("v")).over(w))
           .with_column("mn", fmin(col("x")).over(w))
           .with_column("mx", fmax(col("x")).over(w))
           .with_column("n", count_star().over(w))
           .with_column("av", avg(col("v")).over(w)))
    assert_tpu_cpu_equal(q, rel_tol=1e-6)


def test_bounded_rows_frame(df):
    w = _w().rows_between(-2, 1)
    q = (df.with_column("s", fsum(col("v")).over(w))
           .with_column("n", count_star().over(w))
           .with_column("av", avg(col("x")).over(w)))
    assert_tpu_cpu_equal(q, rel_tol=1e-6)


def test_ntile(df):
    q = df.with_column("nt", ntile(3).over(_w()))
    assert_tpu_cpu_equal(q)


def test_window_device_in_plan(session, df):
    q = df.with_column("rn", row_number().over(_w()))
    plan = session._physical(q.logical, True)

    from spark_rapids_tpu.plan.aqe import AdaptiveExec
    if isinstance(plan, AdaptiveExec):
        plan = plan.final_plan()

    def has(p, name):
        return type(p).__name__ == name or any(has(c, name) for c in p.children)
    assert has(plan, "TpuWindowExec"), plan.tree_string()


def test_multiple_specs_stack(df):
    w1 = Window.partition_by("k").order_by(col("v").asc(), col("x").asc())
    w2 = Window.partition_by("k")
    q = (df.with_column("rn", row_number().over(w1))
           .with_column("tot", fsum(col("v")).over(w2)))
    assert_tpu_cpu_equal(q)


def test_with_column_overwrites_existing_with_window(session):
    # regression: window column replacing an existing column of the same name
    t = pa.table({"k": [1, 1, 2], "x": [10, 20, 30]})
    df = session.create_dataframe(t)
    w = Window.partition_by("k").order_by(col("x").asc())
    out = df.with_column("x", row_number().over(w)).collect()
    assert sorted(out.column("x").to_pylist()) == [1, 1, 2]


def test_bounded_rows_minmax_cpu_fallback(session, rng):
    t = data_gen(rng, 150, {"k": ("int32", 0, 4), "v": ("int64", 0, 40),
                            "x": "float64"}, null_prob=0.1)
    df = session.create_dataframe(t)
    w = Window.partition_by("k").order_by(col("v").asc(), col("x").asc()) \
        .rows_between(-3, 2)
    q = (df.with_column("mn", fmin(col("x")).over(w))
           .with_column("mx", fmax(col("x")).over(w)))
    assert_tpu_cpu_equal(q)


def test_cache_under_limit_no_leak(session):
    # regression: abandoning a cached scan mid-stream must not leak buffers
    from spark_rapids_tpu.memory import get_catalog
    t = pa.table({"a": list(range(100))})
    df = session.create_dataframe(t).cache()
    before = get_catalog().stats()["buffers"]
    df.limit(5).collect(device=True)
    after = get_catalog().stats()["buffers"]
    assert after - before <= 1  # at most the fully-drained cache entry


def test_bounded_rows_minmax(df):
    """min/max over bounded ROWS frames run on device via the sparse-table
    kernel (was a host fallback; reference GpuWindowExpression rolling)."""
    w = _w().rows_between(-2, 1)
    q = (df.with_column("mn", fmin(col("x")).over(w))
           .with_column("mx", fmax(col("v")).over(w)))
    assert_tpu_cpu_equal(q, rel_tol=1e-6)


@pytest.mark.slow
def test_bounded_range_frame(session, rng):
    """Bounded RANGE frames: value-offset windows along one numeric order
    key, all aggregate kinds, ASC and DESC. Slow tier (~19s of window
    kernel compiles); tier-1 keeps test_bounded_range_device_in_plan's
    cheaper pin on the same frame lowering."""
    t = data_gen(rng, 150, {"k": ("int32", 0, 4), "o": ("int64", 0, 40),
                            "v": "float64"}, null_prob=0.1)
    df = session.create_dataframe(t, num_partitions=2)
    from spark_rapids_tpu.expr.window import Window
    w = Window.partition_by("k").order_by(col("o").asc()).range_between(-5, 5)
    q = (df.with_column("s", fsum(col("v")).over(w))
           .with_column("c", count_star().over(w))
           .with_column("mn", fmin(col("v")).over(w))
           .with_column("mx", fmax(col("v")).over(w)))
    assert_tpu_cpu_equal(q, rel_tol=1e-6)
    wd = Window.partition_by("k").order_by(col("o").desc()) \
        .range_between(-5, 2)
    q2 = df.with_column("s", fsum(col("v")).over(wd)) \
        .with_column("mx", fmax(col("v")).over(wd))
    assert_tpu_cpu_equal(q2, rel_tol=1e-6)


def test_bounded_range_device_in_plan(session, rng):
    t = data_gen(rng, 60, {"k": ("int32", 0, 3), "o": ("int64", 0, 20),
                           "v": "float64"}, null_prob=0.0)
    df = session.create_dataframe(t)
    from spark_rapids_tpu.expr.window import Window
    w = Window.partition_by("k").order_by(col("o").asc()).range_between(-3, 3)
    q = df.with_column("s", fsum(col("v")).over(w))
    text = q.explain("tpu")
    assert "bounded RANGE" not in text, text   # no fallback reason anymore

    # two order keys: invalid in Spark (AnalysisException) — tagged off
    # device, and the host engine rejects it too
    w2 = Window.partition_by("k").order_by(col("o").asc(), col("v").asc()) \
        .range_between(-3, 3)
    q2 = df.with_column("s", fsum(col("v")).over(w2))
    assert "bounded RANGE frames need exactly one order key" \
        in q2.explain("tpu")
    with pytest.raises(NotImplementedError):
        q2.collect(device=False)


def test_bounded_range_manual_check(session):
    """Hand-computed RANGE window on a tiny example."""
    t = pa.table({"o": [1, 2, 4, 7, 8], "v": [1.0, 2.0, 3.0, 4.0, 5.0]})
    df = session.create_dataframe(t)
    from spark_rapids_tpu.expr.window import Window
    w = Window.order_by(col("o").asc()).range_between(-1, 1)
    out = assert_tpu_cpu_equal(
        df.with_column("s", fsum(col("v")).over(w)), ignore_order=False,
        rel_tol=1e-9)
    # windows: o=1:[1,2] o=2:[1,2] o=4:[4] o=7:[7,8] o=8:[7,8]
    assert out.column("s").to_pylist() == [3.0, 3.0, 3.0, 9.0, 9.0]


def test_bounded_range_decimal_and_nan_keys(session):
    """RANGE offsets on decimal keys are VALUE units (not scaled-int64
    units); NaN keys form one peer group at the top of the total order."""
    from spark_rapids_tpu.columnar import dtypes as dtm
    from spark_rapids_tpu.expr.window import Window
    t = pa.table({"o": [1.00, 2.00, 8.00], "v": [1.0, 2.0, 4.0]})
    df = session.create_dataframe(t)
    df = df.select(col("o").cast(dtm.DecimalType(10, 2)).alias("o"),
                   col("v"))
    w = Window.order_by(col("o").asc()).range_between(-1, 1)
    out = assert_tpu_cpu_equal(df.with_column("s", fsum(col("v")).over(w)),
                               ignore_order=False)
    assert out.column("s").to_pylist() == [3.0, 3.0, 4.0]

    t2 = pa.table({"o": [1.0, 2.0, float("nan"), float("nan")],
                   "v": [1.0, 2.0, 4.0, 8.0]})
    df2 = session.create_dataframe(t2)
    w2 = Window.order_by(col("o").asc()).range_between(0, 0)
    out2 = assert_tpu_cpu_equal(
        df2.with_column("c", count_star().over(w2)), ignore_order=False)
    got = dict(zip(out2.column("v").to_pylist(),
                   out2.column("c").to_pylist()))
    assert got[4.0] == 2 and got[8.0] == 2   # NaN rows are peers


def test_bounded_range_large_long_keys(session):
    """int64 RANGE keys beyond 2^53 stay distinct (no float64 collapse)."""
    from spark_rapids_tpu.expr.window import Window
    base = 1 << 53
    t = pa.table({"o": [base, base + 1, base + 3],
                  "v": [1.0, 2.0, 4.0]})
    df = session.create_dataframe(t)
    w = Window.order_by(col("o").asc()).range_between(0, 1)
    out = assert_tpu_cpu_equal(df.with_column("s", fsum(col("v")).over(w)),
                               ignore_order=False)
    assert out.column("s").to_pylist() == [3.0, 2.0, 4.0]


def test_string_partition_keys_on_device(session, rng):
    """String partition keys run on device: the sort packs them to uint64
    key words and segment detection compares byte rows (+ length, so "ab"
    and "ab\\x00" stay distinct partitions)."""
    t = data_gen(rng, 300, {"k": "string", "v": ("int64", 0, 50),
                            "x": "float64"}, null_prob=0.1)
    df = session.create_dataframe(t, num_partitions=2)
    w = Window.partition_by("k").order_by(col("v").asc(), col("x").asc())
    q = (df.with_column("rn", row_number().over(w))
           .with_column("s", fsum(col("v")).over(Window.partition_by("k"))))
    assert_tpu_cpu_equal(q)
    plan = session._physical(q.logical, True)
    from spark_rapids_tpu.plan.aqe import AdaptiveExec
    if isinstance(plan, AdaptiveExec):
        plan = plan.final_plan()

    def has(p, name):
        subs = list(p.children)
        for a in ("inner", "stage"):
            sub = getattr(p, a, None)
            if sub is not None:
                subs.append(sub)
        return type(p).__name__ == name or any(has(c, name) for c in subs)
    assert has(plan, "TpuWindowExec"), plan.tree_string()


def test_string_order_keys_peer_groups(session):
    """String ORDER keys: rank/dense_rank peer groups split on byte-row
    equality, including the embedded-NUL edge."""
    t = pa.table({
        "k": [1, 1, 1, 1, 1, 2, 2],
        "s": ["ab", "ab", "ab\x00", "b", None, "z", "z"],
    })
    df = session.create_dataframe(t, num_partitions=2)
    w = Window.partition_by("k").order_by(col("s").asc())
    q = df.with_column("r", rank().over(w)) \
          .with_column("dr", dense_rank().over(w))
    assert_tpu_cpu_equal(q)


def test_lag_offsets_do_not_share_compiled_kernels(session):
    """lag(v,1) and lag(v,2) (and ntile(2) vs ntile(4)) bake their
    parameters into the compiled kernel closure; their plan signatures
    must differ or the compile cache would serve the wrong kernel."""
    t = pa.table({"k": [1, 1, 1, 1, 1], "v": [10.0, 20.0, 30.0, 40.0, 50.0]})
    df = session.create_dataframe(t)
    w = Window.partition_by("k").order_by(col("v").asc())
    out1 = df.with_column("l", lag(col("v"), 1).over(w)) \
        .collect(device=True).to_pandas().sort_values("v")
    out2 = df.with_column("l", lag(col("v"), 2).over(w)) \
        .collect(device=True).to_pandas().sort_values("v")
    assert out1.l.tolist()[1:] == [10.0, 20.0, 30.0, 40.0]
    assert out2.l.tolist()[2:] == [10.0, 20.0, 30.0]
    n2 = df.with_column("nt", ntile(2).over(w)).collect(device=True)
    n4 = df.with_column("nt", ntile(4).over(w)).collect(device=True)
    assert max(n2.column("nt").to_pylist()) == 2
    assert max(n4.column("nt").to_pylist()) == 4
