"""Datetime + hash/id expression tests (reference analogues:
datetimeExpressions / HashFunctions suites)."""
import datetime as pydt

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.expr.functions import (
    col, lit, year, month, dayofmonth, dayofweek, weekday, dayofyear,
    weekofyear, quarter, hour, minute, second, date_add, date_sub, datediff,
    add_months, last_day, months_between, unix_timestamp, from_unixtime,
    date_format, trunc, hash as fhash, xxhash64, spark_partition_id,
    monotonically_increasing_id)
from harness import assert_tpu_cpu_equal, data_gen


@pytest.fixture
def ddf(session, rng):
    t = data_gen(rng, 150, {"d": "date", "ts": "timestamp", "n": "int32"})
    return session.create_dataframe(t)


def test_extract_date_parts(ddf):
    out = assert_tpu_cpu_equal(ddf.select(
        col("d").alias("d"),
        year(col("d")).alias("y"),
        month(col("d")).alias("m"),
        dayofmonth(col("d")).alias("dom"),
        dayofweek(col("d")).alias("dow"),
        weekday(col("d")).alias("wd"),
        dayofyear(col("d")).alias("doy"),
        weekofyear(col("d")).alias("woy"),
        quarter(col("d")).alias("q"),
    ))
    # cross-check against Python's calendar
    for row in out.to_pylist():
        if row["d"] is None:
            continue
        d = row["d"]
        assert row["y"] == d.year and row["m"] == d.month
        assert row["dom"] == d.day
        assert row["dow"] == (d.isoweekday() % 7) + 1   # Sunday=1
        assert row["wd"] == d.weekday()
        assert row["doy"] == d.timetuple().tm_yday
        assert row["woy"] == d.isocalendar()[1]
        assert row["q"] == (d.month - 1) // 3 + 1


def test_extract_time_parts(ddf):
    out = assert_tpu_cpu_equal(ddf.select(
        col("ts").alias("ts"),
        hour(col("ts")).alias("h"),
        minute(col("ts")).alias("mi"),
        second(col("ts")).alias("s"),
    ))
    for row in out.to_pylist():
        if row["ts"] is None:
            continue
        t = row["ts"]
        assert row["h"] == t.hour and row["mi"] == t.minute \
            and row["s"] == t.second


def test_date_arithmetic(ddf):
    out = assert_tpu_cpu_equal(ddf.select(
        col("d").alias("d"),
        date_add(col("d"), lit(10)).alias("plus"),
        date_sub(col("d"), col("n") % lit(100)).alias("minus"),
        datediff(col("d"), lit(pydt.date(2000, 1, 1))).alias("diff"),
        add_months(col("d"), lit(13)).alias("am"),
        last_day(col("d")).alias("ld"),
    ))
    for row in out.to_pylist():
        if row["d"] is None:
            continue
        assert row["plus"] == row["d"] + pydt.timedelta(days=10)
        assert row["diff"] == (row["d"] - pydt.date(2000, 1, 1)).days
        nxt = row["ld"] + pydt.timedelta(days=1)
        assert nxt.day == 1   # last_day is end of month


def test_months_between_trunc(ddf):
    assert_tpu_cpu_equal(ddf.select(
        months_between(col("d"), lit(pydt.date(2010, 6, 15))).alias("mb"),
        trunc(col("d"), "year").alias("ty"),
        trunc(col("d"), "month").alias("tm"),
        trunc(col("d"), "week").alias("tw"),
        trunc(col("d"), "quarter").alias("tq"),
        unix_timestamp(col("ts")).alias("ut"),
    ))


def test_format_host_fallback(ddf):
    assert_tpu_cpu_equal(ddf.select(
        date_format(col("d"), "yyyy-MM-dd").alias("fmt"),
        from_unixtime(unix_timestamp(col("ts"))).alias("fu"),
    ))


def test_murmur3_host_device_agree(session, rng):
    t = data_gen(rng, 200, {
        "i32": "int32", "i64": "int64", "f64": "float64", "f32": "float32",
        "b": "bool", "s": "string", "d": "date", "ts": "timestamp",
    })
    df = session.create_dataframe(t)
    assert_tpu_cpu_equal(df.select(
        fhash(col("i32")).alias("h_i32"),
        fhash(col("i64")).alias("h_i64"),
        fhash(col("f64")).alias("h_f64"),
        fhash(col("f32")).alias("h_f32"),
        fhash(col("b")).alias("h_b"),
        fhash(col("s")).alias("h_s"),
        fhash(col("d"), col("ts")).alias("h_multi"),
        fhash(col("i32"), col("s"), col("f64")).alias("h_mixed"),
    ), ignore_order=False)


def test_murmur3_known_values(session):
    """Spot-check the scalar host reference implementation properties:
    seed folding, null-skip, and string tail handling."""
    df = session.create_dataframe(pa.table({
        "a": pa.array([1, 2, None], type=pa.int32()),
        "s": pa.array(["", "abc", "abcd"]),
    }))
    out = df.select(fhash(col("a")).alias("ha"),
                    fhash(col("s")).alias("hs")).collect(device=False)
    ha = out.column("ha").to_pylist()
    hs = out.column("hs").to_pylist()
    # null input leaves hash at seed-fold of nothing = initial seed path:
    # hash(null) must equal seed 42 folded over zero columns -> 42
    assert ha[2] == 42
    assert len(set(hs)) == 3          # distinct strings hash distinctly
    assert all(isinstance(v, int) for v in ha + hs)


def test_xxhash64(session, rng):
    t = data_gen(rng, 100, {"i64": "int64", "f64": "float64", "s": "string"})
    df = session.create_dataframe(t)
    assert_tpu_cpu_equal(df.select(
        xxhash64(col("i64")).alias("x1"),
        xxhash64(col("i64"), col("f64")).alias("x2"),
        xxhash64(col("s")).alias("xs"),       # device byte-matrix kernel
        xxhash64(col("s"), col("i64")).alias("xf"),  # fold across types
    ), ignore_order=False)


def test_xxhash64_string_device_bit_identical(session):
    """The device byte-matrix XXH64 kernel must match the scalar host
    implementation bit-for-bit across every phase boundary of the
    algorithm (stripe 32, word 8, chunk 4, tail bytes)."""
    import numpy as _np
    rng = _np.random.default_rng(7)
    strs = []
    for L in (0, 1, 3, 4, 5, 7, 8, 9, 15, 16, 31, 32, 33, 39, 40, 47, 48,
              63, 64, 65, 100):
        strs.append(bytes(rng.integers(32, 127, L,
                                       dtype=_np.uint8)).decode("ascii"))
    df = session.create_dataframe(pa.table({"s": strs}), num_partitions=2)
    out = assert_tpu_cpu_equal(
        df.select(col("s"), xxhash64(col("s")).alias("h")))
    from spark_rapids_tpu.expr.hashing import _xx_bytes_host
    got = {r["s"]: r["h"] for r in out.to_pylist()}
    for s in strs:
        expect = _xx_bytes_host(s.encode(), 42)
        if expect >= 2 ** 63:
            expect -= 2 ** 64
        assert got[s] == expect, (len(s), got[s], expect)


def test_ids_and_partitions(session):
    df = session.create_dataframe(
        pa.table({"x": np.arange(100, dtype=np.int64)}), num_partitions=4)
    out = df.select(
        col("x").alias("x"),
        spark_partition_id().alias("pid"),
        monotonically_increasing_id().alias("mid"),
    ).collect(device=True)
    pids = set(out.column("pid").to_pylist())
    assert pids <= {0, 1, 2, 3} and len(pids) > 1
    mids = out.column("mid").to_pylist()
    assert len(set(mids)) == 100      # globally unique
    # id encodes partition in high bits
    for pid, mid in zip(out.column("pid").to_pylist(), mids):
        assert mid >> 33 == pid
