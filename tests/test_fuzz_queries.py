"""Random-plan differential fuzzing.

Reference: the plugin's integration harness fuzzes data; its breadth comes
from running the whole Spark SQL test corpus differentially. This engine
owns both engines, so the analogue is PLAN fuzzing: compose random
pipelines (filter/project/agg/join/sort/limit/window/distinct/union) over
randomly generated tables and assert the device engine matches the host
engine exactly — operator-interaction corners (masked rows flowing into
joins, windows over aggregated output, unions of filtered branches...)
that the targeted suites don't enumerate.

Seeds are fixed: failures reproduce by seed.
"""
import numpy as np
import pytest

from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.expr.functions import (avg, col, count_star, lit,
                                             collect_list as F_collect_list,
                                             max as f_max, min as f_min,
                                             sum as f_sum)

from spark_rapids_tpu.columnar import dtypes as dtypes_mod
from harness import assert_tables_equal, data_gen

NUM_COLS = ["i32", "i64", "f64"]


def _table(rng, n):
    return data_gen(rng, n, {
        "k": ("int64", 0, 12),
        "i32": ("int32", -50, 50),
        "i64": "int64",
        "f64": "float64",
        "s": "string",
    }, null_prob=0.15)


def _rand_predicate(rng):
    c = col(str(rng.choice(NUM_COLS)))
    thresh = float(rng.uniform(-30, 30))
    op = rng.integers(0, 4)
    if op == 0:
        return c > lit(thresh)
    if op == 1:
        return c <= lit(thresh)
    if op == 2:
        return c.is_not_null() & (c < lit(thresh))
    return (c > lit(thresh - 40)) & (c < lit(thresh + 40))


def _apply_random_op(rng, df, other):
    """One random transformation; returns (df, grouped_flag)."""
    op = rng.integers(0, 11)
    if op == 8:   # round-3 string kernels: concat_ws / substring_index
        from spark_rapids_tpu.expr.functions import concat_ws, \
            substring_index
        if "s" not in df.columns:   # right/full joins drop the string col
            df = df.with_column("s", lit("zz-a"))
        which = rng.integers(0, 2)
        if which == 0:
            return df.with_column(
                "s", concat_ws(str(rng.choice([",", "-", ""])),
                               col("s"), col("s")))
        return df.with_column(
            "s", substring_index(col("s"), str(rng.choice(["a", "-", "e"])),
                                 int(rng.integers(-2, 3))))
    if op == 9:   # round-3 nested slice: collect_list -> explode round trip
        agg = df.group_by("k").agg(
            F_collect_list(col("i64")).alias("arr"),
            f_sum(col("f64")).alias("f64"))
        ex = agg.explode("arr", "i64", outer=bool(rng.integers(0, 2)))
        # restore the fuzz schema so later ops keep resolving
        return ex.select("k", col("i64"),
                         col("i64").cast(dtypes_mod.INT).alias("i32"),
                         col("f64"), lit("x").alias("s"))
    if op == 10:  # array scalar ops over a collected list
        from spark_rapids_tpu.expr.collections import (ArrayContains,
                                                       ArrayMax, Size)
        from spark_rapids_tpu.expr.functions import Column
        agg = df.group_by("k").agg(
            F_collect_list(col("i32")).alias("arr"),
            f_sum(col("f64")).alias("f64"))
        return agg.select(
            "k",
            Column(Size(col("arr").expr)).alias("i32"),
            Column(ArrayMax(col("arr").expr))
            .cast(dtypes_mod.LONG).alias("i64"),
            col("f64"), lit("y").alias("s"))
    if op == 0:
        return df.filter(_rand_predicate(rng))
    if op == 1:
        c = str(rng.choice(NUM_COLS))
        return df.with_column("expr", col(c) * lit(2.0) + lit(1.0))
    if op == 2:  # aggregate (terminal-ish: reduces columns)
        return df.group_by("k").agg(
            f_sum(col("f64")).alias("i64"),       # reuse names so later
            f_min(col("i64")).alias("i32"),       # ops still resolve
            count_star().alias("f64")) \
            .with_column("i32", col("i32").cast(dtypes_mod.INT)) \
            .with_column("f64", col("f64").cast(dtypes_mod.DOUBLE))
    if op == 3:  # join against the dimension table
        how = str(rng.choice(["inner", "left", "left_semi", "left_anti",
                              "right", "full"]))
        joined = df.join(other, on="k", how=how)
        keep = [c for c in df.columns] if how in ("left_semi", "left_anti") \
            else [c for c in joined.columns]
        out = joined.select(*keep)
        if how in ("right", "full"):
            # numeric columns may be null-padded now; keep pipeline simple
            out = out.select("k", *[c for c in NUM_COLS if c in out.columns])
        return out
    if op == 4:
        keys = [col("k").asc(), col(str(rng.choice(NUM_COLS))).desc()]
        return df.sort(*keys).limit(int(rng.integers(5, 60)))
    if op == 5:
        from spark_rapids_tpu.expr.window import Window, row_number
        # row_number over TIED order keys is nondeterministic (Spark too);
        # a total order over every column makes remaining ties full-row
        # duplicates, whose rn permutations are multiset-equal. A PRIOR
        # window's rn must not order this one (it's itself tie-dependent)
        first = str(rng.choice(NUM_COLS))
        orders = [col(first).asc()] + [
            col(c).asc() for c in df.columns if c not in (first, "rn")]
        w = Window.partition_by("k").order_by(*orders)
        return df.with_column("rn", row_number().over(w))
    if op == 6:
        return df.union(df.filter(_rand_predicate(rng)))
    return df.select("k", *NUM_COLS).distinct()


# Tier-1 keeps a 4-seed sweep (even/odd split still exercises AQE both
# ways); the long tail of seeds stays in the slow tier.
@pytest.mark.parametrize(
    "seed",
    [s if s < 4 else pytest.param(s, marks=pytest.mark.slow)
     for s in range(36)])
def test_random_pipeline_differential(seed):
    rng = np.random.default_rng(1000 + seed)
    sess = TpuSession({
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.shuffle.partitions": 3,
        "spark.rapids.tpu.shuffle.mode": "host",
        # exercise AQE half the time
        "spark.rapids.tpu.aqe.enabled": bool(seed % 2),
    })
    df = sess.create_dataframe(_table(rng, int(rng.integers(50, 400))),
                               num_partitions=int(rng.integers(1, 4)))
    other = sess.create_dataframe(
        _table(rng, 30).to_pandas()[["k", "f64"]].rename(
            columns={"f64": "dim_v"}), num_partitions=2)
    for _ in range(int(rng.integers(1, 4))):
        df = _apply_random_op(rng, df, other)
    dev = df.collect(device=True)
    cpu = df.collect(device=False)
    assert_tables_equal(dev, cpu, ignore_order=True, rel_tol=1e-9)
