"""Data-movement observatory: the runtime sync/transfer ledger (ISSUE 17).

Covers the acceptance contract:
- ledger attribution round-trip on TPC-H q1/q3/q6: every query's event
  log carries a v11 ``movement_summary`` whose per-site walls/bytes are
  internally consistent and agree (within tolerance) with the
  critical-path ``sync_wait`` + ``h2d_upload`` categories,
- device-residency tracking: an injected D2H->H2D bounce (download,
  host-side reshape, re-upload within one query) flags as a round trip,
- zero overhead when off: the funnel hooks compile down to a single
  module-constant check (bytecode pin, the utils/faults.py pattern) and
  the v11 record's payload is null,
- the static<->runtime join: every instrumented site maps onto
  srtpu-analyze sync-baseline keys and tools/diagnose.py ranks measured
  sites against them,
- the history sentinel's D2H-bytes gate and compare.py's transfer-byte
  regression gate read the summary's totals.

Process-wide ledger state is drained between modules by the conftest
``_drain_movement_state_per_module`` fixture (the retry/fallback drain
pattern), so nothing here leaks into later modules.
"""
import glob
import json
import os
import pathlib

import pyarrow as pa
import pytest

from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.utils import movement

PKG = pathlib.Path(__file__).resolve().parent.parent / "spark_rapids_tpu"

_TO_HOST = "spark_rapids_tpu/columnar/device.py::DeviceTable.to_host"
_UPLOAD = ("spark_rapids_tpu/exec/transitions.py"
           "::HostToDeviceExec._upload_retryable")


@pytest.fixture
def ledger():
    """A fresh process-wide ledger; cleared afterwards so the module
    leaves the default (off) state behind."""
    led = movement.configure_movement(RapidsConf(
        {"spark.rapids.tpu.movement.enabled": True}))
    yield led
    movement.reset_movement()


# ---------------------------------------------------------------------------
# zero overhead when off
# ---------------------------------------------------------------------------
def test_zero_overhead_when_off_bytecode_pin():
    """Off is the default; every funnel hook's FIRST action must be the
    module-constant is-None check — co_names[0] pins that no other
    global (let alone a conf lookup) is touched before the early return
    (the utils/faults.py cost-model pattern)."""
    movement.reset_movement()
    for fn in (movement.clock, movement.note_d2h, movement.note_h2d,
               movement.tag_lineage):
        assert fn.__code__.co_names[0] == "_LEDGER", fn.__name__
    assert movement.active() is None
    # and the disabled path records nothing / returns the null payload
    movement.note_d2h(_TO_HOST, 1024)
    movement.note_h2d(_UPLOAD, 1024)
    assert movement.clock() == 0.0
    assert movement.drain_ring() == []
    assert movement.query_summary(0) is None
    assert movement.movement_stats() == {}


def test_conf_off_means_no_ledger():
    assert movement.configure_movement(RapidsConf({})) is None
    assert movement.active() is None


# ---------------------------------------------------------------------------
# ledger mechanics: recording, lineage, round trips
# ---------------------------------------------------------------------------
def _device_table(n=64):
    from spark_rapids_tpu.columnar import DeviceTable, HostTable
    t = pa.table({"x": pa.array([float(i) for i in range(n)]),
                  "y": pa.array(list(range(n)), type=pa.int64())})
    return DeviceTable.from_host(HostTable.from_arrow(t), min_bucket=8)


def test_round_trip_bounce_detected(ledger):
    """Injected D2H->H2D bounce: download through the real to_host
    funnel, reshape on the host (lineage propagates through slice), then
    re-upload — the H2D funnel must flag a round trip and name the site
    the batch came from."""
    dt = _device_table()
    ht = dt.to_host()                      # real D2H funnel fires
    assert getattr(ht, "_tpu_lineage", None) is not None
    part = ht.slice(0, 16)                 # host-side reshape keeps lineage
    assert getattr(part, "_tpu_lineage", None) == ht._tpu_lineage
    movement.note_h2d(_UPLOAD, 1024, movement.clock(), origin=part)
    ring = movement.drain_ring()
    d2h = [e for e in ring if e["direction"] == "d2h"]
    h2d = [e for e in ring if e["direction"] == "h2d"]
    assert d2h and d2h[0]["site"] == _TO_HOST and d2h[0]["bytes"] > 0
    assert d2h[0]["blocking"] is True
    assert h2d[0]["round_trip"] is True
    assert h2d[0]["bounced_from"] == _TO_HOST
    summary = movement.query_summary(None)
    assert summary["totals"]["round_trips"] == 1
    up = [s for s in summary["sites"] if s["site"] == _UPLOAD]
    assert up and up[0]["round_trips"] == 1


def test_no_round_trip_without_lineage(ledger):
    """An upload of a host batch that never came off the device is NOT a
    round trip."""
    from spark_rapids_tpu.columnar import HostTable
    fresh = HostTable.from_arrow(pa.table({"x": [1.0, 2.0]}))
    movement.note_h2d(_UPLOAD, 64, origin=fresh)
    (entry,) = movement.drain_ring()
    assert entry["round_trip"] is False
    assert movement.query_summary(None)["totals"]["round_trips"] == 0


def test_callable_nbytes_and_call_site(ledger):
    """Byte counts may be lazy callables (nothing computed when off) and
    every entry carries the caller's file:line — who asked for the
    crossing, not where the funnel lives."""
    movement.note_d2h(_TO_HOST, lambda: 4096, movement.clock())
    (entry,) = movement.drain_ring()
    assert entry["bytes"] == 4096
    assert entry["call_site"] and "test_movement.py" in entry["call_site"]


def test_ring_is_bounded(ledger):
    led = movement.configure_movement(RapidsConf(
        {"spark.rapids.tpu.movement.enabled": True,
         "spark.rapids.tpu.movement.ringSize": 8}))
    for _ in range(50):
        movement.note_d2h(_TO_HOST, 4)
    assert len(led.drain_ring()) == 8           # oldest dropped
    assert led.totals()["d2h_count"] == 50      # aggregation stays exact


def test_every_site_maps_onto_static_baseline():
    """The static<->runtime join: every instrumented D2H site's baseline
    keys name a LIVE srtpu-analyze sync finding — either baselined debt
    (in the committed counts) or a deliberately suppressed sync-ok site.
    A key matching neither is stale and the diagnose ranking would join
    against nothing. H2D sites (deferred uploads) carry no sync-baseline
    keys by design. Since the async-first refactor drove hot sync debt
    to zero, every remaining crossing is a deliberate funnel: the join
    lands entirely on the SUPPRESSED side, and that side must be live."""
    from spark_rapids_tpu.tools.analyze import analyze_paths, load_baseline
    counts = (load_baseline() or {}).get("counts", {})
    report = analyze_paths([str(PKG)], checks=["sync"])
    suppressed = {f.key() for f in report.suppressed}
    joined = 0
    for site, info in movement.SITES.items():
        assert info.direction in ("d2h", "h2d")
        assert info.hint
        if info.direction == "h2d":
            assert info.baseline_keys == ()
            continue
        assert info.baseline_keys, site
        for key in info.baseline_keys:
            path, rule, _sym = key.split("::")
            assert path == site.split("::")[0]
            assert rule.startswith("sync-")
            assert key in counts or key in suppressed, f"stale key {key}"
            if key in suppressed:
                joined += 1
    # the deliberate-funnel side of the join is live (hot debt is zero,
    # so nothing joins through counts anymore — that was PR-17's world)
    assert joined >= 2


# ---------------------------------------------------------------------------
# TPC-H end to end: v11 records, attribution, critical-path consistency
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tpch_app(tmp_path_factory):
    """q1/q3/q6 under the observatory + tracer + event log, replayed."""
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.tools import tpch
    from spark_rapids_tpu.tools.eventlog import load_event_log
    logdir = str(tmp_path_factory.mktemp("movement_evl"))
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.dir": logdir,
        "spark.rapids.tpu.movement.enabled": True,
        "spark.rapids.tpu.trace.enabled": True,
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.shuffle.partitions": 2,
    })
    tables = tpch.gen_all(0, tiny=True)
    dfs = tpch.build_dataframes(sess, tables, num_partitions=2)
    for name in ("q1", "q3", "q6"):
        getattr(tpch, name)(dfs).collect(device=True)
    sess.close()
    movement.reset_movement()
    (path,) = glob.glob(os.path.join(logdir, "*.jsonl"))
    records = [json.loads(line) for line in open(path, encoding="utf-8")]
    return load_event_log(path), records


def test_tpch_every_query_carries_v11_movement_summary(tpch_app):
    app, _records = tpch_app
    assert len(app.queries) == 3
    for q in app.queries.values():
        mv = q.movement_summary
        assert mv is not None, f"q{q.query_id} movement_summary missing"
        t = mv["totals"]
        assert t["d2h_bytes"] > 0 and t["d2h_count"] > 0
        assert t["h2d_bytes"] > 0 and t["h2d_count"] > 0
        assert t["blocking_count"] > 0
        assert mv["sites"] and mv["operators"]
        # attribution: sites are the known funnels, operators are real
        # plan operators (the node-context attribution)
        for s in mv["sites"]:
            assert s["site"] in movement.SITES, s["site"]
        assert any(o["operator"] != "<none>" for o in mv["operators"])


def test_tpch_summary_internal_consistency(tpch_app):
    """Per-site rows must sum back to the totals exactly — the ledger
    folds each crossing into both under one lock."""
    app, _records = tpch_app
    for q in app.queries.values():
        mv = q.movement_summary
        t = mv["totals"]
        for direction in ("d2h", "h2d"):
            rows = [s for s in mv["sites"]
                    if s["direction"] == direction]
            assert sum(s["bytes"] for s in rows) == t[f"{direction}_bytes"]
            assert sum(s["count"] for s in rows) == t[f"{direction}_count"]
        assert sum(s["wall_s"] for s in mv["sites"]) \
            == pytest.approx(t["wall_s"], abs=1e-9)
        assert sum(o["bytes"] for o in mv["operators"]) \
            == t["d2h_bytes"] + t["h2d_bytes"]


def test_tpch_walls_consistent_with_critical_path(tpch_app):
    """The measured ledger walls and the critical path's sync_wait +
    h2d_upload categories watch the same crossings from two sides (the
    ledger times the raw transfer inside the funnel, the tracer spans
    wrap it), so per query they must agree within a generous band —
    catching gross drift (a funnel that stopped reporting, a span that
    moved off the transfer) without flaking on scheduler noise."""
    app, _records = tpch_app
    checked = 0
    for q in app.queries.values():
        cp = q.critical_path or {}
        cats = cp.get("categories_s") or {}
        cp_both = (cats.get("sync_wait", 0.0) or 0.0) \
            + (cats.get("h2d_upload", 0.0) or 0.0)
        if cp_both <= 0:
            continue
        mv_wall = sum(s["wall_s"] for s in q.movement_summary["sites"]
                      if s["site"] in (_TO_HOST, _UPLOAD))
        # the ledger region sits strictly inside the traced span, so it
        # can't exceed the span time by more than noise; and the span
        # can't dwarf the transfer it wraps
        assert mv_wall <= cp_both * 5 + 0.25
        assert cp_both <= max(q.movement_summary["totals"]["wall_s"],
                              mv_wall) * 20 + 0.25
        checked += 1
    assert checked >= 1   # tracing was on: at least one query has both


def test_v11_record_shape(tpch_app):
    """Pin the populated movement_summary record shape (the null-payload
    variant is pinned in tests/test_observability.py)."""
    _app, records = tpch_app
    mvs = [r for r in records if r["event"] == "movement_summary"]
    assert len(mvs) == 3
    for rec in mvs:
        assert set(rec) == {"event", "query_id", "ts", "movement"}
        mv = rec["movement"]
        assert set(mv) == {"totals", "sites", "operators"}
        assert set(mv["totals"]) == set(movement.TOTAL_KEYS) | {"wall_s"}
        for s in mv["sites"]:
            assert set(s) == {"site", "direction", "count", "bytes",
                              "wall_s", "blocking_count", "round_trips"}
        for o in mv["operators"]:
            assert set(o) == {"operator", "direction", "count", "bytes",
                              "wall_s", "blocking_count", "round_trips"}
    # per-query stats carry the movement gauges the sentinel's
    # D2H-bytes gate and statusd /metrics read
    ends = [r for r in records if r["event"] == "query_end"
            and not r.get("error")]
    assert ends and all(
        r["stats"].get("movement_d2h_bytes", 0) > 0 for r in ends)


def test_diagnose_measured_movement_ranking(tpch_app):
    """tools/diagnose.py joins the measured sites onto the srtpu-analyze
    baseline keys and renders the ranked data-movement section next to
    the static sync_debt inventory."""
    from spark_rapids_tpu.tools.diagnose import diagnose_app
    app, _records = tpch_app
    report = diagnose_app(app)
    obj = json.loads(report.to_json())
    rows = obj["measured_movement"]
    assert rows, "no measured movement rows"
    for row in rows:
        assert row["site"] in movement.SITES
        assert row["status"] in ("baselined sync debt",
                                 "suppressed (deliberate sync)",
                                 "deferred transfer")
        assert row["suggestion"]
    # ranked heaviest-wall first
    walls = [r["wall_s"] for r in rows]
    assert walls == sorted(walls, reverse=True)
    # the static inventory renders alongside, not instead
    assert "sync_debt" in obj
    text = report.summary()
    assert "data movement (measured, movement ledger)" in text
    assert "static sync-site debt" in text


def test_health_check_warns_on_sync_wait_fraction(tmp_path):
    """A query whose critical path is mostly sync_wait gets a health
    warning naming the heaviest measured site (v11)."""
    from spark_rapids_tpu.tools.eventlog import load_event_log
    recs = [
        {"event": "app_start", "app_id": "mv", "schema_version": 12,
         "ts": 0.0, "conf": {}},
        {"event": "query_start", "query_id": 0, "ts": 1.0, "plan": "p",
         "trace_id": "t"},
        {"event": "movement_summary", "query_id": 0, "ts": 2.0,
         "movement": {
             "totals": {"d2h_bytes": 4096, "h2d_bytes": 0, "d2h_count": 2,
                        "h2d_count": 0, "blocking_count": 2,
                        "deferred_count": 0, "round_trips": 2,
                        "wall_s": 0.5},
             "sites": [{"site": _TO_HOST, "direction": "d2h", "count": 2,
                        "bytes": 4096, "wall_s": 0.5, "blocking_count": 2,
                        "round_trips": 2}],
             "operators": []}},
        {"event": "query_end", "query_id": 0, "ts": 2.0, "wall_s": 1.0,
         "final_plan": "p", "aqe_events": [], "spill_count": {},
         "semaphore_wait_s": 0.0, "stats": {}, "trace_id": "t",
         "critical_path": {"sync_wait_frac": 0.6,
                           "categories_s": {"sync_wait": 0.6},
                           "fractions": {"sync_wait": 0.6},
                           "total_s": 1.0, "coverage": 1.0}},
        {"event": "app_end", "ts": 3.0},
    ]
    path = tmp_path / "mv.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    app = load_event_log(str(path))
    warnings = app.health_check()
    sync_warns = [w for w in warnings if "sync wait is 60%" in w]
    assert sync_warns and _TO_HOST in sync_warns[0]
    assert any("round trip" in w for w in warnings)


# ---------------------------------------------------------------------------
# regression gates: sentinel D2H bytes + compare.py transfer bytes
# ---------------------------------------------------------------------------
def test_compare_movement_delta_gate():
    from spark_rapids_tpu.tools.compare import movement_delta
    base = {"d2h_bytes": 10 << 20, "h2d_bytes": 1 << 20, "round_trips": 0}
    # +5% under the 1 MiB floor: clean
    small = dict(base, d2h_bytes=base["d2h_bytes"] + (1 << 19))
    _deltas, flagged = movement_delta(base, small)
    assert "d2h_bytes" not in flagged
    # +50% and past the floor: flagged, and new round trips always flag
    big = dict(base, d2h_bytes=15 << 20, round_trips=3)
    deltas, flagged = movement_delta(base, big)
    assert deltas["d2h_bytes"] == 5 << 20
    assert "d2h_bytes" in flagged and "round_trips" in flagged
    # missing on either side (ledger off): nothing to gate
    assert movement_delta(None, big) == ({}, [])


def test_sentinel_d2h_bytes_gate(tmp_path):
    """Two synthetic runs whose only difference is movement_d2h_bytes
    growth past the 10% + 1 MiB gate: the sentinel flags d2h_bytes."""
    from spark_rapids_tpu.tools.history import (HistoryStore, run_sentinel,
                                                D2H_BYTES_KEY)

    def _log(path, app_id, d2h):
        recs = [
            {"event": "app_start", "app_id": app_id, "schema_version": 12,
             "ts": 0.0, "conf": {}},
            {"event": "query_start", "query_id": 0, "ts": 1.0,
             "plan": "p", "trace_id": "t"},
            {"event": "query_end", "query_id": 0, "ts": 2.0,
             "wall_s": 1.0, "final_plan": "p", "aqe_events": [],
             "spill_count": 0, "semaphore_wait_s": 0.0,
             "stats": {D2H_BYTES_KEY: d2h}, "trace_id": "t",
             "critical_path": None},
            {"event": "app_end", "ts": 3.0},
        ]
        path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        return str(path)

    store = HistoryStore(str(tmp_path / "store"))
    store.append_run(_log(tmp_path / "a.jsonl", "run_a", 10 << 20),
                     app_id="run_a")
    store.append_run(_log(tmp_path / "b.jsonl", "run_b", 20 << 20),
                     app_id="run_b")
    verdict = run_sentinel(store, candidate="run_b", baseline="run_a")
    assert not verdict["ok"]
    assert "d2h_bytes" in verdict["flags"]
    assert verdict["d2h_bytes_regressions"][0]["delta"] == 10 << 20
    # same bytes: clean
    store.append_run(_log(tmp_path / "c.jsonl", "run_c", 10 << 20),
                     app_id="run_c")
    verdict = run_sentinel(store, candidate="run_c", baseline="run_a")
    assert verdict["ok"] and "d2h_bytes" not in verdict["flags"]
