"""Out-of-core execution tests (reference: GpuSortExec.scala OutOfCoreSort,
aggregate.scala merge passes, AbstractGpuJoinIterator sub-partitioning):
operators must complete correctly when the device pool is smaller than the
data, with buffers migrating through the spill tiers."""
import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.device import DeviceTable
from spark_rapids_tpu.columnar.host import HostColumn, HostTable
from spark_rapids_tpu.memory.catalog import BufferCatalog, set_catalog
from spark_rapids_tpu.plan.schema import Field, Schema


@pytest.fixture
def small_catalog():
    """Device pool far below the test data size -> forced spills."""
    cat = BufferCatalog(device_limit=60_000, host_limit=40_000)
    set_catalog(cat)
    yield cat
    set_catalog(None)


class _Source:
    def __init__(self, batches, schema):
        self.batches = batches
        self.schema = schema
        self.num_partitions = 1
        self.children = ()

    def execute_columnar(self, pidx):
        yield from self.batches


def _num_batches(n_rows, n_batches, seed=0, extra_cols=0):
    rng = np.random.default_rng(seed)
    per = n_rows // n_batches
    batches, all_a, all_b = [], [], []
    for i in range(n_batches):
        a = rng.integers(-500, 500, per).astype(np.int64)
        b = rng.uniform(-5, 5, per)
        all_a.append(a)
        all_b.append(b)
        cols = [HostColumn(dt.LONG, a), HostColumn(dt.DOUBLE, b)]
        names = ["a", "b"]
        t = HostTable(names, cols)
        batches.append(DeviceTable.from_host(t, min_bucket=8))
    schema = Schema([Field("a", dt.LONG, True), Field("b", dt.DOUBLE, True)])
    return batches, schema, np.concatenate(all_a), np.concatenate(all_b)


def test_out_of_core_sort_spills(small_catalog):
    from spark_rapids_tpu.exec.sort import TpuSortExec
    from spark_rapids_tpu.expr.functions import SortOrder, col
    batches, schema, a, b = _num_batches(6000, 10)
    src = _Source(batches, schema)
    orders = [SortOrder(col("a").expr, True), SortOrder(col("b").expr, True)]
    s = TpuSortExec(src, orders, min_bucket=8, batch_bytes=20_000)
    frames = [HostTable.to_arrow(x.to_host()).to_pandas()
              for x in s.execute_columnar(0)]
    got = pd.concat(frames, ignore_index=True)
    exp = pd.DataFrame({"a": a, "b": b}).sort_values(
        ["a", "b"], kind="stable").reset_index(drop=True)
    assert len(got) == len(exp)
    assert (got["a"].values == exp["a"].values).all()
    assert np.allclose(got["b"].values, exp["b"].values)
    spills = small_catalog.stats()["spill_count"]
    assert sum(spills.values()) > 0, spills


def test_out_of_core_grace_join(small_catalog):
    from spark_rapids_tpu.exec.joins import TpuShuffledHashJoinExec
    rng = np.random.default_rng(1)
    nl, nr = 3000, 2000
    lk = rng.integers(0, 200, nl).astype(np.int64)
    lv = rng.uniform(0, 1, nl)
    rk = rng.integers(0, 200, nr).astype(np.int64)
    rv = rng.uniform(0, 1, nr)
    lbatches = [DeviceTable.from_host(HostTable(
        ["k", "lv"], [HostColumn(dt.LONG, lk[i::3]),
                      HostColumn(dt.DOUBLE, lv[i::3])]), min_bucket=8)
        for i in range(3)]
    rbatches = [DeviceTable.from_host(HostTable(
        ["k", "rv"], [HostColumn(dt.LONG, rk[i::2]),
                      HostColumn(dt.DOUBLE, rv[i::2])]), min_bucket=8)
        for i in range(2)]
    lschema = Schema([Field("k", dt.LONG, True), Field("lv", dt.DOUBLE, True)])
    rschema = Schema([Field("k", dt.LONG, True), Field("rv", dt.DOUBLE, True)])
    left = _Source(lbatches, lschema)
    right = _Source(rbatches, rschema)
    # batch_bytes below the build size -> grace sub-partitioned join
    j = TpuShuffledHashJoinExec(left, right, ["k"], ["k"], "inner", None,
                                merge_keys=True, min_bucket=8,
                                batch_bytes=8_000)
    frames = [HostTable.to_arrow(x.to_host()).to_pandas()
              for x in j.execute_columnar(0)]
    got = pd.concat(frames, ignore_index=True).sort_values(
        ["k", "lv", "rv"]).reset_index(drop=True)
    exp = pd.merge(pd.DataFrame({"k": lk, "lv": lv}),
                   pd.DataFrame({"k": rk, "rv": rv}), on="k").sort_values(
        ["k", "lv", "rv"]).reset_index(drop=True)
    assert len(got) == len(exp)
    assert np.allclose(got["lv"].values, exp["lv"].values)
    assert np.allclose(got["rv"].values, exp["rv"].values)


def test_out_of_core_left_join_grace(small_catalog):
    from spark_rapids_tpu.exec.joins import TpuShuffledHashJoinExec
    rng = np.random.default_rng(5)
    nl, nr = 2000, 1500
    lk = rng.integers(0, 400, nl).astype(np.int64)  # some keys unmatched
    rk = rng.integers(0, 200, nr).astype(np.int64)
    lv = rng.uniform(0, 1, nl)
    rv = rng.uniform(0, 1, nr)
    lschema = Schema([Field("k", dt.LONG, True), Field("lv", dt.DOUBLE, True)])
    rschema = Schema([Field("k", dt.LONG, True), Field("rv", dt.DOUBLE, True)])
    left = _Source([DeviceTable.from_host(HostTable(
        ["k", "lv"], [HostColumn(dt.LONG, lk), HostColumn(dt.DOUBLE, lv)]),
        min_bucket=8)], lschema)
    right = _Source([DeviceTable.from_host(HostTable(
        ["k", "rv"], [HostColumn(dt.LONG, rk), HostColumn(dt.DOUBLE, rv)]),
        min_bucket=8)], rschema)
    j = TpuShuffledHashJoinExec(left, right, ["k"], ["k"], "left", None,
                                merge_keys=True, min_bucket=8,
                                batch_bytes=6_000)
    frames = [HostTable.to_arrow(x.to_host()).to_pandas()
              for x in j.execute_columnar(0)]
    got = pd.concat(frames, ignore_index=True)
    exp = pd.merge(pd.DataFrame({"k": lk, "lv": lv}),
                   pd.DataFrame({"k": rk, "rv": rv}), on="k", how="left")
    assert len(got) == len(exp)
    assert np.isclose(got["lv"].sum(), exp["lv"].sum())
    assert np.isclose(got["rv"].fillna(0).sum(), exp["rv"].fillna(0).sum())


def test_windowed_expand_bounds_output(small_catalog):
    """High-multiplicity join: gather output exceeds the budget and must be
    emitted in probe windows rather than one oversized batch."""
    from spark_rapids_tpu.exec.joins import TpuShuffledHashJoinExec
    nl, nr = 600, 400
    lk = np.zeros(nl, dtype=np.int64)  # every pair matches: 240k rows out
    rk = np.zeros(nr, dtype=np.int64)
    lv = np.arange(nl, dtype=np.float64)
    rv = np.arange(nr, dtype=np.float64)
    lschema = Schema([Field("k", dt.LONG, True), Field("lv", dt.DOUBLE, True)])
    rschema = Schema([Field("k", dt.LONG, True), Field("rv", dt.DOUBLE, True)])
    left = _Source([DeviceTable.from_host(HostTable(
        ["k", "lv"], [HostColumn(dt.LONG, lk), HostColumn(dt.DOUBLE, lv)]),
        min_bucket=8)], lschema)
    right = _Source([DeviceTable.from_host(HostTable(
        ["k", "rv"], [HostColumn(dt.LONG, rk), HostColumn(dt.DOUBLE, rv)]),
        min_bucket=8)], rschema)
    j = TpuShuffledHashJoinExec(left, right, ["k"], ["k"], "inner", None,
                                merge_keys=True, min_bucket=8,
                                batch_bytes=500_000)
    max_out = j._max_out_rows()
    assert max_out < nl * nr
    total = 0
    nbatches = 0
    for x in j.execute_columnar(0):
        n = int(x.num_rows)
        assert x.capacity <= max(max_out * 2, 8), \
            f"batch capacity {x.capacity} blew past budget {max_out}"
        total += n
        nbatches += 1
    assert total == nl * nr
    assert nbatches > 1


def test_aggregate_merge_state_bounded(small_catalog):
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.expr.aggregates import Sum
    from spark_rapids_tpu.expr.base import AttributeReference
    from spark_rapids_tpu.plan.physical import AggSpec
    rng = np.random.default_rng(2)
    batches = []
    per = 512
    nb = 12
    ks, vs = [], []
    for i in range(nb):
        k = rng.integers(0, 40, per).astype(np.int64)
        v = rng.uniform(0, 1, per)
        ks.append(k)
        vs.append(v)
        batches.append(DeviceTable.from_host(HostTable(
            ["k", "_agg0_in0"], [HostColumn(dt.LONG, k),
                                 HostColumn(dt.DOUBLE, v)]), min_bucket=8))
    schema = Schema([Field("k", dt.LONG, True),
                     Field("_agg0_in0", dt.DOUBLE, True)])
    src = _Source(batches, schema)
    spec = AggSpec("_agg0", Sum(AttributeReference("_agg0_in0", dt.DOUBLE)))
    agg = TpuHashAggregateExec(src, ["k"], [spec], "partial")
    outs = list(agg.execute_columnar(0))
    assert len(outs) == 1
    out = outs[0]
    # running state shrank to the group bucket, not sum of batch capacities
    assert out.capacity < per * nb
    h = out.to_host()
    got = pd.DataFrame({"k": h.column("k").values,
                        "s": h.column("_agg0_sum").values}) \
        .sort_values("k").reset_index(drop=True)
    exp = pd.DataFrame({"k": np.concatenate(ks),
                        "v": np.concatenate(vs)}).groupby("k")["v"].sum() \
        .reset_index().rename(columns={"v": "s"})
    assert np.allclose(got["s"].values, exp["s"].values)


def test_tpch_query_under_memory_pressure(small_catalog):
    """End-to-end: a TPC-H query completes with the pool below data size."""
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.tools import tpch
    sess = TpuSession({"spark.rapids.tpu.batchRowsMinBucket": 8,
                       "spark.rapids.sql.batchSizeBytes": 50_000})
    lineitem = tpch.gen_lineitem(0, seed=0, rows=4000)
    df = sess.create_dataframe(lineitem, num_partitions=4)
    t = {"lineitem": df}
    got = tpch.q1(t).collect(device=True).to_pandas()
    exp = tpch.q1(t).collect(device=False).to_pandas()
    assert len(got) == len(exp)
    for c in got.columns:
        if got[c].dtype.kind in "fi":
            assert np.allclose(got[c].values.astype(float),
                               exp[c].values.astype(float)), c
        else:
            assert (got[c].values == exp[c].values).all(), c


# ---------------------------------------------------------------------------
# Runtime OOM -> spill -> retry (reference: DeviceMemoryEventHandler.scala:33)
# ---------------------------------------------------------------------------
def _spillable_tables(cat, n=4, rows=512):
    rng = np.random.default_rng(0)
    handles = []
    for i in range(n):
        ht = HostTable(["a"], [HostColumn(dt.DOUBLE, rng.normal(size=rows))])
        handles.append(cat.register(DeviceTable.from_host(ht, 64)))
    return handles


def test_runtime_oom_spills_and_retries():
    """A RESOURCE_EXHAUSTED from the runtime triggers one synchronous
    spill + retry at the jit chokepoint — the query completes."""
    from spark_rapids_tpu.memory.catalog import BufferCatalog, set_catalog
    from spark_rapids_tpu.utils.compile_cache import oom_retry
    cat = BufferCatalog(device_limit=10**9, host_limit=10**9)
    set_catalog(cat)
    try:
        handles = _spillable_tables(cat)
        calls = {"n": 0}

        def flaky(x):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: Out of memory while trying to "
                    "allocate 123456 bytes.")
            return x + 1

        out = oom_retry(flaky)(41)
        assert out == 42 and calls["n"] == 2
        assert cat.oom_events == 1
        assert sum(cat.spill_count.values()) > 0, cat.spill_count
        # spilled buffers restore transparently on next access
        assert handles[0].get().num_rows == 512
    finally:
        set_catalog(None)


def test_runtime_oom_second_failure_dumps_diagnostics():
    from spark_rapids_tpu.memory.catalog import BufferCatalog, set_catalog
    from spark_rapids_tpu.utils.compile_cache import oom_retry
    cat = BufferCatalog(device_limit=10**9, host_limit=10**9)
    set_catalog(cat)
    try:
        _spillable_tables(cat, n=2)

        def always_oom(_):
            raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory")

        with pytest.raises(RuntimeError, match="catalog state"):
            oom_retry(always_oom)(0)
        # non-OOM errors pass through untouched
        def boom(_):
            raise ValueError("unrelated")
        with pytest.raises(ValueError, match="unrelated"):
            oom_retry(boom)(0)
    finally:
        set_catalog(None)
