"""Planner-integrated device (ICI) exchange tests — the accelerated shuffle
tier reached through a real query plan (reference analogue: using
RapidsShuffleManager instead of default Spark shuffle, SURVEY §2.7)."""
import jax
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.session import TpuSession


def _mesh_session(**extra):
    from spark_rapids_tpu.parallel.mesh import virtual_cpu_mesh
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    sess = TpuSession({
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.shuffle.partitions": 4,
        # these tests assert the STATIC planner lowering (exchange nodes in
        # the plan tree); AQE replaces exchanges with materialized stages
        "spark.rapids.tpu.aqe.enabled": False,
        **extra,
    })
    sess.attach_mesh(virtual_cpu_mesh(8))
    return sess


def _find(plan, cls):
    if isinstance(plan, cls):
        return plan
    for c in plan.children:
        r = _find(c, cls)
        if r is not None:
            return r
    return None


def test_ici_exchange_quota_rightsized():
    """Quota from a count pass shrinks the exchange intermediate (weak #4)."""
    from jax.sharding import Mesh
    from spark_rapids_tpu.columnar.device import DeviceTable
    from spark_rapids_tpu.columnar.host import HostColumn, HostTable
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.shuffle.ici import (ici_all_to_all_exchange,
                                              shard_table, unshard_table)
    devices = np.array(jax.devices()[:8])
    if len(devices) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = Mesh(devices, ("dp",))
    rng = np.random.default_rng(7)
    k = rng.integers(0, 50, 512).astype(np.int64)
    v = rng.uniform(0, 1, 512)
    t = HostTable(["k", "v"], [HostColumn(dt.LONG, k),
                               HostColumn(dt.DOUBLE, v)])
    dtab = DeviceTable.from_host(t, min_bucket=8, capacity=512)
    sharded = shard_table(dtab, mesh)
    out = ici_all_to_all_exchange(sharded, ["k"], mesh, quota=32)
    # right-sized: per-shard capacity is n*quota, not n*local_capacity
    assert out.capacity == 8 * 8 * 32
    assert int(out.num_rows) == 512
    merged = unshard_table(out).to_host()
    got = sorted(zip(merged.column("k").values.tolist(),
                     np.round(merged.column("v").values, 9).tolist()))
    exp = sorted(zip(k.tolist(), np.round(v, 9).tolist()))
    assert got == exp


def test_planner_groupby_uses_device_exchange():
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    sess = _mesh_session()
    rng = np.random.default_rng(0)
    t = pa.table({"k": rng.integers(0, 20, 400),
                  "v": rng.uniform(0, 10, 400)})
    df = sess.create_dataframe(t, num_partitions=3)
    from spark_rapids_tpu.expr.functions import col, count, sum as fsum
    q = df.group_by("k").agg(fsum(col("v")).alias("s"),
                             count(col("v")).alias("n"))
    plan = sess._physical(q.logical, device=True)
    assert _find(plan, TpuShuffleExchangeExec) is not None, plan.tree_string()
    got = q.collect(device=True).to_pandas().sort_values("k").reset_index(drop=True)
    exp = q.collect(device=False).to_pandas().sort_values("k").reset_index(drop=True)
    assert np.allclose(got["s"], exp["s"])
    assert (got["n"] == exp["n"]).all()
    assert (got["k"] == exp["k"]).all()


def test_planner_groupby_string_keys_device_exchange():
    """String group keys exchange via the width-independent device hash."""
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    sess = _mesh_session()
    rng = np.random.default_rng(1)
    keys = np.array(["alpha", "beta", "gamma", "d", "epsilon-long-key", ""])
    t = pa.table({"k": keys[rng.integers(0, len(keys), 300)],
                  "v": rng.uniform(0, 5, 300)})
    df = sess.create_dataframe(t, num_partitions=2)
    from spark_rapids_tpu.expr.functions import col, sum as fsum
    q = df.group_by("k").agg(fsum(col("v")).alias("s"))
    plan = sess._physical(q.logical, device=True)
    assert _find(plan, TpuShuffleExchangeExec) is not None, plan.tree_string()
    got = q.collect(device=True).to_pandas().sort_values("k").reset_index(drop=True)
    exp = q.collect(device=False).to_pandas().sort_values("k").reset_index(drop=True)
    assert (got["k"] == exp["k"]).all()
    assert np.allclose(got["s"], exp["s"])


def test_planner_join_uses_device_exchange():
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    sess = _mesh_session()
    rng = np.random.default_rng(2)
    left = pa.table({"k": rng.integers(0, 30, 250),
                     "a": rng.uniform(0, 1, 250)})
    right = pa.table({"k": np.arange(30), "b": rng.uniform(0, 1, 30)})
    # disable broadcast so the join plans as shuffled-hash with exchanges
    ldf = sess.create_dataframe(left, num_partitions=3)
    rdf = sess.create_dataframe(right, num_partitions=2)
    sess.set_conf("spark.rapids.tpu.autoBroadcastJoinThreshold", -1)
    try:
        q = ldf.join(rdf, on="k", how="inner")
        plan = sess._physical(q.logical, device=True)
        assert _find(plan, TpuShuffleExchangeExec) is not None, \
            plan.tree_string()
        got = q.collect(device=True).to_pandas() \
            .sort_values(["k", "a"]).reset_index(drop=True)
        exp = q.collect(device=False).to_pandas() \
            .sort_values(["k", "a"]).reset_index(drop=True)
        assert len(got) == len(exp)
        assert np.allclose(got["a"], exp["a"])
        assert np.allclose(got["b"], exp["b"])
    finally:
        sess.set_conf("spark.rapids.tpu.autoBroadcastJoinThreshold", 10 * 1024 * 1024)


def test_host_mode_keeps_host_exchange():
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    sess = _mesh_session(**{"spark.rapids.tpu.shuffle.mode": "host"})
    rng = np.random.default_rng(3)
    t = pa.table({"k": rng.integers(0, 20, 100), "v": rng.uniform(0, 1, 100)})
    df = sess.create_dataframe(t, num_partitions=2)
    from spark_rapids_tpu.expr.functions import col, sum as fsum
    q = df.group_by("k").agg(fsum(col("v")).alias("s"))
    plan = sess._physical(q.logical, device=True)
    assert _find(plan, TpuShuffleExchangeExec) is None


def test_exchange_streams_chunks_out_of_core():
    """The device exchange must NOT stage its whole input at once: child
    batches stream through the all-to-all in bounded chunks, and finished
    output shards spill when the device budget tightens (round-2 weak #3;
    reference: per-batch streaming, GpuShuffleExchangeExecBase.scala:146)."""
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.expr.functions import col, sum as fsum
    from spark_rapids_tpu.memory.catalog import BufferCatalog, set_catalog

    sess = _mesh_session(**{
        # tiny chunks: a 4-partition input becomes multiple chunks/shard
        "spark.rapids.tpu.shuffle.exchangeChunkRows": 512,
    })
    rng = np.random.default_rng(11)
    nrows = 8000
    t = pa.table({"k": rng.integers(0, 40, nrows).astype("int64"),
                  "v": rng.uniform(0, 10, nrows)})
    df = sess.create_dataframe(t, num_partitions=4)
    q = df.group_by("k").agg(fsum(col("v")).alias("s"))

    # device pool far below the ~128KB input -> output shards must spill
    cat = BufferCatalog(device_limit=100_000, host_limit=60_000)
    set_catalog(cat)
    try:
        plan = sess._physical(q.logical, device=True)
        ex = _find(plan, TpuShuffleExchangeExec)
        assert ex is not None, plan.tree_string()
        got = plan.collect().to_arrow().to_pandas() \
            .sort_values("k").reset_index(drop=True)
        # streamed: at least one partition saw more than one chunk
        assert any(len(s) > 1 for s in ex._shards), \
            [len(s) for s in ex._shards]
        assert sum(cat.spill_count.values()) > 0, cat.spill_count
    finally:
        set_catalog(None)
    exp = t.to_pandas().groupby("k").v.sum().reset_index() \
        .sort_values("k").reset_index(drop=True)
    assert (got["k"] == exp["k"]).all()
    assert np.allclose(got["s"], exp["v"])


def test_hash_strategies_over_mesh():
    """The TPU-default (auto off-CPU) hash group-by and hash join compile
    and run through the ICI mesh exchange under shard_map — the exact
    program shape the real-chip bench uses."""
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.expr.functions import col, count, sum as fsum
    from spark_rapids_tpu.parallel.mesh import virtual_cpu_mesh

    rng = np.random.default_rng(0)
    sess = TpuSession({"spark.rapids.tpu.batchRowsMinBucket": 8,
                       "spark.rapids.tpu.shuffle.partitions": 4,
                       "spark.rapids.tpu.groupby.strategy": "hash",
                       "spark.rapids.tpu.join.strategy": "hash",
                       "spark.rapids.tpu.autoBroadcastJoinThreshold": -1})
    sess.attach_mesh(virtual_cpu_mesh(8))
    n = 2048
    t = pa.table({"k": rng.integers(0, 16, n).astype(np.int64),
                  "v": rng.uniform(0, 10, n)})
    df = sess.create_dataframe(t, num_partitions=2)
    q = df.group_by("k").agg(fsum(col("v")).alias("s"),
                             count(col("v")).alias("n"))
    got = q.collect(device=True)
    assert got.num_rows == 16
    total = sum(got.column("s").to_pylist())
    expected = float(np.sum(t.column("v").to_numpy()))
    assert abs(total - expected) / expected < 1e-9
    dim = sess.create_dataframe(
        pa.table({"k": np.arange(16, dtype=np.int64),
                  "w": rng.uniform(0, 1, 16)}), num_partitions=2)
    jd = df.join(dim, on="k", how="inner").collect(device=True)
    assert jd.num_rows == n


def test_ici_exchange_skew_record_matches_partition_counts():
    """v7 skew telemetry parity (device tier): the shuffle_skew() record
    an exchange exposes after materializing must agree with its actual
    per-output-partition row counts — same totals, and the headline
    imbalance IS max/mean of the published distribution. A shuffled-hash
    join carries raw rows through the exchange (a group-by would
    partial-aggregate the hot key away upstream), so a lopsided keyspace
    shows up as a lopsided partition."""
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    sess = _mesh_session(**{
        "spark.rapids.tpu.autoBroadcastJoinThreshold": -1})
    rng = np.random.default_rng(21)
    nrows = 600
    # deliberately lopsided keyspace: ~85% of rows share one hot key, so
    # one hash partition dwarfs the rest
    k = np.where(rng.uniform(size=nrows) < 0.85, 7,
                 rng.integers(0, 40, nrows)).astype("int64")
    left = sess.create_dataframe(
        pa.table({"k": k, "v": rng.uniform(0, 10, nrows)}),
        num_partitions=3)
    right = sess.create_dataframe(
        pa.table({"k": np.arange(40, dtype=np.int64),
                  "w": rng.uniform(0, 1, 40)}), num_partitions=2)
    q = left.join(right, on="k", how="inner")
    plan = sess._physical(q.logical, device=True)
    ex = _find(plan, TpuShuffleExchangeExec)
    assert ex is not None, plan.tree_string()
    assert ex.shuffle_skew() is None  # nothing materialized yet
    plan.collect()
    rec = ex.shuffle_skew()
    assert rec is not None
    per = rec["per_partition_rows"]
    # device tier shards across the attached 8-device mesh
    assert rec["partitions"] == len(per) == 8
    assert sum(per) in (nrows, 40)  # whichever join side this exchange is
    assert rec["rows"]["min"] == min(per)
    assert rec["rows"]["max"] == max(per)
    mean = sum(per) / len(per)
    assert rec["rows"]["imbalance"] == pytest.approx(max(per) / mean)
    # byte estimates follow the same shape: heaviest partition also
    # carries the most bytes
    assert rec["bytes"]["max"] >= rec["bytes"]["p50"]
    # SOME exchange in the plan carried the raw hot-key side: its
    # distribution must cross the diagnose 2x flag
    def _all(plan, cls, out):
        if isinstance(plan, cls):
            out.append(plan)
        for c in plan.children:
            _all(c, cls, out)
        return out
    recs = [e.shuffle_skew() for e in _all(plan, TpuShuffleExchangeExec, [])]
    recs = [r for r in recs if r is not None]
    raw = [r for r in recs if sum(r["per_partition_rows"]) == nrows]
    assert raw and raw[0]["rows"]["imbalance"] > 2.0, recs


def test_host_exchange_skew_record_matches_partition_counts():
    """v7 skew telemetry parity (host fallback tier): same contract as
    the device tier, via the host hash-partition ShuffleExchangeExec."""
    from spark_rapids_tpu.plan.physical import ShuffleExchangeExec
    sess = TpuSession({
        "spark.rapids.tpu.batchRowsMinBucket": 8,
        "spark.rapids.tpu.shuffle.partitions": 4,
        "spark.rapids.tpu.shuffle.mode": "host",
        "spark.rapids.tpu.aqe.enabled": False,
        "spark.rapids.tpu.autoBroadcastJoinThreshold": -1,
    })
    rng = np.random.default_rng(22)
    nrows = 400
    k = np.where(rng.uniform(size=nrows) < 0.8, 3,
                 rng.integers(0, 30, nrows)).astype("int64")
    left = sess.create_dataframe(
        pa.table({"k": k, "v": rng.uniform(0, 1, nrows)}),
        num_partitions=2)
    right = sess.create_dataframe(
        pa.table({"k": np.arange(30, dtype=np.int64),
                  "w": rng.uniform(0, 1, 30)}), num_partitions=2)
    q = left.join(right, on="k", how="inner")
    plan = sess._physical(q.logical, device=False)
    ex = _find(plan, ShuffleExchangeExec)
    assert ex is not None, plan.tree_string()
    plan.collect()

    def _all(plan, cls, out):
        if isinstance(plan, cls):
            out.append(plan)
        for c in plan.children:
            _all(c, cls, out)
        return out
    recs = [e.shuffle_skew()
            for e in _all(plan, ShuffleExchangeExec, [])]
    recs = [r for r in recs if r is not None]
    assert recs
    for rec in recs:
        per = rec["per_partition_rows"]
        assert rec["partitions"] == len(per) == 4
        assert rec["rows"]["min"] == min(per)
        assert rec["rows"]["max"] == max(per)
        mean = sum(per) / len(per)
        assert rec["rows"]["imbalance"] == pytest.approx(max(per) / mean)
    raw = [r for r in recs if sum(r["per_partition_rows"]) == nrows]
    assert raw and raw[0]["rows"]["imbalance"] > 2.0, recs
